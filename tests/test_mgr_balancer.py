"""mgr balancer tests — eval scoring, both optimization modes, plan
execution through the Incremental machinery, and the compat weight-set
consumed bit-exactly by every mapper backend (reference fixtures:
pybind/mgr/balancer/module.py + src/test/osd/TestOSDMap.cc upmap cases).
"""

import errno
import io
from contextlib import redirect_stdout

import numpy as np
import pytest

from ceph_tpu.mgr import (
    Balancer,
    MappingState,
    calc_eval,
    compat_ws_to_choose_args,
    synthetic_pg_stats,
)
from ceph_tpu.mgr.eval import Eval
from ceph_tpu.mgr.module import get_compat_weight_set_weights
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import decode_incremental, encode_incremental
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgId, PgPool, PoolType


def skewed_map(n_host=4, per=4, pg_num=128, skew=2.0):
    """Alternate-host weight skew: deviation for the optimizers to eat."""
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=pg_num, pgp_num=pg_num)

    def wf(osd):
        return int(0x10000 * (skew if (osd // per) % 2 else 1.0))

    return build_hierarchical(n_host, per, pool=pool, weight_fn=wf)


def host_state(m, desc="current"):
    return MappingState(m, synthetic_pg_stats(m), desc=desc, mapper="host")


class TestCalcStats:
    def _stats(self, count, target, total):
        pe = Eval(ms=None)
        full = {t: dict(count) for t in ("pgs", "objects", "bytes")}
        tot = {t: total for t in ("pgs", "objects", "bytes")}
        return pe.calc_stats(full, target, tot)["pgs"]

    def test_perfect_distribution_scores_zero(self):
        target = {0: 0.25, 1: 0.25, 2: 0.25, 3: 0.25}
        st = self._stats({o: 100 for o in target}, target, 400)
        assert st["score"] == 0.0
        assert st["stddev"] == pytest.approx(0.0)

    def test_weighted_perfect_scores_zero(self):
        target = {0: 0.5, 1: 0.25, 2: 0.25}
        st = self._stats({0: 200, 1: 100, 2: 100}, target, 400)
        assert st["score"] == pytest.approx(0.0)

    def test_overfull_scores_positive_and_bounded(self):
        target = {0: 0.25, 1: 0.25, 2: 0.25, 3: 0.25}
        st = self._stats({0: 250, 1: 50, 2: 50, 3: 50}, target, 400)
        assert 0.0 < st["score"] < 1.0
        # more imbalance -> strictly worse score
        st2 = self._stats({0: 370, 1: 10, 2: 10, 3: 10}, target, 400)
        assert st2["score"] > st["score"]

    def test_empty_total_is_zero(self):
        st = self._stats({}, {0: 1.0}, 0)
        assert st["score"] == 0 and st["stddev"] == 0


class TestCalcEval:
    def test_scores_skew(self):
        pe = calc_eval(host_state(skewed_map()))
        assert 0.0 < pe.score < 1.0
        assert set(pe.pool_name.values()) == {"rbd"}
        assert list(pe.score_by_root) == ["default"]
        tgt = pe.target_by_root["default"]
        assert sum(tgt.values()) == pytest.approx(1.0)
        # counts cover every replica of every PG
        assert pe.total_by_root["default"]["pgs"] == 128 * 3
        assert "score" in pe.show()

    def test_forced_imbalance_scores_worse(self):
        """Piling PGs onto one OSD via upmap must strictly worsen the
        score (the monotonicity optimize() relies on)."""
        m = build_hierarchical(4, 4, pool=PgPool(
            type=PoolType.REPLICATED, size=3, crush_rule=0,
            pg_num=128, pgp_num=128,
        ))
        pe0 = calc_eval(host_state(m))
        moved = 0
        for ps in range(128):
            if moved >= 24:
                break
            up, _, _, _ = m.pg_to_up_acting_osds(PgId(0, ps))
            if 0 in up:
                continue
            m.pg_upmap_items[PgId(0, ps)] = [(up[-1], 0)]
            moved += 1
        pe1 = calc_eval(host_state(m))
        assert pe1.score > pe0.score


class TestUpmapMode:
    def test_optimize_improves_and_applies(self):
        m = skewed_map(pg_num=256)
        ms = host_state(m)
        bal = Balancer(rng=np.random.default_rng(42))
        pe0 = bal.eval(ms)
        plan = bal.plan_create("p", ms, mode="upmap")
        rc, detail = bal.optimize(plan)
        assert rc == 0, detail
        assert plan.inc.new_pg_upmap_items
        pe1 = bal.eval(plan.final_state())
        assert pe1.score < pe0.score

        # the plan IS an Incremental: wire round-trip, then execute
        blob = encode_incremental(plan.finalize_inc())
        inc2 = decode_incremental(blob)
        assert inc2.new_pg_upmap_items == {
            pg: list(v) for pg, v in plan.inc.new_pg_upmap_items.items()
        }
        rc, detail = bal.execute(plan, m)
        assert rc == 0, detail
        assert m.epoch == 2
        assert m.pg_upmap_items == plan.osdmap.pg_upmap_items

    def test_already_balanced_returns_ealready(self):
        m = build_hierarchical(4, 4, pool=PgPool(
            type=PoolType.REPLICATED, size=3, crush_rule=0,
            pg_num=64, pgp_num=64,
        ))
        bal = Balancer(
            options={"upmap_max_deviation": 100},
            rng=np.random.default_rng(0),
        )
        plan = bal.plan_create("p", host_state(m), mode="upmap")
        rc, detail = bal.optimize(plan)
        assert rc == -errno.EALREADY
        assert "optimiz" in detail

    def test_respects_max_optimizations(self):
        m = skewed_map(pg_num=256)
        bal = Balancer(
            options={"upmap_max_optimizations": 3},
            rng=np.random.default_rng(1),
        )
        plan = bal.plan_create("p", host_state(m), mode="upmap")
        rc, _ = bal.optimize(plan)
        assert rc == 0
        changed = len(plan.inc.new_pg_upmap_items) + len(
            plan.inc.old_pg_upmap_items
        )
        assert 0 < changed <= 3


class TestCrushCompatMode:
    def _optimized(self, iterations=8, pg_num=128):
        m = skewed_map(pg_num=pg_num)
        ms = host_state(m)
        bal = Balancer(
            options={"crush_compat_max_iterations": iterations},
            rng=np.random.default_rng(7),
        )
        pe0 = bal.eval(ms)
        plan = bal.plan_create("c", ms, mode="crush-compat")
        rc, detail = bal.optimize(plan)
        assert rc == 0, detail
        return m, bal, plan, pe0

    def test_score_strictly_improves(self):
        m, bal, plan, pe0 = self._optimized()
        pe1 = bal.eval(plan.final_state())
        assert pe1.score < pe0.score
        assert plan.compat_ws

    def test_writes_real_choose_args(self):
        m, bal, plan, _ = self._optimized(iterations=4)
        ca = plan.osdmap.crush.choose_args[-1]
        # one row (position) per bucket, row length == bucket size,
        # internal-node entries = subtree weight-set sums
        for bid, b in plan.osdmap.crush.buckets.items():
            rows = ca.weight_sets[bid]
            assert len(rows) == 1 and len(rows[0]) == b.size
        ws = get_compat_weight_set_weights(plan.osdmap.crush)
        for osd, w in plan.compat_ws.items():
            assert ws[osd] == pytest.approx(w, abs=2 / 0x10000)

    def test_execute_carries_weight_set_through_incremental(self):
        m, bal, plan, _ = self._optimized(iterations=4)
        rc, detail = bal.execute(plan, m)
        assert rc == 0, detail
        assert m.epoch == 2
        assert -1 in m.crush.choose_args
        # the crush blob round-trip preserves the mapping bit-for-bit
        for ps in range(0, 128, 7):
            a = m.pg_to_up_acting_osds(PgId(0, ps))
            b = plan.osdmap.pg_to_up_acting_osds(PgId(0, ps))
            assert a == b, ps

    def test_failure_restores_working_map(self):
        """A rejected optimization (every candidate exceeds the
        misplaced ratio -> EDOM) must leave the plan's working map in
        its ORIGINAL state, not with the last rejected weight-set."""
        m = skewed_map()
        orig_weights = list(m.osd_weight)
        bal = Balancer(
            options={"crush_compat_max_iterations": 3,
                     "target_max_misplaced_ratio": 0.0},
            rng=np.random.default_rng(7),
        )
        plan = bal.plan_create("c", host_state(m), mode="crush-compat")
        rc, _ = bal.optimize(plan)
        assert rc == -errno.EDOM
        assert plan.compat_ws == {} and plan.osd_weights == {}
        assert -1 not in plan.osdmap.crush.choose_args
        assert plan.osdmap.osd_weight == orig_weights

    def test_stale_plan_rejected(self):
        m, bal, plan, _ = self._optimized(iterations=2)
        m.epoch += 1
        rc, detail = bal.execute(plan, m)
        assert rc == -errno.ESTALE and "epoch" in detail


def test_compat_weight_set_consumed_by_pipeline():
    """A written compat weight-set flows through the batched JAX
    pipeline bit-exactly (choose_args fallback key -1, the path the
    mgr's plans rely on)."""
    m = skewed_map(pg_num=64)
    ws = get_compat_weight_set_weights(m.crush)
    rng = np.random.default_rng(5)
    ws = {o: w * float(rng.uniform(0.6, 1.4)) for o, w in ws.items()}
    m.crush.choose_args[-1] = compat_ws_to_choose_args(m.crush, ws)

    from ceph_tpu.osd.pipeline_jax import PoolMapper

    up, upp, _, _ = PoolMapper(m, 0).map_all()
    for ps in range(64):
        w_up, w_upp, _, _ = m.pg_to_up_acting_osds(PgId(0, ps))
        got = [o for o in up[ps] if o != ITEM_NONE]
        assert got == w_up, ps
        assert upp[ps] == w_upp, ps


class TestCli:
    def test_optimize_show_execute(self, tmp_path, capsys):
        from ceph_tpu.cli.balancer import main

        plan_fn = tmp_path / "plan.inc"
        out_fn = tmp_path / "out.bin"
        rc = main([
            "--synthetic", "4,4,128", "--mapper", "host",
            "optimize", "t1", "--mode", "upmap",
            "--plan-out", str(plan_fn),
            "--execute", "-o", str(out_fn),
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "score" in out and "->" in out
        before, after = (
            float(tok) for tok in
            [ln for ln in out.splitlines() if ln.startswith("score")][0]
            .split()[1:4:2]
        )
        assert after < before
        assert plan_fn.exists() and out_fn.exists()

        rc = main(["show", str(plan_fn)])
        assert rc == 0
        shown = capsys.readouterr().out
        assert "pg-upmap-items" in shown

        # applying the plan file to the original map reproduces the
        # executed map's epoch
        rc = main([
            "--synthetic", "4,4,128", "execute", str(plan_fn),
        ])
        assert rc == 0
        assert "epoch 2" in capsys.readouterr().out

    def test_eval_and_status(self, capsys):
        from ceph_tpu.cli.balancer import main

        assert main(["--synthetic", "4,4,64", "--mapper", "host",
                     "eval", "-v"]) == 0
        out = capsys.readouterr().out
        assert "score" in out and "osd." in out
        assert main(["status"]) == 0
        assert '"mode"' in capsys.readouterr().out


@pytest.mark.slow
def test_mgr_loop_state_backends_equivalent_100k():
    """Satellite: the mgr do_upmap loop at 100k PGs makes IDENTICAL
    decisions on the reference-faithful SetState and the
    device-resident DeviceState (balancer/state.py equivalence, now
    under the module-level pool iteration)."""
    def run(backend):
        pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                      pg_num=100_000, pgp_num=100_000)
        m = build_hierarchical(8, 8, n_rack=2, pool=pool)
        for o in range(0, 16):
            m.osd_weight[o] = int(0x10000 * 0.8)
        bal = Balancer(
            options={"upmap_state_backend": backend,
                     "upmap_max_optimizations": 12},
            rng=np.random.default_rng(99),
        )
        ms = MappingState(m, synthetic_pg_stats(m), mapper="jax")
        plan = bal.plan_create("p", ms, mode="upmap")
        rc, detail = bal.optimize(plan)
        assert rc in (0, -errno.EALREADY), detail
        return plan

    p_sets = run("sets")
    p_dev = run("device")
    assert p_sets.inc.new_pg_upmap_items == p_dev.inc.new_pg_upmap_items
    assert p_sets.inc.old_pg_upmap_items == p_dev.inc.old_pg_upmap_items
    assert p_sets.osdmap.pg_upmap_items == p_dev.osdmap.pg_upmap_items


@pytest.mark.slow
def test_compat_weight_set_bitexact_jax_vs_native_100k():
    """Acceptance: the weight-set a crush-compat plan writes produces
    bit-identical mappings from mapper_jax and native/mapper.py at
    >=100k placement seeds."""
    from ceph_tpu.crush import mapper_ref
    from ceph_tpu.crush.mapper_jax import compile_batched
    from ceph_tpu.crush.soa import build_arrays

    m = skewed_map(n_host=8, per=8, pg_num=256)
    bal = Balancer(
        options={"crush_compat_max_iterations": 5},
        rng=np.random.default_rng(3),
    )
    plan = bal.plan_create("c", host_state(m), mode="crush-compat")
    rc, detail = bal.optimize(plan)
    assert rc == 0, detail
    crush = plan.osdmap.crush
    ca = crush.choose_args[-1]

    A = build_arrays(crush, ca)
    n = 100_000
    xs = (np.arange(n, dtype=np.uint32) * 2654435761) % (2**31)
    weights = [w for w in plan.osdmap.osd_weight]
    dev_w = np.asarray(weights, np.uint32)
    jax_rows = np.asarray(compile_batched(A, 0, 3)(xs, dev_w))

    try:
        from ceph_tpu.native.mapper import NativeMapper, available
    except Exception:
        available = lambda: False  # noqa: E731
    if not available():
        pytest.skip("native crush engine unavailable (no C++ toolchain)")
    nat_rows = NativeMapper(crush, choose_args=ca).map_batch(
        0, xs, 3, weights
    )
    assert np.array_equal(jax_rows, nat_rows)
    # ground a sample against the host reference oracle as well
    for i in range(0, n, n // 64):
        want = mapper_ref.do_rule(
            crush, 0, int(xs[i]), 3, list(weights), ca
        )
        want = (want + [ITEM_NONE] * 3)[:3]
        assert list(jax_rows[i]) == want, i
