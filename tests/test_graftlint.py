"""graftlint: every pass fires on its seeded-violation fixture, stays
silent on the negative control, suppressions work, the reporters keep
their shape, and the whole repo scans clean (that last one IS the
contract gate: dispatch spans don't sync, kernels don't bake tables,
counters/spans/knobs/fault-points match their registries)."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.graftlint import PASSES, Context, Module, run

REPO = Path(__file__).resolve().parents[1]


def lint(tmp_path, src: str, pass_name: str, name: str = "fixture.py"):
    """Run one pass over one fixture file; returns violations."""
    p = tmp_path / name
    p.write_text(src)
    ctx = Context(paths=[], include_tests=False)  # real registries, no scan
    return PASSES[pass_name].check_module(Module(p, REPO), ctx)


# -- no-print ---------------------------------------------------------------

def test_no_print_fires(tmp_path):
    v = lint(tmp_path, (
        "import sys\n"
        "print('a')\n"
        "print('b', file=sys.stdout)\n"
    ), "no-print")
    assert [x.line for x in v] == [2, 3]


def test_no_print_clean(tmp_path):
    v = lint(tmp_path, (
        "import sys\n"
        "print('c', file=sys.stderr)\n"
        "print('d', file=w)\n"
    ), "no-print")
    assert v == []


# -- host-sync --------------------------------------------------------------

def test_host_sync_fires_alias_aware(tmp_path):
    v = lint(tmp_path, (
        "import numpy as xnp\n"
        "from numpy import asarray as aa\n"
        "import jax\n"
        "with obs.span('pipeline.map_block', pgs=1):\n"
        "    a = xnp.asarray(x)\n"          # aliased module
        "    b = aa(x)\n"                   # from-import alias
        "    c = int(x.sum())\n"            # int() joined the sync list
        "    d = jax.device_get(x)\n"
        "    e = x.block_until_ready()\n"
        "with obs.span('ec.gf_dispatch'):\n"
        "    f = bool(flg)\n"
    ), "host-sync")
    assert [x.line for x in v] == [5, 6, 7, 8, 9, 11]
    assert "numpy.asarray()" in v[0].message
    assert "ec.gf_dispatch" in v[5].message


def test_host_sync_reports_every_span_item(tmp_path):
    # the old walker reported spans[0] only; both names must show up
    v = lint(tmp_path, (
        "with obs.span('pipeline.map_block'), obs.span('pipeline.rescue'):\n"
        "    a = float(x)\n"
    ), "host-sync")
    assert len(v) == 1
    assert "pipeline.map_block" in v[0].message
    assert "pipeline.rescue" in v[0].message


def test_host_sync_clean(tmp_path):
    v = lint(tmp_path, (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "n = int(x)\n"                       # outside any span
        "with obs.span('pipeline.map_block'):\n"
        "    a = jnp.asarray(x)\n"            # device op, not a sync
        "    b = np.resize(x, 4)\n"           # host alloc, not a sync
        "with obs.span('pipeline.fetch'):\n"
        "    c = np.asarray(x)\n"              # fetch span: allowed
        "with obs.span('bench.cold_pass'):\n"
        "    d = float(x)\n"                   # not a dispatch span
    ), "host-sync")
    assert v == []


# -- trace-constant ---------------------------------------------------------

def test_trace_constant_fires_on_closure(tmp_path):
    v = lint(tmp_path, (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def build(n):\n"
        "    table = np.arange(n)\n"
        "    @jax.jit\n"
        "    def kern(x):\n"
        "        return x + table\n"          # closure -> trace constant
        "    return kern\n"
    ), "trace-constant")
    assert len(v) == 1 and "table" in v[0].message


def test_trace_constant_fires_on_asarray_of_free_var(tmp_path):
    v = lint(tmp_path, (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def build(data):\n"
        "    @jax.jit\n"
        "    def kern(x):\n"
        "        return x + jnp.asarray(data)\n"
        "    return kern\n"
    ), "trace-constant")
    assert len(v) == 1 and "data" in v[0].message


def test_trace_constant_fires_through_jit_call_and_vmap(tmp_path):
    v = lint(tmp_path, (
        "import jax\n"
        "import numpy as np\n"
        "def build(n):\n"
        "    w = np.zeros(n)\n"
        "    def kern(x):\n"
        "        return x * w\n"
        "    return jax.jit(jax.vmap(kern))\n"
    ), "trace-constant")
    assert len(v) == 1 and "'w'" in v[0].message


def test_trace_constant_clean_operand_style(tmp_path):
    v = lint(tmp_path, (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def build(n):\n"
        "    table = np.arange(n)\n"
        "    @jax.jit\n"
        "    def kern(x, tb):\n"              # table rides as an operand
        "        return x + tb\n"
        "    def run(x):\n"
        "        return kern(jnp.asarray(x), jnp.asarray(table))\n"
        "    return run\n"                    # asarray outside jit: fine
    ), "trace-constant")
    assert v == []


# -- counter-decl -----------------------------------------------------------

def test_counter_decl_fires_on_typo(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "L = obs.logger_for('fixg')\n"
        "L.add_u64('ok', 'fine')\n"
        "L.inc('ok')\n"
        "L.inc('typo')\n"
    ), "counter-decl")
    assert len(v) == 1 and v[0].line == 5 and "'typo'" in v[0].message


def test_counter_decl_resolves_function_returning_logger(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "def _c():\n"
        "    L = obs.logger_for('fixg')\n"
        "    L.add_u64('hits', '')\n"
        "    return L\n"
        "_c().inc('hits')\n"
        "_c().inc('misses')\n"
    ), "counter-decl")
    assert len(v) == 1 and v[0].line == 7 and "'misses'" in v[0].message


def test_counter_decl_dynamic_suffix_family(tmp_path):
    # JitAccount-style f-string declares allow endswith-matched updates
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "L = obs.logger_for('fixg')\n"
        "def declare(key):\n"
        "    L.add_u64(f'{key}_things', '')\n"
        "L.inc('foo_things')\n"
        "L.inc('foo_stuff')\n"
    ), "counter-decl")
    assert len(v) == 1 and v[0].line == 6


def test_counter_decl_knows_quantile_kind(tmp_path):
    # add_quantile is a declare like the other four kinds: updates on a
    # quantile key resolve, a typo'd key still fires
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "L = obs.logger_for('fixg')\n"
        "L.add_quantile('lat_hist', 'tails')\n"
        "with L.time('lat_hist'):\n"
        "    pass\n"
        "L.observe('lat_hist', 0.5)\n"
        "L.observe('lat_mist', 0.5)\n"
    ), "counter-decl")
    assert len(v) == 1 and v[0].line == 7 and "'lat_mist'" in v[0].message


def test_counter_decl_merge_histogram_update(tmp_path):
    # merge_histogram (the placement group's device-folded histogram
    # update) is an update like inc/observe: a declared histogram key
    # resolves, an undeclared one fires
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "L = obs.logger_for('fixg')\n"
        "L.add_histogram('choose_tries', [0, 1, 2], 'retries')\n"
        "L.merge_histogram('choose_tries', [5, 1, 0])\n"
        "L.merge_histogram('chose_tries', [5, 1, 0])\n"
    ), "counter-decl")
    assert len(v) == 1 and v[0].line == 5 and "'chose_tries'" in v[0].message


def test_counter_decl_observe_and_time(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "L = obs.logger_for('fixg')\n"
        "L.add_time_avg('lat', '')\n"
        "with L.time('lat'):\n"
        "    pass\n"
        "L.observe('lat', 0.5)\n"
        "L.observe('latency', 0.5)\n"
    ), "counter-decl")
    assert len(v) == 1 and v[0].line == 7


def test_counter_decl_state_group_idiom(tmp_path):
    # the ClusterState perf group's exact declaration pattern: u64 +
    # quantile declares resolve, a typo'd update on either kind fires
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "_L = obs.logger_for('state')\n"
        "_L.add_u64('delta_applies', 'value deltas applied on device')\n"
        "_L.add_u64('device_put_bytes', 'upload accounting')\n"
        "_L.add_quantile('apply_seconds', 'per-apply wall time')\n"
        "_L.inc('delta_applies')\n"
        "_L.inc('device_put_bytes', 448)\n"
        "with _L.time('apply_seconds'):\n"
        "    pass\n"
        "_L.inc('delta_aplies')\n"
        "_L.observe('apply_second', 0.1)\n"
    ), "counter-decl")
    assert [x.line for x in v] == [10, 11]
    assert "'delta_aplies'" in v[0].message


# -- env-knob ---------------------------------------------------------------

def test_env_knob_fires_on_unregistered(tmp_path):
    v = lint(tmp_path, (
        "import os\n"
        "a = os.environ.get('CEPH_TPU_BOGUS_KNOB')\n"
        "b = os.environ['CEPH_TPU_ALSO_BOGUS']\n"
        "c = 'CEPH_TPU_THIRD_BOGUS' in os.environ\n"
    ), "env-knob")
    assert [x.line for x in v] == [2, 3, 4]


def test_env_knob_fires_on_dynamic_key(tmp_path):
    v = lint(tmp_path, (
        "import os\n"
        "PREFIX = 'CEPH_TPU_'\n"
        "x = os.environ.get(PREFIX + name)\n"
    ), "env-knob")
    assert len(v) == 1 and "dynamic" in v[0].message


def test_env_knob_sees_registry_reader(tmp_path):
    # knobs.get() is the registry's own checked reader: a bogus name
    # fires, a registered one is silent (and counts as a read)
    v = lint(tmp_path, (
        "from ceph_tpu.utils import knobs\n"
        "a = knobs.get('CEPH_TPU_TRACE')\n"
        "b = knobs.get('CEPH_TPU_BOGUS_KNOB')\n"
    ), "env-knob")
    assert [x.line for x in v] == [3]


def test_env_knob_clean(tmp_path):
    v = lint(tmp_path, (
        "import os\n"
        "from os import environ\n"
        "ENV_VAR = 'CEPH_TPU_FAULTS'\n"
        "a = os.environ.get('CEPH_TPU_TRACE')\n"   # registered
        "b = environ.get(ENV_VAR)\n"                # via constant: registered
        "c = os.environ.get('BENCH_PGS')\n"         # not a CEPH_TPU knob
        "d = os.environ.get('XLA_FLAGS', '')\n"
    ), "env-knob")
    assert v == []


# -- span-name --------------------------------------------------------------

def test_span_name_fires_on_typo(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "with obs.span('pipeline.map_blok'):\n"
        "    pass\n"
        "obs.instant('no.such_marker')\n"
        "obs.counter('no.such_track', 1.0)\n"
    ), "span-name")
    assert sorted(x.line for x in v) == [2, 4, 5]


def test_span_name_fires_on_unregistered_fstring_prefix(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "with obs.span(f'bogus.{x}'):\n"
        "    pass\n"
    ), "span-name")
    assert len(v) == 1 and "bogus.{...}" in v[0].message


def test_span_name_clean(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "with obs.span('pipeline.map_block', pgs=1):\n"
        "    pass\n"
        "with obs.span(f'stage.{name}'):\n"        # registered prefix
        "    pass\n"
        "with obs.span(f'{group}.{key}.dispatch'):\n"  # no static head
        "    pass\n"
        "with obs.span(variable):\n"                # not statically checkable
        "    pass\n"
        "obs.instant('fault.fired', point='x')\n"
        "obs.counter('balancer.stddev', 1.0)\n"
        "time.perf_counter()\n"                     # not a trace counter
    ), "span-name")
    assert v == []


def test_span_name_checks_jitaccount_base(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "f = obs.JitAccount(fn, L, 'k', span='ec.gf_matmul')\n"
        "g = obs.JitAccount(fn, L, 'k', span='ec.gf_matmull')\n"
    ), "span-name")
    assert len(v) == 1 and v[0].line == 3


def test_span_name_state_spans_registered(tmp_path):
    # the ClusterState spans are registry entries; a near-miss fires
    v = lint(tmp_path, (
        "from ceph_tpu import obs\n"
        "with obs.span('state.apply', epoch=2):\n"
        "    pass\n"
        "with obs.span('state.raw_fixup', pool=0, seeds=4):\n"
        "    pass\n"
        "with obs.span('state.aply'):\n"
        "    pass\n"
    ), "span-name")
    assert [x.line for x in v] == [6]


# -- fault-point ------------------------------------------------------------

def test_fault_point_fires_on_undeclared_base(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu.runtime import faults\n"
        "faults.check('bogus_point')\n"
        "SPEC = {'CEPH_TPU_FAULTS': 'nonexistent=fail:x x1'}\n"
    ), "fault-point")
    assert [x.line for x in v] == [2, 3]
    assert "bogus_point" in v[0].message


def test_fault_point_clean(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu.runtime import faults\n"
        "faults.check('map_batch')\n"
        "faults.check('init', qual='tpu')\n"
        "SPEC = 'init.auto=hang:600,stage_end.ec_jax=exit:3'\n"
        "FLAKY = 'epoch_apply=lost:chaos@p0.3x2'\n"  # probabilistic arm
        "NOT_A_SPEC = 'a=b,c=d'\n"             # unknown action: not a spec
    ), "fault-point")
    assert v == []


def test_fault_point_probabilistic_spec_undeclared_base(tmp_path):
    """The `@pP` suffix must not hide an undeclared point from the
    spec-string scan."""
    v = lint(tmp_path, (
        "SPEC = 'bogus_flaky=lost@p0.5x1'\n"
    ), "fault-point")
    assert [x.line for x in v] == [1]
    assert "bogus_flaky" in v[0].message


def test_fault_point_flags_untested_declared_point():
    ctx = Context(paths=[])  # parses tests/, no scanned modules
    ctx.fault_points = dict(ctx.fault_points, zz_unused="never exercised")
    ctx.fault_lines["zz_unused"] = 1
    PASSES["fault-point"].run(ctx)
    msgs = [v.message for v in ctx.violations]
    assert any("zz_unused" in m for m in msgs)
    # the real points are all exercised by the suite
    assert not any("'init'" in m or "'map_batch'" in m or "'stage'" in m
                   or "'stage_end'" in m for m in msgs)


# -- health-check -----------------------------------------------------------

def test_health_check_fires_on_undeclared_code(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu.obs import health\n"
        "health.raise_check('TOTALLY_BOGUS', health.WARN, 'x')\n"
        "health.clear('ALSO_BOGUS')\n"
        "health.raise_check('OSD_DOWN', health.WARN, 'declared: fine')\n"
    ), "health-check")
    assert [x.line for x in v] == [2, 3]
    assert "TOTALLY_BOGUS" in v[0].message
    assert "HEALTH_CHECKS" in v[0].message


def test_health_check_clean_on_declared_codes(tmp_path):
    v = lint(tmp_path, (
        "from ceph_tpu.obs import health\n"
        "health.raise_check('PG_UNMAPPED', health.ERR, 'x')\n"
        "health.clear('SLO_BURN')\n"
        "code = pick()\n"
        "health.clear(code)\n"  # dynamic first arg: not a literal site
    ), "health-check")
    assert v == []


def test_health_check_registry_module_exempt(tmp_path):
    """obs/health.py hosts the machinery and docstring examples — an
    undeclared literal there must not fire direction (a)."""
    d = tmp_path / "obs"
    d.mkdir()
    f = d / "health.py"
    f.write_text("health.raise_check('DOC_EXAMPLE', 'HEALTH_WARN', 'x')\n")
    ctx = Context(paths=[], include_tests=False)
    assert PASSES["health-check"].check_module(Module(f, REPO), ctx) == []


def test_health_check_flags_untested_declared_code():
    """Direction (b): a declared code no test references is a violation
    pointing at its registry line — and every *real* code is covered."""
    # built dynamically: a bare literal here would itself count as the
    # test reference the pass is looking for (this file lives in tests/)
    code = "ZZ_" + "UNTESTED"
    ctx = Context(paths=[])  # parses tests/, no scanned modules
    ctx.health_checks = dict(ctx.health_checks, **{code: "never seen"})
    ctx.health_lines[code] = 1
    PASSES["health-check"].run(ctx)
    assert len(ctx.violations) == 1
    v = ctx.violations[0]
    assert code in v.message and "no test" in v.message
    assert v.path == "ceph_tpu/obs/health.py"


# -- scenario-event ---------------------------------------------------------

def test_scenario_event_fires_on_undeclared_drawn_kind(tmp_path):
    """Direction (a): an event_probs() tuple whose kind is missing from
    EVENT_KINDS fires; declared kinds the fixture never draws surface
    as dead vocabulary."""
    d = tmp_path / "sim"
    d.mkdir()
    f = d / "lifetime.py"
    f.write_text(
        "class Scenario:\n"
        "    def event_probs(self):\n"
        "        return ((\"flap\", 0.1), (\"bogus_kind\", 0.2))\n"
    )
    ctx = Context(paths=[], include_tests=False)
    ctx.modules = [Module(f, REPO)]
    PASSES["scenario-event"].run(ctx)
    msgs = [v.message for v in ctx.violations]
    assert any("bogus_kind" in m and "not declared" in m for m in msgs)
    assert any("'death'" in m and "dead vocabulary" in m for m in msgs)


def test_scenario_event_flags_untested_declared_kind():
    """Direction (b): a declared kind no test literal references is a
    violation pointing at the EVENT_KINDS registry line — and every
    *real* kind is covered by the suite."""
    kind = "zz_" + "never_forced"
    ctx = Context(paths=[])  # parses tests/, no scanned modules
    ctx.event_kinds = dict(ctx.event_kinds, **{kind: "never"})
    ctx.event_lines[kind] = 1
    PASSES["scenario-event"].run(ctx)
    assert len(ctx.violations) == 1
    v = ctx.violations[0]
    assert kind in v.message and "no test" in v.message
    assert v.path == "ceph_tpu/sim/lifetime.py"


# -- sweep-grammar ----------------------------------------------------------

def test_sweep_grammar_fires_on_unregistered_axis_literal(tmp_path):
    """Direction (a): an `axis=<key>:` literal sweeping a key outside
    SWEEP_AXES/FLEET_KNOBS fires (it would raise at parse time);
    registered keys and the docs' `axis=key:` placeholder are silent."""
    # built dynamically: a bare bogus literal here would itself be
    # flagged by the repo-wide scan (this file lives in tests/)
    bogus = "axis=zz_bog" + "us:1|2"
    v = lint(tmp_path, (
        f"SPEC = 'base=epochs=4;{bogus};axis=seed:1|2'\n"
        "DOC = 'axis=key:v1|v2'\n"
    ), "sweep-grammar")
    assert len(v) == 1 and v[0].line == 1
    assert "zz_bogus" in v[0].message
    assert "unregistered" in v[0].message


def test_sweep_grammar_fires_on_knob_shadowing_field():
    """A fleet knob named like a Scenario field makes the grammar
    ambiguous — the pass refuses it at the registry line."""
    ctx = Context(paths=[], include_tests=False)
    ctx.fleet_knobs = dict(ctx.fleet_knobs, seed="shadow")
    ctx.fleet_knob_lines = dict(ctx.fleet_knob_lines, seed=1)
    PASSES["sweep-grammar"].run(ctx)
    assert len(ctx.violations) == 1, ctx.violations
    assert "shadows a Scenario field" in ctx.violations[0].message
    assert ctx.violations[0].path == "ceph_tpu/fleet/spec.py"


def test_sweep_grammar_flags_undocumented_untested_axis():
    """Directions (b)+(c)+(d): a salted axis that is not a Scenario
    field, missing from the README table, and swept by no test fires
    all three ways — and every *real* key is clean (no other
    violations)."""
    key = "zz_" + "phantom"
    ctx = Context()  # full scan: README and tests/ in view
    ctx.sweep_axes = dict(ctx.sweep_axes, **{key: "never"})
    ctx.sweep_lines = dict(ctx.sweep_lines, **{key: 1})
    PASSES["sweep-grammar"].run(ctx)
    assert len(ctx.violations) == 3, ctx.violations
    msgs = [v.message for v in ctx.violations]
    assert any(key in m and "not a Scenario" in m for m in msgs)
    assert any(key in m and "README" in m for m in msgs)
    assert any(key in m and "swept by no test" in m for m in msgs)


def test_sweep_grammar_flags_untested_fleet_knob():
    """A declared fleet knob needs a README row and a `<key>=` directive
    literal in some test — a salted knob fires both; the real knobs are
    all covered."""
    key = "zz_" + "knob"
    ctx = Context()  # full scan
    ctx.fleet_knobs = dict(ctx.fleet_knobs, **{key: "never"})
    ctx.fleet_knob_lines = dict(ctx.fleet_knob_lines, **{key: 1})
    PASSES["sweep-grammar"].run(ctx)
    assert len(ctx.violations) == 2, ctx.violations
    msgs = [v.message for v in ctx.violations]
    assert any(key in m and "README" in m for m in msgs)
    assert any(key in m and "exercised by no test" in m for m in msgs)


# -- balancer-options -------------------------------------------------------

def test_balancer_options_fires_on_undeclared_key(tmp_path):
    """Direction (a): a get_option() site consuming an upmap_* key that
    DEFAULT_OPTIONS never declares fires; declared upmap keys and
    non-upmap keys stay silent."""
    v = lint(tmp_path, (
        "x = self.get_option('upmap_bogus_knob')\n"
        "y = self.get_option('upmap_max_deviation')\n"
        "z = self.get_option('mode')\n"
    ), "balancer-options")
    assert [x.line for x in v] == [1]
    assert "upmap_bogus_knob" in v[0].message
    assert "never be set" in v[0].message


def test_balancer_options_flags_undocumented_untested_key(monkeypatch):
    """Directions (b)+(c): a declared upmap_* key missing from both the
    README options table and every test literal fires twice — and every
    *real* key is documented and test-forced (no other violations)."""
    import tools.graftlint.passes.balancer_options as bo

    # built dynamically: a bare literal here would itself count as the
    # test forcing the pass is looking for (this file lives in tests/)
    key = "upmap_zz_" + "phantom"
    real = bo._load_registry

    def salted(path, name, default):
        declared, lines = real(path, name, default)
        if name == "DEFAULT_OPTIONS" and declared:
            declared = dict(declared, **{key: 0})
            lines = dict(lines, **{key: 1})
        return declared, lines

    monkeypatch.setattr(bo, "_load_registry", salted)
    ctx = Context()  # full scan: README and tests/ in view
    PASSES["balancer-options"].run(ctx)
    assert len(ctx.violations) == 2, ctx.violations
    msgs = [v.message for v in ctx.violations]
    assert any(key in m and "README" in m for m in msgs)
    assert any(key in m and "no test" in m for m in msgs)


# -- suppressions -----------------------------------------------------------

def test_suppression_silences_one_pass(tmp_path):
    src = (
        "import numpy as np\n"
        "with obs.span('pipeline.map_block'):\n"
        "    a = np.asarray(x)  # graftlint: disable=host-sync\n"
        "    b = np.asarray(x)  # graftlint: disable=all\n"
        "    c = np.asarray(x)  # graftlint: disable=span-name\n"
    )
    v = lint(tmp_path, src, "host-sync")
    assert [x.line for x in v] == [5]  # wrong pass name does not suppress


def test_shim_find_violations_honors_root(tmp_path):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        from check_no_print import find_violations
    finally:
        sys.path.pop(0)
    bad = tmp_path / "ceph_tpu" / "osd"
    bad.mkdir(parents=True)
    (bad / "bad.py").write_text("print('oops')\n")
    v = find_violations(tmp_path)
    assert len(v) == 1 and "bad.py" in v[0]


# -- registries stay self-consistent ---------------------------------------

def test_span_registry_shape():
    from ceph_tpu.obs import spans

    assert set(spans.DISPATCH_SPANS) <= set(spans.SPANS)
    assert spans.known("pipeline.map_block")
    assert spans.known("stage.anything")
    assert not spans.known("pipeline.map_blok")


def test_knob_registry_and_readme_table():
    from ceph_tpu.utils import knobs

    table = knobs.render_table()
    readme = (REPO / "README.md").read_text()
    for name in knobs.KNOBS:
        assert f"`{name}`" in table
        assert name in readme, f"{name} missing from README knob table"
    with pytest.raises(KeyError):
        knobs.get("CEPH_TPU_NOT_A_KNOB")
    assert knobs.get("CEPH_TPU_TRACE", "dflt") is not None or True


def test_fault_registry_covers_compiled_in_points():
    from ceph_tpu.runtime import faults

    assert set(faults.FAULT_POINTS) == {
        "init", "map_batch", "stage", "stage_end",
        "epoch_apply", "lifetime_step", "recovery_step",
        "hazard_decay", "serve_dispatch", "epoch_swap",
    }


# -- serve-reply ------------------------------------------------------------

def test_serve_reply_fires_on_undeclared_and_dropped(tmp_path):
    v = lint(tmp_path, (
        "def answer(bad, worse) -> Reply:\n"
        "    if bad:\n"
        "        return Reply('EWEIRD', error='x')\n"   # undeclared
        "    if worse:\n"
        "        return\n"                              # dropped reply
        "    lanes = STATUS_CODES['ENOPE']\n"           # undeclared code
        "    return Reply('ok')\n"
    ), "serve-reply", name="serve_fixture.py")
    assert sorted(x.line for x in v) == [3, 5, 6]
    msgs = " | ".join(x.message for x in v)
    assert "EWEIRD" in msgs and "not declared" in msgs
    assert "ENOPE" in msgs
    assert "dropped reply" in msgs


def test_serve_reply_clean_on_declared_statuses(tmp_path):
    v = lint(tmp_path, (
        "def answer(n) -> Reply:\n"
        "    if n:\n"
        "        return Reply('EBUSY', error='full')\n"
        "    lanes = STATUS_CODES['ETIMEDOUT']\n"
        "    def fill():\n"
        "        return\n"       # nested, un-annotated: not a reply path
        "    fill()\n"
        "    return Reply('ok')\n"
        "def helper():\n"
        "    return\n"           # un-annotated: not a reply path
    ), "serve-reply", name="serve_fixture.py")
    assert v == []


def test_serve_reply_flags_untested_declared_status():
    status = "EZZ_" + "UNSEEN"
    ctx = Context(paths=[])  # parses tests/, no scanned modules
    ctx.reply_statuses = dict(ctx.reply_statuses, **{status: "never"})
    ctx.reply_lines = dict(ctx.reply_lines, **{status: 1})
    PASSES["serve-reply"].run(ctx)
    assert len(ctx.violations) == 1, ctx.violations
    v = ctx.violations[0]
    assert status in v.message and "no test" in v.message
    assert v.path == "ceph_tpu/serve/service.py"
    # the real vocabulary is fully pinned by the suite
    assert not any(s in v.message for s in
                   ("'ok'", "'EBUSY'", "'ETIMEDOUT'", "'ESHUTDOWN'"))


# -- runner + reporters -----------------------------------------------------

def test_run_unknown_pass_raises():
    with pytest.raises(KeyError, match="no-such-pass"):
        run(select=["no-such-pass"])


def test_json_report_shape(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import numpy as np\n"
                   "with obs.span('pipeline.map_block'):\n"
                   "    a = np.asarray(x)\n")
    violations, report = run(select=["host-sync"], paths=[bad])
    assert report["tool"] == "graftlint"
    assert report["passes"] == ["host-sync"]
    assert report["count"] == len(violations) == 1
    (rec,) = report["violations"]
    assert rec["pass"] == "host-sync" and rec["line"] == 3


def test_unparseable_file_is_a_violation(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n")
    violations, report = run(select=["no-print"], paths=[bad])
    assert report["count"] == 1
    assert violations[0].pass_name == "parse"


# -- the repo itself is clean (the actual contract gate) --------------------

def test_repo_scans_clean_all_passes():
    violations, report = run()
    assert report["passes"] == sorted(PASSES)
    assert len(report["passes"]) >= 7
    assert violations == [], "\n".join(v.format() for v in violations)


@pytest.mark.slow
def test_cli_json_whole_repo():
    """The CLI entry bench.py --selftest shells out to: exit 0, JSON on
    stdout, all passes, zero violations, well under the 30 s budget."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    rep = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rep["count"] == 0 and rep["violations"] == []
    assert set(rep["passes"]) == set(PASSES)
    assert rep["elapsed_s"] < 30, rep["elapsed_s"]
    assert "clean" in proc.stderr


def test_cli_list_and_bad_select():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--list"],
        capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert out.returncode == 0
    for name in PASSES:
        assert name in out.stdout
    bad = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--select", "nope"],
        capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert bad.returncode == 2 and "unknown pass" in bad.stderr
