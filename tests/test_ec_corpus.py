"""EC non-regression corpus gate (VERDICT r5 item 7).

The frozen corpus (tests/data/ec_corpus.json, written by
`python -m tools.ec_corpus create`) pins the encoded stripe bytes of
every plugin family; verification re-encodes deterministic inputs on
every available backend (numpy / native SIMD / jax) and requires
identical SHA-256 digests plus byte-exact erasure decodes.  A digest
mismatch here IS the regression the reference's
ceph_erasure_code_non_regression harness exists to catch.
"""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools import ec_corpus  # noqa: E402

CORPUS = ec_corpus.DEFAULT_CORPUS

pytestmark = pytest.mark.smoke


def _entries():
    data = json.loads(CORPUS.read_text())
    return data["entries"]


def test_corpus_exists_and_covers_all_families():
    names = {e["name"] for e in _entries()}
    for family in ("rs_", "isa_", "clay_", "shec_", "lrc_"):
        assert any(n.startswith(family) for n in names), family


def test_corpus_pins_decode_under_erasure():
    """v2 corpus: every entry carries digest-pinned decode cases, with
    multi-loss patterns wherever the profile tolerates more than one
    lost shard — decode PLANS are frozen, not just encode bytes."""
    for e in _entries():
        cases = e.get("decode")
        assert cases, e["name"]
        sizes = {len(c["erased"]) for c in cases}
        assert 1 in sizes, e["name"]
        # every frozen profile tolerates (and pins) multi-loss decodes
        assert max(sizes) >= 2, (e["name"], sizes)
        for c in cases:
            assert len(c["digest"]) == 64


@pytest.mark.parametrize("entry", _entries(), ids=lambda e: e["name"])
def test_backends_pinned_to_corpus_bytes(entry):
    """Every available backend reproduces the frozen stripe digest and
    decodes the erasure sets back to identical bytes."""
    problems = ec_corpus.verify_entry(entry, ("numpy", "native", "jax"))
    assert not problems, problems


def test_digest_actually_gates():
    """A corrupted corpus digest must be detected (the tool is not
    vacuously green)."""
    entry = dict(_entries()[0])
    entry["digest"] = "0" * 64
    problems = ec_corpus.verify_entry(
        entry, ("numpy",), check_decode=False
    )
    assert problems and "digest" in problems[0]
