"""Runtime robustness layer: fault injection, preflight, degradation
ladder, stage scheduler checkpoint/resume, and the bench.py integration.

Every retry/backoff/degradation/resume path runs CPU-only with injected
faults (runtime.faults) — no test waits on a real timeout longer than
~2s; hangs are killed by watchdogs armed with sub-second budgets.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from ceph_tpu import runtime
from ceph_tpu.runtime import faults

# the whole layer is CPU-only and fast — smoke tier — except the
# two-full-bench-runs resume test, which is marked slow instead
smoke = pytest.mark.smoke

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm_all()


# ------------------------------------------------------------------ faults

@smoke
class TestFaults:
    def test_spec_parsing_and_counts(self):
        faults.configure("init.tpu=fail:ENOLINK x2, map_batch=lost")
        assert faults.active() == {
            "init.tpu": "fail:ENOLINK x2", "map_batch": "lost:",
        }
        with pytest.raises(runtime.FaultInjected):
            faults.check("init", qual="tpu")
        with pytest.raises(runtime.FaultInjected):
            faults.check("init", qual="tpu")
        faults.check("init", qual="tpu")  # budget of 2 exhausted
        faults.check("init", qual="cpu")  # qualifier mismatch: no fire
        with pytest.raises(runtime.DeviceLostError):
            faults.check("map_batch")
        with pytest.raises(runtime.DeviceLostError):
            faults.check("map_batch")  # unlimited without xN

    def test_qualified_beats_bare(self):
        faults.configure("stage=fail:generic,stage.ec=fail:specific x1")
        with pytest.raises(runtime.FaultInjected, match="specific"):
            faults.check("stage", qual="ec")

    def test_bad_specs_rejected(self):
        with pytest.raises(ValueError):
            faults.configure("init=explode:1")
        with pytest.raises(ValueError):
            faults.configure("just-a-word")
        with pytest.raises(ValueError):
            faults.configure("init=fail@p1.5")  # p outside (0, 1]
        with pytest.raises(ValueError):
            faults.configure("init=fail@p0")

    def test_probabilistic_arming_deterministic(self):
        """`@pP` fires per-hit with probability P from an RNG seeded by
        the spec itself: the fire/skip sequence is identical across
        re-arms (chaos replay), skips consume no xN budget, and
        active() renders the probability back."""
        spec = "map_batch=lost:flaky@p0.3x2"

        def sequence(n: int) -> list[bool]:
            faults.configure(spec)
            out = []
            for _ in range(n):
                try:
                    faults.check("map_batch")
                    out.append(False)
                except runtime.DeviceLostError:
                    out.append(True)
            faults.disarm_all()
            return out

        a = sequence(40)
        assert a == sequence(40)     # bit-identical replay
        assert sum(a) == 2           # xN budget still bounds firings
        assert 0 < a.index(True)     # and some hits were skipped

        faults.configure(spec)
        assert faults.active() == {"map_batch": "lost:flaky@p0.3 x2"}

    def test_probabilistic_points_draw_independently(self):
        """Two points armed with the SAME action/arg/p must not fire in
        lockstep: the rng seed includes the armed point."""
        faults.configure("map_batch=lost@p0.5,epoch_apply=lost@p0.5")

        def seq(point, qual=None):
            out = []
            for _ in range(30):
                try:
                    faults.check(point, qual=qual)
                    out.append(False)
                except runtime.DeviceLostError:
                    out.append(True)
            return out

        assert seq("map_batch") != seq("epoch_apply")

    def test_probabilistic_skip_no_fallthrough(self):
        """A probabilistic skip on the specific match must not fall
        through to a bare always-fire entry."""
        faults.configure("stage=fail:generic,"
                         "stage.ec=fail:specific@p0.001x1")
        for _ in range(20):  # p=0.001: these hits all skip
            faults.check("stage", qual="ec")
        with pytest.raises(runtime.FaultInjected, match="generic"):
            faults.check("stage", qual="other")

    def test_disarmed_is_noop(self):
        faults.disarm_all()
        faults.check("init", qual="tpu")
        faults.check("anything")


# ---------------------------------------------------------------- preflight

@smoke
class TestPreflight:
    def test_inprocess_cpu_probe(self):
        r = runtime.probe("cpu", watchdog=False)
        assert r.ok and r.backend == "cpu" and r.n_devices >= 1

    def test_inprocess_probe_reports_injected_failure(self):
        faults.arm("init.cpu", "fail", "EAGAIN", 1)
        r = runtime.probe("cpu", watchdog=False)
        assert not r.ok and "EAGAIN" in r.error

    def test_diagnosis_never_empty(self):
        finds = runtime.diagnose_init_failure("tpu")
        assert finds and all(isinstance(f, str) for f in finds)


# ------------------------------------------------------------------- ladder

@smoke
class TestLadder:
    def test_retry_then_success_records_attempts(self):
        faults.arm("init.cpu", "fail", "flake", 2)
        info = runtime.acquire_backend(
            ladder=["cpu"], watchdog=False, attempts=3,
            sleep=lambda s: None,
        )
        assert info.backend == "cpu"
        assert info.attempts == 3
        assert info.fallback_reason is None  # first rung won in the end
        assert len(info.failures) == 2

    def test_degradation_records_fallback_reason(self):
        faults.arm("init.fakeaccel", "fail", "transport down")
        info = runtime.acquire_backend(
            ladder=["fakeaccel", "cpu"], watchdog=False, attempts=1,
        )
        assert info.backend == "cpu"
        assert "transport down" in info.fallback_reason
        assert info.rungs_tried == ["fakeaccel", "cpu"]
        prov = info.provenance()
        for key in ("backend", "fallback_reason", "attempts",
                    "init_seconds"):
            assert key in prov
        assert runtime.last_provenance()["backend"] == "cpu"

    def test_native_terminal_rung(self):
        faults.arm("init.cpu", "fail", "even cpu is gone")
        info = runtime.acquire_backend(
            ladder=["cpu", "native"], watchdog=False, attempts=1,
        )
        assert info.backend == "native"

    def test_ladder_exhausted_raises(self):
        faults.arm("init.cpu", "fail", "gone")
        with pytest.raises(runtime.RequiredBackendError, match="gone"):
            runtime.acquire_backend(
                ladder=["cpu"], watchdog=False, attempts=1,
            )

    def test_require_gate_blocks_degraded_result(self):
        faults.arm("init.faketpu", "fail", "down")
        with pytest.raises(runtime.RequiredBackendError, match="faketpu"):
            runtime.acquire_backend(
                ladder=["faketpu", "cpu"], watchdog=False, attempts=1,
                require="faketpu",
            )

    def test_backoff_is_exponential_and_bounded(self):
        slept = []
        faults.arm("init.cpu", "fail", "flake", 3)
        runtime.acquire_backend(
            ladder=["cpu"], watchdog=False, attempts=4,
            sleep=slept.append,
        )
        assert len(slept) == 3  # no sleep after the final success
        # base 2^i growth with jitter <= base/4, capped at BACKOFF_MAX_S
        from ceph_tpu.runtime import ladder as lad

        for i, s in enumerate(slept):
            base = min(lad.BACKOFF_BASE_S * (2 ** i), lad.BACKOFF_MAX_S)
            assert base <= s <= base * 1.25 + 1e-9
        assert slept[0] < slept[1] < slept[2]

    def test_watchdogged_hang_is_killed_and_degrades(self):
        # an injected init hang in the probe CHILD (the real stall site);
        # the parent watchdog kills it after ~1s of device-init budget
        faults.disarm_all()
        os.environ[faults.ENV_VAR] = "init.auto=hang:600"
        try:
            t0 = time.time()
            info = runtime.acquire_backend(
                ladder=["auto", "cpu"], timeout_s=1.0, attempts=1,
            )
        finally:
            del os.environ[faults.ENV_VAR]
        assert info.backend == "cpu"
        assert "hung" in info.fallback_reason
        assert info.attempts == 2
        # jax import in two probe children is real work; the *hang* only
        # cost the 1s watchdog budget
        assert time.time() - t0 < 45


# ------------------------------------------- scheduler checkpoint/resume

@smoke
class TestScheduler:
    def test_priority_order_beats_declaration_order(self, tmp_path):
        ran = []
        ck = runtime.Checkpoint(tmp_path / "ck.json")
        s = runtime.StageScheduler(ck, deadline_s=60)
        s.add("low", lambda h: ran.append("low") or {}, priority=10)
        s.add("high", lambda h: ran.append("high") or {}, priority=90)
        s.run()
        assert ran == ["high", "low"]

    def test_budget_skip_records_reason(self, tmp_path):
        ck = runtime.Checkpoint(tmp_path / "ck.json")
        s = runtime.StageScheduler(ck, deadline_s=5)
        s.add("huge", lambda h: {}, priority=90, est_s=500)
        s.add("fits", lambda h: {"ok": 1}, priority=10, est_s=1)
        out = s.run()
        assert "huge" not in out["stages_done"]
        assert out["huge_skipped"]["needed_s"] == 500
        assert "fits" in out["stages_done"]

    def test_failure_checkpointed_run_continues(self, tmp_path):
        ck = runtime.Checkpoint(tmp_path / "ck.json")
        s = runtime.StageScheduler(ck, deadline_s=60)

        def boom(h):
            raise ValueError("stage exploded")

        s.add("bad", boom, priority=90)
        s.add("good", lambda h: {"ok": 1}, priority=10)
        out = s.run()
        assert "ValueError" in out["errors"]["bad"]
        assert "good" in out["stages_done"]

    def test_overrun_watchdog_abandons_stage(self, tmp_path):
        faults.arm("stage.wedged", "overrun", "5", 1)
        ck = runtime.Checkpoint(tmp_path / "ck.json")
        s = runtime.StageScheduler(ck, deadline_s=60)
        s.add("wedged", lambda h: {"never": 1}, priority=90,
              soft_timeout_s=0.5)
        s.add("next", lambda h: {"ok": 1}, priority=10)
        t0 = time.time()
        out = s.run()
        assert time.time() - t0 < 3  # abandoned, not waited out
        assert "overrun" in out["errors"]["wedged"]
        assert "wedged" not in out["stages_done"]
        assert "next" in out["stages_done"]

    def test_resume_skips_done_keeps_results(self, tmp_path):
        p = tmp_path / "ck.json"
        ck = runtime.Checkpoint(p)
        s = runtime.StageScheduler(ck, deadline_s=60)
        s.add("a", lambda h: {"v": 1}, priority=90)
        s.run()
        # second run: a must not re-run; b is new work
        ran = []
        ck2 = runtime.Checkpoint(p, resume=True)
        s2 = runtime.StageScheduler(ck2, deadline_s=60)
        s2.add("a", lambda h: ran.append("a") or {"v": 99}, priority=90)
        s2.add("b", lambda h: ran.append("b") or {"v": 2}, priority=10)
        out = s2.run()
        assert ran == ["b"]
        assert out["a"]["v"] == 1  # original result survived
        assert out["resumed_stages"] == ["a"]
        assert out["resumed"] == 1

    def test_checkpoint_atomic_and_progress_not_done(self, tmp_path):
        p = tmp_path / "ck.json"
        ck = runtime.Checkpoint(p)
        ck.progress("partial_stage", {"rounds": 1})
        on_disk = json.loads(p.read_text())
        assert on_disk["partial_stage"]["rounds"] == 1
        assert "partial_stage" not in on_disk["stages_done"]
        # resume re-runs a stage that only has partial progress
        ck2 = runtime.Checkpoint(p, resume=True)
        assert not ck2.done("partial_stage")


# ----------------------------------------------------- bench integration

def _run_bench(tmp_path, env_extra, args=(), timeout=300):
    env = dict(os.environ)
    env.pop("BENCH_WORKER", None)
    env.pop("BENCH_REQUIRE_TPU", None)
    env.update({
        # miniature sizes; cfg2/headline share shapes for cache reuse
        "BENCH_PGS": "8192", "BENCH_OSDS": "256", "BENCH_CHUNK": "4096",
        "BENCH_CFG2_PGS": "4096", "BENCH_CFG2_OSDS": "256",
        "BENCH_BASELINE_PGS": "20000", "BENCH_EC_MB": "2",
        "BENCH_NS_PGS": "2048", "BENCH_NS_OSDS": "64",
        "BENCH_NS_ROUNDS": "2", "BENCH_REPS": "1",
        "BENCH_DEADLINE_S": "240", "BENCH_HEADLINE_RESERVE": "20",
        "BENCH_SKIP_EC": "1",
        "BENCH_FORCE_CPU": "1",
        "BENCH_PARTIAL": str(tmp_path / "partial.json"),
    })
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    return proc, out


@pytest.mark.slow
def test_bench_resume_after_midrun_kill(tmp_path):
    """bench.py --resume: a worker killed right after checkpointing the
    first mapping config must, on resume, skip it and finish the rest."""
    # run 1: die (os._exit, SIGKILL-grade) after crushtool_1k_32 lands
    proc, out = _run_bench(
        tmp_path,
        {"CEPH_TPU_FAULTS": "stage_end.crushtool_1k_32=exit:9 x1"},
    )
    partial = json.loads((tmp_path / "partial.json").read_text())
    assert "crushtool_1k_32" in partial["stages_done"]
    assert "headline" not in partial["stages_done"]
    stamp = partial["crushtool_1k_32"]["hist_checksum"]

    # run 2: --resume finishes the remainder without re-running stage 1
    proc2, out2 = _run_bench(tmp_path, {}, args=("--resume",))
    assert "crushtool_1k_32" in out2.get("resumed_stages", [])
    for stage in ("crushtool_1k_32", "testmappgs_100k_1k", "rebalance",
                  "headline"):
        assert stage in out2["stages_done"], stage
    # identical object proves it was resumed, not recomputed
    assert out2["configs"]["crushtool_1k_32"]["hist_checksum"] == stamp
    assert any("resumed" in n for n in out2.get("notes", []))


def test_bench_minimal_run_records_provenance(tmp_path):
    """Cheap tier-1 gate: one real bench run (CPU ladder, tiny deadline)
    must complete its first mapping config, budget-skip the stages that
    cannot fit, and carry acquisition provenance in the output JSON."""
    # deadline 45: cfg1 (min budget 25) always fits after a ~6s cpu
    # acquisition; rebalance (100) and headline (90) can never fit, so
    # their budget-skips are deterministic; everything lands well before
    # the supervisor's kill
    proc, out = _run_bench(tmp_path, {"BENCH_DEADLINE_S": "45"})
    assert proc.returncode == 0
    assert out["backend"] == "cpu"
    assert out["attempts"] >= 1
    assert "init" in out["stages_done"]
    assert "crushtool_1k_32" in out["stages_done"]
    assert "rebalance_skipped" in out["stages_done"]
    assert "headline_skipped" in out["stages_done"]
    partial = json.loads((tmp_path / "partial.json").read_text())
    assert partial["rebalance_skipped"]["needed_s"] == 100


@smoke
@pytest.mark.slow
def test_bench_selftest():
    """The survivability gate: injected TPU-init hang, every stage
    (including the miniature rebalance and the 510-epoch lifetime chaos
    scenario) must complete with degradation provenance.  Minutes-scale
    on a throttled container; in the smoke tier and full runs (slow:
    the jax worker compiles and the lifetime epochs are far too heavy
    for the tier-1 budget — the scheduler/ladder units, the minimal
    bench run above, and tests/test_lifetime.py cover this layer
    there)."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--selftest"],
        capture_output=True, text=True, timeout=900,
        cwd=str(REPO),
        env={k: v for k, v in os.environ.items()
             if k not in ("BENCH_WORKER", "BENCH_REQUIRE_TPU")},
    )
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert proc.returncode == 0, verdict
    assert verdict["selftest"] == "ok", verdict
    assert verdict["backend"] == "cpu"
    assert verdict["attempts"] >= 2
    assert "rebalance" in verdict["stages_done"]


# -------------------------------------------- degraded-mode admin surface

@smoke
def test_daemon_runtime_command():
    from ceph_tpu.obs import admin_socket

    faults.arm("init.xpu", "fail", "down")
    out = json.loads(admin_socket.handle_command("runtime"))
    assert "provenance" in out
    assert out["faults_armed"] == {"init.xpu": "fail:down"}
    assert "cpu" in out["default_ladder"] or out["default_ladder"]
