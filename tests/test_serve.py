"""Placement serving daemon: micro-batching, admission control,
deadlines, epoch swaps, device-loss degradation, crash-restart, and the
chaos-client harness.

Tier-1 runs only small in-process variants against ONE module-scoped
service (one compile set; the tier-1 budget is nearly spent — see
ROADMAP).  The sustained chaos run and the subprocess kill/restart
test ride the slow tier."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu.core.intmath import pg_mask_for, stable_mod
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import Incremental
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgId, PgPool, PoolType
from ceph_tpu.runtime import faults
from ceph_tpu.serve import PlacementService, ServeConfig

REPO = Path(__file__).resolve().parents[1]

N_PGS = 256
N_OSDS = 16


def _map():
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=N_PGS, pgp_num=N_PGS)
    return build_hierarchical(4, 4, n_rack=1, pool=pool)


def _cfg(**kw):
    base = dict(window_s=0.02, block=64, fill=512, max_queue=8,
                deadline_s=5.0, degraded_batches=1, bulk_max=256)
    base.update(kw)
    return ServeConfig(**base)


@pytest.fixture(scope="module")
def svc():
    s = PlacementService(_map(), config=_cfg(), name="test.serve")
    yield s
    s.close()


@pytest.fixture(scope="module")
def svc2():
    """Two-pool service (the meshcheck witness map at small size) for
    the mixed-pool bulk tests; prewarm off — the overlay variants are
    already exercised through the main fixture."""
    from ceph_tpu.serve.meshcheck import build_default

    s = PlacementService(build_default(pgs=64, osds=8),
                         config=_cfg(prewarm=False), name="test.serve2")
    yield s
    s.close()


def _oracle_rows(m, pid, seeds, width):
    up = np.full((len(seeds), width), ITEM_NONE, np.int32)
    act = np.full((len(seeds), width), ITEM_NONE, np.int32)
    upp = np.full(len(seeds), -1, np.int32)
    actp = np.full(len(seeds), -1, np.int32)
    for i, s in enumerate(seeds):
        u, u_p, a, a_p = m.pg_to_up_acting_osds(PgId(pid, int(s)))
        up[i, : len(u)] = u[:width]
        act[i, : len(a)] = a[:width]
        upp[i], actp[i] = u_p, a_p
    return up, upp, act, actp


# -- answering --------------------------------------------------------------

def test_lookup_matches_host_oracle(svc):
    seeds = np.asarray([0, 1, 42, 137, 255], np.uint32)
    r = svc.lookup_batch(0, seeds)
    assert r.ok and r.source == "device" and r.epoch == svc.epoch
    up, upp, act, actp = _oracle_rows(svc._active.m, 0, seeds,
                                      r.up.shape[1])
    assert np.array_equal(r.up, up)
    assert np.array_equal(r.up_primary, upp)
    assert np.array_equal(r.acting, act)
    assert np.array_equal(r.acting_primary, actp)


def test_object_query_matches_osdmaptool_semantics(svc):
    name = "rbd_data.1f3a.0000000000000007"
    r = svc.lookup_object(0, name)
    assert r.ok
    pool = svc._active.m.pools[0]
    ps = pool.hash_key(name)
    seed = int(stable_mod(ps, pool.pg_num, pg_mask_for(pool.pg_num)))
    want = svc.lookup(0, seed)
    assert np.array_equal(r.acting, want.acting)
    assert r.acting_primary[0] == want.acting_primary[0]


def test_unknown_pool_answers_efault(svc):
    r = svc.lookup(99, 0)
    assert r.status == "EFAULT" and "no pool" in r.error


def test_micro_batching_coalesces_concurrent_requests(svc):
    from ceph_tpu import obs

    svc.pause()
    out: list = []
    ths = [threading.Thread(
        target=lambda i=i: out.append(
            svc.lookup_batch(0, np.arange(i * 10, i * 10 + 10))))
        for i in range(6)]
    for t in ths:
        t.start()
    deadline = time.time() + 5
    while len(svc._q) < 6 and time.time() < deadline:
        time.sleep(0.01)
    before = obs.perf_dump()["serve"]["batches"]
    svc.unpause()
    for t in ths:
        t.join(timeout=30)
    assert len(out) == 6 and all(r.ok for r in out)
    # six concurrent requests coalesced into ONE device dispatch batch
    assert obs.perf_dump()["serve"]["batches"] - before == 1


# -- overload + deadlines ---------------------------------------------------

def test_admission_control_sheds_with_ebusy_never_drops(svc):
    svc.pause()
    replies: list = []
    lock = threading.Lock()

    def go():
        r = svc.lookup_batch(0, [1, 2, 3], deadline_s=10.0)
        with lock:
            replies.append(r)

    n = svc.config.max_queue + 4
    ths = [threading.Thread(target=go) for _ in range(n)]
    for t in ths:
        t.start()
    deadline = time.time() + 5
    while time.time() < deadline:
        with lock:
            shed = len(replies)
        if len(svc._q) + shed >= n:
            break
        time.sleep(0.01)
    svc.unpause()
    for t in ths:
        t.join(timeout=30)
    by = {}
    for r in replies:
        by[r.status] = by.get(r.status, 0) + 1
    # every request answered (nothing dropped); exactly the overflow
    # shed with an explicit EBUSY
    assert len(replies) == n
    assert by.get("EBUSY") == 4, by
    assert by.get("ok") == svc.config.max_queue, by


def test_expired_deadline_answers_etimedout(svc):
    svc.pause()
    try:
        t0 = time.perf_counter()
        r = svc.lookup(0, 7, deadline_s=0.05)
        dt = time.perf_counter() - t0
        assert r.status == "ETIMEDOUT"
        assert dt < 2.0  # the watchdogged wait, not a hang
    finally:
        svc.unpause()


# -- epoch swaps ------------------------------------------------------------

def test_epoch_swap_serves_new_map_and_books_zero_compiles(svc):
    from ceph_tpu import obs

    e0 = svc.epoch
    jit0 = obs.jit_counters()
    inc = Incremental(epoch=e0 + 1)
    inc.new_weight[3] = int(0x10000 * 0.5)
    res = svc.apply(inc)
    assert res["ok"] and svc.epoch == e0 + 1
    seeds = np.arange(64, dtype=np.uint32)
    r = svc.lookup_batch(0, seeds)
    assert r.ok and r.epoch == e0 + 1
    _, _, act, actp = _oracle_rows(svc._active.m, 0, seeds,
                                   r.acting.shape[1])
    assert np.array_equal(r.acting, act)
    assert np.array_equal(r.acting_primary, actp)
    # a value-only epoch swap is an operand refresh: staging, warm
    # dispatch and the post-swap queries all ride _PIPE_CACHE
    jd = obs.jit_counters_delta(jit0)
    assert jd["compiles"] == 0 and jd["retraces"] == 0, jd
    # the reader-visible stall was measured and is tiny
    stall = obs.perf_dump()["serve"]["swap_stall_seconds"]
    assert stall["count"] >= 1
    assert stall["max"] < 0.05


def test_readers_drain_during_swap(svc):
    """Queries submitted while a swap stages are answered (on whichever
    buffer they captured), never dropped or blocked past the deadline."""
    stop = threading.Event()
    replies: list = []

    def reader():
        while not stop.is_set():
            replies.append(svc.lookup_batch(0, np.arange(32)))

    t = threading.Thread(target=reader)
    t.start()
    try:
        for _ in range(3):
            inc = Incremental(epoch=svc.epoch + 1)
            inc.new_weight[5] = int(0x10000 * 0.9)
            assert svc.apply(inc)["ok"]
    finally:
        stop.set()
        t.join(timeout=30)
    assert replies and all(r.ok for r in replies)


def test_epoch_swap_fault_leaves_old_epoch_serving(svc):
    e0 = svc.epoch
    faults.arm("epoch_swap", "fail", "staging blew up", 1)
    try:
        res = svc.apply(Incremental(epoch=e0 + 1))
    finally:
        faults.disarm("epoch_swap")
    assert not res["ok"] and "staging blew up" in res["error"]
    assert svc.epoch == e0
    r = svc.lookup(0, 3)
    assert r.ok and r.epoch == e0


# -- device loss ------------------------------------------------------------

def test_device_loss_degrades_answers_and_recovers(svc):
    seeds = np.asarray([5, 9, 100, 200], np.uint32)
    base = svc.lookup_batch(0, seeds)
    assert base.ok and base.source == "device"
    faults.arm("serve_dispatch", "lost", "mid-traffic loss", 1)
    try:
        r1 = svc.lookup_batch(0, seeds)  # the lost batch: answered
        r2 = svc.lookup_batch(0, seeds)  # degraded spell (1 batch)
        r3 = svc.lookup_batch(0, seeds)  # recovery: device again
    finally:
        faults.disarm("serve_dispatch")
    assert r1.ok and r1.source == "host"
    assert r2.ok and r2.source == "host"
    assert r3.ok and r3.source == "device"
    # bit-exact degradation: same padded bytes from both paths
    for r in (r1, r2, r3):
        assert np.array_equal(r.acting, base.acting)
        assert np.array_equal(r.acting_primary, base.acting_primary)
    prov = svc.provenance()
    assert prov["device_loss_fallbacks"] >= 1
    assert any("host mapper" in e for e in prov["fallback_events"])
    assert any(e.startswith("recovered") for e in prov["fallback_events"])
    from ceph_tpu import obs

    d = obs.perf_dump()["serve"]
    assert d["degraded_answered"] >= 2 * len(seeds)
    assert d["device_recoveries"] >= 1


# -- introspection ----------------------------------------------------------

def test_serve_status_admin_command(svc):
    from ceph_tpu.obs.admin_socket import handle_command

    out = json.loads(handle_command("serve status"))
    st = out["services"]["test.serve"]
    assert st["epoch"] == svc.epoch
    assert st["queries"] > 0
    assert 0 in st["pools"]
    assert st["config"]["block"] == svc.config.block


# -- crash-restart ----------------------------------------------------------

def test_checkpoint_restart_resumes_epoch_and_answers_identically(
        tmp_path):
    ck = str(tmp_path / "serve_ck.json")
    s1 = PlacementService(_map(), config=_cfg(), checkpoint=ck,
                          name="test.ck1")
    try:
        for _ in range(2):
            inc = Incremental(epoch=s1.epoch + 1)
            inc.new_weight[1] = int(0x10000 * 0.75)
            assert s1.apply(inc)["ok"]
        epoch = s1.epoch
        digest = s1.sample_digest()
        spot = s1.lookup_batch(0, np.arange(16))
    finally:
        s1.close()  # a clean close; the kill variant rides the slow tier
    s2 = PlacementService(config=_cfg(), checkpoint=ck, resume=True,
                          name="test.ck2")
    try:
        assert s2.resumed_from == epoch and s2.epoch == epoch
        assert s2.sample_digest() == digest
        again = s2.lookup_batch(0, np.arange(16))
        assert np.array_equal(again.acting, spot.acting)
        assert np.array_equal(again.acting_primary, spot.acting_primary)
    finally:
        s2.close()


def test_resume_without_state_raises(tmp_path):
    with pytest.raises(ValueError, match="needs a map"):
        PlacementService(config=_cfg(),
                         checkpoint=str(tmp_path / "empty.json"),
                         resume=True)


# -- bulk protocol edge -----------------------------------------------------

def test_query_block_matches_host_oracle(svc):
    rng = np.random.default_rng(7)
    seeds = rng.integers(0, N_PGS, 500).astype(np.uint32)
    r = svc.query_block(0, seeds)
    assert r.ok and r.source == "device" and r.epoch == svc.epoch
    up, upp, act, actp = _oracle_rows(svc._active.m, 0, seeds,
                                      r.up.shape[1])
    assert np.array_equal(r.up, up)
    assert np.array_equal(r.up_primary, upp)
    assert np.array_equal(r.acting, act)
    assert np.array_equal(r.acting_primary, actp)
    # the scalar edge is a thin wrapper over the same answers
    for i in (0, 137, 499):
        s = svc.submit(0, int(seeds[i]))
        assert s.ok
        assert np.array_equal(s.acting[0], r.acting[i])
        assert int(s.acting_primary[0]) == int(r.acting_primary[i])


def test_bulk_partial_shed_answers_every_lane(svc):
    """An oversized block sheds the over-capacity tail EBUSY per-lane
    — the granted lanes still answer with correct rows and every lane
    carries exactly one status (dropped == 0 by construction)."""
    cap = svc.config.max_queue * svc.config.block  # bulk lane bound
    n = cap + 488
    seeds = (np.arange(n, dtype=np.uint32) * 3) % N_PGS
    r = svc.query_block(0, seeds)
    c = r.counts()
    assert c == {"ok": cap, "EBUSY": n - cap}
    assert sum(c.values()) == n  # nothing dropped
    assert "capacity" in r.error
    up, upp, act, actp = _oracle_rows(svc._active.m, 0, seeds[:cap],
                                      r.up.shape[1])
    assert np.array_equal(r.acting[:cap], act)
    assert np.array_equal(r.acting_primary[:cap], actp)
    # shed lanes carry NONE-padded rows, not stale answers
    assert (r.acting[cap:] == ITEM_NONE).all()
    assert (r.acting_primary[cap:] == -1).all()


def test_bulk_deadline_expiry_answers_etimedout_remainder(svc):
    """A stalled first sub-block spends the deadline; the remaining
    lanes answer ETIMEDOUT instead of blocking or vanishing."""
    sub = max(svc.config.bulk_max, svc.config.block)
    seeds = np.arange(2 * sub, dtype=np.uint32) % N_PGS
    faults.arm("serve_dispatch.test.serve", "stall", "0.5", 1)
    try:
        r = svc.query_block(0, seeds, deadline_s=0.25)
    finally:
        faults.disarm("serve_dispatch.test.serve")
    assert r.counts() == {"ok": sub, "ETIMEDOUT": sub}
    assert "deadline" in r.error
    up, upp, act, actp = _oracle_rows(svc._active.m, 0, seeds[:sub],
                                      r.up.shape[1])
    assert np.array_equal(r.acting[:sub], act)
    assert np.array_equal(r.acting_primary[:sub], actp)


def test_bulk_and_scalar_interleave_equivalence(svc):
    """Caller-thread bulk blocks beside queued scalar traffic: both
    paths answer the host-mapper oracle bit-exactly while interleaved."""
    rng = np.random.default_rng(11)
    scalar_out: list = []
    stop = threading.Event()

    def scalar_client():
        while not stop.is_set():
            s = int(rng.integers(0, N_PGS))
            scalar_out.append((s, svc.submit(0, s)))

    t = threading.Thread(target=scalar_client)
    t.start()
    try:
        m = svc._active.m
        for _ in range(5):
            seeds = rng.integers(0, N_PGS, 300).astype(np.uint32)
            r = svc.query_block(0, seeds)
            assert r.ok
            _, _, act, actp = _oracle_rows(m, 0, seeds, r.up.shape[1])
            assert np.array_equal(r.acting, act)
            assert np.array_equal(r.acting_primary, actp)
    finally:
        stop.set()
        t.join(timeout=30)
    assert scalar_out and all(rep.ok for _, rep in scalar_out)
    m = svc._active.m
    for s, rep in scalar_out[:20]:
        _, _, act, actp = _oracle_rows(m, 0, np.asarray([s]),
                                       rep.acting.shape[1])
        assert np.array_equal(rep.acting, act)
        assert int(rep.acting_primary[0]) == int(actp[0])


def test_submit_many_mixed_pools_scatters_in_input_order(svc2):
    m = svc2._active.m
    p0, p1 = sorted(m.pools)[:2]
    rng = np.random.default_rng(23)
    pools = rng.choice([p0, p1], 240)
    lo = min(m.pools[p0].pg_num, m.pools[p1].pg_num)
    seeds = rng.integers(0, lo, 240).astype(np.uint32)
    r = svc2.submit_many(pools, seeds)
    assert r.ok and r.epoch == svc2.epoch
    W = r.up.shape[1]
    assert W == max(m.pools[p0].size, m.pools[p1].size)
    for pid in (p0, p1):
        mask = pools == pid
        up, upp, act, actp = _oracle_rows(m, pid, seeds[mask], W)
        assert np.array_equal(r.up[mask], up)
        assert np.array_equal(r.acting[mask], act)
        assert np.array_equal(r.acting_primary[mask], actp)
    # scalar-pool fast path and the shape-mismatch EFAULT answer
    one = svc2.submit_many([p0], seeds[:16])
    assert one.ok and one.up.shape[1] == m.pools[p0].size
    bad = svc2.submit_many(pools[:5], seeds[:7])
    assert bad.counts() == {"EFAULT": 7} and "mismatch" in bad.error


def test_closed_service_answers_eshutdown():
    from ceph_tpu.serve.meshcheck import build_default

    s = PlacementService(build_default(pgs=64, osds=8),
                         config=_cfg(prewarm=False), name="test.shut")
    s.close()
    r = s.query_block(0, np.arange(8, dtype=np.uint32))
    assert r.counts() == {"ESHUTDOWN": 8}
    assert s.lookup(0, 0).status == "ESHUTDOWN"


def test_serve_status_carries_bulk_and_swap_surface(svc):
    st = svc.status()
    assert st["bulk_blocks"] >= 1
    assert st["bulk_lookups"] >= 1
    assert st["structural_swap_stalls"] == 0
    assert st["prewarmed_structures"] >= 2
    assert st["config"]["bulk_max"] == svc.config.bulk_max
    # micro-batch fill quantile: visible once the queued path ran
    assert st["batch_fill_p50"] is not None
    assert st["batch_fill_p99"] is not None
    assert st["mesh"]["devices"] >= 1


# -- mesh-sharded serving buffer --------------------------------------------

@pytest.mark.slow
def test_mesh_sharded_bulk_bit_identical_across_devices(svc2):
    """The PG axis of the serving buffer shards over the forced-device
    mesh exactly like ClusterState; the placement digest over every PG
    of every pool must be bit-identical on 1 vs 2 devices (and match
    the host oracle on both legs).  Slow: spawns a fresh interpreter
    (full jax import) for the 2-device leg; the same identity is gated
    every bench --selftest run."""
    from ceph_tpu.serve.meshcheck import placement_digest

    digest1, oracle1 = placement_digest(svc2, svc2._active.m)
    assert oracle1
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CEPH_TPU_MESH_DEVICES="2",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    p = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.serve.meshcheck",
         "--pgs", "64", "--osds", "8"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert p.returncode == 0, (p.returncode, p.stderr[-800:])
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["devices"] == 2
    assert out["oracle_match"] is True
    assert out["mesh"]["devices"] == 2
    prov = out["mesh"]["provenance"]
    assert prov["actual"] == 2 and not prov["degraded"]
    assert out["digest"] == digest1


# -- multi-replica front ----------------------------------------------------

def test_front_bit_identical_and_staggered_fanout():
    from ceph_tpu.serve.front import ServeFront

    f = ServeFront(_map(), replicas=2, config=_cfg(), name="test.front")
    try:
        rng = np.random.default_rng(3)
        seeds = rng.integers(0, N_PGS, 300).astype(np.uint32)
        r = f.query_block(0, seeds)
        assert r.ok and r.epoch == f.epoch
        m = f.replicas[0]._active.m
        up, upp, act, actp = _oracle_rows(m, 0, seeds, r.up.shape[1])
        assert np.array_equal(r.up, up)
        assert np.array_equal(r.acting, act)
        assert np.array_equal(r.acting_primary, actp)
        sc = f.lookup(0, int(seeds[0]))
        assert sc.ok
        assert np.array_equal(sc.acting[0], r.acting[0])
        # staggered epoch fan-out: both replicas land the epoch, the
        # front keeps answering, never two replicas staging at once
        e0 = f.epoch
        inc = Incremental(epoch=e0 + 1)
        inc.new_weight[3] = int(0x10000 * 0.5)
        res = f.apply(inc)
        assert res["ok"] and f.epoch == e0 + 1
        assert [rep.epoch for rep in f.replicas] == [e0 + 1, e0 + 1]
        st = f.status()
        assert st["front_staggered_swaps"] >= 1
        assert st["staging"] == []
        r2 = f.query_block(0, seeds)
        assert r2.ok and r2.epoch == e0 + 1
        m2 = f.replicas[0]._active.m
        _, _, act2, actp2 = _oracle_rows(m2, 0, seeds, r2.up.shape[1])
        assert np.array_equal(r2.acting, act2)
        assert np.array_equal(r2.acting_primary, actp2)
    finally:
        f.close()


def test_front_sheds_stalled_replica():
    """An injected stall on ONE replica (`serve_dispatch.<name>`) is
    absorbed: the front sheds the slow replica after one slow block,
    remaps only its lanes (rendezvous exclusion), and every block
    keeps answering ok."""
    from ceph_tpu.serve.front import ServeFront

    f = ServeFront(_map(), replicas=2, config=_cfg(), name="test.shed")
    try:
        seeds = np.arange(64, dtype=np.uint32)
        for _ in range(3):  # settle both replicas' latency EWMA
            assert f.query_block(0, seeds).ok
        st0 = f.status()
        faults.arm("serve_dispatch.test.shed.r1", "stall", "0.5", 1)
        try:
            replies = [f.query_block(0, seeds) for _ in range(6)]
        finally:
            faults.disarm("serve_dispatch.test.shed.r1")
        assert all(r.ok for r in replies)  # absorbed, never surfaced
        st = f.status()
        assert st["front_replica_sheds"] > st0["front_replica_sheds"]
        assert st["front_shed_routes"] > st0["front_shed_routes"]
    finally:
        f.close()


# -- chaos + kill/restart (slow tier) ---------------------------------------

CHAOS_SCENARIO = (
    "hosts=4,osds_per_host=3,racks=1,pgs=32,ec=,size=3,"
    "balance_every=0,p_pg_temp=0,p_split=0,p_pool_create=0,"
    "p_expand=0,p_remove=0,p_death=0.1,p_flap=0.5,p_reweight=0.3,"
    "spotcheck_every=0,checkpoint_every=0,seed=31"
)


@pytest.mark.slow
def test_sustained_chaos_never_drops_under_churn():
    from ceph_tpu.serve.chaos import run_chaos

    out = run_chaos(scenario=CHAOS_SCENARIO, epochs=24,
                    config=_cfg(block=32, deadline_s=10.0),
                    clients=2, client_batch=64)
    assert out["dropped"] == 0
    assert out["answered_ok"] > 0
    assert out["swaps_ok"] + out["swaps_rejected"] == 24
    assert out["swaps_ok"] >= 20
    assert out["sim_violations"] == 0
    assert out["p99_s"] is not None and out["p99_s"] > 0
    assert out["swap_stall_p99_s"] is not None


@pytest.mark.slow
def test_cli_kill_mid_serve_and_restart_answers_identically(tmp_path):
    """The crash-restart acceptance proof: the daemon dies (exit:9 via
    the serve_dispatch fault) mid-chaos after several epoch swaps; a
    restart with --resume serves the checkpointed epoch and produces
    the same sample digest as an independent in-process resume from the
    same checkpoint, plus a host-oracle spot check."""
    ck = str(tmp_path / "serve_kill_ck.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CEPH_TPU_FAULTS="serve_dispatch.30=exit:9")
    p = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.cli.serve", "chaos",
         "--scenario", CHAOS_SCENARIO, "--epochs", "40",
         "--checkpoint", ck, "--clients", "2", "--batch", "32",
         "--json"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert p.returncode == 9, (p.returncode, p.stderr[-500:])
    state = json.loads(Path(ck).read_text())["serve"]
    assert state["epoch"] >= 2  # swaps landed before the kill
    env.pop("CEPH_TPU_FAULTS")
    p2 = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.cli.serve", "chaos",
         "--checkpoint", ck, "--resume", "--clients", "1",
         "--batch", "32", "--json"],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert p2.returncode == 0, (p2.returncode, p2.stderr[-500:])
    out = json.loads(p2.stdout.strip().splitlines()[-1])
    assert out["resumed_epoch"] == state["epoch"]
    assert out["dropped"] == 0
    # independent resume from the same checkpoint answers identically
    svc = PlacementService(config=_cfg(), checkpoint=ck, resume=True,
                           name="test.kill")
    try:
        assert svc.epoch == state["epoch"]
        assert svc.sample_digest() == out["sample_digest"]
        # host-oracle spot check through the full client path
        m = svc._active.m
        for seed in (0, 7, 19):
            r = svc.lookup(0, seed)
            _, _, a, ap = m.pg_to_up_acting_osds(PgId(0, seed))
            got = [int(o) for o in r.acting[0] if o != ITEM_NONE]
            assert got == list(a) and int(r.acting_primary[0]) == ap
    finally:
        svc.close()
