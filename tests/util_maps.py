"""Shared test-map construction: builds identical maps in our Python model and
(optionally) in the compiled C oracle, so outputs can be compared bit-exactly.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.crush.types import BucketAlg, CrushMap, Rule, RuleOp, Tunables

HOST = 1
RACK = 2
ROOT = 3


def build_flat(n_osd=32, alg=BucketAlg.STRAW2, weights=None, tunables=None):
    """One root bucket holding n_osd devices."""
    m = CrushMap(tunables)
    if weights is None:
        weights = [0x10000] * n_osd
    root = m.add_bucket(alg, ROOT, list(range(n_osd)), weights, name="root")
    return m, root


def build_tree(
    rng: np.random.Generator,
    n_host=8,
    osd_per_host=4,
    host_alg=BucketAlg.STRAW2,
    root_alg=BucketAlg.STRAW2,
    weight_fn=None,
    tunables=None,
    n_rack=0,
):
    """hosts of osds under (optional racks under) one root.  weight_fn(osd_id)
    gives the 16.16 device weight (uniform buckets force equal weights)."""
    m = CrushMap(tunables)
    host_ids = []
    osd = 0
    for h in range(n_host):
        items = list(range(osd, osd + osd_per_host))
        if weight_fn is None or host_alg == BucketAlg.UNIFORM:
            ws = [0x10000] * osd_per_host
        else:
            ws = [int(weight_fn(i)) for i in items]
        hid = m.add_bucket(host_alg, HOST, items, ws, name=f"host{h}")
        host_ids.append((hid, sum(ws)))
        osd += osd_per_host
    if n_rack:
        per = max(1, n_host // n_rack)
        rack_ids = []
        for r in range(n_rack):
            hs = host_ids[r * per : (r + 1) * per] or [host_ids[-1]]
            rid = m.add_bucket(
                BucketAlg.STRAW2,
                RACK,
                [h for h, _ in hs],
                [w for _, w in hs],
                name=f"rack{r}",
            )
            rack_ids.append((rid, sum(w for _, w in hs)))
        top = rack_ids
    else:
        top = host_ids
    root = m.add_bucket(
        root_alg, ROOT, [b for b, _ in top], [w for b, w in top], name="root"
    )
    return m, root


def to_oracle(m: CrushMap, tunables: Tunables | None = None):
    """Mirror a CrushMap into the C oracle (same construction order =>
    same bucket ids).  Returns the OracleMap."""
    from oracle import OracleMap

    om = OracleMap(tunables or m.tunables)
    # insert in id order -1, -2, ... to reproduce sequential id assignment
    for bid in sorted(m.buckets.keys(), reverse=True):
        b = m.buckets[bid]
        got = om.add_bucket(int(b.alg), b.hash, b.type, b.items, b.weights)
        assert got == bid, (got, bid)
    for r in m.rules:
        assert r is not None
        om.add_rule(
            [(int(op), a1, a2) for op, a1, a2 in r.steps],
            ruleset=r.ruleset,
            type_=r.type,
            minsize=r.min_size,
            maxsize=r.max_size,
        )
    om.finalize()
    return om


def replicated_rule(m: CrushMap, root: int, fd_type=0, numrep=0):
    return m.make_replicated_rule(root, fd_type, numrep)


def ec_rule(m: CrushMap, root: int, fd_type=0, k_m=0):
    return m.make_erasure_rule(root, fd_type, k_m)
