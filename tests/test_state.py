"""ClusterState: delta-vs-rebuild classification, O(delta) on-device
apply (counter-proven: 0 compiles, 0 full-table device_puts on a
value-only chain), bit-identical rows vs a from-scratch build and the
host oracle, device-resident raw fixups, and the serve fork.

Tier-1 keeps ONE tiny module-scoped cluster (one compile set shared
through _PIPE_CACHE; the budget is nearly spent — see ROADMAP); the
at-scale variant rides the slow tier."""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import Incremental, apply_incremental
from ceph_tpu.osd.osdmap import (
    IN_WEIGHT,
    OSD_EXISTS,
    OSD_UP,
    build_hierarchical,
)
from ceph_tpu.osd.state import (
    ClusterState,
    classify_incremental,
    value_copy_map,
)
from ceph_tpu.osd.types import PgId, PgPool, PoolType

N_PGS = 32
N_OSDS = 8


def _mk_map():
    return build_hierarchical(4, 2, n_rack=2, pool=PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=N_PGS, pgp_num=N_PGS))


def _oracle_up(m, pid, seed):
    up, _, _, _ = m.pg_to_up_acting_osds(PgId(pid, int(seed)))
    return up


# ------------------------------------------------- classification (no jax)


def _inc(m, **kw):
    inc = Incremental(epoch=m.epoch + 1)
    for k, v in kw.items():
        setattr(inc, k, v)
    return inc


def test_classify_value_only_deltas():
    m = _mk_map()
    for kw in (
        {"new_weight": {2: IN_WEIGHT // 2}},          # reweight
        {"new_state": {1: OSD_UP}},                   # flap down/up
        {"new_primary_affinity": {3: 0x8000}},        # affinity (first!)
        {"new_pg_temp": {PgId(0, 4): [1, 2, 3]}},     # acting override
        {"new_primary_temp": {PgId(0, 4): 2}},
        {"new_pg_upmap_items": {PgId(0, 5): [(1, 6)]}},
        {"old_pg_upmap_items": {PgId(0, 5)}},
        {"new_flags": 0x8000},
    ):
        kind, info = classify_incremental(_inc(m, **kw), m)
        assert kind == "delta", kw
    # destroy (state XOR with EXISTS on an existing osd) is value-only
    # but raw-changing (EXISTS feeds the descent's nonexistent filter)
    kind, info = classify_incremental(
        _inc(m, new_state={2: OSD_EXISTS}), m)
    assert kind == "delta" and info["raw"]
    # flaps change the up filter only: raw survives
    kind, info = classify_incremental(_inc(m, new_state={2: OSD_UP}), m)
    assert kind == "delta" and not info["raw"]
    # upmap deltas name their pool
    kind, info = classify_incremental(
        _inc(m, new_pg_upmap_items={PgId(0, 5): [(1, 6)]}), m)
    assert info["upmap_pools"] == {0}


def test_classify_structural_deltas():
    m = _mk_map()
    # max_osd growth
    assert classify_incremental(_inc(m, new_max_osd=16), m)[0] \
        == "rebuild"
    # pg_num split of an EXISTING pool
    inc = Incremental(epoch=m.epoch + 1)
    pool = inc.get_new_pool(0, m.pools[0])
    pool.pg_num *= 2
    assert classify_incremental(inc, m)[0] == "rebuild"
    # a structural crush change (tree edit)
    import copy

    from ceph_tpu.crush.codec import encode_crushmap

    c2 = copy.deepcopy(m.crush)
    c2.insert_item(8, 1.0, "osd.8", {"host": "hostX", "root": "default"})
    assert classify_incremental(
        _inc(m, crush=encode_crushmap(c2)), m)[0] == "rebuild"
    # an out-of-range osd id cannot be a vector scatter
    assert classify_incremental(
        _inc(m, new_weight={99: IN_WEIGHT}), m)[0] == "rebuild"
    # a brand-NEW pool is value-only (no device operand changes; its
    # caches build lazily) — the old steady-epoch semantics
    inc2 = Incremental(epoch=m.epoch + 1, new_pool_max=1)
    inc2.new_pools[1] = PgPool(type=PoolType.REPLICATED, size=3,
                               crush_rule=0, pg_num=16, pgp_num=16)
    inc2.new_pool_names[1] = "p1"
    assert classify_incremental(inc2, m)[0] == "delta"


def test_classify_choose_args_value_delta():
    """A crush blob differing ONLY in choose_args weight values is a
    pos_weights-plane delta, not a re-key."""
    import copy

    from ceph_tpu.crush.codec import encode_crushmap
    from ceph_tpu.mgr.module import compat_ws_to_choose_args

    m = _mk_map()
    ws = {o: 1.0 for o in range(m.max_osd)}
    m.crush.choose_args[-1] = compat_ws_to_choose_args(m.crush, ws)
    c2 = copy.deepcopy(m.crush)
    ws2 = dict(ws)
    ws2[0] = 0.5
    c2.choose_args[-1] = compat_ws_to_choose_args(c2, ws2)
    kind, info = classify_incremental(
        _inc(m, crush=encode_crushmap(c2)), m)
    assert kind == "delta" and info["pos_weights"]


def test_value_copy_map_shares_structure():
    m = _mk_map()
    m.pg_temp[PgId(0, 3)] = [0, 1, 2]
    c = value_copy_map(m)
    assert c.crush is m.crush          # shared: value deltas replace it
    assert c.pools[0] is m.pools[0]    # PgPool shared
    assert c.osd_weight == m.osd_weight and \
        c.osd_weight is not m.osd_weight
    # a value chain on the copy leaves the original untouched
    apply_incremental(c, _inc(m, new_weight={1: 123},
                              new_pg_temp={PgId(0, 9): [2, 3, 4]}))
    assert m.osd_weight[1] == IN_WEIGHT
    assert PgId(0, 9) not in m.pg_temp
    assert c.osd_weight[1] == 123


# --------------------------------------------------- device state (jax)


@pytest.fixture(scope="module")
def st():
    from ceph_tpu import obs  # noqa: F401  (jax warmup path)

    m = _mk_map()
    return ClusterState(m, chunk=256)


def _state_counters():
    from ceph_tpu import obs

    return dict(obs.perf_dump().get("state") or {})


def test_rows_match_host_oracle(st):
    # every PG against the host oracle (a standalone PoolMapper would
    # compile a second — unquantized — kernel variant just for this
    # compare; the tier-1 budget is tight and the oracle subsumes it)
    rows, skey, tag = st.rows(0)
    got = np.asarray(rows)
    for s in range(N_PGS):
        row = [int(o) for o in got[s] if o >= 0]
        assert row == _oracle_up(st.m, 0, s), s


def test_value_chain_books_zero_compiles_and_zero_rebuilds(st):
    """The tentpole contract: a value-only Incremental chain mutates
    operands ON DEVICE in O(delta) — 0 compiles, 0 full rebuilds, no
    full-table device_put — and maps bit-identically to a from-scratch
    build."""
    from ceph_tpu import obs

    m = st.m
    st.rows(0)  # warm
    jit0 = obs.jit_counters()
    c0 = _state_counters()
    rb0 = st.full_rebuilds
    up5 = _oracle_up(m, 0, 5)
    to5 = next(o for o in range(m.max_osd)
               if o not in up5 and m.is_up(o) and m.is_in(o))
    chain = [  # built lazily: each inc's epoch follows the last apply
        lambda: _inc(m, new_weight={2: IN_WEIGHT // 2}),
        lambda: _inc(m, new_state={1: OSD_UP}),            # down
        lambda: _inc(m, new_primary_affinity={3: 0x4000}),  # first table!
        lambda: _inc(m, new_pg_upmap_items={
            PgId(0, 5): [(up5[0], to5)]}),
        lambda: _inc(m, new_pg_temp={PgId(0, 8):
                                     _oracle_up(m, 0, 8)[::-1]}),
        lambda: _inc(m, new_state={1: OSD_UP}),            # revive
    ]
    for mk in chain:
        assert st.apply(mk()) == "delta"
        st.rows(0)
    jd = obs.jit_counters_delta(jit0)
    c1 = _state_counters()
    assert jd["compiles"] == 0 and jd["retraces"] == 0, jd
    assert st.full_rebuilds == rb0
    assert c1["delta_applies"] - c0["delta_applies"] == len(chain)
    assert c1["full_rebuilds"] == c0["full_rebuilds"]
    # O(delta) upload: each apply moves one padded scatter block of
    # operands (32 lanes x 14 bytes), never a full table
    assert (c1["device_put_bytes"] - c0["device_put_bytes"]
            <= len(chain) * 600)

    # bit-identical to a from-scratch build of the same map (which
    # itself rides _PIPE_CACHE: same structure, zero compiles)
    rows, _, _ = st.rows(0)
    fresh = ClusterState(m, chunk=256)
    rows2, _, _ = fresh.rows(0)
    assert np.array_equal(np.asarray(rows), np.asarray(rows2))
    for s in (0, 5, 8, 17):
        got = [int(o) for o in np.asarray(rows)[s] if o >= 0]
        assert got == _oracle_up(m, 0, s), s


def test_version_tags_skip_unchanged_pools(st):
    c0 = _state_counters()
    r1, _, t1 = st.rows(0)
    c1 = _state_counters()
    assert c1["rows_served"] == c0["rows_served"] + 1
    assert c1["rows_remapped"] == c0["rows_remapped"]
    # a pg_temp delta leaves `up` rows untagged (acting-only)
    assert st.apply(_inc(st.m, new_primary_temp={PgId(0, 2): -1})) \
        == "delta"
    r2, _, t2 = st.rows(0)
    assert t2 == t1
    # a weight delta invalidates: rows re-dispatch
    assert st.apply(_inc(st.m, new_weight={4: IN_WEIGHT // 4})) \
        == "delta"
    _, _, t3 = st.rows(0)
    assert t3 != t1


def test_raw_rows_match_host_descent(st):
    pm = st.mapper(0)
    pm.refresh_dev()
    seeds = np.asarray([0, 3, 9, 31])
    raw = pm.raw_rows(seeds)
    for i, s in enumerate(seeds):
        want, _ = st.m._pg_to_raw_osds(st.m.pools[0], PgId(0, int(s)))
        got = [int(o) for o in raw[i] if o != ITEM_NONE]
        assert got == list(want), (s, got, want)


def test_structural_split_forces_exactly_one_rekey(st):
    rb0 = st.full_rebuilds
    inc = Incremental(epoch=st.m.epoch + 1)
    pool = inc.get_new_pool(0, st.m.pools[0])
    pool.pg_num *= 2
    pool.pgp_num = pool.pg_num
    assert st.apply(inc) == "rebuild"
    assert st.full_rebuilds == rb0 + 1
    rows, _, _ = st.rows(0)
    assert rows.shape[0] == N_PGS * 2
    for s in (1, 40, 63):
        got = [int(o) for o in np.asarray(rows)[s] if o >= 0]
        assert got == _oracle_up(st.m, 0, s)


def test_fork_is_copy_free_and_isolated(st):
    c0 = _state_counters()
    e0 = st.m.epoch
    w0 = st.m.osd_weight[5]
    f = st.fork(_inc(st.m, new_weight={5: IN_WEIGHT // 8}))
    c1 = _state_counters()
    assert c1["value_forks"] == c0["value_forks"] + 1
    assert f.m.crush is st.m.crush        # structure shared, not copied
    assert f.m.epoch == e0 + 1 and st.m.epoch == e0
    assert st.m.osd_weight[5] == w0       # parent untouched
    assert f.m.osd_weight[5] == IN_WEIGHT // 8
    # parent vectors untouched (functional scatter)
    assert f.vectors["weight"] is not st.vectors["weight"]
    rows, _, _ = f.rows(0)
    for s in (0, 11):
        got = [int(o) for o in np.asarray(rows)[s] if o >= 0]
        assert got == _oracle_up(f.m, 0, s)
    # a structural inc refuses to fork
    inc = Incremental(epoch=st.m.epoch + 1, new_max_osd=32)
    with pytest.raises(ValueError, match="value-only"):
        st.fork(inc)


def test_destroy_revive_refreshes_raw_caches(st):
    """Regression (review finding): a new_state XOR that sets EXISTS
    back ON (revival of a destroyed OSD) changes the descent's
    nonexistent-removal input exactly like the destroy did — the raw
    version must bump BOTH ways or overlay fixups/oracle serve stale
    descents."""
    m = st.m
    # ensure the pool carries an overlay entry so fixups are live
    seeds = st._overlay_seeds(0)
    if not seeds:
        up = _oracle_up(m, 0, 5)
        to = next(o for o in range(m.max_osd)
                  if o not in up and m.is_up(o) and m.is_in(o))
        assert st.apply(_inc(m, new_pg_upmap_items={
            PgId(0, 5): [(up[0], to)]})) == "delta"
    st.rows(0)
    victim = _oracle_up(m, 0, int(st._overlay_seeds(0)[0]))[0]
    # destroy (EXISTS clears) then revive (XOR sets EXISTS back)
    kind, info = classify_incremental(
        _inc(m, new_state={victim: OSD_EXISTS}), m)
    assert kind == "delta" and info["raw"]
    assert st.apply(_inc(m, new_state={victim: OSD_EXISTS})) == "delta"
    st.rows(0)
    kind, info = classify_incremental(
        _inc(m, new_state={victim: OSD_EXISTS}), m)
    assert info["raw"], "revival must be raw-changing too"
    assert st.apply(_inc(m, new_state={victim: OSD_EXISTS})) == "delta"
    # mark it up+in again and verify every row against the host oracle
    inc = _inc(m, new_up_client={victim: b""},
               new_weight={victim: IN_WEIGHT})
    assert st.apply(inc) == "delta"
    rows, _, _ = st.rows(0)
    got = np.asarray(rows)
    for s in range(m.pools[0].pg_num):
        row = [int(o) for o in got[s] if o >= 0]
        assert row == _oracle_up(m, 0, s), s


def test_delta_knob_forces_rebuild(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_STATE_DELTA", "0")
    st2 = ClusterState(_mk_map(), chunk=256)
    rb0 = st2.full_rebuilds
    assert st2.apply(_inc(st2.m, new_weight={1: 77})) \
        == "forced_rebuild"
    assert st2.full_rebuilds == rb0 + 1
    assert st2.delta_applies == 0


@pytest.mark.slow
def test_value_chain_at_scale_zero_compiles():
    """The at-scale variant: a bigger cluster, a longer value chain,
    same 0-compile / 0-rebuild contract (per the 870s tier-1 budget
    this rides the slow tier)."""
    from ceph_tpu import obs

    m = build_hierarchical(8, 4, n_rack=2, pool=PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=1024, pgp_num=1024))
    st = ClusterState(m, chunk=1024)
    st.rows(0)
    jit0 = obs.jit_counters()
    rng = np.random.default_rng(7)
    for e in range(32):
        inc = Incremental(epoch=m.epoch + 1)
        for o in rng.choice(m.max_osd, 3, replace=False):
            inc.new_weight[int(o)] = int(IN_WEIGHT
                                         * (0.5 + 0.5 * rng.random()))
        assert st.apply(inc) == "delta"
        rows, _, _ = st.rows(0)
    jd = obs.jit_counters_delta(jit0)
    assert jd["compiles"] == 0 and jd["retraces"] == 0, jd
    for s in rng.integers(0, 1024, 8):
        got = [int(o) for o in np.asarray(rows)[int(s)] if o >= 0]
        assert got == _oracle_up(m, 0, int(s))
