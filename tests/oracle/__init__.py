"""ctypes loader for the C differential oracle (see shim.c).

Compiles shim.c against the *read-only* reference CRUSH sources at first use
(cached in tests/oracle/build/).  If the reference mount or a C compiler is
unavailable, `load()` returns None and differential tests self-skip — the
pure-Python reference mapper (ceph_tpu.crush.mapper_ref) remains the oracle
for CI environments without the mount.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

REF = Path(os.environ.get("CEPH_REFERENCE", "/root/reference"))
HERE = Path(__file__).resolve().parent
SO = HERE / "build" / "liboracle.so"

_SOURCES = ["mapper.c", "hash.c", "builder.c", "crush.c"]


def build() -> Path | None:
    crush_dir = REF / "src" / "crush"
    if not crush_dir.is_dir():
        return None
    srcs = [str(crush_dir / s) for s in _SOURCES]
    newest = max(os.path.getmtime(s) for s in srcs + [str(HERE / "shim.c")])
    if SO.exists() and os.path.getmtime(SO) >= newest:
        return SO
    SO.parent.mkdir(parents=True, exist_ok=True)
    # acconfig.h is normally cmake-generated in the reference build tree;
    # an empty stub suffices on Linux (__u8 etc. come from linux/types.h).
    (SO.parent / "acconfig.h").write_text("/* stub for oracle build */\n")
    cmd = [
        "cc", "-O2", "-g", "-fPIC", "-shared",
        "-I", str(SO.parent),
        "-I", str(crush_dir),
        "-I", str(REF / "src"),
        str(HERE / "shim.c"), *srcs,
        "-o", str(SO), "-lm",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError):
        return None
    return SO


_lib = None


def load():
    global _lib
    if _lib is not None:
        return _lib
    so = build()
    if so is None:
        return None
    lib = ctypes.CDLL(str(so))
    lib.oracle_map_create.restype = ctypes.c_void_p
    lib.oracle_map_create.argtypes = [ctypes.c_int] * 6
    lib.oracle_add_bucket.restype = ctypes.c_int
    lib.oracle_add_bucket.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.oracle_add_rule.restype = ctypes.c_int
    lib.oracle_add_rule.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
    ]
    lib.oracle_finalize.argtypes = [ctypes.c_void_p]
    lib.oracle_do_rule.restype = ctypes.c_int
    lib.oracle_do_rule.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint), ctypes.c_int,
    ]
    lib.oracle_set_choose_args.restype = ctypes.c_int
    lib.oracle_set_choose_args.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint),
    ]
    lib.oracle_bench_rule.restype = ctypes.c_longlong
    lib.oracle_bench_rule.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_uint),
        ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.oracle_hash32_2.restype = ctypes.c_uint
    lib.oracle_hash32_2.argtypes = [ctypes.c_uint, ctypes.c_uint]
    lib.oracle_hash32_3.restype = ctypes.c_uint
    lib.oracle_hash32_3.argtypes = [ctypes.c_uint] * 3
    lib.oracle_map_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class OracleMap:
    """Pythonic wrapper over the C oracle for building maps + running rules."""

    def __init__(self, tunables=None):
        from ceph_tpu.crush.types import Tunables

        t = tunables or Tunables()
        self.lib = load()
        assert self.lib is not None
        self.h = self.lib.oracle_map_create(
            t.choose_local_tries, t.choose_local_fallback_tries,
            t.choose_total_tries, t.chooseleaf_descend_once,
            t.chooseleaf_vary_r, t.chooseleaf_stable)

    def add_bucket(self, alg, hash_, type_, items, weights):
        n = len(items)
        ia = (ctypes.c_int * n)(*[int(i) for i in items])
        wa = (ctypes.c_int * n)(*[int(w) for w in weights])
        bid = self.lib.oracle_add_bucket(self.h, alg, hash_, type_, n, ia, wa)
        assert bid < 0, f"oracle_add_bucket failed: {bid}"
        return bid

    def add_rule(self, steps, ruleset=0, type_=1, minsize=1, maxsize=10):
        n = len(steps)
        ops = (ctypes.c_int * n)(*[s[0] for s in steps])
        a1 = (ctypes.c_int * n)(*[s[1] for s in steps])
        a2 = (ctypes.c_int * n)(*[s[2] for s in steps])
        return self.lib.oracle_add_rule(self.h, ruleset, type_, minsize,
                                        maxsize, n, ops, a1, a2)

    def finalize(self):
        self.lib.oracle_finalize(self.h)

    def set_choose_args(self, positions, flat_weights):
        n = len(flat_weights)
        wa = (ctypes.c_uint * n)(*[int(w) for w in flat_weights])
        self.lib.oracle_set_choose_args(self.h, positions, wa)

    def do_rule(self, ruleno, x, weights, result_max):
        res = (ctypes.c_int * result_max)()
        wn = len(weights)
        wa = (ctypes.c_uint * wn)(*[int(w) for w in weights])
        n = self.lib.oracle_do_rule(self.h, ruleno, int(x) & 0xFFFFFFFF, res,
                                    result_max, wa, wn)
        return [res[i] for i in range(n)]

    def bench_rule(self, ruleno, x0, n, pool, weights, result_max):
        """Time n do_rule calls in C; returns (elapsed_ns, checksum)."""
        wa = (ctypes.c_uint * len(weights))(*[int(w) for w in weights])
        sink = ctypes.c_longlong(0)
        ns = self.lib.oracle_bench_rule(
            self.h, ruleno, int(x0) & 0xFFFFFFFF, n, pool, result_max,
            wa, len(weights), ctypes.byref(sink),
        )
        return ns, sink.value

    def __del__(self):
        try:
            self.lib.oracle_map_destroy(self.h)
        except Exception:
            pass
