/* Differential-test oracle shim.
 *
 * This file is OUR code; it is compiled (at test time only) against the
 * reference checkout's CRUSH C sources, which are taken verbatim from the
 * *read-only* reference mount via -I/--include paths — nothing from the
 * reference is copied into this repository.  The resulting .so is the
 * bit-exactness oracle for the JAX placement kernels: tests build identical
 * maps on both sides and compare crush_do_rule outputs element-wise.
 *
 * Exposed API (ctypes-friendly, flat arrays only):
 *   oracle_map_create / oracle_map_destroy
 *   oracle_add_bucket   -> bucket id (< 0)
 *   oracle_add_rule
 *   oracle_finalize
 *   oracle_do_rule      -> result_len
 *   oracle_set_choose_args / oracle_clear_choose_args
 *   oracle_ln           -> exposes straw2's fixed-point log via a probe
 */

#include <stdlib.h>
#include <string.h>

#include "crush.h"
#include "builder.h"
#include "mapper.h"
#include "hash.h"

struct oracle {
    struct crush_map *map;
    struct crush_choose_arg *choose_args; /* optional, max_buckets entries */
};

void *oracle_map_create(int choose_local_tries, int choose_local_fallback_tries,
                        int choose_total_tries, int chooseleaf_descend_once,
                        int chooseleaf_vary_r, int chooseleaf_stable) {
    struct oracle *o = calloc(1, sizeof(*o));
    o->map = crush_create();
    o->map->choose_local_tries = choose_local_tries;
    o->map->choose_local_fallback_tries = choose_local_fallback_tries;
    o->map->choose_total_tries = choose_total_tries;
    o->map->chooseleaf_descend_once = chooseleaf_descend_once;
    o->map->chooseleaf_vary_r = chooseleaf_vary_r;
    o->map->chooseleaf_stable = chooseleaf_stable;
    return o;
}

/* alg: 1=uniform 2=list 3=tree 4=straw 5=straw2; returns assigned id (<0) */
int oracle_add_bucket(void *vo, int alg, int hash, int type, int size,
                      const int *items, const int *weights) {
    struct oracle *o = vo;
    struct crush_bucket *b =
        crush_make_bucket(o->map, alg, hash, type, size, (int *)items,
                          (int *)weights);
    if (!b)
        return 1; /* invalid: bucket ids are negative */
    int id = 0;
    if (crush_add_bucket(o->map, 0, b, &id) < 0)
        return 1;
    return id;
}

int oracle_add_rule(void *vo, int ruleset, int type, int minsize, int maxsize,
                    int nsteps, const int *ops, const int *arg1s,
                    const int *arg2s) {
    struct oracle *o = vo;
    struct crush_rule *r = crush_make_rule(nsteps, ruleset, type, minsize,
                                           maxsize);
    for (int i = 0; i < nsteps; i++)
        crush_rule_set_step(r, i, ops[i], arg1s[i], arg2s[i]);
    return crush_add_rule(o->map, r, -1);
}

void oracle_finalize(void *vo) {
    struct oracle *o = vo;
    crush_finalize(o->map);
}

int oracle_max_buckets(void *vo) {
    struct oracle *o = vo;
    return o->map->max_buckets;
}

/* weight_sets: [max_buckets][positions][bucket_size] flattened ragged via
 * offsets; ids==NULL keeps bucket items.  Minimal version: one weight_set
 * per bucket with `positions` positions, weights laid out densely in
 * ws[bucket][pos*size+i] with per-bucket size from the map. */
int oracle_set_choose_args(void *vo, int positions, const unsigned *weights) {
    struct oracle *o = vo;
    int nb = o->map->max_buckets;
    o->choose_args = calloc(nb, sizeof(struct crush_choose_arg));
    const unsigned *p = weights;
    for (int b = 0; b < nb; b++) {
        struct crush_bucket *bk = o->map->buckets[b];
        if (!bk)
            continue;
        struct crush_choose_arg *ca = &o->choose_args[b];
        ca->ids = NULL;
        ca->ids_size = 0;
        ca->weight_set_positions = positions;
        ca->weight_set = calloc(positions, sizeof(struct crush_weight_set));
        for (int pos = 0; pos < positions; pos++) {
            ca->weight_set[pos].size = bk->size;
            ca->weight_set[pos].weights = malloc(bk->size * sizeof(unsigned));
            memcpy(ca->weight_set[pos].weights, p, bk->size * sizeof(unsigned));
            p += bk->size;
        }
    }
    return 0;
}

int oracle_do_rule(void *vo, int ruleno, int x, int *result, int result_max,
                   const unsigned *weight, int weight_max) {
    struct oracle *o = vo;
    if (!o->map->working_size)
        crush_finalize(o->map);
    /* crush_do_rule uses 3*result_max ints of scratch beyond working_size
     * (see the a/b/c pointers at reference src/crush/mapper.c:907-909) */
    char *work = malloc(o->map->working_size + 3 * result_max * sizeof(int));
    crush_init_workspace(o->map, work);
    int n = crush_do_rule(o->map, ruleno, x, result, result_max, weight,
                          weight_max, work, o->choose_args);
    free(work);
    return n;
}

/* Single-core benchmark loop: time n crush_do_rule calls (x = x0..x0+n-1,
 * each pre-mixed with crush_hash32_2(x, pool) like CrushTester's --pool_id
 * path, reference src/crush/CrushTester.cc:612-623) entirely in C so the
 * baseline measures the reference kernel, not ctypes.  Returns elapsed
 * nanoseconds; *sink accumulates results to defeat dead-code elimination. */
#include <time.h>
long long oracle_bench_rule(void *vo, int ruleno, unsigned x0, int n,
                            int pool, int result_max, const unsigned *weight,
                            int weight_max, long long *sink) {
    struct oracle *o = vo;
    if (!o->map->working_size)
        crush_finalize(o->map);
    char *work = malloc(o->map->working_size + 3 * result_max * sizeof(int));
    int *result = malloc(result_max * sizeof(int));
    long long acc = 0;
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (int i = 0; i < n; i++) {
        unsigned x = crush_hash32_2(CRUSH_HASH_RJENKINS1, x0 + i,
                                    (unsigned)pool);
        crush_init_workspace(o->map, work);
        int c = crush_do_rule(o->map, ruleno, x, result, result_max, weight,
                              weight_max, work, o->choose_args);
        for (int j = 0; j < c; j++)
            acc += result[j];
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    free(result);
    free(work);
    if (sink)
        *sink = acc;
    return (t1.tv_sec - t0.tv_sec) * 1000000000LL + (t1.tv_nsec - t0.tv_nsec);
}

unsigned oracle_hash32_2(unsigned a, unsigned b) {
    return crush_hash32_2(CRUSH_HASH_RJENKINS1, a, b);
}
unsigned oracle_hash32_3(unsigned a, unsigned b, unsigned c) {
    return crush_hash32_3(CRUSH_HASH_RJENKINS1, a, b, c);
}

void oracle_map_destroy(void *vo) {
    struct oracle *o = vo;
    /* leak choose_args/map internals; oracle processes are short-lived */
    crush_destroy(o->map);
    free(o);
}
