"""Differential tests: pure-Python reference mapper vs the compiled C
reference crush_do_rule, over a grid of topologies / bucket algorithms /
tunables / rules.  Exact element-wise equality is required."""

import numpy as np
import pytest

from ceph_tpu.crush.mapper_ref import do_rule
from ceph_tpu.crush.types import BucketAlg, ChooseArgs, CrushMap, Rule, RuleOp, Tunables

from util_maps import build_flat, build_tree, to_oracle, HOST, ROOT


def compare(m, om, ruleno, weights, xs, result_max=3, choose_args=None):
    for x in xs:
        ours = do_rule(m, ruleno, int(x), result_max, weights, choose_args)
        theirs = om.do_rule(ruleno, int(x), weights, result_max)
        assert ours == theirs, (
            f"x={x} rule={ruleno} ours={ours} theirs={theirs}"
        )


XS = list(range(64)) + [12345, 999999, 2**31 - 1, 2**32 - 5]


@pytest.mark.parametrize("alg", [BucketAlg.STRAW2, BucketAlg.STRAW,
                                 BucketAlg.LIST, BucketAlg.TREE,
                                 BucketAlg.UNIFORM])
def test_flat_firstn(oracle_lib, alg):
    m, root = build_flat(17, alg)
    r = m.make_replicated_rule(root, 0)
    om = to_oracle(m)
    compare(m, om, r, [0x10000] * 17, XS, result_max=3)


@pytest.mark.parametrize("alg", [BucketAlg.STRAW2, BucketAlg.LIST,
                                 BucketAlg.TREE, BucketAlg.UNIFORM])
def test_flat_indep(oracle_lib, alg):
    m, root = build_flat(10, alg)
    m.add_rule(Rule([(RuleOp.TAKE, root, 0),
                     (RuleOp.CHOOSE_INDEP, 0, 0),
                     (RuleOp.EMIT, 0, 0)], type=3))
    om = to_oracle(m)
    compare(m, om, 0, [0x10000] * 10, XS, result_max=4)


def test_flat_weighted_straw2(oracle_lib, rng):
    n = 25
    weights = [int(w) for w in rng.integers(1, 8 * 0x10000, n)]
    weights[3] = 0  # a zero-weight item
    m = CrushMap()
    root = m.add_bucket(BucketAlg.STRAW2, ROOT, list(range(n)), weights)
    r = m.make_replicated_rule(root, 0)
    om = to_oracle(m)
    compare(m, om, r, [0x10000] * n, XS)


def test_flat_reweighted_devices(oracle_lib, rng):
    """device in/out probability vector != crush weights"""
    n = 20
    m, root = build_flat(n)
    r = m.make_replicated_rule(root, 0)
    om = to_oracle(m)
    dev_w = [int(w) for w in rng.integers(0, 0x10000 + 1, n)]
    dev_w[0] = 0
    dev_w[1] = 0x10000
    dev_w[2] = 0x8000
    compare(m, om, r, dev_w, XS)


@pytest.mark.parametrize("host_alg", [BucketAlg.STRAW2, BucketAlg.LIST,
                                      BucketAlg.TREE, BucketAlg.UNIFORM,
                                      BucketAlg.STRAW])
def test_chooseleaf_firstn(oracle_lib, rng, host_alg):
    m, root = build_tree(rng, n_host=6, osd_per_host=4, host_alg=host_alg,
                         weight_fn=lambda i: 0x10000 + (i % 5) * 0x4000)
    r = m.make_replicated_rule(root, HOST)
    om = to_oracle(m)
    compare(m, om, r, [0x10000] * 24, XS)


def test_chooseleaf_indep_ec(oracle_lib, rng):
    m, root = build_tree(rng, n_host=8, osd_per_host=3)
    r = m.make_erasure_rule(root, HOST)
    om = to_oracle(m)
    compare(m, om, r, [0x10000] * 24, XS, result_max=6)


def test_choose_then_chooseleaf(oracle_lib, rng):
    """multi-step rule: choose 2 racks, then chooseleaf 2 hosts under each."""
    m, root = build_tree(rng, n_host=8, osd_per_host=3, n_rack=4)
    m.add_rule(Rule([(RuleOp.TAKE, root, 0),
                     (RuleOp.CHOOSE_FIRSTN, 2, 2),  # 2 racks
                     (RuleOp.CHOOSELEAF_FIRSTN, 2, HOST),
                     (RuleOp.EMIT, 0, 0)]))
    om = to_oracle(m)
    compare(m, om, 0, [0x10000] * 24, XS, result_max=4)


@pytest.mark.parametrize("profile", ["legacy", "bobtail", "firefly", "jewel"])
def test_tunables_profiles(oracle_lib, rng, profile):
    t = Tunables.profile(profile)
    m, root = build_tree(rng, n_host=5, osd_per_host=4, tunables=t,
                         weight_fn=lambda i: 0x10000 * (1 + i % 3))
    r = m.make_replicated_rule(root, HOST)
    om = to_oracle(m)
    # also mark some devices partially/fully out to exercise retries
    w = [0x10000] * 20
    w[2] = 0
    w[7] = 0x4000
    w[11] = 0
    compare(m, om, r, w, XS)


def test_degenerate_small_hierarchy(oracle_lib, rng):
    """numrep > devices available under constraint -> skip_rep/NONE paths"""
    m, root = build_tree(rng, n_host=3, osd_per_host=2)
    rr = m.make_replicated_rule(root, HOST)  # only 3 hosts for numrep=3
    re_ = m.make_erasure_rule(root, HOST)
    om = to_oracle(m)
    compare(m, om, rr, [0x10000] * 6, XS, result_max=3)
    compare(m, om, re_, [0x10000] * 6, XS, result_max=5)


def test_set_tries_steps(oracle_lib, rng):
    m, root = build_tree(rng, n_host=6, osd_per_host=4)
    m.add_rule(Rule([
        (RuleOp.SET_CHOOSE_TRIES, 100, 0),
        (RuleOp.SET_CHOOSELEAF_TRIES, 7, 0),
        (RuleOp.SET_CHOOSELEAF_VARY_R, 0, 0),
        (RuleOp.SET_CHOOSELEAF_STABLE, 0, 0),
        (RuleOp.TAKE, root, 0),
        (RuleOp.CHOOSELEAF_FIRSTN, 0, HOST),
        (RuleOp.EMIT, 0, 0)]))
    om = to_oracle(m)
    w = [0x10000] * 24
    w[5] = 0
    compare(m, om, 0, w, XS)


def test_choose_args_weight_set(oracle_lib, rng):
    """choose_args per-position weight overrides (straw2 only)."""
    m, root = build_tree(rng, n_host=4, osd_per_host=4)
    r = m.make_replicated_rule(root, HOST)
    om = to_oracle(m)
    positions = 3
    ca = ChooseArgs()
    flat = []
    # oracle_set_choose_args consumes weights bucket-slot-major (b=0 => id -1)
    for slot in range(m.max_buckets):
        bid = -1 - slot
        b = m.buckets[bid]
        ws = []
        for pos in range(positions):
            row = [int(w) for w in rng.integers(1, 4 * 0x10000, b.size)]
            ws.append(row)
            flat.extend(row)
        ca.weight_sets[bid] = ws
    om.set_choose_args(positions, flat)
    compare(m, om, r, [0x10000] * 16, XS, choose_args=ca)


def test_zero_size_take_of_device(oracle_lib):
    """rule that takes a device directly, and an emit of it"""
    m, root = build_flat(4)
    m.add_rule(Rule([(RuleOp.TAKE, 2, 0), (RuleOp.EMIT, 0, 0)]))
    om = to_oracle(m)
    compare(m, om, 0, [0x10000] * 4, XS, result_max=3)


def test_big_random_grid(oracle_lib, rng):
    """randomized topologies & mixed algs, moderate x sweep"""
    algs = [BucketAlg.STRAW2, BucketAlg.LIST, BucketAlg.TREE,
            BucketAlg.UNIFORM, BucketAlg.STRAW]
    for trial in range(6):
        host_alg = algs[trial % len(algs)]
        n_host = int(rng.integers(2, 9))
        per = int(rng.integers(1, 6))
        m, root = build_tree(
            rng, n_host=n_host, osd_per_host=per, host_alg=host_alg,
            weight_fn=lambda i: int(rng.integers(1, 3 * 0x10000)))
        rr = m.make_replicated_rule(root, HOST)
        om = to_oracle(m)
        n = n_host * per
        w = [int(v) for v in rng.integers(0, 0x10001, n)]
        compare(m, om, rr, w, range(100), result_max=3)
