"""CLI tests — the cram-transcript pattern of the reference
(reference src/test/cli/crushtool/*.t, src/test/cli/osdmaptool/*.t):
run the tools in-process, assert on their output."""

import io
import json
import re
import sys

import numpy as np
import pytest

from ceph_tpu.cli import crushtool, ec_benchmark, osdmaptool, psim
from ceph_tpu.osd.io import load_osdmap


def run_cli(mod, argv, capsys):
    rc = mod.main(argv)
    out = capsys.readouterr()
    return rc, out.out, out.err


@pytest.fixture
def crush_file(tmp_path):
    from ceph_tpu.cli.crushtool import build_map
    from ceph_tpu.crush.compiler import decompile

    m = build_map(
        16, [("host", "straw2", 4), ("root", "straw2", 0)]
    )
    m.make_replicated_rule(
        min(m.buckets.keys(), key=lambda b: -b if False else b), 1
    )
    # root bucket is the last one created (holds the hosts)
    p = tmp_path / "map.txt"
    p.write_text(decompile(m))
    return str(p)


class TestCrushtool:
    def test_build_and_tree(self, tmp_path, capsys):
        out_f = str(tmp_path / "built.txt")
        rc, out, err = run_cli(
            crushtool,
            ["--build", "--num_osds", "8",
             "host", "straw2", "2", "root", "straw2", "0",
             "-o", out_f],
            capsys,
        )
        assert rc == 0
        text = open(out_f).read()
        assert "host host0" in text and "root root" in text
        assert text.count("device ") == 8

    def test_compile_decompile_roundtrip(self, tmp_path, capsys):
        out_f = str(tmp_path / "built.txt")
        run_cli(
            crushtool,
            ["--build", "--num_osds", "4", "host", "straw2", "2",
             "root", "straw2", "0", "-o", out_f],
            capsys,
        )
        rc, out, err = run_cli(crushtool, ["-d", out_f], capsys)
        assert rc == 0
        assert "# begin crush map" in out

    def test_test_statistics(self, tmp_path, capsys):
        out_f = str(tmp_path / "m.txt")
        run_cli(
            crushtool,
            ["--build", "--num_osds", "8", "host", "straw2", "2",
             "root", "straw2", "0", "-o", out_f],
            capsys,
        )
        rc, out, err = run_cli(
            crushtool,
            ["-i", out_f, "--test", "--num-rep", "3",
             "--min-x", "0", "--max-x", "255",
             "--show-statistics", "--backend", "jax"],
            capsys,
        )
        assert rc == 0
        assert re.search(
            r"rule 0 \(\w+\) num_rep 3 result size == 3:\t256/256", out
        )

    def test_bad_mappings_shown_when_exhausted(self, tmp_path, capsys):
        # 2 hosts but ask for 3 distinct hosts -> bad mappings
        out_f = str(tmp_path / "m.txt")
        run_cli(
            crushtool,
            ["--build", "--num_osds", "4", "host", "straw2", "2",
             "root", "straw2", "0", "-o", out_f],
            capsys,
        )
        rc, out, err = run_cli(
            crushtool,
            ["-i", out_f, "--test", "--num-rep", "3",
             "--min-x", "0", "--max-x", "63", "--show-bad-mappings",
             "--backend", "jax"],
            capsys,
        )
        assert rc == 0
        assert "bad mapping rule 0" in out

    def test_simulate(self, tmp_path, capsys):
        out_f = str(tmp_path / "m.txt")
        run_cli(
            crushtool,
            ["--build", "--num_osds", "4", "root", "straw2", "0",
             "-o", out_f],
            capsys,
        )
        rc, out, err = run_cli(
            crushtool,
            ["-i", out_f, "--test", "--num-rep", "2", "--max-x", "31",
             "--simulate", "--show-mappings"],
            capsys,
        )
        assert rc == 0
        assert "RNG rule 0" in out


class TestOsdmaptool:
    def test_createsimple_and_stats(self, tmp_path, capsys):
        mf = str(tmp_path / "om.json")
        rc, out, err = run_cli(
            osdmaptool, [mf, "--createsimple", "16", "--pg-bits", "4",
                         "--with-default-pool"],
            capsys,
        )
        assert rc == 0 and "writing epoch" in out
        # bare simple map: all OSDs on one "localhost" host, so the
        # chooseleaf-host rule yields size-1 mappings (reference semantics)
        rc, out, err = run_cli(
            osdmaptool, [mf, "--mark-up-in", "--test-map-pgs",
                         "--backend", "jax"], capsys
        )
        assert rc == 0
        assert "pool 1 pg_num 256" in out
        assert "#osd\tcount\tfirst\tprimary\tc wt\twt" in out
        assert " in 16" in out
        assert re.search(r"size 1\t256", out)

    def test_cram_flow_import_built_crush(self, tmp_path, capsys):
        """The reference cram recipe (src/test/cli/osdmaptool/
        test-map-pgs.t): createsimple + import a crushtool --build map,
        then size==pool-size for every PG."""
        mf = str(tmp_path / "om.json")
        run_cli(osdmaptool, [mf, "--createsimple", "16", "--pg-bits", "4",
                             "--with-default-pool"], capsys)
        cf = str(tmp_path / "crush.txt")
        run_cli(
            crushtool,
            ["--build", "--num_osds", "16", "node", "straw2", "4",
             "root", "straw2", "0", "-o", cf],
            capsys,
        )
        run_cli(osdmaptool, [mf, "--import-crush", cf], capsys)
        rc, out, _ = run_cli(
            osdmaptool,
            [mf, "--mark-up-in", "--test-map-pgs", "--backend", "jax"],
            capsys,
        )
        assert rc == 0
        assert re.search(r"size 3\t256", out)

    def test_backends_agree(self, tmp_path, capsys):
        mf = str(tmp_path / "om.json")
        run_cli(osdmaptool, [mf, "--createsimple", "8", "--pg-bits", "4",
                             "--with-default-pool"], capsys)
        _, out_jax, _ = run_cli(
            osdmaptool, [mf, "--mark-up-in", "--test-map-pgs",
                         "--backend", "jax"], capsys
        )
        _, out_ref, _ = run_cli(
            osdmaptool, [mf, "--mark-up-in", "--test-map-pgs",
                         "--backend", "ref"], capsys
        )
        assert out_jax == out_ref

    def test_dump_and_test_map_pg(self, tmp_path, capsys):
        mf = str(tmp_path / "om.json")
        run_cli(osdmaptool, [mf, "--createsimple", "8", "--pg-bits", "3",
                             "--with-default-pool"], capsys)
        rc, out, _ = run_cli(
            osdmaptool, [mf, "--mark-up-in", "--test-map-pgs-dump",
                         "--backend", "ref"],
            capsys,
        )
        assert rc == 0
        assert re.search(r"1\.0\t\[\d+(,\d+)*\]\t\d+", out)
        rc, out, _ = run_cli(osdmaptool, [mf, "--test-map-pg", "1.5"],
                             capsys)
        assert "parsed '1.5'" in out

    def test_health_ok_exits_zero(self, tmp_path, capsys):
        from ceph_tpu.osd.io import save_osdmap
        from ceph_tpu.osd.osdmap import build_hierarchical
        from ceph_tpu.osd.types import PgPool, PoolType

        pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                      pg_num=32, pgp_num=32)
        m = build_hierarchical(4, 4, n_rack=2, pool=pool)
        mf = str(tmp_path / "om.bin")
        save_osdmap(m, mf)
        rc, out, _ = run_cli(osdmaptool, [mf, "--health"], capsys)
        assert rc == 0
        h = json.loads(out)
        assert h["status"] == "HEALTH_OK" and h["checks"] == {}
        assert "OSD_DOWN" in h["registry"]  # full dump carries the registry

    def test_health_down_osd_exits_one(self, tmp_path, capsys):
        from ceph_tpu.osd.io import save_osdmap
        from ceph_tpu.osd.osdmap import build_hierarchical
        from ceph_tpu.osd.types import PgPool, PoolType

        pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                      pg_num=32, pgp_num=32)
        m = build_hierarchical(4, 4, n_rack=2, pool=pool)
        m.osd_state[0] &= ~0b10  # clear UP: osd.0 is down but exists
        mf = str(tmp_path / "om.bin")
        save_osdmap(m, mf)
        rc, out, _ = run_cli(osdmaptool, [mf, "--health"], capsys)
        assert rc == 1  # scriptable: non-OK is a nonzero exit
        h = json.loads(out)
        assert h["status"] != "HEALTH_OK"
        assert h["checks"]["OSD_DOWN"]["summary"] == "1/16 osds down"
        assert h["checks"]["PG_DEGRADED"]["count"] > 0

    def test_upmap_writes_commands(self, tmp_path, capsys):
        mf = str(tmp_path / "om.json")
        run_cli(osdmaptool, [mf, "--createsimple", "12", "--pg-bits", "5",
                             "--with-default-pool"], capsys)
        uf = str(tmp_path / "upmaps.txt")
        rc, out, err = run_cli(
            osdmaptool,
            [mf, "--mark-up-in", "--upmap", uf, "--upmap-deviation", "1",
             "--upmap-max", "20", "--backend", "ref", "--save"],
            capsys,
        )
        assert rc == 0
        body = open(uf).read()
        # createsimple is flat (single host) => chooseleaf osd remaps exist
        for line in body.strip().splitlines():
            assert line.startswith(
                ("ceph osd pg-upmap-items", "ceph osd rm-pg-upmap-items")
            )
        # the upmaps persisted into the map file
        m = load_osdmap(mf)
        assert len(m.pg_upmap_items) == len(
            [l for l in body.splitlines() if "pg-upmap-items" in l
             and not l.startswith("ceph osd rm")]
        )

    def test_export_import_crush(self, tmp_path, capsys):
        mf = str(tmp_path / "om.json")
        run_cli(osdmaptool, [mf, "--createsimple", "4",
                             "--with-default-pool"], capsys)
        cf = str(tmp_path / "cm.txt")
        rc, out, _ = run_cli(osdmaptool, [mf, "--export-crush", cf],
                             capsys)
        assert rc == 0 and "exported crush map" in out
        rc, out, _ = run_cli(osdmaptool, [mf, "--import-crush", cf],
                             capsys)
        assert rc == 0 and "byte crush map" in out


class TestEcBenchmark:
    @pytest.mark.parametrize("workload", ["encode", "decode"])
    def test_runs_and_prints(self, workload, capsys):
        rc, out, _ = run_cli(
            ec_benchmark,
            ["--plugin", "jerasure", "--workload", workload,
             "--size", "65536", "--iterations", "2",
             "--parameter", "k=4", "--parameter", "m=2",
             "--erasures", "2"],
            capsys,
        )
        assert rc == 0
        secs, kib = out.strip().split("\t")
        assert float(secs) > 0
        assert float(kib) == 128.0

    def test_exhaustive_erasures(self, capsys):
        rc, out, _ = run_cli(
            ec_benchmark,
            ["--plugin", "jerasure", "--workload", "decode",
             "--size", "4096", "--iterations", "15",
             "--parameter", "k=4", "--parameter", "m=2",
             "--erasures", "2", "--erasures-generation", "exhaustive"],
            capsys,
        )
        assert rc == 0


class TestPsim:
    def test_runs(self, capsys):
        rc = psim.main(["8"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "osd.0" in out and "avg" in out


class TestUpmapCleanup:
    def test_cleanup_removes_stale_entries(self, tmp_path, capsys):
        from ceph_tpu.osd.io import save_osdmap
        from ceph_tpu.osd.types import PgId

        mf = str(tmp_path / "om.json")
        run_cli(osdmaptool, [mf, "--createsimple", "8", "--pg-bits", "3",
                             "--with-default-pool"], capsys)
        m = load_osdmap(mf)
        for o in range(m.max_osd):
            m.mark_up_in(o)
        raw, _ = m.pg_to_raw_osds(PgId(1, 0))
        m.pg_upmap_items[PgId(1, 0)] = [(99, 5)]  # frm never in raw
        m.pg_upmap[PgId(1, 1)] = list(raw)
        save_osdmap(m, mf)
        # reference parity: --upmap-cleanup takes a file ('-' = stdout)
        # and does NOT persist the cleaned map
        rc, out, err = run_cli(osdmaptool, [mf, "--upmap-cleanup", "-"],
                               capsys)
        assert rc == 0
        assert "rm-pg-upmap-items 1.0" in out
        m2 = load_osdmap(mf)
        assert PgId(1, 0) in m2.pg_upmap_items  # not persisted


class TestReweight:
    def test_reweight_propagates_to_ancestors(self, tmp_path, capsys):
        out_f = str(tmp_path / "m.txt")
        run_cli(
            crushtool,
            ["--build", "--num_osds", "4", "host", "straw2", "2",
             "root", "straw2", "0", "-o", out_f],
            capsys,
        )
        new_f = str(tmp_path / "m2.txt")
        rc, _, _ = run_cli(
            crushtool,
            ["-i", out_f, "--reweight-item", "osd.0", "3.0",
             "-o", new_f],
            capsys,
        )
        assert rc == 0
        from ceph_tpu.crush.compiler import compile_text

        m = compile_text(open(new_f).read())
        by_name = {v: k for k, v in m.item_names.items()}
        h0, root = by_name["host0"], by_name["root"]
        # host0 itself: osd.0 now 3.0
        assert m.buckets[h0].weights[0] == 3 * 0x10000
        # root's entry for host0 reflects the propagated delta (2->4)
        idx = m.buckets[root].items.index(h0)
        assert m.buckets[root].weights[idx] == 4 * 0x10000
