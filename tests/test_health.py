"""Health model + timeline flight recorder + serve SLO burn engine.

Three contracts under test (ceph_tpu/obs/health.py, obs/timeline.py,
serve/slo.py):

- the check registry is a declared-codes-only surface (undeclared codes
  raise at the call site, not at cluster-unhealthy time), muting drops a
  check from the summarized status without hiding it from dumps, and
  `evaluate()` maps the standard host reductions onto the standard
  codes;
- the timeline is a bounded 2-tier recorder whose indices stay
  monotonic across checkpoint/resume and whose tier-1 ring holds 8:1
  averaged evictions;
- the SLO engine is a multiwindow burn detector that drives the
  SLO_BURN check through a full raise->clear transition;
- and the whole stack is a *pure observer*: disabling it
  (CEPH_TPU_HEALTH=0, CEPH_TPU_TIMELINE_CAP=0) is bit-invisible to
  lifetime digests and steady-state compile counts.
"""

from __future__ import annotations

import ast
from pathlib import Path

import pytest

from ceph_tpu.obs import health, timeline
from ceph_tpu.serve.slo import Objectives, SloEngine

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _clean():
    """Health checks and timeline series are process globals."""
    health.reset()
    timeline.reset()
    yield
    health.reset()
    timeline.reset()


# ------------------------------------------------------------ health model


def test_raise_clear_transition_booleans():
    assert health.raise_check("OSD_DOWN", health.WARN, "2/8 osds down",
                              count=2)
    # refresh, not a transition
    assert not health.raise_check("OSD_DOWN", health.WARN, "3/8 osds down",
                                  count=3)
    assert health.checks()["OSD_DOWN"]["count"] == 3  # refresh updated it
    assert health.clear("OSD_DOWN")
    assert not health.clear("OSD_DOWN")  # already clear
    assert health.checks() == {}


def test_undeclared_code_and_bad_severity_throw_at_call_site():
    with pytest.raises(KeyError, match="undeclared"):
        health.raise_check("NOT_A_CHECK", health.WARN, "x")
    with pytest.raises(KeyError, match="undeclared"):
        health.clear("NOT_A_CHECK")
    with pytest.raises(ValueError, match="severity"):
        health.raise_check("OSD_DOWN", "HEALTH_OK", "x")
    with pytest.raises(ValueError, match="severity"):
        health.raise_check("OSD_DOWN", "fatal", "x")


def test_status_is_worst_unmuted_severity():
    assert health.status() == health.OK
    assert health.rank(health.status()) == 0
    health.raise_check("PG_DEGRADED", health.WARN, "3 pgs degraded")
    assert health.status() == health.WARN
    health.raise_check("PG_UNMAPPED", health.ERR, "1 pgs unmapped")
    assert health.status() == health.ERR
    assert health.rank(health.ERR) == 2
    health.clear("PG_UNMAPPED")
    assert health.status() == health.WARN


def test_mute_drops_from_status_but_not_from_dump(monkeypatch):
    health.raise_check("PG_AT_RISK", health.ERR, "2 pgs past EC tolerance")
    assert health.status() == health.ERR
    monkeypatch.setenv("CEPH_TPU_HEALTH_MUTE", "PG_AT_RISK, SLO_BURN")
    assert health.muted() == {"PG_AT_RISK", "SLO_BURN"}
    assert health.status() == health.OK  # muted out of the summary...
    s = health.summary()
    assert s["status"] == health.OK
    assert s["checks"]["PG_AT_RISK"]["muted"] is True  # ...but still shown
    d = health.dump()
    assert d["muted"] == ["PG_AT_RISK", "SLO_BURN"]
    assert "PG_AT_RISK" in d["registry"]
    monkeypatch.delenv("CEPH_TPU_HEALTH_MUTE")
    assert health.status() == health.ERR  # unmute restores


def test_evaluate_maps_standard_reductions_onto_standard_codes():
    st = health.evaluate(
        osds_down=2, osd_count=8, degraded=3, unmapped=1, at_risk=1,
        backlog_gb=1.5, device_degraded=1, detail=("osd.3", "osd.5"),
    )
    assert st == health.ERR
    snap = health.summary()["checks"]
    assert set(snap) == {"OSD_DOWN", "PG_DEGRADED", "PG_UNMAPPED",
                         "PG_AT_RISK", "RECOVERY_BACKLOG",
                         "DEVICE_DEGRADED"}
    assert snap["OSD_DOWN"]["summary"] == "2/8 osds down"
    assert snap["RECOVERY_BACKLOG"]["summary"] == "1.500 GB awaiting recovery"
    assert health.dump()["checks"]["OSD_DOWN"]["detail"] == ["osd.3", "osd.5"]
    # recovery drains, one pg stays degraded: ERR collapses to WARN
    st = health.evaluate(osds_down=0, osd_count=8, degraded=1)
    assert st == health.WARN
    assert set(health.summary()["checks"]) == {"PG_DEGRADED"}
    # all clear
    assert health.evaluate() == health.OK
    assert health.checks() == {}


def test_disabled_health_is_inert(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_HEALTH", "0")
    assert not health.enabled()
    assert health.evaluate(osds_down=5, osd_count=5, unmapped=9) == health.OK
    assert health.checks() == {}


# ------------------------------------------------------- timeline recorder


def test_timeline_ring_eviction_folds_8_to_1(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_TIMELINE_CAP", "4")
    assert timeline.cap() == 4
    for k in range(12):  # 8 evictions -> exactly one tier-1 sample
        assert timeline.sample("s", {"v": float(k)}) == k
    d = timeline.dump("s")
    assert d["count"] == 12
    assert d["tier0"]["index"] == [8, 9, 10, 11]
    assert d["tier0"]["fields"]["v"] == [8.0, 9.0, 10.0, 11.0]
    assert d["tier1"]["factor"] == timeline.TIER1_FACTOR == 8
    assert d["tier1"]["index"] == [0]  # stamped with the window's first
    assert d["tier1"]["fields"]["v"] == [pytest.approx(sum(range(8)) / 8)]
    assert timeline.next_index("s") == 12
    assert timeline.last("s") == (11, {"v": 11.0})


def test_timeline_absent_field_reads_zero(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_TIMELINE_CAP", "8")
    timeline.sample("s", {"a": 1.0})
    timeline.sample("s", {"b": 2.0})
    assert timeline.last("s") == (1, {"a": 0.0, "b": 2.0})


def test_timeline_state_restore_continues_indices(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_TIMELINE_CAP", "4")
    for k in range(10):
        timeline.sample("s", {"v": float(k)})
    st = timeline.state("s")
    before = timeline.dump("s")
    timeline.reset()
    assert timeline.next_index("s") == 0
    timeline.restore("s", st)
    assert timeline.dump("s") == before  # both tiers survive the trip
    # the monotonic index continues exactly where the checkpoint stopped
    assert timeline.next_index("s") == 10
    assert timeline.sample("s", {"v": 10.0}) == 10
    # the fold accumulator survived too: the 6 pre-checkpoint evictions
    # plus the post-resume ones close tier-1 windows on schedule
    for k in range(11, 20):
        timeline.sample("s", {"v": float(k)})
    d = timeline.dump("s")
    assert d["tier1"]["index"] == [0, 8]
    assert d["tier1"]["fields"]["v"] == [
        pytest.approx(sum(range(0, 8)) / 8),
        pytest.approx(sum(range(8, 16)) / 8),
    ]


def test_timeline_disabled_is_inert(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_TIMELINE_CAP", "0")
    assert not timeline.enabled()
    assert timeline.sample("s", {"v": 1.0}) == -1
    assert timeline.next_index("s") == 0
    timeline.restore("s", {"count": 5})  # no-op while disabled
    assert timeline.dump() == {}
    assert timeline.prometheus_gauges() == ""


# --------------------------------------------------- serve SLO burn engine


def test_slo_engine_raises_then_clears_slo_burn():
    obj = Objectives(p99_s=0.1, error_ratio=0.01, shed_ratio=0.05)
    assert obj.as_dict() == {"p99_ms": 100.0, "error_pct": 1.0,
                             "shed_pct": 5.0}
    eng = SloEngine(obj)
    t = 0.0
    r = eng.observe(p99_s=0.5, queries=100, errors=0, shed=0, wall_t=t)
    assert r["breach"] and r["reasons"] == ["p99"] and not r["burning"]
    t += 1.0  # second breaching sample: fast=1.0, slow=1.0 -> raise
    r = eng.observe(p99_s=0.5, queries=100, errors=0, shed=0, wall_t=t)
    assert r["burning"] and eng.burns_raised == 1
    assert "SLO_BURN" in health.checks()
    assert health.status() == health.WARN
    # clears only after a full fast window of clean samples
    for k in range(SloEngine.FAST):
        t += 1.0
        r = eng.observe(p99_s=0.01, queries=100, errors=0, shed=0, wall_t=t)
        assert r["burning"] == (k < SloEngine.FAST - 1)
    assert eng.burns_cleared == 1
    assert "SLO_BURN" not in health.checks()
    st = eng.status()
    assert st["samples"] == 10 and st["breaches"] == 2
    # burning t=1..9; status() rounds to 4 decimals
    assert st["burn_minutes"] == pytest.approx(8 / 60.0, abs=1e-3)


def test_slo_engine_scores_error_and_shed_ratios():
    eng = SloEngine(Objectives(p99_s=1.0, error_ratio=0.01, shed_ratio=0.05))
    r = eng.observe(p99_s=0.001, queries=100, errors=2, shed=6, wall_t=0.0)
    assert r["reasons"] == ["errors", "shed"]
    r = eng.observe(p99_s=None, queries=100, errors=1, shed=5, wall_t=1.0)
    assert not r["breach"]  # at-objective is not a breach; p99 unknown


def test_slo_objectives_from_env(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_SLO_P99_MS", "100")
    monkeypatch.setenv("CEPH_TPU_SLO_ERROR_PCT", "2")
    monkeypatch.setenv("CEPH_TPU_SLO_SHED_PCT", "10")
    obj = Objectives.from_env()
    assert obj == Objectives(p99_s=0.1, error_ratio=0.02, shed_ratio=0.1)


# ------------------------------------------------------ pure-observer pin


def test_observers_are_bit_invisible_to_lifetime_digest(monkeypatch):
    """THE purity contract: the same tiny jax lifetime run with health +
    timeline enabled vs disabled lands on the identical replay digest
    and identical steady-state compile count — observation must never
    leak into device work."""
    from ceph_tpu.sim.lifetime import LifetimeSim, Scenario

    spec = ("epochs=12,seed=5,hosts=6,osds_per_host=2,racks=2,pgs=32,"
            "ec=2+2,ec_pgs=16,chunk=256,balance_every=6,spotcheck_every=4,"
            "checkpoint_every=0")
    on = LifetimeSim(Scenario.parse(spec), backend="jax").run()
    assert sum(on["health"]["epochs"].values()) == 12  # every epoch scored
    assert on["health"]["timeline_samples"] == 12

    monkeypatch.setenv("CEPH_TPU_HEALTH", "0")
    monkeypatch.setenv("CEPH_TPU_TIMELINE_CAP", "0")
    health.reset()
    timeline.reset()
    off = LifetimeSim(Scenario.parse(spec), backend="jax").run()
    assert sum(off["health"]["epochs"].values()) == 0  # observers off
    assert off["health"]["timeline_samples"] == 0

    assert off["digest"] == on["digest"]
    assert (off["trace_once"]["steady_compiles"]
            == on["trace_once"]["steady_compiles"] == 0)


# ------------------------------------- osdmaptool USAGE vs parser contract


def test_osdmaptool_usage_matches_parser():
    """Every flag the USAGE banner advertises is either handled by the
    arg loop or on the explicit not-implemented list — the banner is the
    tool's contract, and the reference's silent-skip argparse makes a
    drifted flag a no-op instead of an error."""
    src = (REPO / "ceph_tpu" / "cli" / "osdmaptool.py").read_text()
    tree = ast.parse(src)

    usage = next(
        n.value.value for n in ast.walk(tree)
        if isinstance(n, ast.Assign)
        and any(isinstance(t, ast.Name) and t.id == "USAGE"
                for t in n.targets)
    )
    advertised = {
        line.strip().split()[0]
        for line in usage.splitlines()
        if line.strip().startswith("--")
    }

    parsed = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("flag", "witharg", "withint")):
            parsed |= {a.value for a in node.args
                       if isinstance(a, ast.Constant)
                       and isinstance(a.value, str)}
    parsed.add("--tree")  # handled via peek() for the --tree=json form

    # reference features the graft intentionally leaves out
    UNIMPLEMENTED = {"--clear-temp", "--clean-temps", "--test-random",
                     "--upmap-active", "--test-crush"}
    assert advertised - parsed == UNIMPLEMENTED
    assert not (UNIMPLEMENTED & parsed), "implemented flag still listed"
