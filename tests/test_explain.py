"""Placement-decision observability: explain replay, device diagnostics
planes, and the jax-vs-host first-divergence locator.

Three contracts under test:

1. **Recorder purity** — `mapper_ref.do_rule(recorder=...)` emits the
   full decision log (descents, straw2 draws, rejections) without
   changing a single mapping byte.
2. **Plane exactness** — the instrumented device kernel's diagnostics
   (retry histogram, bad-mapping flags, per-step work vectors)
   reproduce the host oracle bit-for-bit on a seeded corpus where the
   plan is diag-exact, and the `--show-choose-tries` unification keeps
   the tester's histogram identical across backends.
3. **Triage** — on a deliberately perturbed-tunables map the
   first-divergence locator pins the exact earliest differing choose
   step (computed independently here from two host walks).
"""

import io
import json

import numpy as np
import pytest

from ceph_tpu.crush import explain, mapper_ref
from ceph_tpu.crush.soa import build_arrays
from ceph_tpu.crush.tester import CrushTester, TesterConfig
from ceph_tpu.crush.types import ITEM_NONE, Tunables
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgPool
from tests.util_maps import build_flat, build_tree, ec_rule, \
    replicated_rule

N_X = 128
W32 = [0x10000] * 32


def _host_hist(m, ruleno, xs, nr, w, bound=51):
    m.choose_tries_histogram = [0] * bound
    for x in xs:
        mapper_ref.do_rule(m, ruleno, int(x), nr, list(w),
                           collect_choose_tries=True)
    return list(m.choose_tries_histogram)


@pytest.fixture(scope="module")
def hier():
    """(map, ruleno, arrays): chooseleaf firstn over hosts."""
    m, root = build_tree(np.random.default_rng(7), n_host=8,
                         osd_per_host=4)
    ruleno = replicated_rule(m, root, fd_type=1, numrep=3)
    return m, ruleno, build_arrays(m)


@pytest.fixture(scope="module")
def hier_perturbed():
    """Same construction, different tunables — the seeded divergence."""
    m, root = build_tree(
        np.random.default_rng(7), n_host=8, osd_per_host=4,
        tunables=Tunables(chooseleaf_vary_r=0, chooseleaf_stable=0),
    )
    replicated_rule(m, root, fd_type=1, numrep=3)
    return m


class TestExplainReplay:
    def test_recorder_never_perturbs(self, hier):
        m, ruleno, _ = hier
        for x in range(32):
            want = mapper_ref.do_rule(m, ruleno, x, 3, W32)
            ex = explain.explain_seed(m, ruleno, x, 3, W32)
            assert ex["result"] == want

    def test_event_log_shape(self, hier):
        m, ruleno, _ = hier
        ex = explain.explain_seed(m, ruleno, 1234, 3, W32)
        kinds = [ev["ev"] for ev in ex["events"]]
        assert kinds[0] == "take"
        assert "choose" in kinds and "emit" in kinds
        # one work vector per choose step, matching the final result
        assert len(ex["steps"]) == 1
        assert ex["steps"][0] == ex["result"]
        places = [ev for ev in ex["events"] if ev["ev"] == "place"]
        # chooseleaf: outer + leaf recursion placements
        assert len(places) == 3 * 2

    def test_straw2_draws_name_the_winner(self, hier):
        m, ruleno, _ = hier
        ex = explain.explain_seed(m, ruleno, 42, 3, W32)
        draws = [ev for ev in ex["events"] if ev["ev"] == "straw2"]
        assert draws
        for ev in draws:
            best = max(ev["draws"], key=lambda d: d[1])
            assert best[0] == ev["winner"]

    def test_render_text(self, hier):
        m, ruleno, _ = hier
        txt = explain.render_text(
            explain.explain_seed(m, ruleno, 7, 3, W32), m.item_names)
        assert "take" in txt and "straw2" in txt and "PLACE" in txt
        assert "result=" in txt

    def test_explain_pool_pg(self):
        m = build_hierarchical(4, 4, pool=PgPool(pg_num=64, size=3))
        ex = explain.explain_pool_pg(m, 0, 5)
        assert ex["pool"] == 0 and ex["seed"] == 5
        up, _, _, _ = m.pg_to_up_acting_osds(
            __import__("ceph_tpu.osd.types", fromlist=["PgId"]).PgId(0, 5))
        assert ex["up"] == [int(v) for v in up]
        assert "error" in explain.explain_pool_pg(m, 9, 0)
        assert "error" in explain.explain_pool_pg(m, 0, 10_000)


class TestDeviceHistogram:
    """--show-choose-tries single source of truth: device planes."""

    def test_hier_bit_identical(self, hier):
        m, ruleno, A = hier
        xs = np.arange(N_X, dtype=np.uint32)
        hist, unres = explain.device_choose_tries(
            A, ruleno, 3, xs, np.asarray(W32, np.uint32), 51)
        assert len(unres) == 0
        assert list(hist) == _host_hist(m, ruleno, xs, 3, W32)

    def test_flat_weighted_bit_identical(self):
        # out-of-weight rejections in play: half the devices weight 0
        w = [0x10000 if i % 2 else 0 for i in range(16)]
        m, root = build_flat(16, weights=[0x10000] * 16)
        ruleno = replicated_rule(m, root, fd_type=0, numrep=3)
        A = build_arrays(m)
        xs = np.arange(N_X, dtype=np.uint32)
        hist, unres = explain.device_choose_tries(
            A, ruleno, 3, xs, np.asarray(w, np.uint32), 51)
        mask = np.ones(N_X, bool)
        mask[unres] = False
        host = _host_hist(m, ruleno, xs[mask], 3, w)
        assert list(hist) == host
        assert sum(host) > 0

    def test_indep_bit_identical(self):
        m, root = build_tree(np.random.default_rng(3), n_host=8,
                             osd_per_host=4)
        ruleno = ec_rule(m, root, fd_type=0, k_m=6)
        A = build_arrays(m)
        xs = np.arange(N_X, dtype=np.uint32)
        hist, unres = explain.device_choose_tries(
            A, ruleno, 6, xs, np.asarray(W32, np.uint32), 51)
        assert len(unres) == 0
        assert list(hist) == _host_hist(m, ruleno, xs, 6, W32)

    def test_tester_jax_matches_ref_output(self, hier):
        m, _, _ = hier
        outs = []
        for backend in ("jax", "ref"):
            cfg = TesterConfig(min_x=0, max_x=63, num_rep=3,
                               show_choose_tries=True, backend=backend)
            out = io.StringIO()
            CrushTester(m, cfg, out=out).test()
            outs.append(out.getvalue())
        assert outs[0] == outs[1]
        assert "choose_tries histogram" in outs[0]


class TestFirstDivergence:
    def test_agreement_on_same_map(self, hier):
        m, ruleno, A = hier
        xs = np.arange(N_X, dtype=np.uint32)
        assert explain.first_divergence(m, A, ruleno, xs, 3, W32) is None

    def test_perturbed_tunables_pins_first_step(self, hier,
                                                hier_perturbed):
        m, ruleno, A = hier
        m2 = hier_perturbed
        xs = np.arange(N_X, dtype=np.uint32)
        d = explain.first_divergence(m2, A, ruleno, xs, 3, W32)
        assert d is not None
        # independent expectation: device(A)==host(m) step-for-step
        # (asserted above), so the earliest divergence must equal the
        # earliest host(m)-vs-host(m2) step difference over the batch
        expect = None
        for x in xs:
            s1 = explain.explain_seed(m, ruleno, int(x), 3, W32,
                                      detail=False)["steps"]
            s2 = explain.explain_seed(m2, ruleno, int(x), 3, W32,
                                      detail=False)["steps"]
            for s in range(max(len(s1), len(s2))):
                a = (list(s1[s]) if s < len(s1) else []) + [ITEM_NONE] * 3
                b = (list(s2[s]) if s < len(s2) else []) + [ITEM_NONE] * 3
                if a[:3] != b[:3]:
                    if expect is None or s < expect[0]:
                        expect = (s, int(x))
                    break
        assert expect is not None
        assert d["step"] == expect[0]
        # the reported seed diverges at that step (host log rides along)
        assert d["jax"] != d["host"]
        assert d["host_log"]["x"] == d["x"]
        assert d["n_divergent"] >= 1
        assert d["n_checked"] + d["n_unresolved_skipped"] == N_X

    def test_divergence_against_reweighted_map(self, hier):
        # triage against a *candidate map edit*: same tunables, one
        # host bucket reweighted on the host side only
        m, ruleno, A = hier
        import copy

        m2 = copy.deepcopy(m)
        b = m2.buckets[min(m2.buckets)]
        m2.adjust_item_weight(b.items[0], b.weights[0] * 4)
        xs = np.arange(N_X, dtype=np.uint32)
        d = explain.first_divergence(m2, A, ruleno, xs, 3, W32)
        assert d is not None and d["step"] == 0


class TestCrushtoolCLI:
    @pytest.fixture(scope="class")
    def mapfile(self, tmp_path_factory):
        from ceph_tpu.crush.codec import encode_crushmap

        m, root = build_tree(np.random.default_rng(7), n_host=8,
                             osd_per_host=4)
        replicated_rule(m, root, fd_type=1, numrep=3)
        fn = tmp_path_factory.mktemp("maps") / "m.bin"
        fn.write_bytes(encode_crushmap(m))
        return str(fn)

    def test_explain_command(self, mapfile, capsys):
        from ceph_tpu.cli.crushtool import main

        assert main(["-i", mapfile, "explain", "42",
                     "--num-rep", "3"]) == 0
        out = capsys.readouterr().out
        assert "explain x=42" in out and "straw2" in out

    def test_explain_pool_seed_form(self, mapfile, capsys):
        from ceph_tpu.cli.crushtool import main

        assert main(["-i", mapfile, "explain", "1.7",
                     "--num-rep", "3"]) == 0
        assert "explain pg 1.7" in capsys.readouterr().out

    def test_locate_divergence_clean(self, mapfile, capsys):
        from ceph_tpu.cli.crushtool import main

        rc = main(["-i", mapfile, "--locate-divergence", "--max-x",
                   "63", "--num-rep", "3"])
        assert rc == 0
        assert "no divergence" in capsys.readouterr().out

    def test_locate_divergence_against(self, mapfile, tmp_path, capsys):
        from ceph_tpu.cli.crushtool import main
        from ceph_tpu.crush.codec import encode_crushmap

        m2, root2 = build_tree(
            np.random.default_rng(7), n_host=8, osd_per_host=4,
            tunables=Tunables(chooseleaf_vary_r=0, chooseleaf_stable=0),
        )
        replicated_rule(m2, root2, fd_type=1, numrep=3)
        fn2 = tmp_path / "m2.bin"
        fn2.write_bytes(encode_crushmap(m2))
        rc = main(["-i", mapfile, "--locate-divergence", "--against",
                   str(fn2), "--max-x", "63", "--num-rep", "3"])
        assert rc == 2
        out = capsys.readouterr().out
        assert "DIVERGENCE" in out
        assert "first differing choose step" in out


class TestPoolMapperDiagnose:
    @pytest.fixture(scope="class")
    def pool_map(self):
        return build_hierarchical(8, 4, n_rack=1,
                                  pool=PgPool(pg_num=256, size=3))

    def test_summary_and_default_path_untouched(self, pool_map):
        from ceph_tpu import obs
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        pm = PoolMapper(pool_map, 0, overlays=False)
        ps = np.arange(256, dtype=np.uint32)
        base = pm.map_batch(ps)  # warm the default executable
        s = pm.diagnose(ps, record=False)
        assert s["pgs"] == 256 and s["diag_exact"]
        assert sum(s["tries_histogram"]) > 0
        assert s["bad_mappings"] == 0
        # instrumentation must not have touched the default entry:
        # the next default pass books 0 compiles, identical bytes
        j0 = obs.jit_counters()
        again = pm.map_batch(ps)
        assert obs.jit_counters()["compiles"] - j0["compiles"] == 0
        for a, b in zip(base, again):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_histogram_matches_host(self, pool_map):
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        pm = PoolMapper(pool_map, 0, overlays=False)
        s = pm.diagnose(record=False)
        pool = pool_map.pools[0]
        crush = pool_map.crush
        ruleno = mapper_ref.find_rule(
            crush, pool.crush_rule, int(pool.type), pool.size)
        from ceph_tpu.osd.types import PgId

        pps = [pool.raw_pg_to_pps(PgId(0, x))
               for x in range(pool.pg_num)]
        host = _host_hist(crush, ruleno, pps, pool.size,
                          list(pool_map.osd_weight),
                          bound=s["tries_bound"] + 1)
        assert s["tries_histogram"] == host

    def test_unresolvable_lanes_masked(self):
        # 2 hosts, size-3 chooseleaf: the window cannot prove the C
        # would also fail, so every lane is flagged and EXCLUDED —
        # garbage planes never masquerade as diagnostics
        m = build_hierarchical(2, 2, pool=PgPool(pg_num=64, size=3))
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        s = PoolMapper(m, 0, overlays=False).diagnose(record=False)
        assert s["unresolved"] == 64
        assert sum(s["tries_histogram"]) == 0

    def test_inexact_plan_books_no_exhaustion(self):
        # loop-path tunables compile an inexact plan whose tries planes
        # are all -1 (uninstrumented, NOT exhaustion) — the summary must
        # say so instead of reporting pgs*lanes bogus exhaustions
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        m = build_hierarchical(
            4, 4, pool=PgPool(pg_num=64, size=3),
            tunables=Tunables(chooseleaf_vary_r=0, chooseleaf_stable=0))
        s = PoolMapper(m, 0, overlays=False).diagnose(record=False)
        assert s["diag_exact"] is False
        assert s["retry_exhausted"] == 0
        assert sum(s["tries_histogram"]) == 0

    def test_record_and_explain_registry(self, pool_map):
        from ceph_tpu.obs import placement
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        placement.reset()
        pm = PoolMapper(pool_map, 0, overlays=False)
        pm.diagnose()
        dump = placement.dump()
        assert "pool0" in dump["sources"]
        assert dump["counters"]["pgs_diagnosed"] >= 256
        ex = placement.explain("0.5")
        assert ex.get("pool") == 0 and ex.get("seed") == 5
        assert "error" in placement.explain("9.0")
        assert "error" in placement.explain("garbage")


class TestPlacementObs:
    def test_fold_summary(self):
        from ceph_tpu.obs import placement

        agg: dict = {}
        placement.fold_summary(agg, {
            "pgs": 4, "bad_mappings": 1, "tries_histogram": [3, 1],
            "diag_exact": True})
        placement.fold_summary(agg, {
            "pgs": 2, "collisions": 5,
            "tries_histogram": [1, 0, 2], "diag_exact": True})
        assert agg["pgs"] == 6 and agg["bad_mappings"] == 1
        assert agg["collisions"] == 5
        assert agg["tries_histogram"] == [4, 1, 2]
        assert agg["diag_exact"] is True
        placement.fold_summary(agg, {"pgs": 1})  # no diag_exact: False
        assert agg["diag_exact"] is False

    def test_merge_histogram_counter(self):
        from ceph_tpu.utils.perf_counters import logger_for

        L = logger_for("placement")
        before = L.dump()["choose_tries"]["count"]
        L.merge_histogram("choose_tries", [0, 2, 3])
        rec = L.dump()["choose_tries"]
        assert rec["count"] == before + 5
        with pytest.raises(Exception):
            L.merge_histogram("pgs_diagnosed", [1])

    def test_prometheus_gauges(self):
        from ceph_tpu.obs import placement

        placement.reset()
        assert placement.prometheus_gauges() == ""
        placement.record("testsrc", {"pgs": 8, "bad_mappings": 3,
                                     "retry_exhausted": 2})
        text = placement.prometheus_gauges()
        assert ('ceph_tpu_placement_source_bad_mappings'
                '{source="testsrc"} 3') in text
        assert ('ceph_tpu_placement_source_retry_exhausted'
                '{source="testsrc"} 2') in text
        # label values embed user-chosen plan names -> must be escaped
        placement.record('mgr.a"b\\c\nd', {"bad_mappings": 1})
        hostile = placement.prometheus_gauges()
        assert ('{source="mgr.a\\"b\\\\c\\nd"} 1') in hostile
        assert '\n{' not in hostile
        placement.reset()

    def test_admin_commands(self):
        from ceph_tpu.obs import admin_socket, placement
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        placement.reset()
        m = build_hierarchical(4, 4, pool=PgPool(pg_num=64, size=3))
        PoolMapper(m, 0, overlays=False).diagnose()
        bad = json.loads(admin_socket.handle_command("bad dump"))
        assert "pool0" in bad["sources"]
        ex = json.loads(admin_socket.handle_command("explain 0.3"))
        assert ex.get("seed") == 3
        err = json.loads(admin_socket.handle_command("explain"))
        assert "error" in err


class TestEpochAccounting:
    def test_sim_diag_history(self):
        from ceph_tpu.obs import placement
        from ceph_tpu.sim.failure import ClusterSim

        m = build_hierarchical(4, 4, pool=PgPool(pg_num=64, size=3))
        sim = ClusterSim(m, diagnostics=True)
        sim.fail_osd(3)
        labels = [lab for lab, _ in sim.diag_history]
        assert labels == ["init", "fail osd.3"]
        for _, agg in sim.diag_history:
            assert agg["pgs"] == 64
            assert agg["diag_exact"] is True
        assert sim.diag_history[0][1]["epoch"] < \
            sim.diag_history[1][1]["epoch"]
        assert "sim" in placement.dump()["sources"]

    def test_sim_diag_off_by_default(self, monkeypatch):
        from ceph_tpu.sim.failure import ClusterSim

        monkeypatch.delenv("CEPH_TPU_PLACEMENT_DIAG", raising=False)
        m = build_hierarchical(4, 4, pool=PgPool(pg_num=64, size=3))
        sim = ClusterSim(m)
        sim.fail_osd(1)
        assert sim.diag_history == []

    def test_balancer_execute_accounting(self, monkeypatch):
        from ceph_tpu.mgr.eval import MappingState
        from ceph_tpu.mgr.module import Balancer
        from ceph_tpu.obs import placement

        monkeypatch.setenv("CEPH_TPU_PLACEMENT_DIAG", "1")
        placement.reset()
        m = build_hierarchical(
            4, 4, pool=PgPool(pg_num=64, size=3),
            weight_fn=lambda i: 0x10000 * (1 + (i % 3)))
        b = Balancer()
        plan = b.plan_create("acct", MappingState(m), mode="upmap")
        rc, _ = b.optimize(plan)
        assert rc == 0
        assert b.execute(plan, m) == (0, "")
        src = placement.dump()["sources"]
        assert "mgr.acct" in src
        assert src["mgr.acct"]["pgs"] == 64
        assert src["mgr.acct"]["epoch"] == m.epoch

    def test_balancer_execute_survives_device_loss(self, monkeypatch):
        from ceph_tpu.mgr.eval import MappingState
        from ceph_tpu.mgr.module import Balancer
        from ceph_tpu.obs import placement
        from ceph_tpu.osd.pipeline_jax import PoolMapper
        from ceph_tpu.runtime import DeviceLostError

        monkeypatch.setenv("CEPH_TPU_PLACEMENT_DIAG", "1")
        placement.reset()

        def boom(self, record=True):
            raise DeviceLostError("wedged")

        monkeypatch.setattr(PoolMapper, "diagnose", boom)
        m = build_hierarchical(
            4, 4, pool=PgPool(pg_num=64, size=3),
            weight_fn=lambda i: 0x10000 * (1 + (i % 3)))
        b = Balancer()
        plan = b.plan_create("lost", MappingState(m), mode="upmap")
        rc, _ = b.optimize(plan)
        assert rc == 0
        # the incremental already landed -> diagnostics failure must not
        # turn a successful execute into an error
        assert b.execute(plan, m) == (0, "")
        assert "mgr.lost" not in placement.dump()["sources"]


@pytest.mark.slow
class TestAtScale:
    def test_large_corpus_agreement_and_histogram(self):
        pool = PgPool(pg_num=16384, size=3)
        m = build_hierarchical(16, 8, n_rack=2, pool=pool)
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        pm = PoolMapper(m, 0, overlays=False)
        s = pm.diagnose(record=False)
        assert s["pgs"] == 16384 and s["diag_exact"]
        assert sum(s["tries_histogram"]) >= 16384 * 3
        crush = m.crush
        ruleno = mapper_ref.find_rule(
            crush, pool.crush_rule, int(pool.type), pool.size)
        A = build_arrays(crush)
        xs = (np.arange(4096, dtype=np.uint32) * 2654435761) % (2**31)
        d = explain.first_divergence(
            crush, A, ruleno, xs, 3, list(m.osd_weight))
        assert d is None
