"""Differential tests: rjenkins hash + crush_ln vs the compiled C reference,
and numpy-vs-jax agreement of both."""

import numpy as np
import pytest

from ceph_tpu.core.rjenkins import (
    crush_hash32,
    crush_hash32_2,
    crush_hash32_3,
    crush_hash32_5,
)
from ceph_tpu.core.lntable import crush_ln_np, crush_ln_jax, RH_LH_TBL, LL_TBL
from ceph_tpu.core.intmath import stable_mod, pg_mask_for


def test_hash2_vs_c(oracle_lib, rng):
    a = rng.integers(0, 2**32, 2000, dtype=np.uint32)
    b = rng.integers(0, 2**32, 2000, dtype=np.uint32)
    ours = crush_hash32_2(a, b)
    for i in range(0, 2000, 97):
        assert int(ours[i]) == oracle_lib.oracle_hash32_2(int(a[i]), int(b[i]))


def test_hash3_vs_c(oracle_lib, rng):
    a = rng.integers(0, 2**32, 500, dtype=np.uint32)
    b = rng.integers(0, 2**32, 500, dtype=np.uint32)
    c = rng.integers(0, 2**32, 500, dtype=np.uint32)
    ours = crush_hash32_3(a, b, c)
    for i in range(0, 500, 41):
        assert int(ours[i]) == oracle_lib.oracle_hash32_3(
            int(a[i]), int(b[i]), int(c[i])
        )


def test_hash_known_vectors():
    # values pinned from the C-oracle-verified implementation, so the suite
    # catches regressions even without the reference mount
    from ceph_tpu.core.rjenkins import crush_hash32_4, str_hash_rjenkins

    assert int(crush_hash32(0)) == 398764043
    assert int(crush_hash32(12345)) == 3450610134
    assert int(crush_hash32_2(0, 0)) == 430787817
    assert int(crush_hash32_2(1234, 5678)) == 2437553297
    assert int(crush_hash32_3(1, 2, 3)) == 1935332395
    assert int(crush_hash32_4(1, 2, 3, 4)) == 1768759062
    assert int(crush_hash32_5(1, 2, 3, 4, 5)) == 1262657953
    assert str_hash_rjenkins(b"foo") == 2143417350
    assert str_hash_rjenkins(b"") == 3175731469
    assert str_hash_rjenkins(b"0123456789abcdef") == 3776469959


def test_hash_jax_matches_numpy():
    import jax.numpy as jnp

    a = (np.arange(1000, dtype=np.uint64) * 2654435761 % (2**32)).astype(np.uint32)
    b = (a * np.uint32(31) + np.uint32(7)).astype(np.uint32)
    np_h = crush_hash32_2(a, b)
    jx_h = np.asarray(crush_hash32_2(jnp.asarray(a), jnp.asarray(b), xp=jnp))
    np.testing.assert_array_equal(np_h, jx_h)
    np_h3 = crush_hash32_3(a, b, a ^ b)
    jx_h3 = np.asarray(
        crush_hash32_3(jnp.asarray(a), jnp.asarray(b), jnp.asarray(a ^ b), xp=jnp)
    )
    np.testing.assert_array_equal(np_h3, jx_h3)
    np_h5 = crush_hash32_5(a, b, a, b, a)
    jx_h5 = np.asarray(
        crush_hash32_5(*(jnp.asarray(v) for v in (a, b, a, b, a)), xp=jnp)
    )
    np.testing.assert_array_equal(np_h5, jx_h5)


def test_ln_tables_shapes():
    assert RH_LH_TBL.shape == (258,)
    assert LL_TBL.shape == (256,)
    assert RH_LH_TBL[0] == 1 << 48
    assert RH_LH_TBL[256] == 1 << 47
    assert RH_LH_TBL[257] == 0xFFFF00000000


def test_crush_ln_exhaustive_numpy_vs_jax():
    import jax.numpy as jnp

    x = np.arange(0x10000, dtype=np.uint32)
    a = crush_ln_np(x)
    b = np.asarray(crush_ln_jax(jnp.asarray(x)))
    np.testing.assert_array_equal(a.astype(np.uint64), b.astype(np.uint64))


def test_crush_ln_monotone_and_range():
    x = np.arange(0x10000, dtype=np.uint32)
    v = crush_ln_np(x).astype(np.int64)
    # 2^44*log2(x+1): ln(0)=0, ln(0xffff)=almost 2^48
    assert v[0] == 0
    assert v[-1] <= 1 << 48
    # monotone everywhere except the final step, where the reference's capped
    # RH_LH_TBL[257]=0xffff00000000 entry (not 2^48) makes ln(0xffff) dip —
    # a table quirk we reproduce bit-for-bit.
    assert np.all(np.diff(v)[:-1] >= 0)
    assert v[-1] < v[-2]


def test_stable_mod():
    # reference src/include/rados.h:96-102
    for b in [1, 3, 8, 12, 100, 128, 1000]:
        bmask = pg_mask_for(b)
        for x in range(0, 4 * (bmask + 1), 7):
            lo = x & bmask
            want = lo if lo < b else x & (bmask >> 1)
            assert int(stable_mod(x, b, bmask)) == want
            assert int(stable_mod(x, b, bmask)) < b
