"""Differential tests: rjenkins hash + crush_ln vs the compiled C reference,
and numpy-vs-jax agreement of both."""

import numpy as np
import pytest

from ceph_tpu.core.rjenkins import (
    crush_hash32,
    crush_hash32_2,
    crush_hash32_3,
    crush_hash32_5,
)
from ceph_tpu.core.lntable import crush_ln_np, crush_ln_jax, RH_LH_TBL, LL_TBL
from ceph_tpu.core.intmath import stable_mod, pg_mask_for


def test_hash2_vs_c(oracle_lib, rng):
    a = rng.integers(0, 2**32, 2000, dtype=np.uint32)
    b = rng.integers(0, 2**32, 2000, dtype=np.uint32)
    ours = crush_hash32_2(a, b)
    for i in range(0, 2000, 97):
        assert int(ours[i]) == oracle_lib.oracle_hash32_2(int(a[i]), int(b[i]))


def test_hash3_vs_c(oracle_lib, rng):
    a = rng.integers(0, 2**32, 500, dtype=np.uint32)
    b = rng.integers(0, 2**32, 500, dtype=np.uint32)
    c = rng.integers(0, 2**32, 500, dtype=np.uint32)
    ours = crush_hash32_3(a, b, c)
    for i in range(0, 500, 41):
        assert int(ours[i]) == oracle_lib.oracle_hash32_3(
            int(a[i]), int(b[i]), int(c[i])
        )


def test_hash_known_vectors():
    # values pinned from the C-oracle-verified implementation, so the suite
    # catches regressions even without the reference mount
    from ceph_tpu.core.rjenkins import crush_hash32_4, str_hash_rjenkins

    assert int(crush_hash32(0)) == 398764043
    assert int(crush_hash32(12345)) == 3450610134
    assert int(crush_hash32_2(0, 0)) == 430787817
    assert int(crush_hash32_2(1234, 5678)) == 2437553297
    assert int(crush_hash32_3(1, 2, 3)) == 1935332395
    assert int(crush_hash32_4(1, 2, 3, 4)) == 1768759062
    assert int(crush_hash32_5(1, 2, 3, 4, 5)) == 1262657953
    assert str_hash_rjenkins(b"foo") == 2143417350
    assert str_hash_rjenkins(b"") == 3175731469
    assert str_hash_rjenkins(b"0123456789abcdef") == 3776469959


def test_hash_jax_matches_numpy():
    import jax.numpy as jnp

    a = (np.arange(1000, dtype=np.uint64) * 2654435761 % (2**32)).astype(np.uint32)
    b = (a * np.uint32(31) + np.uint32(7)).astype(np.uint32)
    np_h = crush_hash32_2(a, b)
    jx_h = np.asarray(crush_hash32_2(jnp.asarray(a), jnp.asarray(b), xp=jnp))
    np.testing.assert_array_equal(np_h, jx_h)
    np_h3 = crush_hash32_3(a, b, a ^ b)
    jx_h3 = np.asarray(
        crush_hash32_3(jnp.asarray(a), jnp.asarray(b), jnp.asarray(a ^ b), xp=jnp)
    )
    np.testing.assert_array_equal(np_h3, jx_h3)
    np_h5 = crush_hash32_5(a, b, a, b, a)
    jx_h5 = np.asarray(
        crush_hash32_5(*(jnp.asarray(v) for v in (a, b, a, b, a)), xp=jnp)
    )
    np.testing.assert_array_equal(np_h5, jx_h5)


def test_ln_tables_shapes():
    assert RH_LH_TBL.shape == (258,)
    assert LL_TBL.shape == (256,)
    assert RH_LH_TBL[0] == 1 << 48
    assert RH_LH_TBL[256] == 1 << 47
    assert RH_LH_TBL[257] == 0xFFFF00000000


def test_crush_ln_exhaustive_numpy_vs_jax():
    import jax.numpy as jnp

    x = np.arange(0x10000, dtype=np.uint32)
    a = crush_ln_np(x)
    b = np.asarray(crush_ln_jax(jnp.asarray(x)))
    np.testing.assert_array_equal(a.astype(np.uint64), b.astype(np.uint64))


def test_crush_ln_monotone_and_range():
    x = np.arange(0x10000, dtype=np.uint32)
    v = crush_ln_np(x).astype(np.int64)
    # 2^44*log2(x+1): ln(0)=0, ln(0xffff)=almost 2^48
    assert v[0] == 0
    assert v[-1] <= 1 << 48
    # monotone everywhere except the final step, where the reference's capped
    # RH_LH_TBL[257]=0xffff00000000 entry (not 2^48) makes ln(0xffff) dip —
    # a table quirk we reproduce bit-for-bit.
    assert np.all(np.diff(v)[:-1] >= 0)
    assert v[-1] < v[-2]


def test_stable_mod():
    # reference src/include/rados.h:96-102
    for b in [1, 3, 8, 12, 100, 128, 1000]:
        bmask = pg_mask_for(b)
        for x in range(0, 4 * (bmask + 1), 7):
            lo = x & bmask
            want = lo if lo < b else x & (bmask >> 1)
            assert int(stable_mod(x, b, bmask)) == want
            assert int(stable_mod(x, b, bmask)) < b


def test_crush_ln_scan_jax_exhaustive():
    """crush_ln_scan_jax (the TPU select-scan form) over the full 2^16
    input domain vs the numpy oracle."""
    from ceph_tpu.core.lntable import crush_ln_np, crush_ln_scan_jax

    u = np.arange(65536, dtype=np.uint32)
    want = crush_ln_np(u).astype(np.int64)
    got = np.asarray(crush_ln_scan_jax(u))
    assert np.array_equal(want, got)


def test_crush_ln_onehot_jax_exhaustive():
    """crush_ln_onehot_jax (the MXU one-hot-matmul form) over the full
    2^16 input domain vs the numpy oracle."""
    from ceph_tpu.core.lntable import crush_ln_np, crush_ln_onehot_jax

    u = np.arange(65536, dtype=np.uint32)
    want = crush_ln_np(u).astype(np.int64)
    got = np.asarray(crush_ln_onehot_jax(u))
    assert np.array_equal(want, got)


def test_straw2_magic_division():
    """The row path's invariant-divisor multiply (mapper_jax._magic_div_consts
    + the 24-bit-limb multiply-high in _straw2_rows) equals floor division
    for every weight class and the full numerator range boundary cases."""
    from ceph_tpu.crush.mapper_jax import _magic_div_consts

    rng = np.random.default_rng(1234)
    ws = np.concatenate([
        np.arange(1, 512),
        (2 ** np.arange(0, 32, dtype=np.int64)),
        (2 ** np.arange(1, 32, dtype=np.int64)) - 1,
        (2 ** np.arange(1, 32, dtype=np.int64)) + 1,
        rng.integers(1, 2**32, 1000),
    ]).astype(np.int64)
    ns = np.concatenate([
        np.array([0, 1, 2, (1 << 48) - 1, 1 << 48]),
        rng.integers(0, (1 << 48) + 1, 4000),
    ]).astype(np.int64)
    for w in ws:
        m, l = _magic_div_consts(int(w))
        m0, m1, m2 = m & 0xFFFFFF, (m >> 24) & 0xFFFFFF, m >> 48
        n0, n1 = ns & 0xFFFFFF, ns >> 24
        t0 = n0 * m0
        t1 = n0 * m1 + n1 * m0 + (t0 >> 24)
        t2 = n0 * m2 + n1 * m1 + (t1 >> 24)
        t3 = n1 * m2 + (t2 >> 24)
        high = (t2 & 0xFFFFFF) | (t3 << 24)
        q = high >> (l + 1)
        assert np.array_equal(q, ns // w), f"w={w}"
