"""Fleet simulator: sweep-grammar expansion, stacked-dispatch digest
equivalence against the solo oracle, pareto-front reduction, atomic
whole-stack checkpoint/resume refusal, and the CLI triage surface.

The digest tests are the contract that matters: every member of a
stacked fleet must land on the SAME SHA-256 replay digest a solo
`LifetimeSim` of the identical scenario produces — including a
`correlated=1` member and a member whose starved recovery pipe loses
PGs (the DATA_LOSS latch must survive stacking).  Tier-1 keeps the
fleet small; the 64-cluster acceptance-scale sweep is slow-marked.
"""

from __future__ import annotations

import json
from dataclasses import fields

import pytest

from ceph_tpu import obs
from ceph_tpu.cli import fleet as fleet_cli
from ceph_tpu.fleet.engine import FleetSim
from ceph_tpu.fleet.pareto import (
    Point,
    dominates,
    pareto_front,
    triage_table,
)
from ceph_tpu.fleet.spec import (
    FLEET_KNOBS,
    SWEEP_AXES,
    parse_fleet,
)
from ceph_tpu.runtime import faults
from ceph_tpu.sim.lifetime import LifetimeSim, Scenario

BASE = ("epochs=8,seed=5,hosts=4,osds_per_host=3,racks=2,pgs=32,"
        "ec=2+1,ec_pgs=16,chunk=256,balance_every=0,spotcheck_every=0,"
        "checkpoint_every=0,recovery=queue,max_backfills=4,"
        "recovery_mbps=200,osd_mbps=400")

# the proven loss scenario (test_correlated's overwhelmed pipe) as a
# cluster override: a starved pipe under a brutal death rate loses PGs
LOSS = ("epochs=14,hosts=3,osds_per_host=2,racks=1,pgs=16,ec_pgs=8,"
        "chunk=64,seed=7,p_death=0.25,p_flap=0.05,p_host_outage=0.10,"
        "p_reweight=0,p_pg_temp=0,p_pool_create=0,p_split=0,"
        "p_expand=0,p_remove=0.02,max_backfills=1,recovery_mbps=2,"
        "osd_mbps=4,correlated=1,flappers=1")

# 4 heterogeneous members: plain, balanced, correlated, and data-loss
DIGEST_SPEC = (f"base={BASE};"
               "axis=correlated:0|1;"
               "axis=recovery_mbps:100|400;"
               "cluster=1:balance_every=3;"
               f"cluster=3:{LOSS}")

# small all-host fleet for checkpoint and CLI smoke (fast, no device)
REF_SPEC = (f"base={BASE},epochs=6;"
            "axis=seed:1|2;axis=p_death:0.02|0.1;"
            "backend=ref")


@pytest.fixture(autouse=True)
def _clean():
    obs.health.reset()
    yield
    faults.disarm_all()
    obs.health.reset()


def _solo_digest(member) -> str:
    """The oracle: a solo LifetimeSim of the member's pinned scenario
    (same balancer state backend the fleet pins for jax members)."""
    sim = LifetimeSim(Scenario.parse(member.scenario.spec()),
                      backend=member.backend)
    if member.backend == "jax":
        sim.balancer_options = {"upmap_state_backend": "device_loop"}
    return sim.run()["digest"]


# ------------------------------------------------------- sweep grammar


def test_sweep_axes_are_scenario_fields():
    """Runtime mirror of the graftlint sweep-grammar pass: every
    registered axis names a real Scenario field and no fleet knob
    shadows one (the grammar could not tell the two apart)."""
    names = {f.name for f in fields(Scenario)}
    for key in SWEEP_AXES:
        assert key in names, key
    for key in FLEET_KNOBS:
        assert key not in names, key


def test_readme_sweep_table_covers_every_key():
    import pathlib

    readme = (pathlib.Path(__file__).resolve().parents[1]
              / "README.md").read_text()
    for key in list(SWEEP_AXES) + list(FLEET_KNOBS):
        assert f"| `{key}` |" in readme, (
            f"{key} missing from README sweep-grammar table")


def test_parse_fleet_sweeps_every_registered_axis():
    """One spec sweeping EVERY registered axis parses, and expansion
    order is the cross-product with the last axis varying fastest."""
    spec = (f"base={BASE};"
            "axis=seed:1|2;axis=epochs:6|8;axis=pgs:16|32;"
            "axis=ec:2+1|4+2;axis=ec_pgs:8|16;axis=hosts:3|4;"
            "axis=p_flap:0|0.05;axis=p_death:0|0.1;"
            "axis=correlated:0|1;axis=recovery_mbps:100|400;"
            "axis=max_backfills:1|4;axis=osd_mbps:200|400;"
            "axis=balance_every:0|4;axis=workload:0|1;"
            "axis=base_qps:500|1000;"
            "clusters=4")
    ms = parse_fleet(spec)
    assert len(ms) == 4
    assert ms[0].scenario.seed == 1 and ms[0].scenario.workload == 0
    # last axis (base_qps) varies fastest
    assert ms[0].scenario.base_qps == 500.0
    assert ms[1].scenario.base_qps == 1000.0
    assert ms[1].scenario.workload == 0
    assert ms[2].scenario.workload == 1
    specs = [m.spec() for m in ms]
    assert len(set(specs)) == 4


def test_clusters_cycle_offsets_seed_per_repetition():
    ms = parse_fleet(f"base={BASE};axis=p_death:0.02|0.1;clusters=5")
    assert len(ms) == 5
    assert [m.scenario.seed for m in ms] == [5, 5, 6, 6, 7]
    assert len({m.spec() for m in ms}) == 5
    # a swept seed is pinned: repetitions beyond the combos are clones
    dup = parse_fleet(f"base={BASE};axis=seed:1|2;clusters=4")
    assert dup[0].spec() == dup[2].spec()
    assert dup[1].spec() == dup[3].spec()


def test_cluster_overrides_and_backend_knob():
    ms = parse_fleet(f"base={BASE};axis=seed:1|2;backend=ref;"
                     "cluster=1:p_flap=0.5,backend=jax")
    assert [m.backend for m in ms] == ["ref", "jax"]
    assert ms[0].scenario.p_flap != 0.5
    assert ms[1].scenario.p_flap == 0.5
    # overrides pin the rendered spec string
    assert "p_flap=0.5" in ms[1].spec()


def test_parse_fleet_error_cases():
    # unregistered axis built dynamically: a bare `axis=flappers:`
    # literal here would itself trip the sweep-grammar reverse scan
    with pytest.raises(ValueError, match="unknown sweep axis"):
        parse_fleet(f"base={BASE};axis=flap" + "pers:1|2")
    with pytest.raises(ValueError, match="bad fleet directive"):
        parse_fleet("nonsense")
    with pytest.raises(ValueError, match="sweeps no values"):
        parse_fleet(f"base={BASE};axis=seed:|")
    with pytest.raises(ValueError, match="bad axis directive"):
        parse_fleet(f"base={BASE};axis=seed")
    with pytest.raises(ValueError, match="beyond the fleet size"):
        parse_fleet(f"base={BASE};cluster=7:seed=1")
    with pytest.raises(ValueError, match="neither a Scenario field"):
        parse_fleet(f"base={BASE};cluster=0:bogus_field=1")
    with pytest.raises(ValueError, match="want jax or ref"):
        parse_fleet(f"base={BASE};backend=gpu")
    with pytest.raises(ValueError, match="want >= 1"):
        parse_fleet(f"base={BASE};clusters=0")
    with pytest.raises(ValueError, match="no members"):
        FleetSim([])


# --------------------------------------------------------------- pareto


def _pt(i, cyrs, qps, lost, exp):
    return Point(index=i, spec=f"s{i}", values={
        "cluster_years_per_hour": cyrs, "served_qps": qps,
        "pg_lost": lost, "exposure": exp})


def test_dominates_needs_strict_improvement():
    a = _pt(0, 1.0, 100.0, 0, 0)
    b = _pt(1, 1.0, 100.0, 0, 0)
    assert not dominates(a.values, b.values)  # equal: no strict edge
    c = _pt(2, 1.0, 100.0, 1, 0)
    assert dominates(a.values, c.values)      # fewer PGs lost
    assert not dominates(c.values, a.values)
    d = _pt(3, 2.0, 50.0, 0, 0)               # trade-off: incomparable
    assert not dominates(a.values, d.values)
    assert not dominates(d.values, a.values)


def test_pareto_front_accounts_dominated_points():
    pts = [_pt(0, 2.0, 100.0, 0, 0),   # front
           _pt(1, 1.0, 50.0, 2, 10),   # dominated by 0
           _pt(2, 0.5, 200.0, 0, 0)]   # front (best qps)
    front, dominated = pareto_front(pts)
    assert [p.index for p in front] == [0, 2]
    assert [p.index for p in dominated] == [1]
    assert dominated[0].dominated_by == 0


def test_triage_table_renders_front_first():
    pts = [_pt(0, 1.0, 50.0, 2, 10), _pt(1, 2.0, 100.0, 0, 0)]
    pareto_front(pts)
    table = triage_table(pts)
    lines = table.splitlines()
    assert "beaten-by" in lines[0]
    assert lines[1].startswith("1")  # front member leads
    assert "front 1 / dominated 1 of 2 clusters" in table


def test_point_from_summary_reads_durability_ledger():
    p = Point.from_summary(3, "spec", {
        "pareto": {"cluster_years_per_hour": 1.5, "served_qps": 42.0},
        "durability": {"pg_lost": 2, "exposure_pg_epochs": 7},
    })
    assert p.values == {"cluster_years_per_hour": 1.5,
                       "served_qps": 42.0, "pg_lost": 2.0,
                       "exposure": 7.0}


# -------------------------------------------- stacked digest equivalence


def test_fleet_digests_match_solo_oracle():
    """The tentpole contract: every member of the stacked fleet —
    plain, balancer-driven, correlated, and the data-loss cluster —
    lands bit-identically on its solo oracle digest, steady epochs book
    0 compiles, and the DATA_LOSS latch survives stacking."""
    members = parse_fleet(DIGEST_SPEC)
    assert len(members) == 4
    assert members[2].scenario.correlated == 1
    solo = {}
    for m in members:
        solo[m.index] = _solo_digest(m)
        obs.health.reset()

    fleet = FleetSim(parse_fleet(DIGEST_SPEC))
    fleet.warm()
    out = fleet.run()
    assert out["clusters"] == 4
    for row in out["members"]:
        assert row["digest"] == solo[row["index"]], (
            f"cluster {row['index']} ({row['scenario'][:60]}...) "
            "diverged from its solo oracle")
        assert row["invariant_violations"] == 0
    # the loss member lost PGs and the latch survived the stacking
    assert out["members"][3]["pg_lost"] > 0
    chk = obs.health.checks().get("DATA_LOSS")
    assert chk and chk["severity"] == obs.health.ERR
    # trace-once: steady epochs booked zero compiles
    t = out["trace_once"]
    assert t["steady_compiles"] == 0
    assert t["structural_epochs"] + t["steady_epochs"] \
        == out["fleet_epochs"]
    # the front is never empty (a non-dominated point always exists)
    assert out["pareto"]["front_size"] >= 1
    assert out["pareto"]["front_size"] \
        + len(out["pareto"]["dominated"]) == 4


def test_fleet_unstacked_matches_stacked(monkeypatch):
    """CEPH_TPU_FLEET_STACK=0 solo-steps every member — same digests,
    no stacked dispatch (the knob is a debugging escape hatch, not a
    semantics switch)."""
    spec = f"base={BASE},epochs=5;axis=correlated:0|1"
    stacked = FleetSim(parse_fleet(spec))
    stacked.warm()
    a = stacked.run()
    monkeypatch.setenv("CEPH_TPU_FLEET_STACK", "0")
    solo = FleetSim(parse_fleet(spec))
    assert not solo.stack
    b = solo.run()
    assert [m["digest"] for m in a["members"]] \
        == [m["digest"] for m in b["members"]]


@pytest.mark.slow
def test_fleet_64_clusters_digest_equivalence():
    """Acceptance scale: a 64-cluster heterogeneous sweep (4 axes x 4
    seed repetitions) where EVERY stacked digest matches its solo
    oracle and steady epochs book 0 compiles."""
    spec = (f"base={BASE},epochs=5,seed=3;"
            "axis=correlated:0|1;axis=p_death:0.02|0.12;"
            "axis=recovery_mbps:100|400;axis=pgs:24|32;"
            "clusters=64")
    members = parse_fleet(spec)
    assert len({m.spec() for m in members}) == 64
    solo = {m.index: _solo_digest(m) for m in members}
    obs.health.reset()
    fleet = FleetSim(parse_fleet(spec))
    fleet.warm()
    out = fleet.run()
    mismatches = [r["index"] for r in out["members"]
                  if r["digest"] != solo[r["index"]]]
    assert mismatches == []
    assert out["trace_once"]["steady_compiles"] == 0
    assert out["cluster_epochs"] == 64 * 5


# ------------------------------------------------- checkpoint / resume


def test_fleet_checkpoint_resume_roundtrip(tmp_path):
    straight = FleetSim(parse_fleet(REF_SPEC)).run()
    ck = tmp_path / "fleet.json"
    a = FleetSim(parse_fleet(REF_SPEC), checkpoint=str(ck))
    a.run(stop_after=3)
    assert a.steps == 3
    b = FleetSim(parse_fleet(REF_SPEC), checkpoint=str(ck),
                 resume=True)
    assert b.resumed_from == 3
    out = b.run()
    assert out["resumed_from"] == 3
    assert [m["digest"] for m in out["members"]] \
        == [m["digest"] for m in straight["members"]]


def test_fleet_resume_refuses_count_mismatch(tmp_path):
    ck = tmp_path / "fleet.json"
    FleetSim(parse_fleet(REF_SPEC), checkpoint=str(ck)).run(
        stop_after=2)
    smaller = f"base={BASE},epochs=6;axis=seed:1|2;backend=ref"
    with pytest.raises(ValueError, match="cluster count"):
        FleetSim(parse_fleet(smaller), checkpoint=str(ck),
                 resume=True)


def test_fleet_resume_refuses_order_drift(tmp_path):
    ck = tmp_path / "fleet.json"
    FleetSim(parse_fleet(REF_SPEC), checkpoint=str(ck)).run(
        stop_after=2)
    reordered = parse_fleet(REF_SPEC)
    reordered[1], reordered[2] = reordered[2], reordered[1]
    with pytest.raises(ValueError) as ei:
        FleetSim(reordered, checkpoint=str(ck), resume=True)
    msg = str(ei.value)
    assert "cluster 1" in msg and "cluster 2" in msg
    assert "checkpoint" in msg and "requested" in msg


def test_fleet_resume_refuses_single_spec_drift(tmp_path):
    """Any one member's field drifting kills the resume with a
    per-cluster, per-field diff naming both values."""
    ck = tmp_path / "fleet.json"
    FleetSim(parse_fleet(REF_SPEC), checkpoint=str(ck)).run(
        stop_after=2)
    drifted = parse_fleet(REF_SPEC + ";cluster=2:recovery_mbps=50")
    with pytest.raises(ValueError) as ei:
        FleetSim(drifted, checkpoint=str(ck), resume=True)
    msg = str(ei.value)
    assert "cluster 2: recovery_mbps" in msg
    assert "'200.0'" in msg and "'50.0'" in msg
    assert "cluster 0" not in msg and "cluster 1" not in msg


def test_fleet_resume_refuses_backend_drift(tmp_path):
    ck = tmp_path / "fleet.json"
    FleetSim(parse_fleet(REF_SPEC), checkpoint=str(ck)).run(
        stop_after=2)
    drifted = parse_fleet(REF_SPEC)
    drifted[0].backend = "jax"
    with pytest.raises(ValueError, match="cluster 0: backend"):
        FleetSim(drifted, checkpoint=str(ck), resume=True)


def test_fleet_resume_needs_fleet_state(tmp_path):
    ck = tmp_path / "empty.json"
    with pytest.raises(ValueError, match="no fleet state"):
        FleetSim(parse_fleet(REF_SPEC), checkpoint=str(ck),
                 resume=True)


def test_fleet_fault_kill_mid_cascade_then_resume(tmp_path):
    """The registry-documented kill site at fleet scale: one member is
    mid-cascade (open hazard windows) when an armed `hazard_decay`
    fault kills the whole stack; the atomic checkpoint still holds the
    pre-decay strengths and the resumed fleet replays every member to
    the straight run's digests."""
    spec = (f"base={BASE},epochs=12,correlated=1,flappers=2,"
            "p_host_outage=0.3,p_rack_outage=0.1;"
            "axis=seed:11|12;backend=ref")
    straight = FleetSim(parse_fleet(spec)).run()

    # probe member 0 solo (same trajectory) for the first epoch with
    # open hazard windows — seeded, so deterministic
    probe_sc = parse_fleet(spec)[0].scenario
    probe = LifetimeSim(Scenario.parse(probe_sc.spec()), backend="ref")
    stop = None
    for e in range(1, probe_sc.epochs - 2):
        probe.step()
        if probe.hazards:
            stop = e
            break
    assert stop is not None, "scenario opened no hazard window"

    ck = tmp_path / "fleet.json"
    a = FleetSim(parse_fleet(spec), checkpoint=str(ck))
    a.run(stop_after=stop)
    assert a.engines[0].hazards, \
        "interrupt point lost its active hazard windows"
    faults.arm("hazard_decay", "fail", "mid-cascade fleet kill", 1)
    with pytest.raises(faults.FaultInjected):
        a.step()
    faults.disarm("hazard_decay")

    b = FleetSim(parse_fleet(spec), checkpoint=str(ck), resume=True)
    assert b.resumed_from == stop
    assert b.engines[0].hazards, \
        "checkpoint lost the active hazard windows"
    out = b.run()
    assert [m["digest"] for m in out["members"]] \
        == [m["digest"] for m in straight["members"]]


# ------------------------------------------------------------------ cli


def test_cli_run_smoke(capsys):
    rc = fleet_cli.main(["run", "--spec", REF_SPEC])
    out = capsys.readouterr().out
    assert rc == 0
    assert "clusters        4" in out
    assert "cluster-epochs/s" in out
    assert "steady compile(s)" in out
    assert "pareto" in out and "invariants      0 violation(s)" in out


def test_cli_run_json_parses(capsys):
    rc = fleet_cli.main(["run", "--spec", REF_SPEC, "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out)
    assert rec["clusters"] == 4
    assert len(rec["members"]) == 4
    assert rec["pareto"]["front_size"] >= 1
    for m in rec["members"]:
        assert m["digest"]


def test_cli_pareto_triage_table(capsys):
    rc = fleet_cli.main(["pareto", "--spec", REF_SPEC])
    out = capsys.readouterr().out
    assert rc == 0
    assert "beaten-by" in out
    assert "of 4 clusters" in out


def test_cli_digest_lines(capsys):
    rc = fleet_cli.main(["digest", "--spec", REF_SPEC])
    out = capsys.readouterr().out
    assert rc == 0
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert len(lines) == 4
    for i, ln in enumerate(lines):
        idx, digest = ln.split()
        assert int(idx) == i
        assert len(digest) >= 16


def test_cli_resume_requires_checkpoint(capsys):
    rc = fleet_cli.main(["run", "--resume"])
    assert rc == 2
    assert "--resume needs --checkpoint" in capsys.readouterr().err
