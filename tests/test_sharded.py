"""ShardedClusterMapper correctness on the virtual 8-device CPU mesh.

VERDICT r2 weak 3: the mesh path had zero pytest coverage.  These tests
pin: sharded == unsharded mapping results (the ParallelPGMapper shard
merge invariant, reference src/osd/OSDMapMapping.h:18-140 — shard
boundaries must not change results), uneven PG counts (padding rows),
multi-pool, psum-reduced histogram equality vs a host recount, and the
on-device rebalance_step against a host reimplementation.
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.pipeline_jax import PoolMapper
from ceph_tpu.osd.types import PgId, PgPool, PoolType
from ceph_tpu.parallel.sharded import ShardedClusterMapper, make_mesh


def hier(pg_num=96, n_host=4, per=4, pool=None, size=3):
    pool = pool or PgPool(
        type=PoolType.REPLICATED, size=size, crush_rule=0,
        pg_num=pg_num, pgp_num=pg_num,
    )
    return build_hierarchical(n_host, per, n_rack=2, pool=pool)


def trim(a, n):
    return np.asarray(a)[:n]


# tier-1 keeps one representative of each invariant; the remaining
# shard-count/pg-count combinations and the heavier multi-pool /
# rebalance variants run in the slow tier (tier-1 wall budget)
@pytest.mark.parametrize("n_dev, pg_num", [
    pytest.param(2, 96, marks=pytest.mark.slow),
    pytest.param(8, 96, marks=pytest.mark.slow),
    pytest.param(2, 101, marks=pytest.mark.slow),
    (8, 101),  # uneven shards, full mesh: the load-bearing combination
])
def test_sharded_equals_unsharded(n_dev, pg_num):
    m = hier(pg_num=pg_num)
    mesh = make_mesh(n_dev)
    scm = ShardedClusterMapper(m, 0, mesh)
    out = scm.map_stats()

    pm = PoolMapper(m, 0, overlays=False, path="loop")
    up, upp, acting, actp = pm.map_all()

    assert np.array_equal(trim(out["up"], pg_num), up)
    assert np.array_equal(trim(out["up_primary"], pg_num), upp)
    assert np.array_equal(trim(out["acting"], pg_num), acting)
    assert np.array_equal(trim(out["acting_primary"], pg_num), actp)


def test_histograms_match_host_recount():
    pg_num = 101
    m = hier(pg_num=pg_num)
    mesh = make_mesh(8)
    scm = ShardedClusterMapper(m, 0, mesh)
    out = scm.map_stats()
    acting = trim(out["acting"], pg_num)
    actp = trim(out["acting_primary"], pg_num)

    n = scm.DV
    hist = np.zeros(n, np.int64)
    phist = np.zeros(n, np.int64)
    fhist = np.zeros(n, np.int64)
    for row, p in zip(acting, actp):
        osds = [o for o in row if o != ITEM_NONE and o >= 0]
        for o in osds:
            hist[o] += 1
        if osds:
            fhist[osds[0]] += 1
        if p >= 0:
            phist[p] += 1
    assert np.array_equal(np.asarray(out["pgs_per_osd"]), hist)
    assert np.array_equal(np.asarray(out["primary_per_osd"]), phist)
    assert np.array_equal(np.asarray(out["first_per_osd"]), fhist)


def test_sharded_matches_host_oracle_rows():
    """Spot-check rows against the pure-python oracle (ties the mesh path
    to OSDMap.pg_to_up_acting_osds semantics)."""
    pg_num = 64
    m = hier(pg_num=pg_num)
    scm = ShardedClusterMapper(m, 0, make_mesh(4))
    out = scm.map_stats()
    acting = trim(out["acting"], pg_num)
    actp = trim(out["acting_primary"], pg_num)
    for ps in range(0, pg_num, 7):
        _, _, a, ap = m.pg_to_up_acting_osds(PgId(0, ps))
        w = acting.shape[1]
        assert list(acting[ps]) == list(a) + [ITEM_NONE] * (w - len(a)), ps
        assert int(actp[ps]) == ap, ps


@pytest.mark.slow
def test_multi_pool():
    """Two pools with different shapes map independently on one mesh."""
    m = hier(pg_num=64)
    p2 = PgPool(type=PoolType.REPLICATED, size=2, crush_rule=0,
                pg_num=33, pgp_num=33)
    m.add_pool("small", p2)
    mesh = make_mesh(8)
    for pid, pool in m.pools.items():
        scm = ShardedClusterMapper(m, pid, mesh)
        out = scm.map_stats()
        acting = trim(out["acting"], pool.pg_num)
        assert int(np.asarray(out["pgs_per_osd"]).sum()) == sum(
            len([o for o in row if o != ITEM_NONE]) for row in acting
        )
        pm = PoolMapper(m, pid, overlays=False, path="loop")
        _, _, a2, _ = pm.map_all()
        assert np.array_equal(acting, a2)


@pytest.mark.slow
def test_rebalance_step_matches_host():
    """rebalance_step's histogram == host recount; its weight update
    follows the documented clipped multiplicative rule."""
    pg_num = 128
    m = hier(pg_num=pg_num)
    scm = ShardedClusterMapper(m, 0, make_mesh(8))
    new_w, stddev, hist = scm.rebalance_step()
    hist = np.asarray(hist)

    out = scm.map_stats()
    assert np.array_equal(hist, np.asarray(out["pgs_per_osd"]))

    w = np.asarray(scm.pm.dev["weight"]).astype(np.float64)
    R = scm.pm.spec.size
    target = pg_num * R * w / max(w.sum(), 1.0)  # target_w == w here
    ratio = np.clip(target / np.maximum(hist.astype(np.float64), 1.0),
                    0.5, 2.0)
    expect = np.where((w > 0) & (target > 0),
                      np.clip(w * ratio, 1.0, None), w).astype(np.uint32)
    assert np.array_equal(np.asarray(new_w), expect)
    n_in = int((w > 0).sum())
    expect_sd = np.sqrt(((hist - target) ** 2).sum() / max(n_in, 1))
    assert abs(float(stddev) - expect_sd) < 1e-3 * max(expect_sd, 1.0)


@pytest.mark.slow
def test_rebalance_step_converges_toward_uniform():
    """Feeding updated weights back reduces placement stddev on a
    weight-skewed cluster (one on-device balancer iteration works)."""
    rng = np.random.default_rng(7)

    def wf(_):
        return int(rng.integers(1, 4) * 0x10000)

    m = build_hierarchical(4, 4, n_rack=2, weight_fn=wf, pool=PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=256, pgp_num=256,
    ))
    scm = ShardedClusterMapper(m, 0, make_mesh(8))
    w0 = np.asarray(scm.pm.dev["weight"])
    _, sd0, _ = scm.rebalance_step(w0)
    w = w0
    sd = float(sd0)
    for _ in range(3):
        w, sd_new, _ = scm.rebalance_step(w)
        w = np.asarray(w)
        sd = float(sd_new)
    assert sd <= float(sd0) * 1.05  # not diverging; usually improves
