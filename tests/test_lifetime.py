"""Cluster-lifetime chaos simulator: deterministic trajectories, real
Incremental chains, device-side accounting, invariants, device-loss
degradation, and checkpoint/resume (ceph_tpu.sim.lifetime).

Tier-1 keeps the scenarios tiny (tens of epochs, <=48 PGs per pool);
the >=500-epoch at-scale run and the subprocess kill+--resume CLI test
are slow-marked (tier-1 budget is tight).  The host ("ref") backend
runs the same accounting formulas in numpy, so most determinism checks
avoid jax compiles entirely.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgId, PgPool, PoolType
from ceph_tpu.runtime import faults
from ceph_tpu.sim.failure import ClusterSim, MovementReport
from ceph_tpu.sim.lifetime import (
    LifetimeSim,
    Scenario,
    check_pg_temp_invariants,
    check_rows_invariants,
)

REPO = Path(__file__).resolve().parents[1]

# tiny but complete: replicated + EC pool, every event class reachable
TINY = ("epochs=12,seed=5,hosts=6,osds_per_host=2,racks=2,pgs=32,"
        "ec=2+2,ec_pgs=16,chunk=256,balance_every=6,spotcheck_every=4,"
        "checkpoint_every=0")


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm_all()


# ---------------------------------------------------------------- scenario


def test_scenario_parse_and_spec_roundtrip():
    sc = Scenario.parse("epochs=42,seed=9,ec=4+2,p_flap=0.5,"
                        "recovery_mbps=250")
    assert sc.epochs == 42 and sc.seed == 9
    assert sc.ec_km() == (4, 2)
    assert sc.p_flap == 0.5 and sc.recovery_mbps == 250.0
    again = Scenario.parse(sc.spec())
    assert again == sc


def test_scenario_rejects_unknown_key():
    with pytest.raises(ValueError, match="bad scenario item"):
        Scenario.parse("epochs=5,bogus=1")


# ------------------------------------------------------------ determinism


def test_same_seed_same_digest_host_backend():
    a = LifetimeSim(Scenario.parse(TINY), backend="ref").run()
    b = LifetimeSim(Scenario.parse(TINY), backend="ref").run()
    assert a["digest"] == b["digest"]
    assert a["events"] == b["events"]
    assert a["invariant_violations"] == 0
    # a different seed must diverge
    c = LifetimeSim(Scenario.parse(TINY + ",seed=6"),
                    backend="ref").run()
    assert c["digest"] != a["digest"]


def test_event_mix_applies_real_incremental_chain():
    """Forced events drive one of each structural change through a real
    Incremental chain; the map reflects them and invariants hold."""
    sc = Scenario.parse(TINY + ",balance_every=0,epochs=30")
    sim = LifetimeSim(sc, backend="ref")
    e0 = sim.m.epoch
    osds0 = sim.m.max_osd
    pools0 = len(sim.m.pools)

    sim.step(force_event="death")
    dead = sim.dead[0]
    assert sim.m.is_down(dead) and sim.m.is_out(dead)
    sim.step(force_event="remove")
    assert not sim.m.exists(dead)
    assert dead not in sim.m.crush.item_names

    sim.step(force_event="expand")
    assert sim.m.max_osd == osds0 + sc.osds_per_host
    assert f"host{sc.hosts}" in sim.m.crush.item_names.values()
    assert sim.m.is_up(osds0)  # first new osd came up in

    total_pgs0 = sum(p.pg_num for p in sim.m.pools.values())
    sim.step(force_event="split")
    assert sum(p.pg_num for p in sim.m.pools.values()) > total_pgs0
    sim.step(force_event="pool_create")
    assert len(sim.m.pools) == pools0 + 1

    sim.step(force_event="pg_temp")
    assert sim.m.pg_temp  # override landed in the map
    assert check_pg_temp_invariants(sim.m) == []

    sim.step(force_event="host_outage")
    sim.step(force_event="reweight")
    sim.step(force_event="flap")

    # the epoch chain advanced once per step (no balancer here)
    assert sim.m.epoch == e0 + 9
    assert sim.steps == 9
    assert sim.violations == []
    # shape-changing events (split, pool_create) classify structural
    # even on the host backend; steady epochs stay compile-free
    assert sim.structural_epochs >= 2
    assert sim.steady_compiles == 0


def test_movement_report_merge_at_risk_fields():
    a = MovementReport(total_pgs=10, pgs_remapped=2, replicas_moved=3,
                       degraded_pgs=4, pgs_at_risk=1,
                       at_risk_pg_seconds=30.0)
    b = MovementReport(total_pgs=10, pgs_remapped=3, replicas_moved=1,
                       degraded_pgs=0, pgs_at_risk=2,
                       at_risk_pg_seconds=45.5)
    a.merge(b)
    assert a.total_pgs == 20
    assert a.pgs_at_risk == 3
    assert a.at_risk_pg_seconds == 75.5
    assert a.moved_fraction == 5 / 20


def test_risk_model_integrates_at_risk_window():
    """Downing more chunks than the EC pool tolerates (flaps: down but
    NOT out, so CRUSH does not remap around them) must open a
    data-at-risk window integrated over the epoch's simulated time."""
    sc = Scenario.parse(
        "epochs=14,seed=1,hosts=8,osds_per_host=2,racks=2,pgs=16,"
        "ec=2+1,ec_pgs=16,chunk=64,balance_every=0,spotcheck_every=0,"
        "checkpoint_every=0,interval_s=10,flap_len=30")
    sim = LifetimeSim(sc, backend="ref")
    for _ in range(12):  # flap OSDs until some PG loses 2 of 3 chunks
        sim.step(force_event="flap")
        if sim.report.pgs_at_risk:
            break
    assert sim.report.pgs_at_risk > 0
    assert sim.report.at_risk_pg_seconds >= 10.0  # >= floor duration
    assert sim.degraded_epochs >= 1


# -------------------------------------------------------------- invariants


def _tiny_map():
    return build_hierarchical(4, 2, n_rack=2, pool=PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=16, pgp_num=16))


def test_invariant_negative_control_duplicate_and_upmap():
    """The checker must catch seeded violations: a duplicated OSD in a
    row, and an ignored pg_upmap_items entry."""
    m = _tiny_map()
    rows = np.stack([
        np.asarray(m.pg_to_up_acting_osds(PgId(0, s))[0], np.int32)
        for s in range(16)
    ])
    assert check_rows_invariants(m, 0, rows, 16) == []  # clean control

    bad = rows.copy()
    bad[3, 1] = bad[3, 0]  # duplicate OSD
    msgs = check_rows_invariants(m, 0, bad, 16)
    assert any("duplicate" in v for v in msgs)

    frm = int(rows[5, 0])
    to = next(o for o in range(m.max_osd)
              if o not in rows[5] and m.is_up(o))
    m.pg_upmap_items[PgId(0, 5)] = [(frm, to)]
    msgs = check_rows_invariants(m, 0, rows, 16)  # rows ignore the upmap
    assert any("not respected" in v for v in msgs)


def test_invariant_negative_control_through_engine():
    """A corrupted host-path row must surface as an engine violation
    (the sim's own checker catches it, books the counter, and keeps
    running)."""
    sc = Scenario.parse(TINY + ",balance_every=0,epochs=3,"
                        "spotcheck_every=0")
    sim = LifetimeSim(sc, backend="ref")

    def corrupt(pid, rows):
        if pid == 0:
            rows = rows.copy()
            rows[1, 1] = rows[1, 0]  # duplicate OSD in pg 0.1
        return rows

    sim.corrupt_hook = corrupt
    out = sim.run()
    assert out["epochs"] == 3  # survived, did not abort
    assert out["invariant_violations"] > 0
    assert any("duplicate" in v for v in out["violations"])


def test_pg_temp_invariant_checker():
    m = _tiny_map()
    up, _, _, _ = m.pg_to_up_acting_osds(PgId(0, 2))
    m.pg_temp[PgId(0, 2)] = up[1:] + up[:1]
    m.primary_temp[PgId(0, 2)] = up[1]
    assert check_pg_temp_invariants(m) == []  # the model honors both
    # an entry whose members all died is skipped (acting falls back)
    for o in up:
        m.mark_down(o)
    assert check_pg_temp_invariants(m) == []


# -------------------------------------------------- jax backend + resume


def test_jax_digest_device_loss_and_resume(tmp_path):
    """One compile-amortized jax pass proving four contracts: (a) jax
    and host backends produce identical trajectory digests; (b) an
    injected mid-run device loss degrades that epoch to the host mapper
    (provenance recorded) with the digest UNCHANGED; (c) steady epochs
    book 0 compiles; (d) an interrupted run resumed from its checkpoint
    lands on the same final digest."""
    sc = Scenario.parse(TINY)
    ref = LifetimeSim(sc, backend="ref").run()

    # (a)+(b): device loss at epoch 6 (first pool of that epoch)
    faults.configure("epoch_apply.6=lost:chaos x1")
    sim = LifetimeSim(sc, backend="jax")
    out = sim.run()
    faults.disarm_all()
    assert out["digest"] == ref["digest"]
    assert out["provenance"]["device_loss_fallbacks"] == 1
    assert "epoch 6" in out["provenance"]["fallback_events"][0]
    assert out["invariant_violations"] == 0
    # (c)
    assert out["trace_once"]["steady_compiles"] == 0
    assert out["trace_once"]["steady_pipe_misses"] == 0

    # (d): interrupt at epoch 7, resume, same digest (warm kernels)
    ck = tmp_path / "ck.json"
    LifetimeSim(sc, backend="jax", checkpoint=str(ck)).run(stop_after=7)
    resumed = LifetimeSim(sc, backend="jax", checkpoint=str(ck),
                          resume=True)
    assert resumed.resumed_from == 7
    out2 = resumed.run()
    assert out2["digest"] == ref["digest"]
    assert out2["epochs"] == sc.epochs


def test_cli_resume_adopts_checkpoint_scenario(tmp_path, capsys):
    """`--resume` without `--scenario` (the README flow) must adopt the
    checkpoint's pinned scenario instead of crashing on the
    different-scenario guard with defaults."""
    from ceph_tpu.cli import sim as cli_sim

    spec = TINY + ",balance_every=0,epochs=6,spotcheck_every=0"
    ck = tmp_path / "ck.json"
    rc = cli_sim.main(["digest", "--scenario", spec, "--backend", "ref",
                       "--checkpoint", str(ck), "--stop-after", "4"])
    assert rc == 0
    capsys.readouterr()
    rc = cli_sim.main(["digest", "--backend", "ref",
                       "--checkpoint", str(ck), "--resume"])
    assert rc == 0
    resumed = capsys.readouterr().out.strip()
    straight = LifetimeSim(Scenario.parse(spec), backend="ref").run()
    assert resumed == straight["digest"]


def test_resume_rejects_different_scenario(tmp_path):
    ck = tmp_path / "ck.json"
    sc = Scenario.parse(TINY + ",balance_every=0,epochs=2,"
                        "spotcheck_every=0")
    LifetimeSim(sc, backend="ref", checkpoint=str(ck)).run()
    other = Scenario.parse(TINY + ",balance_every=0,epochs=2,"
                           "spotcheck_every=0,seed=99")
    with pytest.raises(ValueError, match="different scenario"):
        LifetimeSim(other, backend="ref", checkpoint=str(ck),
                    resume=True)


@pytest.mark.slow
def test_kill_and_cli_resume_digest_identical(tmp_path):
    """The real kill: an armed `lifetime_step.8=exit:9` dies mid-run
    (os._exit, SIGKILL-grade); `--resume` continues from the last
    checkpoint to the exact digest an uninterrupted run prints."""
    spec = (TINY + ",balance_every=0,epochs=14,checkpoint_every=4,"
            "spotcheck_every=0")
    ck = tmp_path / "ck.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("CEPH_TPU_FAULTS", None)

    r = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.cli.sim", "run",
         "--scenario", spec, "--backend", "ref",
         "--checkpoint", str(ck)],
        env={**env, "CEPH_TPU_FAULTS": "lifetime_step.8=exit:9"},
        capture_output=True, text=True, timeout=240, cwd=REPO,
    )
    assert r.returncode == 9  # died mid-run, as armed
    assert json.loads(ck.read_text())["lifetime"]["steps"] == 4

    r2 = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.cli.sim", "digest",
         "--scenario", spec, "--backend", "ref",
         "--checkpoint", str(ck), "--resume"],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO,
    )
    assert r2.returncode == 0, r2.stderr[-500:]
    straight = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.cli.sim", "digest",
         "--scenario", spec, "--backend", "ref"],
        env=env, capture_output=True, text=True, timeout=240, cwd=REPO,
    )
    assert r2.stdout.strip() == straight.stdout.strip()


@pytest.mark.slow
def test_lifetime_at_scale_500_epochs():
    """The acceptance-shaped run: >=500 epochs on the jax backend with
    the full chaos mix, 0 invariant violations, 0 steady compiles."""
    sc = Scenario.parse(
        "epochs=500,seed=11,hosts=6,osds_per_host=2,racks=2,pgs=64,"
        "ec=2+2,ec_pgs=32,chunk=512,balance_every=64,"
        "spotcheck_every=32,checkpoint_every=0,"
        # growth caps keep the run minutes- not hours-scale on a
        # throttled container (uncapped splits walk pg_num to 4096)
        "max_pools=3,max_pgs=128,max_expand=2")
    out = LifetimeSim(sc, backend="jax").run()
    assert out["epochs"] == 500
    assert out["invariant_violations"] == 0, out["violations"][:5]
    assert out["trace_once"]["steady_compiles"] == 0
    assert out["trace_once"]["steady_pipe_misses"] == 0
    assert out["epochs_per_sec"] > 0
    assert out["cluster_years_per_hour"] > 0
    # chaos actually happened
    assert sum(v for k, v in out["events"].items()
               if k not in ("quiet", "balance")) > 100


# ------------------------------------------------------- thrasher floor


def test_thrash_floor_derives_from_largest_pool():
    """Regression: the thrasher's up-OSD floor must come from the
    largest pool's size (EC k+m), not the old hardcoded 3 — an EC pool
    of size 6 on 8 OSDs may never be thrashed below 6 up OSDs."""
    m = build_hierarchical(8, 1, n_rack=2, pool=PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=16, pgp_num=16))
    root = next(bid for bid, b in m.crush.buckets.items()
                if b.type == 11)
    ruleno = m.crush.make_erasure_rule(root, 1, num_chunks=6)
    m.add_pool("wide-ec", PgPool(
        type=PoolType.ERASURE, size=6, min_size=5, crush_rule=ruleno,
        pg_num=8, pgp_num=8))

    class Probe(ClusterSim):
        min_up = 10 ** 9

        def _step(self, label):
            rep = super()._step(label)
            ups = sum(1 for o in range(self.m.max_osd)
                      if self.m.is_up(o))
            self.min_up = min(self.min_up, ups)
            return rep

    sim = Probe(m, backend="ref")
    sim.thrash(16, rng=np.random.default_rng(7), p_fail=0.9)
    # old code would have thrashed down to 4 up OSDs (> 3 floor)
    assert sim.min_up >= 6
