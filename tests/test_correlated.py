"""Correlated-failure chaos engine: repeat-offender flappers, cascading
failure-domain hazards, false-flap revives vs true deaths, and the
per-PG dead-chunk durability ledger (sim/lifetime.py `correlated=1`).

Tier-1 keeps every scenario tiny and on the host ("ref") backend; the
acceptance-scale 510-epoch run lives in `bench.py --selftest`.  The
quiet-probability overrides (`_QUIET`) zero every event class so a
forced event's aftermath replays deterministically with no chance
chaos on top.
"""

from __future__ import annotations

from dataclasses import fields

import numpy as np
import pytest

from ceph_tpu import obs
from ceph_tpu.runtime import faults
from ceph_tpu.sim.lifetime import (
    EVENT_KINDS,
    LifetimeSim,
    Scenario,
)

# small but complete: EC 2+1 (tolerance 1) + replicated pool, queue
# recovery with a pipe fast enough that a lone wound heals in a few
# epochs, both correlated layers on
CORR = ("epochs=16,seed=11,hosts=4,osds_per_host=3,racks=2,pgs=32,"
        "ec=2+1,ec_pgs=16,chunk=256,balance_every=0,spotcheck_every=0,"
        "checkpoint_every=0,recovery=queue,max_backfills=4,"
        "recovery_mbps=200,osd_mbps=400,correlated=1,flappers=2")

# zero every event probability: forced events only, quiet aftermath
_QUIET = (",p_flap=0,p_death=0,p_remove=0,p_host_outage=0,"
          "p_rack_outage=0,p_reweight=0,p_pg_temp=0,p_pool_create=0,"
          "p_split=0,p_expand=0")


@pytest.fixture(autouse=True)
def _clean():
    obs.health.reset()
    yield
    faults.disarm_all()
    obs.health.reset()


# ------------------------------------------------------ scenario grammar


def test_scenario_spec_covers_every_field():
    """Drift guard: spec() must render EVERY Scenario field (a field
    missing from spec() would silently unpin it from the checkpoint's
    same-scenario guard and from the README grammar table)."""
    sc = Scenario.parse(None)
    items = sc.spec().split(",")
    for f in fields(Scenario):
        assert f"{f.name}={getattr(sc, f.name)}" in items, f.name


def test_readme_grammar_table_covers_every_field():
    """The README scenario-grammar table documents every field as a
    `| `key` | ... |` row — same convention the knob table test pins."""
    import pathlib

    readme = (pathlib.Path(__file__).resolve().parents[1]
              / "README.md").read_text()
    for f in fields(Scenario):
        assert f"| `{f.name}` |" in readme, (
            f"{f.name} missing from README scenario-grammar table")


def test_scenario_correlated_block_roundtrips():
    sc = Scenario.parse(
        "correlated=1,flappers=3,flapper_boost=2.5,cascade_hazard=0.5,"
        "cascade_decay=0.9,cascade_len=4")
    assert sc.correlated == 1 and sc.flappers == 3
    assert sc.cascade_decay == 0.9
    assert Scenario.parse(sc.spec()) == sc


def test_event_kinds_match_event_probs():
    """Both directions of the vocabulary contract (the static mirror of
    graftlint's scenario-event pass)."""
    kinds = [k for k, _ in Scenario().event_probs()]
    assert len(kinds) == len(set(kinds))
    assert sorted(kinds) == sorted(EVENT_KINDS)


# --------------------------------------------------------- determinism


def test_correlated_digest_deterministic_and_regime_segregated():
    a = LifetimeSim(Scenario.parse(CORR), backend="ref").run()
    b = LifetimeSim(Scenario.parse(CORR), backend="ref").run()
    assert a["digest"] == b["digest"]
    assert a["invariant_violations"] == 0
    assert "chaos" in a and "durability" in a
    # the legacy regime must not share digests with the correlated one
    legacy = LifetimeSim(Scenario.parse(CORR + ",correlated=0"),
                         backend="ref").run()
    assert legacy["digest"] != a["digest"]
    assert "chaos" not in legacy and "durability" not in legacy


# ------------------------------------------- flappers / hazards / revive


def test_flappers_drawn_once_per_lifetime():
    """The repeat-offender draw is a pure function of the scenario —
    two engines agree, and the draw never exceeds the initial OSD
    count."""
    a = LifetimeSim(Scenario.parse(CORR), backend="ref")
    b = LifetimeSim(Scenario.parse(CORR), backend="ref")
    assert a.flapper_osds == b.flapper_osds
    assert len(a.flapper_osds) == 2
    assert all(0 <= o < 12 for o in a.flapper_osds)
    # legacy regime draws no offenders
    c = LifetimeSim(Scenario.parse(CORR + ",correlated=0"),
                    backend="ref")
    assert c.flapper_osds == []


def test_rack_outage_opens_decaying_hazard_windows():
    sc = Scenario.parse(CORR + _QUIET)
    sim = LifetimeSim(sc, backend="ref")
    sim.step(force_event="rack_outage")
    assert sim.hazard_windows >= 1
    assert sim.hazards, "rack outage opened no sibling hazard window"
    assert any(k.startswith("rack") for k in sim.domain_outages)
    before = {(h[0], h[1], h[2]): h[3] for h in sim.hazards}
    sim.step()  # quiet epoch: strengths decay, nothing new opens
    after = {(h[0], h[1], h[2]): h[3] for h in sim.hazards}
    for key, s1 in after.items():
        s0 = before[key]
        assert s1 == pytest.approx(s0 * sc.cascade_decay, rel=1e-9)
    # windows expire after cascade_len epochs
    for _ in range(sc.cascade_len + 1):
        sim.step()
    assert sim.hazards == []
    assert sim.violations == []


def test_false_flap_revive_keeps_bytes_intact():
    """A flap is a false-positive down-mark: the OSD revives with its
    bytes, the revive is counted, and the durability ledger never
    records a dead chunk for it."""
    sc = Scenario.parse(CORR + _QUIET + ",flap_len=2,epochs=12")
    sim = LifetimeSim(sc, backend="ref")
    sim.step(force_event="flap")
    for _ in range(sc.flap_len + 2):
        sim.step()
    assert sim.false_flap_revives >= 1
    assert all((w == 0).all() for w in sim.wounded.values())
    assert sim.pg_lost_total == 0
    assert sim.violations == []


# ------------------------------------------------------------ durability


def test_true_death_wounds_then_recovery_heals():
    """A real death wounds every PG that carried the OSD; the recovery
    queue drains the re-replication and the wounds heal — exposure was
    recorded, nothing was lost.  The pipe is slowed so the wound
    survives at least one epoch (the fast default heals inside the
    death epoch and records no exposure)."""
    sc = Scenario.parse(CORR + _QUIET + ",epochs=30,max_backfills=1,"
                        "recovery_mbps=20,osd_mbps=40")
    sim = LifetimeSim(sc, backend="ref")
    sim.step(force_event="death")
    for _ in range(12):
        if all((w == 0).all() for w in sim.wounded.values()):
            break
        sim.step()
    assert all((w == 0).all() for w in sim.wounded.values()), \
        "wounds never healed on a fast recovery pipe"
    assert sim.exposed_pg_epochs > 0, "no exposure recorded for a death"
    assert sim.pg_lost_total == 0
    assert sim.violations == []


def test_overwhelming_death_rate_loses_pgs_and_latches_data_loss():
    """The loss path: a starved pipe under a brutal death rate stacks
    dead chunks past EC tolerance — pg_lost fires, DATA_LOSS latches at
    HEALTH_ERR, and a later all-clear evaluate() does NOT clear it
    (data loss is not a condition that heals; only an explicit
    operator clear() acknowledges it)."""
    sc = Scenario.parse(
        "epochs=14,hosts=3,osds_per_host=2,racks=1,pgs=16,ec=2+1,"
        "ec_pgs=8,chunk=64,seed=7,p_death=0.25,p_flap=0.05,"
        "p_host_outage=0.10,p_reweight=0,p_pg_temp=0,p_pool_create=0,"
        "p_split=0,p_expand=0,p_remove=0.02,balance_every=0,"
        "spotcheck_every=0,checkpoint_every=0,recovery=queue,"
        "max_backfills=1,recovery_mbps=2,osd_mbps=4,correlated=1,"
        "flappers=1")
    out = LifetimeSim(sc, backend="ref").run()
    assert out["durability"]["pg_lost"] > 0
    assert out["durability"]["lost"], "lost map empty with pg_lost > 0"
    chk = obs.health.checks().get("DATA_LOSS")
    assert chk and chk["severity"] == obs.health.ERR
    # standard evaluation may clear its own codes, never the latch
    obs.health.evaluate()
    assert "DATA_LOSS" in obs.health.checks()
    assert obs.health.status() == obs.health.ERR
    obs.health.clear("DATA_LOSS")  # the explicit operator ack
    assert "DATA_LOSS" not in obs.health.checks()


def test_lost_pgs_never_unlose_on_later_heal():
    """`lost` is irreversible: once a PG's dead chunks exceeded
    tolerance, a later drained backlog must not shrink pg_lost."""
    sc = Scenario.parse(
        "epochs=14,hosts=3,osds_per_host=2,racks=1,pgs=16,ec=2+1,"
        "ec_pgs=8,chunk=64,seed=7,p_death=0.25,p_flap=0.05,"
        "p_host_outage=0.10,p_reweight=0,p_pg_temp=0,p_pool_create=0,"
        "p_split=0,p_expand=0,p_remove=0.02,balance_every=0,"
        "spotcheck_every=0,checkpoint_every=0,recovery=queue,"
        "max_backfills=1,recovery_mbps=2,osd_mbps=4,correlated=1,"
        "flappers=1")
    sim = LifetimeSim(sc, backend="ref")
    peak = 0
    for _ in range(sc.epochs):
        sim.step()
        assert sim.pg_lost_total >= peak
        peak = max(peak, sim.pg_lost_total)
    assert peak > 0


# ------------------------------------------------------ resume contracts


def test_resume_mid_cascade_pins_hazard_state(tmp_path):
    """Kill during an active outage window: the checkpoint carries the
    decayed hazard strengths (path-dependent state — recomputing them
    would fork the trajectory), and the resumed run lands on the
    straight run's digest."""
    sc = Scenario.parse(CORR + ",epochs=14,checkpoint_every=2,"
                        "p_host_outage=0.3,p_rack_outage=0.1")
    straight = LifetimeSim(Scenario.parse(sc.spec()),
                           backend="ref").run()

    # find the first epoch (seeded, so deterministic) with open windows
    probe = LifetimeSim(Scenario.parse(sc.spec()), backend="ref")
    stop = None
    for e in range(1, sc.epochs - 2):
        probe.step()
        if probe.hazards:
            stop = e
            break
    assert stop is not None, "scenario opened no hazard window"

    ck = tmp_path / "ck.json"
    a = LifetimeSim(Scenario.parse(sc.spec()), backend="ref",
                    checkpoint=str(ck))
    a.run(stop_after=stop)
    haz = [list(h) for h in a.hazards]
    assert haz, "interrupt point lost its active hazard windows"

    b = LifetimeSim(Scenario.parse(sc.spec()), backend="ref",
                    checkpoint=str(ck), resume=True)
    assert b.resumed_from == stop
    assert [list(h) for h in b.hazards] == haz
    out = b.run()
    assert out["digest"] == straight["digest"]


def test_fault_kill_in_hazard_decay_then_resume(tmp_path):
    """The registry-documented kill site: an armed `hazard_decay.<e>`
    fault dies before that epoch's windows advance, so the checkpoint
    still holds the pre-decay strengths; the resume replays the decay
    curve to the straight run's digest."""
    sc = Scenario.parse(CORR + ",epochs=14,checkpoint_every=1,"
                        "p_host_outage=0.3,p_rack_outage=0.1")
    straight = LifetimeSim(Scenario.parse(sc.spec()),
                           backend="ref").run()

    probe = LifetimeSim(Scenario.parse(sc.spec()), backend="ref")
    stop = None
    for e in range(1, sc.epochs - 2):
        probe.step()
        if probe.hazards:
            stop = e
            break
    assert stop is not None, "scenario opened no hazard window"

    ck = tmp_path / "ck.json"
    a = LifetimeSim(Scenario.parse(sc.spec()), backend="ref",
                    checkpoint=str(ck))
    a.run(stop_after=stop)  # checkpoints at the interrupt epoch
    faults.arm("hazard_decay", "fail", "mid-cascade kill", 1)
    with pytest.raises(faults.FaultInjected):
        a.step()
    faults.disarm("hazard_decay")

    b = LifetimeSim(Scenario.parse(sc.spec()), backend="ref",
                    checkpoint=str(ck), resume=True)
    assert b.resumed_from == stop
    assert b.hazards, "checkpoint lost the active hazard windows"
    out = b.run()
    assert out["digest"] == straight["digest"]


def test_resume_mid_wound_pins_durability_ledger(tmp_path):
    """Kill while a PG is wounded: the wound counts, heal flags, and
    exposure totals ride the checkpoint and the resumed digest matches
    (the |D/|L segments replay bit-identically)."""
    sc = Scenario.parse(CORR + _QUIET
                        + ",epochs=12,checkpoint_every=1,"
                        "max_backfills=1,recovery_mbps=5,osd_mbps=10")
    straight_sim = LifetimeSim(Scenario.parse(sc.spec()), backend="ref")
    straight_sim.step(force_event="death")
    for _ in range(sc.epochs - 1):
        straight_sim.step()
    straight = straight_sim.digest

    ck = tmp_path / "ck.json"
    a = LifetimeSim(Scenario.parse(sc.spec()), backend="ref",
                    checkpoint=str(ck))
    a.step(force_event="death")
    a.step()
    a._checkpoint()
    assert any((w > 0).any() for w in a.wounded.values()), \
        "interrupt point carries no open wound (slow the pipe more)"

    b = LifetimeSim(Scenario.parse(sc.spec()), backend="ref",
                    checkpoint=str(ck), resume=True)
    for pid, w in a.wounded.items():
        assert (b.wounded[pid] == w).all()
    assert b.exposed_pg_epochs == a.exposed_pg_epochs
    for _ in range(sc.epochs - 2):
        b.step()
    assert b.digest == straight


# ----------------------------------------------------------- cli summary


def test_cli_prints_chaos_and_durability_triage(capsys):
    from ceph_tpu.cli import sim as cli_sim

    rc = cli_sim.main(["run", "--scenario", CORR + ",epochs=8",
                       "--backend", "ref"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "chaos" in out and "cascade(s)" in out
    assert "durability" in out and "pg_lost" in out
