"""Native C++ component tests: GF SIMD kernels vs the numpy tables, and the
threaded batch CRUSH mapper vs the Python semantic oracle."""

import numpy as np
import pytest

from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.types import BucketAlg, Tunables
from util_maps import build_flat, build_tree


def _gf_lib():
    from ceph_tpu.native import load_gf

    lib = load_gf()
    if lib is None:
        pytest.skip("no C++ toolchain for native GF library")
    return lib


def _native_mapper():
    from ceph_tpu.native import mapper

    if not mapper.available():
        pytest.skip("no C++ toolchain for native crush library")
    return mapper


class TestNativeGF:
    def test_matvec_matches_numpy(self, rng):
        _gf_lib()
        from ceph_tpu.ec.matrices import vandermonde_rs
        from ceph_tpu.ec.rs import NativeEngine, NumpyEngine

        M = vandermonde_rs(8, 4)
        data = rng.integers(0, 256, (8, 100_000)).astype(np.uint8)
        want = NumpyEngine().matmul(M, data)
        got = NativeEngine().matmul(M, data)
        assert np.array_equal(want, got)

    def test_mul_region(self, rng):
        import ctypes

        lib = _gf_lib()
        from ceph_tpu.ec.gf import GF_MUL_TABLE

        u8p = ctypes.POINTER(ctypes.c_uint8)
        src = rng.integers(0, 256, 1000).astype(np.uint8)
        dst = np.zeros(1000, np.uint8)
        for c in (0, 1, 2, 0x53, 255):
            lib.gf_native_mul_region(
                c, src.ctypes.data_as(u8p), dst.ctypes.data_as(u8p),
                1000, 0,
            )
            assert np.array_equal(dst, GF_MUL_TABLE[c, src]), c

    def test_native_plugin_roundtrip(self, rng):
        _gf_lib()
        from ceph_tpu.ec import create_erasure_code

        code = create_erasure_code(
            {"plugin": "jerasure", "k": 5, "m": 3, "backend": "native"}
        )
        data = rng.integers(0, 256, 4000).astype(np.uint8).tobytes()
        enc = code.encode(set(range(8)), data)
        del enc[0], enc[4], enc[7]
        assert code.decode_concat(enc)[:4000] == data


class TestNativeCrush:
    @pytest.mark.parametrize(
        "alg", [BucketAlg.STRAW2, BucketAlg.STRAW, BucketAlg.LIST,
                BucketAlg.TREE, BucketAlg.UNIFORM]
    )
    def test_flat_map_matches_ref(self, alg, rng):
        mapper = _native_mapper()
        m, root = build_flat(16, alg=alg)
        ruleno = m.make_replicated_rule(root, 0)
        nm = mapper.NativeMapper(m)
        weights = [0x10000] * 16
        xs = np.arange(400, dtype=np.uint32)
        out = nm.map_batch(ruleno, xs, 3, weights)
        for x in range(400):
            want = mapper_ref.do_rule(m, ruleno, x, 3, weights)
            got = [o for o in out[x] if o != 0x7FFFFFFF]
            assert got == want, (alg, x)

    @pytest.mark.parametrize("mode", ["firstn", "indep"])
    def test_hierarchy_matches_ref(self, mode, rng):
        mapper = _native_mapper()
        m, root = build_tree(rng, n_host=8, osd_per_host=4)
        if mode == "firstn":
            ruleno = m.make_replicated_rule(root, 1)
        else:
            ruleno = m.make_erasure_rule(root, 1)
        weights = [0x10000] * 32
        # include some down-weighted and out devices
        weights[3] = 0
        weights[17] = 0x8000
        nm = mapper.NativeMapper(m)
        xs = np.arange(600, dtype=np.uint32)
        out = nm.map_batch(ruleno, xs, 4, weights)
        for x in range(600):
            want = mapper_ref.do_rule(m, ruleno, x, 4, weights)
            if mode == "firstn":
                got = [o for o in out[x] if o != 0x7FFFFFFF]
            else:
                got = list(out[x][: len(want)])
            assert got == want, (mode, x)

    def test_legacy_tunables(self, rng):
        mapper = _native_mapper()
        t = Tunables.profile("bobtail")
        m, root = build_tree(rng, n_host=4, osd_per_host=4, tunables=t)
        ruleno = m.make_replicated_rule(root, 1)
        weights = [0x10000] * 16
        nm = mapper.NativeMapper(m)
        out = nm.map_batch(
            ruleno, np.arange(200, dtype=np.uint32), 3, weights
        )
        for x in range(200):
            want = mapper_ref.do_rule(m, ruleno, x, 3, weights)
            got = [o for o in out[x] if o != 0x7FFFFFFF]
            assert got == want, x

    def test_multithreaded_equals_single(self, rng):
        mapper = _native_mapper()
        m, root = build_tree(rng, n_host=8, osd_per_host=4)
        ruleno = m.make_replicated_rule(root, 1)
        weights = [0x10000] * 32
        nm = mapper.NativeMapper(m)
        xs = np.arange(5000, dtype=np.uint32)
        a = nm.map_batch(ruleno, xs, 3, weights, n_threads=1)
        b = nm.map_batch(ruleno, xs, 3, weights, n_threads=8)
        assert np.array_equal(a, b)

    def test_choose_args_respected(self, rng):
        mapper = _native_mapper()
        from ceph_tpu.crush.types import ChooseArgs

        m, root = build_flat(8)
        ruleno = m.make_replicated_rule(root, 0)
        ca = ChooseArgs()
        # double the weight of osd 0 in the root bucket
        ws = [[0x20000] + [0x10000] * 7]
        ca.weight_sets[root] = ws
        m.choose_args[-1] = ca
        nm = mapper.NativeMapper(m, choose_args=ca)
        weights = [0x10000] * 8
        xs = np.arange(300, dtype=np.uint32)
        out = nm.map_batch(ruleno, xs, 2, weights)
        for x in range(300):
            want = mapper_ref.do_rule(
                m, ruleno, x, 2, weights, choose_args=ca
            )
            got = [o for o in out[x] if o != 0x7FFFFFFF]
            assert got == want, x
