"""Text compiler tests: compile/decompile roundtrips (the contract pinned by
the reference's cram transcripts, reference src/test/cli/crushtool/*.t) and
device-class shadow-tree mapping."""

import numpy as np
import pytest

from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.compiler import CompileError, compile_text, decompile
from ceph_tpu.crush.types import BucketAlg, RuleOp

SAMPLE = """
# begin crush map
tunable choose_local_tries 0
tunable choose_local_fallback_tries 0
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1
tunable straw_calc_version 1
tunable allowed_bucket_algs 54

# devices
device 0 osd.0
device 1 osd.1
device 2 osd.2
device 3 osd.3

# types
type 0 osd
type 1 host
type 11 root

# buckets
host host0 {
\tid -1
\talg straw2
\thash 0
\titem osd.0 weight 1.00000
\titem osd.1 weight 2.00000
}
host host1 {
\tid -2
\talg straw2
\thash 0
\titem osd.2 weight 1.00000
\titem osd.3 weight 1.00000
}
root default {
\tid -3
\talg straw2
\thash 0
\titem host0 weight 3.00000
\titem host1 weight 2.00000
}

# rules
rule replicated_rule {
\tid 0
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}

# end crush map
"""

CLASSED = """
device 0 osd.0 class hdd
device 1 osd.1 class ssd
device 2 osd.2 class hdd
device 3 osd.3 class ssd

type 0 osd
type 1 host
type 11 root

host host0 {
\tid -1
\talg straw2
\thash 0
\titem osd.0 weight 1.00000
\titem osd.1 weight 1.00000
}
host host1 {
\tid -2
\talg straw2
\thash 0
\titem osd.2 weight 1.00000
\titem osd.3 weight 1.00000
}
root default {
\tid -3
\talg straw2
\thash 0
\titem host0 weight 2.00000
\titem host1 weight 2.00000
}

rule ssd_rule {
\tid 0
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default class ssd
\tstep chooseleaf firstn 0 type host
\tstep emit
}
"""


class TestCompile:
    def test_parses_sample(self):
        m = compile_text(SAMPLE)
        assert m.max_devices == 4
        assert set(m.buckets) == {-1, -2, -3}
        assert m.buckets[-3].type == 11
        assert m.buckets[-1].weights == [0x10000, 0x20000]
        assert m.tunables.choose_total_tries == 50
        rule = m.rules[0]
        assert rule.steps[0] == (RuleOp.TAKE, -3, 0)
        assert rule.steps[1] == (RuleOp.CHOOSELEAF_FIRSTN, 0, 1)
        assert m.rule_names[0] == "replicated_rule"

    def test_mapping_works_after_compile(self):
        m = compile_text(SAMPLE)
        weights = [0x10000] * 4
        for x in range(64):
            out = mapper_ref.do_rule(m, 0, x, 2, weights)
            assert len(out) == 2
            hosts = {o // 2 for o in out}
            assert len(hosts) == 2  # one per host

    def test_roundtrip(self):
        m1 = compile_text(SAMPLE)
        text = decompile(m1)
        m2 = compile_text(text)
        assert decompile(m2) == text
        assert m2.buckets.keys() == m1.buckets.keys()
        for bid in m1.buckets:
            b1, b2 = m1.buckets[bid], m2.buckets[bid]
            assert (b1.items, b1.weights, b1.alg, b1.type) == (
                b2.items, b2.weights, b2.alg, b2.type
            )
        assert [r.steps for r in m1.rules if r] == [
            r.steps for r in m2.rules if r
        ]

    def test_pos_reordering(self):
        text = SAMPLE.replace(
            "\titem osd.0 weight 1.00000\n\titem osd.1 weight 2.00000\n",
            "\titem osd.1 weight 2.00000 pos 1\n"
            "\titem osd.0 weight 1.00000 pos 0\n",
        )
        m = compile_text(text)
        assert m.buckets[-1].items == [0, 1]

    def test_errors(self):
        with pytest.raises(CompileError):
            compile_text("bogus syntax here")
        with pytest.raises(CompileError):
            compile_text("type 0 osd\nhost h { id -1 alg nope hash 0 }")
        with pytest.raises(CompileError):
            compile_text("tunable nonsense 3")


class TestDeviceClasses:
    def test_shadow_trees_built(self):
        m = compile_text(CLASSED)
        assert m.item_classes == {0: "hdd", 1: "ssd", 2: "hdd", 3: "ssd"}
        # every original bucket has a shadow per class
        for bid in (-1, -2, -3):
            assert set(
                m.class_names[c] for c in m.class_bucket[bid]
            ) == {"hdd", "ssd"}

    def test_class_rule_maps_only_class_devices(self):
        m = compile_text(CLASSED)
        weights = [0x10000] * 4
        seen = set()
        for x in range(128):
            out = mapper_ref.do_rule(m, 0, x, 2, weights)
            seen.update(out)
            assert all(m.item_classes[o] == "ssd" for o in out)
        assert seen == {1, 3}

    def test_decompile_elides_shadows_and_prints_class(self):
        m = compile_text(CLASSED)
        text = decompile(m)
        assert "~" not in text
        assert "step take default class ssd" in text
        m2 = compile_text(text)
        weights = [0x10000] * 4
        for x in range(32):
            assert mapper_ref.do_rule(m2, 0, x, 2, weights) == \
                mapper_ref.do_rule(m, 0, x, 2, weights)


class TestChooseArgsRoundtrip:
    """Multi-position (positions>1) weight_sets through decompile ->
    compile -> decompile: the per-position rows that drive the straw2
    row-path fallback must survive the text format byte-exactly
    (reference src/test/cli/crushtool/choose-args.t)."""

    def _map_with_args(self, positions=3):
        from ceph_tpu.cli.crushtool import build_map
        from ceph_tpu.crush.types import ChooseArgs

        rng = np.random.default_rng(11)
        m = build_map(9, [("host", "straw2", 3), ("root", "straw2", 0)])
        ca = ChooseArgs()
        for bid, b in m.buckets.items():
            ca.weight_sets[bid] = [
                [int(w) for w in rng.integers(1, 3 * 0x10000, b.size)]
                for _ in range(positions)
            ]
            ca.ids[bid] = [
                int(i) + 1000 if i >= 0 else int(i) for i in b.items
            ]
        m.choose_args[-1] = ca
        m.choose_args[0] = ChooseArgs(
            weight_sets={-1: [[0x8000] * m.buckets[-1].size]}
        )
        return m, ca

    def test_positions_gt1_roundtrip(self):
        m, ca = self._map_with_args()
        text = decompile(m)
        m2 = compile_text(text)
        assert decompile(m2) == text
        assert m2.choose_args[-1].weight_sets == ca.weight_sets
        assert m2.choose_args[-1].ids == ca.ids
        assert m2.choose_args[0].weight_sets == {
            -1: [[0x8000] * m.buckets[-1].size]
        }

    def test_u64_printed_compat_key_normalizes(self):
        """Some reference dumps print the compat (-1) key as u64
        (18446744073709551615); it must parse back to -1 so the binary
        codec's s64 encode can round-trip the map."""
        m, ca = self._map_with_args(positions=2)
        text = decompile(m).replace(
            "choose_args -1", "choose_args 18446744073709551615"
        )
        m2 = compile_text(text)
        assert m2.choose_args[-1].weight_sets == ca.weight_sets

    def test_binary_codec_roundtrip(self):
        from ceph_tpu.crush.codec import decode_crushmap, encode_crushmap

        m, ca = self._map_with_args()
        m3 = decode_crushmap(encode_crushmap(m))
        assert m3.choose_args[-1].weight_sets == ca.weight_sets
        assert m3.choose_args[-1].ids == ca.ids

    def test_mapping_respects_compiled_args(self):
        """The round-tripped positions>1 weight-set changes mappings the
        same way the original does."""
        m, ca = self._map_with_args()
        m2 = compile_text(decompile(m))
        weights = [0x10000] * 9
        for x in range(64):
            a = mapper_ref.do_rule(m, 0, x, 3, weights, ca)
            b = mapper_ref.do_rule(
                m2, 0, x, 3, weights, m2.choose_args[-1]
            )
            assert a == b, x
