"""Erasure-code tests: field properties, matrix constructions, roundtrip
grids (the TestErasureCode* pattern of the reference,
reference src/test/erasure-code/TestErasureCode.cc etc.), and
host-vs-device engine parity."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import create_erasure_code
from ceph_tpu.ec.gf import (
    GF_EXP,
    GF_MUL_TABLE,
    gf_div,
    gf_inv,
    gf_invert_matrix,
    gf_matmul,
    gf_mul,
    gf_pow,
    matrix_to_bitmatrix,
)
from ceph_tpu.ec import matrices
from ceph_tpu.ec.interface import ErasureCodeProfileError


class TestGF:
    def test_mul_table_vs_peasant(self):
        """Table multiply == carry-less peasant multiply mod 0x11D."""

        def slow(a, b):
            p = 0
            while b:
                if b & 1:
                    p ^= a
                a <<= 1
                if a & 0x100:
                    a ^= 0x11D
                b >>= 1
            return p

        rng = np.random.default_rng(7)
        for a, b in rng.integers(0, 256, (500, 2)):
            assert GF_MUL_TABLE[a, b] == slow(int(a), int(b))

    def test_field_axioms_sampled(self):
        rng = np.random.default_rng(8)
        a, b, c = rng.integers(1, 256, 3)
        assert gf_mul(a, b) == gf_mul(b, a)
        assert gf_mul(a, gf_mul(b, c)) == gf_mul(gf_mul(a, b), c)
        assert gf_mul(a, b ^ c) == gf_mul(a, b) ^ gf_mul(a, c)

    def test_inverse(self):
        for a in range(1, 256):
            assert gf_mul(a, gf_inv(a)) == 1
        with pytest.raises(ZeroDivisionError):
            gf_inv(0)

    def test_div_pow(self):
        assert gf_div(gf_mul(7, 9), 9) == 7
        assert gf_pow(2, 8) == GF_EXP[8]
        assert gf_pow(5, 0) == 1
        assert gf_pow(0, 3) == 0

    def test_xtime_is_mul_by_two(self):
        from ceph_tpu.ec.gf import gf_xtime

        x = np.arange(256, dtype=np.uint8)
        assert np.array_equal(gf_xtime(x), gf_mul(x, 2))

    def test_matrix_inversion(self):
        rng = np.random.default_rng(9)
        for _ in range(20):
            M = rng.integers(0, 256, (5, 5)).astype(np.uint8)
            try:
                inv = gf_invert_matrix(M)
            except np.linalg.LinAlgError:
                continue
            eye = gf_matmul(M, inv)
            assert np.array_equal(eye, np.eye(5, dtype=np.uint8))

    def test_bitmatrix_is_multiplication(self):
        rng = np.random.default_rng(10)
        for c in rng.integers(0, 256, 16):
            B = matrix_to_bitmatrix(np.array([[c]], np.uint8))
            for x in rng.integers(0, 256, 8):
                bits = np.array([(int(x) >> i) & 1 for i in range(8)])
                y_bits = B @ bits % 2
                y = sum(int(v) << i for i, v in enumerate(y_bits))
                assert y == GF_MUL_TABLE[c, x]


KM_GRID = [(2, 1), (2, 2), (3, 2), (4, 2), (4, 3), (6, 2), (6, 3), (8, 4)]


class TestMatrices:
    @pytest.mark.parametrize("k,m", KM_GRID)
    def test_vandermonde_mds(self, k, m):
        C = matrices.vandermonde_rs(k, m)
        assert np.all(C[0] == 1)  # first parity row = XOR row
        assert matrices.is_mds(C)

    @pytest.mark.parametrize("k,m", [(3, 2), (4, 2), (5, 3), (8, 4)])
    def test_cauchy_mds(self, k, m):
        assert matrices.is_mds(matrices.cauchy_orig(k, m))
        good = matrices.cauchy_good(k, m)
        assert np.all(good[0] == 1)
        assert matrices.is_mds(good)

    @pytest.mark.parametrize("k,m", [(3, 2), (4, 2), (8, 4)])
    def test_isa_cauchy_mds(self, k, m):
        assert matrices.is_mds(matrices.isa_cauchy(k, m))

    def test_r6(self):
        C = matrices.rs_r6(5)
        assert np.all(C[0] == 1)
        assert matrices.is_mds(C)

    def test_recover_matrix_identity_when_present(self):
        C = matrices.vandermonde_rs(4, 2)
        R = matrices.recover_matrix(C, [0, 1, 2, 3], [0, 1, 2, 3])
        assert np.array_equal(R, np.eye(4, dtype=np.uint8))


def _roundtrip(code, k, m, rng, nbytes=1237):
    data = rng.integers(0, 256, nbytes).astype(np.uint8).tobytes()
    n = k + m
    encoded = code.encode(set(range(n)), data)
    cs = code.get_chunk_size(nbytes)
    assert all(len(encoded[i]) == cs for i in encoded)
    # every erasure pattern up to m losses must decode bit-exactly
    for lost_n in range(1, m + 1):
        for lost in itertools.combinations(range(n), lost_n):
            have = {i: encoded[i] for i in range(n) if i not in lost}
            got = code.decode(set(range(k)), dict(have))
            out = b"".join(got[i].tobytes() for i in range(k))
            assert out[:nbytes] == data, f"lost={lost}"


class TestRoundtrip:
    @pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (4, 2), (8, 4)])
    @pytest.mark.parametrize(
        "technique",
        ["reed_sol_van", "cauchy_orig", "cauchy_good"],
    )
    def test_jerasure(self, k, m, technique, rng):
        code = create_erasure_code(
            {"plugin": "jerasure", "technique": technique,
             "k": k, "m": m}
        )
        _roundtrip(code, k, m, rng)

    @pytest.mark.parametrize("k", [3, 6])
    def test_r6(self, k, rng):
        code = create_erasure_code(
            {"plugin": "jerasure", "technique": "reed_sol_r6_op",
             "k": k, "m": 2}
        )
        _roundtrip(code, k, 2, rng)

    @pytest.mark.parametrize("technique", ["reed_sol_van", "cauchy"])
    def test_isa(self, technique, rng):
        code = create_erasure_code(
            {"plugin": "isa", "technique": technique, "k": 4, "m": 2}
        )
        _roundtrip(code, 4, 2, rng)

    def test_example_xor(self, rng):
        code = create_erasure_code({"plugin": "example", "k": 3, "m": 1})
        _roundtrip(code, 3, 1, rng)


class TestInterface:
    def test_chunk_size_alignment(self):
        code = create_erasure_code({"plugin": "jerasure", "k": 4, "m": 2})
        cs = code.get_chunk_size(1000)
        align = code.get_alignment()
        assert (cs * 4) % align == 0 and cs * 4 >= 1000

    def test_minimum_to_decode(self):
        code = create_erasure_code({"plugin": "jerasure", "k": 3, "m": 2})
        # all wanted available -> want itself
        assert code.minimum_to_decode({0, 1}, {0, 1, 2, 4}) == {0, 1}
        # otherwise first k available
        assert code.minimum_to_decode({0, 1, 2}, {1, 2, 3, 4}) == {1, 2, 3}
        with pytest.raises(ValueError):
            code.minimum_to_decode({0}, {1, 2})

    def test_bad_profiles(self):
        with pytest.raises(ErasureCodeProfileError):
            create_erasure_code({"plugin": "nope"})
        with pytest.raises(ErasureCodeProfileError):
            create_erasure_code({"plugin": "jerasure", "k": "x"})
        with pytest.raises(ErasureCodeProfileError):
            create_erasure_code(
                {"plugin": "jerasure", "technique": "wat"}
            )
        with pytest.raises(ErasureCodeProfileError):
            create_erasure_code({"plugin": "jerasure", "k": 0})

    def test_decode_concat(self, rng):
        code = create_erasure_code({"plugin": "jerasure", "k": 3, "m": 2})
        data = rng.integers(0, 256, 500).astype(np.uint8).tobytes()
        enc = code.encode(set(range(5)), data)
        del enc[1], enc[3]
        assert code.decode_concat(enc)[:500] == data


class TestJaxEngine:
    @pytest.mark.parametrize(
        "strategy", ["logexp", "bitplane", "xor", "xor_cse"]
    )
    def test_matches_numpy(self, strategy, rng):
        from ceph_tpu.ec.jax_backend import JaxEngine
        from ceph_tpu.ec.rs import NumpyEngine

        M = matrices.vandermonde_rs(6, 3)
        data = rng.integers(0, 256, (6, 4096)).astype(np.uint8)
        want = NumpyEngine().matmul(M, data)
        got = JaxEngine(strategy).matmul(M, data)
        assert np.array_equal(want, got)

    def test_bitplane_tiling(self, rng):
        from ceph_tpu.ec.jax_backend import JaxEngine
        from ceph_tpu.ec.rs import NumpyEngine

        M = matrices.vandermonde_rs(4, 2)
        data = rng.integers(0, 256, (4, 5000)).astype(np.uint8)
        eng = JaxEngine("bitplane", tile=1024)  # force multi-tile + pad
        assert np.array_equal(
            eng.matmul(M, data), NumpyEngine().matmul(M, data)
        )

    def test_jax_plugin_roundtrip(self, rng):
        code = create_erasure_code({"plugin": "jax", "k": 4, "m": 2})
        _roundtrip(code, 4, 2, rng, nbytes=2000)


class TestPallasKernel:
    """The fused Pallas GF(2^8) kernel (ec.jax_backend.gf_matmul_pallas)
    runs in interpret mode on the CPU CI mesh — same kernel code the TPU
    executes — and must match the table-driven host oracle exactly."""

    def test_pallas_matches_oracle(self):
        import jax.numpy as jnp

        from ceph_tpu.ec.gf import gf_matvec_data, matrix_to_bitmatrix
        from ceph_tpu.ec.jax_backend import gf_matmul_pallas

        rng = np.random.default_rng(11)
        for k, m, L in ((8, 4, 8192), (7, 3, 4096), (4, 2, 12288)):
            M = rng.integers(0, 256, (m, k)).astype(np.uint8)
            data = rng.integers(0, 256, (k, L)).astype(np.uint8)
            B = jnp.asarray(matrix_to_bitmatrix(M).astype(np.int8))
            got = np.asarray(gf_matmul_pallas(B, jnp.asarray(data), m))
            assert np.array_equal(got, gf_matvec_data(M, data)), (k, m, L)

    def test_engine_pallas_ragged_and_device_residency(self):
        import jax
        import jax.numpy as jnp

        from ceph_tpu.ec.gf import gf_matvec_data
        from ceph_tpu.ec.jax_backend import JaxEngine

        rng = np.random.default_rng(12)
        M = rng.integers(0, 256, (4, 8)).astype(np.uint8)
        data = rng.integers(0, 256, (8, 5000)).astype(np.uint8)
        eng = JaxEngine(strategy="pallas")
        out_np = eng.matmul(M, data)
        assert isinstance(out_np, np.ndarray)
        out_dev = eng.matmul(M, jax.device_put(jnp.asarray(data)))
        assert isinstance(out_dev, jax.Array)  # stays on device
        want = gf_matvec_data(M, data)
        assert np.array_equal(out_np, want)
        assert np.array_equal(np.asarray(out_dev), want)
        # bit-matrix device constant is cached per matrix
        assert len(eng._bitmats) == 1
        eng.matmul(M, data)
        assert len(eng._bitmats) == 1
