"""SHEC + LRC tests, mirroring the reference grids
(reference src/test/erasure-code/TestErasureCodeShec*.cc, TestErasureCodeLrc.cc)."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import create_erasure_code
from ceph_tpu.ec.interface import ErasureCodeProfileError
from ceph_tpu.ec.shec import shec_matrix


class TestShecMatrix:
    @pytest.mark.parametrize("k,m,c", [(4, 3, 2), (6, 3, 2), (8, 4, 3),
                                       (4, 2, 1)])
    def test_shape_and_shingles(self, k, m, c):
        M = shec_matrix(k, m, c)
        assert M.shape == (m, k)
        # each parity row covers a strict subset (shingle) unless c == m
        if c < m:
            assert any((M[r] == 0).any() for r in range(m))
        # every data chunk is covered by >= 1 parity
        assert all((M[:, j] != 0).any() for j in range(k))

    def test_single_vs_multiple_differ(self):
        a = shec_matrix(6, 3, 2, single=True)
        b = shec_matrix(6, 3, 2, single=False)
        assert a.shape == b.shape


class TestShecRoundtrip:
    @pytest.mark.parametrize("k,m,c", [(4, 3, 2), (6, 3, 2), (8, 4, 3)])
    def test_c_erasures_always_recoverable(self, k, m, c, rng):
        code = create_erasure_code(
            {"plugin": "shec", "k": k, "m": m, "c": c}
        )
        n = k + m
        data = rng.integers(0, 256, 1000).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        # SHEC guarantees any <= c erasures
        for e in range(1, c + 1):
            for lost in itertools.combinations(range(n), e):
                have = {i: encoded[i] for i in range(n) if i not in lost}
                got = code.decode(set(range(k)), dict(have))
                out = b"".join(got[i].tobytes() for i in range(k))
                assert out[: len(data)] == data, f"lost={lost}"

    def test_minimum_to_decode_is_local(self, rng):
        code = create_erasure_code(
            {"plugin": "shec", "k": 6, "m": 3, "c": 2}
        )
        n = 9
        avail = set(range(n)) - {0}
        minimum = code.minimum_to_decode({0}, avail)
        # shingled recovery should read fewer than all k+m-1 chunks
        assert len(minimum) < n - 1
        # and the chosen set actually decodes
        data = rng.integers(0, 256, 600).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        have = {i: encoded[i] for i in minimum}
        got = code.decode({0}, have)
        assert np.array_equal(got[0], encoded[0])

    def test_bad_profile(self):
        with pytest.raises(ErasureCodeProfileError):
            create_erasure_code({"plugin": "shec", "k": 4, "m": 3, "c": 9})


class TestLrcKml:
    def test_generate_kml_layout(self):
        from ceph_tpu.ec.lrc import generate_kml

        mapping, layers = generate_kml(4, 2, 3)
        assert mapping == "DD__DD__"
        assert layers[0][0] == "DDc_DDc_"  # global layer
        assert layers[1][0] == "DDDc____"
        assert len(layers) == 3  # 1 global + 2 local

    def test_kml_validation(self):
        with pytest.raises(ErasureCodeProfileError):
            create_erasure_code({"plugin": "lrc", "k": 4, "m": 2, "l": 5})


class TestLrcRoundtrip:
    PROFILE = {
        "plugin": "lrc",
        "mapping": "__DD__DD",
        "layers": '[["_cDD_cDD", ""], ["cDDD____", ""], ["____cDDD", ""]]',
    }

    def test_geometry(self):
        code = create_erasure_code(dict(self.PROFILE))
        assert code.k == 4
        assert code.get_chunk_count() == 8

    def test_single_erasure_local_repair(self, rng):
        code = create_erasure_code(dict(self.PROFILE))
        n = code.get_chunk_count()
        data = rng.integers(0, 256, 777).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        for lost in range(n):
            have = {i: encoded[i] for i in range(n) if i != lost}
            got = code.decode({lost}, dict(have))
            assert np.array_equal(got[lost], encoded[lost]), lost

    def test_minimum_to_decode_prefers_local_layer(self):
        code = create_erasure_code(dict(self.PROFILE))
        n = code.get_chunk_count()
        minimum = code.minimum_to_decode({2}, set(range(n)) - {2})
        # local layer cDDD____ has chunks {0,1,2,3}: reading 3 suffices
        assert minimum <= {0, 1, 3}

    def test_decode_concat(self, rng):
        code = create_erasure_code(dict(self.PROFILE))
        n = code.get_chunk_count()
        data = rng.integers(0, 256, 500).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        del encoded[3], encoded[6]
        assert code.decode_concat(encoded)[:500] == data

    def test_kml_roundtrip(self, rng):
        code = create_erasure_code(
            {"plugin": "lrc", "k": 4, "m": 2, "l": 3}
        )
        n = code.get_chunk_count()
        data = rng.integers(0, 256, 900).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        for lost in range(n):
            have = {i: encoded[i] for i in range(n) if i != lost}
            got = code.decode({lost}, dict(have))
            assert np.array_equal(got[lost], encoded[lost]), lost


class TestReviewRegressions:
    def test_shec_minimum_with_wanted_parity(self, rng):
        """minimum_to_decode must stay decodable when an erased parity is
        wanted alongside other erased data (2 <= c erasures)."""
        import itertools

        for k, m, c in [(4, 3, 2), (6, 3, 2)]:
            code = create_erasure_code(
                {"plugin": "shec", "k": k, "m": m, "c": c}
            )
            n = k + m
            data = np.random.default_rng(5).integers(
                0, 256, 500
            ).astype(np.uint8).tobytes()
            encoded = code.encode(set(range(n)), data)
            for lost in itertools.combinations(range(n), 2):
                for want in lost:
                    avail = set(range(n)) - set(lost)
                    minimum = code.minimum_to_decode({want}, avail)
                    have = {i: encoded[i] for i in minimum}
                    got = code.decode({want}, have)
                    assert np.array_equal(got[want], encoded[want]), (
                        lost, want,
                    )

    def test_lrc_minimum_raises_when_unrecoverable(self):
        code = create_erasure_code(
            {
                "plugin": "lrc",
                "mapping": "__DD__DD",
                "layers": '[["_cDD_cDD", ""], ["cDDD____", ""],'
                          ' ["____cDDD", ""]]',
            }
        )
        n = code.get_chunk_count()
        # losing all of {1,2,3} exceeds every covering layer's coding
        # capacity -> minimum_to_decode must raise, not lie
        with pytest.raises(ValueError):
            code.minimum_to_decode({2}, set(range(n)) - {1, 2, 3})
        # losing a local parity + a global parity IS recoverable via the
        # multi-sweep decode (global repairs 1, then local repairs 0)
        minimum = code.minimum_to_decode({0, 1}, set(range(n)) - {0, 1})
        rng = np.random.default_rng(6)
        data = rng.integers(0, 256, 400).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        have = {i: encoded[i] for i in minimum}
        got = code.decode({0, 1}, have)
        assert np.array_equal(got[0], encoded[0])
        assert np.array_equal(got[1], encoded[1])
