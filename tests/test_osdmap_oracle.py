"""Port of the reference's TestOSDMap upmap/temp oracle scenarios.

Reference: src/test/osd/TestOSDMap.cc — the fixture builds a 6-OSD map
through *incrementals* (set_up_map, :45-101), then pins concrete
behaviors of _apply_upmap / clean_pg_upmaps / pg_temp / primary
affinity.  The scenarios ported here are the ones VERDICT round 2 called
out: EC pools, down vs out upmap targets (trackers 37493/37501),
overlapping-parent EC remaps (37968), stale upmap cancellation, and the
negative-pg_upmap guard (TestOSDMap.cc:599-1123).
"""

from __future__ import annotations

import pytest

from ceph_tpu.crush.types import Rule, RuleOp
from ceph_tpu.osd.incremental import Incremental, apply_incremental
from ceph_tpu.osd.osdmap import OSD_UP, OSDMap, build_simple
from ceph_tpu.osd.types import PgId, PgPool, PoolType

N_OSDS = 6
EC_POOL = 1
REP_POOL = 2


def set_up_map(n=N_OSDS) -> OSDMap:
    """TestOSDMap::set_up_map (reference TestOSDMap.cc:45-101): bare
    build_simple + an incremental bringing every osd up/in, then an EC
    rule/pool and a replicated pool added via incrementals."""
    # the reference test env pins osd_crush_chooseleaf_type=0
    # (TestOSDMap.cc:23): rule 0's failure domain is the osd
    m = build_simple(n, default_pool=False, mark_up_in=False,
                     chooseleaf_type=0)
    inc = Incremental(epoch=m.epoch + 1)
    for i in range(n):
        inc.new_state[i] = 0b1 | 0b1000  # EXISTS|NEW
        inc.new_up_client[i] = b""
        inc.new_weight[i] = 0x10000
    m = apply_incremental(m, inc)

    # EC rule: failure domain osd, indep (add_simple_rule "erasure")
    root = next(b for b, bb in m.crush.buckets.items() if bb.type == 11)
    ec_rule = m.crush.make_erasure_rule(root, 0)
    m.crush.rule_names[ec_rule] = "erasure"

    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pool_max = max(m.pool_max, 0) + 2
    inc.new_pools[EC_POOL] = PgPool(
        type=PoolType.ERASURE, size=3, pg_num=64, pgp_num=64,
        crush_rule=ec_rule,
    )
    inc.new_pool_names[EC_POOL] = "ec"
    inc.new_pools[REP_POOL] = PgPool(
        type=PoolType.REPLICATED, size=3, pg_num=64, pgp_num=64,
        crush_rule=0, flags=1,
    )
    inc.new_pool_names[REP_POOL] = "reppool"
    return apply_incremental(m, inc)


def move_to_hosts(m: OSDMap, n_hosts: int) -> None:
    """The crush_move loops of TestOSDMap.cc:602-622: distribute the
    osds over host-0..host-(n-1) buckets."""
    per = m.max_osd // n_hosts
    for i in range(m.max_osd):
        host = f"host-{i // per}"
        m.crush.create_or_move_item(
            i, 1.0, f"osd.{i}", {"host": host, "root": "default"}
        )


def have_pg_upmaps(m: OSDMap, pg: PgId) -> bool:
    return pg in m.pg_upmap or pg in m.pg_upmap_items


def host_of(m: OSDMap, osd: int) -> int:
    from ceph_tpu.balancer.crush_analysis import get_parent_of_type

    return get_parent_of_type(m.crush, osd, 1)


# ------------------------------------------------------------ basic oracle


def test_map_functions_match():
    """MapFunctionsMatch (TestOSDMap.cc:274): the composed
    pg_to_up_acting_osds agrees with its stage functions for every PG."""
    m = set_up_map()
    for pool in (EC_POOL, REP_POOL):
        for ps in range(m.pools[pool].pg_num):
            pg = PgId(pool, ps)
            up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
            up2, upp2 = m.pg_to_raw_up(pg)
            assert list(up) == list(up2)
            assert upp == upp2


def test_primary_is_first():
    """PrimaryIsFirst (TestOSDMap.cc:302)."""
    m = set_up_map()
    for ps in range(64):
        up, upp, acting, actp = m.pg_to_up_acting_osds(PgId(REP_POOL, ps))
        assert upp == up[0]
        assert actp == acting[0]


def test_pg_temp_respected():
    """PGTempRespected (TestOSDMap.cc:316): reversed acting set via
    pg_temp incremental."""
    m = set_up_map()
    pg = PgId(REP_POOL, 0)
    up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_temp[pg] = list(reversed(acting))
    m = apply_incremental(m, inc)
    up2, upp2, acting2, actp2 = m.pg_to_up_acting_osds(pg)
    assert list(acting2) == list(reversed(acting))
    assert list(up2) == list(up)


def test_primary_temp_respected():
    """PrimaryTempRespected (TestOSDMap.cc:344)."""
    m = set_up_map()
    pg = PgId(REP_POOL, 0)
    up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_primary_temp[pg] = acting[-1]
    m = apply_incremental(m, inc)
    _, _, acting2, actp2 = m.pg_to_up_acting_osds(pg)
    assert actp2 == acting[-1]
    assert list(acting2) == list(acting)


def test_primary_affinity():
    """PrimaryAffinity (TestOSDMap.cc:455): affinity 0 => never primary
    (but still serves); default => roughly proportional."""
    m = set_up_map()
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_primary_affinity[0] = 0
    m = apply_incremental(m, inc)
    any_count = [0] * N_OSDS
    primary_count = [0] * N_OSDS
    for ps in range(64):
        _, _, acting, actp = m.pg_to_up_acting_osds(PgId(REP_POOL, ps))
        for o in acting:
            any_count[o] += 1
        if actp >= 0:
            primary_count[actp] += 1
    assert any_count[0] > 0  # still serves data
    assert primary_count[0] == 0  # never primary


# -------------------------------------------------------- CleanPGUpmaps


def hosted_map():
    m = set_up_map()
    move_to_hosts(m, 3)
    root = next(b for b, bb in m.crush.buckets.items() if bb.type == 11)
    ruleno = m.crush.make_replicated_rule(root, 1)  # failure domain host
    m.crush.rule_names[ruleno] = "upmap"
    pool_id = m.pool_max + 1
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pool_max = pool_id
    inc.new_pools[pool_id] = PgPool(
        type=PoolType.REPLICATED, size=2, pg_num=64, pgp_num=64,
        crush_rule=ruleno, flags=1,
    )
    inc.new_pool_names[pool_id] = "upmap_pool"
    m = apply_incremental(m, inc)
    return m, pool_id


def test_host_disjoint_and_stale_upmap_cancelled():
    """CleanPGUpmaps main body (TestOSDMap.cc:622-693): the host rule
    gives host-disjoint mappings; an upmap whose `from` is not in the
    raw mapping is stale and gets cancelled."""
    m, pool_id = hosted_map()
    pg = PgId(pool_id, 0)
    up, upp = m.pg_to_raw_up(pg)
    assert len(up) > 1
    assert host_of(m, up[0]) != host_of(m, up[1])

    frm = next(i for i in range(N_OSDS) if i not in up)
    to = next(i for i in range(N_OSDS) if i not in up and i != frm)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap_items[pg] = [(frm, to)]
    m = apply_incremental(m, inc)
    assert have_pg_upmaps(m, pg)
    m.clean_pg_upmaps()
    assert not have_pg_upmaps(m, pg)


def test_ec_upmap_down_target_kept():
    """tracker 37493 (TestOSDMap.cc:694-741): a DOWN (but in) upmap
    target does not get cleaned."""
    m = set_up_map()
    pg = PgId(EC_POOL, 0)
    up, _ = m.pg_to_raw_up(pg)
    frm = up[0]
    to = next(i for i in range(N_OSDS) if i not in up)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap_items[pg] = [(frm, to)]
    m = apply_incremental(m, inc)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_state[to] = OSD_UP  # XOR: mark down
    m = apply_incremental(m, inc)
    assert not m.is_up(to)
    assert have_pg_upmaps(m, pg)
    m.clean_pg_upmaps()
    assert have_pg_upmaps(m, pg)


def test_ec_upmap_out_target_removed():
    """tracker 37501 (TestOSDMap.cc:743-791): an OUT upmap target is a
    bad mapping and gets cleaned."""
    m = set_up_map()
    pg = PgId(EC_POOL, 0)
    up, _ = m.pg_to_raw_up(pg)
    frm = up[0]
    to = next(i for i in range(N_OSDS) if i not in up)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap_items[pg] = [(frm, to)]
    m = apply_incremental(m, inc)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_weight[to] = 0  # CEPH_OSD_OUT
    m = apply_incremental(m, inc)
    assert m.is_out(to)
    assert have_pg_upmaps(m, pg)
    m.clean_pg_upmaps()
    assert not have_pg_upmaps(m, pg)


def test_ec_overlapping_parent_upmap_kept():
    """tracker 37968 (TestOSDMap.cc:793-916): EC rule `choose indep 2
    host / choose indep 2 osd`; an upmap to a same-host sibling is
    valid and survives clean_pg_upmaps."""
    m = set_up_map()
    move_to_hosts(m, 2)
    root = next(b for b, bb in m.crush.buckets.items() if bb.type == 11)
    rno = m.crush.add_rule(Rule(
        ruleset=len(m.crush.rules),  # crush_make_rule(rno, ...) parity
        steps=[
            (RuleOp.SET_CHOOSELEAF_TRIES, 5, 0),
            (RuleOp.SET_CHOOSE_TRIES, 100, 0),
            (RuleOp.TAKE, root, 0),
            (RuleOp.CHOOSE_INDEP, 2, 1),
            (RuleOp.CHOOSE_INDEP, 2, 0),
            (RuleOp.EMIT, 0, 0),
        ],
        type=int(PoolType.ERASURE), min_size=3, max_size=4,
    ))
    m.crush.rule_names[rno] = "rule_37968"
    pool_id = m.pool_max + 1
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pool_max = pool_id
    inc.new_pools[pool_id] = PgPool(
        type=PoolType.ERASURE, size=4, pg_num=8, pgp_num=8,
        crush_rule=rno, flags=1,
    )
    inc.new_pool_names[pool_id] = "pool_37968"
    m = apply_incremental(m, inc)

    pg = PgId(pool_id, 0)
    up, _ = m.pg_to_raw_up(pg)
    assert len([o for o in up if o >= 0]) == 4
    frm = up[0]
    parent = host_of(m, frm)
    to = next(
        i for i in range(N_OSDS)
        if i not in up and host_of(m, i) == parent
    )
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap_items[pg] = [(frm, to)]
    m = apply_incremental(m, inc)
    assert have_pg_upmaps(m, pg)
    m.clean_pg_upmaps()
    assert have_pg_upmaps(m, pg)


def test_full_pg_upmap_and_negative_guard():
    """TEST pg_upmap section (TestOSDMap.cc:918-1000): a negative id in
    pg_upmap is ignored by _apply_upmap; a valid full remap replaces the
    vector and survives clean_pg_upmaps."""
    m, pool_id = hosted_map()
    pg = PgId(pool_id, 0)
    up, _ = m.pg_to_raw_up(pg)
    parent = host_of(m, up[0])
    siblings = [
        i for i in range(N_OSDS)
        if host_of(m, i) == parent and i != up[0]
    ]
    assert siblings
    replaced_by = siblings[0]

    # negative value must not crash and must be ignored
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap[pg] = [up[0], -823648512]
    m = apply_incremental(m, inc)
    new_up, _ = m.pg_to_raw_up(pg)
    assert all(o >= 0 for o in new_up if o != 2147483647)

    # valid full remap: [up[0], sibling-of-up[0]]
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap[pg] = [up[0], replaced_by]
    m = apply_incremental(m, inc)
    new_up, _ = m.pg_to_raw_up(pg)
    assert list(new_up) == [up[0], replaced_by]


def test_clean_pg_upmaps_dead_pool():
    """Entries referencing a deleted pool are cancelled
    (check_pg_upmaps' pool-existence guard)."""
    m, pool_id = hosted_map()
    pg = PgId(pool_id, 0)
    up, _ = m.pg_to_raw_up(pg)
    frm = up[0]
    to = next(i for i in range(N_OSDS) if i not in up)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap_items[pg] = [(frm, to)]
    m = apply_incremental(m, inc)
    inc = Incremental(epoch=m.epoch + 1)
    inc.old_pools = {pool_id}
    m = apply_incremental(m, inc)
    m.clean_pg_upmaps()
    assert not have_pg_upmaps(m, pg)


def test_pipeline_matches_oracle_with_upmaps():
    """The batched XLA pipeline agrees with the host oracle on the
    hosted upmap_pool map including upmap overlays (ties the oracle
    scenarios back to the TPU path)."""
    import numpy as np

    from ceph_tpu.crush.types import ITEM_NONE
    from ceph_tpu.osd.pipeline_jax import PoolMapper

    m, pool_id = hosted_map()
    pg = PgId(pool_id, 3)
    up, _ = m.pg_to_raw_up(pg)
    frm = up[0]
    to = next(i for i in range(N_OSDS) if i not in up)
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_pg_upmap_items[pg] = [(frm, to)]
    m = apply_incremental(m, inc)

    pm = PoolMapper(m, pool_id)
    jup, jupp, jact, jactp = pm.map_all()
    for ps in range(m.pools[pool_id].pg_num):
        u, upp, a, ap = m.pg_to_up_acting_osds(PgId(pool_id, ps))
        w = jup.shape[1]
        padded = list(u) + [ITEM_NONE] * (w - len(u))
        assert list(np.asarray(jup[ps])) == padded, ps
        assert int(jupp[ps]) == upp, ps
