"""benchdiff: the frozen BENCH fixture series (real r01-r05 rounds —
including the genuine r02 gap — plus synthetic calibrated rounds with a
seeded regression) loads without crashing, the r05-strategy calibration
normalizes cross-container numbers, the seeded regressions are flagged,
uncalibrated hardware deltas never flag, and the reports keep their
shape.  The same fixture run is embedded in `bench.py --selftest`."""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tools.benchdiff import (
    SCHEMA_VERSION,
    Round,
    diff_series,
    extract_metrics,
    load_round,
    load_series,
    render_markdown,
)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "data" / "benchdiff"


def fixture_rounds():
    return load_series(sorted(FIXTURES.glob("*.json")))


# -- loading ----------------------------------------------------------------

def test_fixture_series_loads_with_gap_and_partial():
    rounds = fixture_rounds()
    names = [r.name for r in rounds]
    # numeric rounds in order, non-numbered (the partial) after them
    assert names[:8] == ["r01", "r02", "r03", "r04", "r05", "r06", "r07",
                         "r08"]
    assert names[-1] == "BENCH_partial"
    by = {r.name: r for r in rounds}
    assert by["r02"].empty and by["r02"].notes  # the real rc=1 round
    assert not by["r05"].empty
    assert by["BENCH_partial"].partial
    # calibration only exists from the synthetic PR6-era rounds on
    assert by["r05"].calibration is None
    assert by["r07"].calibration == pytest.approx(0.078)


def test_load_round_unreadable_is_a_gap(tmp_path):
    p = tmp_path / "BENCH_r42.json"
    p.write_text("{not json")
    r = load_round(p)
    assert r.empty and r.name == "r42" and r.notes


def test_partial_checkpoint_folds_to_final_shape():
    r = load_round(FIXTURES / "BENCH_partial.json")
    assert r.partial and not r.empty
    m = extract_metrics(r.record)
    assert "configs.headline.mappings_per_sec" in m
    # perf snapshot survives the fold: the balancer build-state time is
    # extractable (the ROADMAP item-5 cost, tracked per round)
    assert "perf.balancer.build_state_avgtime" in m


def test_schema_version_future_round_noted():
    r = Round("r99", {"schema_version": SCHEMA_VERSION + 1,
                      "configs": {}})
    assert any("newer bench" in n for n in r.notes)


# -- diffing ----------------------------------------------------------------

def test_seeded_regressions_flagged():
    rep = diff_series(fixture_rounds())
    assert rep["verdict"] == "regression"
    flagged = {d["metric"] for d in rep["regressions"]}
    structural = {
        "configs.headline.jit.compiles",       # 0 -> 6: trace-once broken
        "ec.trace_once_ok",                    # the stage's own proof bit
        # lifetime chaos trajectory (v4): seeded scenario, so these are
        # semantic drift — compared raw, never calibration-normalized
        "lifetime.invariant_violations",       # 0 -> 3
        "lifetime.steady_compiles",            # 0 -> 6
        "lifetime.jit_compiles_per_epoch",     # 0.0538 -> 0.31
        # serving daemon (v5): seeded load + swap cadence, so the
        # shed/stall/compile counts and the recovery bit compare raw
        "serve.steady_shed",                   # 0 -> 37
        "serve.swap_stalls",                   # 0 -> 2
        "serve.steady_compiles",               # 0 -> 3
        "serve.device_loss_recovered",         # the proof bit flipped
        "serve.chaos.dropped",                 # 0 -> 4: queries dropped
        # ClusterState O(delta) contract (v6, seeded in r09->r10):
        # value applies falling back to rebuilds and serve swaps
        # restaging from scratch are semantic drift, compared raw
        "lifetime.steady_full_rebuilds",       # 0 -> 5
        "lifetime.balancer_builds",            # 0 -> 6
        "lifetime.state.delta_applies",        # 497 -> 3
        "lifetime.state.full_rebuilds",        # 14 -> 180
        "serve.swap_delta_applies",            # 9 -> 0
        "serve.swap_full_restages",            # 0 -> 4
        "serve.swap_state_rebuilds",           # 0 -> 9
        # recovery data plane (v7, seeded in r11->r12): a queue losing
        # bytes is device/host disagreement — semantic, compared raw
        "lifetime.recovery.conservation_violations",  # 0 -> 3
        # mesh-sharded placement (v8, seeded in mc-r13->mc-r14): the
        # sharded lifetime digest stopped matching single-device — the
        # bit-exactness contract itself, compared raw
        "multichip.ok",                        # the wrapper verdict bit
        "multichip.scaling.digest_match",      # True -> False
        # health / SLO (v9, seeded in r15->r16): seeded scenarios, so
        # a status-rank shift, err epochs appearing, a burn that never
        # clears, or the pure-observer bit flipping are semantic drift
        "lifetime.health.rank",                # HEALTH_OK -> HEALTH_ERR
        "lifetime.health.err_epochs",          # 0 -> 9
        "lifetime.health_pure",                # True -> False
        "serve.health.rank",                   # HEALTH_OK -> HEALTH_WARN
        "serve.slo.burns_cleared",             # 1 -> 0: burn never cleared
        "serve.slo.breaches",                  # 6 -> 94
        # correlated durability (v10, seeded in r17->r18): the default
        # scenario is sized survivable, so pg_lost appearing from zero
        # and the exposure blow-up are semantic drift, compared raw
        "lifetime.durability.pg_lost",         # 0 -> 3: DATA LOSS
        "lifetime.durability.exposed_pg_epochs",  # 61 -> 188
        # device-loop optimizer (v11, seeded in r19->r20): the
        # one-dispatch plan fell apart into per-round launches and the
        # live background window compiled — dispatch/compile counts
        # are bit-determined by the seeded run, compared raw
        "rebalance.plan_dispatches",           # 2 -> 20
        "rebalance.dispatches_per_change",     # 0.1 -> 1.0
        "serve.background_query_compiles",     # 0 -> 3: zero baseline
        # fleet simulator (v12, seeded in r21->r22): stacked digests
        # stopped matching the solo oracles, the stacked dispatch
        # started compiling in steady state, and the pareto front went
        # empty — all bit-determined by the seeded members, raw
        "fleet.digest_matches",                # 64 -> 49
        "fleet.steady_compiles",               # 0 -> 5: zero baseline
        "fleet.pareto_front_size",             # 3 -> 0
        # bulk protocol edge (v13, seeded in r23->r24): the
        # amortization ratio is a same-stage quotient — dimensionless,
        # compared raw (the qps itself flags normalized below)
        "serve.bulk_ratio",                    # 69.4 -> 6.4
    }
    assert structural | {
        "configs.headline.mappings_per_sec",   # throughput -47%
        "ec.rs84_encode_gbps_jax",             # EC encode -70%
        "quantiles.pipeline.map_block.p99",    # tail x4
        "serve.qps",                           # serving rate -71%
        "serve.request_p99_s",                 # serving tail x7.5
        "lifetime.workload.served_qps",        # pareto service -32%
        "lifetime.recovery.drain_gbps",        # drain rate -45%
        "serve.slo.burn_minutes",              # 0.02 -> 1.8 burning
        # candidate-batched optimizer (v8, seeded in r13->r14):
        # batching went inert — back to ~1 dispatch per change; same
        # calibration, so it flags as a same-machine semantic slowdown
        "balancer.dispatches_per_change",      # 0.1875 -> 1.0625
        "serve.bulk_qps",                      # bulk edge -91%
    } <= flagged
    # every flagged throughput/tail metric compared on the same-machine
    # calibration basis, not raw cross-container numbers
    for d in rep["regressions"]:
        if d["metric"] not in structural:
            assert d["normalized"], d


def test_state_contract_fixture_pair_v6():
    """The v6 seeded pair in isolation: the healthy ClusterState round
    (r09) against the O(delta)-contract regression (r10) — every state
    metric flags raw, and the epochs/s collapse flags normalized (same
    calibration, so it is a same-machine semantic slowdown)."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r09"], by["r10"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    for name in ("lifetime.steady_full_rebuilds",
                 "lifetime.balancer_builds",
                 "lifetime.state.delta_applies",
                 "lifetime.state.full_rebuilds",
                 "serve.swap_delta_applies",
                 "serve.swap_full_restages",
                 "serve.swap_state_rebuilds"):
        assert name in flagged, name
        assert not flagged[name]["normalized"]  # structural: raw
    assert "lifetime.epochs_per_sec" in flagged  # 175 -> 14
    assert flagged["lifetime.epochs_per_sec"]["normalized"]
    # the healthy direction stays clean
    assert diff_series([by["r08"], by["r09"]])["verdict"] != \
        "regression" or not any(
            d["metric"].startswith(("lifetime.state", "serve.swap_"))
            for d in diff_series([by["r08"], by["r09"]])["regressions"])


def test_recovery_workload_fixture_pair_v7():
    """The v7 seeded pair in isolation: the healthy recovery/workload
    round (r11) against the regression (r12) — conservation violations
    flag raw (byte loss is device/host disagreement, never hardware),
    the pareto service level and drain rate flag normalized (same
    calibration: a same-machine semantic slowdown)."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r11"], by["r12"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    assert "lifetime.recovery.conservation_violations" in flagged
    assert not flagged["lifetime.recovery.conservation_violations"][
        "normalized"]
    assert "lifetime.workload.served_qps" in flagged
    assert flagged["lifetime.workload.served_qps"]["normalized"]
    assert "lifetime.recovery.drain_gbps" in flagged
    assert flagged["lifetime.recovery.drain_gbps"]["normalized"]
    # the healthy direction (r10 regression recovering into r11) never
    # flags a recovery/workload metric
    rep2 = diff_series([by["r10"], by["r11"]])
    assert not any(
        d["metric"].startswith(("lifetime.recovery.",
                                "lifetime.workload."))
        for d in rep2["regressions"])


def test_mesh_batch_fixture_pairs_v8():
    """The v8 seeded pairs in isolation: the candidate-batched
    optimizer going inert (r13->r14, dispatches/change 0.19 -> 1.06,
    flagged normalized — same calibration, semantic slowdown) and the
    sharded lifetime digest mismatch (mc-r13 -> mc-r14, the
    bit-exactness bit, flagged raw)."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r13"], by["r14"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    assert "balancer.dispatches_per_change" in flagged
    assert flagged["balancer.dispatches_per_change"]["normalized"]
    rep2 = diff_series([by["mc-r13"], by["mc-r14"]])
    flagged2 = {d["metric"]: d for d in rep2["regressions"]}
    assert "multichip.scaling.digest_match" in flagged2
    assert not flagged2["multichip.scaling.digest_match"]["normalized"]
    # the healthy record alone extracts the full scaling shape
    m = extract_metrics(by["mc-r13"].record)
    assert m["multichip.scaling.devices"][0] == 8
    assert m["multichip.scaling.digest_match"][0] == 1.0
    assert m["multichip.scaling.eps_per_device"][2] is False  # raw
    assert "multichip.dispatch_reduction_x" in m


def test_health_slo_fixture_pair_v9():
    """The v9 seeded pair in isolation: the healthy observability round
    (r15) against the health regression (r16) — the status rank shift,
    the err epochs appearing, the SLO_BURN that never cleared, and the
    pure-observer proof bit all flag raw (seeded scenarios: semantic
    drift); burn_minutes flags normalized (wall-clock under burning)."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r15"], by["r16"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    for name in ("lifetime.health.rank", "lifetime.health.err_epochs",
                 "lifetime.health_pure", "serve.health.rank",
                 "serve.slo.burns_cleared", "serve.slo.breaches"):
        assert name in flagged, name
        assert not flagged[name]["normalized"]  # structural: raw
    assert "serve.slo.burn_minutes" in flagged
    assert flagged["serve.slo.burn_minutes"]["normalized"]
    # the healthy record alone extracts the full v9 shape
    m = extract_metrics(by["r15"].record)
    assert m["lifetime.health.rank"][0] == 0.0
    assert m["lifetime.health.timeline_samples"][0] == 48
    assert m["lifetime.health_pure"][0] == 1.0
    assert m["serve.slo.burns_raised"][0] == 1
    assert m["serve.timeline_samples"][0] == 220
    # the healthy direction (r14 regression recovering into r15) never
    # flags a health/SLO metric
    rep2 = diff_series([by["r14"], by["r15"]])
    assert not any(
        d["metric"].startswith(("lifetime.health", "serve.slo.",
                                "serve.health"))
        for d in rep2["regressions"])


def test_durability_fixture_pair_v10():
    """The v10 seeded pair in isolation: the survivable correlated
    round (r17, pg_lost 0) against the durability regression (r18,
    pg_lost 3).  pg_lost rides the structural zero-baseline rule —
    there is no relative change from 0, so the threshold cannot
    arbitrate, and a loss appearing at all must flag."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r17"], by["r18"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    assert "lifetime.durability.pg_lost" in flagged
    d = flagged["lifetime.durability.pg_lost"]
    assert not d["normalized"]          # structural: raw
    assert d["prev"] == 0 and d["cur"] == 3
    assert d["change"] is None          # zero baseline: no finite pct
    assert "lifetime.durability.exposed_pg_epochs" in flagged
    # the healthy record alone extracts the full v10 shape
    m = extract_metrics(by["r17"].record)
    assert m["lifetime.durability.pg_lost"][0] == 0.0
    assert m["lifetime.chaos.cascades"][0] == 3
    assert m["lifetime.chaos.false_flap_revives"][0] == 9
    assert m["lifetime.overwhelmed.pg_lost"][0] == 4
    assert m["lifetime.overwhelmed.data_loss_latched"][0] == 1.0
    assert m["lifetime.ref_digest_match"][0] == 1.0
    # every v10 metric is structural (raw compare)
    for name, (_, _, cal) in m.items():
        if name.startswith(("lifetime.chaos.", "lifetime.durability.",
                            "lifetime.overwhelmed.")):
            assert not cal, name
    # the healthy direction (r16 regression recovering into r17) never
    # flags a chaos/durability metric
    rep2 = diff_series([by["r16"], by["r17"]])
    assert not any(
        d["metric"].startswith(("lifetime.chaos.",
                                "lifetime.durability.",
                                "lifetime.overwhelmed."))
        for d in rep2["regressions"])


def test_deviceloop_fixture_pair_v11():
    """The v11 seeded pair in isolation: the healthy device-loop round
    (r19, one dispatch per plan, 0 compiles in the background window)
    against the regression (r20: the plan fell apart into per-round
    dispatches, the round tail blew out, and the live window compiled).
    Dispatch counts are bit-determined by the seeded run — raw; the
    round tail is wall-clock — normalized; the window compile count
    rides the structural zero-baseline rule."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r19"], by["r20"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    for name in ("rebalance.plan_dispatches",
                 "rebalance.dispatches_per_change"):
        assert name in flagged, name
        assert not flagged[name]["normalized"]  # structural: raw
    assert flagged["rebalance.plan_dispatches"]["prev"] == 2
    assert flagged["rebalance.plan_dispatches"]["cur"] == 20
    assert "serve.background_round_p99_ms" in flagged
    assert flagged["serve.background_round_p99_ms"]["normalized"]
    d = flagged["serve.background_query_compiles"]
    assert not d["normalized"]
    assert d["prev"] == 0 and d["cur"] == 3
    assert d["change"] is None          # zero baseline: no finite pct
    # the healthy record alone extracts the full v11 shape
    m = extract_metrics(by["r19"].record)
    assert m["rebalance.plan_dispatches"][0] == 2
    assert m["rebalance.dispatches_per_change"][0] == 0.1
    assert m["serve.background_round_p99_ms"][0] == 85.0
    assert m["serve.background_query_compiles"][0] == 0.0
    # the healthy direction (r18 regression recovering into r19) never
    # flags a device-loop metric
    rep2 = diff_series([by["r18"], by["r19"]])
    assert not any(
        d["metric"].startswith(("rebalance.", "serve.background"))
        for d in rep2["regressions"])


def test_fleet_fixture_pair_v12():
    """The v12 seeded pair in isolation: the healthy fleet round (r21,
    every stacked digest bit-identical to its solo oracle, 0 steady
    compiles, a 3-point pareto front) against the regression (r22: 15
    digests diverged, the stacked dispatch compiled in steady state,
    the front went empty, and the aggregate rate collapsed).  The
    digest/compile/front counts are bit-determined by the seeded
    members — raw; the cluster-epochs rate is a hardware number — same
    calibration, so it flags as a same-machine semantic slowdown."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r21"], by["r22"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    for name in ("fleet.digest_matches", "fleet.pareto_front_size"):
        assert name in flagged, name
        assert not flagged[name]["normalized"]  # structural: raw
    assert flagged["fleet.digest_matches"]["prev"] == 64
    assert flagged["fleet.digest_matches"]["cur"] == 49
    d = flagged["fleet.steady_compiles"]
    assert not d["normalized"]
    assert d["prev"] == 0 and d["cur"] == 5
    assert d["change"] is None          # zero baseline: no finite pct
    assert "fleet.cluster_epochs_per_sec" in flagged
    assert flagged["fleet.cluster_epochs_per_sec"]["normalized"]
    # the healthy record alone extracts the full v12 shape
    m = extract_metrics(by["r21"].record)
    assert m["fleet.cluster_epochs_per_sec"] == (120.0, True, True)
    assert m["fleet.digest_matches"] == (64.0, True, False)
    assert m["fleet.steady_compiles"] == (0.0, False, False)
    assert m["fleet.pareto_front_size"] == (3.0, True, False)
    # the healthy direction (r20 regression recovering into r21) never
    # flags a fleet metric
    rep2 = diff_series([by["r20"], by["r21"]])
    assert not any(d["metric"].startswith("fleet.")
                   for d in rep2["regressions"])


def test_bulk_fixture_pair_v13():
    """The v13 seeded pair in isolation: the healthy bulk-edge round
    (r23: bulk 10^2x over the scalar submit edge, 0 compiles, 0
    structural stalls, mesh digests matching, the front shedding its
    stalled replica with nothing dropped) against the regression (r24:
    the bulk edge collapsed ~10x).  The qps flags normalized (same
    calibration: a same-machine semantic slowdown); the ratio — the
    amortization headline — flags raw."""
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r23"], by["r24"]])
    assert rep["verdict"] == "regression"
    flagged = {d["metric"]: d for d in rep["regressions"]}
    assert "serve.bulk_qps" in flagged
    assert flagged["serve.bulk_qps"]["normalized"]
    assert flagged["serve.bulk_qps"]["prev"] == 125000.0
    assert flagged["serve.bulk_qps"]["cur"] == 11500.0
    assert "serve.bulk_ratio" in flagged
    assert not flagged["serve.bulk_ratio"]["normalized"]  # raw
    # the healthy record alone extracts the full v13 shape
    m = extract_metrics(by["r23"].record)
    assert m["serve.bulk_qps"] == (125000.0, True, True)
    assert m["serve.bulk_ratio"] == (69.4, True, False)
    assert m["serve.bulk_compiles"] == (0.0, False, False)
    assert m["serve.structural_swap_stalls"] == (0.0, False, False)
    assert m["serve.mesh_devices"] == (2.0, True, False)
    assert m["serve.mesh_digest_match"] == (1.0, True, False)
    assert m["serve.front_p99_ms"] == (45.0, False, True)
    assert m["serve.front_sheds"] == (1.0, True, False)
    # the healthy direction (r22 fleet regression recovering into r23)
    # never flags a bulk/mesh/front metric
    rep2 = diff_series([by["r22"], by["r23"]])
    assert not any(
        d["metric"].startswith(("serve.bulk", "serve.mesh",
                                "serve.front",
                                "serve.structural_swap_stalls"))
        for d in rep2["regressions"])


def test_healthy_calibrated_rounds_are_clean():
    by = {r.name: r for r in fixture_rounds()}
    rep = diff_series([by["r06"], by["r07"]])
    assert rep["verdict"] == "ok"
    assert rep["regressions"] == []


def test_gap_rounds_reported_never_fatal():
    rep = diff_series(fixture_rounds())
    assert any(g["round"] == "r02" for g in rep["gaps"])
    # the gap contributes no deltas
    assert not any("r02" in (d["from"], d["to"]) for d in rep["deltas"])


def _mk(name, mps, cal):
    rec = {"configs": {"headline": {"mappings_per_sec": mps}},
           "ec": {"r05_strategy_gbps": cal} if cal else {}}
    return Round(name, rec)


def test_calibration_normalizes_cross_container():
    # second container is exactly half as fast (calibration halves, raw
    # throughput halves): normalized delta is zero -> clean
    rep = diff_series([_mk("a", 60000.0, 0.16), _mk("b", 30000.0, 0.08)])
    assert rep["verdict"] == "ok"
    d = [x for x in rep["deltas"]
         if x["metric"] == "configs.headline.mappings_per_sec"][0]
    assert d["normalized"] and d["change"] == pytest.approx(0.0)


def _mk_t(name, wall_s, cal):
    rec = {"balancer": {"upmap": {"wall_s": wall_s}},
           "ec": {"r05_strategy_gbps": cal} if cal else {}}
    return Round(name, rec)


def test_calibration_normalizes_time_metrics_inversely():
    # time scales AGAINST machine speed: a half-speed container (half
    # the calibration) legitimately takes 2x the wall clock — the
    # normalized delta must be zero, not a 4x-amplified "regression"
    rep = diff_series([_mk_t("a", 1.0, 0.16), _mk_t("b", 2.0, 0.08)])
    assert rep["verdict"] == "ok"
    d = [x for x in rep["deltas"]
         if x["metric"] == "balancer.upmap.wall_s"][0]
    assert d["normalized"] and d["change"] == pytest.approx(0.0)
    # ...while the same slowdown on the SAME machine is a regression
    rep = diff_series([_mk_t("a", 1.0, 0.16), _mk_t("b", 2.0, 0.16)])
    assert rep["verdict"] == "regression"


def test_uncalibrated_hardware_delta_never_flags():
    # a 50% raw drop with no calibration anywhere: informational only
    rep = diff_series([_mk("a", 60000.0, None), _mk("b", 30000.0, None)])
    assert rep["verdict"] == "ok"
    d = [x for x in rep["deltas"]
         if x["metric"] == "configs.headline.mappings_per_sec"][0]
    assert d.get("uncalibrated") and not d["normalized"]


def test_same_machine_regression_flags():
    rep = diff_series([_mk("a", 60000.0, 0.08), _mk("b", 30000.0, 0.08)])
    assert rep["verdict"] == "regression"


def test_compiles_from_zero_always_flag():
    def mk(name, compiles):
        return Round(name, {"configs": {"headline": {
            "mappings_per_sec": 1000.0, "jit": {"compiles": compiles}}}})
    rep = diff_series([mk("a", 0), mk("b", 1)], threshold=10.0)
    assert [d["metric"] for d in rep["regressions"]] == [
        "configs.headline.jit.compiles"]


def test_timing_from_zero_is_noise_not_structural():
    # bench rounds build_s to one decimal: 0.0 -> 0.1 on a timing
    # metric is measurement noise, not the compiles-from-zero case
    def mk(name, build_s):
        return Round(name, {"rebalance": {"build_s": build_s},
                            "ec": {"r05_strategy_gbps": 0.08}})
    rep = diff_series([mk("a", 0.0), mk("b", 0.1)])
    assert rep["verdict"] == "ok"


def test_disappearing_metric_is_surfaced():
    # a dropped guard metric (e.g. the jit section gone) must be
    # visible in the report, not silently skipped
    a = Round("a", {"configs": {"headline": {
        "mappings_per_sec": 1000.0, "jit": {"compiles": 0}}}})
    b = Round("b", {"configs": {"headline": {
        "mappings_per_sec": 1000.0}}})
    rep = diff_series([a, b])
    assert {"metric": "configs.headline.jit.compiles",
            "from": "a", "to": "b"} in rep["missing"]
    md = render_markdown(rep)
    assert "disappeared between rounds" in md


def _mk_mc(tmp_path, name, n_devices=8, ok=True, stddev=2.0,
           skipped=False):
    p = tmp_path / f"MULTICHIP_{name}.json"
    p.write_text(json.dumps({
        "n_devices": n_devices, "rc": 0 if ok else 1, "ok": ok,
        "skipped": skipped,
        "tail": f"dryrun_multichip ok: {n_devices} devices, 64 PGs, "
                f"stddev={stddev:.3f}\n" if ok else "",
    }))
    return p


def test_multichip_rounds_load_as_their_own_series(tmp_path):
    paths = [_mk_mc(tmp_path, "r01"), _mk_mc(tmp_path, "r02")]
    rounds = load_series(paths)
    assert [r.name for r in rounds] == ["mc-r01", "mc-r02"]
    mc = rounds[0].record["multichip"]
    assert mc == {"n_devices": 8, "ok": True, "pgs": 64, "stddev": 2.0}
    rep = diff_series(rounds)
    assert [r["round"] for r in rep["multichip_rounds"]] == \
        ["mc-r01", "mc-r02"]
    assert rep["rounds"] == []  # not mixed into the BENCH series
    assert rep["verdict"] == "ok"


def test_multichip_mixed_with_bench_series(tmp_path):
    paths = [_mk_mc(tmp_path, "r01"), _mk_mc(tmp_path, "r02")]
    rounds = load_series(paths) + [
        _mk("r01", 1000, 0.05), _mk("r02", 1010, 0.05)]
    rep = diff_series(rounds)
    assert len(rep["rounds"]) == 2 and len(rep["multichip_rounds"]) == 2
    # consecutive deltas never cross series
    for d in rep["deltas"]:
        assert d["metric"].startswith("multichip.") == \
            d["from"].startswith("mc-")


def test_multichip_ok_flip_flags(tmp_path):
    rounds = load_series([
        _mk_mc(tmp_path, "r01", ok=True),
        _mk_mc(tmp_path, "r02", ok=False),
    ])
    assert not rounds[1].empty  # a failed round is data, not a gap
    rep = diff_series(rounds)
    assert rep["verdict"] == "regression"
    assert any(d["metric"] == "multichip.ok" for d in rep["regressions"])


def test_multichip_skipped_is_a_gap(tmp_path):
    rounds = load_series([
        _mk_mc(tmp_path, "r01"),
        _mk_mc(tmp_path, "r02", skipped=True),
    ])
    rep = diff_series(rounds)
    assert any(g["round"] == "mc-r02" for g in rep["gaps"])
    assert rep["verdict"] == "ok"


def test_diagnostics_metrics_are_structural():
    dg = {"bad_mappings": 0, "retry_exhausted": 0, "collisions": 100,
          "diag_exact": True, "mapping_identical": True,
          "default_path_compiles": 0,
          "tries_histogram": [900, 80, 20, 0, 0]}
    vals = extract_metrics({"diagnostics": dg})
    # raw-compared everywhere: bit-determined by map + tunables
    for name, (v, up, cal_sensitive) in vals.items():
        assert name.startswith("diagnostics.")
        assert not cal_sensitive, name
    assert vals["diagnostics.bad_mappings"] == (0.0, False, False)
    assert vals["diagnostics.tries_max"] == (2.0, False, False)
    assert vals["diagnostics.diag_exact"] == (1.0, True, False)


def test_diagnostics_bad_mappings_from_zero_flags():
    r1 = Round("r01", {"diagnostics": {"bad_mappings": 0}})
    r2 = Round("r02", {"diagnostics": {"bad_mappings": 7}})
    rep = diff_series([r1, r2])
    assert rep["verdict"] == "regression"
    assert any(d["metric"] == "diagnostics.bad_mappings"
               for d in rep["regressions"])


def test_threshold_configurable():
    rounds = [_mk("a", 60000.0, 0.08), _mk("b", 50000.0, 0.08)]  # -17%
    assert diff_series(rounds, threshold=0.10)["verdict"] == "regression"
    assert diff_series(rounds, threshold=0.25)["verdict"] == "ok"


# -- reports ----------------------------------------------------------------

def test_markdown_report_shape():
    rep = diff_series(fixture_rounds())
    md = render_markdown(rep)
    assert "verdict: **regression**" in md
    assert "| r02 | - | - | - | GAP:" in md
    assert "configs.headline.mappings_per_sec" in md
    assert "uncalibrated" in md  # the informational-deltas footnote


def test_json_report_round_trips():
    rep = diff_series(fixture_rounds())
    again = json.loads(json.dumps(rep))
    assert again["verdict"] == "regression"
    assert again["schema_version"] == SCHEMA_VERSION


# -- CLI (subprocess; slow-marked for the tier-1 budget) --------------------

@pytest.mark.slow
def test_cli_over_fixtures_exits_one_on_regression():
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-m", "tools.benchdiff",
         *sorted(str(p) for p in FIXTURES.glob("*.json")),
         "--json", "-"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert out.returncode == 1, out.stderr[-500:]
    rep = json.loads(out.stdout)
    assert rep["verdict"] == "regression"
    assert time.time() - t0 < 60  # pure-JSON tool: no jax import cost
