"""Aux subsystem tests: failure/recovery sim + thrasher (including
degraded-mode placement through the runtime fault points), perf
counters, config layering, leveled logging (SURVEY §5 coverage)."""

import io
import json

import numpy as np
import pytest

from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgPool, PoolType
from ceph_tpu.sim import ClusterSim

pytestmark = pytest.mark.smoke


def _map(pg_num=128):
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=pg_num, pgp_num=pg_num)
    return build_hierarchical(6, 4, pool=pool)


class TestClusterSim:
    def test_failure_moves_little_and_no_failed_target(self):
        m = _map()
        sim = ClusterSim(m, backend="ref")
        rep = sim.fail_osd(5)
        # failed osd never appears in the new mapping
        up, _, _, _ = sim.current[0]
        assert not (up == 5).any()
        # CRUSH property: movement proportional to lost capacity (~1/24)
        assert 0 < rep.moved_fraction < 0.35
        assert rep.degraded_pgs == 0  # enough osds to re-place

    def test_down_not_out_degrades(self):
        m = _map()
        sim = ClusterSim(m, backend="ref")
        rep = sim.fail_osd(5, out=False)  # down but still "in"
        assert rep.degraded_pgs > 0  # holes until marked out

    def test_revival_restores_mapping(self):
        m = _map()
        sim = ClusterSim(m, backend="ref")
        before = {
            ps: list(sim.current[0][0][ps]) for ps in range(128)
        }
        sim.fail_osd(7)
        rep = sim.revive_osd(7)
        after = {ps: list(sim.current[0][0][ps]) for ps in range(128)}
        assert before == after  # CRUSH determinism: full restoration
        assert rep.pgs_remapped > 0

    def test_thrasher_keeps_cluster_mapped(self):
        m = _map(pg_num=64)
        sim = ClusterSim(m, backend="ref")
        reports = sim.thrash(8, rng=np.random.default_rng(3))
        assert len(reports) == 8
        up, _, _, _ = sim.current[0]
        # every PG still has at least one live replica
        from ceph_tpu.crush.types import ITEM_NONE

        for ps in range(64):
            assert any(o != ITEM_NONE for o in up[ps]), ps

    def test_device_loss_degrades_to_identical_mappings(self):
        """Runtime fault point `map_batch`: device loss mid-batch must
        degrade that mapping pass to the host mapper, produce IDENTICAL
        placements (the bit-exactness contract), and record provenance
        (ClusterSim.fallback_events + runtime perf counter)."""
        from ceph_tpu import obs
        from ceph_tpu.runtime import faults

        m_jax, m_ref = _map(pg_num=32), _map(pg_num=32)
        sim = ClusterSim(m_jax, backend="jax")  # healthy jax baseline
        oracle = ClusterSim(m_ref, backend="ref")
        before = obs.perf_dump().get("runtime", {}).get(
            "device_loss_fallbacks", 0)
        faults.arm("map_batch", "lost", "injected transport loss", 1)
        try:
            rep = sim.fail_osd(5)
        finally:
            faults.disarm_all()
        rep_ref = oracle.fail_osd(5)
        # degraded pass == healthy host pass, PG for PG
        for j in range(4):
            assert np.array_equal(sim.current[0][j], oracle.current[0][j])
        assert rep.pgs_remapped == rep_ref.pgs_remapped
        assert rep.moved_fraction == rep_ref.moved_fraction
        # the descent was recorded, not silent
        assert len(sim.fallback_events) == 1
        assert "injected transport loss" in sim.fallback_events[0]
        prov = sim.provenance()
        assert prov["backend"] == "jax"
        assert prov["device_loss_fallbacks"] == 1
        after = obs.perf_dump()["runtime"]["device_loss_fallbacks"]
        assert after == before + 1

    def test_thrasher_through_device_loss_stays_mapped(self):
        """OSDThrasher + injected device losses: every revive/fail epoch
        that loses the device degrades and the cluster never unmaps."""
        from ceph_tpu.crush.types import ITEM_NONE
        from ceph_tpu.runtime import faults

        m = _map(pg_num=32)
        sim = ClusterSim(m, backend="jax")
        faults.arm("map_batch", "lost", "thrash-loss", 2)
        try:
            reports = sim.thrash(3, rng=np.random.default_rng(7))
        finally:
            faults.disarm_all()
        assert len(reports) == 3
        assert len(sim.fallback_events) == 2  # both losses degraded
        up, _, _, _ = sim.current[0]
        for ps in range(32):
            assert any(o != ITEM_NONE for o in up[ps]), ps

    def test_pg_temp_overrides_acting(self):
        from ceph_tpu.osd.types import PgId

        m = _map(pg_num=32)
        sim = ClusterSim(m, backend="ref")
        up0 = [o for o in sim.current[0][0][0] if o != 0x7FFFFFFF]
        tmp = [o for o in range(3)]
        sim.set_pg_temp(PgId(0, 0), tmp, primary=tmp[1])
        _, _, acting, actp = sim.current[0]
        assert list(acting[0][:3]) == tmp
        assert actp[0] == tmp[1]


class TestPerfCounters:
    def test_counters_and_dump(self):
        from ceph_tpu.utils import perf_counters as pc

        pc.reset()
        log = pc.logger_for("crush")
        log.add_u64("mappings", "total mappings")
        log.add_time_avg("map_latency")
        log.add_histogram("batch_size", [10, 100, 1000])
        log.inc("mappings", 42)
        with log.time("map_latency"):
            pass
        log.observe("batch_size", 50)
        log.observe("batch_size", 5000)
        d = pc.perf_dump()
        assert d["crush"]["mappings"] == 42
        assert d["crush"]["map_latency"]["avgcount"] == 1
        assert d["crush"]["batch_size"]["buckets"] == [0, 1, 0, 1]
        json.dumps(d)  # must be serializable

    def test_registry_reuse(self):
        from ceph_tpu.utils import perf_counters as pc

        pc.reset()
        a = pc.logger_for("x")
        b = pc.logger_for("x")
        assert a is b


class TestConfig:
    def test_defaults_env_file_layering(self, tmp_path, monkeypatch):
        from ceph_tpu.utils.config import Config

        cfg = Config(env=False)
        assert cfg.get("osd_pool_default_size") == 3
        f = tmp_path / "ceph_tpu.conf"
        f.write_text("osd_pool_default_size = 5\n# comment\n")
        cfg = Config(conf_file=str(f), env=False)
        assert cfg.get("osd_pool_default_size") == 5
        monkeypatch.setenv("CEPH_TPU_OSD_POOL_DEFAULT_SIZE", "7")
        cfg = Config(conf_file=str(f), env=True)
        assert cfg.get("osd_pool_default_size") == 7  # env beats file

    def test_validation_and_observers(self):
        from ceph_tpu.utils.config import Config, ConfigError

        cfg = Config(env=False)
        with pytest.raises(ConfigError):
            cfg.set_val("crush_backend", "gpu")
        with pytest.raises(ConfigError):
            cfg.set_val("osd_pool_default_size", 0)
        with pytest.raises(ConfigError):
            cfg.get("bogus")
        seen = []
        cfg.add_observer(lambda k, v: seen.append((k, v)))
        cfg.set_val("upmap_max_deviation", 3)
        assert seen == [("upmap_max_deviation", 3)]


class TestDout:
    def test_levels_and_subsys(self):
        from ceph_tpu.utils import dout

        buf = io.StringIO()
        dout.set_output(buf)
        log = dout.subsys_logger("testsub")
        dout.set_subsys_level("testsub", 5)
        log(1, "important")
        log(5, "normal")
        log(10, "hidden")
        out = buf.getvalue()
        assert "important" in out and "normal" in out
        assert "hidden" not in out
        assert log.enabled(5) and not log.enabled(6)


class TestCrc32cEngines:
    def test_fast_and_native_match_scalar(self):
        import os

        from ceph_tpu.utils.crc32c import (
            _crc_bytes,
            _load_native,
            crc32c,
            crc32c_fast,
        )

        rng_data = os.urandom(10_007)  # odd size: exercises the tail loop
        ref = _crc_bytes(rng_data, 0xFFFFFFFF)
        assert crc32c_fast(rng_data) == ref
        assert crc32c(rng_data) == ref
        lib = _load_native()
        if lib is not None:
            assert lib.ceph_tpu_crc32c(0xFFFFFFFF, rng_data,
                                       len(rng_data)) == ref
        # streaming chain equivalence
        assert crc32c(rng_data[5000:], crc32c(rng_data[:5000])) == ref
