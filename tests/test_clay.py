"""Clay code tests — mirrors the reference's TestErasureCodeClay grid
(reference src/test/erasure-code/TestErasureCodeClay.cc): roundtrip over
(k,m,d) configs incl. shortened (nu>0) codes, every erasure pattern up to m,
and the minimum-bandwidth single-chunk repair path."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec import create_erasure_code

CONFIGS = [
    # (k, m, d): d=k+m-1 (classic) and d<k+m-1 (nu>0 shortened)
    (2, 2, 3),
    (3, 2, 4),
    (4, 2, 5),
    (4, 3, 6),
    (4, 2, 4),  # nu > 0
    (8, 4, 11),
]


def _code(k, m, d):
    return create_erasure_code(
        {"plugin": "clay", "k": k, "m": m, "d": d}
    )


class TestClayGeometry:
    def test_params(self):
        c = _code(8, 4, 11)
        assert (c.q, c.t, c.nu) == (4, 3, 0)
        assert c.sub_chunk_no == 64
        assert c.get_sub_chunk_count() == 64

    def test_shortened(self):
        c = _code(4, 2, 4)
        # q=1? d-k+1 = 1 -> degenerate; recompute: q=1,t=6,sub=1
        assert c.q == 1 and c.sub_chunk_no == 1

    def test_chunk_size_multiple_of_subchunks(self):
        c = _code(4, 3, 6)  # q=3, k+m=7, nu=2, t=3, sub=27
        assert (c.q, c.nu, c.t, c.sub_chunk_no) == (3, 2, 3, 27)
        cs = c.get_chunk_size(123456)
        assert cs % c.sub_chunk_no == 0

    def test_bad_d(self):
        from ceph_tpu.ec.interface import ErasureCodeProfileError

        with pytest.raises(ErasureCodeProfileError):
            _code(4, 2, 7)


class TestClayRoundtrip:
    @pytest.mark.parametrize("k,m,d", CONFIGS)
    def test_all_erasure_patterns(self, k, m, d, rng):
        code = _code(k, m, d)
        n = k + m
        nbytes = 3511
        data = rng.integers(0, 256, nbytes).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        cs = code.get_chunk_size(nbytes)
        assert all(len(encoded[i]) == cs for i in encoded)
        max_patterns = 40
        pats = [
            p
            for e in range(1, m + 1)
            for p in itertools.combinations(range(n), e)
        ]
        if len(pats) > max_patterns:
            idx = rng.choice(len(pats), max_patterns, replace=False)
            pats = [pats[int(j)] for j in idx]
        for lost in pats:
            have = {i: encoded[i] for i in range(n) if i not in lost}
            got = code.decode(set(range(k)), dict(have), cs)
            out = b"".join(got[i].tobytes() for i in range(k))
            assert out[:nbytes] == data, f"lost={lost}"

    def test_parity_deterministic(self, rng):
        code = _code(4, 2, 5)
        data = rng.integers(0, 256, (4, code.get_chunk_size(4 * 100) )).astype(np.uint8)
        e1 = code.encode_chunks(data)
        e2 = code.encode_chunks(data)
        assert np.array_equal(e1, e2)


class TestClayRepair:
    @pytest.mark.parametrize("k,m,d", [(4, 2, 5), (4, 3, 6), (8, 4, 11)])
    @pytest.mark.parametrize("lost_kind", ["data", "parity"])
    def test_single_chunk_repair_bandwidth(self, k, m, d, lost_kind, rng):
        code = _code(k, m, d)
        n = k + m
        nbytes = 2048
        data = rng.integers(0, 256, nbytes).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        cs = code.get_chunk_size(nbytes)
        lost = 1 if lost_kind == "data" else k + 1
        avail = set(range(n)) - {lost}

        assert code.is_repair({lost}, avail)
        minimum = code.minimum_to_repair({lost}, avail)
        assert len(minimum) == d
        # each helper sends exactly 1/q of its sub-chunks
        frac = sum(c for _, c in next(iter(minimum.values())))
        assert frac == code.sub_chunk_no // code.q

        sc = cs // code.sub_chunk_no
        helpers = {}
        for h, runs in minimum.items():
            arr = np.frombuffer(
                encoded[h].tobytes(), np.uint8
            ).reshape(code.sub_chunk_no, sc)
            planes = [
                z for ind, cnt in runs for z in range(ind, ind + cnt)
            ]
            helpers[h] = arr[planes].reshape(-1)  # ONLY repair sub-chunks

        got = code.repair({lost}, helpers, cs)
        assert np.array_equal(
            np.frombuffer(got[lost].tobytes(), np.uint8),
            np.frombuffer(encoded[lost].tobytes(), np.uint8),
        )

    def test_decode_routes_to_repair(self, rng):
        code = _code(4, 2, 5)
        n = 6
        data = rng.integers(0, 256, 1024).astype(np.uint8).tobytes()
        encoded = code.encode(set(range(n)), data)
        cs = code.get_chunk_size(1024)
        lost = 0
        minimum = code.minimum_to_repair({lost}, set(range(1, n)))
        sc = cs // code.sub_chunk_no
        helpers = {}
        for h, runs in minimum.items():
            arr = np.frombuffer(encoded[h].tobytes(), np.uint8).reshape(
                code.sub_chunk_no, sc
            )
            planes = [
                z for ind, cnt in runs for z in range(ind, ind + cnt)
            ]
            helpers[h] = arr[planes].reshape(-1)
        got = code.decode({lost}, helpers, cs)
        assert np.array_equal(
            np.frombuffer(got[lost].tobytes(), np.uint8),
            np.frombuffer(encoded[lost].tobytes(), np.uint8),
        )

    def test_minimum_to_decode_falls_back(self, rng):
        code = _code(4, 2, 5)
        # two erasures -> not a repair, base first-k rule applies
        got = code.minimum_to_decode({0, 1}, {2, 3, 4, 5})
        assert got == {2, 3, 4, 5}
