"""Binary OSDMap codec tests.

The strongest oracle available in-tree: a real production cluster's
osdmap (epoch 2982809, 1476 OSDs) shipped as a compressor test fixture in
the reference (src/test/compressor/osdmaps/osdmap.2982809).  We require
full-fidelity decode (CRC verified) and byte-exact re-encode, then drive
the decoded map through the placement stack.
"""

import os

import numpy as np
import pytest

from ceph_tpu.osd.codec import (
    decode_osdmap,
    encode_osdmap,
    looks_like_osdmap,
)
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgId, PgPool, PoolType

FIXTURE = "/root/reference/src/test/compressor/osdmaps/osdmap.2982809"


@pytest.fixture(scope="module")
def fixture_bytes():
    if not os.path.exists(FIXTURE):
        pytest.skip("reference osdmap fixture unavailable")
    with open(FIXTURE, "rb") as f:
        return f.read()


@pytest.fixture(scope="module")
def fixture_map(fixture_bytes):
    return decode_osdmap(fixture_bytes)


def test_detect(fixture_bytes):
    assert looks_like_osdmap(fixture_bytes)
    assert not looks_like_osdmap(b"not an osdmap at all....")


def test_decode_fields(fixture_map):
    m = fixture_map
    assert m.epoch == 2982809
    assert m.max_osd == 1476
    assert sorted(m.pools) == [4, 5, 75, 78]
    assert m.pool_name[4] == "volumes"
    assert m.pools[4].size == 3
    assert m.pools[4].pg_num == 8192
    assert m.pools[75].erasure_code_profile == "critical"
    assert len(m.osd_state) == 1476
    assert len(m.osd_weight) == 1476
    assert len(m.pg_upmap_items) == 4935
    assert len(m.crush.buckets) == 144
    assert len(m.crush.rules) == 5


def test_byte_exact_roundtrip(fixture_bytes, fixture_map):
    assert encode_osdmap(fixture_map) == fixture_bytes


def test_crc_rejects_corruption(fixture_bytes):
    bad = bytearray(fixture_bytes)
    bad[1000] ^= 0xFF
    with pytest.raises(Exception, match="crc"):
        decode_osdmap(bytes(bad))


def test_real_map_places(fixture_map):
    """The decoded production map drives the placement pipeline: every PG
    of the 3x pool maps to 3 distinct up OSDs."""
    m = fixture_map
    for seed in range(32):
        up, upp, acting, actp = m.pg_to_up_acting_osds(PgId(4, seed))
        assert len(up) == 3, (seed, up)
        assert len(set(up)) == 3
        assert all(0 <= o < m.max_osd for o in up)
        assert upp == up[0]


def test_real_map_batched_matches_oracle(fixture_map):
    """The vmapped TPU pipeline agrees with the host oracle on the real
    cluster map (hammer-era tunables: vary_r=4, stable=0 — exercises the
    loop kernel path)."""
    from ceph_tpu.osd.pipeline_jax import PoolMapper

    m = fixture_map
    pm = PoolMapper(m, 4)
    n = 64
    up, upp, acting, actp = pm.map_batch(np.arange(n, dtype=np.uint32))
    for seed in range(n):
        w_up, w_upp, w_act, w_actp = m.pg_to_up_acting_osds(PgId(4, seed))
        got = [o for o in up[seed] if o != 0x7FFFFFFF]
        assert got == w_up, (seed, got, w_up)
        assert upp[seed] == w_upp


def test_self_built_roundtrip():
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=128, pgp_num=128)
    m = build_hierarchical(8, 4, pool=pool)
    m.pg_upmap_items[PgId(0, 3)] = [(1, 2)]
    m.pg_temp[PgId(0, 5)] = [7, 8, 9]
    m.primary_temp[PgId(0, 6)] = 11
    enc = encode_osdmap(m)
    assert looks_like_osdmap(enc)
    m2 = decode_osdmap(enc)
    assert m2.max_osd == m.max_osd
    assert m2.epoch == m.epoch
    assert m2.pools[0].pg_num == 128
    assert m2.pg_upmap_items == {PgId(0, 3): [(1, 2)]}
    assert m2.pg_temp == {PgId(0, 5): [7, 8, 9]}
    assert m2.primary_temp == {PgId(0, 6): 11}
    assert m2.osd_weight == m.osd_weight
    # stable re-encode
    assert encode_osdmap(m2) == enc
    # placement agrees
    for seed in range(16):
        assert (
            m.pg_to_up_acting_osds(PgId(0, seed))
            == m2.pg_to_up_acting_osds(PgId(0, seed))
        )
