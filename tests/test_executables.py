"""Executable registry + quantile counters: records register at cache
miss, call accounting splits compile from dispatch, JAX cost analysis is
lazy/cached and budget-bounded, the dumps and Prometheus gauges keep
their shape, the hot caches (_PIPE_CACHE) really register, and the
quantile estimator is sane on known distributions."""

from __future__ import annotations

import json

import numpy as np
import pytest

from ceph_tpu import obs
from ceph_tpu.obs import executables, quantiles


# -- quantile estimator (pure math) ----------------------------------------

def test_estimate_interpolates_within_bucket():
    bounds = [1.0, 10.0, 100.0]
    # 10 observations, all in (1, 10]: p50 lands mid-bucket
    p50 = quantiles.estimate(bounds, [0, 10, 0, 0], 0.5)
    assert 1.0 < p50 < 10.0
    # log-spaced buckets -> geometric midpoint, not arithmetic
    assert p50 == pytest.approx(1.0 * (10.0 / 1.0) ** 0.5)


def test_estimate_respects_min_max():
    bounds = [1.0, 10.0]
    assert quantiles.estimate(bounds, [5, 0, 0], 0.5, vmin=0.4,
                              vmax=0.6) <= 1.0
    # overflow bucket clamps to the observed max
    v = quantiles.estimate(bounds, [0, 0, 4], 0.99, vmax=42.0)
    assert 10.0 < v <= 42.0


def test_estimate_empty_histogram_is_zero():
    assert quantiles.estimate([1.0], [0, 0], 0.5) == 0.0


def test_quantile_counter_dump_and_reset():
    L = obs.logger_for("t_exec_q")
    L.add_quantile("lat", "latencies")
    rng = np.random.default_rng(1)
    vals = rng.lognormal(mean=np.log(1e-3), sigma=0.6, size=3000)
    for v in vals:
        L.observe("lat", float(v))
    d = obs.perf_dump()["t_exec_q"]["lat"]
    assert d["count"] == 3000
    assert d["min"] <= d["p50"] <= d["p90"] <= d["p99"] <= d["max"]
    # the estimator tracks the true quantiles within a bucket ratio
    true = np.quantile(vals, [0.5, 0.99])
    assert d["p50"] == pytest.approx(true[0], rel=0.8)
    assert d["p99"] == pytest.approx(true[1], rel=0.8)
    assert obs.perf_schema()["t_exec_q"]["lat"]["type"] == "quantile"
    from ceph_tpu.utils import perf_counters as pc
    pc.reset_values()
    d = obs.perf_dump()["t_exec_q"]["lat"]
    assert d["count"] == 0 and d["p50"] == 0.0 and d["min"] == 0.0


def test_time_context_manager_feeds_quantile():
    L = obs.logger_for("t_exec_q2")
    L.add_quantile("span_t", "timed spans")
    with L.time("span_t"):
        pass
    d = obs.perf_dump()["t_exec_q2"]["span_t"]
    assert d["count"] == 1 and d["p50"] > 0


# -- registry records -------------------------------------------------------

def _small_jit():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return (x.astype(jnp.uint32) * 3 + 1).sum()

    return f


def test_register_dedupes_on_structural_key():
    key = ("t_exec", "dedupe", 1)
    a = executables.register("ec", "xor", key)
    b = executables.register("ec", "xor", key)
    assert a is b


def test_wrap_books_compile_then_dispatch_and_analyzes():
    import jax.numpy as jnp

    fn = executables.wrap(_small_jit(), "ec", "xor",
                          ("t_exec", "wrapped", 2))
    x = jnp.ones((4, 1024), jnp.uint8)
    fn(x)
    fn(x)
    fn(x)
    rec = fn.rec
    assert rec.compiles == 1 and rec.hits == 2
    assert rec.compile_seconds > 0
    cost = rec.analyze()
    assert cost and "error" not in cost
    assert cost["flops"] > 0 and cost["bytes_accessed"] > 0
    assert rec.analyze() is cost  # cached, not recomputed
    e = rec.summary(analyze=True)
    assert e["cache"] == "ec" and e["kind"] == "xor"
    assert e["roofline"]["dispatch_avg_s"] >= 0


def test_wrap_new_shape_is_a_new_compile():
    import jax.numpy as jnp

    fn = executables.wrap(_small_jit(), "ec", "xor",
                          ("t_exec", "shapes", 3))
    fn(jnp.ones((2, 64), jnp.uint8))
    fn(jnp.ones((2, 128), jnp.uint8))  # retrace: booked as compile
    assert fn.rec.compiles == 2 and fn.rec.hits == 0


def test_dump_shape_and_cached_cost_rides_cheap_dumps():
    import jax.numpy as jnp

    fn = executables.wrap(_small_jit(), "ec", "xor",
                          ("t_exec", "dump", 4))
    fn(jnp.ones((2, 64), jnp.uint8))
    fn(jnp.ones((2, 64), jnp.uint8))
    d = executables.dump(analyze=False)
    assert json.loads(json.dumps(d)) == d  # JSON-clean
    assert d["by_cache"].get("ec", 0) >= 1
    e = [x for x in d["entries"] if x["key"] == fn.rec.key_digest][0]
    for field in ("cache", "kind", "cache_key", "compiles",
                  "compile_seconds", "hits", "last_use_unix", "cost"):
        assert field in e
    # analyze=False never computed a cost for a fresh record
    assert e["cost"] is None
    # after a targeted analyze, the cached cost (and roofline) ride
    # every later no-work dump — the admin-socket perf-dump path
    cost = fn.rec.analyze()
    assert cost and cost["flops"] > 0
    e2 = [x for x in executables.dump(analyze=False)["entries"]
          if x["key"] == fn.rec.key_digest][0]
    assert e2["cost"]["flops"] > 0
    assert "dispatch_avg_s" in e2["roofline"]
    # memory analysis is opt-in (it compiles): "full" adds peak temp
    full = fn.rec.analyze(memory=True)
    assert "peak_temp_bytes" in full


def test_jitaccount_feeds_exec_record():
    import jax.numpy as jnp

    raw = _small_jit()
    rec = executables.register("bench", "stats", ("t_exec", "acct", 5),
                               fn=raw)
    acct = obs.JitAccount(raw, obs.logger_for("t_exec_acct"), "k",
                          exec_record=rec)
    x = jnp.ones((2, 32), jnp.uint8)
    acct(x)
    acct(x)
    assert rec.compiles == 1 and rec.hits == 1
    assert rec.analyze()["flops"] > 0


def test_prometheus_gauges_shape():
    import jax.numpy as jnp

    fn = executables.wrap(_small_jit(), "ec", "xor",
                          ("t_exec", "gauges", 6))
    fn(jnp.ones((2, 64), jnp.uint8))
    text = executables.prometheus_gauges()
    assert '# TYPE ceph_tpu_executables_registered gauge' in text
    assert 'ceph_tpu_executables_registered{cache="ec"}' in text
    assert text.endswith("\n")


# -- the hot caches really register ----------------------------------------

def test_pipe_cache_registers_and_quantiles_advance():
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.pipeline_jax import PoolMapper
    from ceph_tpu.osd.types import PgPool, PoolType

    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=97, pgp_num=97)
    m = build_hierarchical(2, 8, n_rack=1, pool=pool)
    before = obs.perf_dump()["pipeline"]["map_block_seconds"]["count"]
    pm = PoolMapper(m, 0, overlays=False)
    pm.map_batch(np.arange(97, dtype=np.uint32))  # cold: compile only
    mb = obs.perf_dump()["pipeline"]["map_block_seconds"]
    assert mb["count"] == before  # cold calls never pollute the tail
    pm.map_batch(np.arange(97, dtype=np.uint32))  # warm dispatch
    d = executables.dump(analyze=False)
    assert any(e["cache"] == "pipe" and e["kind"] == "fast"
               for e in d["entries"])
    # the map_block dispatch quantile advanced and estimates a tail
    mb = obs.perf_dump()["pipeline"]["map_block_seconds"]
    assert mb["count"] > before
    assert mb["p99"] >= mb["p50"] > 0
    # THE pipe entry this mapper just dispatched cost-analyzes (the
    # selftest acceptance path) — targeted, not a whole-registry sweep
    # (a full test session registers dozens of big kernels)
    rec = max(executables.records("pipe", "fast"),
              key=lambda r: r.last_use)
    cost = rec.analyze()
    assert cost and "error" not in cost and cost["flops"] > 0


def test_memory_analysis_attempted_at_most_once():
    import jax.numpy as jnp

    fn = executables.wrap(_small_jit(), "ec", "xor",
                          ("t_exec", "memonce", 7))
    fn(jnp.ones((2, 32), jnp.uint8))
    cost = fn.rec.analyze(memory=True)
    assert fn.rec._mem_tried
    # even if the backend yielded no memory stats (simulated by
    # dropping the key), the attempt counts: a "full" dump must not
    # re-pay the lower+compile forever
    cost.pop("peak_temp_bytes", None)
    assert not fn.rec.analysis_pending(memory=True)
    assert fn.rec.analyze(memory=True) is cost


def test_failed_memory_pass_keeps_good_cached_cost():
    import jax.numpy as jnp

    fn = executables.wrap(_small_jit(), "ec", "xor",
                          ("t_exec", "clobber", 9))
    fn(jnp.ones((2, 32), jnp.uint8))
    cost = fn.rec.analyze()
    assert cost["flops"] > 0

    class _Wedged:
        def lower(self, *a, **kw):
            raise RuntimeError("device wedged")

    fn.rec._fn = _Wedged()  # the later "full" pass hits a dead device
    out = fn.rec.analyze(memory=True)
    assert out["flops"] > 0 and "error" not in out  # good data kept
    assert fn.rec._mem_tried  # ...and the attempt still counted


def test_dump_budget_bounds_work_before_it_starts():
    import jax.numpy as jnp

    fn = executables.wrap(_small_jit(), "ec", "xor",
                          ("t_exec", "budget", 8))
    fn(jnp.ones((2, 32), jnp.uint8))
    # pretend this executable took a big-kernel compile: the estimated
    # re-lower cost exceeds the whole budget, so a prompt diagnostic
    # dump must skip it rather than stall on it
    fn.rec.compile_seconds = 60.0
    e = [x for x in executables.dump(analyze=True, budget_s=5.0)["entries"]
         if x["key"] == fn.rec.key_digest][0]
    assert e["cost"] is None
    # cached results are served for free regardless of the estimate
    fn.rec.analyze()
    e = [x for x in executables.dump(analyze=True, budget_s=5.0)["entries"]
         if x["key"] == fn.rec.key_digest][0]
    assert e["cost"] and e["cost"]["flops"] > 0


def test_admin_socket_commands_expose_registry():
    from ceph_tpu.obs.admin_socket import handle_command
    from ceph_tpu.obs.prometheus import prometheus_text

    d = json.loads(handle_command("perf dump"))
    assert "executables" in d and "entries" in d["executables"]
    c = json.loads(handle_command("cache dump"))
    assert "entries" in c and "by_cache" in c
    assert "cache dump" in json.loads(handle_command("help"))
    # a SAVED perf-dump reply renders offline: the embedded executables
    # section (dicts/lists, not counters) must be skipped, not guessed
    # into a summary shape that KeyErrors
    text = prometheus_text(d, schema={})
    assert "ceph_tpu_pipeline" in text or "ceph_tpu_ec" in text
    # the registry section has its own gauge exposition; its scalar
    # fields must not leak bogus counter series into the render
    assert "ceph_tpu_executables_cost_analyzed" not in text
