"""Recovery data plane + client workload generator
(ceph_tpu.recovery.queue, ceph_tpu.sim.workload, lifetime wiring).

Tier-1 keeps everything on the host ("ref") backend and hand-sized
inputs — the numpy executors ARE the authoritative formulas, and the
device path's bit-exactness is already proven in tier-1 by the TINY
jax==ref digest test in test_lifetime.py (which now runs the queue
model).  The direct jnp-vs-numpy kernel comparison and the at-scale
queue+workload jax run ride the slow tier (tier-1 budget is nearly
spent).
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.recovery import (
    RecoveryQueue,
    drain_pool_np,
    stream_bytes_per_epoch,
)
from ceph_tpu.runtime import faults
from ceph_tpu.sim.lifetime import LifetimeSim, Scenario
from ceph_tpu.sim.workload import workload_pool_np

TINY_WL = ("epochs=8,seed=5,hosts=6,osds_per_host=2,racks=2,pgs=32,"
           "ec=2+2,ec_pgs=16,chunk=256,balance_every=4,"
           "spotcheck_every=0,checkpoint_every=0,workload=1")


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm_all()


# ------------------------------------------------------------ drain model


def test_stream_rate_pipelined_vs_serial():
    """EC repair chains encode->transfer: serial stages sum (harmonic
    rate), RapidRAID-style pipelining runs at the bottleneck stage —
    strictly faster whenever both stages are finite."""
    t_us = 30_000_000  # 30s epoch
    xfer_only = stream_bytes_per_epoch(100.0, t_us)
    assert xfer_only == 100_000_000 * 30
    serial = stream_bytes_per_epoch(100.0, t_us, ec_gbps=1.6)
    pipelined = stream_bytes_per_epoch(100.0, t_us, ec_gbps=1.6,
                                       pipelined=True)
    # serial = enc*xfer/(enc+xfer) < min(enc, xfer) = pipelined
    assert serial < pipelined <= xfer_only
    assert pipelined == xfer_only  # transfer is the bottleneck here


def test_drain_hand_computed_two_osds():
    """The hand-computable 2-OSD case: one PG with 5 GB of backlog on
    osd.0, one clean PG on osd.1.  One stream at 1 GB/epoch, ample
    capacity: exactly 1 GB drains, 4 GB carries, conservation holds."""
    rows = np.array([[0, 1], [1, 0]], np.int32)
    backlog = np.array([5_000_000_000, 0], np.int64)
    cap = np.full(4, 10_000_000_000, np.int64)
    slots = np.full(4, 1, np.int64)
    b, cap2, slots2, s = drain_pool_np(
        backlog, None, rows, cap, slots, shard_bytes=1,
        stream_bytes=1_000_000_000, t_us=30_000_000, n=2, size=2,
        tol=1)
    assert b.tolist() == [4_000_000_000, 0]
    assert s["enqueued"] == 0
    assert s["drained"] == 1_000_000_000
    assert s["backlog"] == 4_000_000_000
    assert s["queued"] == 1 and s["completed"] == 0 and s["streams"] == 1
    assert int(cap2[0]) == 9_000_000_000  # osd.0 paid the drain
    assert int(slots2[0]) == 0 and int(slots2[1]) == 1
    # conservation: prev + enqueued == drained + backlog
    assert 5_000_000_000 + s["enqueued"] == s["drained"] + s["backlog"]

    # enqueue path: 2 moved lanes on PG 1 queue 2*shard_bytes
    b2, _, _, s2 = drain_pool_np(
        np.zeros(2, np.int64), np.array([0, 2], np.int64), rows,
        cap.copy(), slots.copy(), shard_bytes=500_000_000,
        stream_bytes=1_000_000_000, t_us=30_000_000, n=2, size=2,
        tol=1)
    assert s2["enqueued"] == 1_000_000_000
    # fully drained within the epoch: completion counted
    assert s2["drained"] == 1_000_000_000 and s2["completed"] == 1
    assert b2.tolist() == [0, 0]


def test_drain_at_risk_priority_and_slot_limit():
    """Two PGs queue on the same OSD with ONE slot: the at-risk PG
    (class 0) takes the slot and the whole allotment; the healthy PG
    waits.  The at-risk PG's completion mid-epoch books a partial risk
    window (backlog/share of the epoch)."""
    # PG 0 at risk (only 1 of 3 lanes alive, tol 1), PG 1 healthy
    rows = np.array([[0, -1, -1], [0, 1, 2]], np.int32)
    backlog = np.array([1_000_000_000, 2_000_000_000], np.int64)
    cap = np.full(4, 10_000_000_000, np.int64)
    slots = np.full(4, 1, np.int64)
    t_us = 30_000_000
    b, _, _, s = drain_pool_np(
        backlog, None, rows, cap, slots, shard_bytes=1,
        stream_bytes=4_000_000_000, t_us=t_us, n=2, size=3, tol=1)
    assert b.tolist() == [0, 2_000_000_000]  # at-risk drained first
    assert s["completed"] == 1 and s["streams"] == 1
    # risk window: 1 GB / 4 GB-per-epoch share -> a quarter epoch
    assert s["risk_us"] == (1_000_000_000 * t_us) // 4_000_000_000


def test_drain_at_risk_without_backlog_accrues_whole_epoch():
    """An at-risk PG with nothing queued (down-not-out OSDs CRUSH has
    not remapped around) stays at risk the whole epoch."""
    rows = np.array([[0, -1, -1]], np.int32)
    _, _, _, s = drain_pool_np(
        np.zeros(1, np.int64), None, rows,
        np.full(4, 10 ** 10, np.int64), np.full(4, 2, np.int64),
        shard_bytes=1, stream_bytes=10 ** 9, t_us=30_000_000, n=1,
        size=3, tol=1)
    assert s["risk_us"] == 30_000_000
    assert s["drained"] == 0 and s["queued"] == 0


# ------------------------------------------------------- queue vs flat A/B


def test_queue_vs_flat_ab_and_flat_floor():
    """The A/B: the flat model's epoch duration follows the legacy
    one-division formula (silently flooring sub-interval drains); the
    queue model keeps fixed intervals and carries the remainder as
    backlog.  Same scenario, different models, different digests —
    and spec() pins the model."""
    base = ("epochs=6,seed=3,hosts=6,osds_per_host=2,racks=2,pgs=32,"
            "ec=,size=3,balance_every=0,spotcheck_every=0,"
            "checkpoint_every=0,p_flap=0,p_death=1.0,p_remove=0,"
            "p_host_outage=0,p_rack_outage=0,p_reweight=0,p_pg_temp=0,"
            "p_pool_create=0,p_split=0,p_expand=0,interval_s=10,"
            "recovery_mbps=50,pg_gb=1.0")
    flat = LifetimeSim(Scenario.parse(base + ",recovery=flat"),
                       backend="ref")
    fout = flat.run()
    # legacy formula replay: every epoch >= interval_s, and an epoch
    # that moved shards longer than the interval stretched to
    # moved_bytes / rate
    assert fout["sim_seconds"] >= 6 * 10
    queue = LifetimeSim(Scenario.parse(base + ",recovery=queue"),
                        backend="ref")
    qout = queue.run()
    # fixed control-plane intervals: the queue run's clock is exact
    assert qout["sim_seconds"] == 6 * 10
    assert qout["digest"] != fout["digest"]
    rec = qout["recovery"]
    assert rec["model"] == "queue"
    # deaths moved shards: bytes were enqueued, conserved, and (at
    # 50 MB/s against 1 GB PGs) a backlog was actually observed
    assert rec["enqueued_gb"] > 0
    assert rec["backlog_peak_gb"] > 0
    assert rec["conservation_violations"] == 0
    assert qout["invariant_violations"] == 0
    assert "recovery=queue" in qout["scenario"]
    assert "recovery=flat" in fout["scenario"]
    assert fout["recovery"] is None  # flat run has no queue section


def test_conservation_negative_control():
    """A drain that loses bytes (tampered scalars) must surface as a
    sim invariant violation and the recovery counter."""
    sc = Scenario.parse(
        "epochs=2,seed=3,hosts=4,osds_per_host=2,racks=2,pgs=16,ec=,"
        "size=3,balance_every=0,spotcheck_every=0,checkpoint_every=0")
    sim = LifetimeSim(sc, backend="ref")

    def corrupt(pid, scal):
        scal = dict(scal)
        scal["drained"] += 7  # bytes from nowhere
        return scal

    sim.recovery_corrupt_hook = corrupt
    out = sim.run()
    assert out["epochs"] == 2  # survived, did not abort
    assert out["invariant_violations"] > 0
    assert any("conservation" in v for v in out["violations"])
    assert out["recovery"]["conservation_violations"] > 0


def test_recovery_step_fault_degrades_digest_unchanged():
    """An armed `recovery_step` device loss degrades the drain to the
    host mirror mid-run: fallback recorded, digest unchanged."""
    sc = Scenario.parse(
        "epochs=5,seed=4,hosts=6,osds_per_host=2,racks=2,pgs=32,ec=,"
        "size=3,balance_every=0,spotcheck_every=0,checkpoint_every=0")
    clean = LifetimeSim(sc, backend="ref").run()
    faults.configure("recovery_step.3=lost:chaos x1")
    sim = LifetimeSim(sc, backend="ref")
    out = sim.run()
    faults.disarm_all()
    assert out["digest"] == clean["digest"]
    assert out["recovery"]["fallback_epochs"] == 1
    assert out["provenance"]["device_loss_fallbacks"] >= 1


# --------------------------------------------------------------- workload


def test_workload_pool_np_hand_computed():
    """Traffic formula on a hand case: degraded reads, at-risk hits,
    backlog hits, per-OSD client bytes (reads -> primary, writes -> all
    live lanes)."""
    rows = np.array([
        [0, 1, 2],     # healthy
        [1, -1, -1],   # degraded AND at risk (1 of 3, tol 1)
        [-1, -1, -1],  # dead: unserved
    ], np.int32)
    backlog = np.array([10, 0, 0], np.int64)
    seeds = np.array([0, 1, 2, 0], np.int64)
    read = np.array([True, True, True, False])
    client, s = workload_pool_np(
        rows, backlog, seeds, read, wq=5, obj_bytes=100, DV=8,
        size=3, tol=1)
    assert s["requests"] == 20 and s["reads"] == 15 and s["writes"] == 5
    assert s["degraded_reads"] == 5   # the read on PG 1
    assert s["at_risk_hits"] == 10    # PGs 1 AND 2 below tolerance
    assert s["backlog_hits"] == 10    # both PG-0 requests
    assert s["unserved"] == 5         # PG 2
    # osd.0: read primary on PG 0 + write lane on PG 0 = 2 * 100 * 5;
    # osd.1: primary read on PG 1 + write lane = 1000; osd.2: write lane
    assert client[:3].tolist() == [1000, 1000, 500]
    assert int(client.sum()) == 2500


def test_workload_determinism_and_seed_divergence():
    a = LifetimeSim(Scenario.parse(TINY_WL), backend="ref").run()
    b = LifetimeSim(Scenario.parse(TINY_WL), backend="ref").run()
    assert a["digest"] == b["digest"]
    assert a["workload"] == b["workload"]
    c = LifetimeSim(Scenario.parse(TINY_WL + ",seed=6"),
                    backend="ref").run()
    assert c["digest"] != a["digest"]
    # the generator actually served traffic and saw the chaos
    assert a["workload"]["requests"] > 0
    assert a["workload"]["served_qps"] > 0
    assert a["pareto"]["served_qps"] == a["workload"]["served_qps"]


def test_workload_digest_segments_only_when_enabled():
    """Turning the generator on must change the digest (new |W
    segments); the workload-off run chains the legacy lines."""
    base = TINY_WL.replace(",workload=1", "")
    off = LifetimeSim(Scenario.parse(base), backend="ref").run()
    on = LifetimeSim(Scenario.parse(TINY_WL), backend="ref").run()
    assert off["digest"] != on["digest"]
    assert off["workload"] is None


def test_workload_contention_throttles_clients():
    """A starved cluster (tiny per-OSD capacity, heavy QPS) must book
    throttled client bytes and contended OSD-epochs."""
    sc = Scenario.parse(
        "epochs=3,seed=2,hosts=4,osds_per_host=2,racks=2,pgs=16,ec=,"
        "size=3,balance_every=0,spotcheck_every=0,checkpoint_every=0,"
        "workload=1,base_qps=50000,obj_kb=512,osd_mbps=1")
    out = LifetimeSim(sc, backend="ref").run()
    wl = out["workload"]
    assert wl["throttled_gb"] > 0
    assert wl["contended_osd_epochs"] > 0


def test_resume_with_workload_and_queue(tmp_path):
    """Digest-exact resume with BOTH subsystems enabled: backlog
    vectors and workload tallies restore bit-exactly."""
    sc = Scenario.parse(TINY_WL)
    straight = LifetimeSim(sc, backend="ref").run()
    ck = tmp_path / "ck.json"
    LifetimeSim(sc, backend="ref", checkpoint=str(ck)).run(stop_after=4)
    resumed = LifetimeSim(sc, backend="ref", checkpoint=str(ck),
                          resume=True)
    assert resumed.resumed_from == 4
    out = resumed.run()
    assert out["digest"] == straight["digest"]
    assert out["workload"]["requests"] == \
        straight["workload"]["requests"]
    assert out["recovery"]["enqueued_gb"] == \
        straight["recovery"]["enqueued_gb"]


def test_resume_rejects_model_mix(tmp_path):
    """spec() pins the recovery model: a queue checkpoint can never be
    resumed under flat (and vice versa)."""
    ck = tmp_path / "ck.json"
    sc = Scenario.parse(TINY_WL + ",epochs=2")
    LifetimeSim(sc, backend="ref", checkpoint=str(ck)).run()
    other = Scenario.parse(TINY_WL + ",epochs=2,recovery=flat")
    with pytest.raises(ValueError, match="different scenario"):
        LifetimeSim(other, backend="ref", checkpoint=str(ck),
                    resume=True)


def test_scenario_rejects_unknown_recovery_model():
    with pytest.raises(ValueError, match="recovery="):
        Scenario.parse("epochs=2,recovery=bogus")


# ------------------------------------------------------------- slow tier


@pytest.mark.slow
def test_drain_and_workload_kernels_bit_identical_to_numpy():
    """The device executors against the authoritative numpy formulas on
    a seeded random input: every output int64 must match exactly."""
    import jax.numpy as jnp

    from ceph_tpu.recovery.queue import _drain_account
    from ceph_tpu.sim.workload import _wl_account

    rng = np.random.default_rng(7)
    N, W, DV, S = 32, 3, 32, 16
    rows = rng.integers(-1, 12, size=(N, W)).astype(np.int32)
    backlog = rng.integers(0, 5, size=N).astype(np.int64) * 10 ** 9
    moved = rng.integers(0, 3, size=N).astype(np.int64)
    cap = np.full(DV, 3 * 10 ** 9, np.int64)
    slots = np.full(DV, 2, np.int64)
    kw = dict(shard_bytes=333_333_333, stream_bytes=3 * 10 ** 9,
              t_us=30_000_000, n=N, size=3, tol=1)
    bh, ch, sh, sch = drain_pool_np(backlog, moved, rows, cap.copy(),
                                    slots.copy(), **kw)
    bd, cd, sd, scd = _drain_account((N, W, DV))(
        jnp.asarray(backlog), jnp.asarray(moved), jnp.asarray(rows),
        jnp.asarray(cap), jnp.asarray(slots), np.int64(333_333_333),
        np.int64(3 * 10 ** 9), np.int64(30_000_000), np.uint32(N),
        np.int32(3), np.int32(1))
    assert np.array_equal(bh, np.asarray(bd))
    assert np.array_equal(ch, np.asarray(cd))
    assert np.array_equal(sh, np.asarray(sd))
    assert list(sch.values()) == [int(v) for v in np.asarray(scd)]

    seeds = rng.integers(0, N, size=S).astype(np.int64)
    read = rng.random(S) < 0.7
    clh, wsh = workload_pool_np(rows, backlog, seeds, read, wq=11,
                                obj_bytes=65536, DV=DV, size=3, tol=1)
    cld, wsd = _wl_account((N, W, DV, S))(
        jnp.asarray(rows), jnp.asarray(backlog), jnp.asarray(seeds),
        jnp.asarray(read), np.int64(11), np.int64(65536), DV,
        np.int32(3), np.int32(1))
    assert np.array_equal(clh, np.asarray(cld))
    assert list(wsh.values()) == [int(v) for v in np.asarray(wsd)]


@pytest.mark.slow
def test_at_scale_queue_workload_jax():
    """200 chaos epochs on the jax backend with BOTH subsystems on:
    0 violations (conservation included), 0 steady compiles, backlog
    observed, served QPS recorded."""
    sc = Scenario.parse(
        "epochs=200,seed=11,hosts=6,osds_per_host=2,racks=2,pgs=64,"
        "ec=2+2,ec_pgs=32,chunk=512,balance_every=32,"
        "spotcheck_every=32,checkpoint_every=0,workload=1,"
        "pipeline_repair=1,max_pools=3,max_pgs=128,max_expand=2")
    out = LifetimeSim(sc, backend="jax").run()
    assert out["epochs"] == 200
    assert out["invariant_violations"] == 0, out["violations"][:5]
    assert out["trace_once"]["steady_compiles"] == 0
    assert out["recovery"]["conservation_violations"] == 0
    assert out["recovery"]["backlog_peak_gb"] > 0
    assert out["workload"]["served_qps"] > 0
    assert out["pareto"]["cluster_years_per_hour"] > 0
