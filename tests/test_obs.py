"""Observability layer: perf-dump layout vs the reference shape, tracer
nesting + thread safety, counters advancing on real hot-path runs, dout
line shape, the daemon CLI / admin socket, and the no-print lint."""

from __future__ import annotations

import io
import json
import os
import re
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu import obs
from ceph_tpu.obs import trace
from ceph_tpu.utils import perf_counters as pc

REPO = Path(__file__).resolve().parents[1]


# -- perf-dump JSON layout (reference perf_counters.h shapes) --------------

def test_perf_dump_layout():
    L = obs.logger_for("t_layout")
    L.add_u64("ops", "op count")
    L.add_avg("batch", "batch size")
    L.add_time_avg("lat", "latency")
    L.add_histogram("sz", [10.0, 100.0], "sizes")
    L.inc("ops", 3)
    L.observe("batch", 7.0)
    L.observe("lat", 0.25)
    for v in (5.0, 50.0, 500.0):
        L.observe("sz", v)

    d = obs.perf_dump()["t_layout"]
    # u64: bare integer
    assert d["ops"] == 3
    # avg: {avgcount, sum}
    assert d["batch"] == {"avgcount": 1, "sum": 7.0}
    # time_avg: {avgcount, sum, avgtime}
    assert set(d["lat"]) == {"avgcount", "sum", "avgtime"}
    assert d["lat"]["avgcount"] == 1
    assert d["lat"]["avgtime"] == pytest.approx(d["lat"]["sum"])
    # histogram: bounds + one-larger buckets + sum/count
    h = d["sz"]
    assert h["bounds"] == [10.0, 100.0]
    assert h["buckets"] == [1, 1, 1]
    assert h["count"] == 3 and h["sum"] == pytest.approx(555.0)


def test_perf_schema_and_reset_values():
    L = obs.logger_for("t_schema")
    L.add_u64("n", "a count")
    L.inc("n", 9)
    s = obs.perf_schema()["t_schema"]["n"]
    assert s == {"type": "u64", "description": "a count"}
    obs.reset_values()
    assert obs.perf_dump()["t_schema"]["n"] == 0
    L.inc("n")  # declarations survive a reset
    assert obs.perf_dump()["t_schema"]["n"] == 1


def test_declaration_idempotent_and_errors():
    L = obs.logger_for("t_decl")
    L.add_u64("k", "first")
    L.inc("k", 5)
    L.add_u64("k", "again")  # idempotent: value survives
    assert obs.perf_dump()["t_decl"]["k"] == 5

    with pytest.raises(pc.CounterKindError, match="t_decl.*k"):
        L.add_avg("k")
    with pytest.raises(obs.UndeclaredCounterError, match="t_decl.*nope"):
        L.inc("nope")
    with pytest.raises(obs.UndeclaredCounterError, match="t_decl.*nope"):
        L.observe("nope", 1.0)
    with pytest.raises(pc.CounterKindError):
        L.observe("k", 1.0)  # u64 needs inc()


# -- tracer ----------------------------------------------------------------

@pytest.fixture
def tracer(tmp_path):
    prev = trace.trace_path()  # may be set via CEPH_TPU_TRACE in the env
    path = str(tmp_path / "trace.json")
    trace.clear()
    obs.set_trace_path(path)
    yield path
    obs.set_trace_path(prev)
    trace.clear()


def test_tracer_disabled_records_nothing():
    prev = trace.trace_path()  # may be set via CEPH_TPU_TRACE in the env
    obs.set_trace_path(None)
    try:
        n0 = trace.n_events()
        with obs.span("t.noop"):
            pass
        assert trace.n_events() == n0
    finally:
        obs.set_trace_path(prev)


def test_tracer_nesting(tracer):
    with obs.span("t.outer", depth=0):
        with obs.span("t.inner"):
            pass
    assert obs.flush() == tracer
    doc = json.loads(Path(tracer).read_text())
    ev = {e["name"]: e for e in doc["traceEvents"]}
    outer, inner = ev["t.outer"], ev["t.inner"]
    assert outer["ph"] == inner["ph"] == "X"
    assert outer["tid"] == inner["tid"]
    # time containment = nesting in the trace-event model
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"depth": 0}


def test_tracer_thread_safety(tracer):
    N_THREADS, N_SPANS = 8, 50
    # all threads in flight together (pthread ids are reused once a
    # thread exits, which would collapse the distinct-tid check)
    gate = threading.Barrier(N_THREADS)

    def work(i):
        gate.wait()
        for j in range(N_SPANS):
            with obs.span(f"t.worker{i}", j=j):
                pass

    ts = [threading.Thread(target=work, args=(i,)) for i in range(N_THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert trace.n_events() == N_THREADS * N_SPANS
    doc = json.loads(Path(obs.flush()).read_text())
    tids = {e["tid"] for e in doc["traceEvents"]}
    assert len(tids) == N_THREADS


def test_tracer_counter_and_instant(tracer):
    obs.instant("t.marker", note="x")
    obs.counter("t.gauge", 3.5)
    doc = json.loads(Path(obs.flush()).read_text())
    phases = {e["name"]: e["ph"] for e in doc["traceEvents"]}
    assert phases == {"t.marker": "i", "t.gauge": "C"}


# -- counters advance on real hot-path runs --------------------------------

def test_pipeline_counters_advance():
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.pipeline_jax import PoolMapper
    from ceph_tpu.osd.types import PgPool, PoolType

    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=64, pgp_num=64)
    m = build_hierarchical(2, 8, n_rack=1, pool=pool)
    before = obs.perf_dump()["pipeline"]
    pm = PoolMapper(m, 0, overlays=False)
    pm.map_batch(np.arange(64, dtype=np.uint32))
    after = obs.perf_dump()["pipeline"]
    assert after["pgs_mapped"] - before["pgs_mapped"] == 64
    # the jitted fast path went through compile/dispatch accounting
    assert after["fast_compiles"] >= 1
    assert after["fast_compile_seconds"]["avgcount"] >= 1
    assert after["fast_compile_seconds"]["sum"] > 0
    # the d2h fetch of the unresolved flags is booked
    assert after["result_fetch_seconds"]["avgcount"] > (
        before.get("result_fetch_seconds", {"avgcount": 0})["avgcount"]
        if isinstance(before.get("result_fetch_seconds"), dict) else 0
    )


def test_ec_counters_advance():
    from ceph_tpu.ec.registry import create_erasure_code

    code = create_erasure_code({"plugin": "jax", "k": "8", "m": "4"})
    data = np.arange(8 * 4096, dtype=np.uint8).reshape(8, 4096)
    before = obs.perf_dump()["ec"]
    enc = code.encode_chunks(data)
    after = obs.perf_dump()["ec"]
    assert after["bytes_encoded"] - before["bytes_encoded"] == data.size
    assert (after["encode_seconds"]["avgcount"]
            == before["encode_seconds"]["avgcount"] + 1)

    chunks = {i: enc[i] for i in range(12) if i not in (0, 5)}
    code.decode_chunks({0, 5}, dict(chunks), 4096)
    after2 = obs.perf_dump()["ec"]
    assert after2["bytes_decoded"] - after["bytes_decoded"] == 2 * 4096


def test_balancer_counters_advance():
    from ceph_tpu.balancer.upmap import calc_pg_upmaps
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.types import PgPool, PoolType

    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=256, pgp_num=256)
    m = build_hierarchical(4, 8, n_rack=1, pool=pool)
    m.osd_weight[0] = int(0x10000 * 0.5)
    before = obs.perf_dump()["balancer"]
    calc_pg_upmaps(m, max_deviation=1, max_iter=5,
                   rng=np.random.default_rng(7))
    after = obs.perf_dump()["balancer"]
    assert after["rounds"] > before["rounds"]
    assert after["build_state_seconds"]["avgcount"] > (
        before["build_state_seconds"]["avgcount"])


# -- Prometheus text exposition --------------------------------------------

_LABEL = r'[a-zA-Z_]+="(?:[^"\\]|\\.)*"'
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_][a-zA-Z0-9_]*(\{" + _LABEL + r"(," + _LABEL + r")*\})? "
    r"(-?\d+(\.\d+)?(e[+-]?\d+)?|NaN)$"
)


def test_prometheus_exposition_golden():
    """Exact exposition for every declared kind — u64/avg/time_avg/
    histogram/quantile — with cumulative `_bucket` ordering and `+Inf`.
    Kinds come from the declaration schema, never from duck-typing the
    dump (quantile and histogram dumps share a shape)."""
    from ceph_tpu.obs.prometheus import prometheus_text

    L = obs.logger_for("t_gold")
    L.add_u64("ops", "op count")
    L.add_avg("batch", "batch sizes")
    L.add_time_avg("lat", "latency")
    L.add_histogram("sz", [1.0, 10.0, 100.0], "sizes")
    L.add_quantile("ql", "dispatch latencies", bounds=[0.25, 2.0, 16.0])
    L.inc("ops", 3)
    L.observe("batch", 4.0)
    L.observe("batch", 6.0)
    L.observe("lat", 0.25)
    for v in (0.5, 5.0, 50.0, 500.0):  # one per bucket incl. overflow
        L.observe("sz", v)
    for v in (0.125, 0.5, 0.5, 4.0, 32.0):
        L.observe("ql", v)
    text = prometheus_text({"t_gold": obs.perf_dump()["t_gold"]})
    assert text == (
        "# HELP ceph_tpu_t_gold_batch batch sizes\n"
        "# TYPE ceph_tpu_t_gold_batch summary\n"
        "ceph_tpu_t_gold_batch_sum 10.0\n"
        "ceph_tpu_t_gold_batch_count 2\n"
        "# HELP ceph_tpu_t_gold_lat latency\n"
        "# TYPE ceph_tpu_t_gold_lat summary\n"
        "ceph_tpu_t_gold_lat_sum 0.25\n"
        "ceph_tpu_t_gold_lat_count 1\n"
        "# HELP ceph_tpu_t_gold_ops op count\n"
        "# TYPE ceph_tpu_t_gold_ops counter\n"
        "ceph_tpu_t_gold_ops 3\n"
        "# HELP ceph_tpu_t_gold_ql dispatch latencies\n"
        "# TYPE ceph_tpu_t_gold_ql histogram\n"
        'ceph_tpu_t_gold_ql_bucket{le="0.25"} 1\n'
        'ceph_tpu_t_gold_ql_bucket{le="2.0"} 3\n'
        'ceph_tpu_t_gold_ql_bucket{le="16.0"} 4\n'
        'ceph_tpu_t_gold_ql_bucket{le="+Inf"} 5\n'
        "ceph_tpu_t_gold_ql_sum 37.125\n"
        "ceph_tpu_t_gold_ql_count 5\n"
        "# HELP ceph_tpu_t_gold_sz sizes\n"
        "# TYPE ceph_tpu_t_gold_sz histogram\n"
        'ceph_tpu_t_gold_sz_bucket{le="1.0"} 1\n'
        'ceph_tpu_t_gold_sz_bucket{le="10.0"} 2\n'
        'ceph_tpu_t_gold_sz_bucket{le="100.0"} 3\n'
        'ceph_tpu_t_gold_sz_bucket{le="+Inf"} 4\n'
        "ceph_tpu_t_gold_sz_sum 555.5\n"
        "ceph_tpu_t_gold_sz_count 4\n"
    )


def test_prometheus_text_valid():
    L = obs.logger_for("t_prom")
    L.add_u64("hits", "hit count")
    L.add_time_avg("lat", "latency")
    L.add_histogram("sz", [1.0, 10.0], "sizes")
    L.inc("hits", 2)
    L.observe("lat", 0.5)
    L.observe("sz", 5.0)
    text = obs.prometheus_text()
    assert text.endswith("\n")
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_][a-zA-Z0-9_]* ", line)
        else:
            assert _METRIC_LINE.match(line), f"bad metric line: {line!r}"
    assert "ceph_tpu_t_prom_hits 2" in text
    assert 'ceph_tpu_t_prom_sz_bucket{le="+Inf"} 1' in text
    assert "ceph_tpu_t_prom_lat_count 1" in text


def test_prometheus_health_timeline_gauges_golden(monkeypatch):
    """Exact exposition of the health-check and timeline gauges, with
    the label-escaping path exercised: a check summary embedding `\\`,
    `"` and a newline must stay one valid exposition line."""
    from ceph_tpu.obs import health, timeline

    monkeypatch.setenv("CEPH_TPU_HEALTH_MUTE", "PG_DEGRADED")
    health.reset()
    timeline.reset()
    try:
        health.raise_check("OSD_DOWN", health.WARN, "1/8 osds down", count=1)
        health.raise_check("PG_DEGRADED", health.WARN,
                           '3 pgs "degraded"\nback\\slash', count=3)
        assert health.prometheus_gauges() == (
            "# HELP ceph_tpu_health_status cluster health "
            "(0=OK 1=WARN 2=ERR)\n"
            "# TYPE ceph_tpu_health_status gauge\n"
            "ceph_tpu_health_status 1\n"
            "# HELP ceph_tpu_health_check per-check count (labels: code, "
            "severity, summary, muted)\n"
            "# TYPE ceph_tpu_health_check gauge\n"
            'ceph_tpu_health_check{code="OSD_DOWN",severity="HEALTH_WARN",'
            'summary="1/8 osds down",muted="0"} 1\n'
            'ceph_tpu_health_check{code="PG_DEGRADED",'
            'severity="HEALTH_WARN",'
            'summary="3 pgs \\"degraded\\"\\nback\\\\slash",muted="1"} 3\n'
        )

        timeline.sample("serve", {"p99_s": 0.25, "qps": 1000.0})
        timeline.sample("serve", {"p99_s": 0.5, "qps": 2000.0})
        timeline.sample("sim", {"health": 1.0})
        assert timeline.prometheus_gauges() == (
            "# HELP ceph_tpu_timeline_samples samples recorded per series\n"
            "# TYPE ceph_tpu_timeline_samples gauge\n"
            'ceph_tpu_timeline_samples{series="serve"} 2\n'
            'ceph_tpu_timeline_samples{series="sim"} 1\n'
            "# HELP ceph_tpu_timeline_last newest sample value per "
            "series/field\n"
            "# TYPE ceph_tpu_timeline_last gauge\n"
            'ceph_tpu_timeline_last{series="serve",field="p99_s"} 0.5\n'
            'ceph_tpu_timeline_last{series="serve",field="qps"} 2000.0\n'
            'ceph_tpu_timeline_last{series="sim",field="health"} 1.0\n'
        )

        # the package-level exposition now carries these multi-label
        # lines — every one must still parse as a valid metric line
        for line in obs.prometheus_text().rstrip("\n").split("\n"):
            if line.startswith("#"):
                assert re.match(
                    r"^# (HELP|TYPE) [a-zA-Z_][a-zA-Z0-9_]* ", line)
            else:
                assert _METRIC_LINE.match(line), f"bad line: {line!r}"
    finally:
        health.reset()
        timeline.reset()


# -- quantile summarize == per-quantile estimate ---------------------------

def test_quantile_summarize_matches_estimate():
    """The single-pass `summarize()` (one cumulative walk per counter
    dump) must stay value-equivalent to three independent `estimate()`
    walks, across randomized dense/sparse histograms with and without
    tracked min/max."""
    from ceph_tpu.obs import quantiles

    bounds = list(quantiles.DEFAULT_BOUNDS)
    rng = np.random.default_rng(42)
    for trial in range(200):
        buckets = rng.integers(0, 6, size=len(bounds) + 1)
        buckets[rng.integers(0, len(buckets), size=20)] = 0  # sparse holes
        vmin = float(rng.uniform(1e-7, 1e-5)) if trial % 3 else None
        vmax = float(rng.uniform(10.0, 1000.0)) if trial % 2 else None
        s = quantiles.summarize(bounds, buckets, vmin=vmin, vmax=vmax)
        for name, q in quantiles.REPORTED:
            assert s[name] == quantiles.estimate(
                bounds, buckets, q, vmin=vmin, vmax=vmax
            ), (trial, name)
    assert quantiles.summarize(bounds, [0] * (len(bounds) + 1)) == {
        "p50": 0.0, "p90": 0.0, "p99": 0.0}


# -- dout line shape + set_output ------------------------------------------

def test_dout_line_shape_and_late_set_output():
    from ceph_tpu.utils import dout

    log = dout.subsys_logger("t_dout")  # created BEFORE set_output
    dout.set_subsys_level("t_dout", 5)
    buf = io.StringIO()
    dout.set_output(buf)
    try:
        log(5, "hello", 42)
        assert log.enabled(5) and not log.enabled(6)
    finally:
        dout.set_output(None)
    line = buf.getvalue().rstrip("\n")
    # 2026-08-02T10:11:12.345678+0000 7f3a00c0 5 t_dout: hello 42
    assert re.match(
        r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{6}[+-]\d{4} "
        r"[0-9a-f]+ +5 t_dout: hello 42$",
        line,
    ), f"bad log line: {line!r}"


# -- admin socket + daemon CLI ---------------------------------------------

def test_admin_socket_roundtrip(tmp_path):
    from ceph_tpu.obs import admin_socket

    L = obs.logger_for("t_sock")
    L.add_u64("n")
    L.inc("n", 4)
    srv = admin_socket.start(str(tmp_path / "x.asok"))
    try:
        out = admin_socket.client_command(srv.path, "perf dump")
        assert json.loads(out)["t_sock"]["n"] == 4
        out = admin_socket.client_command(srv.path, "metrics")
        assert "ceph_tpu_t_sock_n 4" in out
        out = admin_socket.client_command(srv.path, "bogus")
        assert "unknown command" in json.loads(out)["error"]
    finally:
        srv.close()


def test_handle_command_perf_reset():
    from ceph_tpu.obs.admin_socket import handle_command

    L = obs.logger_for("t_reset")
    L.add_u64("n")
    L.inc("n", 2)
    assert json.loads(handle_command("perf reset")) == {"ok": True}
    assert obs.perf_dump()["t_reset"]["n"] == 0


def test_admin_socket_slow_command_does_not_block_concurrent_client(
        tmp_path, monkeypatch):
    """Per-connection handler threads: a slow `cache dump`-style command
    must not block a concurrent `perf dump` — the always-answers
    diagnostic path."""
    import threading
    import time

    from ceph_tpu.obs import admin_socket

    orig = admin_socket.handle_command

    def slowable(cmd):
        if cmd == "t_slow":
            time.sleep(1.5)
            return json.dumps({"slow": True})
        return orig(cmd)

    monkeypatch.setattr(admin_socket, "handle_command", slowable)
    srv = admin_socket.start(str(tmp_path / "conc.asok"))
    try:
        box: dict = {}

        def slow_client():
            box["slow"] = admin_socket.client_command(
                srv.path, "t_slow", timeout=10)

        t = threading.Thread(target=slow_client)
        t.start()
        time.sleep(0.2)  # the slow handler is now holding its thread
        t0 = time.perf_counter()
        out = admin_socket.client_command(srv.path, "perf dump")
        fast_dt = time.perf_counter() - t0
        assert json.loads(out)  # answered
        assert fast_dt < 1.0, (
            f"perf dump took {fast_dt:.2f}s behind a slow command — "
            "connections are being handled inline in the accept loop")
        t.join(timeout=10)
        assert json.loads(box["slow"]) == {"slow": True}
    finally:
        srv.close()


def test_admin_socket_reclaims_stale_socket_file(tmp_path, monkeypatch):
    """A dead process's leftover socket file must not stop the next
    process from serving the path."""
    import socket as socklib

    from ceph_tpu.obs import admin_socket

    path = str(tmp_path / "stale.asok")
    s = socklib.socket(socklib.AF_UNIX, socklib.SOCK_STREAM)
    s.bind(path)
    s.close()  # no unlink: the killed-process shape — file, no listener
    assert os.path.exists(path)
    assert not admin_socket._path_serving(path)
    monkeypatch.setenv("CEPH_TPU_ADMIN_SOCKET", path)
    monkeypatch.setattr(admin_socket, "_server", None)
    srv = admin_socket.maybe_start_from_env()
    try:
        assert srv is not None
        out = admin_socket.client_command(path, "help")
        assert "perf dump" in json.loads(out)
    finally:
        if srv is not None:
            srv.close()
        admin_socket._server = None


def test_admin_socket_never_steals_live_servers_path(
        tmp_path, monkeypatch):
    """A client shell with CEPH_TPU_ADMIN_SOCKET still exported imports
    obs too — it must not unlink the socket of the live process it is
    about to query (simulated here by clearing the module global while
    the server object stays alive, the other-process view)."""
    from ceph_tpu.obs import admin_socket

    path = str(tmp_path / "live.asok")
    srv = admin_socket.start(path)
    try:
        monkeypatch.setenv("CEPH_TPU_ADMIN_SOCKET", path)
        monkeypatch.setattr(admin_socket, "_server", None)
        assert admin_socket._path_serving(path)
        assert admin_socket.maybe_start_from_env() is None
        # the live server kept its socket and still answers
        assert os.path.exists(path)
        out = admin_socket.client_command(path, "help")
        assert "perf dump" in json.loads(out)
    finally:
        monkeypatch.setattr(admin_socket, "_server", srv)
        srv.close()
        admin_socket._server = None


def test_admin_socket_connection_error_logged_not_swallowed(
        tmp_path, monkeypatch, capfd):
    """A per-connection failure (peer vanishes mid-reply) lands in the
    dout log with the command, instead of the old bare `except: pass`."""
    import socket as socklib
    import struct
    import time

    from ceph_tpu.obs import admin_socket

    orig = admin_socket.handle_command

    def delayed(cmd):
        if cmd == "t_err":
            # wait past the client's RST-close, then try a reply too
            # big for the (dead) socket buffer: sendall must fail
            time.sleep(0.3)
            return "x" * (1 << 20)
        return orig(cmd)

    monkeypatch.setattr(admin_socket, "handle_command", delayed)
    srv = admin_socket.start(str(tmp_path / "err.asok"))
    try:
        c = socklib.socket(socklib.AF_UNIX, socklib.SOCK_STREAM)
        c.connect(srv.path)
        c.sendall(b"t_err\n")
        # SO_LINGER(0): close sends RST — the server's send must error
        c.setsockopt(socklib.SOL_SOCKET, socklib.SO_LINGER,
                     struct.pack("ii", 1, 0))
        c.close()
        deadline = time.time() + 5
        logged = ""
        while time.time() < deadline:
            logged += capfd.readouterr().err
            if "admin socket connection failed" in logged:
                break
            time.sleep(0.05)
        assert "admin socket connection failed" in logged, logged[-500:]
        assert "t_err" in logged
    finally:
        srv.close()


@pytest.mark.slow
def test_daemon_cli_selftest():
    """`python -m ceph_tpu.cli.daemon perf dump` in a fresh process runs a
    small mapping + RS encode and prints reference-layout JSON."""
    out = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.cli.daemon", "perf dump"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-800:]
    d = json.loads(out.stdout)
    assert d["pipeline"]["pgs_mapped"] > 0
    assert d["ec"]["bytes_encoded"] > 0
    assert d["pipeline"]["fast_compile_seconds"]["avgcount"] >= 1


# -- hot paths never print to stdout ---------------------------------------

def test_no_print_lint():
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_no_print.py")],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr


def test_no_print_lint_catches_violation(tmp_path):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        from check_no_print import check_file
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import sys\nprint('a')\nprint('b', file=sys.stdout)\n"
        "print('c', file=sys.stderr)\n"
    )
    v = check_file(bad)
    assert len(v) == 2  # stderr print is allowed


# -- dispatch spans never host-sync ----------------------------------------

def test_no_host_sync_lint():
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_no_host_sync.py")],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr


def test_no_host_sync_lint_catches_violation(tmp_path):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        from check_no_host_sync import check_file
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import numpy as np\n"
        "with obs.span('pipeline.map_block', pgs=1):\n"
        "    a = np.asarray(x)\n"
        "    b = x.item()\n"
        "    c = float(x)\n"
        "with obs.span('pipeline.rescue'):\n"
        "    d = np.resize(x, 4)\n"       # not a sync: allowed
        "    e = np.array(x)\n"
        "with obs.span('pipeline.fetch'):\n"
        "    f = np.asarray(x)\n"          # fetch span: allowed
        "with span('pipeline.map_block'):\n"
        "    g = np.asarray(x)\n"          # bare span() counts too
    )
    v = check_file(bad)
    assert len(v) == 5, v


# -- satellite: pytest must not collect TesterConfig -----------------------

def test_tester_config_not_collected():
    from ceph_tpu.crush.tester import TesterConfig

    assert TesterConfig.__test__ is False
