"""Mesh-sharded production placement + candidate-batched optimizer.

Tier-1 (small, 2 of the forced 8 CPU devices): the ClusterState rows a
meshed state serves must be bit-identical to the unsharded state's and
to the host oracle — across a value-only delta apply — and the
candidate-batched calc_pg_upmaps must match the sequential optimizer's
plan quality at equal max_deviation while booking FEWER scoring
dispatches per accepted change (counter-proven).  The knob/provenance
surface (CEPH_TPU_MESH_DEVICES -> default_mesh, requested-vs-actual
recording in make_mesh) is pinned here too.

The 8-device lifetime digest-identity run and at-scale scaling rides
the slow tier (tier-1 wall budget).
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu import obs
from ceph_tpu.balancer import calc_pg_upmaps
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import Incremental
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.state import ClusterState
from ceph_tpu.osd.types import PgId, PgPool, PoolType
from ceph_tpu.parallel.sharded import (
    default_mesh,
    last_mesh_provenance,
    make_mesh,
)


def hier(pg_num=96, n_host=4, per=4, size=3):
    pool = PgPool(
        type=PoolType.REPLICATED, size=size, crush_rule=0,
        pg_num=pg_num, pgp_num=pg_num,
    )
    return build_hierarchical(n_host, per, n_rack=2, pool=pool)


def skewed(pg_num=512, n_host=8, per=4, down=6, seed=5):
    m = hier(pg_num=pg_num, n_host=n_host, per=per)
    rng = np.random.default_rng(seed)
    for o in rng.choice(n_host * per, down, replace=False):
        m.osd_weight[int(o)] = int(0x10000 * 0.6)
    return m


def _bal_snap():
    d = obs.perf_dump().get("balancer") or {}
    return {k: int(d.get(k, 0)) for k in (
        "changes_accepted", "changes_rejected", "candidate_batches",
        "candidates_scored")}


# -- mesh knob + provenance -------------------------------------------------

class TestMeshKnob:
    def test_default_mesh_none_when_unset(self, monkeypatch):
        monkeypatch.delenv("CEPH_TPU_MESH_DEVICES", raising=False)
        assert default_mesh() is None

    def test_default_mesh_routes_knob(self, monkeypatch):
        monkeypatch.setenv("CEPH_TPU_MESH_DEVICES", "2")
        mesh = default_mesh()
        assert mesh is not None and mesh.devices.size == 2
        monkeypatch.setenv("CEPH_TPU_MESH_DEVICES", "1")
        assert default_mesh() is None  # <=1 = single-device

    def test_make_mesh_records_requested_vs_actual(self):
        # more devices than the forced 8 exist: allow_fewer degrades
        # WITH provenance — a shrunken mesh can't pose as a scaling run
        mesh = make_mesh(64, allow_fewer=True)
        prov = last_mesh_provenance()
        assert mesh.devices.size == prov["actual"] <= 8
        assert prov["requested"] == 64
        assert prov["degraded"] is True
        with pytest.raises(RuntimeError):
            make_mesh(64)  # strict form still refuses
        mesh2 = make_mesh(2)
        prov2 = last_mesh_provenance()
        assert mesh2.devices.size == 2
        assert prov2 == {**prov2, "requested": 2, "actual": 2,
                         "degraded": False}

    def test_default_mesh_degrades_oversized_knob(self, monkeypatch):
        monkeypatch.setenv("CEPH_TPU_MESH_DEVICES", "999")
        mesh = default_mesh()
        assert mesh is not None and mesh.devices.size <= 8
        assert last_mesh_provenance()["degraded"] is True


# -- sharded ClusterState == unsharded == oracle ----------------------------

class TestShardedState:
    @pytest.fixture(scope="class")
    def pair(self):
        mesh = make_mesh(2)
        return (ClusterState(hier(), mesh=mesh), ClusterState(hier()))

    def test_rows_equal_and_oracle(self, pair):
        cs_sh, cs = pair
        r_sh, _, _ = cs_sh.rows(0)
        r, _, _ = cs.rows(0)
        a, b = np.asarray(r_sh), np.asarray(r)
        assert np.array_equal(a, b)
        # PG-sharded layout actually landed on the mesh
        assert len(r_sh.sharding.device_set) == 2
        m = cs_sh.m
        for ps in range(0, 96, 7):
            up, _, _, _ = m.pg_to_up_acting_osds(PgId(0, ps))
            got = [int(o) for o in a[ps] if o != ITEM_NONE]
            assert got == up, ps

    def test_value_delta_apply_under_mesh(self, pair):
        cs_sh, cs = pair
        for st in pair:
            inc = Incremental(epoch=st.m.epoch + 1)
            inc.new_weight[3] = int(0x10000 * 0.7)
            inc.new_state[7] = 4  # OSD_UP xor: mark osd.7 down
            assert st.apply(inc) == "delta"
        r_sh, _, t1 = cs_sh.rows(0)
        r, _, _ = cs.rows(0)
        assert np.array_equal(np.asarray(r_sh), np.asarray(r))
        m = cs_sh.m
        for ps in range(0, 96, 11):
            up, _, _, _ = m.pg_to_up_acting_osds(PgId(0, ps))
            got = [int(o) for o in np.asarray(r_sh)[ps]
                   if o != ITEM_NONE]
            assert got == up, ps
        # tag-stable re-read does no device work
        before = int((obs.perf_dump().get("state") or {})
                     .get("rows_remapped", 0))
        _, _, t2 = cs_sh.rows(0)
        after = int((obs.perf_dump().get("state") or {})
                    .get("rows_remapped", 0))
        assert t1 == t2 and before == after

    def test_mgr_eval_scores_identically(self, pair):
        from ceph_tpu.mgr import MappingState, synthetic_pg_stats
        from ceph_tpu.mgr.eval import calc_eval

        cs_sh, cs = pair
        stats = synthetic_pg_stats(cs_sh.m)
        pe_sh = calc_eval(MappingState(cs_sh.m, stats, state=cs_sh))
        pe = calc_eval(MappingState(cs.m, stats, state=cs))
        assert pe_sh.score == pe.score
        assert pe_sh.count_by_pool == pe.count_by_pool


# -- candidate-batched optimizer --------------------------------------------

class TestCandidateBatched:
    def test_quality_matches_sequential_with_fewer_dispatches(self):
        max_dev = 2
        m1, m2 = skewed(), skewed()
        s0 = _bal_snap()
        r1 = calc_pg_upmaps(
            m1, max_deviation=max_dev, max_iter=40, use_tpu=False,
            rng=np.random.default_rng(42))
        s1 = _bal_snap()
        r2 = calc_pg_upmaps(
            m2, max_deviation=max_dev, max_iter=40, use_tpu=False,
            rng=np.random.default_rng(42), candidate_batch=16)
        s2 = _bal_snap()
        seq_acc = s1["changes_accepted"] - s0["changes_accepted"]
        seq_rej = s1["changes_rejected"] - s0["changes_rejected"]
        acc = s2["changes_accepted"] - s1["changes_accepted"]
        batches = s2["candidate_batches"] - s1["candidate_batches"]
        assert acc > 0 and batches > 0
        assert s2["candidates_scored"] > s1["candidates_scored"]
        # counter proof: strictly fewer scoring dispatches per accepted
        # change than the sequential one-eval-per-change loop
        seq_ratio = (seq_acc + seq_rej) / max(seq_acc, 1)
        assert batches / acc < seq_ratio
        assert batches < acc
        # plan quality no worse at equal max_deviation (equal budget)
        assert r2.max_deviation <= max(r1.max_deviation,
                                       float(max_dev)) + 1e-6
        # budget semantics match the sequential loop's
        assert r2.num_changed <= 40
        self._assert_valid(m2)

    @staticmethod
    def _assert_valid(m, pool_id=0):
        pool = m.pools[pool_id]
        for pg, items in m.pg_upmap_items.items():
            assert pg.pool == pool_id and pg.seed < pool.pg_num
            for frm, to in items:
                assert 0 <= to < m.max_osd and m.exists(to)
        for ps in range(pool.pg_num):
            up, _, _, _ = m.pg_to_up_acting_osds(PgId(pool_id, ps))
            real = [o for o in up if o != ITEM_NONE]
            assert len(real) == len(set(real)) == pool.size, ps

    def test_device_backend_scores_on_device(self):
        """The jnp scoring kernel path (backend="device"): valid plan,
        improvement, and the batch counters advance."""
        m = skewed(pg_num=256, n_host=4, down=4)
        s0 = _bal_snap()
        r = calc_pg_upmaps(
            m, max_deviation=1, max_iter=12,
            rng=np.random.default_rng(7), backend="device",
            candidate_batch=8)
        s1 = _bal_snap()
        assert s1["candidate_batches"] > s0["candidate_batches"]
        if r.num_changed:
            assert r.stddev >= 0
            self._assert_valid(m)

    def test_mgr_option_routes_candidate_batch(self):
        from ceph_tpu.mgr import Balancer, MappingState, \
            synthetic_pg_stats

        m = skewed(pg_num=256, n_host=4, down=4, seed=9)
        bal = Balancer(options={"upmap_max_optimizations": 8,
                                "upmap_candidate_batch": 8},
                       rng=np.random.default_rng(3))
        ms = MappingState(m, synthetic_pg_stats(m), mapper="host")
        plan = bal.plan_create("t", ms, mode="upmap")
        s0 = _bal_snap()
        rc, _ = bal.optimize(plan)
        s1 = _bal_snap()
        if rc == 0:
            assert s1["candidate_batches"] > s0["candidate_batches"]


# -- fully device-resident optimizer (device_loop) --------------------------

def _loop_snap():
    d = obs.perf_dump().get("balancer") or {}
    return {k: int(d.get(k, 0)) for k in (
        "changes_accepted", "changes_rejected", "candidate_batches",
        "plan_dispatches", "plan_readback_reverts",
        "device_loop_compiles", "device_loop_cache_hits",
        "device_loop_retraces")}


class TestDeviceLoop:
    """The whole-plan device-resident optimizer: ONE XLA dispatch per
    plan, quality no worse than the host backends, every accepted move
    OSD-disjoint and individually improving."""

    def test_equivalence_gate_one_dispatch(self):
        # identical fresh maps, same seed, a budget all three backends
        # can converge within (upmap_state_backend="device_loop"
        # behind the same calc_pg_upmaps options as "sets"/"device")
        max_dev = 2
        m1, m2, m3 = skewed(), skewed(), skewed()
        s0 = _loop_snap()
        r1 = calc_pg_upmaps(
            m1, max_deviation=max_dev, max_iter=200, use_tpu=False,
            rng=np.random.default_rng(42))
        s1 = _loop_snap()
        r2 = calc_pg_upmaps(
            m2, max_deviation=max_dev, max_iter=200, use_tpu=False,
            rng=np.random.default_rng(42), candidate_batch=16)
        s2 = _loop_snap()
        r3 = calc_pg_upmaps(
            m3, max_deviation=max_dev, max_iter=200,
            rng=np.random.default_rng(42), backend="device_loop",
            candidate_batch=16)
        s3 = _loop_snap()
        assert r3.num_changed > 0
        # ONE plan dispatch for the whole multi-round plan: the
        # counter, the kernel executions, and zero retraces
        assert s3["plan_dispatches"] - s2["plan_dispatches"] == 1
        kernel_execs = (
            s3["device_loop_compiles"] - s2["device_loop_compiles"]
            + s3["device_loop_cache_hits"]
            - s2["device_loop_cache_hits"])
        assert kernel_execs == 1
        assert s3["device_loop_retraces"] == s2["device_loop_retraces"]
        assert s3["plan_readback_reverts"] == s2["plan_readback_reverts"]
        # dispatches per accepted change strictly below the batched
        # backend at equal budget (which is itself below sequential)
        acc2 = s2["changes_accepted"] - s1["changes_accepted"]
        acc3 = s3["changes_accepted"] - s2["changes_accepted"]
        batches2 = s2["candidate_batches"] - s1["candidate_batches"]
        assert acc2 > 0 and acc3 > 0 and batches2 > 1
        assert 1 / acc3 < batches2 / acc2
        # final quality no worse than EITHER host backend
        assert r3.stddev <= min(r1.stddev, r2.stddev) + 1e-9
        assert r3.max_deviation <= min(r1.max_deviation,
                                       r2.max_deviation) + 1e-9
        TestCandidateBatched._assert_valid(m3)

    def test_moves_osd_disjoint_and_individually_improving(self):
        """Replay the plan's audit trail: within every round no OSD is
        touched twice (so per-move deltas are additive), and each
        move's own delta — evaluated against the counts at its round's
        start — is strictly negative."""
        m = skewed()
        r = calc_pg_upmaps(
            m, max_deviation=2, max_iter=200,
            rng=np.random.default_rng(42), backend="device_loop",
            candidate_batch=16)
        assert r.moves and len(r.moves) == r.num_changed
        # counts/targets of the identical fresh map
        from ceph_tpu.balancer.upmap import _build_pgs_by_osd
        from ceph_tpu.balancer.crush_analysis import (
            get_rule_weight_osd_map,
        )
        from ceph_tpu.crush import mapper_ref

        m0 = skewed()
        pool = m0.pools[0]
        ruleno = mapper_ref.find_rule(
            m0.crush, pool.crush_rule, int(pool.type), pool.size)
        osd_weight = {
            o: m0.get_weightf(o) * w for o, w in
            get_rule_weight_osd_map(m0.crush, ruleno).items()
            if m0.get_weightf(o) * w > 0}
        ppw = pool.size * pool.pg_num / sum(osd_weight.values())
        pbo = _build_pgs_by_osd(m0, set(), use_tpu=False)
        counts = {o: len(pbo.get(o, ())) for o in osd_weight}
        rounds: dict[int, list] = {}
        for pg, frm, to, rnd in r.moves:
            rounds.setdefault(rnd, []).append((pg, frm, to))
        for rnd in sorted(rounds):
            touched: set[int] = set()
            dev = {o: counts[o] - osd_weight[o] * ppw
                   for o in osd_weight}
            for pg, frm, to in rounds[rnd]:
                assert frm not in touched and to not in touched, \
                    (rnd, frm, to)
                touched |= {frm, to}
                delta = 2 * (dev[to] - dev[frm]) + 2
                assert delta < 0, (rnd, pg, frm, to, delta)
            for _, frm, to in rounds[rnd]:
                counts[frm] -= 1
                counts[to] += 1
        # the replayed end state matches the plan's reported quality
        d = np.asarray([counts[o] - osd_weight[o] * ppw
                        for o in sorted(osd_weight)])
        assert abs(float(np.sum(d * d)) - r.stddev) < 1e-6
        assert abs(float(np.max(np.abs(d))) - r.max_deviation) < 1e-6

    def test_mesh_bit_identical_plan(self):
        """The plan shards over CEPH_TPU_MESH_DEVICES like the PR 15
        pipeline: 2 forced devices produce the bit-identical plan (the
        PG-axis work is elementwise + exact-int scatter-min, so GSPMD
        partitioning cannot move a decision)."""
        m1, m2 = skewed(), skewed()
        r1 = calc_pg_upmaps(
            m1, max_deviation=2, max_iter=48,
            rng=np.random.default_rng(5), backend="device_loop")
        r2 = calc_pg_upmaps(
            m2, max_deviation=2, max_iter=48,
            rng=np.random.default_rng(5), backend="device_loop",
            mesh=make_mesh(2))
        assert m1.pg_upmap_items == m2.pg_upmap_items
        assert r1.moves == r2.moves
        assert r1.stddev == r2.stddev
        assert r1.max_deviation == r2.max_deviation

    def test_mgr_option_routes_device_loop(self):
        """upmap_state_backend="device_loop" flows through the mgr's
        options dict unchanged — Balancer.optimize plans through the
        one-dispatch backend."""
        from ceph_tpu.mgr import Balancer, MappingState, \
            synthetic_pg_stats

        m = skewed(pg_num=256, n_host=4, down=4, seed=9)
        bal = Balancer(options={"upmap_max_optimizations": 8,
                                "upmap_max_deviation": 1,
                                "upmap_state_backend": "device_loop",
                                "upmap_candidate_batch": 8},
                       rng=np.random.default_rng(3))
        ms = MappingState(m, synthetic_pg_stats(m), mapper="host")
        plan = bal.plan_create("t", ms, mode="upmap")
        s0 = _loop_snap()
        rc, _ = bal.optimize(plan)
        s1 = _loop_snap()
        if rc == 0:
            assert s1["plan_dispatches"] > s0["plan_dispatches"]

    def test_background_balance_off_query_path(self):
        """serve: a background balancing round plans + applies as a
        value-only overlay swap while lookups keep answering — the
        plan never runs on the query path."""
        from ceph_tpu.serve.service import PlacementService, \
            ServeConfig

        m = skewed()
        svc = PlacementService(
            m, config=ServeConfig(block=128, fill=256, max_queue=32,
                                  deadline_s=5.0))
        try:
            base = dict(obs.perf_dump().get("serve") or {})
            r1 = svc.background_balance(max_deviation=1, max_iter=16)
            assert r1["ok"] and r1["num_changed"] > 0
            # applied as a VALUE-ONLY overlay epoch
            d = dict(obs.perf_dump().get("serve") or {})
            assert d.get("swap_delta_applies", 0) \
                > base.get("swap_delta_applies", 0)
            assert d.get("swap_full_restages", 0) \
                == base.get("swap_full_restages", 0)
            assert d.get("background_rounds", 0) \
                == base.get("background_rounds", 0) + 1
            rep = svc.lookup_batch(0, np.arange(32, dtype=np.uint32))
            assert rep.ok and rep.epoch == r1["epoch"]
            # a second round keeps converging (fewer or zero changes)
            r2 = svc.background_balance(max_deviation=1, max_iter=16)
            assert r2["ok"] and r2["num_changed"] <= r1["num_changed"]
        finally:
            svc.close()


# -- sharded lifetime digest identity (slow tier) ---------------------------

MC_SCENARIO = (
    "epochs=36,seed=11,hosts=4,osds_per_host=3,racks=2,pgs=64,ec=2+1,"
    "ec_pgs=32,chunk=512,balance_every=12,balance_max=4,"
    "spotcheck_every=12,checkpoint_every=0,recovery=flat,workload=0"
)


@pytest.mark.slow
def test_sharded_lifetime_digest_identity():
    """Chaos epochs on an 8-device mesh chain the SAME SHA-256 replay
    digest as single-device — the reductions are exact-integer, so
    GSPMD partitioning cannot move a digest bit — and steady epochs
    still book 0 compiles under sharding."""
    from ceph_tpu.sim.lifetime import LifetimeSim, Scenario

    a = LifetimeSim(Scenario.parse(MC_SCENARIO), backend="jax",
                    mesh=make_mesh(8)).run()
    b = LifetimeSim(Scenario.parse(MC_SCENARIO), backend="jax").run()
    assert a["digest"] == b["digest"]
    assert a["invariant_violations"] == 0
    assert a["trace_once"]["steady_compiles"] == 0


@pytest.mark.slow
def test_sharded_rebalance_at_scale():
    """Candidate-batched device-backend optimizer on an 8-device mesh
    at a bigger shape: valid plan, >=2x fewer dispatches per change."""
    m1, m2 = skewed(pg_num=2048), skewed(pg_num=2048)
    mesh = make_mesh(8)
    s0 = _bal_snap()
    calc_pg_upmaps(m1, max_deviation=2, max_iter=48,
                   rng=np.random.default_rng(1), backend="device",
                   mesh=mesh)
    s1 = _bal_snap()
    calc_pg_upmaps(m2, max_deviation=2, max_iter=48,
                   rng=np.random.default_rng(1), backend="device",
                   mesh=mesh, candidate_batch=32)
    s2 = _bal_snap()
    seq_acc = s1["changes_accepted"] - s0["changes_accepted"]
    seq_rej = s1["changes_rejected"] - s0["changes_rejected"]
    acc = s2["changes_accepted"] - s1["changes_accepted"]
    batches = s2["candidate_batches"] - s1["candidate_batches"]
    assert acc > 0
    assert (seq_acc + seq_rej) / max(seq_acc, 1) \
        >= 2 * (batches / max(acc, 1))
