"""OSDMap::Incremental tests — epoch chains, wire round-trip, apply.

Mirrors the reference semantics of src/osd/OSDMap.h:376-496 (field model),
src/osd/OSDMap.cc:557-935 (codec) and :2061 (apply_incremental): a chain of
synthetic deltas round-trips byte-exactly, and applying it reproduces the
state reached by direct mutation — including on the real 1476-OSD
production fixture.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from ceph_tpu.crush.codec import encode_crushmap
from ceph_tpu.osd.codec import decode_osdmap, encode_osdmap
from ceph_tpu.osd.incremental import (
    Incremental,
    apply_incremental,
    decode_incremental,
    encode_incremental,
)
from ceph_tpu.osd.osdmap import (
    OSD_EXISTS,
    OSD_UP,
    OSDMap,
    build_hierarchical,
    build_simple,
)
from ceph_tpu.osd.types import PgId, PgPool, PoolType

FIXTURE = "/root/reference/src/test/compressor/osdmaps/osdmap.2982809"


def small_map() -> OSDMap:
    return build_hierarchical(4, 4, n_rack=2, pool=PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=64, pgp_num=64,
    ))


# ------------------------------------------------------------- round-trip


def rt(inc: Incremental) -> Incremental:
    blob = encode_incremental(inc)
    out = decode_incremental(blob)
    assert encode_incremental(out) == blob  # decode->encode byte-exact
    return out


def test_roundtrip_empty():
    inc = Incremental(epoch=5)
    out = rt(inc)
    assert out.epoch == 5
    assert out.new_flags == -1
    assert out.new_max_osd == -1
    assert out.new_pool_max == -1
    assert not out.new_weight and not out.new_pg_upmap_items


def test_roundtrip_fields():
    inc = Incremental(epoch=9)
    inc.new_max_osd = 20
    inc.new_flags = 0x18000
    inc.new_pool_max = 3
    inc.new_weight = {3: 0, 7: 0x8000}
    inc.new_state = {3: OSD_UP, 5: OSD_EXISTS | OSD_UP}
    inc.new_primary_affinity = {2: 0x4000}
    inc.new_pg_temp = {PgId(1, 4): [2, 0, 1], PgId(1, 9): []}
    inc.new_primary_temp = {PgId(1, 4): 2, PgId(1, 5): -1}
    inc.new_pg_upmap = {PgId(1, 7): [3, 2, 1]}
    inc.old_pg_upmap = {PgId(1, 8)}
    inc.new_pg_upmap_items = {PgId(1, 2): [(0, 5), (1, 6)]}
    inc.old_pg_upmap_items = {PgId(1, 3)}
    inc.new_erasure_code_profiles = {"p1": {"k": "4", "m": "2"}}
    inc.old_erasure_code_profiles = ["dead"]
    inc.new_pool_names = {2: "renamed"}
    inc.old_pools = {9}
    out = rt(inc)
    for f in ("new_max_osd", "new_flags", "new_pool_max", "new_weight",
              "new_state", "new_primary_affinity", "new_pg_temp",
              "new_primary_temp", "new_pg_upmap", "old_pg_upmap",
              "new_pg_upmap_items", "old_pg_upmap_items",
              "new_erasure_code_profiles", "old_erasure_code_profiles",
              "new_pool_names", "old_pools"):
        assert getattr(out, f) == getattr(inc, f), f


def test_roundtrip_pool_and_crush():
    m = small_map()
    inc = Incremental(epoch=2)
    pool = PgPool(type=PoolType.REPLICATED, size=2, crush_rule=0,
                  pg_num=32, pgp_num=32)
    inc.new_pools[5] = pool
    inc.new_pool_names[5] = "newpool"
    inc.crush = encode_crushmap(m.crush)
    out = rt(inc)
    assert out.new_pools[5].pg_num == 32
    assert out.new_pools[5].size == 2
    assert out.crush == inc.crush


def test_crc_guard():
    blob = bytearray(encode_incremental(Incremental(epoch=3)))
    blob[20] ^= 0xFF
    with pytest.raises(Exception, match="crc|truncated|Codec"):
        decode_incremental(bytes(blob))


# ------------------------------------------------------------------ apply


def test_apply_epoch_guard():
    m = small_map()
    with pytest.raises(ValueError, match="epoch"):
        apply_incremental(m, Incremental(epoch=m.epoch + 2))


def test_apply_fsid_guard():
    """Mismatching fsid rejected (reference OSDMap.cc:2064-2067)."""
    m = small_map()
    m.wire = {"fsid": b"A" * 16, "pools": {}}
    inc = Incremental(epoch=m.epoch + 1, fsid=b"B" * 16)
    with pytest.raises(ValueError, match="fsid"):
        apply_incremental(m, inc)


def test_apply_chain_equals_direct_mutation():
    """A 4-epoch chain reproduces the directly-mutated map, and the chain
    re-encodes byte-exactly after a decode round-trip of every link."""
    m = small_map()
    base_epoch = m.epoch

    # direct mutation copy
    d = small_map()
    d.epoch = base_epoch

    chain: list[bytes] = []

    inc1 = Incremental(epoch=base_epoch + 1)
    inc1.new_weight = {2: 0}
    inc1.new_state = {3: OSD_UP}  # mark osd.3 down (XOR of UP bit)
    chain.append(encode_incremental(inc1))
    d.osd_weight[2] = 0
    d.osd_state[3] &= ~OSD_UP

    inc2 = Incremental(epoch=base_epoch + 2)
    inc2.new_pg_upmap_items = {PgId(0, 5): [(1, 9)]}
    inc2.new_pg_temp = {PgId(0, 7): [8, 9, 10]}
    inc2.new_primary_temp = {PgId(0, 7): 9}
    chain.append(encode_incremental(inc2))
    d.pg_upmap_items[PgId(0, 5)] = [(1, 9)]
    d.pg_temp[PgId(0, 7)] = [8, 9, 10]
    d.primary_temp[PgId(0, 7)] = 9

    inc3 = Incremental(epoch=base_epoch + 3)
    inc3.new_weight = {2: 0x10000}
    inc3.new_pg_temp = {PgId(0, 7): []}      # removal
    inc3.new_primary_temp = {PgId(0, 7): -1}  # removal
    inc3.old_pg_upmap_items = {PgId(0, 5)}
    chain.append(encode_incremental(inc3))
    d.osd_weight[2] = 0x10000
    del d.pg_temp[PgId(0, 7)]
    del d.primary_temp[PgId(0, 7)]
    del d.pg_upmap_items[PgId(0, 5)]

    inc4 = Incremental(epoch=base_epoch + 4)
    inc4.new_erasure_code_profiles = {"ec42": {"k": "4", "m": "2",
                                               "plugin": "jax"}}
    inc4.new_pool_names = {0: "rbd-renamed"}
    chain.append(encode_incremental(inc4))
    d.erasure_code_profiles["ec42"] = {"k": "4", "m": "2", "plugin": "jax"}
    d.pool_name[0] = "rbd-renamed"
    d.epoch = base_epoch + 4

    for blob in chain:
        inc = decode_incremental(blob)
        assert encode_incremental(inc) == blob
        m = apply_incremental(m, inc)

    assert m.epoch == d.epoch
    assert m.osd_weight == d.osd_weight
    assert m.osd_state == d.osd_state
    assert m.pg_temp == d.pg_temp
    assert m.primary_temp == d.primary_temp
    assert m.pg_upmap_items == d.pg_upmap_items
    assert m.erasure_code_profiles == d.erasure_code_profiles
    assert m.pool_name == d.pool_name
    # the applied map's own encoding decodes cleanly
    m2 = decode_osdmap(encode_osdmap(m))
    assert m2.epoch == m.epoch
    assert m2.osd_weight == m.osd_weight


def test_apply_destroy_and_new_up():
    m = small_map()
    inc = Incremental(epoch=m.epoch + 1)
    # destroy osd.1: EXISTS set in both prev state and delta
    inc.new_state = {1: OSD_EXISTS}
    # new osd comes up via new_up_client
    inc.new_max_osd = m.max_osd + 1
    new_osd = m.max_osd
    inc.new_up_client = {new_osd: b""}
    inc.new_weight = {new_osd: 0x10000}
    m = apply_incremental(m, inc)
    assert m.osd_state[1] == 0
    assert not m.exists(1)
    assert m.exists(new_osd) and m.is_up(new_osd)
    assert m.osd_weight[new_osd] == 0x10000


def test_apply_fullmap():
    m = small_map()
    target = build_simple(8, 5, 5)
    target.epoch = m.epoch + 1
    inc = Incremental(epoch=m.epoch + 1)
    inc.fullmap = encode_osdmap(target)
    out = apply_incremental(m, inc)
    assert out.epoch == target.epoch
    assert out.max_osd == 8


def test_apply_new_pool_and_mapping_changes():
    """Weight + upmap deltas shift the actual pipeline output."""
    m = small_map()
    up0, _, _, _ = m.pg_to_up_acting_osds(PgId(0, 3))
    inc = Incremental(epoch=m.epoch + 1)
    # kill the first up osd of pg 0.3
    victim = up0[0]
    inc.new_weight = {victim: 0}
    inc.new_state = {victim: OSD_UP}
    m = apply_incremental(m, inc)
    up1, _, _, _ = m.pg_to_up_acting_osds(PgId(0, 3))
    assert victim not in up1


# --------------------------------------------------- production fixture


@pytest.mark.skipif(not os.path.exists(FIXTURE),
                    reason="reference osdmap fixture unavailable")
def test_apply_on_production_map():
    with open(FIXTURE, "rb") as f:
        m = decode_osdmap(f.read())
    e0 = m.epoch
    pool_id = sorted(m.pools)[0]
    inc = Incremental(epoch=e0 + 1)
    inc.fsid = m.wire["fsid"]  # strict fsid guard (OSDMap.cc:2064-2067)
    inc.new_weight = {17: 0}
    inc.new_pg_upmap_items = {PgId(pool_id, 1): [(4, 5)]}
    blob = encode_incremental(inc)
    inc2 = decode_incremental(blob)
    assert encode_incremental(inc2) == blob
    m = apply_incremental(m, inc2)
    assert m.epoch == e0 + 1
    assert m.osd_weight[17] == 0
    assert m.pg_upmap_items[PgId(pool_id, 1)] == [(4, 5)]
    # map still encodes and re-decodes
    m2 = decode_osdmap(encode_osdmap(m))
    assert m2.osd_weight[17] == 0


# ------------------------------------------------------------------- CLI


def test_osdmaptool_apply_incremental(tmp_path):
    from ceph_tpu.osd.io import save_osdmap

    m = small_map()
    mapfile = tmp_path / "om.bin"
    save_osdmap(m, str(mapfile))
    inc = Incremental(epoch=m.epoch + 1)
    inc.new_weight = {0: 0}
    incfile = tmp_path / "inc.bin"
    incfile.write_bytes(encode_incremental(inc))
    r = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.cli.osdmaptool", str(mapfile),
         "--apply-incremental", str(incfile)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        cwd="/root/repo",
    )
    assert r.returncode == 0, r.stderr
    m2 = decode_osdmap(mapfile.read_bytes())
    assert m2.epoch == m.epoch + 1
    assert m2.osd_weight[0] == 0
