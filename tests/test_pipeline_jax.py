"""Differential tests: batched JAX PG→OSD pipeline vs the host OSDMap oracle
(reference semantics src/osd/OSDMap.cc:2435-2715).  Exact equality of the
padded (up, up_primary, acting, acting_primary) tuples for every PG."""

import numpy as np
import pytest

from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import OSDMap, build_hierarchical, build_simple
from ceph_tpu.osd.pipeline_jax import PoolMapper
from ceph_tpu.osd.types import PgId, PgPool, PoolType


def check_pool(m: OSDMap, pool_id: int):
    pm = PoolMapper(m, pool_id)
    up, upp, acting, actp = pm.map_all()
    W = up.shape[1]
    pool = m.pools[pool_id]
    for ps in range(pool.pg_num):
        w_up, w_upp, w_act, w_actp = m.pg_to_up_acting_osds(
            PgId(pool_id, ps)
        )
        pad = lambda v: (list(v) + [ITEM_NONE] * W)[:W]
        assert list(up[ps]) == pad(w_up), (ps, list(up[ps]), w_up)
        assert upp[ps] == w_upp, (ps, upp[ps], w_upp)
        assert list(acting[ps]) == pad(w_act), (ps, list(acting[ps]), w_act)
        assert actp[ps] == w_actp, (ps, actp[ps], w_actp)


def hier_map(rng, pool=None, n_host=8, osd_per_host=4, **kw):
    pool = pool or PgPool(pg_num=128, size=3)
    return build_hierarchical(
        n_host, osd_per_host, pool=pool,
        weight_fn=lambda i: int(rng.integers(1, 4)) * 0x8000, **kw
    )


def test_replicated_clean(rng):
    check_pool(hier_map(rng), 0)


def test_build_simple():
    m = build_simple(8, pg_bits=4)
    check_pool(m, 1)


def test_replicated_down_out(rng):
    m = hier_map(rng)
    for o in rng.choice(m.max_osd, 6, replace=False):
        m.mark_down(int(o))
    for o in rng.choice(m.max_osd, 5, replace=False):
        m.mark_out(int(o))
    check_pool(m, 0)


def test_erasure_down_out(rng):
    pool = PgPool(type=PoolType.ERASURE, size=6, pg_num=128, crush_rule=1)
    m = hier_map(rng, pool)
    m.crush.make_erasure_rule(
        min(m.crush.buckets.keys(), key=lambda b: -m.crush.buckets[b].type), 1
    )
    # rule index: make_replicated_rule was rule 0, erasure is rule 1 with
    # ruleset 1 — pool.crush_rule must match the ruleset
    for o in rng.choice(m.max_osd, 6, replace=False):
        m.mark_down(int(o))
    for o in rng.choice(m.max_osd, 4, replace=False):
        m.mark_out(int(o))
    check_pool(m, 0)


def test_primary_affinity(rng):
    m = hier_map(rng)
    for o in range(m.max_osd):
        r = rng.integers(0, 4)
        if r == 0:
            m.set_primary_affinity(o, 0)
        elif r == 1:
            m.set_primary_affinity(o, int(rng.integers(0, 0x10000)))
    check_pool(m, 0)


def test_upmap_full_and_items(rng):
    m = hier_map(rng)
    pool = m.pools[0]
    for ps in rng.choice(pool.pg_num, 20, replace=False):
        ps = int(ps)
        kind = rng.integers(0, 2)
        if kind == 0:
            tgt = [int(o) for o in rng.choice(m.max_osd, 3, replace=False)]
            m.pg_upmap[PgId(0, ps)] = tgt
        else:
            frm = int(rng.integers(0, m.max_osd))
            to = int(rng.integers(0, m.max_osd))
            m.pg_upmap_items[PgId(0, ps)] = [(frm, to)]
    # some targets marked out to exercise the reject guards
    for o in rng.choice(m.max_osd, 4, replace=False):
        m.mark_out(int(o))
    check_pool(m, 0)


def test_upmap_multi_pairs(rng):
    m = hier_map(rng)
    pool = m.pools[0]
    # build pairs from actual raw mappings so swaps really engage
    for ps in range(0, pool.pg_num, 3):
        raw, _ = m.pg_to_raw_osds(PgId(0, ps))
        if len(raw) < 2:
            continue
        to1 = int((raw[0] + 1) % m.max_osd)
        to2 = int((raw[1] + 7) % m.max_osd)
        m.pg_upmap_items[PgId(0, ps)] = [(raw[0], to1), (raw[1], to2)]
    check_pool(m, 0)


def test_pg_temp_primary_temp(rng):
    m = hier_map(rng)
    pool = m.pools[0]
    for ps in rng.choice(pool.pg_num, 24, replace=False):
        ps = int(ps)
        kind = rng.integers(0, 3)
        if kind == 0:
            tgt = [int(o) for o in rng.choice(m.max_osd, 3, replace=False)]
            m.pg_temp[PgId(0, ps)] = tgt
        elif kind == 1:
            m.primary_temp[PgId(0, ps)] = int(rng.integers(0, m.max_osd))
        else:
            tgt = [int(o) for o in rng.choice(m.max_osd, 2, replace=False)]
            m.pg_temp[PgId(0, ps)] = tgt
            m.primary_temp[PgId(0, ps)] = tgt[-1]
    for o in rng.choice(m.max_osd, 8, replace=False):
        m.mark_down(int(o))
    check_pool(m, 0)


def test_ec_pg_temp(rng):
    pool = PgPool(type=PoolType.ERASURE, size=4, pg_num=64, crush_rule=1)
    m = hier_map(rng, pool)
    m.crush.make_erasure_rule(
        min(m.crush.buckets.keys(), key=lambda b: -m.crush.buckets[b].type), 1
    )
    for ps in rng.choice(pool.pg_num, 10, replace=False):
        ps = int(ps)
        m.pg_temp[PgId(0, ps)] = [
            int(o) for o in rng.choice(m.max_osd, 4, replace=False)
        ]
    for o in rng.choice(m.max_osd, 6, replace=False):
        m.mark_down(int(o))
    check_pool(m, 0)


def test_everything_at_once(rng):
    """All overlays + degraded cluster + affinity, replicated."""
    m = hier_map(rng, PgPool(pg_num=256, size=3), n_host=12, n_rack=3)
    pool = m.pools[0]
    for o in range(m.max_osd):
        if rng.integers(0, 5) == 0:
            m.set_primary_affinity(o, int(rng.integers(0, 0x10001)))
    for o in rng.choice(m.max_osd, 10, replace=False):
        m.mark_down(int(o))
    for o in rng.choice(m.max_osd, 8, replace=False):
        m.mark_out(int(o))
    for ps in rng.choice(pool.pg_num, 40, replace=False):
        ps = int(ps)
        k = rng.integers(0, 4)
        if k == 0:
            m.pg_upmap[PgId(0, ps)] = [
                int(o) for o in rng.choice(m.max_osd, 3, replace=False)
            ]
        elif k == 1:
            m.pg_upmap_items[PgId(0, ps)] = [
                (int(rng.integers(0, m.max_osd)),
                 int(rng.integers(0, m.max_osd))),
                (int(rng.integers(0, m.max_osd)),
                 int(rng.integers(0, m.max_osd))),
            ]
        elif k == 2:
            m.pg_temp[PgId(0, ps)] = [
                int(o) for o in rng.choice(m.max_osd, 3, replace=False)
            ]
        else:
            m.primary_temp[PgId(0, ps)] = int(rng.integers(0, m.max_osd))
    check_pool(m, 0)


def test_nonhashpspool(rng):
    pool = PgPool(pg_num=64, size=3, flags=0)
    check_pool(hier_map(rng, pool), 0)


def test_non_pow2_pg_num(rng):
    pool = PgPool(pg_num=100, size=3, pgp_num=96)
    check_pool(hier_map(rng, pool), 0)


def test_upmap_rejected_full_skips_items(rng):
    """The early `return` of reference src/osd/OSDMap.cc:2474: a pg_upmap
    with an out target must also suppress pg_upmap_items for that PG."""
    m = hier_map(rng)
    m.mark_out(1)
    for ps in range(0, 32):
        raw, _ = m.pg_to_raw_osds(PgId(0, ps))
        m.pg_upmap[PgId(0, ps)] = [0, 1, 2]  # osd.1 is out -> rejected
        if raw:
            m.pg_upmap_items[PgId(0, ps)] = [(raw[0], (raw[0] + 9) % 32)]
    check_pool(m, 0)


def test_primary_temp_without_pg_temp(rng):
    m = hier_map(rng)
    for ps in range(0, 64, 5):
        m.primary_temp[PgId(0, ps)] = int(rng.integers(0, m.max_osd))
    check_pool(m, 0)


def test_choose_args_default_fallback(rng):
    """choose_args_get_with_fallback (reference src/crush/CrushWrapper.h:
    1451-1457): pool id missing -> the DEFAULT_CHOOSE_ARGS (-1) set."""
    from ceph_tpu.crush.types import ChooseArgs

    m = hier_map(rng)
    ca = ChooseArgs()
    for bid, b in m.crush.buckets.items():
        ca.weight_sets[bid] = [
            [max(1, w // 2 + int(rng.integers(0, w + 1))) for w in b.weights]
        ]
    m.crush.choose_args[-1] = ca
    check_pool(m, 0)


def test_choose_args_positions_gt1_pipeline(rng):
    """A positions>1 weight-set keyed to the pool flows through the full
    batched pipeline (forcing the exact-loop kernel: the fast path's
    positions==1 precondition fails) and agrees with the host oracle."""
    from ceph_tpu.crush.types import ChooseArgs

    m = hier_map(
        rng, pool=PgPool(pg_num=64, size=3), n_host=4
    )
    pid = sorted(m.pools)[0]
    ca = ChooseArgs()
    for bid, b in m.crush.buckets.items():
        ca.weight_sets[bid] = [
            [int(w) for w in rng.integers(1, 3 * 0x10000, b.size)]
            for _ in range(2)
        ]
    m.crush.choose_args[pid] = ca
    pm = PoolMapper(m, pid)
    assert pm.arrays.positions == 2
    up, upp, acting, actp = pm.map_all()
    for ps in range(64):
        w_up, w_upp, w_act, w_actp = m.pg_to_up_acting_osds(PgId(pid, ps))
        got = [o for o in up[ps] if o != ITEM_NONE]
        assert got == w_up, ps
        assert upp[ps] == w_upp, ps
