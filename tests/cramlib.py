"""Minimal cram-format transcript runner.

Executes the reference's .t CLI transcripts (reference
src/test/cli/{osdmaptool,crushtool}/*.t) against OUR tools: a shim dir
maps `osdmaptool`/`crushtool` onto python -m ceph_tpu.cli.*, each `  $ `
command runs through bash in a scratch dir with TESTDIR set, and output
is matched with cram's rules:

- plain lines: byte-exact (including trailing whitespace)
- `line (re)`: regex, anchored both ends
- `line (esc)`: python-style escapes (\\t etc) decoded first
- `line (glob)`: * and ? wildcards
- `[N]`: expected exit status (absent = 0)

Returns per-command diffs so a failing transcript pinpoints the first
divergence.
"""

from __future__ import annotations

import codecs
import fnmatch
import os
import re
import subprocess
from dataclasses import dataclass, field
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


@dataclass
class Command:
    line_no: int
    cmd: str
    expected: list[str] = field(default_factory=list)
    exit_code: int = 0


def parse_t(path: Path) -> list[Command]:
    cmds: list[Command] = []
    cur: Command | None = None
    for i, raw in enumerate(path.read_text().splitlines(), 1):
        if raw.startswith("  $ "):
            cur = Command(i, raw[4:])
            cmds.append(cur)
        elif raw.startswith("  > ") and cur is not None:
            cur.cmd += "\n" + raw[4:]
        elif raw.startswith("  ") and cur is not None:
            line = raw[2:]
            m = re.fullmatch(r"\[(\d+)\]", line)
            if m:
                cur.exit_code = int(m.group(1))
            else:
                cur.expected.append(line)
        # comment / blank lines reset nothing
    return cmds


def _match_line(expected: str, actual: str) -> bool:
    if expected.endswith(" (esc)"):
        want = codecs.decode(expected[:-6], "unicode_escape")
        return want == actual
    if expected.endswith(" (re)"):
        try:
            return re.fullmatch(expected[:-5], actual) is not None
        except re.error:
            return False
    if expected.endswith(" (glob)"):
        return fnmatch.fnmatchcase(actual, expected[:-7])
    return expected == actual


def make_shims(shim_dir: Path) -> None:
    shim_dir.mkdir(parents=True, exist_ok=True)
    for tool in ("osdmaptool", "crushtool"):
        sh = shim_dir / tool
        sh.write_text(
            "#!/bin/sh\n"
            # env -u: actually unset the axon pool var (an empty value
            # would still count as "present" to presence-checking readers)
            f'exec env -u PALLAS_AXON_POOL_IPS PYTHONPATH="{REPO}" '
            "JAX_PLATFORMS=cpu "
            "TF_CPP_MIN_LOG_LEVEL=3 "  # silence XLA slow-op alarms
            f'python3 -u -m ceph_tpu.cli.{tool} "$@"\n'
        )
        sh.chmod(0o755)


@dataclass
class CmdResult:
    cmd: Command
    ok: bool
    actual: list[str]
    rc: int

    def diff(self) -> str:
        out = [f"$ {self.cmd.cmd}   (line {self.cmd.line_no}, "
               f"rc={self.rc} want {self.cmd.exit_code})"]
        exp, act = self.cmd.expected, self.actual
        for i in range(max(len(exp), len(act))):
            e = exp[i] if i < len(exp) else "<missing>"
            a = act[i] if i < len(act) else "<missing>"
            mark = " " if i < len(exp) and i < len(act) and \
                _match_line(e, a) else "!"
            out.append(f"{mark} want: {e!r}")
            if mark == "!":
                out.append(f"  got : {a!r}")
        return "\n".join(out)


def run_transcript(
    t_path: Path, workdir: Path, shim_dir: Path,
    skip_cmd_res: list[str] | None = None,
) -> list[CmdResult]:
    """Run every command; returns results (ok flag per command).
    skip_cmd_res: command regexes to skip (unsupported surface)."""
    make_shims(shim_dir)
    env = dict(
        os.environ,
        PATH=f"{shim_dir}:{os.environ['PATH']}",
        TESTDIR=str(t_path.parent),
        PYTHONPATH=str(REPO),
        JAX_PLATFORMS="cpu",
    )
    # same accelerator isolation as the shims, for commands that invoke
    # python directly rather than through them
    env.pop("PALLAS_AXON_POOL_IPS", None)
    cmds = [
        c for c in parse_t(t_path)
        if not (skip_cmd_res and any(re.search(p, c.cmd)
                                     for p in skip_cmd_res))
    ]
    # one bash session so shell state (vars, files) persists; a sentinel
    # after every command carries its exit status and splits the capture
    sent = "__CRAM_SENTINEL__"
    script_lines = ["exec 2>&1"]
    for c in cmds:
        script_lines.append(c.cmd)
        script_lines.append(f'echo "{sent}$?"')
    proc = subprocess.run(
        ["bash", "-c", "\n".join(script_lines)], cwd=workdir, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    chunks: list[tuple[list[str], int]] = []
    buf: list[str] = []
    for line in proc.stdout.splitlines():
        if line.startswith(sent):
            chunks.append((buf, int(line[len(sent):] or 0)))
            buf = []
        else:
            buf.append(line)
    results: list[CmdResult] = []
    for c, (actual, rc) in zip(cmds, chunks):
        ok = rc == c.exit_code and len(actual) == len(c.expected) and all(
            _match_line(e, a) for e, a in zip(c.expected, actual)
        )
        results.append(CmdResult(c, ok, actual, rc))
    return results
