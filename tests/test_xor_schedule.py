"""XOR-schedule compiler + trace-once EC engine tests.

Covers the PR-6 contract end to end: schedules lower once per matrix
(CSE-deduplicated, bit-exact against the mul-table oracle), compiled
executables key into `_EC_CACHE` like `_PIPE_CACHE` (hits proven at the
counter level), decode plans cache per erasure pattern, batched-stripe
kernels match per-stripe results, and the strategy knobs
(CEPH_TPU_EC_STRATEGY, profile["strategy"], autotune) resolve as
documented."""

import numpy as np
import pytest

from ceph_tpu import obs
from ceph_tpu.ec import matrices
from ceph_tpu.ec.gf import gf_matvec_data
from ceph_tpu.ec.jax_backend import (
    _AUTOTUNE,
    _EC_CACHE,
    STRATEGIES,
    JaxEngine,
)
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.ec.xor_schedule import (
    _SCHEDULES,
    bit_terms,
    build_schedule,
    host_apply,
    matrix_key,
)


def _ec_counters() -> dict:
    return dict(obs.perf_dump()["ec"])


# -- the compiler -----------------------------------------------------------

class TestScheduleCompiler:
    def test_bit_terms_match_bitmatrix_semantics(self):
        """Term (8i+j) in output r <=> bit j of M[r,i] — virtual row
        8i+j carries 2^j·data[i]."""
        M = np.array([[1, 2], [3, 255]], np.uint8)
        terms = bit_terms(M)
        assert terms[0] == [0, 9]            # 1·d0 ^ 2·d1
        assert terms[1][:2] == [0, 1]        # 3 = bits 0,1
        assert [t - 8 for t in terms[1][2:]] == list(range(8))  # 255

    @pytest.mark.parametrize("k,m", [(4, 2), (8, 4), (6, 3)])
    def test_host_apply_matches_oracle(self, k, m, rng):
        """The CSE DAG (ops/outs, not the naive terms) reproduces the
        table-driven GF matmul exactly."""
        M = matrices.vandermonde_rs(k, m)
        sched = build_schedule(M)
        data = rng.integers(0, 256, (k, 1000)).astype(np.uint8)
        assert np.array_equal(
            host_apply(sched, data), gf_matvec_data(M, data)
        )

    def test_random_matrices_bit_exact(self, rng):
        """Schedules are exact for arbitrary (not just MDS) matrices."""
        for _ in range(5):
            m, k = int(rng.integers(1, 5)), int(rng.integers(1, 7))
            M = rng.integers(0, 256, (m, k)).astype(np.uint8)
            data = rng.integers(0, 256, (k, 257)).astype(np.uint8)
            sched = build_schedule(M)
            assert np.array_equal(
                host_apply(sched, data), gf_matvec_data(M, data)
            ), M

    def test_cse_reduces_xors(self):
        """Paar dedup must strictly beat the naive program on the
        headline RS(8,4) profile (~106 -> ~63 xors)."""
        sched = build_schedule(matrices.vandermonde_rs(8, 4))
        assert sched.n_xors_cse < sched.n_xors_naive
        assert sched.stats()["temps"] > 0

    def test_schedule_cached_per_matrix(self):
        M = matrices.vandermonde_rs(5, 2)
        before = _ec_counters()
        s1 = build_schedule(M)
        s2 = build_schedule(M.copy())
        after = _ec_counters()
        assert s1 is s2  # same object: keyed on content, not identity
        assert matrix_key(M) in _SCHEDULES
        assert after["xor_schedule_cache_hits"] > (
            before["xor_schedule_cache_hits"]
        )


# -- the trace-once executable cache ---------------------------------------

class TestEcCache:
    def test_second_engine_hits_ec_cache(self, rng):
        """Two engines, same matrix: the second's executor comes from
        _EC_CACHE (a pipe_cache_hit, zero new jits) — the _PIPE_CACHE
        contract applied to EC."""
        M = matrices.cauchy_good(5, 3)
        data = rng.integers(0, 256, (5, 2048)).astype(np.uint8)
        e1 = JaxEngine("xor")
        want = e1.matmul(M, data)
        key = ("xor", matrix_key(M), False, False)
        assert key in _EC_CACHE
        before = _ec_counters()
        e2 = JaxEngine("xor")
        got = e2.matmul(M, data)
        after = _ec_counters()
        assert np.array_equal(got, want)
        assert after["pipe_cache_hits"] > before["pipe_cache_hits"]

    def test_stripes_do_not_recompile(self, rng):
        """After one warm call, further stripes of the same shape book
        zero compiles (jit cache-hit counters advance instead)."""
        M = matrices.vandermonde_rs(4, 2)
        eng = JaxEngine("xor")
        data = rng.integers(0, 256, (4, 4096)).astype(np.uint8)
        eng.matmul(M, data)  # warm
        before = obs.jit_counters()
        for _ in range(3):
            eng.matmul(M, rng.integers(0, 256, (4, 4096)).astype(np.uint8))
        delta = obs.jit_counters_delta(before)
        assert delta["compiles"] == 0, delta
        assert delta["cache_hits"] >= 3


# -- decode plans -----------------------------------------------------------

class TestDecodePlans:
    def test_plan_cached_per_erasure_pattern(self, rng):
        code = create_erasure_code(
            {"plugin": "jax", "k": 4, "m": 2, "backend": "jax"}
        )
        data = rng.integers(0, 256, (4, 1024)).astype(np.uint8)
        enc = np.asarray(code.encode_chunks(data))
        n = 6
        lost = [1, 4]
        avail = {i: enc[i] for i in range(n) if i not in lost}
        before = _ec_counters()
        d1 = code.decode_chunks(set(lost), dict(avail), 1024)
        mid = _ec_counters()
        d2 = code.decode_chunks(set(lost), dict(avail), 1024)
        after = _ec_counters()
        for i in lost:
            assert np.array_equal(np.asarray(d1[i]), enc[i])
            assert np.array_equal(np.asarray(d2[i]), enc[i])
        # first decode of the pattern builds the plan, the repeat hits
        assert mid["decode_plan_misses"] > before["decode_plan_misses"]
        assert after["decode_plan_hits"] > mid["decode_plan_hits"]
        assert after["decode_plan_misses"] == mid["decode_plan_misses"]

    def test_plans_shared_across_instances(self, rng):
        """A second code with the same generator reuses the first's
        plans (module-level cache keyed on matrix content)."""
        prof = {"plugin": "jerasure", "k": 4, "m": 2}
        c1 = create_erasure_code(dict(prof))
        c2 = create_erasure_code(dict(prof))
        data = rng.integers(0, 256, (4, 512)).astype(np.uint8)
        enc = c1.encode_chunks(data)
        avail = {i: enc[i] for i in range(6) if i != 2}
        c1.decode_chunks({2}, dict(avail), 512)
        before = _ec_counters()
        c2.decode_chunks({2}, dict(avail), 512)
        after = _ec_counters()
        assert after["decode_plan_hits"] > before["decode_plan_hits"]


# -- batched-stripe kernels -------------------------------------------------

class TestBatched:
    @pytest.mark.parametrize(
        "strategy", ["xor", "xor_cse", "bitplane", "logexp", "pallas"]
    )
    def test_encode_batch_matches_per_stripe(self, strategy, rng):
        """Batched == per-stripe for every strategy (pallas folds the
        stripes axis into the byte axis: interpret-mode stays a couple
        of grid steps, fast on CPU)."""
        code = create_erasure_code(
            {"plugin": "jax", "k": 4, "m": 2, "strategy": strategy}
        )
        batch = rng.integers(0, 256, (3, 4, 2048)).astype(np.uint8)
        got = np.asarray(code.encode_batch(batch))
        want = np.stack(
            [np.asarray(code.encode_chunks(s)) for s in batch]
        )
        assert np.array_equal(got, want)

    def test_encode_batch_zero_compiles_after_warm(self, rng):
        code = create_erasure_code({"plugin": "jax", "k": 4, "m": 2})
        batch = rng.integers(0, 256, (2, 4, 1024)).astype(np.uint8)
        code.encode_batch(batch)  # warm
        before = obs.jit_counters()
        for _ in range(3):
            code.encode_batch(
                rng.integers(0, 256, (2, 4, 1024)).astype(np.uint8)
            )
        delta = obs.jit_counters_delta(before)
        assert delta["compiles"] == 0, delta

    def test_decode_batch_matches_per_stripe(self, rng):
        code = create_erasure_code({"plugin": "jax", "k": 4, "m": 2})
        batch = rng.integers(0, 256, (3, 4, 1024)).astype(np.uint8)
        enc = np.asarray(code.encode_batch(batch))  # [3, 6, L]
        lost = [0, 5]
        chunks = {
            i: enc[:, i] for i in range(6) if i not in lost
        }
        out = code.decode_batch(set(lost), dict(chunks), 1024)
        for i in lost:
            assert np.array_equal(np.asarray(out[i]), enc[:, i])

    def test_numpy_engine_batch_fallback(self, rng):
        """encode_batch works (loop fallback) for engines without a
        batched kernel."""
        code = create_erasure_code({"plugin": "jerasure", "k": 3, "m": 2})
        batch = rng.integers(0, 256, (2, 3, 512)).astype(np.uint8)
        got = code.encode_batch(batch)
        want = np.stack([code.encode_chunks(s) for s in batch])
        assert np.array_equal(got, want)


# -- strategy knobs ---------------------------------------------------------

class TestStrategyKnobs:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("CEPH_TPU_EC_STRATEGY", "bitplane")
        assert JaxEngine().strategy == "bitplane"
        monkeypatch.setenv("CEPH_TPU_EC_STRATEGY", "logexp")
        assert JaxEngine().strategy == "logexp"
        # the env is a FORCE: it overrides even explicit/profile picks
        # (the documented way to pin one strategy fleet-wide)
        assert JaxEngine("xor").strategy == "logexp"
        code = create_erasure_code(
            {"plugin": "jax", "k": 3, "m": 2, "strategy": "xor"}
        )
        assert code.engine.strategy == "logexp"
        monkeypatch.delenv("CEPH_TPU_EC_STRATEGY")
        assert JaxEngine("xor").strategy == "xor"

    def test_env_override_rejected_when_unknown(self, monkeypatch):
        monkeypatch.setenv("CEPH_TPU_EC_STRATEGY", "warp-drive")
        with pytest.raises(ValueError, match="warp-drive"):
            JaxEngine()

    def test_profile_strategy_knob(self, rng):
        from ceph_tpu.ec.interface import ErasureCodeProfileError

        code = create_erasure_code(
            {"plugin": "jax", "k": 3, "m": 2, "strategy": "bitplane"}
        )
        assert code.engine.strategy == "bitplane"
        with pytest.raises(ErasureCodeProfileError):
            create_erasure_code(
                {"plugin": "jax", "k": 3, "m": 2, "strategy": "nope"}
            )

    def test_every_documented_strategy_exists(self):
        assert set(STRATEGIES) == {
            "xor", "xor_cse", "bitplane", "logexp", "pallas", "auto"
        }

    def test_autotune_resolves_and_caches(self, rng):
        M = matrices.vandermonde_rs(3, 2)
        data = rng.integers(0, 256, (3, 4096)).astype(np.uint8)
        want = gf_matvec_data(M, data)
        before = _ec_counters()
        e1 = JaxEngine("auto")
        assert np.array_equal(e1.matmul(M, data), want)
        picked = e1._resolved_strategy
        assert picked in STRATEGIES and picked != "auto"
        mid = _ec_counters()
        assert mid["autotunes"] > before["autotunes"]
        # a second auto engine reuses the measured record: no new tune
        e2 = JaxEngine("auto")
        assert np.array_equal(e2.matmul(M, data), want)
        after = _ec_counters()
        assert after["autotunes"] == mid["autotunes"]
        rec = _AUTOTUNE[(
            __import__("jax").default_backend(), matrix_key(M)
        )]
        assert rec["strategy"] == picked
        assert rec["measured_gbps"][picked] > 0


# -- every strategy against the frozen corpus shapes ------------------------

class TestStrategiesBitExact:
    @pytest.mark.parametrize("strategy",
                             ["xor", "xor_cse", "bitplane", "logexp",
                              "pallas"])
    def test_rs84_encode_decode(self, strategy, rng):
        """All strategies produce identical stripes AND identical
        decode-plan rebuilds on the headline RS(8,4) shape."""
        code = create_erasure_code(
            {"plugin": "jax", "k": 8, "m": 4, "strategy": strategy}
        )
        oracle = create_erasure_code({"plugin": "jerasure",
                                      "k": 8, "m": 4})
        data = rng.integers(0, 256, (8, 4096)).astype(np.uint8)
        enc = np.asarray(code.encode_chunks(data))
        assert np.array_equal(enc, oracle.encode_chunks(data)), strategy
        lost = [0, 5, 9]
        avail = {i: enc[i] for i in range(12) if i not in lost}
        dec = code.decode_chunks(set(lost), dict(avail), 4096)
        for i in lost:
            assert np.array_equal(np.asarray(dec[i]), enc[i]), (
                strategy, i
            )


# -- clay product-matrix repair plans --------------------------------------

class TestClayRepairPlan:
    def test_repair_plan_cached_and_exact(self, rng):
        code = create_erasure_code(
            {"plugin": "clay", "k": 4, "m": 2, "d": "5"}
        )
        sub = code.get_sub_chunk_count()
        L = 64 * sub
        data = rng.integers(0, 256, (4, L)).astype(np.uint8)
        enc = code.encode_chunks(data)
        want = {2}
        need = code.minimum_to_repair(want, set(range(6)) - want)
        helpers = {}
        for j, runs in need.items():
            arr = enc[j].reshape(sub, -1)
            planes = [z for ind, cnt in runs for z in range(ind, ind + cnt)]
            helpers[j] = np.ascontiguousarray(arr[planes]).reshape(-1)
        before = _ec_counters()
        out1 = code.repair(want, dict(helpers), L)
        mid = _ec_counters()
        out2 = code.repair(want, dict(helpers), L)
        after = _ec_counters()
        assert np.array_equal(out1[2], enc[2])
        assert np.array_equal(out2[2], enc[2])
        assert mid["repair_plan_misses"] > before["repair_plan_misses"]
        assert after["repair_plan_hits"] > mid["repair_plan_hits"]


# -- at-scale variants (tier-1 budget: slow-marked) -------------------------

@pytest.mark.slow
class TestAtScale:
    def test_big_stripe_all_strategies(self, rng):
        data = rng.integers(0, 256, (8, 1 << 20)).astype(np.uint8)
        oracle = gf_matvec_data(matrices.vandermonde_rs(8, 4), data)
        for strategy in ("xor", "xor_cse", "bitplane", "logexp"):
            eng = JaxEngine(strategy)
            got = eng.matmul(matrices.vandermonde_rs(8, 4), data)
            assert np.array_equal(got, oracle), strategy

    def test_big_batched_vmap_zero_compiles(self, rng):
        code = create_erasure_code({"plugin": "jax", "k": 8, "m": 4})
        batch = rng.integers(0, 256, (8, 8, 1 << 17)).astype(np.uint8)
        code.encode_batch(batch[:2])  # warm the 2-stripe shape
        code.encode_batch(batch)      # warm the 8-stripe shape
        before = obs.jit_counters()
        out = np.asarray(code.encode_batch(batch))
        delta = obs.jit_counters_delta(before)
        assert delta["compiles"] == 0, delta
        want = np.asarray(code.encode_chunks(batch[3]))
        assert np.array_equal(out[3], want)
