"""Test config: run JAX on a virtual 8-device CPU mesh so sharding tests work
without TPU hardware; the real-chip path is exercised by bench.py."""

import os

# Force CPU for tests.  The session environment pins JAX_PLATFORMS to the
# TPU plugin and a sitecustomize imports jax at interpreter start, so the
# env var is already captured — jax.config.update is the only reliable
# override at this point.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))  # for `oracle`

import numpy as np
import pytest


@pytest.fixture(scope="session")
def oracle_lib():
    from oracle import load

    lib = load()
    if lib is None:
        pytest.skip("reference C oracle unavailable (no mount or compiler)")
    return lib


@pytest.fixture
def rng():
    return np.random.default_rng(0xC3A5)
