"""choose_tries histogram (reference src/crush/mapper.c:640-643) and the
fast-window bound it substantiates.

PROFILE_r05 §5 claims the fast kernel's candidate window of
numrep + FAST_WINDOW_EXTRA draws covers all but a vanishing fraction of
placements.  The histogram is the instrument that proves it: collected
by the host reference mapper per placement (retry count at success),
surfaced through CrushTester/--show-choose-tries, and compared here
against the fast kernel's actual unresolved-lane count.
"""

import io

import numpy as np
import pytest

from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.mapper_jax import FAST_WINDOW_EXTRA, compile_batched
from ceph_tpu.crush.soa import build_arrays
from ceph_tpu.crush.tester import CrushTester, TesterConfig
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgPool, PoolType

N_X = 512


def bench_shape():
    """The BENCH topology (hosts of 8 under racks, size-3 chooseleaf)."""
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=N_X, pgp_num=N_X)
    return build_hierarchical(16, 4, n_rack=2, pool=pool)


@pytest.fixture(scope="module")
def collected():
    """(crush, weights, xs, hist) with the histogram collected once."""
    m = bench_shape()
    crush = m.crush
    w = list(m.osd_weight)
    crush.choose_tries_histogram = [0] * (
        crush.tunables.choose_total_tries + 1
    )
    xs = (np.arange(N_X, dtype=np.uint32) * 2654435761) % (2**31)
    for x in xs:
        mapper_ref.do_rule(crush, 0, int(x), 3, w,
                           collect_choose_tries=True)
    return crush, w, xs, list(crush.choose_tries_histogram)


class TestHistogram:
    def test_counts_every_placement(self, collected):
        crush, w, xs, hist = collected
        # chooseleaf counts the host placement AND the leaf recursion's
        # placement: 2 increments per replica slot
        assert sum(hist) == len(xs) * 3 * 2
        assert all(v >= 0 for v in hist)

    def test_tester_dump(self):
        m = bench_shape()
        cfg = TesterConfig(
            min_x=0, max_x=63, num_rep=3, show_choose_tries=True,
            backend="jax",  # transparently rerouted to ref for collection
        )
        out = io.StringIO()
        t = CrushTester(m.crush, cfg, out=out)
        t.test()
        text = out.getvalue()
        assert "choose_tries histogram" in text
        assert t.choose_tries is not None
        assert sum(t.choose_tries) == 64 * 3 * 2
        # dump starts at retry count 0 = first-draw successes
        assert " 0: " in text

    def test_crushtool_flag(self, tmp_path, capsys):
        from ceph_tpu.cli.crushtool import main
        from ceph_tpu.crush.compiler import decompile

        fn = tmp_path / "map.txt"
        fn.write_text(decompile(bench_shape().crush))
        rc = main(["-i", str(fn), "--test", "--min-x", "0", "--max-x",
                   "31", "--num-rep", "3", "--show-choose-tries"])
        assert rc == 0
        assert "choose_tries histogram" in capsys.readouterr().out


class TestFastWindowBound:
    """The PROFILE_r05 §5 claim, made falsifiable."""

    def test_mass_within_window(self, collected):
        _, _, _, hist = collected
        total = sum(hist)
        # ~96% of placements succeed on the first draw on this shape...
        assert hist[0] / total >= 0.9
        # ...and NOTHING needs more retries than the window slack
        assert sum(hist[FAST_WINDOW_EXTRA + 1:]) == 0

    def test_fast_kernel_agrees_with_histogram(self, collected):
        crush, w, xs, hist = collected
        A = build_arrays(crush)
        dev_w = np.asarray(w, np.uint32)
        # default window: the histogram said every placement fits, so
        # the fast kernel must flag no lane unresolved... measured via
        # the flagged variant the rescue machinery uses
        import jax
        from ceph_tpu.crush.mapper_jax import compile_rule

        fn = jax.jit(jax.vmap(
            compile_rule(A, 0, 3, with_flag=True), in_axes=(0, None)
        ))
        _, flg = fn(xs, dev_w)
        assert int(np.asarray(flg).sum()) == 0

    def test_zero_slack_window_rescues_exactly(self, collected):
        """Shrinking the window below the histogram's tail forces
        unresolved lanes; the loop-kernel rescue keeps the batch
        bit-exact regardless (the trade PROFILE_r05 §5 names)."""
        crush, w, xs, _ = collected
        A = build_arrays(crush)
        run = compile_batched(A, 0, 3, window_extra=0)
        got = np.asarray(run(xs, np.asarray(w, np.uint32)))
        for i, x in enumerate(xs[:64]):
            want = mapper_ref.do_rule(crush, 0, int(x), 3, list(w))
            want = (want + [ITEM_NONE] * 3)[:3]
            assert list(got[i]) == want, x
