"""Differential tests: vmapped JAX mapper vs the Python reference mapper
(which is itself differentially tested against the compiled C).  Exact
element-wise equality on the padded result vectors."""

import numpy as np
import pytest

from ceph_tpu.crush.mapper_ref import do_rule
from ceph_tpu.crush.mapper_jax import compile_batched
from ceph_tpu.crush.soa import build_arrays
from ceph_tpu.crush.types import (
    BucketAlg,
    ChooseArgs,
    CrushMap,
    ITEM_NONE,
    Rule,
    RuleOp,
    Tunables,
)

from util_maps import build_flat, build_tree, HOST, RACK, ROOT

N_X = 257


def compare_jax(m, ruleno, weights, result_max, n_x=N_X, choose_args=None):
    A = build_arrays(m, choose_args)
    fn = compile_batched(A, ruleno, result_max)
    xs = np.arange(n_x, dtype=np.uint32) * 2654435761 % (2**31)
    dev_w = np.zeros(max(m.max_devices, 1), np.uint32)
    dev_w[: len(weights)] = weights
    got = np.asarray(fn(xs, dev_w))
    if isinstance(choose_args, (int, str)):
        choose_args = m.choose_args.get(choose_args)
    for i, x in enumerate(xs):
        want = do_rule(m, ruleno, int(x), result_max, list(weights),
                       choose_args)
        want = (want + [ITEM_NONE] * result_max)[:result_max]
        assert list(got[i]) == want, (
            f"x={x}: jax={list(got[i])} ref={want}"
        )


@pytest.mark.parametrize("alg", [BucketAlg.STRAW2, BucketAlg.STRAW,
                                 BucketAlg.LIST, BucketAlg.TREE,
                                 BucketAlg.UNIFORM])
def test_flat_firstn(alg):
    m, root = build_flat(17, alg)
    r = m.make_replicated_rule(root, 0)
    compare_jax(m, r, [0x10000] * 17, 3)


@pytest.mark.parametrize("alg", [BucketAlg.STRAW2, BucketAlg.LIST,
                                 BucketAlg.TREE, BucketAlg.UNIFORM])
def test_flat_indep(alg):
    m, root = build_flat(10, alg)
    m.add_rule(Rule([(RuleOp.TAKE, root, 0),
                     (RuleOp.CHOOSE_INDEP, 0, 0),
                     (RuleOp.EMIT, 0, 0)], type=3))
    compare_jax(m, 0, [0x10000] * 10, 4)


def test_flat_weighted_straw2(rng):
    n = 25
    weights = [int(w) for w in rng.integers(1, 8 * 0x10000, n)]
    weights[3] = 0
    m = CrushMap()
    root = m.add_bucket(BucketAlg.STRAW2, ROOT, list(range(n)), weights)
    r = m.make_replicated_rule(root, 0)
    dev_w = [int(w) for w in rng.integers(0, 0x10001, n)]
    compare_jax(m, r, dev_w, 3)


@pytest.mark.parametrize("host_alg", [BucketAlg.STRAW2, BucketAlg.LIST,
                                      BucketAlg.TREE, BucketAlg.UNIFORM,
                                      BucketAlg.STRAW])
def test_chooseleaf_firstn(rng, host_alg):
    m, root = build_tree(rng, n_host=6, osd_per_host=4, host_alg=host_alg,
                         weight_fn=lambda i: 0x10000 + (i % 5) * 0x4000)
    r = m.make_replicated_rule(root, HOST)
    w = [0x10000] * 24
    w[3] = 0
    w[10] = 0x8000
    compare_jax(m, r, w, 3)


@pytest.mark.parametrize("host_alg", [BucketAlg.STRAW2, BucketAlg.UNIFORM])
def test_chooseleaf_indep_ec(rng, host_alg):
    m, root = build_tree(rng, n_host=8, osd_per_host=3, host_alg=host_alg)
    r = m.make_erasure_rule(root, HOST)
    w = [0x10000] * 24
    w[7] = 0
    compare_jax(m, r, w, 6)


def test_three_level(rng):
    m, root = build_tree(rng, n_host=8, osd_per_host=3, n_rack=4)
    r = m.make_replicated_rule(root, RACK)
    compare_jax(m, r, [0x10000] * 24, 3)


def test_choose_then_chooseleaf(rng):
    m, root = build_tree(rng, n_host=8, osd_per_host=3, n_rack=4)
    m.add_rule(Rule([(RuleOp.TAKE, root, 0),
                     (RuleOp.CHOOSE_FIRSTN, 2, RACK),
                     (RuleOp.CHOOSELEAF_FIRSTN, 2, HOST),
                     (RuleOp.EMIT, 0, 0)]))
    compare_jax(m, 0, [0x10000] * 24, 4)


def test_firefly_tunables(rng):
    t = Tunables.profile("firefly")
    m, root = build_tree(rng, n_host=5, osd_per_host=4, tunables=t,
                         weight_fn=lambda i: 0x10000 * (1 + i % 3))
    r = m.make_replicated_rule(root, HOST)
    w = [0x10000] * 20
    w[2] = 0
    w[7] = 0x4000
    compare_jax(m, r, w, 3)


def test_vary_r_stable_off(rng):
    m, root = build_tree(rng, n_host=6, osd_per_host=4)
    m.add_rule(Rule([
        (RuleOp.SET_CHOOSELEAF_VARY_R, 0, 0),
        (RuleOp.SET_CHOOSELEAF_STABLE, 0, 0),
        (RuleOp.TAKE, root, 0),
        (RuleOp.CHOOSELEAF_FIRSTN, 0, HOST),
        (RuleOp.EMIT, 0, 0)]))
    w = [0x10000] * 24
    w[5] = 0
    compare_jax(m, 0, w, 3)


def test_choose_args(rng):
    m, root = build_tree(rng, n_host=4, osd_per_host=4)
    r = m.make_replicated_rule(root, HOST)
    ca = ChooseArgs()
    for bid, b in m.buckets.items():
        ca.weight_sets[bid] = [
            [int(w) for w in rng.integers(1, 4 * 0x10000, b.size)]
            for _ in range(3)
        ]
    compare_jax(m, r, [0x10000] * 16, 3, choose_args=ca)


def test_degenerate_numrep_exceeds(rng):
    m, root = build_tree(rng, n_host=3, osd_per_host=2)
    rr = m.make_replicated_rule(root, HOST)
    re_ = m.make_erasure_rule(root, HOST)
    compare_jax(m, rr, [0x10000] * 6, 3)
    compare_jax(m, re_, [0x10000] * 6, 5)


def test_all_out_devices(rng):
    m, root = build_tree(rng, n_host=4, osd_per_host=2)
    r = m.make_replicated_rule(root, HOST)
    compare_jax(m, r, [0] * 8, 3)  # everything out -> empty result


def test_indep_numrep_exceeds_result_max(rng):
    """CHOOSE_INDEP with arg1 > result_max: the r-stride must use the full
    numrep even though output is capped (review regression)."""
    m, root = build_flat(12, BucketAlg.STRAW2)
    m.add_rule(Rule([(RuleOp.TAKE, root, 0),
                     (RuleOp.CHOOSE_INDEP, 6, 0),
                     (RuleOp.EMIT, 0, 0)], type=3))
    w = [0x10000] * 12
    for i in (1, 4, 6):
        w[i] = 0  # force retries
    compare_jax(m, 0, w, 3)


def test_firstn_numrep_exceeds_result_max(rng):
    """CHOOSE_FIRSTN with arg1 > result_max: skipped reps must be
    compensated by later rep values (review regression)."""
    m, root = build_flat(12, BucketAlg.STRAW2)
    m.add_rule(Rule([(RuleOp.SET_CHOOSE_TRIES, 2, 0),
                     (RuleOp.TAKE, root, 0),
                     (RuleOp.CHOOSE_FIRSTN, 6, 0),
                     (RuleOp.EMIT, 0, 0)]))
    w = [0x10000] * 12
    for i in (0, 2, 3, 5, 7, 8, 10):
        w[i] = 0
    compare_jax(m, 0, w, 3)


def test_chooseleaf_indep_type0_stale_out2():
    """reference src/crush/mapper.c:799-801: a found device is written to
    out2 before the is_out check, so an always-rejected (weight-0) device is
    still emitted after tries exhaust."""
    m, root = build_flat(8)
    ruleno = m.add_rule(Rule([
        (RuleOp.TAKE, root, 0),
        (RuleOp.CHOOSELEAF_INDEP, 4, 0),
        (RuleOp.EMIT, 0, 0),
    ], type=3))
    weights = [0x10000] * 8
    for dead in (2, 5, 6):
        weights[dead] = 0
    compare_jax(m, ruleno, weights, 4, n_x=64)


# ---------------------------------------------------------------------------
# Row-path (gather-free unrolled descent) differential coverage.  The row
# path only auto-activates on accelerator backends; FORCE_ROW_PATH=True
# exercises it under the CPU test mesh, against the same host oracle.
# ---------------------------------------------------------------------------

@pytest.fixture(params=[
    # the scan-ln variant compiles the same unrolled descent a second
    # time (~2 min across the five tests) and differs only in the
    # crush_ln kernel; onehot is the accelerator default, scan rides in
    # the slow tier (tier-1 budget is tight)
    pytest.param("scan", marks=pytest.mark.slow),
    "onehot",
])
def row_path(request):
    from ceph_tpu.crush import mapper_jax as mj

    mj.FORCE_ROW_PATH = True
    mj.LN_IMPL = request.param
    yield request.param
    mj.FORCE_ROW_PATH = None
    mj.LN_IMPL = None


def test_rowpath_chooseleaf_three_level(rng, row_path):
    m, root = build_tree(rng, n_host=8, osd_per_host=3, n_rack=4,
                         weight_fn=lambda i: 0x10000 + (i % 7) * 0x3000)
    r = m.make_replicated_rule(root, HOST)
    w = [0x10000] * 24
    w[3] = 0
    w[10] = 0x8000
    compare_jax(m, r, w, 3)


def test_rowpath_choose_then_chooseleaf(rng, row_path):
    m, root = build_tree(rng, n_host=8, osd_per_host=3, n_rack=4)
    m.add_rule(Rule([(RuleOp.TAKE, root, 0),
                     (RuleOp.CHOOSE_FIRSTN, 2, RACK),
                     (RuleOp.CHOOSELEAF_FIRSTN, 2, HOST),
                     (RuleOp.EMIT, 0, 0)]))
    compare_jax(m, 0, [0x10000] * 24, 4)


def test_rowpath_indep_ec(rng, row_path):
    m, root = build_tree(rng, n_host=8, osd_per_host=3)
    r = m.make_erasure_rule(root, HOST)
    w = [0x10000] * 24
    w[7] = 0
    compare_jax(m, r, w, 6)


def test_rowpath_mixed_algs(rng, row_path):
    """straw + list hosts take the row form; a tree host forces the
    per-level gather fallback within the same unrolled descent."""
    for alg in (BucketAlg.STRAW, BucketAlg.LIST, BucketAlg.TREE):
        m, root = build_tree(rng, n_host=5, osd_per_host=4, host_alg=alg)
        r = m.make_replicated_rule(root, HOST)
        w = [0x10000] * 20
        w[2] = 0
        compare_jax(m, r, w, 3, n_x=101)


def test_rowpath_onehot_reach(rng, row_path):
    """Reach sets >= _REACH_ONEHOT_MIN fetch rows via the one-hot matmul;
    32 hosts crosses the threshold."""
    m, root = build_tree(rng, n_host=32, osd_per_host=2,
                         weight_fn=lambda i: 0x10000 + (i % 11) * 0x1000)
    r = m.make_replicated_rule(root, HOST)
    w = [0x10000] * 64
    w[5] = 0
    w[33] = 0x4000
    compare_jax(m, r, w, 3, n_x=101)


def test_choose_args_positions_row_path_fallback(rng):
    """positions>1 weight-sets through BOTH kernels of both rule types:
    the row path must fall back per level (a _RowLevel with positions>1
    is not row_ok) and stay bit-exact, pinning the compat weight-set
    path the mgr balancer writes (VERDICT r5 item 6)."""
    from ceph_tpu.crush import mapper_jax

    m, root = build_tree(rng, n_host=4, osd_per_host=4)
    rrep = m.make_replicated_rule(root, HOST)
    rind = m.add_rule(Rule([
        (RuleOp.TAKE, root, 0),
        (RuleOp.CHOOSELEAF_INDEP, 0, HOST),
        (RuleOp.EMIT, 0, 0)], ruleset=1, type=3))
    ca = ChooseArgs()
    for bid, b in m.buckets.items():
        ca.weight_sets[bid] = [
            [int(w) for w in rng.integers(1, 4 * 0x10000, b.size)]
            for _ in range(3)
        ]
        ca.ids[bid] = [int(i) + 7 if i >= 0 else int(i) for i in b.items]
    old = mapper_jax.FORCE_ROW_PATH
    try:
        mapper_jax.FORCE_ROW_PATH = True
        compare_jax(m, rrep, [0x10000] * 16, 3, n_x=65, choose_args=ca)
        compare_jax(m, rind, [0x10000] * 16, 3, n_x=65, choose_args=ca)
    finally:
        mapper_jax.FORCE_ROW_PATH = old
