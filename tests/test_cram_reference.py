"""Golden-transcript tests against the reference's own cram suite.

The reference pins exact crushtool behavior in cram transcripts
(reference src/test/cli/crushtool/*.t) that run `--test` on *binary*
crushmap fixtures and list every expected mapping line.  Decoding those
fixtures with our wire codec and replaying the tester against the expected
output proves end-to-end bit-exactness — codec + map model + mapper — on
the reference's own data, across all tunables generations (legacy/bobtail/
firefly/hammer/jewel), vary-r 0..4, firstn+indep, and tries-vs-retries.
"""

from __future__ import annotations

import io
import re
from pathlib import Path

import pytest

from ceph_tpu.crush.codec import decode_crushmap
from ceph_tpu.crush.tester import CrushTester, TesterConfig

CRAM_DIR = Path("/root/reference/src/test/cli/crushtool")

# transcripts whose --test commands we replay (binary fixture + mappings)
CRAM_FILES = [
    "test-map-a.t",
    "test-map-vary-r-0.t",
    "test-map-vary-r-1.t",
    "test-map-vary-r-2.t",
    "test-map-vary-r-3.t",
    "test-map-vary-r-4.t",
    "test-map-legacy-tunables.t",
    "test-map-bobtail-tunables.t",
    "test-map-firefly-tunables.t",
    "test-map-hammer-tunables.t",
    "test-map-jewel-tunables.t",
    "test-map-firstn-indep.t",
    "test-map-indep.t",
    "test-map-tries-vs-retries.t",
    "bad-mappings.t",
]

_SET_TUNABLES = {
    "--set-choose-local-tries": "choose_local_tries",
    "--set-choose-local-fallback-tries": "choose_local_fallback_tries",
    "--set-choose-total-tries": "choose_total_tries",
    "--set-chooseleaf-descend-once": "chooseleaf_descend_once",
    "--set-chooseleaf-vary-r": "chooseleaf_vary_r",
    "--set-chooseleaf-stable": "chooseleaf_stable",
}


def parse_cram(path: Path):
    """Yield (mapfile, argv, expected_lines) for each crushtool --test."""
    lines = path.read_text().splitlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        m = re.match(r'^  \$ crushtool -i "\$TESTDIR/([^"]+)" --test(.*)$',
                     line)
        if not m:
            i += 1
            continue
        mapfile, rest = m.group(1), m.group(2)
        argv = rest.split()
        expected = []
        i += 1
        while i < len(lines):
            ln = lines[i]
            if ln.startswith("  $") or not ln.startswith("  "):
                break
            body = ln[2:]
            if body.endswith(" (esc)"):
                # cram escape format: python string escapes + " (esc)"
                body = (
                    body[: -len(" (esc)")]
                    .encode()
                    .decode("unicode_escape")
                )
            # the reference tool's exit chrome, not tester output
            if not body.startswith(
                "crushtool successfully built or modified map"
            ):
                expected.append(body)
            i += 1
        yield mapfile, argv, expected


def build_config(argv: list[str]):
    """-> (cfg, tunable_overrides) or None if an unsupported flag appears."""
    cfg = TesterConfig(backend="native")
    overrides: dict[str, int] = {}
    i = 0
    while i < len(argv):
        a = argv[i]

        def nxt():
            nonlocal i
            i += 1
            return argv[i]

        if a == "--show-mappings":
            cfg.show_mappings = True
        elif a == "--show-statistics":
            cfg.show_statistics = True
        elif a == "--show-bad-mappings":
            cfg.show_bad_mappings = True
        elif a == "--rule":
            cfg.rule = int(nxt())
        elif a == "--x":
            cfg.min_x = cfg.max_x = int(nxt())
        elif a == "--min-x":
            cfg.min_x = int(nxt())
        elif a == "--max-x":
            cfg.max_x = int(nxt())
        elif a == "--num-rep":
            cfg.num_rep = int(nxt())
        elif a == "--weight":
            osd = int(nxt())
            w = float(nxt())
            cfg.weights[osd] = int(w * 0x10000)
        elif a in _SET_TUNABLES:
            overrides[_SET_TUNABLES[a]] = int(nxt())
        else:
            return None  # unsupported flag; skip this command
        i += 1
    return cfg, overrides


def _cases():
    cases = []
    for fname in CRAM_FILES:
        path = CRAM_DIR / fname
        if not path.exists():
            continue
        for j, (mapfile, argv, expected) in enumerate(parse_cram(path)):
            cases.append(
                pytest.param(mapfile, argv, expected, id=f"{fname}:{j}")
            )
    return cases


@pytest.mark.skipif(not CRAM_DIR.is_dir(), reason="no reference mount")
class TestReferenceCram:
    @pytest.mark.parametrize("mapfile,argv,expected", _cases())
    def test_transcript(self, mapfile, argv, expected):
        from ceph_tpu.native import mapper as native_mapper

        path = CRAM_DIR / mapfile
        if path.exists():
            m = decode_crushmap(path.read_bytes())
        elif (CRAM_DIR / (mapfile + ".txt")).exists():
            # the cram builds this map from text source; so do we
            from ceph_tpu.crush.compiler import compile_text

            m = compile_text((CRAM_DIR / (mapfile + ".txt")).read_text())
        else:
            pytest.skip(f"fixture {mapfile} missing")
        parsed = build_config(argv)
        if parsed is None:
            pytest.skip(f"unsupported flags: {argv}")
        cfg, overrides = parsed
        for k, v in overrides.items():
            setattr(m.tunables, k, v)
        if not native_mapper.available():
            cfg.backend = "ref"
            if cfg.max_x - cfg.min_x > 64:
                pytest.skip("python backend too slow for full range")
        buf = io.StringIO()
        CrushTester(m, cfg, out=buf).test()
        got = buf.getvalue().splitlines()
        assert got == expected, (
            "transcript mismatch: first diff at line "
            f"{next((i for i, (a, b) in enumerate(zip(got, expected)) if a != b), min(len(got), len(expected)))}"
            f"\n got[:5]={got[:5]}\n want[:5]={expected[:5]}"
            f"\n lens {len(got)} vs {len(expected)}"
        )


class TestCodecRoundtrip:
    def test_fixture_decode_reencode(self):
        fixtures = sorted(CRAM_DIR.glob("*.crushmap")) + sorted(
            CRAM_DIR.glob("*.crush")
        )
        if not fixtures:
            pytest.skip("no reference fixtures")
        from ceph_tpu.crush.codec import looks_like_crushmap

        count = 0
        for f in fixtures:
            data = f.read_bytes()
            if not looks_like_crushmap(data):
                continue  # some fixtures are text despite the extension
            try:
                m = decode_crushmap(data)
            except Exception as e:
                raise AssertionError(f"{f.name}: decode failed: {e}")
            from ceph_tpu.crush.codec import encode_crushmap

            data2 = encode_crushmap(m)
            m2 = decode_crushmap(data2)
            assert m2.buckets.keys() == m.buckets.keys(), f.name
            for bid in m.buckets:
                b1, b2 = m.buckets[bid], m2.buckets[bid]
                assert (b1.items, b1.weights, b1.alg, b1.type, b1.hash) == (
                    b2.items, b2.weights, b2.alg, b2.type, b2.hash
                ), f.name
            assert [r.steps if r else None for r in m.rules] == [
                r.steps if r else None for r in m2.rules
            ], f.name
            assert m2.tunables == m.tunables, f.name
            assert m2.item_classes == m.item_classes, f.name
            count += 1
        assert count >= 5

    def test_own_map_roundtrip_simple(self):
        from ceph_tpu.crush.codec import encode_crushmap
        from ceph_tpu.osd.osdmap import build_hierarchical
        from ceph_tpu.osd.types import PgPool

        m = build_hierarchical(4, 4, pool=PgPool(pg_num=32))
        data = encode_crushmap(m.crush)
        m2 = decode_crushmap(data)
        assert m2.buckets.keys() == m.crush.buckets.keys()
        assert m2.item_names == m.crush.item_names
        assert m2.rule_names == m.crush.rule_names
        # mapping equivalence
        from ceph_tpu.crush import mapper_ref

        w = [0x10000] * 16
        for x in range(64):
            assert mapper_ref.do_rule(m2, 0, x, 3, w) == mapper_ref.do_rule(
                m.crush, 0, x, 3, w
            )
