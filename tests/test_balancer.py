"""Upmap balancer tests — property-based, after the reference's
TestOSDMap upmap cases (reference src/test/osd/TestOSDMap.cc:622-790):
build synthetic unbalanced maps, run calc_pg_upmaps, check that the
produced pg_upmap_items are valid and the distribution improves."""

import numpy as np
import pytest

from ceph_tpu.balancer import calc_pg_upmaps
from ceph_tpu.balancer.crush_analysis import (
    get_parent_of_type,
    get_rule_weight_osd_map,
    subtree_contains,
)
from ceph_tpu.balancer.upmap import try_remap_rule
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgId, PgPool, PoolType


def _map(n_host=4, per=4, pg_num=128, size=3):
    pool = PgPool(
        type=PoolType.REPLICATED, size=size, crush_rule=0,
        pg_num=pg_num, pgp_num=pg_num,
    )
    return build_hierarchical(n_host, per, pool=pool)


def _pg_counts(m, pool_id=0):
    counts = {}
    pool = m.pools[pool_id]
    for ps in range(pool.pg_num):
        up, _, _, _ = m.pg_to_up_acting_osds(PgId(pool_id, ps))
        for o in up:
            if o != ITEM_NONE:
                counts[o] = counts.get(o, 0) + 1
    return counts


class TestCrushAnalysis:
    def test_subtree_and_parent(self):
        m = _map()
        crush = m.crush
        by_name = {v: k for k, v in crush.item_names.items()}
        host0 = by_name["host0"]
        root = by_name["default"]
        assert subtree_contains(crush, root, 0)
        assert subtree_contains(crush, host0, 0)
        assert not subtree_contains(crush, host0, 5)
        assert get_parent_of_type(crush, 0, 1, 0) == host0
        assert get_parent_of_type(crush, 0, 1) == host0

    def test_rule_weight_map(self):
        m = _map(n_host=2, per=2)
        pmap = get_rule_weight_osd_map(m.crush, 0)
        assert set(pmap) == {0, 1, 2, 3}
        assert all(abs(v - 0.25) < 1e-6 for v in pmap.values())


class TestTryRemapRule:
    def test_swaps_overfull_for_underfull(self):
        m = _map(n_host=4, per=2)
        # orig maps to osds 0,2,4 (hosts 0,1,2); evacuate 0 -> want 6 or 7
        out = try_remap_rule(
            m, 0, 3, overfull={0}, underfull=[6], more_underfull=[],
            orig=[0, 2, 4],
        )
        assert out == [6, 2, 4]

    def test_respects_failure_domain(self):
        m = _map(n_host=4, per=2)
        # 3 is on host1 which already hosts 2: replacement must come from
        # the same chooseleaf subtree walk, so 2->? can't land on host of 4
        out = try_remap_rule(
            m, 0, 3, overfull={2}, underfull=[3], more_underfull=[],
            orig=[0, 2, 4],
        )
        # 3 shares host with 2: still a valid swap (same subtree)
        assert out == [0, 3, 4]

    def test_no_op_when_no_overfull_in_orig(self):
        m = _map(n_host=4, per=2)
        out = try_remap_rule(
            m, 0, 3, overfull={7}, underfull=[6], more_underfull=[],
            orig=[0, 2, 4],
        )
        assert out == [0, 2, 4]


def _assert_valid_upmaps(m, pool_id=0):
    pool = m.pools[pool_id]
    for pg, items in m.pg_upmap_items.items():
        assert pg.pool == pool_id and pg.seed < pool.pg_num
        for frm, to in items:
            assert 0 <= to < m.max_osd and m.exists(to)
    # mappings stay duplicate-free and full-size
    for ps in range(pool.pg_num):
        up, _, _, _ = m.pg_to_up_acting_osds(PgId(pool_id, ps))
        real = [o for o in up if o != ITEM_NONE]
        assert len(real) == len(set(real)) == pool.size


class TestCalcPgUpmaps:
    @staticmethod
    def _sq_dev_vs_target(m):
        """Sum of squared deviations from the weight-proportional target —
        the objective calc_pg_upmaps minimizes (OSDMap.cc:4707-4732)."""
        pmap = get_rule_weight_osd_map(m.crush, 0)
        total_w = sum(
            m.get_weightf(o) * w for o, w in pmap.items()
        )
        pool = m.pools[0]
        total_pgs = pool.size * pool.pg_num
        counts = _pg_counts(m)
        s = 0.0
        for o, w in pmap.items():
            target = m.get_weightf(o) * w / total_w * total_pgs
            d = counts.get(o, 0) - target
            s += d * d
        return s

    @pytest.mark.parametrize("use_tpu", [False, True])
    @pytest.mark.parametrize("skewed", [False, True])
    def test_balances_cluster(self, use_tpu, skewed):
        if use_tpu and skewed:
            pytest.skip("same code path as use_tpu+uniform")
        pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                      pg_num=256, pgp_num=256)
        wf = (lambda o: 0x20000 if o < 4 else 0x10000) if skewed else None
        m = build_hierarchical(4, 4, pool=pool, weight_fn=wf)
        dev_before = self._sq_dev_vs_target(m)
        res = calc_pg_upmaps(
            m, max_deviation=1, max_iter=20, use_tpu=use_tpu,
            rng=np.random.default_rng(42),
        )
        dev_after = self._sq_dev_vs_target(m)
        _assert_valid_upmaps(m)
        if res.num_changed:
            assert dev_after < dev_before
        assert res.stddev >= 0

    def test_converges_and_is_stable(self):
        m = _map(n_host=4, per=4, pg_num=256)
        r1 = calc_pg_upmaps(
            m, max_deviation=1, max_iter=50, use_tpu=False,
            rng=np.random.default_rng(1),
        )
        # second run from the balanced state should do (almost) nothing
        r2 = calc_pg_upmaps(
            m, max_deviation=1, max_iter=50, use_tpu=False,
            rng=np.random.default_rng(2),
        )
        _assert_valid_upmaps(m)
        assert r2.num_changed <= max(2, r1.num_changed // 4)

    def test_already_perfect_returns_zero(self):
        m = _map(n_host=4, per=4, pg_num=256)
        res = calc_pg_upmaps(m, max_deviation=100, use_tpu=False)
        assert res.num_changed == 0

    def test_batched_pipeline_agrees_after_balancing(self):
        """The TPU overlay path must reproduce the balanced mapping."""
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        m = _map(n_host=4, per=4, pg_num=128)
        calc_pg_upmaps(
            m, max_deviation=1, max_iter=20, use_tpu=False,
            rng=np.random.default_rng(3),
        )
        if not m.pg_upmap_items:
            pytest.skip("balancer made no changes on this map")
        pm = PoolMapper(m, 0)
        up, upp, acting, actp = pm.map_all()
        pool = m.pools[0]
        for ps in range(pool.pg_num):
            w_up, w_upp, w_act, w_actp = m.pg_to_up_acting_osds(
                PgId(0, ps)
            )
            got = [o for o in up[ps] if o != ITEM_NONE]
            assert got == w_up, f"ps={ps}"
            assert upp[ps] == w_upp


class TestDeviceBackend:
    """The device-resident membership backend (balancer/state.DeviceState)
    must make byte-identical decisions to the reference-faithful
    dict-of-sets backend — same rng, same change sequence, same result."""

    def _pair(self, pg_num=512, n_host=8, per=4, seed=42, mesh=None,
              **kw):
        def mk():
            return _map(n_host=n_host, per=per, pg_num=pg_num)

        m1, m2 = mk(), mk()
        r1 = calc_pg_upmaps(
            m1, rng=np.random.default_rng(seed), backend="sets", **kw
        )
        r2 = calc_pg_upmaps(
            m2, rng=np.random.default_rng(seed), backend="device",
            mesh=mesh, **kw
        )
        assert m1.pg_upmap_items == m2.pg_upmap_items
        assert r1.old_pg_upmap_items == r2.old_pg_upmap_items
        assert r1.num_changed == r2.num_changed
        assert abs(r1.stddev - r2.stddev) < 1e-6
        return m2

    def test_equivalent_small(self):
        m = self._pair(max_deviation=1, max_iter=8)
        _assert_valid_upmaps(m)

    def test_equivalent_second_round_drops(self):
        """Dropping existing pairs (the overfull/underfull un-remap paths)
        must also match: run two successive optimization rounds."""
        def run(backend):
            m = _map(n_host=8, per=4, pg_num=512)
            calc_pg_upmaps(
                m, max_deviation=1, max_iter=6,
                rng=np.random.default_rng(7), backend=backend,
            )
            # perturb: mark one osd out, rebalance again (pairs now drop)
            m.osd_weight[5] = 0
            calc_pg_upmaps(
                m, max_deviation=1, max_iter=6,
                rng=np.random.default_rng(8), backend=backend,
            )
            return m

        m1, m2 = run("sets"), run("device")
        assert m1.pg_upmap_items == m2.pg_upmap_items

    def test_equivalent_sharded_mesh(self):
        """Device backend with membership rows sharded over the 8-device
        CPU mesh (the ParallelPGMapper analogue, reference
        src/osd/OSDMapMapping.h:18-140) — same decisions again."""
        from ceph_tpu.parallel.sharded import make_mesh

        m = self._pair(
            max_deviation=1, max_iter=6, mesh=make_mesh(8), pg_num=1024
        )
        _assert_valid_upmaps(m)
