"""Trace-once pipeline contract: the compiled-pipeline cache and the
constant-folding regression guard.

Per-map data (bucket tables, straw2 planes, osd weight/state vectors)
rides as runtime operands; only structural facts are baked into the
trace and summarized by `fn.cache_key`.  So:

  * two maps that differ only in weights / osd state / choose_args
    VALUES share one compiled executable through _PIPE_CACHE — zero new
    XLA compiles (the balancer-iteration shape);
  * shape / rule / tunable changes produce different cache_keys (a miss
    is correct — the trace really differs);
  * the traced program embeds no table-sized literal, so XLA never
    constant-folds a [65536, ...] pred tensor again (BENCH_r05 burned
    >2s per compile on exactly that).

Counter-based assertions use deltas (the perf registry and _PIPE_CACHE
are process-global and other tests may have warmed them).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from ceph_tpu.osd.osdmap import build_hierarchical
from ceph_tpu.osd.types import PgId, PgPool, PoolType

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ceph_tpu import obs  # noqa: E402
from ceph_tpu.crush.types import ITEM_NONE  # noqa: E402
from ceph_tpu.osd.pipeline_jax import (  # noqa: E402
    PoolMapper,
    PoolSpec,
    compile_pipeline,
)


def _mk_map(n_pgs, n_osds=64, per_host=8):
    pool = PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=n_pgs, pgp_num=n_pgs,
    )
    n_host = max(1, n_osds // per_host)
    return build_hierarchical(
        n_host, per_host, n_rack=max(1, n_host // 4), pool=pool
    )


_jit_counters = obs.jit_counters
_delta = obs.jit_counters_delta


# -- cache_key semantics (no jit, cheap) ------------------------------------

def test_cache_key_ignores_weights_and_choose_args_values():
    """Weight / choose-args VALUE changes keep the structural signature."""
    from ceph_tpu.crush.soa import build_arrays

    m1 = _mk_map(512)
    m2 = _mk_map(512)
    for o in (1, 5, 9):
        m2.osd_weight[o] = int(0x10000 * 0.5)
    # a compat weight-set on m2 only: still values, not structure
    from ceph_tpu.mgr.module import compat_ws_to_choose_args

    m2.crush.choose_args[-1] = compat_ws_to_choose_args(
        m2.crush, {o: 1.0 for o in range(m2.max_osd)}
    )
    keys = []
    for m in (m1, m2):
        ca = m.crush.choose_args.get(0, m.crush.choose_args.get(-1))
        A = build_arrays(m.crush, ca)
        spec = PoolSpec.for_pool(m, 0)
        keys.append(compile_pipeline(A, spec).cache_key)
    assert keys[0] == keys[1]


def test_cache_key_misses_on_structural_change():
    """pg_num / tunables / rule changes MUST change the key."""
    from ceph_tpu.crush.soa import build_arrays

    def key_of(m):
        A = build_arrays(m.crush, None)
        return compile_pipeline(A, PoolSpec.for_pool(m, 0)).cache_key

    base = key_of(_mk_map(512))
    assert key_of(_mk_map(640)) != base  # pg_num
    mt = _mk_map(512)
    mt.crush.tunables.choose_total_tries += 7
    assert key_of(mt) != base  # tunables
    mw = _mk_map(512)
    mw.pools[0].size = 2  # numrep/out width
    assert key_of(mw) != base


def test_pool_operands_key_spans_pools():
    """With pool_operands the pool identity / pg counts are u32 operands:
    pools sharing rule/size/osd-bound share the key (tunables still
    miss — the trace really differs)."""
    from ceph_tpu.crush.soa import build_arrays

    def key_of(m):
        A = build_arrays(m.crush, None)
        return compile_pipeline(
            A, PoolSpec.for_pool(m, 0), pool_operands=True
        ).cache_key

    base = key_of(_mk_map(512))
    assert key_of(_mk_map(640)) == base  # pg_num is an operand now
    mt = _mk_map(512)
    mt.crush.tunables.choose_total_tries += 7
    assert key_of(mt) != base


@pytest.mark.slow
def test_cross_pool_sharing_zero_compiles():
    """Two maps whose pools differ in pg_num (and hence pps math inputs)
    dispatch the SAME executable at a fixed block shape — zero compiles,
    rows bit-exact per pool (the testmappgs/headline bench sharing)."""
    n1, n2 = 1100, 1900
    pm1 = PoolMapper(_mk_map(n1), 0, chunk=512)
    pm1.map_all()
    m2 = _mk_map(n2)
    c0 = _jit_counters()
    pm2 = PoolMapper(m2, 0, chunk=512)
    up2, _, _, _ = pm2.map_all()
    d = _delta(c0)
    assert d["compiles"] == 0 and d["retraces"] == 0, d
    assert d["pipe_cache_hits"] >= 1, d
    for s in range(0, n2, 173):
        want, _, _, _ = m2.pg_to_up_acting_osds(PgId(0, s))
        got = [int(x) for x in up2[s] if x != ITEM_NONE]
        assert got == list(want), (s, got, want)


# -- executable sharing through _PIPE_CACHE ---------------------------------

def _warm_both_kernels(pm: PoolMapper):
    """Compile fast AND loop kernels at the full-pool block shape so
    later deltas isolate executable reuse (jax compiles per shape; the
    loop kernel otherwise compiles lazily on the first rescue, at the
    rescue-tier shapes)."""
    from ceph_tpu.crush.mapper_jax import RESCUE_PADS

    pm.map_all()
    for p in RESCUE_PADS:
        ps = np.zeros(p, np.uint32)
        pm.jitted_loop()(jnp.asarray(ps), pm.dev, {})


def test_same_shape_weight_change_hits_pipe_cache():
    """The exact shape of a balancer iteration: same structure, new
    weights -> 0 new compiles, 0 retraces, rows still bit-exact."""
    n = 832  # tier-1 budget: small map; compile cost is size-independent
    _warm_both_kernels(PoolMapper(_mk_map(n), 0))
    m2 = _mk_map(n)
    for o in (3, 7, 11, 40):
        m2.osd_weight[o] = int(0x10000 * 0.7)
    c0 = _jit_counters()
    pm2 = PoolMapper(m2, 0)
    up, _, _, _ = pm2.map_batch(np.arange(n, dtype=np.uint32))
    d = _delta(c0)
    assert d["compiles"] == 0, d
    assert d["retraces"] == 0, d
    assert d["pipe_cache_hits"] >= 2, d  # fast + loop JitAccounts reused
    assert d["pipe_cache_misses"] == 0, d
    for s in range(0, n, 131):  # spot-check against the host oracle
        want, _, _, _ = m2.pg_to_up_acting_osds(PgId(0, s))
        got = [int(x) for x in up[s] if x != ITEM_NONE]
        assert got == list(want), (s, got, want)


@pytest.mark.slow
def test_structural_change_misses_pipe_cache():
    """Counter-level form of the key-miss test (a real second compile —
    slow; the cache_key inequality itself is tier-1 above)."""
    n = 1248
    mt = _mk_map(n)
    mt.crush.tunables.choose_total_tries += 5
    c0 = _jit_counters()
    PoolMapper(mt, 0).map_batch(np.arange(256, dtype=np.uint32))
    d = _delta(c0)
    assert d["pipe_cache_misses"] >= 1, d
    assert d["compiles"] >= 1, d


# -- the acceptance shape: a crush-compat round -----------------------------

def test_crush_compat_compiles_only_in_iteration_one():
    """ISSUE 5 acceptance: a 3-iteration do_crush_compat round on a
    same-shape map reports exactly the compile count of iteration 1 —
    every weight-set re-score past the first is a cache hit (the
    weight-set values are operands, not new traces)."""
    from ceph_tpu.mgr import Balancer, MappingState, synthetic_pg_stats

    snaps = []

    class CountingBalancer(Balancer):
        def eval(self, ms, pools=None):
            r = super().eval(ms, pools)
            snaps.append(_jit_counters())
            return r

    m = _mk_map(1024)
    rng = np.random.default_rng(7)
    for o in rng.choice(64, 4, replace=False):
        m.osd_weight[int(o)] = int(0x10000 * 0.8)
    bal = CountingBalancer(
        options={"crush_compat_max_iterations": 3},
        rng=np.random.default_rng(17),
    )
    ms = MappingState(m, synthetic_pg_stats(m), mapper="jax")
    plan = bal.plan_create("t", ms, mode="crush-compat")
    rc, detail = bal.optimize(plan)
    assert rc == 0, detail
    # snaps[0] = initial score, snaps[1..] = one per loop iteration
    assert len(snaps) >= 4, len(snaps)  # 3 full iterations ran
    it1, final = snaps[1], snaps[-1]
    assert final["compiles"] == it1["compiles"], snaps
    assert final["retraces"] == it1["retraces"], snaps
    # and the later iterations really went through the caches
    assert final["cache_hits"] > it1["cache_hits"], snaps
    assert final["pipe_cache_hits"] > it1["pipe_cache_hits"], snaps


@pytest.mark.slow
def test_upmap_round_compiles_once_per_shape():
    """A do_upmap optimize round on a warmed structure: zero compiles
    (the overlay-free eval kernel is shared; accumulated pg_upmap
    entries are host fixups, not new traces)."""
    from ceph_tpu.mgr import Balancer, MappingState, synthetic_pg_stats

    n = 1408
    m = _mk_map(n)
    rng = np.random.default_rng(11)
    for o in rng.choice(64, 4, replace=False):
        m.osd_weight[int(o)] = int(0x10000 * 0.75)
    _warm_both_kernels(PoolMapper(m, 0, overlays=False))
    bal = Balancer(
        options={"upmap_max_optimizations": 8},
        rng=np.random.default_rng(3),
    )
    ms = MappingState(m, synthetic_pg_stats(m), mapper="jax")
    c0 = _jit_counters()
    plan = bal.plan_create("t", ms, mode="upmap")
    rc, detail = bal.optimize(plan)
    bal.eval(plan.final_state())  # re-score the result as well
    d = _delta(c0)
    assert d["compiles"] == 0, (rc, detail, d)
    assert d["retraces"] == 0, d


# -- constant-folding regression guard --------------------------------------

MAX_LITERAL = 4096


def _collect_consts(j, acc):
    for c in getattr(j, "consts", ()):
        acc.append(c)
    core = getattr(j, "jaxpr", j)
    for eqn in core.eqns:
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for w in vs:
                if hasattr(w, "eqns") or hasattr(w, "jaxpr"):
                    _collect_consts(w, acc)
    return acc


def _big_consts(jaxpr):
    return [
        tuple(c.shape) for c in _collect_consts(jaxpr, [])
        if getattr(c, "size", 0) > MAX_LITERAL
    ]


def test_no_table_literals_in_headline_trace():
    """The headline-shaped pipeline (1024 OSDs) traces fast and embeds
    NO table-sized literal: every table >4096 elements is an operand.
    (BENCH_r05: XLA spent >2s constant-folding a pred[65536,11] literal
    per compile; a baked table would reappear here as a giant const.)"""
    m = _mk_map(4096, n_osds=1024, per_host=16)
    pm = PoolMapper(m, 0, overlays=False)
    vfast = jax.vmap(pm._fast, in_axes=(0, None, 0))
    t0 = time.monotonic()
    jaxpr = jax.make_jaxpr(vfast)(
        jnp.zeros(65536, jnp.uint32), pm.dev, {}
    )
    trace_s = time.monotonic() - t0
    assert trace_s < 30.0, f"trace took {trace_s:.1f}s"
    assert _big_consts(jaxpr) == []


def test_guard_detects_baked_tables():
    """Negative control: the legacy bare-fn path (no operand pytree)
    bakes the tables as trace constants — the guard must see them, or
    the positive test above proves nothing."""
    m = _mk_map(512, n_osds=1024, per_host=16)
    pm = PoolMapper(m, 0, overlays=False)
    dev = {k: v for k, v in pm.dev.items() if k != "crush"}
    vfast = jax.vmap(pm._fast, in_axes=(0, None, 0))
    jaxpr = jax.make_jaxpr(vfast)(jnp.zeros(512, jnp.uint32), dev, {})
    assert _big_consts(jaxpr) != []


# -- EC GF tables: one device_put per backend -------------------------------

def test_gf_device_tables_cached_per_backend():
    from ceph_tpu.ec.gf import _DEV_TABLES, gf_device_tables

    t1 = gf_device_tables()
    t2 = gf_device_tables()
    assert t1 is t2  # same dict object: no re-upload
    assert set(t1) == {"exp", "log", "mul"}
    b = jax.default_backend()
    assert _DEV_TABLES[b] is t1
    assert t1["exp"].shape == (512,)
    assert t1["mul"].shape == (256, 256)


def test_gf_logexp_kernel_uses_cached_tables():
    """Two encodes with different matrices share the device tables (the
    r05 gap: per-call re-upload of log/exp on every retrace)."""
    from ceph_tpu.ec.gf import gf_device_tables
    from ceph_tpu.ec.jax_backend import JaxEngine, _matmul_logexp

    gft = gf_device_tables()
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(4, 1024), dtype=np.uint8)
    M = np.array([[1, 1, 1, 1], [1, 2, 4, 8]], dtype=np.uint8)
    mt = tuple(tuple(int(c) for c in r) for r in M)
    out = np.asarray(_matmul_logexp(mt, jnp.asarray(data),
                                    gft["exp"], gft["log"]))
    # reference via the numpy mul table
    from ceph_tpu.ec.gf import GF_MUL_TABLE

    want = np.zeros((2, 1024), np.uint8)
    for i in range(2):
        acc = np.zeros(1024, np.uint8)
        for j in range(4):
            acc ^= GF_MUL_TABLE[M[i, j], data[j]]
        want[i] = acc
    np.testing.assert_array_equal(out, want)
    assert gf_device_tables() is gft  # still the same upload


# -- heavy variant ----------------------------------------------------------

@pytest.mark.slow
def test_weight_change_zero_compiles_at_scale():
    """65536 PGs / 256 OSDs: a reweighted same-shape map re-maps with
    zero compiles and the rows match the fresh-compile result."""
    n = 65536
    m1 = _mk_map(n, n_osds=256, per_host=8)
    pm1 = PoolMapper(m1, 0, overlays=False)
    _warm_both_kernels(pm1)
    pm1.map_all_device()
    m2 = _mk_map(n, n_osds=256, per_host=8)
    rng = np.random.default_rng(23)
    for o in rng.choice(256, 16, replace=False):
        m2.osd_weight[int(o)] = int(0x10000 * 0.6)
    c0 = _jit_counters()
    rows = np.asarray(PoolMapper(m2, 0, overlays=False).map_all_device())
    d = _delta(c0)
    assert d["compiles"] == 0, d
    for s in range(0, n, 4099):
        want, _, _, _ = m2.pg_to_up_acting_osds(PgId(0, s))
        got = [int(x) for x in rows[s] if x != ITEM_NONE]
        assert got == list(want), (s, got, want)
