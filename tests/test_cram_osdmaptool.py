"""Replay the reference's osdmaptool cram transcripts against OUR CLI.

The reference pins osdmaptool's exact CLI behavior — messages, output
formats, exit codes, epoch bumps, even the upmap optimizer's concrete
decisions — in cram transcripts (reference src/test/cli/osdmaptool/*.t).
Passing them end-to-end proves drop-in compatibility of the whole stack:
conf/builders, binary codec, print/tree formats, placement pipeline, and
the upmap balancer.
"""

from __future__ import annotations

from pathlib import Path

import pytest

from cramlib import run_transcript

CRAM_DIR = Path("/root/reference/src/test/cli/osdmaptool")

# (transcript, command-regexes to skip: surfaces we don't implement)
TRANSCRIPTS = [
    ("help.t", []),
    ("missing-argument.t", []),
    ("print-empty.t", []),
    ("print-nonexistent.t", []),
    ("clobber.t", []),
    ("crush.t", []),
    ("tree.t", []),
    ("pool.t", []),
    ("create-print.t", []),
    ("create-racks.t", []),
    ("test-map-pgs.t", []),
    ("upmap.t", []),
    ("upmap-out.t", []),
]


@pytest.mark.skipif(not CRAM_DIR.exists(),
                    reason="reference cram transcripts unavailable")
@pytest.mark.parametrize(
    "name,skips", TRANSCRIPTS, ids=[t for t, _ in TRANSCRIPTS]
)
def test_transcript(name, skips, tmp_path):
    t = CRAM_DIR / name
    if not t.exists():
        pytest.skip(f"{name} not in reference")
    results = run_transcript(
        t, workdir=tmp_path, shim_dir=tmp_path / "bin", skip_cmd_res=skips
    )
    bad = [r for r in results if not r.ok]
    assert not bad, (
        f"{len(bad)}/{len(results)} commands diverged; first:\n"
        + bad[0].diff()
    )
