"""Headline benchmarks: CRUSH mapping throughput + EC throughput.

Measures, on the default jax device (the real TPU chip when present):

1. PG->OSD mapping rate of the batched 5-stage placement pipeline
   (ceph_tpu.osd.pipeline_jax) on the BASELINE.md configs:
     - config 1: 1k PGs / 32 OSDs   (crushtool --test shape)
     - config 2: 100k PGs / 1k OSDs (osdmaptool --test-map-pgs shape)
     - headline: BENCH_PGS (default 1M) PGs / BENCH_OSDS (default 1024)
   vs the single-core C reference kernel (crush_do_rule in a tight loop —
   the hot loop of crushtool --test, reference src/crush/CrushTester.cc:
   612-623) compiled from the read-only reference mount.

2. EC throughput (BASELINE.md configs 3-4): RS(k=8,m=4) encode/decode GB/s
   on the device engine (ec.jax_backend: per-profile × per-strategy
   table, measured autotune pick, batched-stripe rates, XOR-schedule
   stats, and the jit cache-counter proof of 0 compiles across stripes
   and warmed erasure patterns — see bench_ec_jax) and the native SIMD
   engine (reference tool:
   src/test/erasure-code/ceph_erasure_code_benchmark.cc:156-317), plus
   Clay(8,4,d=11) single-chunk repair bandwidth.

Survivability design (this file prints ONE JSON line, always, rc=0),
built on ceph_tpu.runtime:

- Supervisor/worker split: the measurements run in a child process; the
  parent enforces a wall-clock deadline (BENCH_DEADLINE_S, default 540s)
  and, if the child hangs, OOMs, or crashes, kills it and assembles the
  final JSON from whatever stages checkpointed.
- The worker acquires its backend through `runtime.acquire_backend()`:
  `jax.devices()` runs in a watchdogged subprocess probe (a TPU init
  hang costs BENCH_PROBE_TIMEOUT, not the run), degrades tpu -> cpu down
  the ladder, and records full provenance (backend, fallback_reason,
  attempts, init_seconds, diagnosis) into the output JSON.
  BENCH_REQUIRE_TPU is the hard gate: nonzero = fail instead of degrade.
- Stages run under `runtime.StageScheduler`: priority-ordered against
  the deadline, each completed stage checkpointed atomically to
  BENCH_partial.json.  EC stages outrank mapping configs, and the
  north-star rebalance stage outranks the slow headline config, so a
  pathological headline run cannot starve it.  `bench.py --resume` after
  a mid-run kill skips checkpointed stages and finishes the remainder.
- `bench.py --selftest`: a ~1-minute CPU-only run that injects a TPU-init
  hang (runtime.faults) and asserts every stage — including a miniature
  rebalance and one balancer round of each mgr mode — completes with
  correct provenance.
- The PG axis is chunked (BENCH_CHUNK, default 65536): peak device memory
  is O(chunk), not O(BENCH_PGS) — the r02 failure mode (XLA OOM
  materializing [N, T, lanes] intermediates at N=1M) cannot recur.
- The JAX persistent compilation cache is enabled; repeat runs skip the
  ~20-40s per-config compiles.

A `balancer` stage runs one optimization round of each mgr balancer
mode (upmap / crush-compat, ceph_tpu.mgr) on a synthetic cluster so the
BENCH JSON records balancer eval throughput and score deltas.

Output observability (docs/BENCH_SCHEMA.md is the field contract; the
record carries `schema_version`): the final JSON embeds an
`executables` section (the compile-cache registry with per-kernel cost
analysis and rooflines, ceph_tpu.obs.executables) and a `quantiles`
section (p50/p90/p99 of the hot dispatch spans).  `--diff-against
'BENCH_r*.json'` diffs the fresh run against a prior series through
tools/benchdiff (calibration-normalized, regressions flagged inline in
the output), and `--selftest` additionally runs the differ over a
frozen fixture series and fails unless the seeded regression is
flagged.

A `lifetime` stage runs a >=500-epoch seeded chaos scenario through
ceph_tpu.sim.lifetime (failure/churn/growth as real Incremental chains,
device-side accounting, invariant checks) and records epochs/s,
simulated cluster-years per wallclock hour, and three robustness
proofs: injected device loss degrades with an unchanged digest, an
interrupted run resumes to the straight run's digest, and steady
epochs book 0 compiles.

Env knobs: BENCH_PGS, BENCH_OSDS, BENCH_BASELINE_PGS, BENCH_EC_MB,
BENCH_CHUNK, BENCH_DEADLINE_S, BENCH_REPS, BENCH_REQUIRE_TPU,
BENCH_SKIP_EC, BENCH_PROBE_TIMEOUT, BENCH_CFG2_PGS/_OSDS (shrink the
second mapping config, selftest), BENCH_BAL_PGS/_OSDS/_COMPAT_ITERS
(balancer stage), BENCH_LIFETIME_SCENARIO/_EPOCHS/_CK (lifetime
stage), BENCH_SERVE_PGS/_OSDS/_SECONDS/_CLIENTS/_BLOCK/_CHAOS_EPOCHS/
_STALL_BOUND/_BULK_SECONDS/_FRONT_BLOCKS/_MESH_PGS (serve stage),
BENCH_FLEET_CLUSTERS/_EPOCHS/_SPEC (fleet
stage), plus the CEPH_TPU_FAULTS /
CEPH_TPU_LADDER / CEPH_TPU_INIT_* runtime knobs and
CEPH_TPU_EC_STRATEGY (forces one ec.jax_backend strategy; the ec_jax
stage measures all of them anyway).

A `fleet` stage (ceph_tpu.fleet) advances >=64 heterogeneous clusters
in lockstep — ONE vmapped accounting dispatch per epoch batch — after
running a solo LifetimeSim oracle per member in the same stage: every
stacked digest must be bit-identical to its oracle, steady batches
must book 0 compiles, the aggregate cluster-epochs/s must beat the
serial-solo baseline, and the pareto front over (cluster-years/h,
served QPS, pg_lost, exposure) must be non-empty.

A `serve` stage runs the placement serving daemon (ceph_tpu.serve)
under seeded client load: sustained QPS + p50/p99 across live epoch
swaps (swap stall bounded and recorded), an injected mid-traffic
device loss answered host-side, a deterministic overload burst (EBUSY
shedding), and a chaos phase where the lifetime engine churns epochs
against the live service.

`python bench.py --multichip` is the mesh-scaling record: per device
count (BENCH_MC_DEVICES, default 1,2,8) a fresh subprocess self-forces
that many virtual host devices, shards the production pipeline over a
CEPH_TPU_MESH_DEVICES mesh, and measures map throughput, a lifetime
chaos digest that must be bit-identical across all counts, and the
candidate-batched vs sequential optimizer dispatch ratio (>=5x gate).
Knobs: BENCH_MC_DEVICES/_PGS/_OSDS/_CHUNK/_REPS/_SCENARIO/_TIMEOUT/
_BACKEND/_BAL_PGS/_BAL_OSDS/_BAL_ITER.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

from ceph_tpu import obs, runtime

_HERE = Path(__file__).resolve().parent
sys.path.insert(0, str(_HERE / "tests"))

# the BENCH record shape this file writes; the reader contract lives in
# tools/benchdiff.py (docs/BENCH_SCHEMA.md documents the fields)
from tools.benchdiff import SCHEMA_VERSION  # noqa: E402

# frozen benchdiff fixture series (built from the real BENCH_r01-r05
# rounds + synthetic calibrated rounds with a seeded regression); the
# selftest runs the differ over it and embeds the verdict
BENCHDIFF_FIXTURES = _HERE / "tests" / "data" / "benchdiff"

N_PGS = int(os.environ.get("BENCH_PGS", 1_000_000))
N_OSDS = int(os.environ.get("BENCH_OSDS", 1024))
CFG2_PGS = int(os.environ.get("BENCH_CFG2_PGS", 100_000))
CFG2_OSDS = int(os.environ.get("BENCH_CFG2_OSDS", 1024))
BASELINE_PGS = int(os.environ.get("BENCH_BASELINE_PGS", 200_000))
EC_MB = int(os.environ.get("BENCH_EC_MB", 16))
_CHUNK_ENV = os.environ.get("BENCH_CHUNK", "")  # "" = pipeline default;
                                                # <=0 = disable chunking
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", 540))
REPS = int(os.environ.get("BENCH_REPS", 3))
OSD_PER_HOST = 8

PARTIAL = _HERE / os.environ.get("BENCH_PARTIAL", "BENCH_partial.json")


def _log(msg: str) -> None:
    print(f"bench[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


# ----------------------------------------------------------------- worker
# Stage checkpointing lives in runtime.Checkpoint (the class this file's
# old Stages accumulator grew into); the compile-cache pre-warm is
# runtime.prewarm_compile_cache, run by acquire_backend().


# (pipeline cache_key, block shape, device bound) -> jitted stats kernels.
# Mirrors pipeline_jax._PIPE_CACHE for the bench's own histogram wrappers:
# stages whose maps share structure share the compile.
_BENCH_JITS: dict = {}


# stage records embed the per-stage compile/cache DELTA (`jit` key) so
# every BENCH_*.json says how many XLA compiles each stage paid and how
# many dispatches rode an already-compiled executable
_jit_counters = obs.jit_counters
_jit_delta = obs.jit_counters_delta


def build_map(n_pgs: int, n_osds: int):
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.types import PgPool, PoolType

    n_host = max(1, n_osds // OSD_PER_HOST)
    pool = PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=n_pgs, pgp_num=n_pgs,
    )
    return build_hierarchical(
        n_host, OSD_PER_HOST, n_rack=max(1, n_host // 16), pool=pool
    )


def bench_mapping(m, n_pgs: int, reps: int = REPS) -> dict:
    """Device mapping rate, PG axis chunked to BENCH_CHUNK-size blocks
    (peak memory O(chunk)).

    Measures the same work the reference tools do per PG — map + per-OSD
    count/primary histograms (reference src/crush/CrushTester.cc:637-698,
    src/tools/osdmaptool.cc:696-754) — with the histograms reduced ON
    device and only the O(OSDs) totals fetched, which is also what forces
    completion (honest wall clock; device->host transfer of per-PG results
    is not part of the workload, exactly as the C keeps its histogram in
    L1).  Lanes whose fast-window was inconclusive are excluded from the
    main histogram and recomputed through the exact loop kernel INSIDE the
    timed region, so the recorded rate always includes the rescue cost.

    Reports warm rate (compiled, reps passes) and cold rate (compile +
    first pass) — real `crushtool --test` pays no warm-up, so both are
    recorded."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.crush.mapper_jax import RESCUE_PAD
    from ceph_tpu.osd.pipeline_jax import DEFAULT_CHUNK, PoolMapper
    from ceph_tpu.parallel.sharded import _hist

    jit0 = _jit_counters()
    pm = PoolMapper(m, 0, overlays=False)
    chunk = int(_CHUNK_ENV) if _CHUNK_ENV else DEFAULT_CHUNK
    if chunk <= 0:
        chunk = n_pgs
    B = min(chunk, n_pgs)
    nb = (n_pgs + B - 1) // B
    DV = int(pm.dev["weight"].shape[0])
    pl = obs.logger_for("pipeline")
    # stats kernels keyed on the pipeline's structural signature + block
    # shape: pool identity/pg counts are operands (pool_operands), so
    # testmappgs and headline — same rule/OSD bound/chunk, different pg
    # counts — dispatch ONE compiled program; the map's tables ride in
    # pm.dev.  The compile/dispatch split lands in the pipeline perf
    # group (the 24.7s cold compiles of r05 became
    # pipeline.bench_stats_compile_seconds in every BENCH_partial.json
    # stage instead of hiding in the headline number).
    bkey = (pm.cache_key, B, DV)
    ent = _BENCH_JITS.get(bkey)
    if ent is None:
        vfast = jax.vmap(pm._fast, in_axes=(0, None, 0))
        # pm.fn IS the exact loop kernel with the same overlay/affinity
        # gates as pm._fast — recompiling one here could silently drift
        vloop = jax.vmap(pm.fn, in_axes=(0, None, 0))

        @jax.jit
        def stats_block(ps, dev):
            _, _, act, actp, flg = vfast(ps, dev, {})
            ok = ~flg
            hist = _hist(act, DV, ok[:, None])
            phist = _hist(actp[:, None], DV, ok[:, None])
            return hist, phist, flg, flg.sum()

        @jax.jit
        def rescue_block(ps, dev, mask):
            # [:4]: the exact kernel's trailing with_raw output is not
            # a histogram input
            _, _, act, actp = vloop(ps, dev, {})[:4]
            hist = _hist(act, DV, mask[:, None])
            phist = _hist(actp[:, None], DV, mask[:, None])
            return hist, phist

        # _BENCH_JITS entries register in the executable registry like
        # every other trace-once cache (compile cost + lazy cost
        # analysis land in the `executables` output section)
        stats_block = obs.JitAccount(
            stats_block, pl, "bench_stats",
            exec_record=obs.executables.register(
                "bench", "stats", bkey, fn=stats_block),
            # one logical distribution with the PoolMapper fast kernel:
            # warm stats-block dispatches ARE map_block dispatches
            warm_hist="map_block_seconds",
        )
        rescue_block = obs.JitAccount(
            rescue_block, pl, "bench_rescue",
            exec_record=obs.executables.register(
                "bench", "rescue", bkey, fn=rescue_block),
        )
        _BENCH_JITS[bkey] = ent = (stats_block, rescue_block)
    stats_block, rescue_block = ent

    @jax.jit
    def accum(h, p, n, dh, dp, dn):
        return h + dh, p + dp, n + dn

    dev = jax.device_put(pm.dev)
    blocks = [
        jax.device_put(jnp.asarray(
            (np.arange(i * B, (i + 1) * B) % n_pgs).astype(np.uint32)))
        for i in range(nb)
    ]

    def one_pass():
        h = jnp.zeros(DV, jnp.int32)
        p = jnp.zeros(DV, jnp.int32)
        nflg = jnp.int64(0)
        flags = []
        for b in blocks:
            with obs.span("pipeline.map_block", pgs=B, bench=True):
                dh, dp, f, nf = stats_block(b, dev)
                h, p, nflg = accum(h, p, nflg, dh, dp, nf)
            flags.append(f)
        unresolved = int(nflg)  # forces the whole chain
        pl.inc("pgs_mapped", n_pgs)  # not nb*B: pad lanes are not real PGs
        if unresolved:
            pl.inc("rescue_invocations")
            # flag fetch + host index math BEFORE the span: the rescue
            # span times dispatch only (graftlint host-sync pass)
            rescue_xs = []
            for bi, f in enumerate(flags):
                fv = np.asarray(f)
                if not fv.any():
                    continue
                idx = np.nonzero(fv)[0]
                # pad lanes (global index >= n_pgs) are duplicate
                # seeds, not real unresolved PGs
                pl.inc("unresolved_pgs", int((idx + bi * B < n_pgs).sum()))
                rescue_xs.append(
                    ((np.arange(bi * B, (bi + 1) * B) % n_pgs)[idx])
                    .astype(np.uint32)
                )
            # exact recompute of flagged lanes through the loop kernel,
            # merged into the histograms (cycle-padded fixed-size batches)
            with obs.span("pipeline.rescue", lanes=unresolved, bench=True):
                for xs in rescue_xs:
                    for i in range(0, len(xs), RESCUE_PAD):
                        blk = xs[i:i + RESCUE_PAD]
                        # fixed shape: 1 compile
                        pad = np.resize(blk, RESCUE_PAD)
                        mask = np.zeros(RESCUE_PAD, bool)
                        mask[: len(blk)] = True
                        dh, dp = rescue_block(
                            jnp.asarray(pad), dev, jnp.asarray(mask)
                        )
                        h, p = h + dh, p + dp
        with obs.span("pipeline.fetch", bench=True):
            hist = np.asarray(h)  # tiny fetch; forces completion
            return hist, np.asarray(p), unresolved

    t0 = time.perf_counter()
    with obs.span("bench.cold_pass", pgs=nb * B):
        hist, phist, unresolved = one_pass()
    cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        with obs.span("bench.warm_pass", pgs=nb * B):
            hist, phist, unresolved = one_pass()
    dt = (time.perf_counter() - t0) / reps
    mapped = nb * B
    return {
        "mappings_per_sec": round(mapped / dt, 1),
        "mappings_per_sec_cold": round(mapped / cold_s, 1),
        "wall_s": round(dt, 4),
        "cold_s": round(cold_s, 1),
        "unresolved": unresolved,
        "rescue_included": True,
        "pgs": mapped,
        "chunk": B,
        "hist_checksum": int(hist.sum()) + int(phist.sum()),
        "jit": _jit_delta(jit0),
    }


def bench_diagnostics(m, n_pgs: int) -> dict:
    """The BENCH `diagnostics` section: the placement flight-recorder
    summary of the headline map (device-reduced retry histogram,
    collision/rejection/bad-mapping tallies) PLUS the proof that
    instrumenting observed nothing it changed — the default pipeline is
    warmed, the instrumented (with_diag) variant is built and
    dispatched, then the default path runs again and must book 0
    compiles and map bit-identically (instrumentation is a static plan
    fact with its own cache entry)."""
    from ceph_tpu.osd.pipeline_jax import PoolMapper

    pm = PoolMapper(m, 0, overlays=False)
    n = min(n_pgs, int(os.environ.get("BENCH_DIAG_PGS", 262_144)))
    ps = np.arange(n, dtype=np.uint32)
    base = pm.map_batch(ps)  # warm the default path
    summary = pm.diagnose(ps, source="bench.headline")
    jit0 = _jit_counters()
    again = pm.map_batch(ps)
    jd = _jit_delta(jit0)
    summary["default_path_compiles"] = jd.get("compiles", -1)
    summary["mapping_identical"] = bool(all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(base, again)
    ))
    return summary


def bench_rebalance(n_pgs: int, n_osds: int, rounds: int,
                    remaining, handle=None) -> dict:
    """North-star sim (BASELINE config 5): build an n_pgs/n_osds map,
    perturb OSD reweights, then run upmap balancer rounds with per-round
    wall-clock — the reference's `osdmaptool --upmap` loop
    (src/tools/osdmaptool.cc:490-543 prints per-round "Time elapsed"; each
    round's calc_pg_upmaps internally re-maps every PG of every pool,
    src/osd/OSDMap.cc:4634,4652-4665).  Runs on the fully device-resident
    backend: the whole multi-round greedy is ONE lax.while_loop dispatch
    per plan (membership rows stay in HBM, host holds O(OSDs)), sharded
    over the CEPH_TPU_MESH_DEVICES mesh like the mapping pipeline."""
    from ceph_tpu.balancer.upmap import calc_pg_upmaps
    from ceph_tpu.parallel.sharded import default_mesh

    def _loop_snap():
        d = obs.perf_dump().get("balancer") or {}
        return {k: int(d.get(k, 0)) for k in (
            "plan_dispatches", "rounds", "changes_accepted",
            "plan_readback_reverts")}

    res: dict = {"pgs": n_pgs, "osds": n_osds, "backend": "device_loop"}
    jit0 = _jit_counters()
    t0 = time.perf_counter()
    m = build_map(n_pgs, n_osds)
    res["build_s"] = round(time.perf_counter() - t0, 1)
    # reweight: simulate reweight-by-utilization on 2% of OSDs
    rng = np.random.default_rng(5)
    for o in rng.choice(n_osds, max(1, n_osds // 50), replace=False):
        m.osd_weight[int(o)] = int(0x10000 * 0.85)
    cache: dict = {}
    per_round = []
    res["rounds"] = per_round
    total_changed = 0
    plan_dispatches = 0
    for rnd in range(rounds):
        s0 = _loop_snap()
        t0 = time.perf_counter()
        r = calc_pg_upmaps(
            m, max_deviation=5, max_iter=10, backend="device_loop",
            mesh=default_mesh(),
            rng=np.random.default_rng(100 + rnd), device_cache=cache,
        )
        dt = time.perf_counter() - t0
        s1 = _loop_snap()
        per_round.append({
            "round": rnd,
            "wall_s": round(dt, 2),
            "num_changed": r.num_changed,
            "stddev": round(float(r.stddev), 1),
            "max_deviation": round(float(r.max_deviation), 2),
            # one plan = one kernel dispatch, however many greedy
            # rounds converged inside it
            "plan_dispatches": s1["plan_dispatches"]
            - s0["plan_dispatches"],
            "loop_rounds": s1["rounds"] - s0["rounds"],
            "readback_reverts": s1["plan_readback_reverts"]
            - s0["plan_readback_reverts"],
        })
        total_changed += r.num_changed
        plan_dispatches += per_round[-1]["plan_dispatches"]
        res["plan_dispatches"] = plan_dispatches
        res["dispatches_per_change"] = round(
            plan_dispatches / total_changed, 4) if total_changed \
            else None
        res["total_changed"] = total_changed
        res["upmap_items"] = len(m.pg_upmap_items)
        res["jit"] = _jit_delta(jit0)
        if handle is not None:  # flush progress: a killed worker keeps
            handle.progress(res)  # completed rounds (not marked done —
            # a resume re-runs the stage, never trusts a partial)
        if r.num_changed == 0:
            res["converged"] = True
            break
        if remaining() < 1.5 * dt + 30:
            res["truncated_by_deadline"] = True
            break
    res["plan_digest"] = _plan_digest(m)
    if n_pgs <= 65536:
        # determinism proof at selftest scale: a fresh identical map
        # rebalanced with the same seeds lands on the same plan bytes
        m2 = build_map(n_pgs, n_osds)
        rng2 = np.random.default_rng(5)
        for o in rng2.choice(n_osds, max(1, n_osds // 50),
                             replace=False):
            m2.osd_weight[int(o)] = int(0x10000 * 0.85)
        for rnd in range(len(per_round)):
            calc_pg_upmaps(
                m2, max_deviation=5, max_iter=10,
                backend="device_loop", mesh=default_mesh(),
                rng=np.random.default_rng(100 + rnd),
            )
        res["digest_stable"] = _plan_digest(m2) == res["plan_digest"]
    return res


def _plan_digest(m) -> str:
    """Order-independent digest of the accumulated upmap plan."""
    import hashlib

    h = hashlib.sha256()
    for pg in sorted(m.pg_upmap_items):
        h.update(repr((pg, m.pg_upmap_items[pg])).encode())
    return h.hexdigest()[:16]


def _balancer_snap() -> dict:
    d = obs.perf_dump().get("balancer") or {}
    return {k: int(d.get(k, 0)) for k in (
        "changes_accepted", "changes_rejected", "candidate_batches",
        "candidates_scored")}


def bench_balancer(n_pgs: int, n_osds: int, compat_iters: int) -> dict:
    """One optimization round of EACH mgr balancer mode on a synthetic
    cluster (the reference's `ceph balancer optimize` pair: do_upmap /
    do_crush_compat, pybind/mgr/balancer/module.py:964/1031), scored by
    calc_eval through the batched pipeline.  Records per-mode wall
    time, score delta, and eval throughput (PGs scored per second) —
    plus a candidate-batched upmap run on an identical fresh map, whose
    `dispatches_per_change` (candidate_batches / changes_accepted)
    against the sequential path's one-eval-per-change ratio is the
    batched-optimizer proof benchdiff tracks (schema v8)."""
    from ceph_tpu.mgr import Balancer, MappingState, synthetic_pg_stats

    def mk_map():
        m = build_map(n_pgs, n_osds)
        rng = np.random.default_rng(9)
        for o in rng.choice(n_osds, max(1, n_osds // 25),
                            replace=False):
            m.osd_weight[int(o)] = int(0x10000 * 0.8)
        return m

    m = mk_map()
    res: dict = {"pgs": n_pgs, "osds": n_osds}
    stats = synthetic_pg_stats(m)
    seq_ratio = None
    for mode, opts in (
        ("upmap", {"upmap_max_optimizations": 16}),
        ("crush-compat", {"crush_compat_max_iterations": compat_iters}),
    ):
        b0 = _balancer_snap()
        bal = Balancer(options=opts, rng=np.random.default_rng(17))
        ms = MappingState(m, stats, mapper="jax")
        before = obs.perf_dump()["mgr"]["eval_pgs_mapped"]
        jit0 = _jit_counters()
        t0 = time.perf_counter()
        with obs.span("bench.balancer", mode=mode, pgs=n_pgs):
            pe0 = bal.eval(ms)
            plan = bal.plan_create("bench", ms, mode=mode)
            rc, detail = bal.optimize(plan)
            if rc != 0:
                pe1 = pe0
            elif plan.final_eval is not None:
                pe1 = plan.final_eval  # compat: already scored; a
                # re-eval would recompile the pipeline for nothing
            else:
                pe1 = bal.eval(plan.final_state())
        dt = time.perf_counter() - t0
        scored = obs.perf_dump()["mgr"]["eval_pgs_mapped"] - before
        entry = {
            "rc": rc,
            "wall_s": round(dt, 2),
            "score_before": round(pe0.score, 6),
            "score_after": round(pe1.score, 6),
            "eval_pgs_per_sec": round(scored / dt, 1) if dt else 0.0,
            "jit": _jit_delta(jit0),
        }
        if rc != 0:
            entry["detail"] = detail
        if mode == "upmap":
            entry["changes"] = (
                len(plan.inc.new_pg_upmap_items)
                + len(plan.inc.old_pg_upmap_items)
            )
            b1 = _balancer_snap()
            acc = b1["changes_accepted"] - b0["changes_accepted"]
            rej = b1["changes_rejected"] - b0["changes_rejected"]
            # the sequential greedy evaluates exactly one prospective
            # change per accepted/rejected round-trip
            seq_ratio = round((acc + rej) / max(acc, 1), 4)
            entry["dispatches_per_change"] = seq_ratio
        else:
            entry["weight_set_osds"] = len(plan.compat_ws)
        res[mode.replace("-", "_")] = entry

    # candidate-batched upmap on an identical fresh map: same budget,
    # whole batches of prospective changes scored per dispatch
    cand_k = int(os.environ.get("BENCH_BAL_CAND", 16))
    m2 = mk_map()
    bal = Balancer(
        options={"upmap_max_optimizations": 16,
                 "upmap_candidate_batch": cand_k,
                 "upmap_state_backend": "device"},
        rng=np.random.default_rng(17),
    )
    ms = MappingState(m2, stats, mapper="jax")
    b0 = _balancer_snap()
    t0 = time.perf_counter()
    with obs.span("bench.balancer", mode="upmap_batched", pgs=n_pgs):
        pe0 = bal.eval(ms)
        plan = bal.plan_create("bench-batched", ms, mode="upmap")
        rc, _ = bal.optimize(plan)
        pe1 = bal.eval(plan.final_state()) if rc == 0 else pe0
    dt = time.perf_counter() - t0
    b1 = _balancer_snap()
    acc = b1["changes_accepted"] - b0["changes_accepted"]
    batches = b1["candidate_batches"] - b0["candidate_batches"]
    cb = {
        "rc": rc,
        "wall_s": round(dt, 2),
        "candidate_batch": cand_k,
        "batches": batches,
        "scored": b1["candidates_scored"] - b0["candidates_scored"],
        "changes": acc,
        "score_before": round(pe0.score, 6),
        "score_after": round(pe1.score, 6),
        "dispatches_per_change": round(batches / max(acc, 1), 4),
    }
    res["upmap_batched"] = cb
    # the benchdiff metric pair (schema v8): batched vs sequential
    # scoring dispatches per accepted change
    res["dispatches_per_change"] = cb["dispatches_per_change"]
    res["seq_dispatches_per_change"] = seq_ratio
    if seq_ratio and acc:
        res["dispatch_reduction_x"] = round(
            seq_ratio / max(cb["dispatches_per_change"], 1e-9), 1)
    return res


def bench_c_reference(m, n: int) -> float | None:
    """Single-core C crush_do_rule loop; mappings/sec, None if unavailable."""
    try:
        from util_maps import to_oracle

        om = to_oracle(m.crush)
        weights = list(m.osd_weight)
        om.bench_rule(0, 0, min(n, 1000), 1, weights, 3)  # warm
        ns, _ = om.bench_rule(0, 0, n, 1, weights, 3)
    except Exception:
        return None
    if ns <= 0:
        return None
    return n / (ns * 1e-9)


def _time_engine(fn, reps=REPS) -> float:
    fn()  # warm/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def bench_ec_engine(name: str, profile: dict) -> dict:
    """RS(8,4) encode + 2-erasure decode GB/s for one HOST engine
    (reference prints seconds/KiB: ceph_erasure_code_benchmark.cc:
    176-184).  The device engine has its own stage (bench_ec_jax)."""
    from ceph_tpu.ec.registry import create_erasure_code

    k, mm = 8, 4
    L = EC_MB * (1 << 20) // k
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    total = k * L
    code = create_erasure_code(dict(profile))
    enc_s = _time_engine(lambda: code.encode_chunks(data))
    encoded = code.encode_chunks(data)
    chunks = {i: encoded[i] for i in range(k + mm) if i not in (0, 5)}
    dec_s = _time_engine(
        lambda: code.decode_chunks({0, 5}, dict(chunks), L)
    )
    return {
        f"rs84_encode_gbps_{name}": round(total / enc_s / 1e9, 3),
        f"rs84_decode2_gbps_{name}": round(total / dec_s / 1e9, 3),
    }


# the per-strategy table measures these profiles (name -> jax profile)
EC_PROFILES = {
    "rs84": {"plugin": "jax", "k": "8", "m": "4"},
    "cauchy42": {"plugin": "jax", "k": "4", "m": "2",
                 "technique": "cauchy_good"},
}


def _ec_time(fn, reps: int = 1) -> float:
    """Warm (compile) + time `reps` steady-state calls."""
    fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def bench_ec_jax() -> dict:
    """Device-engine EC stage: per-profile encode/decode GB/s for EVERY
    strategy (ec.jax_backend.STRATEGIES), the measured autotune pick,
    the XOR-schedule lowering stats, batched-stripe rates, and the jit
    cache-counter deltas proving 0 compiles across stripes AND across
    warmed erasure patterns.

    Stripes are DEVICE-RESIDENT across calls (HBM is the TPU's RAM
    exactly as the reference benchmark's buffers live in host RAM);
    completion is forced by fetching a tiny result slice, so the rate
    measures encode work, not tunnel I/O.  The per-strategy table runs
    at quarter size (the headline keys run full EC_MB); cpu runs time
    the pallas strategy on a one-tile sample — interpret mode executes
    the kernel per grid step in python and would swamp the stage."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ec.jax_backend import STRATEGIES, pallas_interpret
    from ceph_tpu.ec.registry import create_erasure_code
    from ceph_tpu.ec.xor_schedule import build_schedule

    rng = np.random.default_rng(1)
    out: dict = {"ec_mb": EC_MB, "profiles": {}}
    # the table covers the authoritative strategy list; a forced env
    # strategy (a true override: engines ignore per-call picks under
    # it) narrows the table to itself
    forced = os.environ.get("CEPH_TPU_EC_STRATEGY")
    table = (forced,) if forced else tuple(
        s for s in STRATEGIES if s != "auto"
    )

    def dev_stripe(k, L):
        return jax.device_put(jnp.asarray(
            rng.integers(0, 256, size=(k, L), dtype=np.uint8)
        ))

    interp = pallas_interpret()
    for pname, prof in EC_PROFILES.items():
        k, mm = int(prof["k"]), int(prof["m"])
        n = k + mm
        Lq = max(4096, (EC_MB * (1 << 20) // k) // 4)
        ddata = dev_stripe(k, Lq)
        rec: dict = {}
        for strategy in table:
            p = dict(prof)
            p["strategy"] = strategy
            code = create_erasure_code(p)
            d = ddata
            note = None
            if strategy == "pallas" and interp:
                d = ddata[:, :4096]
                note = "interpret-sample"
            total = int(np.prod(d.shape))

            def enc():
                r = code.encode_chunks(d)
                np.asarray(r[-1, :64])  # force completion

            enc_s = _ec_time(enc)
            encoded = code.encode_chunks(d)
            chunks = {i: encoded[i] for i in range(n)
                      if i not in (0, 5)}
            Ld = int(d.shape[1])

            def dec():
                r = code.decode_chunks({0, 5}, dict(chunks), Ld)
                np.asarray(r[0][:64])

            dec_s = _ec_time(dec)
            srec = {
                "encode_gbps": round(total / enc_s / 1e9, 3),
                "decode2_gbps": round(total / dec_s / 1e9, 3),
            }
            if note:
                srec["note"] = note
            rec[strategy] = srec
        sched = build_schedule(
            create_erasure_code(dict(prof)).C
        )
        rec["xor_schedule"] = sched.stats()
        out["profiles"][pname] = rec

    # headline: autotuned full-size RS(8,4) + the trace-once proof
    k, mm = 8, 4
    n = k + mm
    L = EC_MB * (1 << 20) // k
    total = k * L
    code = create_erasure_code(
        {"plugin": "jax", "k": "8", "m": "4", "strategy": "auto"}
    )
    ddata = dev_stripe(k, L)

    def enc():
        r = code.encode_chunks(ddata)
        np.asarray(r[-1, :64])

    enc_s = _ec_time(enc, reps=REPS)
    tunes = list(code.engine.autotune.values())
    if tunes:  # one record: the RS(8,4) generator
        out["autotune"] = tunes[-1]
    out["strategy"] = code.engine._resolved_strategy
    encoded = code.encode_chunks(ddata)
    patterns = ((0, 5), (1, 2))  # two erasure patterns, both warmed
    chunk_sets = [
        {i: encoded[i] for i in range(n) if i not in pat}
        for pat in patterns
    ]

    def dec(j):
        pat, chunks = patterns[j], chunk_sets[j]
        r = code.decode_chunks(set(pat), dict(chunks), L)
        np.asarray(r[pat[0]][:64])

    dec_s = _ec_time(lambda: dec(0), reps=REPS)
    dec(1)  # warm the second pattern's plan + executable

    # reference-faithful parity rate: the reference benchmark's encoded
    # data chunks alias the input bufferlist (zero copy), so parity
    # generation is the measured work; encode_chunks additionally pays
    # a full-stripe device copy (see rs84_encode_gbps_jax)
    def par():
        r = code.encode_parity(ddata)
        np.asarray(r[-1, :64])

    par_s = _ec_time(par, reps=REPS)
    out["rs84_parity_gbps_jax"] = round(total / par_s / 1e9, 3)

    # same-machine r05 baseline: the exact strategy r05's jax number
    # (0.153 GB/s) ran — calibrates this container against the r05 CPU
    # class, so vs_r05_strategy is the hardware-normalized speedup
    code_r05 = create_erasure_code(
        {"plugin": "jax", "k": "8", "m": "4", "strategy": "logexp"}
    )

    def enc_r05():
        r = code_r05.encode_chunks(ddata)
        np.asarray(r[-1, :64])

    r05_s = _ec_time(enc_r05)
    out["r05_strategy_gbps"] = round(total / r05_s / 1e9, 3)

    # trace-once proof: fresh stripes and BOTH patterns, zero compiles
    jit0 = _jit_counters()
    for _ in range(2):
        enc()
        dec(0)
        dec(1)
    warm_delta = _jit_delta(jit0)
    out["jit_after_warmup"] = warm_delta
    out["trace_once_ok"] = warm_delta.get("compiles", 0) == 0

    # batched stripes: 4 stripes in one dispatch
    nb = 4
    batch = jnp.stack(
        [dev_stripe(k, max(4096, L // nb)) for _ in range(nb)]
    )
    bbytes = int(np.prod(batch.shape))

    def encb():
        r = code.encode_batch(batch)
        np.asarray(r[-1, -1, :64])

    encb_s = _ec_time(encb, reps=REPS)
    out["batch"] = {
        "stripes": nb,
        "encode_gbps": round(bbytes / encb_s / 1e9, 3),
    }
    out["rs84_encode_gbps_jax"] = round(total / enc_s / 1e9, 3)
    out["rs84_decode2_gbps_jax"] = round(total / dec_s / 1e9, 3)
    if out["r05_strategy_gbps"] > 0:
        out["vs_r05_strategy"] = round(
            out["rs84_encode_gbps_jax"] / out["r05_strategy_gbps"], 1
        )
    return out


def bench_clay() -> dict:
    """Clay(8,4,d=11) single-lost-chunk repair: bandwidth advantage is the
    point (reads (d+1)/(m+1) of the stripe; ErasureCodeClay.cc:325)."""
    from ceph_tpu.ec.registry import create_erasure_code

    k, mm = 8, 4
    rng = np.random.default_rng(1)
    from ceph_tpu.ec.interface import ErasureCodeProfileError

    prof = {"plugin": "clay", "k": str(k), "m": str(mm), "d": "11",
            "backend": "native"}
    try:
        clay = create_erasure_code(dict(prof))
    except ErasureCodeProfileError:  # no C++ toolchain: numpy fallback
        prof["backend"] = "numpy"
        clay = create_erasure_code(dict(prof))
    sub = clay.get_sub_chunk_count()
    Lc = max(4096, (1 << 20) // sub * sub)
    cdata = rng.integers(0, 256, size=(k, Lc), dtype=np.uint8)
    enc = clay.encode_chunks(cdata)
    want = {2}
    # true minimum-bandwidth repair: helpers send only their repair
    # sub-chunk runs ((d+1)/(m+1) of each chunk, reference
    # ErasureCodeClay.cc:325,360), not full chunks
    need = clay.minimum_to_repair(want, set(range(k + mm)) - want)
    helpers = {}
    for j, runs in need.items():
        arr = enc[j].reshape(sub, -1)
        planes = [z for ind, cnt in runs for z in range(ind, ind + cnt)]
        helpers[j] = np.ascontiguousarray(arr[planes]).reshape(-1)
    out = clay.repair(want, dict(helpers), Lc)
    assert np.array_equal(out[2], enc[2]), "clay repair mismatch"
    rep_s = _time_engine(lambda: clay.repair(want, dict(helpers), Lc))
    read_frac = sum(len(v) for v in helpers.values()) / (k * Lc)
    return {
        "clay84_repair_gbps": round(k * Lc / rep_s / 1e9, 3),
        "clay84_repair_read_fraction": round(read_frac, 3),
    }


def bench_serve(h) -> dict:
    """The `serve` stage: the placement serving daemon under load.

    Phase A (steady): a seeded client load runs against a live
    `PlacementService` while value-only epoch swaps (reweight
    Incrementals) land every ~second and one `serve_dispatch` device
    loss is injected mid-run.  Proves, in the record: sustained QPS
    with p50/p99, swaps that never stall readers beyond the recorded
    `swap_stall` bound, 0 compiles in steady state (swaps are operand
    refreshes through _PIPE_CACHE), the injected loss answered host-side
    and recovered, and zero dropped queries.

    Phase B (burst): with the dispatcher paused, `max_queue + K`
    requests overflow admission — exactly K must shed with EBUSY
    (deterministic), the rest answer after unpause.

    Phase C (chaos): the PR 10 lifetime engine drives epoch churn
    against the service under client load (serve.chaos.run_chaos) —
    client-visible p50/p99 under control-plane contention, with a live
    background-balancing round (the device-loop optimizer) planned and
    applied between churn epochs.

    Phase D (background balancing): on a fresh skewed service, one
    pre-seeded balancing round pays the device-loop compile and the
    overlay staging warm OFF the query path; then clients run while two
    more rounds plan + apply live — the whole window must book 0
    compiles (query path and background rounds both ride warm caches),
    and the client p99 stays recorded.

    Phase E (bulk edge + mesh + front, schema v13): a scalar
    `submit()` window measures the per-lookup protocol edge, then two
    bulk clients drive `query_block` while a FORCED structural swap
    (an upmap overlay adopted mid-window) lands — the pre-traced
    overlay variants must keep the window compile-free and the flip
    under the structural stall bound (`structural_swap_stalls` delta
    0), with bulk qps >= 10x the scalar edge and zero shed lanes.  A
    mesh leg re-answers the same placement set in a subprocess with 2
    forced host devices (CEPH_TPU_MESH_DEVICES) and compares placement
    digests — bit-identity across shardings.  A 2-replica ServeFront
    absorbs an injected one-replica stall: every lane still answers
    ok, the stalled replica sheds, and the client-visible block p99 is
    recorded."""
    import threading

    from ceph_tpu.runtime import faults
    from ceph_tpu.serve.chaos import _Client, _pct, run_chaos
    from ceph_tpu.serve.service import PlacementService, ServeConfig
    from ceph_tpu.osd.incremental import Incremental

    pgs = int(os.environ.get("BENCH_SERVE_PGS", 65536))
    osds = int(os.environ.get("BENCH_SERVE_OSDS", 256))
    seconds = float(os.environ.get("BENCH_SERVE_SECONDS", 10))
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 3))
    block = int(os.environ.get("BENCH_SERVE_BLOCK", 2048))
    chaos_epochs = int(os.environ.get("BENCH_SERVE_CHAOS_EPOCHS", 16))
    stall_bound_s = float(os.environ.get("BENCH_SERVE_STALL_BOUND",
                                         0.050))
    cfg = ServeConfig(block=block, fill=4 * block, max_queue=64,
                      deadline_s=2.0, degraded_batches=2)
    # stage-local health story: the lifetime stage's raised checks
    # belong to ITS record; serve starts from a clean registry
    obs.health.reset()
    m = build_map(pgs, osds)
    svc = PlacementService(m, config=cfg, name="bench.serve")
    res: dict = {"pgs": pgs, "osds": osds, "block": block,
                 "clients": clients, "seconds": seconds}
    try:
        jit0 = _jit_counters()  # service staged + warmed above
        st0 = dict(obs.perf_dump().get("state") or {})
        sv0 = dict(obs.perf_dump().get("serve") or {})

        # -- phase A: steady load + live swaps + injected device loss --
        stop = threading.Event()
        load = [_Client(svc, i, block // 2, stop) for i in range(clients)]
        loss_at = max(1, int(seconds / 2))
        rng = np.random.default_rng(3)
        t0 = time.perf_counter()
        with obs.span("bench.serve", phase="steady"):
            for c in load:
                c.thread.start()
            swaps = 0
            stalls_over = 0
            next_swap = t0 + 1.0
            lost = False
            while time.perf_counter() - t0 < seconds:
                time.sleep(0.05)
                now = time.perf_counter()
                if not lost and now - t0 >= loss_at:
                    # one mid-traffic device loss: the batch it hits
                    # must answer host-side, then recovery re-walks
                    faults.arm("serve_dispatch", "lost", "bench", 1)
                    lost = True
                if now >= next_swap:
                    inc = Incremental(epoch=svc.epoch + 1)
                    for o in rng.choice(osds, 4, replace=False):
                        inc.new_weight[int(o)] = int(
                            0x10000 * (0.7 + 0.3 * rng.random()))
                    r = svc.apply(inc)
                    if r["ok"]:
                        swaps += 1
                        if r["swap_stall_s"] > stall_bound_s:
                            stalls_over += 1
                    next_swap = now + 1.0
            stop.set()
            for c in load:
                c.thread.join(timeout=30)
        faults.disarm("serve_dispatch")
        wall = time.perf_counter() - t0
        # drain the degraded spell (small host batches) so recovery —
        # dispatch re-walking back to the device — is proven in-record
        # even when the loss landed near the end of the window
        for _ in range(cfg.degraded_batches + 2):
            r = svc.lookup_batch(0, np.arange(64), deadline_s=30.0)
            if r.ok and r.source == "device":
                break
        steady_jit = _jit_delta(jit0)
        st1 = dict(obs.perf_dump().get("state") or {})
        sv1 = dict(obs.perf_dump().get("serve") or {})

        def _d(snap0, snap1, key):
            return int(snap1.get(key, 0)) - int(snap0.get(key, 0))

        lat = [v for c in load for v in c.latencies]
        submitted = sum(c.submitted for c in load)
        replied = sum(c.replied for c in load)
        ok = sum(c.by_status.get("ok", 0) for c in load)
        st = svc.status()
        d = obs.perf_dump().get("serve") or {}
        stall = d.get("swap_stall_seconds") or {}
        res.update({
            "qps": round(ok / wall, 1) if wall else 0.0,
            "answered_ok": ok,
            "submitted": submitted,
            "dropped": submitted - replied,
            "request_p50_s": _pct(lat, 50),
            "request_p99_s": _pct(lat, 99),
            "swaps": swaps,
            "swap_stall_p99_s": stall.get("p99"),
            "swap_stall_max_s": stall.get("max"),
            # swaps whose reader-visible stall exceeded the bound: the
            # structural "never blocks readers" count (0 when healthy)
            "stall_bound_s": stall_bound_s,
            "swap_stalls": stalls_over,
            "steady_shed": st["queries_shed"],
            "steady_compiles": steady_jit["compiles"]
            + steady_jit["retraces"],
            # the O(delta) swap proofs: every phase-A swap (value-only
            # reweights) must stage via ClusterState fork — no full
            # restage, no state re-key, no full-table device_put
            "swap_delta_applies": _d(sv0, sv1, "swap_delta_applies"),
            "swap_full_restages": _d(sv0, sv1, "swap_full_restages"),
            "swap_state_rebuilds": _d(st0, st1, "full_rebuilds"),
            "swap_device_put_bytes": _d(st0, st1, "device_put_bytes"),
            "swap_prepare_avg_s": round(
                ((d.get("swap_prepare_seconds") or {}).get("avgtime")
                 or 0.0), 6),
            "degraded_answered": st["degraded_answered"],
            "device_loss_recovered": bool(
                svc.provenance()["device_loss_fallbacks"]
                and not st["degraded_batches_left"]),
            "jit_steady": steady_jit,
        })

        # -- phase B: deterministic overload burst ----------------------
        svc.pause()
        extra = 8
        burst_replies: list = []
        bl = threading.Lock()

        def one_burst():
            r = svc.lookup_batch(0, [1, 2, 3], deadline_s=5.0)
            with bl:
                burst_replies.append(r)

        ths = [threading.Thread(target=one_burst, daemon=True)
               for _ in range(cfg.max_queue + extra)]
        for t in ths:
            t.start()
        # every request has either enqueued (max_queue) or shed (extra)
        # BEFORE the drain restarts — the shed count is deterministic
        deadline = time.time() + 10
        while time.time() < deadline:
            with bl:
                n_shed = len(burst_replies)
            if len(svc._q) + n_shed >= cfg.max_queue + extra:
                break
            time.sleep(0.01)
        svc.unpause()
        for t in ths:
            t.join(timeout=30)
        res["burst"] = {
            "requests": cfg.max_queue + extra,
            "shed": sum(1 for r in burst_replies
                        if r.status == "EBUSY"),
            "answered": len(burst_replies),
        }
        res["burst_shed"] = res["burst"]["shed"]
    finally:
        svc.close()
    h.progress(res)

    # -- phase C: lifetime-engine churn against a live service ---------
    # generous deadline: on a throttled container the sim's epoch work
    # and structural-swap tracing hold the GIL for seconds at a time —
    # exactly the control-plane/client contention being measured
    # a bounded run of stalled dispatches early in the chaos window
    # blows the windowed p99 past the SLO objective: the burn must
    # RAISE SLO_BURN, and once the stalls exhaust, a fast window of
    # clean samples must CLEAR it — the raise->clear transition rides
    # the serve timeline across structural swaps, and dropped stays 0
    # (a stalled batch still answers; stall < deadline)
    faults.arm("serve_dispatch", "stall", "0.4", 8)
    try:
        chaos = run_chaos(
            epochs=chaos_epochs,
            config=ServeConfig(block=256, fill=1024, max_queue=64,
                               deadline_s=10.0),
            clients=2, client_batch=128,
            background_every=2,
        )
    finally:
        faults.disarm("serve_dispatch")
    res["chaos"] = {k: chaos.get(k) for k in (
        "epochs", "qps", "p50_s", "p99_s", "dropped", "swaps_ok",
        "swaps_rejected", "swap_stall_p99_s", "queries_shed",
        "queries_expired", "sim_violations", "degraded_reads_served",
        "at_risk_hits", "recovery_backlog_gb", "traffic",
        "client_read_mix", "background")}
    # health / SLO / timeline (schema v9): the burn-rate engine's
    # transition counts, the summarized end-of-stage status, and the
    # serve-timeline sample count
    res["slo"] = chaos.get("slo")
    res["health"] = (chaos.get("health") or {}).get("status")
    res["health_checks"] = sorted(
        (chaos.get("health") or {}).get("checks") or ())
    res["timeline_samples"] = chaos.get("timeline_samples")

    # -- phase D: live background balancing off the query path ---------
    # a skewed map so the optimizer has real work; the pre-seed round
    # pays the device-loop kernel compile AND the overlay staging warm
    # (the first applied plan flips the pipeline to its overlay-gated
    # variant) before the measured window opens
    m2 = build_map(pgs, osds)
    rng = np.random.default_rng(7)
    for o in rng.choice(osds, max(2, osds // 10), replace=False):
        m2.osd_weight[int(o)] = int(0x10000 * 0.7)
    svc2 = PlacementService(m2, config=cfg, name="bench.serve.bg")
    try:
        # two pre-seed rounds: the first flips the pipeline to its
        # overlay-gated variant, the second saturates the upmap pair
        # width (a PG picking up a second composed pair re-keys the
        # overlay tensors once) — both staged off the query path
        pre = [svc2.background_balance(max_deviation=1, max_iter=8,
                                       candidate_batch=8)
               for _ in range(2)]
        svc2.lookup_batch(0, np.arange(cfg.block, dtype=np.uint32),
                          deadline_s=30.0)  # warm post-flip query path
        jit_bg = _jit_counters()
        stop = threading.Event()
        load = [_Client(svc2, i, 128, stop) for i in range(2)]
        with obs.span("bench.serve", phase="background"):
            for c in load:
                c.thread.start()
            bg = [svc2.background_balance(max_deviation=1, max_iter=8,
                                          candidate_batch=8)
                  for _ in range(2)]
            time.sleep(0.5)  # a clean post-round client window
            stop.set()
            for c in load:
                c.thread.join(timeout=30)
        bg_jit = _jit_delta(jit_bg)
        lat = [v for c in load for v in c.latencies]
        submitted = sum(c.submitted for c in load)
        replied = sum(c.replied for c in load)
        res["background"] = {
            "preseed_changed": sum(p["num_changed"] for p in pre),
            "rounds": len(pre) + len(bg),
            "applied": sum(1 for b in pre + bg if b["ok"]),
            "changes": sum(b["num_changed"] for b in pre + bg),
            "stddev_final": bg[-1]["stddev"],
            "query_compiles": bg_jit["compiles"] + bg_jit["retraces"],
            "client_p99_s": _pct(lat, 99),
            "dropped": submitted - replied,
        }
        # the steady-state round tail: the MEASURED (post-warm) rounds
        # only — chaos-phase rounds re-stage after every adopt_map and
        # tell a staging story, not a background-balancing one
        res["background_round_p99_ms"] = round(
            _pct([b["round_s"] * 1e3 for b in bg], 99), 3)
        res["background_query_compiles"] = \
            res["background"]["query_compiles"]
    finally:
        svc2.close()
    h.progress(res)

    # -- phase E: bulk protocol edge + forced structural swap ----------
    from ceph_tpu.osd.state import value_copy_map
    from ceph_tpu.osd.types import PgId
    from ceph_tpu.serve.front import ServeFront
    from ceph_tpu.serve.meshcheck import build_default, placement_digest

    bulk_seconds = float(os.environ.get("BENCH_SERVE_BULK_SECONDS",
                                        max(2.0, seconds / 2)))
    mesh_pgs = int(os.environ.get("BENCH_SERVE_MESH_PGS", 64))
    svc3 = PlacementService(m, config=cfg, name="bench.serve.bulk")
    try:
        bmax = max(cfg.bulk_max, cfg.block)
        seeds = (np.arange(bmax, dtype=np.uint32) * 7) % pgs
        svc3.query_block(0, seeds, deadline_s=60.0)  # warm both shapes
        # pre-seed ONE structural adopt (width-1 overlay) off the
        # measured window: the first overlay epoch pays ClusterState
        # construction for an overlay-carrying map; the MEASURED swap
        # below re-keys to the width-2 variant, which the constructor
        # prewarm already traced — that flip must be free
        mu0 = value_copy_map(svc3._active.m)
        mu0.epoch += 1
        mu0.pg_upmap_items = dict(mu0.pg_upmap_items)
        mu0.pg_upmap_items[PgId(0, 0)] = [(0, 0)]
        pre_swap = svc3.adopt_map(mu0, reason="bench preseed overlay")
        svc3.query_block(0, seeds, deadline_s=60.0)  # warm post-flip

        # scalar protocol edge: per-lookup submit() through the queued
        # micro-batcher — the dispatcher overhead the bulk edge
        # amortizes away
        stop = threading.Event()
        scalar_ok = [0, 0]

        def scalar_client(i):
            srng = np.random.default_rng(100 + i)
            while not stop.is_set():
                if svc3.submit(0, int(srng.integers(0, pgs)),
                               deadline_s=30.0).ok:
                    scalar_ok[i] += 1

        ths = [threading.Thread(target=scalar_client, args=(i,))
               for i in range(2)]
        t0 = time.perf_counter()
        for t in ths:
            t.start()
        time.sleep(max(1.0, bulk_seconds / 2))
        stop.set()
        for t in ths:
            t.join(timeout=30)
        scalar_qps = sum(scalar_ok) / (time.perf_counter() - t0)

        sv_e0 = dict(obs.perf_dump().get("serve") or {})
        jit_e = _jit_counters()
        stop = threading.Event()
        lanes_ok = [0, 0]
        lanes_not_ok = [0, 0]

        def bulk_client(i):
            s = (seeds + i) % pgs
            while not stop.is_set():
                c = svc3.query_block(0, s, deadline_s=60.0).counts()
                lanes_ok[i] += c.get("ok", 0)
                lanes_not_ok[i] += sum(
                    v for k, v in c.items() if k != "ok")

        ths = [threading.Thread(target=bulk_client, args=(i,))
               for i in range(2)]
        with obs.span("bench.serve", phase="bulk"):
            t0 = time.perf_counter()
            for t in ths:
                t.start()
            time.sleep(bulk_seconds / 2)
            # the forced STRUCTURAL swap: a second PG picks up a
            # width-2 composed pair mid-window — pipeline re-keys to
            # the prewarmed variant, readers never see the staging
            mu = value_copy_map(svc3._active.m)
            mu.epoch += 1
            mu.pg_upmap_items = dict(mu.pg_upmap_items)
            mu.pg_upmap_items[PgId(0, 1)] = [(0, 0), (1, 1)]
            swap = svc3.adopt_map(mu, reason="bench forced structural")
            time.sleep(bulk_seconds / 2)
            stop.set()
            for t in ths:
                t.join(timeout=60)
            bulk_wall = time.perf_counter() - t0
        bulk_jit = _jit_delta(jit_e)
        sv_e1 = dict(obs.perf_dump().get("serve") or {})
        bulk_qps = sum(lanes_ok) / bulk_wall
        res["structural_swap_stalls"] = _d(sv_e0, sv_e1,
                                           "structural_swap_stalls")
        res["bulk"] = {
            "qps": round(bulk_qps, 1),
            "scalar_qps": round(scalar_qps, 1),
            "ratio": round(bulk_qps / scalar_qps, 1)
            if scalar_qps else None,
            "lookups_ok": sum(lanes_ok),
            "not_ok": sum(lanes_not_ok),
            "block_lanes": bmax,
            "compiles": bulk_jit["compiles"] + bulk_jit["retraces"],
            "preseed_swap_ok": bool(pre_swap.get("ok")),
            "swap_ok": bool(swap.get("ok")),
            "swap_stall_s": swap.get("swap_stall_s"),
        }
    finally:
        svc3.close()
    h.progress(res)

    # mesh bit-identity: the same placement set answered in-process
    # (however many devices this process sees) and in a subprocess with
    # 2 FORCED host devices sharding the serving buffer's PG axis —
    # the sha256 placement digests must match bit-for-bit
    mesh_m = build_default(pgs=mesh_pgs, osds=8)
    msvc = PlacementService(
        mesh_m, config=ServeConfig(block=128, max_queue=64,
                                   deadline_s=0, bulk_max=mesh_pgs,
                                   prewarm=False),
        name="bench.serve.mesh")
    try:
        digest1, oracle1 = placement_digest(msvc, mesh_m)
    finally:
        msvc.close()
    # the subprocess is a bit-identity witness, not a fault-injection
    # target: drop inherited injected faults (the selftest's init hang
    # would stall its ladder probe) and pin the ladder to cpu
    menv = dict(os.environ, JAX_PLATFORMS="cpu",
                CEPH_TPU_LADDER="cpu",
                CEPH_TPU_MESH_DEVICES="2",
                XLA_FLAGS="--xla_force_host_platform_device_count=2")
    menv.pop("CEPH_TPU_FAULTS", None)
    try:
        p = subprocess.run(
            [sys.executable, "-m", "ceph_tpu.serve.meshcheck",
             "--pgs", str(mesh_pgs), "--osds", "8"],
            env=menv, capture_output=True, text=True, timeout=300,
            cwd=str(_HERE))
        mrec = json.loads(p.stdout.strip().splitlines()[-1])
    except Exception as e:
        mrec = {"error": f"{type(e).__name__}: {e}"[:200]}
    res["mesh"] = {
        "pgs": mesh_pgs,
        "devices": mrec.get("devices"),
        "digest_match": mrec.get("digest") == digest1,
        "oracle_match_1dev": bool(oracle1),
        "oracle_match_ndev": bool(mrec.get("oracle_match")),
        "provenance": (mrec.get("mesh") or {}).get("provenance"),
        "error": mrec.get("error"),
    }
    h.progress(res)

    # multi-replica front: a one-replica injected stall is absorbed —
    # the replica sheds out of routing after one slow block, every
    # lane still answers ok, and the client-visible p99 is recorded
    front = ServeFront(m, replicas=2, config=cfg, name="bench.front")
    try:
        fseeds = (np.arange(cfg.block, dtype=np.uint32) * 5) % pgs
        for _ in range(3):  # settle the per-replica latency EWMAs
            front.query_block(0, fseeds, deadline_s=60.0)
        nblocks = int(os.environ.get("BENCH_SERVE_FRONT_BLOCKS", 20))
        fok = fbad = 0
        faults.arm(f"serve_dispatch.{front.name}.r1", "stall", "0.5", 2)
        try:
            with obs.span("bench.serve", phase="front"):
                for _ in range(nblocks):
                    c = front.query_block(0, fseeds,
                                          deadline_s=60.0).counts()
                    fok += c.get("ok", 0)
                    fbad += sum(v for k, v in c.items() if k != "ok")
        finally:
            faults.disarm(f"serve_dispatch.{front.name}.r1")
        fst = front.status()
        res["front"] = {
            "replicas": fst["replicas"],
            "blocks": nblocks,
            "lookups_ok": fok,
            "dropped": fbad,
            "p99_ms": round(
                (fst.get("front_block_p99_s") or 0.0) * 1e3, 3),
            "sheds": fst["front_replica_sheds"],
            "shed_routes": fst["front_shed_routes"],
            "staggered_swaps": fst["front_staggered_swaps"],
        }
    finally:
        front.close()
    res["jit"] = _jit_delta(jit0)
    return res


DEFAULT_LIFETIME_SCENARIO = (
    "hosts=4,osds_per_host=3,racks=2,pgs=32,ec=2+1,ec_pgs=16,"
    "chunk=256,balance_every=96,balance_max=4,spotcheck_every=48,"
    "checkpoint_every=128,seed=11,p_death=0.03,p_reweight=0.05,"
    "max_pools=3,max_pgs=64,max_expand=1,new_pool_pgs=32,"
    # the recovery data plane + client workload (PR 14): queue-model
    # recovery with RapidRAID-style pipelined EC repair, and seeded
    # client traffic so the headline is a pareto record —
    # cluster-years/hour AT a stated served QPS.  Bandwidth is sized
    # so a single wound's repair drains within an epoch or two —
    # backlog still carries across epochs during cascades, but a lone
    # death heals before the next one lands
    "recovery=queue,pipeline_repair=1,workload=1,wl_sample=64,"
    "max_backfills=4,recovery_mbps=200,osd_mbps=400,"
    # the correlated-failure chaos model (PR 17): repeat-offender
    # flappers, cascading domain outages via decaying sibling hazards,
    # and per-PG dead-chunk durability accounting.  This scenario must
    # stay SURVIVABLE (pg_lost == 0, gated in --selftest): losses are
    # proven separately by the overwhelmed mini-run
    "correlated=1,flappers=2"
)

# the overwhelming counterpart: a cluster too small and a recovery
# pipe too starved for its death rate, so dead chunks stack past EC
# tolerance before the backlog drains — pg_lost > 0 and a DATA_LOSS
# check that latches at HEALTH_ERR are the acceptance proof that the
# durability accounting can actually fire (not just stay zero)
OVERWHELMED_SCENARIO = (
    "epochs=60,hosts=3,osds_per_host=2,racks=1,pgs=16,ec=2+1,ec_pgs=8,"
    "chunk=64,seed=7,p_death=0.25,p_flap=0.05,p_host_outage=0.10,"
    "p_reweight=0,p_pg_temp=0,p_pool_create=0,p_split=0,p_expand=0,"
    "p_remove=0.02,balance_every=0,spotcheck_every=0,"
    "checkpoint_every=0,recovery=queue,max_backfills=1,"
    "recovery_mbps=2,osd_mbps=4,correlated=1,flappers=1"
)


def bench_lifetime(h) -> dict:
    """The `lifetime` stage: a >=500-epoch seeded chaos scenario through
    ceph_tpu.sim.lifetime, measuring epochs/s and simulated
    cluster-years per wallclock hour, with three robustness proofs in
    the record:

    - an injected mid-run device loss (`epoch_apply=lost`) must degrade
      that epoch's accounting to the bit-exact host mapper — provenance
      recorded, trajectory digest UNCHANGED;
    - an interrupted run resumed from its runtime.Checkpoint must land
      on the same final digest as the uninterrupted run.  The straight
      run checkpoints near its end; that file is snapshotted as the
      interrupt point and a FRESH engine resumes from it (full
      state round-trip through the serialized checkpoint) — proving
      resume without paying a second whole lifetime;
    - steady epochs (structure unchanged) must book 0 compiles
      (trace-once, `pipe_cache_*`/JitAccount counters), and the
      invariant checker must stay at 0 violations.
    """
    import shutil

    from ceph_tpu.runtime import faults
    from ceph_tpu.sim.lifetime import LifetimeSim, Scenario

    spec = os.environ.get("BENCH_LIFETIME_SCENARIO",
                          DEFAULT_LIFETIME_SCENARIO)
    epochs = int(os.environ.get("BENCH_LIFETIME_EPOCHS", 510))
    sc = Scenario.parse(spec)
    sc.epochs = epochs
    loss_epoch = max(2, epochs // 2 + 1)
    stop = max(1, epochs - 10)  # the snapshotted interrupt point
    ck = _HERE / os.environ.get("BENCH_LIFETIME_CK",
                                "BENCH_lifetime_ck.json")
    ck2 = ck.with_suffix(".snap.json")
    ck.unlink(missing_ok=True)
    ck2.unlink(missing_ok=True)
    jit0 = _jit_counters()
    bal0 = dict(obs.perf_dump().get("balancer") or {})

    # run A: straight through, with a device loss injected mid-run and
    # a checkpoint snapshot taken at `stop`
    faults.arm(f"epoch_apply.{loss_epoch}", "lost", "bench", 1)
    try:
        with obs.span("bench.lifetime", phase="straight",
                      epochs=epochs):
            sim_a = LifetimeSim(sc, backend="jax", checkpoint=str(ck))
            sim_a.run(stop_after=stop)  # checkpoints at `stop`
            shutil.copy(ck, ck2)
            out_a = sim_a.run()  # straight on to the end
    finally:
        # only OUR fault: disarm_all would wipe env-armed faults aimed
        # at the later (lower-priority) stages of this same worker
        faults.disarm(f"epoch_apply.{loss_epoch}")
    h.progress({"straight": {k: out_a[k] for k in
                             ("epochs", "digest", "wall_s")}})

    # run B: a fresh engine resumed from the snapshotted interrupt
    with obs.span("bench.lifetime", phase="resumed",
                  epochs=epochs - stop):
        sim_b = LifetimeSim(sc, backend="jax", checkpoint=str(ck2),
                            resume=True)
        out_b = sim_b.run()
    ck.unlink(missing_ok=True)
    ck2.unlink(missing_ok=True)

    # pure-observer proof (schema v9): a slice of the same scenario
    # with the health model and timeline recorder DISABLED must land on
    # the same replay digest with the same steady-epoch compile count —
    # the observers may read the accounting, never steer it
    sc_p = Scenario.parse(spec)
    sc_p.epochs = min(24, epochs)
    purity = []
    for off in (False, True):
        overrides = ({"CEPH_TPU_HEALTH": "0",
                      "CEPH_TPU_TIMELINE_CAP": "0"} if off else {})
        saved = {k: os.environ.get(k) for k in overrides}
        os.environ.update(overrides)
        try:
            with obs.span("bench.lifetime", phase="purity",
                          observers=not off, epochs=sc_p.epochs):
                out_p = LifetimeSim(sc_p, backend="jax").run()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        purity.append({"observers": not off, "digest": out_p["digest"],
                       "steady_compiles":
                       out_p["trace_once"]["steady_compiles"]})
    health_pure = (purity[0]["digest"] == purity[1]["digest"]
                   and purity[0]["steady_compiles"]
                   == purity[1]["steady_compiles"])

    # backend cross-check (schema v10): the same sliced scenario on the
    # host-only ref backend must land on the purity slice's digest —
    # hazard decay, flapper draws, false-flap revives, and the wound
    # ledger are exact host ints on every backend.  A slice, not the
    # full run: ref pays ~0.8 s/epoch where jax pays ~0.05
    with obs.span("bench.lifetime", phase="ref-slice",
                  epochs=sc_p.epochs):
        out_r = LifetimeSim(sc_p, backend="ref").run()
    ref_digest_match = out_r["digest"] == purity[0]["digest"]

    # the overwhelmed mini-run (schema v10): the durability ledger must
    # be able to FIRE, not just stay zero — a starved recovery pipe
    # under a brutal death rate stacks dead chunks past EC tolerance,
    # loses PGs, and latches DATA_LOSS at HEALTH_ERR.  Isolated health
    # registry: reset before (drop the main run's raised checks) and
    # after (never leak HEALTH_ERR into later stages)
    obs.health.reset()
    try:
        with obs.span("bench.lifetime", phase="overwhelmed"):
            out_o = LifetimeSim(Scenario.parse(OVERWHELMED_SCENARIO),
                                backend="ref").run()
        loss_check = obs.health.checks().get("DATA_LOSS") or {}
        overwhelmed = {
            "pg_lost": out_o["durability"]["pg_lost"],
            "exposed_pg_epochs":
                out_o["durability"]["exposed_pg_epochs"],
            "invariant_violations": out_o["invariant_violations"],
            "data_loss_latched":
                loss_check.get("severity") == "HEALTH_ERR",
        }
    finally:
        obs.health.reset()

    tr = out_a["trace_once"]
    # the ClusterState O(delta) proofs: whole-run apply classification
    # and the balancer's membership builds served from the shared rows
    bal1 = dict(obs.perf_dump().get("balancer") or {})
    builds0 = (bal0.get("build_state_seconds") or {}).get("avgcount", 0)
    builds1 = (bal1.get("build_state_seconds") or {}).get("avgcount", 0)
    return {
        "scenario": sc.spec(),
        "epochs": out_a["epochs"],
        "digest": out_a["digest"],
        "epochs_per_sec": out_a["epochs_per_sec"],
        "cluster_years_per_hour": out_a["cluster_years_per_hour"],
        "sim_years": out_a["sim_years"],
        "events": out_a["events"],
        "invariant_violations": out_a["invariant_violations"],
        "violations": out_a["violations"][:5],
        "degraded_epochs": out_a["degraded_epochs"],
        "report": out_a["report"],
        "trace_once": tr,
        "steady_compiles": tr["steady_compiles"],
        "steady_full_rebuilds": tr["steady_full_rebuilds"],
        "state": out_a.get("state"),
        # O(PGs) membership builds the lifetime's balancer epochs paid
        # (0 when every build rode ClusterState's version-tagged rows)
        "balancer_builds": int(builds1) - int(builds0),
        "balancer_state_reuses": int(bal1.get("state_rows_reused", 0))
        - int(bal0.get("state_rows_reused", 0)),
        "jit_compiles_per_epoch": out_a["jit_compiles_per_epoch"],
        "at_risk_pg_seconds": round(
            out_a["report"]["at_risk_pg_seconds"], 3),
        # the recovery data plane + client workload (schema v7): the
        # pareto headline is cluster-years/hour AT the stated served
        # QPS, with the backlog the queue model actually carried
        "recovery": None if out_a.get("recovery") is None else dict(
            out_a["recovery"],
            # observed wall-clock drain rate rides inside the recovery
            # record so the benchdiff metric path mirrors the field path
            drain_gbps=round(
                out_a["recovery"]["drained_gb"] / out_a["wall_s"], 3)
            if out_a.get("wall_s") else 0.0),
        "workload": out_a.get("workload"),
        "pareto": out_a.get("pareto"),
        # cluster health model + timeline flight recorder (schema v9):
        # summarized status, per-epoch ok/warn/err split, the raised
        # checks, and the sim-timeline sample count — plus the
        # pure-observer proof (digest and compiles invariant under
        # CEPH_TPU_HEALTH=0 CEPH_TPU_TIMELINE_CAP=0)
        "health": out_a.get("health"),
        "health_pure": health_pure,
        "health_purity": purity,
        # correlated-failure chaos + durability ledger (schema v10):
        # cascades, repeat offenders, false-flap revives, and the
        # dead-chunk exposure record — the main run must stay
        # SURVIVABLE (pg_lost == 0) while the overwhelmed mini-run
        # proves the loss path fires
        "chaos": out_a.get("chaos"),
        "durability": out_a.get("durability"),
        "overwhelmed": overwhelmed,
        "ref_digest_match": ref_digest_match,
        # robustness proofs
        "device_loss_fallbacks":
            out_a["provenance"]["device_loss_fallbacks"],
        "device_loss_epoch": loss_epoch,
        "resume_from": out_b.get("resumed_from"),
        "resume_digest_match": out_b["digest"] == out_a["digest"],
        # timeline survives the checkpoint round-trip: the resumed
        # engine restores the recorder and keeps the SAME monotonic
        # sample index, so its final count equals the straight run's
        "resume_timeline_samples":
            (out_b.get("health") or {}).get("timeline_samples"),
        "jit": _jit_delta(jit0),
    }


DEFAULT_FLEET_BASE = (
    "hosts=4,osds_per_host=3,racks=2,pgs=32,ec=2+1,ec_pgs=16,"
    "chunk=256,balance_every=0,spotcheck_every=0,checkpoint_every=0,"
    "seed=3,recovery=queue,max_backfills=4,recovery_mbps=200,"
    "osd_mbps=400,p_pool_create=0,p_split=0"
)
# p_pool_create/p_split are zeroed in the BENCH base only: a chaos pool
# create or split mints a new lane shape, which is a structural fleet
# epoch and a stacked-executable retrace by construction — the stage
# headline measures steady-state batching, so the sweep keeps the lane
# structure constant after warmup (tier-1 test_fleet covers the
# structural-churn path with the default event probabilities).


def _fleet_spec(clusters: int, epochs: int) -> str:
    """The default heterogeneous sweep: a 16-combo cross-product
    (failure regime x death pressure x recovery budget x pool scale)
    cycled up to `clusters` members — repetitions offset the seed, so
    every member's pinned spec() is distinct."""
    return (
        f"base=epochs={epochs},{DEFAULT_FLEET_BASE};"
        "axis=correlated:0|1;"
        "axis=p_death:0.02|0.12;"
        "axis=recovery_mbps:100|400;"
        "axis=pgs:24|32;"
        f"clusters={clusters}"
    )


def bench_fleet(h) -> dict:
    """The `fleet` stage: >=64 heterogeneous clusters advanced through
    ceph_tpu.fleet — ONE vmapped accounting dispatch per epoch batch —
    with the acceptance proofs in the record:

    - every member's stacked digest is bit-identical to a solo
      `LifetimeSim` oracle of the same pinned spec, run FIRST in this
      same stage (`digest_matches` == `clusters`);
    - steady fleet epochs book 0 compiles (tag-equal lanes ride as
      self-compares, so the stacked lane structure is constant);
    - the aggregate cluster-epochs/s strictly beats the serial-solo
      baseline those same oracle runs measured, and the pareto front
      over (cluster-years/h, served QPS, pg_lost, exposure) is
      non-empty.
    """
    from ceph_tpu.fleet import FleetSim, parse_fleet
    from ceph_tpu.sim.lifetime import LifetimeSim

    clusters = int(os.environ.get("BENCH_FLEET_CLUSTERS", 64))
    epochs = int(os.environ.get("BENCH_FLEET_EPOCHS", 16))
    spec = os.environ.get("BENCH_FLEET_SPEC",
                          _fleet_spec(clusters, epochs))
    jit0 = _jit_counters()

    # solo oracle loop FIRST: per-member digests and the serial-solo
    # baseline, same stage, same process, same machine.  Each oracle
    # pins the same balancer backend the fleet pins, so the digests
    # compare byte-for-byte.  Health observation is digest-invisible,
    # but the harsher members can latch DATA_LOSS — isolate the
    # registry exactly like the overwhelmed mini-run does.
    obs.health.reset()
    try:
        solo_digests = []
        t0 = time.perf_counter()
        with obs.span("bench.fleet", phase="solo-oracle",
                      clusters=clusters):
            for m in parse_fleet(spec):
                sim = LifetimeSim(m.scenario, backend=m.backend)
                if m.backend == "jax":
                    sim.balancer_options = {
                        "upmap_state_backend": "device_loop"}
                sim.run()
                solo_digests.append(sim.digest)
        serial_wall = time.perf_counter() - t0
        h.progress({"solo_wall_s": round(serial_wall, 1)})

        with obs.span("bench.fleet", phase="stacked",
                      clusters=clusters):
            fleet = FleetSim(parse_fleet(spec))
            # pay the stacked compile outside the timed epochs (the
            # fleet mirror of the solo engine's construction warmup)
            fleet.warm()
            out = fleet.run()
    finally:
        obs.health.reset()

    mismatches = [m["index"] for m, d in zip(out["members"],
                                             solo_digests)
                  if m["digest"] != d]
    serial_eps = (out["cluster_epochs"] / serial_wall
                  if serial_wall else 0.0)
    tr = out["trace_once"]
    return {
        "spec": spec,
        "clusters": out["clusters"],
        "epochs": epochs,
        "fleet_epochs": out["fleet_epochs"],
        "cluster_epochs": out["cluster_epochs"],
        "stacked": out["stacked"],
        "balancer_backend": out["balancer_backend"],
        # the headline: aggregate stacked throughput vs the serial-solo
        # baseline measured by the oracle loop above
        "cluster_epochs_per_sec": out["cluster_epochs_per_sec"],
        "serial_epochs_per_sec": round(serial_eps, 2),
        "speedup_x": round(
            out["cluster_epochs_per_sec"] / serial_eps, 2)
        if serial_eps else 0.0,
        "solo_wall_s": round(serial_wall, 1),
        "fleet_wall_s": out["wall_s"],
        # the exactness proof: stacked digests vs the solo oracles
        "digest_matches": out["clusters"] - len(mismatches),
        "digest_mismatches": mismatches[:8],
        # the trace-once proof: steady batches book 0 compiles
        "trace_once": tr,
        "steady_compiles": tr["steady_compiles"],
        "structural_epochs": tr["structural_epochs"],
        "steady_epochs": tr["steady_epochs"],
        # the pareto record: front instead of a point
        "pareto_front_size": out["pareto"]["front_size"],
        "pareto_front": out["pareto"]["front"][:8],
        "pareto_dominated": len(out["pareto"]["dominated"]),
        "pg_lost_total": sum(m["pg_lost"] for m in out["members"]),
        "invariant_violations": sum(m["invariant_violations"]
                                    for m in out["members"]),
        "jit": _jit_delta(jit0),
    }


PROBE_TIMEOUT_S = float(os.environ.get(
    "BENCH_PROBE_TIMEOUT", os.environ.get("BENCH_INIT_TIMEOUT", 120)))

# wall-clock the rebalance stage leaves on the table for the headline
# stage that runs after it (the reverse of the r01-r05 starvation)
HEADLINE_RESERVE_S = float(os.environ.get("BENCH_HEADLINE_RESERVE", 60))


def _acquire(ck: runtime.Checkpoint) -> runtime.BackendInfo:
    """Backend acquisition through the runtime ladder; the provenance
    record (backend, fallback_reason, attempts, ...) becomes the `init`
    stage.  Runs even on --resume: a resumed run may land on different
    hardware, and the output must say which backend produced it."""
    require = None
    if os.environ.get("BENCH_REQUIRE_TPU", "0") not in ("", "0"):
        require = "tpu"
    ladder = None
    if os.environ.get("BENCH_FORCE_CPU"):
        ladder = ["cpu"]
    else:
        # no "native" rung here: every stage needs a jax backend, and the
        # cpu rung only fails when jax itself is broken — fail loudly
        # then.  cpu stays the terminal rung even if a user ladder ends
        # in "native" (which filtering would otherwise drop).
        ladder = [r for r in runtime.default_ladder() if r != "native"]
        if "cpu" not in ladder:
            ladder.append("cpu")
    try:
        info = runtime.acquire_backend(
            ladder=ladder, require=require, timeout_s=PROBE_TIMEOUT_S,
            attempts=int(os.environ.get("CEPH_TPU_INIT_ATTEMPTS", 1)),
            prewarm_cache=True,
        )
    except runtime.RequiredBackendError as e:
        ck.fail("init", e)
        _log(f"backend acquisition failed: {e}")
        raise SystemExit(2)
    prov = info.provenance()
    prov["init_s"] = round(info.init_seconds, 1)  # legacy key
    ck.put("init", prov)
    return info


def worker() -> None:
    ck = runtime.Checkpoint(
        PARTIAL, resume=bool(os.environ.get("BENCH_RESUME"))
    )
    ck.data["schema_version"] = SCHEMA_VERSION
    t_start = float(os.environ.get("BENCH_T0", time.time()))
    sched = runtime.StageScheduler(ck, DEADLINE_S, t0=t_start)
    _acquire(ck)

    # -- stage declarations; priority order, not source order, runs ------
    def ec_stage(name, profile):
        return lambda h: bench_ec_engine(name, profile)

    if not os.environ.get("BENCH_SKIP_EC"):
        # EC outranks mapping: a mapping failure can't destroy EC numbers
        sched.add("ec_jax", lambda h: bench_ec_jax(),
                  priority=90, est_s=40, min_budget_s=25)
        sched.add("ec_native",
                  ec_stage("native", {"plugin": "isa", "k": "8", "m": "4",
                                      "backend": "native"}),
                  priority=88, est_s=10, min_budget_s=10)
        sched.add("ec_clay", lambda h: bench_clay(),
                  priority=86, est_s=20, min_budget_s=15)

    def cfg1(h):
        m1 = build_map(1000, 32)
        r = bench_mapping(m1, 1000)
        c1 = bench_c_reference(m1, 100_000)
        if c1:
            r["c_baseline_mps"] = round(c1, 1)
            r["vs_c"] = round(r["mappings_per_sec"] / c1, 3)
        return r

    def cfg2(h):
        m2 = build_map(CFG2_PGS, CFG2_OSDS)
        r = bench_mapping(m2, CFG2_PGS)
        c2 = bench_c_reference(m2, min(BASELINE_PGS, CFG2_PGS))
        if c2:
            r["c_baseline_mps"] = round(c2, 1)
            r["vs_c"] = round(r["mappings_per_sec"] / c2, 3)
        return r

    def rebalance(h):
        # north-star: 10M-PG / 10k-OSD rebalance sim.  Outranks headline
        # so a slow headline can never starve it again (r01-r05), but
        # leaves HEADLINE_RESERVE_S of deadline for headline to run after.
        ns_pgs = int(os.environ.get("BENCH_NS_PGS", 10_000_000))
        ns_osds = int(os.environ.get("BENCH_NS_OSDS", 10_000))
        ns_rounds = int(os.environ.get("BENCH_NS_ROUNDS", 10))
        return bench_rebalance(
            ns_pgs, ns_osds, ns_rounds,
            lambda: h.remaining() - HEADLINE_RESERVE_S, handle=h,
        )

    def headline(h):
        n = N_PGS
        if h.remaining() < 180 and n > 250_000:
            n = 250_000
            _log(f"headline reduced to {n} PGs ({h.remaining():.0f}s left)")
        mh = build_map(n, N_OSDS)
        r = bench_mapping(mh, n, reps=max(1, REPS - 1))
        ch = bench_c_reference(mh, BASELINE_PGS)
        if ch:
            r["c_baseline_mps"] = round(ch, 1)
            r["vs_c"] = round(r["mappings_per_sec"] / ch, 3)
        r["diagnostics"] = bench_diagnostics(mh, n)
        return r

    def balancer_stage(h):
        return bench_balancer(
            int(os.environ.get("BENCH_BAL_PGS", 32768)),
            int(os.environ.get("BENCH_BAL_OSDS", 256)),
            # 3 iterations exercise the trace-once contract: weight-set
            # values are runtime operands, so iterations 2-3 must hit
            # _PIPE_CACHE (the stage's `jit` record proves it)
            int(os.environ.get("BENCH_BAL_COMPAT_ITERS", 3)),
        )

    sched.add("crushtool_1k_32", cfg1, priority=80, est_s=30,
              min_budget_s=25)
    # the lifetime chaos scenario outranks the big mapping configs: a
    # pathological headline run must not starve the robustness torture
    # test, but the soft timeout bounds it so a wedged epoch cannot
    # starve the rebalance/headline stages behind it either
    sched.add("lifetime", lambda h: bench_lifetime(h), priority=75,
              est_s=230, min_budget_s=180, soft_timeout_s=330)
    # the fleet rides right behind lifetime: its digest proof runs a
    # solo oracle per member in the same stage, so the soft timeout
    # bounds the double (serial + stacked) run
    sched.add("fleet", lambda h: bench_fleet(h), priority=74,
              est_s=90, min_budget_s=60, soft_timeout_s=240)
    # the serving daemon is the north-star heavy-traffic scenario: it
    # outranks the big mapping configs, and its soft timeout keeps a
    # wedged dispatcher from starving the stages behind it
    sched.add("serve", lambda h: bench_serve(h), priority=72,
              est_s=60, min_budget_s=35, soft_timeout_s=150)
    sched.add("testmappgs_100k_1k", cfg2, priority=70, est_s=60,
              min_budget_s=40)
    # soft timeout: the balancer stage runs AHEAD of the north-star
    # rebalance, so the watchdog must bound it — a wedged eval pass may
    # not re-starve the rebalance number (the r01-r05 failure mode)
    sched.add("balancer", balancer_stage, priority=65, est_s=90,
              min_budget_s=45, soft_timeout_s=150)
    # reserve: the rebalance watchdog abandons the stage early enough
    # that headline keeps its min budget + the reserve — the round loop's
    # own remaining() check can't help when a single build/round overruns
    # (BENCH r06: 486s gone before the first between-rounds check)
    sched.add("rebalance", rebalance, priority=60, est_s=150,
              min_budget_s=100, reserve_s=HEADLINE_RESERVE_S + 90)
    sched.add("headline", headline, priority=40, est_s=120,
              min_budget_s=90)
    sched.run()
    # final executable-registry snapshot, cost-analyzed: which compiled
    # programs this run built, what each costs per dispatch, and how
    # close each is to roofline.  progress(): stored + flushed, never a
    # stage (a --resume must not skip the stages behind it).
    ck.progress("executables",
                obs.executables.dump(analyze="full", budget_s=20.0))


# -------------------------------------------------------------- multichip
#
# `python bench.py --multichip` — the mesh-scaling record: for each
# device count, a FRESH subprocess self-forces that many virtual host
# devices (the parent's jax runtime is already initialized and cannot
# grow — exactly the sharded.py erroring path this replaces), builds the
# CEPH_TPU_MESH_DEVICES mesh, and measures the PRODUCTION sharded paths:
# ClusterState mapping throughput, a lifetime chaos run whose SHA-256
# digest must be bit-identical across every device count, and the
# candidate-batched vs sequential optimizer dispatch ratio.  The parent
# assembles one MULTICHIP-shaped JSON (tools/benchdiff folds it as the
# multichip trajectory, schema v8).  `backend=tpu`-ready: set
# BENCH_MC_BACKEND=tpu to skip the CPU forcing and run on real devices.

MC_DEVICES = os.environ.get("BENCH_MC_DEVICES", "1,2,8")
MC_PGS = int(os.environ.get("BENCH_MC_PGS", 65536))
MC_OSDS = int(os.environ.get("BENCH_MC_OSDS", 256))
MC_CHUNK = int(os.environ.get("BENCH_MC_CHUNK", 16384))
MC_REPS = int(os.environ.get("BENCH_MC_REPS", 3))
MC_SCENARIO = os.environ.get(
    "BENCH_MC_SCENARIO",
    "epochs=48,seed=11,hosts=4,osds_per_host=3,racks=2,pgs=128,"
    "ec=2+1,ec_pgs=32,chunk=1024,balance_every=16,balance_max=4,"
    "spotcheck_every=16,checkpoint_every=0,recovery=flat,workload=0",
)
MC_TIMEOUT = float(os.environ.get("BENCH_MC_TIMEOUT", 420))


def _mc_optimizer_ab(mesh) -> dict:
    """Sequential vs candidate-batched calc_pg_upmaps on identical
    skewed maps (device backend, rows sharded over `mesh`): the
    counter-proven dispatches-per-accepted-change ratio and the
    plan-quality parity check."""
    from ceph_tpu.balancer.upmap import calc_pg_upmaps

    pgs = int(os.environ.get("BENCH_MC_BAL_PGS", 8192))
    osds = int(os.environ.get("BENCH_MC_BAL_OSDS", 128))
    budget = int(os.environ.get("BENCH_MC_BAL_ITER", 64))
    max_dev = 2

    def mk():
        m = build_map(pgs, osds)
        rng = np.random.default_rng(5)
        for o in rng.choice(osds, max(2, osds // 10), replace=False):
            m.osd_weight[int(o)] = int(0x10000 * 0.6)
        return m

    out: dict = {"pgs": pgs, "osds": osds, "budget": budget}
    for name, kw in (("sequential", {}),
                     ("batched", {"candidate_batch": 32})):
        m = mk()
        s0 = _balancer_snap()
        t0 = time.perf_counter()
        r = calc_pg_upmaps(
            m, max_deviation=max_dev, max_iter=budget,
            rng=np.random.default_rng(100), backend="device",
            mesh=mesh, **kw,
        )
        dt = time.perf_counter() - t0
        s1 = _balancer_snap()
        acc = s1["changes_accepted"] - s0["changes_accepted"]
        rej = s1["changes_rejected"] - s0["changes_rejected"]
        bat = s1["candidate_batches"] - s0["candidate_batches"]
        evals = bat if kw else acc + rej
        out[name] = {
            "wall_s": round(dt, 2),
            "changes": r.num_changed,
            "max_deviation": round(float(r.max_deviation), 2),
            "stddev": round(float(r.stddev), 1),
            "evals": evals,
            "dispatches_per_change": round(evals / max(acc, 1), 4),
        }
    s, b = out["sequential"], out["batched"]
    out["dispatch_reduction_x"] = round(
        s["dispatches_per_change"]
        / max(b["dispatches_per_change"], 1e-9), 1)
    # no worse at equal max_deviation: the batched plan lands at most
    # where the sequential one did (or inside the requested bound)
    out["quality_no_worse"] = bool(
        b["max_deviation"]
        <= max(s["max_deviation"], float(max_dev)) + 1e-6)
    out["dispatches_per_change"] = b["dispatches_per_change"]
    return out


def _mc_worker(n: int) -> None:
    """One device-count measurement, in a fresh self-forced process."""
    backend = os.environ.get("BENCH_MC_BACKEND", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if (backend == "cpu"
            and "xla_force_host_platform_device_count" not in flags):
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    if backend == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from ceph_tpu.osd.state import ClusterState
    from ceph_tpu.parallel.sharded import (
        default_mesh,
        last_mesh_provenance,
    )
    from ceph_tpu.sim.lifetime import LifetimeSim, Scenario

    out: dict = {"n": n}
    with obs.span("bench.multichip", devices=n):
        # parent exported CEPH_TPU_MESH_DEVICES=n; a mesh that came up
        # smaller than asked is visible in the provenance and fails the
        # parent's mesh_ok gate — a degraded mesh can never masquerade
        # as a scaling run
        mesh = default_mesh()
        out["mesh"] = last_mesh_provenance()
        m = build_map(MC_PGS, MC_OSDS)
        state = ClusterState(m, chunk=MC_CHUNK, mesh=mesh)
        pm = state.mapper(0)
        jax.block_until_ready(pm.map_all_device(MC_CHUNK))  # warm
        jit0 = _jit_counters()
        t0 = time.perf_counter()
        for _ in range(MC_REPS):
            rows = pm.map_all_device(MC_CHUNK)
        jax.block_until_ready(rows)
        dt = (time.perf_counter() - t0) / MC_REPS
        out["map"] = {
            "pgs": MC_PGS,
            "mappings_per_sec": round(MC_PGS / dt, 1),
            "warm_jit": _jit_delta(jit0),
        }
        sim = LifetimeSim(Scenario.parse(MC_SCENARIO), backend="jax",
                          mesh=mesh)
        lt = sim.run()
        out["lifetime"] = {
            "digest": lt["digest"],
            "epochs": lt["epochs"],
            "epochs_per_sec": lt["epochs_per_sec"],
            "steady_compiles": lt["trace_once"]["steady_compiles"],
            "violations": lt["invariant_violations"],
        }
        if os.environ.get("BENCH_MC_OPT"):
            out["balancer"] = _mc_optimizer_ab(mesh)
    print(json.dumps(out))


def multichip_supervise(devices: list[int]) -> int:
    t_all = time.time()
    maxn = max(devices)
    results: dict = {}
    notes: list[str] = []
    for n in devices:
        env = dict(os.environ, BENCH_MC_WORKER=str(n),
                   CEPH_TPU_MESH_DEVICES=str(n))
        env.pop("BENCH_WORKER", None)
        if n == maxn:
            env["BENCH_MC_OPT"] = "1"
        _log(f"multichip: measuring {n} device(s)")
        t0 = time.time()
        rec: dict = {"n": n}
        try:
            proc = subprocess.run(
                [sys.executable, str(Path(__file__).resolve()),
                 "--multichip"],
                env=env, capture_output=True, text=True,
                timeout=MC_TIMEOUT,
            )
            rec = json.loads(proc.stdout.strip().splitlines()[-1])
            if proc.returncode != 0:
                notes.append(f"{n}-device worker rc={proc.returncode}")
        except (subprocess.TimeoutExpired, ValueError, IndexError) as e:
            notes.append(f"{n}-device worker failed: {e!r}"[:200])
            rec["error"] = f"{type(e).__name__}"
        rec["wall_s"] = round(time.time() - t0, 1)
        results[n] = rec
    digests = {n: (r.get("lifetime") or {}).get("digest")
               for n, r in results.items()}
    vals = [d for d in digests.values() if d]
    digest_match = (len(vals) == len(devices)
                    and len(set(vals)) == 1)
    # n=1 runs meshless by design (default_mesh: <=1 = single-device,
    # the baseline the digests are compared against)
    mesh_ok = all(
        (not (r.get("mesh") or {}) if n <= 1
         else (r.get("mesh") or {}).get("actual") == n)
        for n, r in results.items())
    steadies = [(r.get("lifetime") or {}).get("steady_compiles", -1)
                for r in results.values()]
    steady = max(steadies) if steadies else -1
    maxrec = results.get(maxn) or {}
    bal = maxrec.get("balancer") or {}
    ok = bool(
        digest_match and mesh_ok and steady == 0
        and not notes
        and bal.get("dispatch_reduction_x", 0) >= 5
        and bal.get("quality_no_worse", False)
    )
    out = {
        "n_devices": maxn,
        "rc": 0 if ok else 1,
        "ok": ok,
        "skipped": False,
        "schema_version": SCHEMA_VERSION,
        "backend": os.environ.get("BENCH_MC_BACKEND", "cpu"),
        "scaling": {
            "devices": maxn,
            "digest_match": digest_match,
            "eps_per_device": round(
                ((maxrec.get("lifetime") or {})
                 .get("epochs_per_sec") or 0.0) / maxn, 3),
            "maps_per_sec_per_device": round(
                ((maxrec.get("map") or {})
                 .get("mappings_per_sec") or 0.0) / maxn, 1),
            "steady_compiles": steady,
        },
        "balancer": bal,
        "mesh_ok": mesh_ok,
        "workers": {str(n): r for n, r in results.items()},
        "cpu_threads": os.cpu_count(),
        "elapsed_s": round(time.time() - t_all, 1),
    }
    if (out["backend"] == "cpu"
            and maxn > (os.cpu_count() or 1)):
        notes = notes + [
            f"forced {maxn} virtual devices on {os.cpu_count()} CPU "
            "thread(s): partitioning overhead without physical "
            "parallelism — wall-clock scaling needs real chips "
            "(BENCH_MC_BACKEND=tpu); the digest-match / 0-compile / "
            "dispatch-ratio proofs are hardware-independent"
        ]
    if notes:
        out["notes"] = notes
    out["tail"] = (
        f"multichip {'ok' if ok else 'FAIL'}: {maxn} devices, "
        f"digest {'match' if digest_match else 'MISMATCH'}, "
        f"{bal.get('dispatch_reduction_x', 0)}x fewer "
        "dispatches/change"
    )
    print(json.dumps(out))
    return 0 if ok else 1


# -------------------------------------------------------------- supervisor

def _strip_perf(stage):
    """Per-stage perf snapshots stay in BENCH_partial.json; the headline
    JSON keeps just the numbers."""
    if isinstance(stage, dict):
        return {k: v for k, v in stage.items() if k != "perf"}
    return stage


def _quantile_section(perf: dict) -> dict:
    """p50/p90/p99 of the hot dispatch spans from a perf snapshot — the
    tail-latency record the serve-stage QPS targets will be written
    against (quantile-kind counters, ceph_tpu.obs.quantiles)."""
    out = {}
    for span, grp, key in (
        ("pipeline.map_block", "pipeline", "map_block_seconds"),
        # registered span-name bases (obs/spans.py), so the section
        # cross-references cleanly against traces
        ("ec.gf_matmul_batch", "ec", "gf_batch_dispatch_hist"),
        ("balancer.round", "balancer", "round_hist"),
    ):
        rec = (perf.get(grp) or {}).get(key)
        if isinstance(rec, dict) and rec.get("count"):
            out[span] = {
                k: (round(rec[k], 6) if isinstance(rec[k], float)
                    else rec[k])
                for k in ("p50", "p90", "p99", "count") if k in rec
            }
    return out


def _assemble(stages: dict, notes: list[str], elapsed: float) -> dict:
    configs = {}
    for key in ("crushtool_1k_32", "testmappgs_100k_1k", "headline"):
        if key in stages:
            configs[key] = _strip_perf(stages[key])
    ec = {}
    for key in ("ec_jax", "ec_native", "ec_clay"):
        if key in stages:
            ec.update(_strip_perf(stages[key]))
    init = stages.get("init", {})
    head = (configs.get("headline") or configs.get("testmappgs_100k_1k")
            or configs.get("crushtool_1k_32") or {})
    value = head.get("mappings_per_sec", 0.0)
    vs = head.get("vs_c", 0.0)
    out = {
        "metric": "pg_mappings_per_sec",
        "schema_version": SCHEMA_VERSION,
        "value": value,
        "unit": "mappings/s",
        "vs_baseline": vs,
        # explicit acquisition provenance (runtime.BackendInfo): which
        # backend produced the number, why it degraded, how hard init was
        "backend": init.get("backend", "none"),
        "device": init.get("device", "none"),
        "fallback_reason": init.get("fallback_reason"),
        "attempts": init.get("attempts", 0),
        "init_s": init.get("init_s"),
        "c_baseline_mps": head.get("c_baseline_mps"),
        "configs": configs,
        "ec": ec,
        "elapsed_s": round(elapsed, 1),
    }
    for key in ("diagnosis", "failures"):
        if init.get(key):
            out[key] = init[key]
    if stages.get("resumed_stages"):
        out["resumed_stages"] = stages["resumed_stages"]
    if "stages_done" in stages:
        out["stages_done"] = list(stages["stages_done"])
    if "balancer" in stages:
        out["balancer"] = _strip_perf(stages["balancer"])
    if "lifetime" in stages:
        out["lifetime"] = _strip_perf(stages["lifetime"])
    if "serve" in stages:
        out["serve"] = _strip_perf(stages["serve"])
    if "fleet" in stages:
        out["fleet"] = _strip_perf(stages["fleet"])
    if "executables" in stages:
        out["executables"] = stages["executables"]
    q = _quantile_section(stages.get("perf") or {})
    if q:
        out["quantiles"] = q
    # the placement flight-recorder section rides the headline map
    # (schema v3); hoisted to the top level for benchdiff
    for cname in ("headline", "testmappgs_100k_1k", "crushtool_1k_32"):
        c = configs.get(cname)
        if isinstance(c, dict) and "diagnostics" in c:
            out["diagnostics"] = c.pop("diagnostics")
            break
    if "rebalance" in stages:
        rb = _strip_perf(stages["rebalance"])
        key = "rebalance"
        if rb.get("pgs") == 10_000_000 and rb.get("osds") == 10_000:
            key = "rebalance_10m_10k"  # the BASELINE config-5 name
        out[key] = rb
    if "headline_skipped" in stages:
        notes = notes + [
            "headline skipped at deadline "
            f"({stages['headline_skipped'].get('remaining_s')}s left); "
            "value falls back to a smaller config"
        ]
    if "errors" in stages:
        out["errors"] = stages["errors"]
    if notes:
        out["notes"] = notes
    return out


INIT_TIMEOUT_S = float(os.environ.get("BENCH_INIT_TIMEOUT", 240))


def _read_partial() -> dict:
    try:
        return json.loads(PARTIAL.read_text())
    except Exception:
        return {}


def _run_worker(env: dict, deadline: float,
                init_timeout: float | None) -> tuple[int | None, str]:
    """Run the worker, polling PARTIAL; returns (rc|None on kill, reason).

    init_timeout: if set and the worker's 'init' stage hasn't appeared
    within that many seconds, the worker is presumed hung in accelerator
    init (the known axon stall) and killed early, leaving deadline budget
    for the CPU retry."""
    proc = subprocess.Popen(
        [sys.executable, str(Path(__file__).resolve())],
        env=env, start_new_session=True,
        stdout=subprocess.DEVNULL,
    )
    t0 = time.time()
    reason = ""
    while True:
        try:
            rc = proc.wait(timeout=2)
            return rc, "" if rc == 0 else f"worker exited rc={rc}"
        except subprocess.TimeoutExpired:
            pass
        el = time.time() - t0
        if el > deadline:
            reason = f"worker killed at {deadline:.0f}s deadline"
            break
        if (init_timeout is not None and el > init_timeout
                and "init" not in _read_partial()):
            reason = (f"accelerator init still hung at {el:.0f}s; "
                      "killed worker")
            break
    _log(reason)
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except OSError:
        proc.kill()
    proc.wait()
    return None, reason


def _diff_against(out: dict, pattern: str) -> dict:
    """Diff this run's assembled record against a prior series
    (`--diff-against 'BENCH_r*.json'`); the summary rides in the output
    JSON so the regression check is part of the bench record itself."""
    from tools.benchdiff import Round, diff_series, load_series

    paths = sorted(glob.glob(pattern))
    if not paths:
        return {"error": f"no files match {pattern!r}"}
    rounds = load_series(paths)
    rounds.append(Round("current", out))
    with obs.span("bench.diff", rounds=len(rounds)):
        rep = diff_series(rounds)
    return {
        "verdict": rep["verdict"],
        "rounds": [r["round"] for r in rep["rounds"]],
        "gaps": [g["round"] for g in rep["gaps"]],
        "regressions": rep["regressions"],
        "improvements": len(rep["improvements"]),
        "calibration_ref_gbps": rep["calibration_ref_gbps"],
    }


def supervise(resume: bool = False, diff_pattern: str | None = None) -> None:
    from ceph_tpu.obs import admin_socket

    admin_socket.release()  # the worker owns CEPH_TPU_ADMIN_SOCKET
    t0 = time.time()
    notes: list[str] = []
    if resume:
        prev = _read_partial()
        done = prev.get("stages_done", [])
        if done:
            notes.append(f"resumed: {len(done)} stage(s) checkpointed")
            _log(f"resuming past stages {done}")
        else:
            resume = False  # nothing to resume from
    if not resume:
        PARTIAL.unlink(missing_ok=True)
    env = dict(os.environ, BENCH_WORKER="1", BENCH_T0=str(t0))
    if resume:
        env["BENCH_RESUME"] = "1"
    rc, reason = _run_worker(env, DEADLINE_S, INIT_TIMEOUT_S)
    if reason:
        notes.append(reason)
    stages = _read_partial()

    # backend acquisition never completed (the runtime ladder itself was
    # killed, or the worker died first) -> one CPU retry, resuming any
    # stages that did checkpoint, so a number exists
    if "init" not in stages.get("stages_done", ()):
        if os.environ.get("BENCH_REQUIRE_TPU", "0") not in ("", "0"):
            print(json.dumps(_assemble(stages, notes, time.time() - t0)))
            raise SystemExit(2)
        left = DEADLINE_S - (time.time() - t0)
        if left > 60:
            _log(f"retrying on CPU ({left:.0f}s left)")
            env = dict(env, BENCH_FORCE_CPU="1", BENCH_RESUME="1",
                       BENCH_T0=str(time.time()),
                       BENCH_DEADLINE_S=str(left))
            rc, reason = _run_worker(env, left, None)
            if reason:
                notes.append(f"cpu retry: {reason}")
            stages = _read_partial()
    out = _assemble(stages, notes, time.time() - t0)
    if diff_pattern:
        try:
            out["benchdiff"] = _diff_against(out, diff_pattern)
        except Exception as e:  # the diff must never eat the numbers
            out["benchdiff"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps(out))


# -------------------------------------------------------------- selftest

SELFTEST_ENV = {
    # miniature workload: every stage runs, CPU-only, ~tens of seconds.
    # headline and cfg2 share OSD count and chunk so the persistent
    # compile cache serves headline from cfg2's compile.
    "BENCH_PGS": "8192", "BENCH_OSDS": "256", "BENCH_CHUNK": "4096",
    "BENCH_CFG2_PGS": "4096", "BENCH_CFG2_OSDS": "256",
    "BENCH_BASELINE_PGS": "20000", "BENCH_EC_MB": "2",
    "BENCH_NS_PGS": "2048", "BENCH_NS_OSDS": "64", "BENCH_NS_ROUNDS": "2",
    "BENCH_BAL_PGS": "1024", "BENCH_BAL_OSDS": "64",
    "BENCH_BAL_COMPAT_ITERS": "1",
    "BENCH_REPS": "1",
    # the acceptance floor: a >=500-epoch seeded chaos scenario with an
    # injected mid-run device loss and an interrupt+resume digest proof
    "BENCH_LIFETIME_EPOCHS": "510",
    "BENCH_LIFETIME_CK": "BENCH_selftest_lifetime_ck.json",
    # serve stage small variant: a live service under load with swaps,
    # an injected device loss, the overload burst, and a short chaos run
    "BENCH_SERVE_PGS": "2048", "BENCH_SERVE_OSDS": "64",
    "BENCH_SERVE_SECONDS": "5", "BENCH_SERVE_CLIENTS": "2",
    "BENCH_SERVE_BLOCK": "512", "BENCH_SERVE_CHAOS_EPOCHS": "6",
    "BENCH_SERVE_BULK_SECONDS": "2", "BENCH_SERVE_FRONT_BLOCKS": "10",
    "BENCH_SERVE_MESH_PGS": "64",
    # fleet stage: the 64-cluster acceptance floor, short lifetimes —
    # the stage pays the solo-oracle loop AND the stacked run
    "BENCH_FLEET_CLUSTERS": "64", "BENCH_FLEET_EPOCHS": "16",
    # generous deadline: the bound comes from the workloads being tiny,
    # not from budget-skipping stages (skips would fail the assert); the
    # 510-epoch lifetime scenario alone is ~200s of real dispatches on a
    # throttled 2-thread container, the fleet stage adds a 64x solo
    # oracle loop plus the stacked run, and the serve bulk/mesh/front
    # phase adds ~2 minutes (incl. the 2-device meshcheck subprocess)
    "BENCH_DEADLINE_S": "720", "BENCH_HEADLINE_RESERVE": "20",
    # the survivability path under test: the configured-platform probe
    # hangs; the watchdog kills it in ~2s and the ladder degrades to cpu
    "CEPH_TPU_FAULTS": "init.auto=hang:600",
    "CEPH_TPU_LADDER": "auto,cpu",
    "BENCH_PROBE_TIMEOUT": "2", "CEPH_TPU_INIT_ATTEMPTS": "1",
    "BENCH_PARTIAL": "BENCH_selftest.json",
}

SELFTEST_STAGES = (
    "init", "ec_jax", "ec_clay", "crushtool_1k_32", "lifetime",
    "fleet", "serve", "testmappgs_100k_1k", "balancer", "rebalance",
    "headline",
)


def _selftest_graftlint(problems: list[str]) -> dict:
    """All graftlint passes over the whole repo, JSON report embedded in
    the selftest record: contract drift (an undeclared counter, a span
    typo, a kernel baking a table into its trace) fails the same fast
    CPU gate that guards the survivability path, instead of surfacing as
    the next r05-style bench post-mortem."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--json"],
            capture_output=True, text=True, timeout=120, cwd=_HERE,
        )
        rep = json.loads(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError) as e:
        problems.append(f"graftlint did not produce a report: {e!r}")
        return {"error": str(e)}
    if proc.returncode != 0 or rep.get("count", -1) != 0:
        problems.append(
            f"graftlint: {rep.get('count')} violation(s): "
            + "; ".join(
                v["path"] + ":" + str(v["line"]) + " " + v["pass"]
                for v in rep.get("violations", [])[:5]
            )
        )
    # the full pass list + zero count is the record of what was checked
    return {k: rep[k] for k in ("passes", "files_scanned", "count",
                                "elapsed_s") if k in rep}


def _selftest_executables(out: dict, problems: list[str]) -> dict:
    """The executable-registry acceptance gate: the run must have
    registered and cost-analyzed at least one pipeline-side executable
    (pipe/kernel/bench caches all compile the mapping pipeline) and one
    EC executable — otherwise the registry is decorative."""
    ex = out.get("executables") or {}
    entries = ex.get("entries") or []

    def analyzed(e):
        return isinstance(e.get("cost"), dict) and "error" not in e["cost"]

    if not entries:
        problems.append("executables registry section empty")
    else:
        if not any(e.get("cache") in ("pipe", "kernel", "bench")
                   and analyzed(e) for e in entries):
            problems.append(
                "no cost-analyzed pipeline executable in the registry")
        if not any(e.get("cache") == "ec" and analyzed(e)
                   for e in entries):
            problems.append("no cost-analyzed EC executable in the registry")
    return {
        "entries": len(entries),
        "by_cache": ex.get("by_cache"),
        "cost_analyzed": ex.get("cost_analyzed"),
        "total_compile_seconds": ex.get("total_compile_seconds"),
    }


def _selftest_benchdiff(problems: list[str]) -> dict:
    """Run the trajectory differ over the frozen fixture series (real
    r01-r05 rounds incl. the r02 gap, plus synthetic calibrated rounds
    with a seeded regression).  The differ must flag the seed — a
    differ that cannot see a planted regression guards nothing."""
    from tools.benchdiff import diff_series, load_series

    try:
        paths = sorted(BENCHDIFF_FIXTURES.glob("*.json"))
        rep = diff_series(load_series(paths))
    except Exception as e:
        problems.append(f"benchdiff fixture run failed: {e!r}")
        return {"error": f"{type(e).__name__}: {e}"[:200]}
    if rep["verdict"] != "regression" or not rep["regressions"]:
        problems.append(
            "benchdiff did not flag the regression seeded in the fixture "
            "series")
    elif not any(d["metric"].startswith("serve.")
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the serve regression seeded in the "
            "fixture series (schema v5 serve.* metrics not folded)")
    elif not any(d["metric"] in ("lifetime.steady_full_rebuilds",
                                 "serve.swap_full_restages")
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the ClusterState O(delta)-contract "
            "regression seeded in the fixture series (schema v6 state "
            "metrics not folded)")
    elif not any(d["metric"].startswith(("lifetime.recovery.",
                                         "lifetime.workload."))
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the recovery/workload regression "
            "seeded in the fixture series (schema v7 metrics not "
            "folded)")
    elif not any(d["metric"].startswith("multichip.scaling.")
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the multichip scaling regression "
            "seeded in the fixture series (schema v8 multichip.scaling "
            "metrics not folded)")
    elif not any(d["metric"] == "balancer.dispatches_per_change"
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the candidate-batched optimizer "
            "regression seeded in the fixture series (schema v8 "
            "balancer.dispatches_per_change not folded)")
    elif not any(d["metric"].startswith(("lifetime.health",
                                         "serve.slo."))
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the health/SLO regression seeded "
            "in the fixture series (schema v9 health/slo metrics not "
            "folded)")
    elif not any(d["metric"].startswith("lifetime.durability.")
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the durability regression seeded "
            "in the fixture series (schema v10 pg_lost 0->N "
            "zero-baseline case not folded)")
    elif not any(d["metric"] in ("rebalance.plan_dispatches",
                                 "rebalance.dispatches_per_change")
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the device-loop dispatch "
            "regression seeded in the fixture series (schema v11 "
            "rebalance metrics not folded)")
    elif not any(d["metric"] == "serve.background_query_compiles"
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the background-balancing compile "
            "regression seeded in the fixture series (schema v11 "
            "serve.background_query_compiles 0->N zero-baseline case "
            "not folded)")
    elif not any(d["metric"].startswith("fleet.")
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the fleet regression seeded in "
            "the fixture series (schema v12 fleet metrics not folded)")
    elif not any(d["metric"] == "serve.bulk_qps"
                 for d in rep["regressions"]):
        problems.append(
            "benchdiff did not flag the bulk-edge qps regression "
            "seeded in the fixture series (schema v13 serve.bulk "
            "metrics not folded)")
    return {
        "verdict": rep["verdict"],
        "rounds": len(rep["rounds"]),
        "gaps": len(rep["gaps"]),
        "regressions": len(rep["regressions"]),
        "flagged": sorted({d["metric"] for d in rep["regressions"]})[:6],
    }


def selftest() -> int:
    """CPU-only survivability check: inject a TPU-init hang, then
    require that EVERY stage (including a miniature rebalance and the
    510-epoch lifetime chaos scenario) completes and the output carries
    the degradation provenance.  Exercises probe watchdog -> ladder
    descent -> scheduler -> checkpoint end to end; a regression in any
    of those fails this fast instead of blanking the next real
    benchmark run.  The lifetime stage makes this a minutes-scale gate
    on a throttled container (bounded by the 480s worker deadline)."""
    t0 = time.time()
    env = dict(os.environ)
    env.pop("BENCH_REQUIRE_TPU", None)
    env.pop("BENCH_WORKER", None)
    env.update(SELFTEST_ENV)
    problems: list[str] = []
    out: dict = {}
    try:
        proc = subprocess.run(
            [sys.executable, str(Path(__file__).resolve())],
            env=env, capture_output=True, text=True, timeout=680,
        )
    except subprocess.TimeoutExpired as e:
        # the one failure mode that must still produce a verdict JSON:
        # the survivability path itself regressed into a wedge
        problems.append(
            "selftest run wedged past 680s (survivability path "
            f"regression?): {str(e.stderr)[-300:] if e.stderr else ''}"
        )
    else:
        try:
            out = json.loads(proc.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            problems.append(f"no JSON on stdout (rc={proc.returncode}): "
                            f"{proc.stdout[-200:]!r} {proc.stderr[-300:]!r}")
    if out:
        missing = [s for s in SELFTEST_STAGES
                   if s not in out.get("stages_done", ())]
        if missing:
            problems.append(f"stages missing: {missing}")
        if out.get("backend") != "cpu":
            problems.append(f"backend={out.get('backend')!r}, wanted cpu")
        if not out.get("fallback_reason"):
            problems.append("no fallback_reason despite injected hang")
        if not out.get("attempts", 0) >= 2:
            problems.append(f"attempts={out.get('attempts')}, wanted >=2")
        if not out.get("value", 0) > 0:
            problems.append("headline value is zero")
        if out.get("schema_version") != SCHEMA_VERSION:
            problems.append(
                f"schema_version={out.get('schema_version')!r}, wanted "
                f"{SCHEMA_VERSION}")
        q = (out.get("quantiles") or {}).get("pipeline.map_block") or {}
        if not (q.get("p50", 0) > 0 and q.get("p99", 0) > 0):
            problems.append(
                "no p50/p99 for pipeline.map_block dispatch in the output")
        # placement-diagnostics acceptance gate: the flight recorder
        # must have seen real decisions, and instrumenting must have
        # cost the default path nothing (0 compiles, identical bytes)
        dg = out.get("diagnostics") or {}
        if not sum(dg.get("tries_histogram") or []):
            problems.append("diagnostics tries histogram empty or missing")
        if dg.get("default_path_compiles") != 0:
            problems.append(
                "default path booked "
                f"{dg.get('default_path_compiles')} compile(s) after the "
                "instrumented variant was built (wanted 0)")
        if not dg.get("mapping_identical"):
            problems.append(
                "default-path mapping not bit-identical after the "
                "instrumented run")
        # lifetime acceptance gates: >=500 chaos epochs, invariants
        # clean, trace-once across epoch applies, device loss degraded
        # not fatal, and interrupt+resume bit-identical
        lf = out.get("lifetime") or {}
        if lf.get("epochs", 0) < 500:
            problems.append(
                f"lifetime ran {lf.get('epochs')} epochs (wanted >=500)")
        if lf.get("invariant_violations", -1) != 0:
            problems.append(
                f"lifetime invariant violations: "
                f"{lf.get('invariant_violations')} "
                f"({(lf.get('violations') or ['?'])[:2]})")
        if lf.get("steady_compiles", -1) != 0:
            problems.append(
                f"lifetime steady epochs booked "
                f"{lf.get('steady_compiles')} compile(s) — epoch apply "
                "is not trace-once")
        if lf.get("steady_full_rebuilds", -1) != 0:
            problems.append(
                f"lifetime steady epochs booked "
                f"{lf.get('steady_full_rebuilds')} ClusterState "
                "rebuild(s) — epoch apply is not O(delta)")
        if lf.get("balancer_builds", -1) != 0:
            problems.append(
                f"lifetime balancer paid {lf.get('balancer_builds')} "
                "O(PGs) membership build(s) — build_state_seconds "
                "should be absent from steady-state balancer rounds "
                "(ClusterState rows not reused)")
        if not lf.get("device_loss_fallbacks"):
            problems.append(
                "lifetime injected device loss did not degrade "
                "(no fallback recorded)")
        if not lf.get("resume_digest_match"):
            problems.append(
                "lifetime resume digest != straight-run digest")
        # health model acceptance gates (schema v9): the chaos
        # scenario must trip real checks, the observers must be
        # provably free, and the timeline must survive the resume
        # round-trip with its monotonic sample index intact
        hl = lf.get("health") or {}
        hep = hl.get("epochs") or {}
        if not (hep.get("warn", 0) + hep.get("err", 0)) > 0:
            problems.append(
                "lifetime chaos scenario recorded no non-OK health "
                "epoch (health model inert through device deaths and "
                "degraded PGs)")
        if not lf.get("health_pure"):
            problems.append(
                f"health/timeline observers are not pure: digests or "
                f"steady compiles moved under CEPH_TPU_HEALTH=0 "
                f"({lf.get('health_purity')})")
        if not hl.get("timeline_samples", 0) > 0:
            problems.append("sim timeline recorded no samples")
        elif lf.get("resume_timeline_samples") \
                != hl.get("timeline_samples"):
            problems.append(
                "timeline did not survive checkpoint resume with a "
                f"continuous sample index (straight "
                f"{hl.get('timeline_samples')} != resumed "
                f"{lf.get('resume_timeline_samples')})")
        # recovery data plane + workload acceptance gates: the queue
        # conserved every byte, a real backlog was observed (the flat
        # model's silent floor would show 0), and the pareto headline
        # carries a stated served QPS
        rcv = lf.get("recovery") or {}
        if rcv.get("conservation_violations", -1) != 0:
            problems.append(
                f"recovery queue conservation violations: "
                f"{rcv.get('conservation_violations')} (enqueued != "
                "drained + backlog somewhere)")
        if not rcv.get("backlog_peak_gb", 0) > 0:
            problems.append(
                "recovery queue observed no backlog across the chaos "
                "scenario (queue model inert — flat-floor behavior)")
        pareto = lf.get("pareto") or {}
        if not pareto.get("served_qps", 0) > 0:
            problems.append(
                "lifetime pareto headline carries no served QPS "
                "(workload generator inert)")
        if not pareto.get("cluster_years_per_hour", 0) > 0:
            problems.append(
                "lifetime pareto headline carries no "
                "cluster-years/hour")
        if not (lf.get("workload") or {}).get("degraded_reads", 0) > 0:
            problems.append(
                "lifetime workload served no degraded reads across a "
                "chaos scenario (client-visible story missing)")
        # correlated-failure chaos acceptance gates (schema v10): the
        # scenario must actually cascade, flap its designated repeat
        # offenders, and revive false-positive down-marks; the
        # durability ledger must record real exposure yet lose NOTHING
        # (the default scenario is sized survivable); the overwhelmed
        # mini-run must lose PGs and latch DATA_LOSS; and the ref
        # backend must land on the jax slice digest bit-for-bit
        cha = lf.get("chaos") or {}
        if not cha.get("cascades", 0) >= 1:
            problems.append(
                "lifetime correlated scenario produced no cascade "
                "(sibling-hazard model inert)")
        if not cha.get("repeat_flaps", 0) >= 2:
            problems.append(
                f"lifetime designated flappers flapped "
                f"{cha.get('repeat_flaps')} time(s) (wanted >=2 — "
                "repeat-offender model inert)")
        if not cha.get("false_flap_revives", 0) >= 1:
            problems.append(
                "lifetime recorded no false-flap revive (network-flap "
                "vs real-death distinction inert)")
        dur = lf.get("durability") or {}
        if dur.get("pg_lost", -1) != 0:
            problems.append(
                f"lifetime durability lost {dur.get('pg_lost')} PG(s) "
                "on the SURVIVABLE default scenario (wanted 0 — either "
                "the heal path broke or the scenario tuning drowned)")
        if not dur.get("exposed_pg_epochs", 0) > 0:
            problems.append(
                "lifetime durability recorded no exposed PG-epochs "
                "across a chaos scenario (wound ledger inert)")
        ovw = lf.get("overwhelmed") or {}
        if not ovw.get("pg_lost", 0) > 0:
            problems.append(
                "overwhelmed mini-run lost no PGs (loss path can "
                "never fire)")
        if not ovw.get("data_loss_latched"):
            problems.append(
                "overwhelmed mini-run did not latch DATA_LOSS at "
                "HEALTH_ERR")
        if ovw.get("invariant_violations", -1) != 0:
            problems.append(
                f"overwhelmed mini-run broke invariants: "
                f"{ovw.get('invariant_violations')}")
        if not lf.get("ref_digest_match"):
            problems.append(
                "lifetime ref-backend slice digest != jax slice digest "
                "(correlated model not backend-exact)")
        # fleet acceptance gates (schema v12): >=64 heterogeneous
        # clusters through ONE stacked dispatch per epoch batch, 0
        # steady compiles, every stacked digest bit-identical to its
        # solo oracle, aggregate throughput strictly above the
        # serial-solo baseline measured in the same stage, and a
        # non-empty pareto front
        flt = out.get("fleet") or {}
        if flt.get("clusters", 0) < 64:
            problems.append(
                f"fleet ran {flt.get('clusters')} clusters "
                "(wanted >=64)")
        if flt.get("digest_matches", -1) != flt.get("clusters", 0):
            problems.append(
                f"fleet stacked digests matched only "
                f"{flt.get('digest_matches')}/{flt.get('clusters')} "
                "solo oracles (mismatched members: "
                f"{flt.get('digest_mismatches')})")
        if flt.get("steady_compiles", -1) != 0:
            problems.append(
                f"fleet steady epoch batches booked "
                f"{flt.get('steady_compiles')} compile(s) — the "
                "stacked lane structure is not constant")
        if flt.get("serial_epochs_per_sec") is None or \
                flt.get("cluster_epochs_per_sec", 0.0) \
                <= flt["serial_epochs_per_sec"]:
            problems.append(
                f"fleet stacked rate "
                f"{flt.get('cluster_epochs_per_sec')} cluster-epochs/s "
                "did not beat the serial-solo baseline "
                f"({flt.get('serial_epochs_per_sec')}) measured in the "
                "same stage")
        if not flt.get("pareto_front_size", 0) >= 1:
            problems.append(
                "fleet pareto front is empty (no non-dominated member)")
        if flt.get("invariant_violations", -1) != 0:
            problems.append(
                f"fleet members booked "
                f"{flt.get('invariant_violations')} invariant "
                "violation(s)")
        # serve acceptance gates: sustained QPS with a recorded tail
        # across live epoch swaps, zero dropped queries, swaps that
        # never stall readers past the bound, 0 steady compiles,
        # deterministic EBUSY shedding, and the injected device loss
        # answered + recovered
        sv = out.get("serve") or {}
        if not sv.get("qps", 0) > 0:
            problems.append("serve recorded no QPS")
        if sv.get("dropped", -1) != 0:
            problems.append(
                f"serve dropped {sv.get('dropped')} queries (wanted 0: "
                "every query must be answered)")
        if not sv.get("swaps", 0) >= 2:
            problems.append(
                f"serve saw {sv.get('swaps')} live epoch swaps "
                "(wanted >=2)")
        if sv.get("swap_stalls", -1) != 0:
            problems.append(
                f"serve: {sv.get('swap_stalls')} swap(s) stalled "
                f"readers past {sv.get('stall_bound_s')}s")
        if not (sv.get("request_p99_s") or 0) > 0:
            problems.append("serve recorded no request p99")
        if sv.get("steady_compiles", -1) != 0:
            problems.append(
                f"serve steady state booked "
                f"{sv.get('steady_compiles')} compile(s) — epoch swaps "
                "are not operand refreshes")
        if not sv.get("swap_delta_applies", 0) >= 2:
            problems.append(
                f"serve staged only {sv.get('swap_delta_applies')} "
                "value-only swap(s) via ClusterState delta (wanted >=2)")
        if sv.get("swap_full_restages", -1) != 0 \
                or sv.get("swap_state_rebuilds", -1) != 0:
            problems.append(
                "serve value-only swaps paid full restages "
                f"({sv.get('swap_full_restages')}) / state rebuilds "
                f"({sv.get('swap_state_rebuilds')}) — staging is not "
                "riding ClusterState deltas")
        if not sv.get("burst_shed", 0) > 0:
            problems.append(
                "serve overload burst shed nothing (admission control "
                "inert)")
        if not sv.get("degraded_answered", 0) > 0 \
                or not sv.get("device_loss_recovered"):
            problems.append(
                "serve injected device loss was not answered host-side "
                "and recovered")
        cz = sv.get("chaos") or {}
        if cz.get("dropped", -1) != 0:
            problems.append(
                f"serve chaos dropped {cz.get('dropped')} queries")
        if not cz.get("swaps_ok", 0) > 0:
            problems.append("serve chaos applied no epoch swaps")
        if cz.get("traffic") != "workload":
            problems.append(
                f"serve chaos traffic was {cz.get('traffic')!r} "
                "(wanted 'workload' — clients must draw from the "
                "Zipf/diurnal generator, not uniform threads)")
        if not cz.get("degraded_reads_served", 0) > 0:
            problems.append(
                "serve chaos served no degraded reads under "
                "workload-driven traffic")
        # SLO burn-rate acceptance gate (schema v9): the injected
        # dispatch stalls must RAISE the burn, the post-fault clean
        # windows must CLEAR it, and none of it may drop a query
        slo = sv.get("slo") or {}
        if not slo.get("burns_raised", 0) >= 1:
            problems.append(
                "serve chaos raised no SLO burn despite injected "
                "dispatch stalls (burn-rate engine inert)")
        elif not slo.get("burns_cleared", 0) >= 1:
            problems.append(
                "serve chaos SLO burn never cleared after the stalls "
                "exhausted (clear path inert)")
        if not sv.get("timeline_samples", 0) > 0:
            problems.append("serve timeline recorded no samples")
        # background-balancing acceptance gates: live device-loop
        # rounds planned + applied while clients query, 0 compiles in
        # the measured window (query path AND warm rounds), nothing
        # dropped, and the chaos phase carried live rounds too
        bgr = sv.get("background") or {}
        if not bgr.get("applied", 0) >= 2:
            problems.append(
                f"serve background balancing applied "
                f"{bgr.get('applied')} round(s) (wanted >=2)")
        if sv.get("background_query_compiles", -1) != 0:
            problems.append(
                f"serve background-balancing window booked "
                f"{sv.get('background_query_compiles')} compile(s) — "
                "planning/applying is leaking compiles into the live "
                "window")
        if bgr.get("dropped", -1) != 0:
            problems.append(
                f"serve background-balancing window dropped "
                f"{bgr.get('dropped')} queries")
        if not (sv.get("background_round_p99_ms") or 0) > 0:
            problems.append(
                "serve recorded no background round p99")
        if not ((cz.get("background") or {}).get("applied", 0)) >= 1:
            problems.append(
                "serve chaos applied no background balancing round "
                "between churn epochs")
        # bulk-edge acceptance gates (schema v13): the bulk protocol
        # edge must beat the scalar submit edge >=10x with zero shed
        # lanes and a compile-free window, and the forced structural
        # swap mid-window must flip stall-free (prewarmed variants)
        bk = sv.get("bulk") or {}
        if not (bk.get("ratio") or 0) >= 10:
            problems.append(
                f"serve bulk qps {bk.get('qps')} is not >=10x the "
                f"scalar submit edge {bk.get('scalar_qps')} "
                f"(ratio {bk.get('ratio')})")
        if bk.get("not_ok", -1) != 0:
            problems.append(
                f"serve bulk window answered {bk.get('not_ok')} "
                "non-ok lane(s) (wanted every lane ok)")
        if bk.get("compiles", -1) != 0:
            problems.append(
                f"serve bulk window booked {bk.get('compiles')} "
                "compile(s) — the forced structural swap is leaking "
                "traces into the measured window")
        if not (bk.get("swap_ok") and bk.get("preseed_swap_ok")):
            problems.append("serve bulk forced structural swap failed")
        if sv.get("structural_swap_stalls", -1) != 0:
            problems.append(
                f"serve bulk window booked "
                f"{sv.get('structural_swap_stalls')} structural swap "
                "stall(s) over the flip bound (wanted 0)")
        # mesh bit-identity gate: 2 forced host devices shard the
        # serving buffer and the placement digest must not move
        mh = sv.get("mesh") or {}
        if mh.get("devices") != 2 or not mh.get("oracle_match_ndev"):
            problems.append(
                f"serve mesh subprocess answered on "
                f"{mh.get('devices')} device(s) "
                f"(oracle_match={mh.get('oracle_match_ndev')}, "
                f"error={mh.get('error')})")
        elif not (mh.get("digest_match")
                  and mh.get("oracle_match_1dev")):
            problems.append(
                "serve mesh placement digest diverged across forced "
                "device counts (sharded buffer is not bit-identical)")
        # front gates: the injected one-replica stall must shed that
        # replica, every lane still answers, and the p99 is recorded
        fr = sv.get("front") or {}
        if not fr.get("sheds", 0) >= 1:
            problems.append(
                "serve front never shed the stalled replica "
                f"(sheds={fr.get('sheds')})")
        if fr.get("dropped", -1) != 0:
            problems.append(
                f"serve front answered {fr.get('dropped')} non-ok "
                "lane(s) under a one-replica stall (wanted 0 — the "
                "stall is absorbed, not surfaced)")
        if not (fr.get("p99_ms") or 0) > 0:
            problems.append("serve front recorded no block p99")
        # device-loop rebalance gates: the whole plan in O(1) XLA
        # dispatches (one per calc_pg_upmaps call), nothing reverted
        # at readback, and the plan bytes deterministic across a
        # fresh identical re-run
        rb = out.get("rebalance") or {}
        rb_rounds = rb.get("rounds") or []
        if rb.get("backend") != "device_loop":
            problems.append(
                f"rebalance ran backend={rb.get('backend')!r} "
                "(wanted device_loop)")
        if not rb_rounds or any(
                r.get("plan_dispatches") != 1 for r in rb_rounds):
            problems.append(
                "rebalance plans were not O(1) dispatches: "
                f"{[r.get('plan_dispatches') for r in rb_rounds]} "
                "(wanted 1 per plan)")
        if any(r.get("readback_reverts") for r in rb_rounds):
            problems.append(
                "rebalance device-accepted moves were rolled back at "
                "readback: "
                f"{[r.get('readback_reverts') for r in rb_rounds]}")
        if not rb.get("digest_stable"):
            problems.append(
                "rebalance plan digest not stable across a fresh "
                "identical re-run")
        # candidate-batched optimizer gate: the balancer stage must
        # record the dispatches-per-change pair, and batching may never
        # cost MORE scoring dispatches per accepted change than the
        # sequential path (the >=5x headline proof lives in the
        # MULTICHIP record, where the cluster is big enough to batch)
        blc = out.get("balancer") or {}
        if blc.get("dispatches_per_change") is None \
                or blc.get("seq_dispatches_per_change") is None:
            problems.append(
                "balancer stage missing the dispatches_per_change / "
                "seq_dispatches_per_change pair (candidate-batched "
                "optimizer not recorded)")
        elif ((blc.get("upmap_batched") or {}).get("changes", 0) > 0
                and blc["dispatches_per_change"]
                > blc["seq_dispatches_per_change"]):
            problems.append(
                "candidate-batched optimizer booked MORE dispatches "
                f"per change ({blc['dispatches_per_change']}) than the "
                f"sequential path ({blc['seq_dispatches_per_change']})")
    lint = _selftest_graftlint(problems)
    execs = _selftest_executables(out, problems)
    bdiff = _selftest_benchdiff(problems)
    verdict = {
        "selftest": "ok" if not problems else "FAIL",
        "elapsed_s": round(time.time() - t0, 1),
        "stages_done": out.get("stages_done"),
        "backend": out.get("backend"),
        "fallback_reason": out.get("fallback_reason"),
        "attempts": out.get("attempts"),
        "graftlint": lint,
        "executables": execs,
        "quantiles": out.get("quantiles"),
        "diagnostics": {
            k: v for k, v in (out.get("diagnostics") or {}).items()
            if k in ("pgs", "bad_mappings", "retry_exhausted",
                     "collisions", "diag_exact", "default_path_compiles",
                     "mapping_identical")
        } or None,
        "lifetime": {
            k: v for k, v in (out.get("lifetime") or {}).items()
            if k in ("epochs", "invariant_violations", "steady_compiles",
                     "steady_full_rebuilds", "balancer_builds",
                     "balancer_state_reuses", "state",
                     "device_loss_fallbacks", "resume_digest_match",
                     "epochs_per_sec", "cluster_years_per_hour",
                     "degraded_epochs", "recovery", "workload",
                     "pareto", "health", "health_pure",
                     "resume_timeline_samples", "chaos", "durability",
                     "overwhelmed", "ref_digest_match")
        } or None,
        "serve": {
            k: v for k, v in (out.get("serve") or {}).items()
            if k in ("qps", "request_p50_s", "request_p99_s", "swaps",
                     "swap_stall_p99_s", "swap_stalls", "dropped",
                     "steady_compiles", "swap_delta_applies",
                     "swap_full_restages", "swap_state_rebuilds",
                     "swap_prepare_avg_s", "burst_shed",
                     "degraded_answered", "device_loss_recovered",
                     "chaos", "slo", "health", "timeline_samples",
                     "background", "background_round_p99_ms",
                     "background_query_compiles", "bulk", "mesh",
                     "front", "structural_swap_stalls")
        } or None,
        "fleet": {
            k: v for k, v in (out.get("fleet") or {}).items()
            if k in ("clusters", "fleet_epochs", "cluster_epochs",
                     "cluster_epochs_per_sec", "serial_epochs_per_sec",
                     "speedup_x", "digest_matches", "steady_compiles",
                     "structural_epochs", "steady_epochs",
                     "pareto_front_size", "pareto_dominated",
                     "pg_lost_total", "invariant_violations")
        } or None,
        "rebalance": {
            k: v for k, v in (out.get("rebalance") or {}).items()
            if k in ("backend", "total_changed", "plan_dispatches",
                     "dispatches_per_change", "plan_digest",
                     "digest_stable", "converged")
        } or None,
        "balancer": {
            k: v for k, v in (out.get("balancer") or {}).items()
            if k in ("dispatches_per_change",
                     "seq_dispatches_per_change",
                     "dispatch_reduction_x")
        } or None,
        "benchdiff": bdiff,
    }
    if problems:
        verdict["problems"] = problems
    print(json.dumps(verdict))
    (_HERE / env["BENCH_PARTIAL"]).unlink(missing_ok=True)
    return 0 if not problems else 1


if __name__ == "__main__":
    if "--multichip" in sys.argv:
        if os.environ.get("BENCH_MC_WORKER"):
            _mc_worker(int(os.environ["BENCH_MC_WORKER"]))
            raise SystemExit(0)
        raise SystemExit(multichip_supervise(
            [int(x) for x in MC_DEVICES.split(",") if x.strip()]))
    if "--selftest" in sys.argv:
        raise SystemExit(selftest())
    if os.environ.get("BENCH_WORKER"):
        worker()
    else:
        diff_pattern = None
        for i, arg in enumerate(sys.argv):
            if arg == "--diff-against":
                if (i + 1 >= len(sys.argv)
                        or sys.argv[i + 1].startswith("-")):
                    # refuse to swallow a following flag as the glob —
                    # the run would silently proceed with wrong semantics
                    _log("--diff-against needs a path/glob argument")
                    raise SystemExit(2)
                diff_pattern = sys.argv[i + 1]
            elif arg.startswith("--diff-against="):
                diff_pattern = arg.split("=", 1)[1]
                if not diff_pattern:
                    _log("--diff-against needs a path/glob argument")
                    raise SystemExit(2)
        supervise(resume="--resume" in sys.argv
                  or bool(os.environ.get("BENCH_RESUME")),
                  diff_pattern=diff_pattern)
