"""Headline benchmark: batched CRUSH PG→OSD mapping throughput.

Measures the full 5-stage placement pipeline (ceph_tpu.osd.pipeline_jax) on
the default jax device (the real TPU chip when present), vs the single-core
C reference kernel (`crush_do_rule` in a tight loop — the hot loop of
`crushtool --test`, reference src/crush/CrushTester.cc:612-623) compiled
from the read-only reference mount.

Prints ONE JSON line:
    {"metric": "pg_mappings_per_sec", "value": N, "unit": "mappings/s",
     "vs_baseline": N/<single-core C mappings/s>}

Env knobs: BENCH_PGS (default 1_000_000), BENCH_OSDS (default 1024),
BENCH_BASELINE_PGS (default 200_000).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent / "tests"))

N_PGS = int(os.environ.get("BENCH_PGS", 1_000_000))
N_OSDS = int(os.environ.get("BENCH_OSDS", 1024))
BASELINE_PGS = int(os.environ.get("BENCH_BASELINE_PGS", 200_000))
OSD_PER_HOST = 8


def build_map():
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.types import PgPool, PoolType

    n_host = max(1, N_OSDS // OSD_PER_HOST)
    pool = PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=N_PGS, pgp_num=N_PGS,
    )
    return build_hierarchical(
        n_host, OSD_PER_HOST, n_rack=max(1, n_host // 16), pool=pool
    )


def bench_tpu(m) -> float:
    """Mappings/sec of the jitted batched pipeline (steady-state)."""
    from ceph_tpu.utils import ensure_jax_backend

    ensure_jax_backend()
    import jax
    import jax.numpy as jnp

    from ceph_tpu.osd.pipeline_jax import PoolMapper

    pm = PoolMapper(m, 0, overlays=False)
    fn = jax.jit(jax.vmap(pm.fn, in_axes=(0, None, 0)))
    ps = jax.device_put(jnp.arange(N_PGS, dtype=jnp.uint32))
    dev = jax.device_put(pm.dev)
    jax.block_until_ready(fn(ps, dev, {}))  # compile + warm
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(ps, dev, {})
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    return N_PGS / dt


def bench_c_reference(m) -> float | None:
    """Single-core C crush_do_rule loop; mappings/sec, None if unavailable."""
    try:
        from util_maps import to_oracle
    except Exception:
        return None
    try:
        om = to_oracle(m.crush)
    except (AssertionError, ImportError, OSError):
        return None
    weights = list(m.osd_weight)
    n = min(BASELINE_PGS, N_PGS)
    # warm once, then measure
    om.bench_rule(0, 0, min(n, 1000), 1, weights, 3)
    ns, _ = om.bench_rule(0, 0, n, 1, weights, 3)
    if ns <= 0:
        return None
    return n / (ns * 1e-9)


def main():
    m = build_map()
    tpu_rate = bench_tpu(m)
    c_rate = bench_c_reference(m)
    vs = tpu_rate / c_rate if c_rate else 0.0
    print(json.dumps({
        "metric": "pg_mappings_per_sec",
        "value": round(tpu_rate, 1),
        "unit": "mappings/s",
        "vs_baseline": round(vs, 2),
    }))


if __name__ == "__main__":
    main()
