"""Headline benchmarks: CRUSH mapping throughput + EC throughput.

Measures, on the default jax device (the real TPU chip when present):

1. PG->OSD mapping rate of the batched 5-stage placement pipeline
   (ceph_tpu.osd.pipeline_jax) on the BASELINE.md configs:
     - config 1: 1k PGs / 32 OSDs   (crushtool --test shape)
     - config 2: 100k PGs / 1k OSDs (osdmaptool --test-map-pgs shape)
     - headline: BENCH_PGS (default 1M) PGs / BENCH_OSDS (default 1024)
   vs the single-core C reference kernel (crush_do_rule in a tight loop —
   the hot loop of crushtool --test, reference src/crush/CrushTester.cc:
   612-623) compiled from the read-only reference mount.

2. EC throughput (BASELINE.md configs 3-4): RS(k=8,m=4) encode/decode GB/s
   on the device engine (ec.jax_backend) and the native SIMD engine
   (reference tool: src/test/erasure-code/ceph_erasure_code_benchmark.cc:
   156-317), plus Clay(8,4,d=11) single-chunk repair bandwidth.

Prints ONE JSON line; the headline metric stays pg_mappings_per_sec and
`backend`/`device` record what actually ran (a CPU fallback is explicit,
never silent).  Env knobs: BENCH_PGS, BENCH_OSDS, BENCH_BASELINE_PGS,
BENCH_EC_MB, BENCH_REQUIRE_TPU (nonzero = hard-fail if the configured
accelerator cannot initialize), BENCH_SKIP_EC, BENCH_CHUNK.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent / "tests"))

N_PGS = int(os.environ.get("BENCH_PGS", 1_000_000))
N_OSDS = int(os.environ.get("BENCH_OSDS", 1024))
BASELINE_PGS = int(os.environ.get("BENCH_BASELINE_PGS", 200_000))
EC_MB = int(os.environ.get("BENCH_EC_MB", 16))
OSD_PER_HOST = 8
REPS = 3


def init_backend() -> tuple[str, str]:
    """Initialize jax; return (backend, device_str).  Loud, never silent:
    a configured-but-unavailable accelerator prints a diagnostic to stderr
    and (with BENCH_REQUIRE_TPU) aborts instead of quietly benching CPU."""
    import jax

    configured = os.environ.get("JAX_PLATFORMS", "")
    try:
        devs = jax.devices()
        return jax.default_backend(), str(devs[0])
    except RuntimeError as e:
        msg = (
            f"bench: configured jax platform {configured!r} failed to "
            f"initialize: {e}"
        )
        print(msg, file=sys.stderr)
        if os.environ.get("BENCH_REQUIRE_TPU", "0") not in ("", "0"):
            print("bench: BENCH_REQUIRE_TPU set -> aborting", file=sys.stderr)
            raise SystemExit(2)
        print("bench: falling back to CPU (recorded in output)",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        return "cpu", str(devs[0])


def build_map(n_pgs: int, n_osds: int):
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.types import PgPool, PoolType

    n_host = max(1, n_osds // OSD_PER_HOST)
    pool = PgPool(
        type=PoolType.REPLICATED, size=3, crush_rule=0,
        pg_num=n_pgs, pgp_num=n_pgs,
    )
    return build_hierarchical(
        n_host, OSD_PER_HOST, n_rack=max(1, n_host // 16), pool=pool
    )


def bench_mapping(m, n_pgs: int) -> dict:
    """Device mapping rate for one map (jitted fast pipeline + rescue)."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.osd.pipeline_jax import PoolMapper

    pm = PoolMapper(m, 0, overlays=False)
    fn = jax.jit(jax.vmap(pm._fast, in_axes=(0, None, 0)))
    ps = jax.device_put(jnp.arange(n_pgs, dtype=jnp.uint32))
    dev = jax.device_put(pm.dev)
    t0 = time.perf_counter()
    out = fn(ps, dev, {})
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    unresolved = int(np.asarray(out[-1]).sum())
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(ps, dev, {})
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / REPS
    return {
        "mappings_per_sec": round(n_pgs / dt, 1),
        "wall_s": round(dt, 4),
        "compile_s": round(compile_s, 1),
        "unresolved": unresolved,
        "pgs": n_pgs,
    }


def bench_c_reference(m, n: int) -> float | None:
    """Single-core C crush_do_rule loop; mappings/sec, None if unavailable."""
    try:
        from util_maps import to_oracle

        om = to_oracle(m.crush)
    except Exception:
        return None
    weights = list(m.osd_weight)
    om.bench_rule(0, 0, min(n, 1000), 1, weights, 3)  # warm
    ns, _ = om.bench_rule(0, 0, n, 1, weights, 3)
    if ns <= 0:
        return None
    return n / (ns * 1e-9)


def _time_engine(fn, reps=REPS) -> float:
    fn()  # warm/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def bench_ec() -> dict:
    """RS(8,4) encode/decode + Clay(8,4,11) repair, GB/s of data processed
    (reference prints seconds/KiB: ceph_erasure_code_benchmark.cc:176-184).
    """
    from ceph_tpu.ec.registry import create_erasure_code

    out: dict = {}
    k, mm = 8, 4
    L = EC_MB * (1 << 20) // k  # bytes per chunk
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    total = k * L

    for name, profile in (
        ("jax", {"plugin": "jax", "k": str(k), "m": str(mm)}),
        ("native", {"plugin": "isa", "k": str(k), "m": str(mm),
                    "backend": "native"}),
    ):
        try:
            code = create_erasure_code(dict(profile))
        except Exception as e:
            out[f"{name}_error"] = str(e)[:120]
            continue
        enc_s = _time_engine(lambda: code.encode_chunks(data))
        out[f"rs84_encode_gbps_{name}"] = round(total / enc_s / 1e9, 3)
        encoded = code.encode_chunks(data)
        chunks = {i: encoded[i] for i in range(k + mm) if i not in (0, 5)}
        dec_s = _time_engine(
            lambda: code.decode_chunks({0, 5}, dict(chunks), L)
        )
        out[f"rs84_decode2_gbps_{name}"] = round(total / dec_s / 1e9, 3)

    # Clay(8,4,d=11) single-lost-chunk repair: bandwidth advantage is the
    # point (reads (d+1)/(m+1) of the stripe; ErasureCodeClay.cc:325)
    try:
        clay = create_erasure_code(
            {"plugin": "clay", "k": str(k), "m": str(mm), "d": "11"}
        )
        sub = clay.get_sub_chunk_count()
        Lc = max(4096, (1 << 20) // sub * sub)  # aligned chunk
        cdata = rng.integers(0, 256, size=(k, Lc), dtype=np.uint8)
        enc = clay.encode_chunks(cdata)
        want = {2}
        need = clay.minimum_to_decode(want, set(range(k + mm)) - want)
        avail = {i: enc[i] for i in need}
        rep_s = _time_engine(lambda: clay.decode_chunks(set(want),
                                                        dict(avail), Lc))
        out["clay84_repair_gbps"] = round(k * Lc / rep_s / 1e9, 3)
    except Exception as e:
        out["clay_error"] = str(e)[:120]
    return out


def main():
    backend, device = init_backend()

    headline = build_map(N_PGS, N_OSDS)
    configs = {}

    # config 1: crushtool --test shape (1k PGs / 32 OSDs)
    m1 = build_map(1000, 32)
    configs["crushtool_1k_32"] = bench_mapping(m1, 1000)
    c1 = bench_c_reference(m1, 100_000)
    if c1:
        configs["crushtool_1k_32"]["c_baseline_mps"] = round(c1, 1)
        configs["crushtool_1k_32"]["vs_c"] = round(
            configs["crushtool_1k_32"]["mappings_per_sec"] / c1, 3
        )

    # config 2: osdmaptool --test-map-pgs shape (100k PGs / 1k OSDs)
    m2 = build_map(100_000, 1024)
    configs["testmappgs_100k_1k"] = bench_mapping(m2, 100_000)
    c2 = bench_c_reference(m2, min(BASELINE_PGS, 100_000))
    if c2:
        configs["testmappgs_100k_1k"]["c_baseline_mps"] = round(c2, 1)
        configs["testmappgs_100k_1k"]["vs_c"] = round(
            configs["testmappgs_100k_1k"]["mappings_per_sec"] / c2, 3
        )

    # headline: big batch
    configs["headline"] = bench_mapping(headline, N_PGS)
    c_rate = bench_c_reference(headline, BASELINE_PGS)
    tpu_rate = configs["headline"]["mappings_per_sec"]
    vs = tpu_rate / c_rate if c_rate else 0.0

    ec = {} if os.environ.get("BENCH_SKIP_EC") else bench_ec()

    print(json.dumps({
        "metric": "pg_mappings_per_sec",
        "value": tpu_rate,
        "unit": "mappings/s",
        "vs_baseline": round(vs, 2),
        "backend": backend,
        "device": device,
        "c_baseline_mps": round(c_rate, 1) if c_rate else None,
        "configs": configs,
        "ec": ec,
    }))


if __name__ == "__main__":
    main()
