"""Lint: no host syncs inside the dispatch spans.

`pipeline.map_block`, `pipeline.rescue` and the EC engine's
`ec.gf_dispatch` spans time DISPATCH — the enqueue of already-compiled
work onto the device.  A `np.asarray(...)`, `.item()` or `float(...)`
on a traced value inside one of those bodies blocks on the device and
silently turns the span into a transfer measurement (the exact bug
that made r05's per-block numbers fetch-bound, and that made the EC
engine's old dispatch span time the d2h fetch of every host-facing
matmul); the fetch belongs in `pipeline.fetch` / `ec.gf_fetch` (or
between the spans, as the unresolved-flag read in
PoolMapper._map_block_inner does).

This lint walks the AST of every hot-path module plus bench.py and
flags, inside any `with obs.span("pipeline.map_block"...)` /
`obs.span("pipeline.rescue"...)` / `obs.span("ec.gf_dispatch"...)`
body:

    np.asarray(...) / np.array(...) / numpy.asarray(...)
    <expr>.item()
    float(...)

The check is syntactic — it cannot prove an operand is traced — so
host-only work belongs *outside* the span (hoist it; every current call
site needs nothing inside but dispatches and device-side scatters).

Runnable standalone (exit 1 on violations) and from tests:

    python tools/check_no_host_sync.py
    from check_no_host_sync import find_violations
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

SPAN_NAMES = ("pipeline.map_block", "pipeline.rescue", "ec.gf_dispatch")

SCAN = (
    "ceph_tpu",
    "bench.py",
    "__graft_entry__.py",
)


def _span_name(item: ast.withitem) -> str | None:
    """The span name if this with-item is obs.span("...")/span("...")."""
    c = item.context_expr
    if not isinstance(c, ast.Call) or not c.args:
        return None
    f = c.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    if name != "span":
        return None
    a0 = c.args[0]
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
        return a0.value
    return None


def _sync_call(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "item" and not node.args:
            return ".item()"
        if (
            f.attr in ("asarray", "array")
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy")
        ):
            return f"{f.value.id}.{f.attr}()"
    elif isinstance(f, ast.Name) and f.id == "float":
        return "float()"
    return None


def check_file(path: Path) -> list[str]:
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: unparseable: {e.msg}"]
    rel = path.relative_to(REPO) if path.is_relative_to(REPO) else path
    out: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        spans = [
            s for s in (_span_name(i) for i in node.items)
            if s in SPAN_NAMES
        ]
        if not spans:
            continue
        for sub in node.body:
            for call in ast.walk(sub):
                if isinstance(call, ast.Call):
                    what = _sync_call(call)
                    if what:
                        out.append(
                            f"{rel}:{call.lineno}: {what} inside a "
                            f"{spans[0]} span (host sync; fetch belongs "
                            "in pipeline.fetch)"
                        )
    return out


def find_violations(root: Path = REPO) -> list[str]:
    out: list[str] = []
    for entry in SCAN:
        p = root / entry
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for py in files:
            if py.exists():
                out.extend(check_file(py))
    return out


def main() -> int:
    violations = find_violations()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"check_no_host_sync: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_no_host_sync: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
