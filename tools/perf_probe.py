"""On-chip performance probe for the CRUSH mapping kernel.

Answers, with measured numbers (committed to PROFILE_r04.md):

1. block-count scaling — same compiled fn, same 65536-PG chunk, k blocks
   dispatched per rep for k in 1..16: is per-block wall time flat?
   Variants isolate dispatch/transfer effects:
     a. hold    — dispatch all k, block at the end, keep outputs on device
                  (what bench.py r03 did)
     b. fetch   — np.asarray each block's outputs immediately (device->host
                  transfer per block, nothing accumulates on device)
     c. serial  — block_until_ready after each dispatch (no queueing)
     d. repeat1 — dispatch the SAME block k times (input reuse; tests
                  whether distinct input buffers matter)
2. straw2 ablations — the headline kernel recompiled with the inner straw2
   draw altered (results become wrong; timing only):
     a. baseline    — s64 table-gather + s64 divide (the real kernel)
     b. nodiv       — divide replaced by multiply
     c. nogather    — 64k-entry s64 table gather replaced by arithmetic
                      crush_ln (jnp path, small tables)
     d. noint64     — draw computed in int32 (truncated)
   The deltas bound how much of the per-PG cost each suspect owns.
3. jax.profiler trace — attempted around one rep; written to
   tools/profile_trace/ when the backend supports it.

Usage: python tools/perf_probe.py [--pgs N] [--osds N] [--quick]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from ceph_tpu import obs  # noqa: E402  (needs the repo-root sys.path)


def log(msg):
    print(f"probe[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def build_map(n_pgs, n_osds):
    from ceph_tpu.osd.osdmap import build_hierarchical
    from ceph_tpu.osd.types import PgPool, PoolType

    n_host = max(1, n_osds // 8)
    pool = PgPool(type=PoolType.REPLICATED, size=3, crush_rule=0,
                  pg_num=n_pgs, pgp_num=n_pgs)
    return build_hierarchical(n_host, 8, n_rack=max(1, n_host // 16),
                              pool=pool)


def make_fn(m):
    import jax

    from ceph_tpu.osd.pipeline_jax import PoolMapper

    pm = PoolMapper(m, 0, overlays=False)
    # deliberately NOT pm.jitted_fast(): the ablation sweep monkeypatches
    # kernel internals without changing the structural cache_key, so the
    # shared _PIPE_CACHE must never see these compiles.  They register
    # under their own "probe" cache instead (ablation variants share the
    # structural key, so their timings aggregate on one record).
    fn = obs.executables.wrap(
        jax.jit(jax.vmap(pm._fast, in_axes=(0, None, 0))),
        "probe", "fast", pm._fast.cache_key,
    )
    dev = jax.device_put(pm.dev)
    return pm, fn, dev


def probe_scaling(m, B=65536, ks=(1, 2, 4, 8, 16), reps=2):
    import jax
    import jax.numpy as jnp

    pm, fn, dev = make_fn(m)
    n_pgs = pm.spec.pg_num
    blocks = [
        jax.device_put(jnp.asarray(
            (np.arange(i * B, (i + 1) * B) % n_pgs).astype(np.uint32)))
        for i in range(max(ks))
    ]
    out = fn(blocks[0], dev, {})
    jax.block_until_ready(out)

    res = {}
    for k in ks:
        row = {}
        # a. hold: r03 bench pattern
        t0 = time.perf_counter()
        for _ in range(reps):
            outs = [fn(b, dev, {}) for b in blocks[:k]]
            jax.block_until_ready(outs)
        row["hold_s_per_block"] = (time.perf_counter() - t0) / reps / k
        del outs
        # b. fetch each block to host immediately
        t0 = time.perf_counter()
        for _ in range(reps):
            for b in blocks[:k]:
                o = fn(b, dev, {})
                _ = [np.asarray(x) for x in o]
        row["fetch_s_per_block"] = (time.perf_counter() - t0) / reps / k
        # c. serial: block after each dispatch, keep on device
        t0 = time.perf_counter()
        for _ in range(reps):
            for b in blocks[:k]:
                jax.block_until_ready(fn(b, dev, {}))
        row["serial_s_per_block"] = (time.perf_counter() - t0) / reps / k
        # d. same block k times
        t0 = time.perf_counter()
        for _ in range(reps):
            outs = [fn(blocks[0], dev, {}) for _ in range(k)]
            jax.block_until_ready(outs)
        row["repeat1_s_per_block"] = (time.perf_counter() - t0) / reps / k
        del outs
        res[k] = {kk: round(v, 4) for kk, v in row.items()}
        log(f"scaling k={k}: {res[k]}")
    return res


def probe_ablations(m, B=65536, reps=3):
    """Recompile the pipeline with the straw2 inner ops ablated."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ceph_tpu.core.lntable import crush_ln_jax, ln64k_table
    from ceph_tpu.crush import mapper_jax

    S64_MIN = mapper_jax.S64_MIN
    _h3 = mapper_jax._h3
    orig = mapper_jax._straw2_choose

    def straw2_variant(divide, gather, sixtyfour):
        def f(d, slot, x, r, position):
            A = d.A
            pos = jnp.clip(position, 0, A.positions - 1)
            w = d.pos_weights[pos, slot].astype(jnp.int64)
            ids = d.arg_ids[slot]
            lane = jnp.arange(A.max_size)
            mask = lane < d.size[slot]
            u = (_h3(x, ids, r) & 0xFFFF).astype(jnp.uint32)
            if gather:
                ln = jnp.asarray(ln64k_table())[u] - jnp.int64(0x1000000000000)
            else:
                ln = crush_ln_jax(u).astype(jnp.int64) - jnp.int64(
                    0x1000000000000)
            if not sixtyfour:
                ln32 = (ln >> 20).astype(jnp.int32)
                w32 = jnp.maximum(w, 1).astype(jnp.int32)
                draw = (lax.div(ln32, w32) if divide else ln32 * w32)
                draw = jnp.where((w > 0) & mask, draw, -(2 ** 31))
                return d.items[slot, jnp.argmax(draw)]
            draw = (lax.div(ln, jnp.maximum(w, 1)) if divide
                    else ln * jnp.maximum(w, 1))
            draw = jnp.where((w > 0) & mask, draw, S64_MIN)
            return d.items[slot, jnp.argmax(draw)]
        return f

    variants = {
        "baseline": straw2_variant(True, True, True),
        "nodiv": straw2_variant(False, True, True),
        "nogather": straw2_variant(True, False, True),
        "nodiv_nogather": straw2_variant(False, False, True),
        "noint64": straw2_variant(True, True, False),
        "noint64_nodiv": straw2_variant(False, True, False),
    }
    xs = np.arange(B, dtype=np.uint32)
    out = {}
    for name, v in variants.items():
        mapper_jax._straw2_choose = v
        try:
            pm, fn, dev = make_fn(m)
            import jax
            xj = jax.device_put(jnp.asarray(xs))
            t0 = time.perf_counter()
            o = fn(xj, dev, {})
            jax.block_until_ready(o)
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(reps):
                jax.block_until_ready(fn(xj, dev, {}))
            dt = (time.perf_counter() - t0) / reps
            out[name] = {"s_per_block": round(dt, 4),
                         "maps_per_sec": round(B / dt, 1),
                         "compile_s": round(compile_s, 1)}
            log(f"ablation {name}: {out[name]}")
        finally:
            mapper_jax._straw2_choose = orig
    return out


def probe_trace(m, B=65536):
    import jax
    import jax.numpy as jnp

    pm, fn, dev = make_fn(m)
    xs = jax.device_put(jnp.asarray(np.arange(B, dtype=np.uint32)))
    jax.block_until_ready(fn(xs, dev, {}))
    tdir = Path(__file__).resolve().parent / "profile_trace"
    try:
        with jax.profiler.trace(str(tdir)):
            jax.block_until_ready(fn(xs, dev, {}))
        files = [str(p.relative_to(tdir)) for p in tdir.rglob("*") if
                 p.is_file()]
        return {"ok": True, "dir": str(tdir), "files": files[:20]}
    except Exception as e:
        return {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pgs", type=int, default=1_048_576)
    ap.add_argument("--osds", type=int, default=1024)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--skip", default="", help="comma list: scaling,"
                    "ablations,trace")
    args = ap.parse_args()
    skip = set(args.skip.split(","))

    import jax
    log(f"devices: {jax.devices()}")
    from ceph_tpu import runtime
    runtime.prewarm_compile_cache()

    m = build_map(args.pgs, args.osds)
    res = {"pgs": args.pgs, "osds": args.osds,
           "device": str(jax.devices()[0])}
    ks = (1, 4, 16) if args.quick else (1, 2, 4, 8, 16)
    if "scaling" not in skip:
        with obs.span("probe.scaling"):
            res["scaling"] = probe_scaling(m, ks=ks)
    if "ablations" not in skip:
        with obs.span("probe.ablations"):
            res["ablations"] = probe_ablations(m)
    if "trace" not in skip:
        with obs.span("probe.trace"):
            res["trace"] = probe_trace(m)
    # the probe drives PoolMapper kernels, so the pipeline perf group has
    # been advancing; ship it (and the span trace, if CEPH_TPU_TRACE is
    # set) with the numbers.  The executables section is the SAME code
    # path bench.py's output uses (obs.executables.dump) — probe runs and
    # bench runs dump one schema, no drift.
    res["perf"] = obs.perf_dump()
    res["executables"] = obs.executables.dump(analyze=True)
    tp = obs.flush()
    if tp:
        res["span_trace"] = tp
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
