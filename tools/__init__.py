"""Repo tooling package (`python -m tools.graftlint`, corpus/probe
scripts).  The lint scripts double as standalone files — see the shims
`check_no_print.py` / `check_no_host_sync.py` — so nothing in here may
import jax or the ceph_tpu runtime."""
