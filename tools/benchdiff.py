"""benchdiff — the BENCH_r*.json trajectory differ.

Five bench rounds and a partial sat on disk with nothing reading them:
the perf trajectory of the repo was unobservable, and regressions were
caught by humans eyeballing PROFILE_*.md.  This tool loads a series of
bench outputs, normalizes cross-container numbers, computes per-stage
deltas, and flags regressions beyond configurable thresholds — as a
library (bench.py `--diff-against`, the selftest fixture gate), and as a
CLI emitting markdown and JSON reports:

    python -m tools.benchdiff                      # repo BENCH series
    python -m tools.benchdiff a.json b.json --json - --threshold 0.2

Input tolerance (the real series is messy, by design of the exercise):

- wrapper records ({"cmd", "rc", "parsed", ...}) — the retrieval shape
  the committed BENCH_r*.json rounds use; an empty/failed `parsed`
  (r02 really is one) becomes a *gap*, reported but never fatal;
- final bench JSON (has "metric"/"configs");
- BENCH_partial.json checkpoint shape (has "stages_done") — stage
  records are folded into the final-JSON shape, perf snapshot kept.

Cross-container normalization: absolute numbers from different
containers are incomparable (r05's jax EC ran 0.153 GB/s on a fast host;
the r07 container runs r05's exact code path at 0.078).  Every round
since PR 6 therefore carries `ec.r05_strategy_gbps` — a same-machine
measurement of one frozen code path.  Hardware-sensitive metrics are
divided by `cal(round)/cal(reference)` before comparison; rounds without
the calibration (r01–r05) still diff, but their hardware-sensitive
deltas are recorded as informational (`uncalibrated`) and never flagged
— a slower container is not a regression.  Structural metrics (jit
compiles, pipe-cache hits, trace_once_ok) compare raw everywhere.

`schema_version`: bench.py stamps the records it writes (current: 4);
this reader accepts <= SCHEMA_VERSION and marks newer rounds with a
note instead of guessing at fields it does not know.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

# the BENCH record shape version bench.py writes and this reader speaks.
# v1: everything before the stamp existed (r01..r07-era records).
# v2: adds schema_version, executables, quantiles, benchdiff sections.
# v3: adds the placement `diagnostics` section (bad mappings, retry
#     histogram, default-path non-perturbation proof) and recognizes
#     MULTICHIP_r*.json trajectory wrappers as their own series.
# v4: adds the `lifetime` section (chaos-scenario structural metrics:
#     invariant violations, steady/jit compiles per epoch, degraded
#     epochs, resume-digest proof; epochs/s and cluster-years/hour as
#     hardware-sensitive rates).
# v5: adds the `serve` section (placement serving daemon: QPS and
#     request p50/p99 calibration-normalized; dropped / steady-shed /
#     swap-stall / steady-compile counts and the degraded-recovery
#     proof bit structural).
# v6: adds the ClusterState O(delta) metrics: lifetime
#     steady_full_rebuilds / balancer_builds and the per-run `state`
#     counters (delta_applies, full_rebuilds, device_put_bytes) under
#     `lifetime.state`, plus serve swap_delta_applies /
#     swap_full_restages / swap_state_rebuilds — all seeded-scenario
#     structural counts, compared raw (an epoch apply or value swap
#     that stops being O(delta) is semantic drift, never hardware
#     variance).  lifetime.epochs_per_sec (already v4) is where the
#     refactor's uplift lands, calibration-normalized as before.
# v7: adds the recovery data plane + client workload sections
#     (`lifetime.recovery.*` / `lifetime.workload.*` / the pareto
#     headline): conservation violations, degraded-reads-served,
#     at-risk hits and backlog counts are seeded-deterministic —
#     compared raw; served QPS and the observed backlog-drain rate
#     are calibration-normalized hardware rates.
# v8: mesh-sharded placement + candidate-batched optimizer.  MULTICHIP
#     wrappers may carry a `scaling` record (`bench.py --multichip`):
#     devices, eps/device, maps/s/device, steady compiles and the
#     sharded-vs-single-device digest-match bit fold as
#     `multichip.scaling.*` — all structural (the scenario is seeded
#     and a digest mismatch or steady compile is semantic drift).  The
#     BENCH `balancer` stage grows `dispatches_per_change` (the
#     candidate-batched optimizer's scoring dispatches per accepted
#     change; lower is better, calibration-normalized alongside the
#     stage's wall times) and `seq_dispatches_per_change` for the
#     same-run sequential baseline.
# v9: cluster health model + timeline flight recorder + serve SLO
#     burn-rate engine (obs/health.py, obs/timeline.py, serve/slo.py).
#     The lifetime stage grows `health` (summarized status rank, per-
#     epoch ok/warn/err counts, sim-timeline sample count) and the
#     `health_pure` proof bit (observers on == observers off, digest
#     and compile-count identical); the serve stage grows `slo`
#     (burns_raised/burns_cleared/breaches — the chaos phase must
#     record a full raise->clear cycle — plus burn_minutes), a
#     summarized `health` status and the serve-timeline sample count.
#     Everything raw except burn_minutes (wall-clock) — the scenarios
#     are seeded, so a check that stops firing is semantic drift.
# v10: correlated-failure chaos engine (sim/lifetime.py correlated
#     model).  The lifetime stage grows `chaos` (cascades, repeat
#     flaps, false-flap revives — seeded counts whose collapse to 0
#     means the correlation model went inert), `durability` (pg_lost
#     and exposed PG-epochs: the default scenario is sized SURVIVABLE,
#     so pg_lost moving 0 -> N is the structural zero-baseline
#     regression this schema exists to flag), the `overwhelmed`
#     mini-run record (pg_lost > 0 and the DATA_LOSS latch prove the
#     loss path can fire) and the `ref_digest_match` backend-exactness
#     bit.  All raw: every one is bit-determined by the seeded
#     scenario.
# v11: fully device-resident upmap optimizer (balancer/upmap.py
#     backend="device_loop": the whole multi-round greedy in ONE
#     lax.while_loop dispatch per plan).  The rebalance stage grows
#     `plan_dispatches` (kernel dispatches across the run — O(1) per
#     plan; a jump means the loop fell apart into per-round dispatches)
#     and `dispatches_per_change` (plan dispatches per accepted change)
#     — both bit-determined by the seeded run, compared raw.  The serve
#     stage grows `background_round_p99_ms` (the live background-
#     balancing round tail, wall-clock so calibration-normalized) and
#     `background_query_compiles` (compiles booked in the measured
#     background window — 0 when healthy; 0 -> N rides the structural
#     zero-baseline rule).
# v12: fleet simulator (ceph_tpu/fleet/): N independent clusters ride
#     ONE vmapped accounting dispatch per epoch batch.  The bench grows
#     a `fleet` stage: `cluster_epochs_per_sec` (the aggregate
#     throughput headline — a hardware rate, calibration-normalized),
#     `digest_matches` (members whose stacked digest is bit-identical
#     to the solo oracle — dropping below the cluster count is the
#     exactness regression), `steady_compiles` (0 when the stacked
#     dispatch structure holds; 0 -> N rides the structural
#     zero-baseline rule) and `pareto_front_size` (the non-dominated
#     front must stay non-empty) — all but the rate bit-determined by
#     the seeded member scenarios, compared raw.
# v13: serve bulk protocol edge + mesh-sharded query blocks + the
#     multi-replica front (serve/service.py query_block/submit_many,
#     serve/meshcheck.py, serve/front.py).  The serve stage grows
#     `bulk` (`serve.bulk_qps` — the bulk-edge lookup rate, the 1M/s
#     headline, hardware-normalized; `serve.bulk_ratio` vs the scalar
#     submit edge; `serve.bulk_compiles` booked inside the measured
#     bulk window — 0 when both warmed shapes hold), `mesh`
#     (`serve.mesh_devices` and the 1-vs-N `serve.mesh_digest_match`
#     bit — bit-determined by the forced topology, raw),
#     `structural_swap_stalls` (flips whose reader stall broke the
#     bound across a FORCED structural swap — 0 when pre-traced
#     variants + the warming thread hold; 0 -> N rides the structural
#     zero-baseline rule) and `front` (`serve.front_p99_ms` — the
#     client tail through the replica front under an injected
#     one-replica stall, normalized; `serve.front_sheds` — the
#     slowest-replica absorb firing under that seeded stall, raw).
SCHEMA_VERSION = 13

_ROUND_RE = re.compile(r"r(\d+)")

# default regression threshold: relative change in the bad direction
DEFAULT_THRESHOLD = 0.10


class Round:
    """One loaded bench round, normalized to the final-JSON shape."""

    def __init__(self, name: str, record: dict, path: str | None = None,
                 partial: bool = False):
        self.name = name
        self.path = path
        self.record = record or {}
        self.partial = partial
        self.empty = not self.record
        self.schema_version = int(self.record.get("schema_version", 1))
        self.notes: list[str] = []
        if self.schema_version > SCHEMA_VERSION:
            self.notes.append(
                f"written by a newer bench (schema_version="
                f"{self.schema_version} > supported {SCHEMA_VERSION}); "
                "unknown fields ignored"
            )

    @property
    def calibration(self) -> float | None:
        """The same-machine r05-strategy GB/s this round measured."""
        cal = (self.record.get("ec") or {}).get("r05_strategy_gbps")
        try:
            cal = float(cal)
        except (TypeError, ValueError):
            return None
        return cal if cal > 0 else None


def _from_partial(raw: dict) -> dict:
    """Fold a BENCH_partial.json checkpoint into the final-JSON shape."""
    rec: dict = {"partial": True}
    configs = {}
    for key in ("crushtool_1k_32", "testmappgs_100k_1k", "headline"):
        st = raw.get(key)
        if isinstance(st, dict):
            configs[key] = {k: v for k, v in st.items() if k != "perf"}
    if configs:
        rec["configs"] = configs
    ec: dict = {}
    for key in ("ec_jax", "ec_native", "ec_clay"):
        st = raw.get(key)
        if isinstance(st, dict):
            ec.update({k: v for k, v in st.items() if k != "perf"})
    if ec:
        rec["ec"] = ec
    for key in ("balancer", "rebalance", "lifetime", "serve", "fleet",
                "executables", "quantiles", "schema_version"):
        if key in raw:
            rec[key] = raw[key]
    init = raw.get("init") or {}
    if init:
        rec["backend"] = init.get("backend")
    if "perf" in raw:
        rec["perf"] = raw["perf"]
    head = configs.get("headline") or {}
    if "mappings_per_sec" in head:
        rec["value"] = head["mappings_per_sec"]
    return rec


_MC_TAIL_RE = re.compile(
    r"(\d+) devices, (\d+) PGs, stddev=([\d.]+)")


def _from_multichip(raw: dict) -> dict:
    """Normalize a MULTICHIP_r*.json wrapper ({n_devices, rc, ok,
    skipped, tail}) into {"multichip": {...}} — its own trajectory,
    diffed separately from the BENCH series (a multichip dry-run and a
    bench run share no metrics).  All structural: device counts, the
    sharded==unsharded verdict, and the rebalance stddev the dry-run
    prints are deterministic, never hardware-scaled.  v8 records
    (`bench.py --multichip`) additionally carry a `scaling` record
    (devices / eps per device / maps per device / steady compiles /
    the digest-match bit) and the candidate-batched optimizer's
    dispatch ratio — folded under the same trajectory."""
    mc: dict = {}
    nd = raw.get("n_devices")
    if isinstance(nd, (int, float)) and not isinstance(nd, bool):
        mc["n_devices"] = nd
    if isinstance(raw.get("ok"), bool):
        mc["ok"] = raw["ok"]
    m = _MC_TAIL_RE.search(raw.get("tail") or "")
    if m:
        mc["pgs"] = int(m.group(2))
        mc["stddev"] = float(m.group(3))
    sc = raw.get("scaling")
    if isinstance(sc, dict):
        mc["scaling"] = {
            k: sc.get(k)
            for k in ("devices", "eps_per_device",
                      "maps_per_sec_per_device", "steady_compiles",
                      "digest_match")
            if sc.get(k) is not None
        }
    bal = raw.get("balancer")
    if isinstance(bal, dict) \
            and bal.get("dispatch_reduction_x") is not None:
        mc["dispatch_reduction_x"] = bal["dispatch_reduction_x"]
    return {"multichip": mc} if mc else {}


def load_round(path: str | Path) -> Round:
    p = Path(path)
    m = _ROUND_RE.search(p.stem)
    name = f"r{int(m.group(1)):02d}" if m else p.stem
    try:
        raw = json.loads(p.read_text())
    except (OSError, ValueError) as e:
        r = Round(name, {}, str(p))
        r.notes.append(f"unreadable: {type(e).__name__}: {e}"[:120])
        return r
    if not isinstance(raw, dict):
        r = Round(name, {}, str(p))
        r.notes.append("not a JSON object")
        return r
    if "n_devices" in raw and "tail" in raw:  # MULTICHIP wrapper
        name = f"mc-{name}"
        if raw.get("skipped"):
            r = Round(name, {}, str(p))
            r.notes.append("multichip round skipped")
            return r
        r = Round(name, _from_multichip(raw), str(p))
        if r.empty:
            r.notes.append(
                f"multichip round unparseable (rc={raw.get('rc')})")
        return r
    if "parsed" in raw:  # retrieval wrapper
        rec = raw.get("parsed") or {}
        r = Round(name, rec if isinstance(rec, dict) else {}, str(p))
        if r.empty:
            r.notes.append(
                f"round produced no parseable output (rc={raw.get('rc')})"
            )
        return r
    if "stages_done" in raw:  # checkpoint shape
        return Round(name, _from_partial(raw), str(p), partial=True)
    return Round(name, raw, str(p))


def load_series(paths) -> list[Round]:
    """Load rounds, ordered by round number (non-numbered files keep
    their given position after the numbered ones)."""
    rounds = [load_round(p) for p in paths]

    def key(item):
        i, r = item
        m = _ROUND_RE.search(r.name)
        return (0, int(m.group(1)), i) if m else (1, 0, i)

    return [r for _, r in sorted(enumerate(rounds), key=lambda t: key(t))]


def default_series_paths(root: str | Path = ".") -> list[Path]:
    root = Path(root)
    out = sorted(root.glob("BENCH_r*.json"))
    partial = root / "BENCH_partial.json"
    if partial.exists():
        out.append(partial)
    # the MULTICHIP trajectory rides along; diff_series partitions it
    # into its own series (different files, different metrics)
    out.extend(sorted(root.glob("MULTICHIP_r*.json")))
    return out


# -- metric extraction ------------------------------------------------------
# (name, value, higher_is_better, hardware_sensitive) per round

def _num(v):
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def extract_metrics(rec: dict) -> dict[str, tuple[float, bool, bool]]:
    out: dict[str, tuple[float, bool, bool]] = {}

    def put(name, v, up, cal):
        v = _num(v)
        if v is not None:
            out[name] = (float(v), up, cal)

    for cname, cfg in (rec.get("configs") or {}).items():
        if not isinstance(cfg, dict):
            continue
        put(f"configs.{cname}.mappings_per_sec",
            cfg.get("mappings_per_sec"), True, True)
        put(f"configs.{cname}.cold_s", cfg.get("cold_s"), False, True)
        jit = cfg.get("jit") or {}
        put(f"configs.{cname}.jit.compiles", jit.get("compiles"),
            False, False)
        put(f"configs.{cname}.jit.pipe_cache_hits",
            jit.get("pipe_cache_hits"), True, False)
    ec = rec.get("ec") or {}
    for k, v in ec.items():
        # rs84_encode_gbps_jax, clay84_repair_gbps, batch rates, ... —
        # everything measured in GB/s except the calibration itself
        if "_gbps" in k and k != "r05_strategy_gbps":
            put(f"ec.{k}", v, True, True)
    if isinstance(ec.get("trace_once_ok"), bool):
        # booleans ride as 0/1 structural metrics: True->False flags
        out["ec.trace_once_ok"] = (float(ec["trace_once_ok"]), True, False)
    bal = rec.get("balancer") or {}
    for mode in ("upmap", "crush_compat"):
        mrec = bal.get(mode) or {}
        put(f"balancer.{mode}.wall_s", mrec.get("wall_s"), False, True)
        put(f"balancer.{mode}.eval_pgs_per_sec",
            mrec.get("eval_pgs_per_sec"), True, True)
        put(f"balancer.{mode}.jit.compiles",
            (mrec.get("jit") or {}).get("compiles"), False, False)
    # candidate-batched optimizer (v8): scoring dispatches per accepted
    # change — the batched path's headline ratio vs its same-run
    # sequential baseline (both lower-is-better)
    put("balancer.dispatches_per_change",
        bal.get("dispatches_per_change"), False, True)
    put("balancer.seq_dispatches_per_change",
        bal.get("seq_dispatches_per_change"), False, True)
    rb = rec.get("rebalance") or rec.get("rebalance_10m_10k") or {}
    put("rebalance.build_s", rb.get("build_s"), False, True)
    rounds = rb.get("rounds") or []
    if rounds and isinstance(rounds[0], dict):
        put("rebalance.round0_wall_s", rounds[0].get("wall_s"),
            False, True)
    # v11: the device-loop dispatch story is seeded and bit-determined
    # — plan_dispatches inflating means the one-dispatch plan fell
    # apart into per-round (or per-change) kernel launches
    put("rebalance.plan_dispatches", rb.get("plan_dispatches"),
        False, False)
    put("rebalance.dispatches_per_change",
        rb.get("dispatches_per_change"), False, False)
    for span, q in (rec.get("quantiles") or {}).items():
        if isinstance(q, dict):
            put(f"quantiles.{span}.p50", q.get("p50"), False, True)
            put(f"quantiles.{span}.p99", q.get("p99"), False, True)
    bs = ((rec.get("perf") or {}).get("balancer") or {}).get(
        "build_state_seconds")
    if isinstance(bs, dict):
        put("perf.balancer.build_state_avgtime", bs.get("avgtime"),
            False, True)
    # placement diagnostics (v3): decision tallies over the bench map
    # are bit-determined by map + tunables, so they compare raw
    # everywhere — a moving bad_mappings/collisions count is semantic
    # drift in the mapping stack, not hardware variance
    dg = rec.get("diagnostics") or {}
    put("diagnostics.bad_mappings", dg.get("bad_mappings"), False, False)
    put("diagnostics.retry_exhausted", dg.get("retry_exhausted"),
        False, False)
    put("diagnostics.collisions", dg.get("collisions"), False, False)
    put("diagnostics.default_path_compiles",
        dg.get("default_path_compiles"), False, False)
    for bkey, bval in (("diag_exact", dg.get("diag_exact")),
                       ("mapping_identical", dg.get("mapping_identical"))):
        if isinstance(bval, bool):
            out[f"diagnostics.{bkey}"] = (float(bval), True, False)
    hist = dg.get("tries_histogram")
    if isinstance(hist, list) and hist:
        put("diagnostics.tries_max",
            max((i for i, v in enumerate(hist) if v), default=0),
            False, False)
    # lifetime chaos scenario (v4): the torture-test trajectory.  The
    # scenario is seeded, so its event/accounting tallies are
    # bit-determined — invariant violations, compiles-per-epoch,
    # degraded-epoch counts and the resume proof compare raw (semantic
    # drift, never hardware variance); only the rates are
    # hardware-sensitive.
    lf = rec.get("lifetime") or {}
    put("lifetime.invariant_violations",
        lf.get("invariant_violations"), False, False)
    put("lifetime.steady_compiles", lf.get("steady_compiles"),
        False, False)
    put("lifetime.jit_compiles_per_epoch",
        lf.get("jit_compiles_per_epoch"), False, False)
    put("lifetime.degraded_epochs", lf.get("degraded_epochs"),
        False, False)
    put("lifetime.epochs", lf.get("epochs"), True, False)
    put("lifetime.at_risk_pg_seconds", lf.get("at_risk_pg_seconds"),
        False, False)
    if isinstance(lf.get("resume_digest_match"), bool):
        out["lifetime.resume_digest_match"] = (
            float(lf["resume_digest_match"]), True, False)
    put("lifetime.epochs_per_sec", lf.get("epochs_per_sec"),
        True, True)
    put("lifetime.cluster_years_per_hour",
        lf.get("cluster_years_per_hour"), True, True)
    # ClusterState O(delta) contract (v6): seeded counts, raw compare
    put("lifetime.steady_full_rebuilds",
        lf.get("steady_full_rebuilds"), False, False)
    put("lifetime.balancer_builds", lf.get("balancer_builds"),
        False, False)
    lst = lf.get("state") or {}
    put("lifetime.state.delta_applies", lst.get("delta_applies"),
        True, False)
    put("lifetime.state.full_rebuilds", lst.get("full_rebuilds"),
        False, False)
    # recovery data plane + client workload (v7): the scenario is
    # seeded, so every byte/hit tally is bit-determined — conservation
    # violations, degraded reads, at-risk/backlog hits compare raw
    # (semantic drift); served QPS (the pareto service level) and the
    # observed wall-clock drain rate are hardware rates.
    rcv = lf.get("recovery") or {}
    put("lifetime.recovery.conservation_violations",
        rcv.get("conservation_violations"), False, False)
    put("lifetime.recovery.backlog_peak_gb",
        rcv.get("backlog_peak_gb"), False, False)
    put("lifetime.recovery.completed_pgs", rcv.get("completed_pgs"),
        True, False)
    put("lifetime.recovery.fallback_epochs",
        rcv.get("fallback_epochs"), False, False)
    put("lifetime.recovery.drain_gbps", rcv.get("drain_gbps"),
        True, True)
    # cluster health model (v9): the chaos scenario is seeded, so the
    # summarized status and the warn/err epoch split are bit-determined
    # — raw compares; the pure-observer proof bit pins that enabling
    # the observers changed no digest byte and compiled nothing.
    rank = {"HEALTH_OK": 0.0, "HEALTH_WARN": 1.0, "HEALTH_ERR": 2.0}
    hl = lf.get("health") or {}
    if hl.get("status") in rank:
        out["lifetime.health.rank"] = (rank[hl["status"]], False, False)
    hep = hl.get("epochs") or {}
    put("lifetime.health.warn_epochs", hep.get("warn"), False, False)
    put("lifetime.health.err_epochs", hep.get("err"), False, False)
    put("lifetime.health.timeline_samples",
        hl.get("timeline_samples"), True, False)
    if isinstance(lf.get("health_pure"), bool):
        out["lifetime.health_pure"] = (
            float(lf["health_pure"]), True, False)
    # correlated-failure chaos + durability ledger (v10): every count
    # is bit-determined by the seeded scenario.  pg_lost is the
    # headline — the default scenario is sized survivable, so a 0 -> N
    # move rides the structural zero-baseline rule and flags
    # unconditionally; cascades/revives collapsing to 0 means the
    # correlation model went inert (higher-is-better wiring).
    cha = lf.get("chaos") or {}
    put("lifetime.chaos.cascades", cha.get("cascades"), True, False)
    put("lifetime.chaos.repeat_flaps", cha.get("repeat_flaps"),
        True, False)
    put("lifetime.chaos.false_flap_revives",
        cha.get("false_flap_revives"), True, False)
    dur = lf.get("durability") or {}
    put("lifetime.durability.pg_lost", dur.get("pg_lost"),
        False, False)
    put("lifetime.durability.exposed_pg_epochs",
        dur.get("exposed_pg_epochs"), False, False)
    ovw = lf.get("overwhelmed") or {}
    put("lifetime.overwhelmed.pg_lost", ovw.get("pg_lost"),
        True, False)  # the loss path must KEEP firing here
    if isinstance(ovw.get("data_loss_latched"), bool):
        out["lifetime.overwhelmed.data_loss_latched"] = (
            float(ovw["data_loss_latched"]), True, False)
    if isinstance(lf.get("ref_digest_match"), bool):
        out["lifetime.ref_digest_match"] = (
            float(lf["ref_digest_match"]), True, False)
    wl = lf.get("workload") or {}
    put("lifetime.workload.served_qps", wl.get("served_qps"),
        True, True)
    put("lifetime.workload.degraded_reads", wl.get("degraded_reads"),
        False, False)
    put("lifetime.workload.at_risk_hits", wl.get("at_risk_hits"),
        False, False)
    put("lifetime.workload.unserved", wl.get("unserved"),
        False, False)
    put("lifetime.workload.contended_osd_epochs",
        wl.get("contended_osd_epochs"), False, False)
    # serving daemon (v5): the client-visible story.  Load and swap
    # cadence are seeded, so the never-dropped / shed / stall /
    # steady-compile counts and the recovery proof bit are semantic
    # drift when they move — compared raw; QPS and the request tail are
    # hardware rates — calibration-normalized.
    sv = rec.get("serve") or {}
    put("serve.qps", sv.get("qps"), True, True)
    put("serve.request_p50_s", sv.get("request_p50_s"), False, True)
    put("serve.request_p99_s", sv.get("request_p99_s"), False, True)
    put("serve.dropped", sv.get("dropped"), False, False)
    put("serve.steady_shed", sv.get("steady_shed"), False, False)
    put("serve.swap_stalls", sv.get("swap_stalls"), False, False)
    put("serve.steady_compiles", sv.get("steady_compiles"),
        False, False)
    put("serve.swaps", sv.get("swaps"), True, False)
    # v6: value-only swaps must stage via ClusterState delta forks
    put("serve.swap_delta_applies", sv.get("swap_delta_applies"),
        True, False)
    put("serve.swap_full_restages", sv.get("swap_full_restages"),
        False, False)
    put("serve.swap_state_rebuilds", sv.get("swap_state_rebuilds"),
        False, False)
    if isinstance(sv.get("device_loss_recovered"), bool):
        out["serve.device_loss_recovered"] = (
            float(sv["device_loss_recovered"]), True, False)
    cz = sv.get("chaos") or {}
    put("serve.chaos.dropped", cz.get("dropped"), False, False)
    put("serve.chaos.p99_s", cz.get("p99_s"), False, True)
    # serve SLO burn-rate engine (v9): load and fault cadence are
    # seeded, so burn transitions are semantic facts — the chaos phase
    # must keep recording its raise->clear cycle (burns_cleared
    # dropping to 0 is the regression the fixture pair seeds); only
    # burn_minutes is wall-clock.
    slo = sv.get("slo") or {}
    put("serve.slo.burns_raised", slo.get("burns_raised"), False, False)
    put("serve.slo.burns_cleared", slo.get("burns_cleared"),
        True, False)
    put("serve.slo.breaches", slo.get("breaches"), False, False)
    put("serve.slo.samples", slo.get("samples"), True, False)
    put("serve.slo.burn_minutes", slo.get("burn_minutes"), False, True)
    if sv.get("health") in rank:
        out["serve.health.rank"] = (rank[sv["health"]], False, False)
    put("serve.timeline_samples", sv.get("timeline_samples"),
        True, False)
    # v11: live background balancing — the measured round tail is
    # wall-clock (normalized); the window's compile count is
    # structural (0 when healthy, 0 -> N is the zero-baseline case)
    put("serve.background_round_p99_ms",
        sv.get("background_round_p99_ms"), False, True)
    put("serve.background_query_compiles",
        sv.get("background_query_compiles"), False, False)
    # bulk edge + mesh + front (v13): the bulk rate and the front tail
    # are hardware numbers — normalized; everything else is
    # bit-determined by the forced topology and the seeded stall —
    # raw (a stall appearing, the digest bit dropping, or a compile
    # inside the bulk window is semantic drift, never jitter)
    bk = sv.get("bulk") or {}
    put("serve.bulk_qps", bk.get("qps"), True, True)
    put("serve.bulk_ratio", bk.get("ratio"), True, False)
    put("serve.bulk_compiles", bk.get("compiles"), False, False)
    put("serve.structural_swap_stalls",
        sv.get("structural_swap_stalls"), False, False)
    mh = sv.get("mesh") or {}
    put("serve.mesh_devices", mh.get("devices"), True, False)
    if isinstance(mh.get("digest_match"), bool):
        out["serve.mesh_digest_match"] = (
            float(mh["digest_match"]), True, False)
    fr = sv.get("front") or {}
    put("serve.front_p99_ms", fr.get("p99_ms"), False, True)
    put("serve.front_sheds", fr.get("sheds"), True, False)
    # fleet simulator (v12): the member scenarios are seeded, so the
    # digest-match count, steady compiles and the pareto front are
    # bit-determined — raw compares (digest_matches dropping below the
    # cluster count, a steady compile appearing, or the front going
    # empty is semantic drift in the stacked path); only the aggregate
    # cluster-epochs rate is a hardware number.
    flt = rec.get("fleet") or {}
    put("fleet.cluster_epochs_per_sec",
        flt.get("cluster_epochs_per_sec"), True, True)
    put("fleet.digest_matches", flt.get("digest_matches"), True, False)
    put("fleet.steady_compiles", flt.get("steady_compiles"),
        False, False)
    put("fleet.pareto_front_size", flt.get("pareto_front_size"),
        True, False)
    # multichip trajectory (normalized MULTICHIP_r*.json wrappers)
    mc = rec.get("multichip") or {}
    put("multichip.n_devices", mc.get("n_devices"), True, False)
    put("multichip.pgs", mc.get("pgs"), True, False)
    put("multichip.stddev", mc.get("stddev"), False, False)
    if isinstance(mc.get("ok"), bool):
        out["multichip.ok"] = (float(mc["ok"]), True, False)
    # mesh-scaling record (v8): all structural — the scenario is
    # seeded, so eps/device movement at equal devices, a steady-epoch
    # compile, or a sharded-vs-single-device digest mismatch is
    # semantic drift, never hardware variance
    msc = mc.get("scaling") or {}
    put("multichip.scaling.devices", msc.get("devices"), True, False)
    put("multichip.scaling.eps_per_device",
        msc.get("eps_per_device"), True, False)
    put("multichip.scaling.maps_per_sec_per_device",
        msc.get("maps_per_sec_per_device"), True, False)
    put("multichip.scaling.steady_compiles",
        msc.get("steady_compiles"), False, False)
    if isinstance(msc.get("digest_match"), bool):
        out["multichip.scaling.digest_match"] = (
            float(msc["digest_match"]), True, False)
    put("multichip.dispatch_reduction_x",
        mc.get("dispatch_reduction_x"), True, False)
    return out


# -- diffing ----------------------------------------------------------------

def _series_metrics(usable: list[Round],
                    ref_cal: float | None) -> tuple[list, list]:
    """(metrics, per_round) rows for one series of non-empty rounds."""
    per_round = []
    metrics: list[dict] = []  # parallel to usable
    for r in usable:
        cal = r.calibration
        factor = (cal / ref_cal) if (cal and ref_cal) else None
        metrics.append({
            "round": r.name, "factor": factor,
            "values": extract_metrics(r.record),
        })
        per_round.append({
            "round": r.name,
            "path": r.path,
            "partial": r.partial,
            "schema_version": r.schema_version,
            "backend": r.record.get("backend"),
            "value": _num(r.record.get("value")),
            "calibration_gbps": cal,
            "notes": r.notes,
        })
    return metrics, per_round


def _series_deltas(metrics: list[dict],
                   threshold: float) -> tuple[list, list, list, list]:
    """(deltas, regressions, improvements, missing) between consecutive
    rounds of one metrics series."""
    deltas, regressions, improvements, missing = [], [], [], []
    for prev, cur in zip(metrics, metrics[1:]):
        # a metric that disappears between rounds is surfaced, not
        # silently skipped — a refactor that stops emitting the jit /
        # trace_once_ok sections would otherwise remove exactly the
        # structural guards this tool enforces.  Informational, not a
        # verdict: real rounds legitimately gain/lose whole stages
        # (r01 predates EC, deadline-killed partials lose stages).
        for name in sorted(set(prev["values"]) - set(cur["values"])):
            missing.append({
                "metric": name, "from": prev["round"], "to": cur["round"],
            })
        for name, (v1, up, cal_sensitive) in cur["values"].items():
            if name not in prev["values"]:
                continue
            v0 = prev["values"][name][0]
            normalized = False
            n0, n1 = v0, v1
            if cal_sensitive:
                if prev["factor"] and cur["factor"]:
                    # project onto the reference machine: throughput
                    # scales WITH machine speed (divide by the factor),
                    # time scales AGAINST it (multiply) — dividing a
                    # wall-clock by the factor would amplify the
                    # hardware difference instead of removing it
                    if up:
                        n0, n1 = v0 / prev["factor"], v1 / cur["factor"]
                    else:
                        n0, n1 = v0 * prev["factor"], v1 * cur["factor"]
                    normalized = True
            change = (n1 - n0) / abs(n0) if n0 else (
                0.0 if n1 == n0 else float("inf"))
            d = {
                "metric": name,
                "from": prev["round"], "to": cur["round"],
                "prev": v0, "cur": v1,
                "change": round(change, 4) if change != float("inf")
                else None,
                "higher_is_better": up,
                "normalized": normalized,
            }
            if cal_sensitive and not normalized:
                d["uncalibrated"] = True
            deltas.append(d)
            bad = (change < -threshold) if up else (change > threshold)
            good = (change > threshold) if up else (change < -threshold)
            if n0 == 0 and n1 > 0:
                # zero baseline: the relative change is undefined (inf),
                # so the threshold cannot arbitrate.  A STRUCTURAL
                # counter appearing from zero is meaningful either way
                # (compiles 0 -> N breaks trace-once; cache hits 0 -> N
                # is the win).  A measured hardware quantity is not:
                # bench rounds timings (build_s to one decimal), so
                # 0.0 -> 0.1 is rounding noise — informational only.
                if cal_sensitive:
                    bad = good = False
                elif not up:
                    bad, good = True, False
                else:
                    bad, good = False, True
            if cal_sensitive and not normalized:
                continue  # cross-container raw delta: informational
            if bad:
                regressions.append(d)
            elif good:
                improvements.append(d)
    return deltas, regressions, improvements, missing


def diff_series(rounds: list[Round],
                threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Per-metric deltas between consecutive non-empty rounds, with
    regressions/improvements beyond `threshold`.  MULTICHIP rounds
    partition into their own series (multichip.* metrics, reported
    under `multichip_rounds`) but merge into the same regression lists
    and verdict.  Returns the JSON report (see render_markdown for the
    human shape)."""

    def is_mc(r: Round) -> bool:
        return r.name.startswith("mc-") or "multichip" in r.record

    main = [r for r in rounds if not is_mc(r)]
    mc_rounds = [r for r in rounds if is_mc(r)]
    usable = [r for r in main if not r.empty]
    gaps = [
        {"round": r.name, "notes": r.notes}
        for r in rounds if r.empty
    ]
    # reference calibration: the latest calibrated round — "would the
    # series regress if every round had run on the newest container"
    ref_cal = None
    for r in reversed(usable):
        if r.calibration:
            ref_cal = r.calibration
            break
    metrics, per_round = _series_metrics(usable, ref_cal)
    deltas, regressions, improvements, missing = _series_deltas(
        metrics, threshold)
    mc_per_round: list = []
    if mc_rounds:
        mc_metrics, mc_per_round = _series_metrics(
            [r for r in mc_rounds if not r.empty], None)
        d2, r2, i2, m2 = _series_deltas(mc_metrics, threshold)
        deltas += d2
        regressions += r2
        improvements += i2
        missing += m2
    return {
        "tool": "benchdiff",
        "schema_version": SCHEMA_VERSION,
        "threshold": threshold,
        "rounds": per_round,
        "multichip_rounds": mc_per_round,
        "gaps": gaps,
        "calibration_ref_gbps": ref_cal,
        "deltas": deltas,
        "missing": missing,
        "regressions": regressions,
        "improvements": improvements,
        "verdict": "regression" if regressions else "ok",
    }


def _pct(d: dict) -> str:
    return "new" if d["change"] is None else f"{d['change'] * 100:+.1f}%"


def render_markdown(report: dict) -> str:
    lines = ["# benchdiff", ""]
    lines.append(
        f"verdict: **{report['verdict']}** "
        f"({len(report['regressions'])} regression(s), "
        f"{len(report['improvements'])} improvement(s), "
        f"threshold ±{report['threshold'] * 100:.0f}%, "
        f"calibration ref {report['calibration_ref_gbps']} GB/s)"
    )
    lines.append("")
    lines.append("| round | backend | headline | calibration | notes |")
    lines.append("|-------|---------|----------|-------------|-------|")
    for r in report["rounds"]:
        notes = "; ".join(r["notes"]) + (" (partial)" if r["partial"]
                                         else "")
        lines.append(
            f"| {r['round']} | {r['backend'] or '?'} | "
            f"{r['value'] if r['value'] is not None else '-'} | "
            f"{r['calibration_gbps'] or '-'} | {notes.strip('; ')} |"
        )
    for r in report["gaps"]:
        lines.append(
            f"| {r['round']} | - | - | - | GAP: "
            f"{'; '.join(r['notes'])} |"
        )
    mc = report.get("multichip_rounds") or []
    if mc:
        lines.append("")
        lines.append("## Multichip trajectory")
        lines.append("| round | notes |")
        lines.append("|-------|-------|")
        for r in mc:
            lines.append(
                f"| {r['round']} | {'; '.join(r['notes']) or '-'} |"
            )
    for title, rows in (("Regressions", report["regressions"]),
                        ("Improvements", report["improvements"])):
        lines.append("")
        lines.append(f"## {title}")
        if not rows:
            lines.append("none")
            continue
        lines.append("| metric | rounds | prev | cur | change | basis |")
        lines.append("|--------|--------|------|-----|--------|-------|")
        for d in rows:
            basis = "normalized" if d["normalized"] else "raw"
            lines.append(
                f"| {d['metric']} | {d['from']}→{d['to']} | {d['prev']} "
                f"| {d['cur']} | {_pct(d)} | {basis} |"
            )
    uncal = sum(1 for d in report["deltas"] if d.get("uncalibrated"))
    if uncal:
        lines.append("")
        lines.append(
            f"{uncal} hardware-sensitive delta(s) involved uncalibrated "
            "rounds (no `ec.r05_strategy_gbps`) and were recorded as "
            "informational only — cross-container raw numbers never flag."
        )
    gone = report.get("missing") or []
    if gone:
        names = sorted({m["metric"] for m in gone})
        shown = ", ".join(f"`{n}`" for n in names[:5])
        more = f" (+{len(names) - 5} more)" if len(names) > 5 else ""
        lines.append("")
        lines.append(
            f"{len(gone)} metric(s) disappeared between rounds "
            f"({shown}{more}) — check the `missing` list in the JSON "
            "report if a guard metric (jit.compiles, trace_once_ok) is "
            "among them."
        )
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.benchdiff",
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument("paths", nargs="*",
                    help="BENCH json files (default: the repo's "
                    "BENCH_r*.json + BENCH_partial.json)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative regression threshold (default 0.10)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the JSON report to PATH ('-' = stdout)")
    ap.add_argument("--md", metavar="PATH",
                    help="write the markdown report to PATH "
                    "('-' = stdout, the default when --json is unset)")
    args = ap.parse_args(argv)
    paths = args.paths or default_series_paths(
        Path(__file__).resolve().parents[1])
    if not paths:
        print("benchdiff: no BENCH files found", file=sys.stderr)
        return 2
    rounds = load_series(paths)
    if not any(not r.empty for r in rounds):
        print("benchdiff: every round is a gap (no parseable record)",
              file=sys.stderr)
        return 2
    report = diff_series(rounds, threshold=args.threshold)
    md = render_markdown(report)
    wrote = False
    for spec, text in ((args.json, json.dumps(report, indent=1)),
                       (args.md, md)):
        if not spec:
            continue
        wrote = True
        if spec == "-":
            print(text)
        else:
            Path(spec).write_text(text)
    if not wrote:
        print(md, end="")
    return 1 if report["verdict"] == "regression" else 0


if __name__ == "__main__":
    raise SystemExit(main())
