"""health-check: health codes are declared, and every one is tested.

`obs/health.py` declares the compiled-in health check codes
(`HEALTH_CHECKS`).  Two contract directions, same shape as fault-point:

- every production `health.raise_check("<CODE>", ...)` /
  `health.clear("<CODE>")` literal must use a declared code — an
  undeclared code would raise KeyError at the exact moment the cluster
  is unhealthy, which is when the observer must not throw;
- every declared code must be referenced by at least one test
  (raise/clear literals or a bare "<CODE>" string constant in tests/) —
  an untested check is an alert nobody has ever seen fire.

The registry-hosting module itself is exempt from direction (a): it
hosts the standard-evaluation machinery and the docstring examples.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, register,
)

HEALTH_MODULE = "ceph_tpu/obs/health.py"


def _code_sites(module: Module):
    """Yield (code, node) for health.raise_check/clear string literals."""
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        c = module.canonical(node.func)
        if c is None:
            continue
        if c.endswith("health.raise_check") or c.endswith("health.clear") \
                or ("." not in c and c == "raise_check"
                    and module.from_alias.get(c, "").endswith(
                        "health.raise_check")):
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                yield a0.value, node


@register
class HealthCheckPass(Pass):
    name = "health-check"
    doc = "health codes declared in HEALTH_CHECKS; each covered by a test"

    def run(self, ctx: Context) -> None:
        if not ctx.health_checks:
            return
        # (a) production sites use declared codes
        for m in ctx.modules:
            if m.tree is None:
                continue
            if m.rel.endswith("obs/health.py"):
                continue  # hosts the machinery (and doc examples)
            for code, node in _code_sites(m):
                if code not in ctx.health_checks:
                    ctx.violations.append(Violation(
                        m.rel, node.lineno, self.name,
                        f"health check code {code!r} is not declared in "
                        "obs/health.py HEALTH_CHECKS",
                    ))

        # (b) every declared code is exercised by at least one test
        if not ctx.test_modules:
            return
        referenced: set[str] = set()
        for tm in ctx.test_modules:
            if tm.tree is None:
                continue
            for code, _ in _code_sites(tm):
                referenced.add(code)
            for node in ast.walk(tm.tree):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str) and node.value in ctx.health_checks:
                    referenced.add(node.value)
        for code in sorted(ctx.health_checks):
            if code not in referenced:
                ctx.violations.append(Violation(
                    HEALTH_MODULE, ctx.health_lines.get(code, 1), self.name,
                    f"declared health check {code!r} is referenced by no "
                    "test — an alert nobody has ever seen fire",
                ))
