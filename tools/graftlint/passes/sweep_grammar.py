"""sweep-grammar: the fleet sweep-axis registry maps to real Scenario
fields, is documented, and every axis is exercised.

`fleet/spec.py` declares the fleet sweep surface as two pure-literal
registries: `SWEEP_AXES` (keys an `axis=key:v1|v2|...` directive may
sweep — each MUST name a `dataclasses.fields(Scenario)` field) and
`FLEET_KNOBS` (fleet-level member keys that are deliberately NOT
Scenario fields — a knob shadowing a field would make the grammar
ambiguous).  Mirroring the `scenario-event` pass, the directions are:

- every `SWEEP_AXES` key names a real Scenario dataclass field (read
  statically from `sim/lifetime.py`'s AnnAssign list — never imported);
- no `FLEET_KNOBS` key shadows a Scenario field;
- every registered key appears in the README sweep-grammar table as a
  ``| `key` |`` row;
- every `SWEEP_AXES` key is forced by at least one test (an
  `axis=<key>:` substring inside a test string literal) and every
  `FLEET_KNOBS` key by a `<key>=` directive literal — an axis the
  suite never sweeps is grammar no digest has ever pinned;
- the reverse: an `axis=<key>:` literal anywhere (tree or tests) whose
  key is unregistered would raise at runtime — flag it statically.
"""

from __future__ import annotations

import ast
import re

from tools.graftlint.engine import (
    EVENT_REGISTRY, SWEEP_REGISTRY, Context, Module, Pass, Violation,
    register,
)

_AXIS_RE = re.compile(r"axis=([a-z_][a-z0-9_]*):")


def _scenario_fields(ctx: Context) -> set[str]:
    """Scenario dataclass field names, read statically out of
    sim/lifetime.py (same file as the event registry)."""
    path = ctx.root / EVENT_REGISTRY
    if not path.exists():
        return set()
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Scenario":
            return {
                stmt.target.id for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return set()


def _string_literals(modules: list[Module]):
    for m in modules:
        if m.tree is None:
            continue
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Constant) and isinstance(
                    node.value, str):
                yield m, node


@register
class SweepGrammarPass(Pass):
    name = "sweep-grammar"
    doc = "fleet sweep axes are real Scenario fields, in README, tested"

    def run(self, ctx: Context) -> None:
        if not ctx.sweep_axes and not ctx.fleet_knobs:
            return
        fields = _scenario_fields(ctx)
        known = set(ctx.sweep_axes) | set(ctx.fleet_knobs)

        if fields:
            for key in sorted(ctx.sweep_axes):
                if key not in fields:
                    ctx.violations.append(Violation(
                        SWEEP_REGISTRY, ctx.sweep_lines.get(key, 1),
                        self.name,
                        f"sweep axis {key!r} is not a Scenario "
                        "dataclass field — the axis can never pin a "
                        "member spec",
                    ))
            for key in sorted(ctx.fleet_knobs):
                if key in fields:
                    ctx.violations.append(Violation(
                        SWEEP_REGISTRY,
                        ctx.fleet_knob_lines.get(key, 1), self.name,
                        f"fleet knob {key!r} shadows a Scenario field "
                        "— the grammar cannot tell the two apart",
                    ))

        # an axis literal sweeping an unregistered key raises at parse
        # time — catch it statically, in the tree AND the tests
        for m, node in _string_literals(
                list(ctx.modules) + list(ctx.test_modules)):
            for match in _AXIS_RE.finditer(node.value):
                if match.group(1) == "key":
                    continue  # the docs' grammar placeholder
                if match.group(1) not in known:
                    ctx.violations.append(Violation(
                        m.rel, node.lineno, self.name,
                        f"axis literal sweeps unregistered key "
                        f"{match.group(1)!r} (declared: "
                        f"{sorted(known)})",
                    ))

        # registry-side drift (whole-tree facts; skip when linting a
        # fixture subset, where most call sites are out of view)
        if len(ctx.modules) < 10:
            return
        readme = ctx.root / "README.md"
        if readme.exists():
            text = readme.read_text()
            for key in sorted(known):
                if f"| `{key}` |" not in text:
                    line_map = (ctx.sweep_lines
                                if key in ctx.sweep_axes
                                else ctx.fleet_knob_lines)
                    ctx.violations.append(Violation(
                        "README.md", 1, self.name,
                        f"sweep-grammar key {key!r} (fleet/spec.py:"
                        f"{line_map.get(key, 1)}) missing from the "
                        "README sweep-grammar table",
                    ))
        if not ctx.test_modules:
            return
        swept: set[str] = set()
        directive: set[str] = set()
        for _, node in _string_literals(ctx.test_modules):
            for match in _AXIS_RE.finditer(node.value):
                swept.add(match.group(1))
            for key in ctx.fleet_knobs:
                if f"{key}=" in node.value:
                    directive.add(key)
        for key in sorted(ctx.sweep_axes):
            if key not in swept:
                ctx.violations.append(Violation(
                    SWEEP_REGISTRY, ctx.sweep_lines.get(key, 1),
                    self.name,
                    f"sweep axis {key!r} is swept by no test "
                    f"(`axis={key}:...` literal) — grammar no digest "
                    "has ever pinned",
                ))
        for key in sorted(ctx.fleet_knobs):
            if key not in directive:
                ctx.violations.append(Violation(
                    SWEEP_REGISTRY, ctx.fleet_knob_lines.get(key, 1),
                    self.name,
                    f"fleet knob {key!r} is exercised by no test "
                    f"(`{key}=...` directive literal)",
                ))
