"""scenario-event: lifetime event kinds are declared, and every one
is exercised.

`sim/lifetime.py` declares the chaos-event vocabulary (`EVENT_KINDS`)
and draws epochs from `Scenario.event_probs()` — a FIXED-order
(kind, probability) walk whose order is part of the replay-digest
contract.  Two directions, same shape as health-check:

- the kinds `event_probs()` returns must match `EVENT_KINDS` exactly,
  both ways — a kind drawn but undeclared has no documented digest
  line; a kind declared but never drawn is dead vocabulary that the
  docs and the force_event API still advertise;
- every declared kind must appear as a string literal in at least one
  test (a `force_event=` call or a bare "<kind>" constant) — an event
  the suite never forces is a code path no digest has ever pinned.

Both directions are static: the pass reads `event_probs()`'s return
tuple out of the AST, never importing the simulator.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    EVENT_REGISTRY, Context, Module, Pass, Violation, register,
)


def _declared_probs(module: Module):
    """Yield (kind, node) for the first-element string literals of the
    tuples `Scenario.event_probs()` returns."""
    if module.tree is None:
        return
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == "event_probs"):
            continue
        for ret in ast.walk(node):
            if not isinstance(ret, ast.Return) or ret.value is None:
                continue
            for tup in ast.walk(ret.value):
                if (isinstance(tup, ast.Tuple) and tup.elts
                        and isinstance(tup.elts[0], ast.Constant)
                        and isinstance(tup.elts[0].value, str)):
                    yield tup.elts[0].value, tup.elts[0]


@register
class ScenarioEventPass(Pass):
    name = "scenario-event"
    doc = "event_probs() kinds match EVENT_KINDS; each forced by a test"

    def run(self, ctx: Context) -> None:
        if not ctx.event_kinds:
            return
        sim = next((m for m in ctx.modules
                    if m.rel.endswith("sim/lifetime.py")), None)
        drawn: dict[str, int] = {}
        if sim is not None:
            for kind, node in _declared_probs(sim):
                drawn.setdefault(kind, node.lineno)
            for kind, line in sorted(drawn.items()):
                if kind not in ctx.event_kinds:
                    ctx.violations.append(Violation(
                        sim.rel, line, self.name,
                        f"event_probs() draws kind {kind!r} that is not "
                        "declared in EVENT_KINDS",
                    ))
            for kind in sorted(ctx.event_kinds):
                if drawn and kind not in drawn:
                    ctx.violations.append(Violation(
                        EVENT_REGISTRY, ctx.event_lines.get(kind, 1),
                        self.name,
                        f"declared event kind {kind!r} is never drawn by "
                        "event_probs() — dead vocabulary",
                    ))

        # every declared kind appears in at least one test literal
        if not ctx.test_modules:
            return
        referenced: set[str] = set()
        for tm in ctx.test_modules:
            if tm.tree is None:
                continue
            for node in ast.walk(tm.tree):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str) and node.value in ctx.event_kinds:
                    referenced.add(node.value)
        for kind in sorted(ctx.event_kinds):
            if kind not in referenced:
                ctx.violations.append(Violation(
                    EVENT_REGISTRY, ctx.event_lines.get(kind, 1),
                    self.name,
                    f"declared event kind {kind!r} is exercised by no "
                    "test — a chaos path no digest has ever pinned",
                ))
