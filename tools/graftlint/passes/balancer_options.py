"""balancer-options: the mgr's upmap_* option surface is documented
and test-forced.

`ceph_tpu/mgr/module.py` `DEFAULT_OPTIONS` is the single registry of
balancer options; the `upmap_*` family routes straight into
`calc_pg_upmaps` (backend selection, deviation target, change budget,
candidate batch), so a key that drifts out of the docs or out of the
test suite silently strands an optimizer code path.  Three drift
directions are checked:

- a `get_option("upmap_*")` call site whose key is not declared in
  `DEFAULT_OPTIONS` (consuming an option that can never be set);
- a declared `upmap_*` key missing from the README balancer options
  table (the operator surface must stay documented);
- a declared `upmap_*` key that no test module forces as a string
  literal (an option nobody sets in a test is an optimizer branch
  nobody runs until an operator flips it in production).
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, _load_registry, register,
)

MGR_MODULE = "ceph_tpu/mgr/module.py"
PREFIX = "upmap_"


def _option_sites(module: Module):
    """Yield (key, node) for each get_option("<literal>") call."""
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        c = module.canonical(node.func)
        if c is None or not c.endswith("get_option"):
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            yield a0.value, node


def _string_literals(module: Module) -> set[str]:
    return {
        node.value
        for node in ast.walk(module.tree)
        if isinstance(node, ast.Constant) and isinstance(node.value, str)
    }


@register
class BalancerOptionsPass(Pass):
    name = "balancer-options"
    doc = "upmap_* options declared, in the README table, test-forced"

    def run(self, ctx: Context) -> None:
        declared, lines = _load_registry(
            ctx.root / MGR_MODULE, "DEFAULT_OPTIONS", {})
        if not declared:
            return
        # (a) every upmap_* consumption site uses a declared key
        for m in ctx.modules:
            if m.tree is None:
                continue
            for key, node in _option_sites(m):
                if key.startswith(PREFIX) and key not in declared:
                    ctx.violations.append(Violation(
                        m.rel, node.lineno, self.name,
                        f"option {key!r} is not declared in "
                        "mgr/module.py DEFAULT_OPTIONS (it can never "
                        "be set)",
                    ))

        # whole-tree facts; skip when linting a fixture subset, where
        # the README and most call sites are out of view
        if len(ctx.modules) < 10:
            return
        upmap_keys = sorted(k for k in declared if k.startswith(PREFIX))
        # (b) every declared key rides the README options table
        readme = ctx.root / "README.md"
        if readme.exists():
            text = readme.read_text()
            for key in upmap_keys:
                if key not in text:
                    ctx.violations.append(Violation(
                        "README.md", 1, self.name,
                        f"balancer option {key!r} missing from the "
                        "README balancer options table",
                    ))
        # (c) every declared key is forced by at least one test
        if not ctx.test_modules:
            return
        forced: set[str] = set()
        for tm in ctx.test_modules:
            if tm.tree is None:
                continue
            forced |= _string_literals(tm)
        for key in upmap_keys:
            if key not in forced:
                ctx.violations.append(Violation(
                    MGR_MODULE, lines.get(key, 1), self.name,
                    f"balancer option {key!r} is forced by no test — "
                    "its optimizer path is unexercised",
                ))
