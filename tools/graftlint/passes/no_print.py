"""no-print: hot-path modules never print() to stdout.

The reference routes all daemon output through dout/derr and the perf
registry — stdout belongs to the CLI tools' machine-readable output
(crushtool -d, perf dump JSON).  A stray debugging `print()` in the
mapping/EC/balancer hot paths corrupts that contract (and is invisible
in a killed bench run, unlike a counter).  `print(..., file=w)` with any
stream other than sys.stdout is allowed — that is how the tester renders
`--show-mappings` output to a caller-chosen stream.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, register,
)

HOT_PACKAGES = (
    "ceph_tpu/crush",
    "ceph_tpu/osd",
    "ceph_tpu/ec",
    "ceph_tpu/balancer",
    "ceph_tpu/mgr",
)

_MSG = ("print() to stdout (route through ceph_tpu.utils.dout or a "
        "perf counter)")


def _is_stdout_print(node: ast.Call, module: Module) -> bool:
    if not (isinstance(node.func, ast.Name) and node.func.id == "print"):
        return False
    for kw in node.keywords:
        if kw.arg == "file":
            return module.canonical(kw.value) == "sys.stdout"
    return True  # bare print() -> stdout


@register
class NoPrintPass(Pass):
    name = "no-print"
    doc = "hot-path modules never print() to stdout"

    def run(self, ctx: Context) -> None:
        for m in ctx.modules:
            if any(m.rel.startswith(p) for p in HOT_PACKAGES):
                for v in self.check_module(m, ctx):
                    ctx.violations.append(v)

    def check_module(self, module: Module, ctx: Context) -> list[Violation]:
        """One file, scope-free (the shim and fixtures enter here)."""
        if module.tree is None:
            return []
        return module.filter([
            Violation(module.rel, node.lineno, self.name, _MSG)
            for node in ast.walk(module.tree)
            if isinstance(node, ast.Call) and _is_stdout_print(node, module)
        ])
