"""host-sync: no host synchronization inside dispatch spans.

The dispatch spans (`obs.spans.DISPATCH_SPANS`: pipeline.map_block,
pipeline.rescue, ec.gf_dispatch) time the ENQUEUE of already-compiled
device work.  A `np.asarray(...)`, `.item()`, `float(...)`, `int(...)`,
`bool(...)`, `jax.device_get(...)` or `.block_until_ready()` on a traced
value inside one of those bodies blocks on the device and silently turns
the span into a transfer measurement (the exact bug that made r05's
per-block numbers fetch-bound).  Fetches belong in `pipeline.fetch` /
`ec.gf_fetch`, or between the spans.

The check is syntactic — it cannot prove an operand is traced — so
host-only scalar work also belongs *outside* the span (hoist it; every
current call site needs nothing inside but dispatches and device-side
scatters).  The span set comes from the registry, not a hardcoded tuple,
and numpy/jax references are alias-resolved (`import numpy as anything`,
`from numpy import asarray as aa`); every matching with-item is named in
the report, not just the first.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, register,
)

_NUMPY_SYNCS = ("asarray", "array")
_BARE_SYNCS = ("float", "int", "bool")


def span_name(item: ast.withitem, module: Module) -> str | None:
    """The span name if this with-item is obs.span("...")/span("...")."""
    c = item.context_expr
    if not isinstance(c, ast.Call) or not c.args:
        return None
    f = c.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    if name != "span":
        return None
    a0 = c.args[0]
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
        return a0.value
    return None


def sync_call(node: ast.Call, module: Module) -> str | None:
    """Human name of the host sync this call performs, else None."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "item" and not node.args:
            return ".item()"
        if f.attr == "block_until_ready" and not node.args:
            return ".block_until_ready()"
    c = module.canonical(f)
    if c is not None:
        mod, _, attr = c.rpartition(".")
        if mod == "numpy" and attr in _NUMPY_SYNCS:
            return f"numpy.{attr}()"
        if mod == "jax" and attr == "device_get":
            return "jax.device_get()"
    if isinstance(f, ast.Name) and f.id in _BARE_SYNCS:
        # a from-import may shadow the builtin; canonical() already
        # returned the import target above for those
        if f.id not in module.from_alias and f.id not in module.mod_alias:
            return f"{f.id}()"
    return None


@register
class HostSyncPass(Pass):
    name = "host-sync"
    doc = "no host syncs inside dispatch spans (registry-sourced set)"

    def run(self, ctx: Context) -> None:
        for m in ctx.modules:
            ctx.violations.extend(self.check_module(m, ctx))

    def check_module(self, module: Module, ctx: Context) -> list[Violation]:
        if module.tree is None:
            return []
        dispatch = set(ctx.dispatch_spans)
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            spans = [
                s for s in (span_name(i, module) for i in node.items)
                if s in dispatch
            ]
            if not spans:
                continue
            where = " + ".join(spans)
            for sub in node.body:
                for call in ast.walk(sub):
                    if not isinstance(call, ast.Call):
                        continue
                    what = sync_call(call, module)
                    if what:
                        out.append(Violation(
                            module.rel, call.lineno, self.name,
                            f"{what} inside a {where} span (host sync; "
                            "hoist it, or fetch in pipeline.fetch)",
                        ))
        return module.filter(out)
