"""Pass registry: importing this package registers every pass.

Adding a pass: create a module here, subclass `engine.Pass`, decorate
with `@engine.register`, and import the module below.  Give it a
kebab-case `name` (that is the `--select` and `# graftlint:
disable=<name>` token) and a one-line `doc` (shown by `--list`).
"""

from tools.graftlint.passes import (  # noqa: F401
    balancer_options,
    counter_decl,
    env_knob,
    fault_point,
    health_check,
    host_sync,
    no_print,
    scenario_event,
    serve_reply,
    span_name,
    sweep_grammar,
    trace_constant,
)
