"""fault-point: fault points are declared, and every one is tested.

`runtime/faults.py` declares the compiled-in fault points
(`FAULT_POINTS`).  Two contract directions:

- every production `faults.check("<point>", ...)` site and every
  fault-spec string baked into scanned code (bench selftest env, CLI
  defaults) must use a declared base point — an undeclared point can
  never be armed by a documented spec;
- every declared point must be referenced by at least one test
  (`faults.arm/check/configure` literals or CEPH_TPU_FAULTS-style spec
  strings in tests/) — an untested fault point is a retry/degradation
  branch nobody runs until a real device wedges.

Tests may arm ad-hoc points (qualifier-mismatch probes, "anything");
only production call sites are held to the registry.
"""

from __future__ import annotations

import ast
import re

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, register,
)

FAULTS_MODULE = "ceph_tpu/runtime/faults.py"

# one item of a CEPH_TPU_FAULTS spec:
# point[.qual]=action[:arg][@pP][ xN]
_SPEC_ITEM = re.compile(
    r"^([A-Za-z_]\w*)(\.[\w.-]+)?="
    r"(hang|stall|fail|lost|exit|overrun)(:[^,\s@]*)?"
    r"(@p[\d.]+)?(\s*x\d+)?$"
)


def _spec_bases(s: str) -> list[str]:
    """Base points of a fault-spec-looking string ("a.b=fail:x x2,c=hang"
    -> ["a", "c"]); [] when the string is not spec-shaped."""
    out = []
    for item in s.split(","):
        m = _SPEC_ITEM.match(item.strip())
        if not m:
            return []
        out.append(m.group(1))
    return out


def _check_sites(module: Module):
    """Yield (base_point, node) for faults.check/arm literals."""
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        c = module.canonical(node.func)
        if c is None:
            continue
        if c.endswith("faults.check") or c.endswith("faults.arm") or (
                "." not in c and c in ("check", "arm")
                and module.from_alias.get(c, "").endswith(f"faults.{c}")):
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                yield a0.value.split(".")[0], node


@register
class FaultPointPass(Pass):
    name = "fault-point"
    doc = "fault points declared in FAULT_POINTS; each covered by a test"

    def run(self, ctx: Context) -> None:
        if not ctx.fault_points:
            return
        # (a) production sites use declared bases
        for m in ctx.modules:
            if m.tree is None:
                continue
            if m.rel.endswith("runtime/faults.py"):
                continue  # hosts the machinery (and doc examples)
            for base, node in _check_sites(m):
                if base not in ctx.fault_points:
                    ctx.violations.append(Violation(
                        m.rel, node.lineno, self.name,
                        f"fault point base {base!r} is not declared in "
                        "runtime/faults.py FAULT_POINTS",
                    ))
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str):
                    for base in _spec_bases(node.value):
                        if base not in ctx.fault_points:
                            ctx.violations.append(Violation(
                                m.rel, node.lineno, self.name,
                                f"fault spec {node.value!r} uses "
                                f"undeclared point base {base!r}",
                            ))

        # (b) every declared point is exercised by at least one test
        if not ctx.test_modules:
            return
        referenced: set[str] = set()
        for tm in ctx.test_modules:
            if tm.tree is None:
                continue
            for base, _ in _check_sites(tm):
                referenced.add(base)
            for node in ast.walk(tm.tree):
                if isinstance(node, ast.Call) and node.args:
                    c = tm.canonical(node.func)
                    if c is not None and c.endswith("faults.configure"):
                        a0 = node.args[0]
                        if isinstance(a0, ast.Constant) and isinstance(
                                a0.value, str):
                            referenced.update(_spec_bases(a0.value))
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str):
                    referenced.update(_spec_bases(node.value))
        for point in sorted(ctx.fault_points):
            if point not in referenced:
                ctx.violations.append(Violation(
                    FAULTS_MODULE, ctx.fault_lines.get(point, 1), self.name,
                    f"declared fault point {point!r} is referenced by no "
                    "test — its retry/degradation branch is unexercised",
                ))
