"""trace-constant: jitted kernels must not bake host arrays into traces.

The r05 blowup class: a jit-wrapped kernel that closes over an
outer-scope array (or materializes one with `jnp.asarray(closure_var)`)
embeds that array as a *literal in the trace* — XLA then constant-folds
it at compile time (BENCH_r05 burned >2 s per compile folding a
pred[65536,11] constant) and the executable can never be reused for a
map that differs only in data.  Per-map data must ride as runtime
operands (the `dev` pytree / table operands), with only structural facts
baked in.  Until now one runtime jaxpr test guarded one kernel; this
pass checks every jit site statically.

Detected jit wrappings: `@jax.jit`, `@jit` (from-imported),
`@partial(jax.jit, ...)`, `jax.jit(f)`, `jax.jit(jax.vmap(f, ...))`
where `f` is a def or lambda visible in the module.

Flagged inside such a function:
- a free variable whose binding (enclosing function scope or module
  level) is an array-constructor call (`np.zeros`, `jnp.asarray`,
  `jax.device_put`, ...) — the closure becomes a trace constant;
- `jnp.asarray(...)` / `jnp.array(...)` / `np.asarray(...)` /
  `np.array(...)` applied to a free variable — same bake-in, spelled
  explicitly.

The check is lexical: arrays reaching the kernel through parameters are
operands and never flagged.  Genuinely static closures (a small
lookup table that must be baked) get a per-line
`# graftlint: disable=trace-constant`.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, register,
)

_ARRAY_MODS = ("numpy", "jax.numpy")
_ARRAY_FNS = (
    "array", "asarray", "zeros", "ones", "arange", "empty", "full",
    "frombuffer", "fromiter", "linspace", "eye", "stack", "concatenate",
)
_MATERIALIZE = {"asarray", "array"}


def _is_array_expr(node: ast.AST, module: Module) -> bool:
    """True when the expression constructs an array on the host/device
    (the kind that must not be closed over by a jitted kernel)."""
    if not isinstance(node, ast.Call):
        return False
    c = module.canonical(node.func)
    if c is None:
        return False
    if c == "jax.device_put":
        return True
    mod, _, attr = c.rpartition(".")
    return mod in _ARRAY_MODS and attr in _ARRAY_FNS


def _is_jit(node: ast.AST, module: Module) -> bool:
    """Is this expression `jax.jit` (possibly through partial())?"""
    if module.canonical(node) == "jax.jit":
        return True
    if isinstance(node, ast.Call):  # partial(jax.jit, ...)
        c = module.canonical(node.func)
        if c in ("functools.partial", "partial") and node.args:
            return module.canonical(node.args[0]) == "jax.jit"
    return False


def _jit_targets(module: Module):
    """Yield (function_node, report_node) for every function the module
    wraps in jax.jit."""
    tree = module.tree
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit(d, module) for d in node.decorator_list):
                yield node, node
        elif isinstance(node, ast.Call) and _is_jit(node.func, module):
            if not node.args:
                continue
            inner = node.args[0]
            # unwrap jax.vmap(f, ...) chains
            while (isinstance(inner, ast.Call)
                   and module.canonical(inner.func) == "jax.vmap"
                   and inner.args):
                inner = inner.args[0]
            if isinstance(inner, ast.Lambda):
                yield inner, node
            elif isinstance(inner, ast.Name) and inner.id in defs:
                yield defs[inner.id], node


def _bound_names(fn: ast.AST) -> set[str]:
    """Names bound anywhere inside fn: params (incl. nested defs and
    comprehensions) and assignment/for/with/import targets."""
    bound: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                bound.add(arg.arg)
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            if not isinstance(node, ast.Lambda):
                bound.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for al in node.names:
                bound.add((al.asname or al.name).split(".")[0])
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
    return bound


def _enclosing_array_bindings(module: Module) -> dict[int, dict[str, int]]:
    """For every function node (by id()), the array-constructor bindings
    visible at that point: maps name -> binding line.  Built per scope
    (module level + each function), child scopes inherit."""
    tree = module.tree
    out: dict[int, dict[str, int]] = {}

    def walk_scope(stmts):
        """Walk statements without descending into nested scopes."""
        stack = list(stmts)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                    stack.append(child)

    def scope_bindings(body) -> dict[str, int]:
        b: dict[str, int] = {}
        for node in walk_scope(body):
            if isinstance(node, ast.Assign):
                if _is_array_expr(node.value, module):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            b[t.id] = node.lineno
        return b

    def visit(node, inherited: dict[str, int]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            own = dict(inherited)
            own.update(scope_bindings(node.body))
            out[id(node)] = own
            for child in ast.iter_child_nodes(node):
                visit(child, own)
        elif isinstance(node, ast.Lambda):
            out[id(node)] = dict(inherited)
        else:
            for child in ast.iter_child_nodes(node):
                visit(child, inherited)

    top = scope_bindings(tree.body)
    for child in ast.iter_child_nodes(tree):
        visit(child, top)
    out[id(tree)] = top
    return out


@register
class TraceConstantPass(Pass):
    name = "trace-constant"
    doc = "jitted kernels must not close over / materialize host arrays"

    def run(self, ctx: Context) -> None:
        for m in ctx.modules:
            ctx.violations.extend(self.check_module(m, ctx))

    def check_module(self, module: Module, ctx: Context) -> list[Violation]:
        if module.tree is None:
            return []
        out: list[Violation] = []
        bindings = _enclosing_array_bindings(module)
        seen: set[tuple[int, int]] = set()
        for fn, report_node in _jit_targets(module):
            visible = bindings.get(id(fn), bindings[id(module.tree)])
            bound = _bound_names(fn)
            body = fn.body if isinstance(fn, ast.Lambda) else fn
            for node in ast.walk(body):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id not in bound
                        and node.id in visible):
                    key = (node.lineno, node.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(Violation(
                        module.rel, node.lineno, self.name,
                        f"jitted kernel closes over array "
                        f"'{node.id}' (bound at line "
                        f"{visible[node.id]}) — it becomes a trace "
                        "constant; pass it as an operand",
                    ))
                if isinstance(node, ast.Call):
                    c = module.canonical(node.func)
                    if c is None or not node.args:
                        continue
                    mod, _, attr = c.rpartition(".")
                    a0 = node.args[0]
                    if (mod in _ARRAY_MODS and attr in _MATERIALIZE
                            and isinstance(a0, ast.Name)
                            and a0.id not in bound):
                        key = (node.lineno, node.col_offset)
                        if key in seen:
                            continue
                        seen.add(key)
                        out.append(Violation(
                            module.rel, node.lineno, self.name,
                            f"{attr}() materializes non-static "
                            f"'{a0.id}' inside a jitted kernel — it "
                            "becomes a trace constant; pass it as an "
                            "operand",
                        ))
        return module.filter(out)
