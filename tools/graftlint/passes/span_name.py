"""span-name: trace event names exist in the obs span registry.

A typo'd span name fails nothing at runtime — the events record under
the wrong track and every Perfetto query / trace-driven analysis
silently misses them.  `ceph_tpu/obs/spans.py` is the single registry;
this pass checks every literal `span(...)` / `instant(...)` /
`obs.counter(...)` name (and `JitAccount(span=...)` base names) against
it.  Dynamically built names must carry a registered static prefix
(`f"stage.{name}"` -> "stage."); f-strings with no static head
(JitAccount's `f"{group}.{key}.{phase}"`) are exempt by construction.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, register,
)


def _fstring_head(node: ast.JoinedStr) -> str:
    head = ""
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            head += v.value
        else:
            break
    return head


def _recv_is_obs(func: ast.Attribute, module: Module) -> bool:
    c = module.canonical(func.value)
    if c is None:
        return False
    tail = c.rsplit(".", 1)[-1]
    return tail in ("obs", "trace")


@register
class SpanNamePass(Pass):
    name = "span-name"
    doc = "span/instant/counter literals exist in the obs span registry"

    def run(self, ctx: Context) -> None:
        for m in ctx.modules:
            ctx.violations.extend(self.check_module(m, ctx))

    def check_module(self, module: Module, ctx: Context) -> list[Violation]:
        if module.tree is None or module.rel.endswith("obs/spans.py"):
            return []
        out: list[Violation] = []

        def check(name_node, registry: dict, kind: str, node: ast.AST):
            if isinstance(name_node, ast.Constant) and isinstance(
                    name_node.value, str):
                name = name_node.value
                ok = name in registry or any(
                    name.startswith(p) for p in ctx.span_prefixes
                )
            elif isinstance(name_node, ast.JoinedStr):
                head = _fstring_head(name_node)
                if not head:
                    return  # fully dynamic: exempt by construction
                name = head + "{...}"
                ok = any(head.startswith(p) for p in ctx.span_prefixes)
            else:
                return  # a variable: not statically checkable
            if not ok:
                out.append(Violation(
                    module.rel, node.lineno, self.name,
                    f"{kind} name {name!r} is not declared in "
                    "ceph_tpu/obs/spans.py (typo'd names orphan their "
                    "trace events; declare it or fix the spelling)",
                ))

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if attr == "span" and node.args:
                check(node.args[0], ctx.spans, "span", node)
            elif attr == "instant" and node.args:
                check(node.args[0], ctx.instants, "instant", node)
            elif (attr == "counter" and node.args
                    and isinstance(f, ast.Attribute)
                    and _recv_is_obs(f, module)):
                check(node.args[0], ctx.trace_counters, "counter", node)
            elif attr == "JitAccount" or (
                    attr is not None and attr.endswith("JitAccount")):
                for kw in node.keywords:
                    if kw.arg == "span":
                        check(kw.value, ctx.spans, "JitAccount span", node)
        return module.filter(out)
