"""serve-reply: reply statuses are declared, constructed, and pinned.

`serve/service.py` declares the answer vocabulary (`REPLY_STATUSES`)
the never-dropped contract is written in: every dispatcher path — the
queued micro-batcher, the bulk protocol edge, the multi-replica front
— must end each request (each lane, for bulk) in exactly one declared
status.  Three static directions:

- every `Reply("<status>", ...)` construction and every
  `STATUS_CODES["<status>"]` lane code in a serve module must name a
  declared status — an early-return path cannot invent an
  undocumented answer code;
- every declared status must be constructed by at least one serve
  path (dead vocabulary otherwise) and must appear as a string
  literal in at least one test — an answer code no test asserts is an
  error path nobody has watched fire;
- a function annotated `-> Reply` / `-> BulkReply` must never `return`
  bare or `return None`: that is a silently dropped reply, the exact
  bug the per-lane status contract exists to rule out.

All directions are AST-only (no serve import); the registry and its
line numbers come from `Context.reply_statuses`/`reply_lines`.
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import (
    REPLY_REGISTRY, Context, Module, Pass, Violation, register,
)

_REPLY_TYPES = ("Reply", "BulkReply")


def _is_serve_module(module: Module) -> bool:
    return (module.rel.startswith("ceph_tpu/serve/")
            or "serve" in module.rel.rsplit("/", 1)[-1])


def _status_sites(module: Module):
    """Yield (status, node, how) for every literal status a serve
    module constructs: `Reply("X", ...)` first arguments and
    `STATUS_CODES["X"]` subscripts."""
    if module.tree is None:
        return
    for node in ast.walk(module.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Reply"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            yield node.args[0].value, node.args[0], "Reply()"
        elif (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == "STATUS_CODES"
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            yield node.slice.value, node.slice, "STATUS_CODES[]"


def _dropped_replies(module: Module):
    """Yield `return` nodes that drop a reply: bare return / return
    None inside a function annotated -> Reply / -> BulkReply."""
    if module.tree is None:
        return
    for fn in ast.walk(module.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        r = fn.returns
        named = (isinstance(r, ast.Name) and r.id in _REPLY_TYPES) or (
            isinstance(r, ast.Constant) and r.value in _REPLY_TYPES)
        if not named:
            continue
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs carry their own annotation
            if isinstance(node, ast.Return) and (
                    node.value is None
                    or (isinstance(node.value, ast.Constant)
                        and node.value.value is None)):
                yield fn.name, node
            stack.extend(ast.iter_child_nodes(node))


@register
class ServeReplyPass(Pass):
    name = "serve-reply"
    doc = "serve reply statuses declared/constructed; no dropped replies"

    def run(self, ctx: Context) -> None:
        if not ctx.reply_statuses:
            return
        serve = [m for m in ctx.modules if _is_serve_module(m)]
        constructed: dict[str, int] = {}
        for m in serve:
            for status, node, how in _status_sites(m):
                constructed.setdefault(status, node.lineno)
                if status not in ctx.reply_statuses:
                    ctx.report(m, node, self.name,
                               f"{how} names status {status!r} that is "
                               "not declared in REPLY_STATUSES — an "
                               "undocumented answer code")
            for fn_name, node in _dropped_replies(m):
                ctx.report(m, node, self.name,
                           f"{fn_name}() is annotated to return a "
                           "reply but this path returns none — a "
                           "dropped reply breaks the never-dropped "
                           "contract")

        # reverse direction only against the real registry home: a
        # fixture module alone cannot prove vocabulary dead
        if any(m.rel.endswith("serve/service.py") for m in serve):
            for status in sorted(ctx.reply_statuses):
                if status not in constructed:
                    ctx.violations.append(Violation(
                        REPLY_REGISTRY, ctx.reply_lines.get(status, 1),
                        self.name,
                        f"declared status {status!r} is constructed by "
                        "no serve path — dead vocabulary",
                    ))

        if not ctx.test_modules:
            return
        pinned: set[str] = set()
        for tm in ctx.test_modules:
            if tm.tree is None:
                continue
            for node in ast.walk(tm.tree):
                if isinstance(node, ast.Constant) and isinstance(
                        node.value, str) \
                        and node.value in ctx.reply_statuses:
                    pinned.add(node.value)
        for status in sorted(ctx.reply_statuses):
            if status not in pinned:
                ctx.violations.append(Violation(
                    REPLY_REGISTRY, ctx.reply_lines.get(status, 1),
                    self.name,
                    f"declared status {status!r} is asserted by no "
                    "test literal — an answer path nobody has watched "
                    "fire",
                ))
