"""counter-decl: every perf-counter update key matches a declaration.

The perf registry raises `UndeclaredCounterError` at runtime on an
update to an undeclared key — but only when that code path actually
runs, which for rarely-taken branches (rescue paths, failure fallbacks)
means the typo ships and the counter silently never exists until a
production run dies on it.  This pass matches update keys against
declares statically, across the whole scanned tree.

Resolution is alias-aware and group-scoped:

- `L = obs.logger_for("pipeline")` binds L to group "pipeline" (module
  or function scope; `logger_for` bare or attribute-qualified);
- a module function whose body returns such a logger propagates the
  group to `_counters().inc(...)`-style call sites;
- declares (`add_u64` / `add_avg` / `add_time_avg` / `add_histogram` /
  `add_quantile`) with literal keys are collected per group ACROSS
  modules — bench.py updating "pipeline" keys declared in
  pipeline_jax.py is fine;
- f-string declares contribute their constant tail as a dynamic-suffix
  pattern (`JitAccount` declares `f"{key}_compiles"` etc.), matched by
  `endswith` for updates whose exact key cannot be known statically;
- updates (`inc` / `observe` / `time` / `set`) with literal keys must
  hit a declared key of their group; unresolvable receivers fall back
  to the union of all declared keys (`set` requires a resolved
  receiver — too many non-logger `.set()` calls exist).
"""

from __future__ import annotations

import ast
from collections import defaultdict

from tools.graftlint.engine import (
    Context, Module, Pass, Violation, register,
)

DECLARES = ("add_u64", "add_avg", "add_time_avg", "add_histogram",
            "add_quantile")
UPDATES = ("inc", "observe", "time", "set", "merge_histogram")


def _logger_for_group(node: ast.AST, module: Module) -> str | None:
    """The group name if node is `[obs.]logger_for("g")`."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    c = module.canonical(node.func)
    if c is None or not (c == "logger_for" or c.endswith(".logger_for")):
        return None
    a0 = node.args[0]
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
        return a0.value
    return None


def _module_logger_maps(module: Module):
    """(name->group for logger variables, funcname->group for functions
    returning a logger).  A name bound to two different groups resolves
    to None (ambiguous)."""
    names: dict[str, str | None] = {}
    funcs: dict[str, str] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign):
            g = _logger_for_group(node.value, module)
            if g is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        prev = names.get(t.id)
                        names[t.id] = g if prev in (None, g) else None
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    g = _logger_for_group(sub.value, module)
                    if g is None and isinstance(sub.value, ast.Name):
                        local = {}
                        for s2 in ast.walk(node):
                            if isinstance(s2, ast.Assign):
                                lg = _logger_for_group(s2.value, module)
                                if lg is not None:
                                    for t in s2.targets:
                                        if isinstance(t, ast.Name):
                                            local[t.id] = lg
                        g = local.get(sub.value.id)
                    if g is not None:
                        funcs[node.name] = g
    return names, funcs


def _receiver_group(recv: ast.AST, module: Module, names, funcs):
    if isinstance(recv, ast.Name):
        return names.get(recv.id)
    if isinstance(recv, ast.Call):
        g = _logger_for_group(recv, module)
        if g is not None:
            return g
        if isinstance(recv.func, ast.Name):
            return funcs.get(recv.func.id)
    return None


def _fstring_tail(node: ast.JoinedStr) -> str | None:
    """The trailing constant of an f-string key (f"{key}_compiles" ->
    "_compiles"), None when it ends dynamically."""
    if node.values and isinstance(node.values[-1], ast.Constant):
        v = node.values[-1].value
        if isinstance(v, str) and v:
            return v
    return None


@register
class CounterDeclPass(Pass):
    name = "counter-decl"
    doc = "perf-counter update keys statically match a declaration"

    def run(self, ctx: Context) -> None:
        declared: dict[str, set[str]] = defaultdict(set)
        wildcard: set[str] = set()   # declares on unresolvable receivers
        suffixes: set[str] = set()   # dynamic-declare key tails
        sites = []  # (module, call, group, method, key)

        for m in ctx.modules:
            if m.tree is None:
                continue
            names, funcs = _module_logger_maps(m)
            for node in ast.walk(m.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                meth = node.func.attr
                if meth not in DECLARES and meth not in UPDATES:
                    continue
                if not node.args:
                    continue
                group = _receiver_group(node.func.value, m, names, funcs)
                a0 = node.args[0]
                if meth in DECLARES:
                    if isinstance(a0, ast.Constant) and isinstance(
                            a0.value, str):
                        if group is not None:
                            declared[group].add(a0.value)
                        else:
                            wildcard.add(a0.value)
                    elif isinstance(a0, ast.JoinedStr):
                        tail = _fstring_tail(a0)
                        if tail:
                            suffixes.add(tail)
                elif isinstance(a0, ast.Constant) and isinstance(
                        a0.value, str):
                    sites.append((m, node, group, meth, a0.value))

        every = wildcard.union(*declared.values()) if declared else wildcard
        for m, node, group, meth, key in sites:
            if group is not None:
                known = declared.get(group, set()) | wildcard
                scope = f"group '{group}'"
            elif meth == "set":
                continue  # unresolved .set("...") receivers: not loggers
            else:
                known = every
                scope = "any scanned group"
            if key in known:
                continue
            if any(key.endswith(s) for s in suffixes):
                continue  # JitAccount-style dynamically declared family
            ctx.violations.append(Violation(
                m.rel, node.lineno, self.name,
                f"counter update {meth}({key!r}) has no declaration in "
                f"{scope} (UndeclaredCounterError at runtime; declare "
                "with add_u64/add_avg/add_time_avg/add_histogram/"
                "add_quantile)",
            ))
