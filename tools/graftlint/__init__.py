"""graftlint — unified contract-checking static analysis for the
trace-once stack.

    python -m tools.graftlint [--json] [--select pass1,pass2] [--list]

One AST/alias-aware engine (`engine.py`), a pass registry
(`passes/`), per-line `# graftlint: disable=<pass>` suppressions, and
human/JSON reporters.  The passes machine-check the conventions PRs 1-6
established by review: dispatch spans never host-sync, jitted kernels
never bake per-map data into traces, counter updates match declares,
CEPH_TPU knobs are registered and documented, span names exist in the
obs registry, fault points are declared and test-covered.

Library surface (used by tests, bench.py --selftest, and the
`check_no_print.py` / `check_no_host_sync.py` compatibility shims):

    from tools.graftlint import run, PASSES, Module, Context
    violations, report = run()                    # all passes, whole repo
    violations, report = run(select=["host-sync"])
"""

from tools.graftlint.engine import (  # noqa: F401
    PASSES,
    Context,
    Module,
    Pass,
    Violation,
    human_report,
    iter_files,
    register,
    run,
)
from tools.graftlint import passes  # noqa: E402,F401  (registers passes)
