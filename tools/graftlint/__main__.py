"""CLI: python -m tools.graftlint [--json] [--select a,b] [--list].

Exit status: 0 clean, 1 violations found, 2 bad usage.  Human output
goes to stderr (like the lints this framework absorbed); --json writes
the machine-readable report to stdout (embedded by bench.py --selftest
into the BENCH record).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from tools.graftlint import PASSES, human_report, run


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="contract-checking static analysis for this repo",
    )
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--select", metavar="PASS[,PASS...]",
                    help="run only these passes (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the README CEPH_TPU_* knob table and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(PASSES):
            print(f"{name:16} {PASSES[name].doc}")
        return 0
    if args.knob_table:
        # late import: keeps lint runs free of the ceph_tpu import graph
        from ceph_tpu.utils.knobs import render_table

        print(render_table(), end="")
        return 0

    select = (
        [s.strip() for s in args.select.split(",") if s.strip()]
        if args.select else None
    )
    t0 = time.perf_counter()
    try:
        violations, report = run(select=select)
    except KeyError as e:
        print(f"graftlint: {e.args[0]}", file=sys.stderr)
        return 2
    report["elapsed_s"] = round(time.perf_counter() - t0, 3)
    if args.json:
        print(json.dumps(report))
    print(human_report(violations, report["passes"]), file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
