"""graftlint core: module model, alias resolution, registries, runner.

One shared AST walker feeds every pass (the old one-off lints each
re-parsed the tree and re-invented alias handling, and each had blind
spots the other had already fixed).  The engine:

- discovers and parses every module once (`Context.modules`), plus the
  test tree (`Context.test_modules`) for passes that cross-check tests;
- resolves import aliases (`import numpy as np`, `from numpy import
  asarray as aa`, `from ceph_tpu.runtime import faults`) to canonical
  dotted names so passes match semantics, not spellings;
- extracts the three contract registries **statically** (span registry,
  env-knob registry, fault-point registry) — linting never imports the
  tree, so a syntax error or import-time side effect cannot wedge it;
- applies per-line `# graftlint: disable=<pass>[,<pass>...]` (or
  `disable=all`) suppressions against the reported violation line;
- renders human (stderr-style lines) and JSON reports.

Passes self-register via `@register`; `run()` executes a selection and
returns sorted, suppression-filtered violations.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

# the shared module walker's scan set: every hot-path package plus the
# entry points and the tooling itself (tools/ is held to its own lints)
SCAN = ("ceph_tpu", "bench.py", "__graft_entry__.py", "tools")
TEST_DIR = "tests"

SPAN_REGISTRY = "ceph_tpu/obs/spans.py"
KNOB_REGISTRY = "ceph_tpu/utils/knobs.py"
FAULT_REGISTRY = "ceph_tpu/runtime/faults.py"
HEALTH_REGISTRY = "ceph_tpu/obs/health.py"
EVENT_REGISTRY = "ceph_tpu/sim/lifetime.py"
SWEEP_REGISTRY = "ceph_tpu/fleet/spec.py"
REPLY_REGISTRY = "ceph_tpu/serve/service.py"

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([\w,-]+)")


@dataclass
class Violation:
    path: str  # repo-relative where possible
    line: int
    pass_name: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"

    def as_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "pass": self.pass_name,
            "message": self.message,
        }


def dotted_name(node: ast.AST) -> str | None:
    """The dotted source spelling of a Name/Attribute chain
    (`jax.numpy.asarray` -> "jax.numpy.asarray"), None for anything
    dynamic (subscripts, calls)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class Module:
    """One parsed file: AST + alias maps + suppression lines."""

    def __init__(self, path: Path, root: Path = REPO):
        self.path = Path(path)
        self.rel = (
            str(self.path.relative_to(root))
            if self.path.is_relative_to(root) else str(self.path)
        )
        src = self.path.read_text()
        self.parse_error: tuple[int, str] | None = None
        try:
            self.tree: ast.Module | None = ast.parse(src, filename=self.rel)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = (e.lineno or 0, e.msg or "syntax error")
        self.suppressions: dict[int, set[str]] = {}
        for i, line in enumerate(src.splitlines(), 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppressions[i] = {
                    p.strip() for p in m.group(1).split(",") if p.strip()
                }
        # import alias maps
        self.mod_alias: dict[str, str] = {}   # local name -> module dotted
        self.from_alias: dict[str, str] = {}  # local name -> module.attr
        if self.tree is not None:
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        self.mod_alias[a.asname or a.name.split(".")[0]] = (
                            a.name if a.asname else a.name.split(".")[0]
                        )
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        if a.name == "*":
                            continue
                        self.from_alias[a.asname or a.name] = (
                            f"{node.module}.{a.name}"
                        )
        # "from ceph_tpu.runtime import faults" binds a *module*: route
        # it through mod_alias so canonical() expands the full path
        for local, target in list(self.from_alias.items()):
            head = target.rsplit(".", 1)[-1]
            if head == local and target.count(".") >= 1:
                # keep in from_alias too; canonical() tries both
                self.mod_alias.setdefault(local, target)

    def canonical(self, node: ast.AST) -> str | None:
        """Alias-resolved dotted name of a Name/Attribute chain:
        `np.asarray` -> "numpy.asarray", `aa` (from numpy import asarray
        as aa) -> "numpy.asarray", `environ.get` (from os import
        environ) -> "os.environ.get"."""
        d = dotted_name(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        if head in self.from_alias:
            base = self.from_alias[head]
        elif head in self.mod_alias:
            base = self.mod_alias[head]
        else:
            base = head
        return f"{base}.{rest}" if rest else base

    def suppressed(self, line: int, pass_name: str) -> bool:
        tags = self.suppressions.get(line)
        return bool(tags) and (pass_name in tags or "all" in tags)

    def filter(self, violations: list["Violation"]) -> list["Violation"]:
        """Drop violations a `# graftlint: disable=` line suppresses —
        the single place suppression is applied for per-module entry
        points (engine.run() applies the same filter for full runs)."""
        return [
            v for v in violations if not self.suppressed(v.line, v.pass_name)
        ]


def iter_files(root: Path = REPO, scan=SCAN) -> list[Path]:
    out: list[Path] = []
    for entry in scan:
        p = root / entry
        if p.is_dir():
            out.extend(
                f for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.exists():
            out.append(p)
    return out


def _literal_assign(tree: ast.Module, name: str):
    """The ast node of a module-level `NAME = <literal>` assignment."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if (isinstance(node.target, ast.Name)
                    and node.target.id == name and node.value is not None):
                return node.value
    return None


def _load_registry(path: Path, name: str, default):
    """literal_eval a module-level constant out of a registry module,
    plus per-key line numbers for dict registries."""
    if not path.exists():
        return default, {}
    tree = ast.parse(path.read_text(), filename=str(path))
    node = _literal_assign(tree, name)
    if node is None:
        return default, {}
    try:
        value = ast.literal_eval(node)
    except ValueError:
        return default, {}
    lines: dict[str, int] = {}
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                lines[k.value] = k.lineno
    return value, lines


class Context:
    """Everything a pass needs: parsed modules, registries, a sink."""

    def __init__(self, root: Path = REPO, paths: list[Path] | None = None,
                 include_tests: bool = True):
        self.root = Path(root)
        files = iter_files(self.root) if paths is None else [
            Path(p) for p in paths
        ]
        self.modules = [Module(p, self.root) for p in files]
        self._include_tests = include_tests
        self._test_modules: list[Module] | None = None
        self.violations: list[Violation] = []
        # contract registries, extracted without importing the tree
        self.spans, _ = _load_registry(self.root / SPAN_REGISTRY, "SPANS", {})
        self.instants, _ = _load_registry(
            self.root / SPAN_REGISTRY, "INSTANTS", {})
        self.trace_counters, _ = _load_registry(
            self.root / SPAN_REGISTRY, "COUNTERS", {})
        self.span_prefixes, _ = _load_registry(
            self.root / SPAN_REGISTRY, "PREFIXES", ())
        self.dispatch_spans, _ = _load_registry(
            self.root / SPAN_REGISTRY, "DISPATCH_SPANS", ())
        self.knobs, self.knob_lines = _load_registry(
            self.root / KNOB_REGISTRY, "KNOBS", {})
        self.fault_points, self.fault_lines = _load_registry(
            self.root / FAULT_REGISTRY, "FAULT_POINTS", {})
        self.health_checks, self.health_lines = _load_registry(
            self.root / HEALTH_REGISTRY, "HEALTH_CHECKS", {})
        self.event_kinds, self.event_lines = _load_registry(
            self.root / EVENT_REGISTRY, "EVENT_KINDS", {})
        self.sweep_axes, self.sweep_lines = _load_registry(
            self.root / SWEEP_REGISTRY, "SWEEP_AXES", {})
        self.fleet_knobs, self.fleet_knob_lines = _load_registry(
            self.root / SWEEP_REGISTRY, "FLEET_KNOBS", {})
        self.reply_statuses, self.reply_lines = _load_registry(
            self.root / REPLY_REGISTRY, "REPLY_STATUSES", {})

    @property
    def test_modules(self) -> list[Module]:
        """tests/ parsed on first access — only the fault-point pass
        consumes these, so single-pass runs (the shims) skip the work."""
        if self._test_modules is None:
            self._test_modules = []
            if self._include_tests and (self.root / TEST_DIR).is_dir():
                self._test_modules = [
                    Module(p, self.root)
                    for p in sorted((self.root / TEST_DIR).rglob("*.py"))
                    if "__pycache__" not in p.parts
                ]
        return self._test_modules

    def report(self, module: Module, node, pass_name: str,
               message: str) -> None:
        line = node if isinstance(node, int) else getattr(node, "lineno", 0)
        self.violations.append(Violation(module.rel, line, pass_name, message))


class Pass:
    """Base class; subclasses set `name`/`doc` and implement run()."""

    name = "?"
    doc = ""

    def run(self, ctx: Context) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def check_module(self, module: Module, ctx: Context) -> list[Violation]:
        """Run this pass against one module only (shim/fixture entry):
        default routes through run() on a throwaway sink."""
        sub = object.__new__(Context)
        sub.__dict__.update(ctx.__dict__)
        sub.modules = [module]
        sub.violations = []
        self.run(sub)
        return [
            v for v in sub.violations
            if not module.suppressed(v.line, v.pass_name)
        ]


PASSES: dict[str, Pass] = {}


def register(cls):
    PASSES[cls.name] = cls()
    return cls


def run(select: list[str] | None = None, root: Path = REPO,
        paths: list[Path] | None = None) -> tuple[list[Violation], dict]:
    """Execute the selected passes; returns (violations, report_dict).

    Unparseable scanned files are themselves violations (every pass is
    blind to a file it cannot parse, so that must fail loudly)."""
    from tools.graftlint import passes as _passes  # noqa: F401  (registers)

    names = sorted(PASSES) if select is None else list(select)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise KeyError(f"unknown pass(es): {', '.join(unknown)}; "
                       f"available: {', '.join(sorted(PASSES))}")
    ctx = Context(root=root, paths=paths)
    for m in ctx.modules:
        if m.parse_error is not None:
            ctx.violations.append(Violation(
                m.rel, m.parse_error[0], "parse",
                f"unparseable: {m.parse_error[1]}",
            ))
    by_path = {m.rel: m for m in ctx.modules}
    for n in names:
        PASSES[n].run(ctx)
    out = [
        v for v in ctx.violations
        if v.path not in by_path
        or not by_path[v.path].suppressed(v.line, v.pass_name)
    ]
    out.sort(key=lambda v: (v.path, v.line, v.pass_name))
    report = {
        "tool": "graftlint",
        "passes": names,
        "files_scanned": len(ctx.modules),
        "count": len(out),
        "violations": [v.as_json() for v in out],
    }
    return out, report


def human_report(violations: list[Violation], names: list[str]) -> str:
    lines = [v.format() for v in violations]
    lines.append(
        f"graftlint [{','.join(names)}]: "
        + (f"{len(violations)} violation(s)" if violations else "clean")
    )
    return "\n".join(lines)
