"""Lint shim: hot-path modules never print() to stdout.

The real check is graftlint's `no-print` pass (tools/graftlint/passes/
no_print.py); this file keeps the historical entry points alive —
`python tools/check_no_print.py` and `from check_no_print import
check_file` (tests/test_obs.py) — by delegating to the shared engine.

    python tools/check_no_print.py          # exit 1 on violations
    python -m tools.graftlint --select no-print
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:  # script/flat-import mode: tools/ is path[0]
    sys.path.insert(0, str(REPO))

from tools.graftlint import PASSES, Context  # noqa: E402

PASS = "no-print"


def check_file(path: Path) -> list[str]:
    from tools.graftlint import Module

    ctx = Context(paths=[], include_tests=False)
    module = Module(Path(path), REPO)
    if module.parse_error is not None:
        line, msg = module.parse_error
        return [f"{module.rel}:{line}: unparseable: {msg}"]
    return [v.format() for v in PASSES[PASS].check_module(module, ctx)]


def find_violations(root: Path = REPO) -> list[str]:
    from tools.graftlint import run

    violations, _ = run(select=[PASS], root=Path(root))
    return [v.format() for v in violations]


def main() -> int:
    violations = find_violations()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"check_no_print: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_no_print: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
