"""Lint: hot-path modules never print() to stdout.

The reference routes all daemon output through dout/derr and the perf
registry — stdout belongs to the CLI tools' machine-readable output
(crushtool -d, perf dump JSON).  A stray debugging `print()` in the
mapping/EC/balancer hot paths corrupts that contract (and is invisible
in a killed bench run, unlike a counter).  This lint walks the AST of
every module under the hot-path packages and flags:

    print(...)                  # no file= -> stdout
    print(..., file=sys.stdout) # explicit stdout

`print(..., file=w)` with any other stream is allowed — that is how the
tester renders `--show-mappings` output to a caller-chosen stream.

Runnable standalone (exit 1 on violations) and from tests:

    python tools/check_no_print.py
    from check_no_print import find_violations
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

HOT_PACKAGES = (
    "ceph_tpu/crush",
    "ceph_tpu/osd",
    "ceph_tpu/ec",
    "ceph_tpu/balancer",
    "ceph_tpu/mgr",
)


def _is_stdout_print(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Name) and node.func.id == "print"):
        return False
    for kw in node.keywords:
        if kw.arg == "file":
            v = kw.value
            return (
                isinstance(v, ast.Attribute)
                and v.attr == "stdout"
                and isinstance(v.value, ast.Name)
                and v.value.id == "sys"
            )
    return True  # bare print() -> stdout


def check_file(path: Path) -> list[str]:
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: unparseable: {e.msg}"]
    rel = path.relative_to(REPO) if path.is_relative_to(REPO) else path
    return [
        f"{rel}:{node.lineno}: print() to stdout "
        "(route through ceph_tpu.utils.dout or a perf counter)"
        for node in ast.walk(tree)
        if isinstance(node, ast.Call) and _is_stdout_print(node)
    ]


def find_violations(root: Path = REPO) -> list[str]:
    out: list[str] = []
    for pkg in HOT_PACKAGES:
        for py in sorted((root / pkg).rglob("*.py")):
            out.extend(check_file(py))
    return out


def main() -> int:
    violations = find_violations()
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"check_no_print: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_no_print: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
