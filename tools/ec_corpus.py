"""EC non-regression corpus — pin every backend to identical bytes.

The reference guards its erasure-code plugins with an archive of encoded
content that newer versions must reproduce byte-for-byte
(reference src/test/erasure-code/ceph_erasure_code_non_regression.cc:
create/check round trips over a --base directory).  Same idea here, one
JSON file instead of a directory tree: for each profile the corpus
records the SHA-256 of the full encoded stripe for a deterministic
input, plus decode-under-erasure cases (1..m lost shards, deterministic
patterns clipped to each profile's actual tolerance) whose REBUILT
bytes are digest-pinned too — decode plans are frozen bit-exact, not
just encode.

Every *backend* of a plugin (host numpy, the native SIMD engine, the
device jax engine) must produce the SAME stripe — the corpus digest is
backend-independent, so a verify run doubles as the numpy/native/jax
equivalence gate (VERDICT r5 item 7).  Plugins without a backend knob
(shec, lrc) are pinned across versions only.

    python -m tools.ec_corpus create [--out FILE] [--bytes N]
    python -m tools.ec_corpus verify [--in FILE] [--backends numpy,...]

The frozen tier-1 corpus lives at tests/data/ec_corpus.json (verified
by tests/test_ec_corpus.py on every run); regenerate it with `create`
only when a deliberate format change is made — a digest change IS the
regression this tool exists to catch.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from pathlib import Path

import numpy as np

# (name, profile, backends to pin; "numpy" is the digest source)
ENTRIES: list[tuple[str, dict, tuple[str, ...]]] = [
    ("rs_k8m4_reed_sol_van",
     {"plugin": "jerasure", "technique": "reed_sol_van", "k": "8", "m": "4"},
     ("numpy", "native", "jax")),
    ("rs_k6m2_reed_sol_r6_op",
     {"plugin": "jerasure", "technique": "reed_sol_r6_op",
      "k": "6", "m": "2"},
     ("numpy", "native", "jax")),
    ("rs_k4m2_cauchy_good",
     {"plugin": "jerasure", "technique": "cauchy_good", "k": "4", "m": "2"},
     ("numpy", "native", "jax")),
    ("isa_k8m4_reed_sol_van",
     {"plugin": "isa", "technique": "reed_sol_van", "k": "8", "m": "4"},
     ("numpy", "native", "jax")),
    ("clay_k4m2_d5",
     {"plugin": "clay", "k": "4", "m": "2", "d": "5"},
     ("numpy", "native", "jax")),
    ("shec_k4m3_c2",
     {"plugin": "shec", "k": "4", "m": "3", "c": "2"},
     ("numpy", "native", "jax")),
    ("lrc_k4m2_l3",
     {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
     ("numpy", "native", "jax")),
]

# erasure sets (chunk indices) each entry must decode through; clipped
# to the entry's chunk count and fault tolerance at run time
ERASURES = ([0], [1, 5])


def decode_patterns(n: int, m: int) -> list[list[int]]:
    """Deterministic erasure patterns, 1..m lost shards: for each loss
    count a leading run (data-heavy), an evenly spread set, and a tail
    run (parity-heavy).  Patterns a profile cannot decode (shec's c <
    m tolerance, lrc layer limits) are dropped at `create` time by
    attempting the decode — what lands in the corpus is exactly what
    every backend must then reproduce."""
    out: list[list[int]] = []
    seen: set[tuple] = set()
    for lost_n in range(1, m + 1):
        cands = (
            list(range(lost_n)),                              # leading
            sorted({(i * n) // lost_n for i in range(lost_n)}),  # spread
            list(range(n - lost_n, n)),                       # tail
        )
        for p in cands:
            p = sorted(set(p))
            if len(p) == lost_n and tuple(p) not in seen:
                seen.add(tuple(p))
                out.append(p)
    return out

DEFAULT_CORPUS = Path(__file__).resolve().parent.parent / "tests" / \
    "data" / "ec_corpus.json"


def _mk_code(profile: dict, backend: str):
    from ceph_tpu.ec.registry import create_erasure_code

    p = dict(profile)
    if backend != "numpy":
        p["backend"] = backend
    return create_erasure_code(p)


def _chunk_len(code, want: int) -> int:
    """Chunk length honoring sub-chunked codes (clay)."""
    sub = 1
    try:
        sub = int(code.get_sub_chunk_count())
    except Exception:
        pass
    return max(want + (-want) % max(sub, 1), sub)


def _data_for(name: str, k: int, length: int) -> np.ndarray:
    """Deterministic input bytes (PCG64 streams are stable across numpy
    versions; the name seeds the stream so entries are independent)."""
    seed = int.from_bytes(hashlib.sha256(name.encode()).digest()[:8], "big")
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(k, length), dtype=np.uint8)


def _to_np(chunk) -> np.ndarray:
    return np.asarray(chunk, dtype=np.uint8)


def _encode(code, data: np.ndarray, backend: str) -> np.ndarray:
    if backend == "jax":
        import jax.numpy as jnp

        out = code.encode_chunks(jnp.asarray(data))
    else:
        out = code.encode_chunks(data)
    return _to_np(out)


def _stripe_digest(chunks: np.ndarray) -> str:
    h = hashlib.sha256()
    for row in chunks:
        h.update(_to_np(row).tobytes())
    return h.hexdigest()


def _rebuilt_digest(dec: dict, erased: list[int]) -> str:
    """Digest of the REBUILT chunks only, in erased-index order."""
    h = hashlib.sha256()
    for i in erased:
        h.update(_to_np(dec[i]).tobytes())
    return h.hexdigest()


def build_entry(name: str, profile: dict, nbytes: int) -> dict:
    code = _mk_code(profile, "numpy")
    k = code.k
    n = code.get_chunk_count()
    L = _chunk_len(code, nbytes)
    data = _data_for(name, k, L)
    enc = _encode(code, data, "numpy")
    assert enc.shape[0] == n, (name, enc.shape, n)
    decode_cases = []
    for erased in decode_patterns(n, code.get_coding_chunk_count()):
        avail = {i: enc[i] for i in range(n) if i not in erased}
        try:
            dec = code.decode_chunks(set(erased), dict(avail), L)
        except Exception:
            continue  # beyond this profile's tolerance: not a case
        for i in erased:  # a wrong rebuild must never be frozen
            assert np.array_equal(_to_np(dec[i]), enc[i]), (name, erased, i)
        decode_cases.append({
            "erased": list(erased),
            "digest": _rebuilt_digest(dec, erased),
        })
    assert decode_cases, name  # every profile pins at least one decode
    return {
        "name": name,
        "profile": profile,
        "chunk_bytes": L,
        "n_chunks": n,
        "digest": _stripe_digest(enc),
        "decode": decode_cases,
    }


def create(path: Path, nbytes: int) -> None:
    corpus = {
        "version": 2,
        "entries": [
            build_entry(name, profile, nbytes)
            for name, profile, _ in ENTRIES
        ],
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(corpus, indent=1) + "\n")
    print(f"wrote {len(corpus['entries'])} entries to {path}")


def verify_entry(entry: dict, backends: tuple[str, ...],
                 check_decode: bool = True) -> list[str]:
    """-> list of problems (empty = entry pinned on every backend)."""
    name = entry["name"]
    profile = dict(entry["profile"])
    wanted = next(
        (bs for n, _, bs in ENTRIES if n == name), ("numpy",)
    )
    problems: list[str] = []
    L = entry["chunk_bytes"]
    ran = 0
    for backend in backends:
        if backend not in wanted:
            continue
        try:
            code = _mk_code(profile, backend)
        except Exception as e:
            # only the native engine may be genuinely absent (no C++
            # toolchain); numpy and jax are always present in this
            # project, so a constructor failure there IS a regression —
            # a silent skip would make the equivalence gate vacuous
            if backend == "native":
                continue
            problems.append(f"{name}[{backend}]: unavailable: {e}")
            continue
        data = _data_for(name, code.k, L)
        try:
            enc = _encode(code, data, backend)
        except Exception as e:
            problems.append(f"{name}[{backend}]: encode raised: {e}")
            continue
        ran += 1
        got = _stripe_digest(enc)
        if got != entry["digest"]:
            problems.append(
                f"{name}[{backend}]: stripe digest {got[:16]}... != "
                f"corpus {entry['digest'][:16]}..."
            )
            continue
        if not check_decode:
            continue
        n = entry["n_chunks"]
        for erased in ERASURES:
            erased = [e for e in erased if e < n]
            if not erased or len(erased) > code.m:
                continue
            avail = {
                i: _to_np(enc[i]) for i in range(n) if i not in erased
            }
            try:
                dec = code.decode_chunks(set(erased), avail, L)
            except Exception as e:
                problems.append(
                    f"{name}[{backend}]: decode{erased} raised: {e}"
                )
                continue
            for i in erased:
                if not np.array_equal(_to_np(dec[i]), _to_np(enc[i])):
                    problems.append(
                        f"{name}[{backend}]: decode{erased} chunk {i} "
                        "bytes differ"
                    )
        # frozen decode-under-erasure cases: the rebuilt bytes of every
        # recorded pattern must reproduce the pinned digest — this is
        # what holds decode PLANS (cached inverses + schedules)
        # bit-exact, not just the encode path
        for case in entry.get("decode", ()):
            erased = list(case["erased"])
            avail = {
                i: _to_np(enc[i]) for i in range(n) if i not in erased
            }
            try:
                dec = code.decode_chunks(set(erased), avail, L)
            except Exception as e:
                problems.append(
                    f"{name}[{backend}]: decode case {erased} raised: {e}"
                )
                continue
            got = _rebuilt_digest(dec, erased)
            if got != case["digest"]:
                problems.append(
                    f"{name}[{backend}]: decode case {erased} digest "
                    f"{got[:16]}... != corpus {case['digest'][:16]}..."
                )
    if ran == 0:
        problems.append(f"{name}: no requested backend available")
    return problems


def verify(path: Path, backends: tuple[str, ...]) -> int:
    corpus = json.loads(path.read_text())
    problems: list[str] = []
    for entry in corpus["entries"]:
        problems += verify_entry(entry, backends)
    if problems:
        for p in problems:
            print(f"FAIL {p}")
        return 1
    print(f"ok: {len(corpus['entries'])} entries pinned on "
          f"{','.join(backends)}")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("create")
    c.add_argument("--out", default=str(DEFAULT_CORPUS))
    c.add_argument("--bytes", type=int, default=4096,
                   help="payload bytes per chunk (default 4096)")
    v = sub.add_parser("verify")
    v.add_argument("--in", dest="infn", default=str(DEFAULT_CORPUS))
    v.add_argument("--backends", default="numpy,native,jax")
    args = ap.parse_args(argv)
    if args.cmd == "create":
        create(Path(args.out), args.bytes)
        return 0
    return verify(Path(args.infn), tuple(args.backends.split(",")))


if __name__ == "__main__":
    raise SystemExit(main())
