"""Balancer module — modes, plans, and weight-set writing.

Semantics port of the reference mgr balancer's optimization surface
(reference pybind/mgr/balancer/module.py):

- `Plan` (:30-58): a named pending change set — an OSDMap Incremental
  for `upmap` mode, a compat weight-set (+ reweight nudges) for
  `crush-compat` mode — plus the MappingState it was computed against.
- `Balancer.do_upmap` (:964-1029): iterate the pools (shuffled for
  equal attention), handing each to the greedy optimizer
  (`balancer.upmap.calc_pg_upmaps`) with `upmap_max_deviation` until
  `upmap_max_optimizations` changes are spent; the resulting
  pg_upmap_items land in an `osd.incremental.Incremental`.
- `Balancer.do_crush_compat` (:1031-1190): iterative per-bucket
  weight-set adjustment — move each OSD's weight-set entry a `step`
  toward target/actual, renormalize per root, re-score through
  `calc_eval`, keep the best state, halve the step on bad/misplacing
  moves — finally written as a REAL `CrushMap.choose_args[-1]` entry
  (the compat weight-set), which both the host oracle and the batched
  JAX pipeline then consume on every subsequent mapping.
- `Balancer.execute` (:1192-1230): apply the plan — both modes flow
  through `osd.incremental.apply_incremental` (upmap items directly;
  the compat weight-set rides the incremental's new-crush blob).

Scores come from `mgr.eval.calc_eval`; rc conventions are the
reference's negative errnos.
"""

from __future__ import annotations

import copy
import errno

import numpy as np

from ceph_tpu import obs
from ceph_tpu.crush.codec import encode_crushmap
from ceph_tpu.crush.types import ChooseArgs, CrushMap
from ceph_tpu.mgr.eval import Eval, MappingState, calc_eval
from ceph_tpu.osd.incremental import Incremental, apply_incremental
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("mgr")

_L = obs.logger_for("mgr")
_L.add_u64("plans_computed", "optimization plans computed")
_L.add_u64("upmap_changes", "pg_upmap_items changes planned by do_upmap")
_L.add_u64("compat_iterations", "crush-compat weight-set iterations")
_L.add_u64("compat_bad_steps", "crush-compat iterations that worsened the score")
_L.add_time_avg("optimize_seconds", "wall time per optimize() call")

# module options and defaults (reference module.py MODULE_OPTIONS)
DEFAULT_OPTIONS: dict = {
    "mode": "upmap",
    "upmap_max_deviation": 5,        # osd_calc_pg_upmaps default
    "upmap_max_optimizations": 10,
    "crush_compat_max_iterations": 25,
    "crush_compat_step": 0.5,
    "crush_compat_metrics": "pgs,objects,bytes",
    "min_score": 0.0,
    "target_max_misplaced_ratio": 0.05,
    # sets | device | device_loop (balancer.state / balancer.upmap):
    # device_loop runs the WHOLE multi-round greedy inside one
    # lax.while_loop — a full plan per pool in one XLA dispatch
    "upmap_state_backend": "sets",
    # 0 = the reference-faithful sequential greedy; N>0 = the
    # candidate-batched optimizer (score N prospective changes per
    # vectorized dispatch, accept the best non-conflicting subset —
    # see balancer.upmap._run_batched; on device_loop it is the
    # per-round on-device candidate budget, default 16)
    "upmap_candidate_batch": 0,
}

MODES = ("none", "upmap", "crush-compat")


# -- compat weight-set <-> choose_args --------------------------------------

def get_compat_weight_set_weights(crush: CrushMap) -> dict[int, float]:
    """Per-OSD weights of the compat (-1) choose_args entry, position 0
    (reference module.py:90 get_compat_weight_set_weights).  Absent an
    entry, fall back to the current crush weights — the state the mon's
    `crush weight-set create-compat` would seed."""
    ca = crush.choose_args.get(-1)
    ws: dict[int, float] = {}
    shadows = {
        sid for per in crush.class_bucket.values() for sid in per.values()
    }
    for bid, b in crush.buckets.items():
        if bid in shadows:
            continue
        row = None
        if ca is not None:
            rows = ca.weight_sets.get(bid)
            if rows:
                row = rows[0]
        if row is None:
            row = b.weights
        for it, w in zip(b.items, row):
            if it >= 0:
                ws[it] = w / 0x10000
    return ws


def compat_ws_to_choose_args(
    crush: CrushMap, ws: dict[int, float]
) -> ChooseArgs:
    """Materialize per-OSD weight-set weights as a full per-bucket
    choose_args entry: device items take ws[osd]; bucket items take
    their subtree's weight-set sum, mirroring how the mon keeps compat
    weight-set internal-node weights consistent (reference
    CrushWrapper::choose_args_adjust_item_weight bubbling)."""
    ca = ChooseArgs()
    memo: dict[int, float] = {}

    def wsum(item: int) -> float:
        if item >= 0:
            return float(ws.get(item, 0.0))
        if item in memo:
            return memo[item]
        memo[item] = 0.0  # cycle guard
        b = crush.buckets.get(item)
        if b is not None:
            memo[item] = sum(wsum(it) for it in b.items)
        return memo[item]

    for bid, b in crush.buckets.items():
        row = []
        for it, w in zip(b.items, b.weights):
            if it >= 0:
                row.append(int(round(ws.get(it, w / 0x10000) * 0x10000)))
            else:
                row.append(int(round(wsum(it) * 0x10000)))
        ca.weight_sets[bid] = [row]
    return ca


# -- plans ------------------------------------------------------------------

class Plan:
    """A named pending optimization (reference module.py:30-58)."""

    def __init__(self, name: str, mode: str, ms: MappingState,
                 pools: list[str] | None = None):
        self.name = name
        self.mode = mode
        self.initial = ms
        self.pools = list(pools or [])
        # working map the optimizers mutate; initial stays pristine
        self.osdmap: OSDMap = copy.deepcopy(ms.osdmap)
        self.inc = Incremental(epoch=ms.osdmap.epoch + 1)
        self.compat_ws: dict[int, float] = {}
        self.osd_weights: dict[int, float] = {}
        # set by do_crush_compat on success: the accepted best state's
        # Eval, so callers need not re-map/re-score the final state
        # (a re-score hits _PIPE_CACHE — no recompile since the weight
        # tables became operands — but still re-maps every PG)
        self.final_eval: Eval | None = None

    def final_state(self) -> MappingState:
        """MappingState of the plan applied (same pg_stats table: stats
        belong to PGs, only the mapping changes are scored)."""
        return MappingState(
            self.osdmap, self.initial.pg_stats,
            desc=f"plan {self.name} final", mapper=self.initial.mapper,
        )

    def finalize_inc(self) -> Incremental:
        """Fill the Incremental so `execute` can apply it: upmap items
        are already recorded by do_upmap; a compat weight-set rides the
        new-crush blob (applied last, reference OSDMap.cc:2330-2341)."""
        if self.compat_ws:
            crush = self.osdmap.crush
            crush.choose_args[-1] = compat_ws_to_choose_args(
                crush, self.compat_ws
            )
            self.inc.crush = encode_crushmap(crush)
        for osd, w in self.osd_weights.items():
            self.inc.new_weight[osd] = int(round(w * 0x10000))
        return self.inc

    def show(self) -> str:
        out = [
            f"plan {self.name}",
            f"mode {self.mode}",
            f"pools {self.pools or 'all'}",
        ]
        if self.inc.new_pg_upmap_items or self.inc.old_pg_upmap_items:
            for pg in sorted(
                self.inc.new_pg_upmap_items, key=lambda p: (p.pool, p.seed)
            ):
                pairs = self.inc.new_pg_upmap_items[pg]
                out.append(
                    f"ceph osd pg-upmap-items {pg.pool}.{pg.seed:x} "
                    + " ".join(f"{a} {b}" for a, b in pairs)
                )
            for pg in sorted(
                self.inc.old_pg_upmap_items, key=lambda p: (p.pool, p.seed)
            ):
                out.append(f"ceph osd rm-pg-upmap-items {pg.pool}.{pg.seed:x}")
        if self.compat_ws:
            for osd in sorted(self.compat_ws):
                out.append(
                    f"ceph osd crush weight-set reweight-compat osd.{osd} "
                    f"{self.compat_ws[osd]:.6f}"
                )
        for osd in sorted(self.osd_weights):
            out.append(
                f"ceph osd reweight osd.{osd} {self.osd_weights[osd]:.6f}"
            )
        return "\n".join(out)


# -- the module -------------------------------------------------------------

class Balancer:
    """Mode dispatch + plan bookkeeping (reference module.py Module)."""

    def __init__(self, options: dict | None = None,
                 rng: np.random.Generator | None = None):
        self.options = dict(DEFAULT_OPTIONS)
        if options:
            self.options.update(options)
        self.rng = rng if rng is not None else np.random.default_rng(0)
        self.plans: dict[str, Plan] = {}
        self.last_eval: Eval | None = None

    def get_option(self, name: str):
        return self.options[name]

    # -- queries ----------------------------------------------------------
    def status(self) -> dict:
        return {
            "mode": self.get_option("mode"),
            "plans": sorted(self.plans),
            "last_score": (
                round(self.last_eval.score, 6) if self.last_eval else None
            ),
            "options": {
                k: v for k, v in self.options.items()
                if k in DEFAULT_OPTIONS
            },
        }

    def eval(self, ms: MappingState, pools: list[str] | None = None) -> Eval:
        pe = calc_eval(ms, pools)
        self.last_eval = pe
        return pe

    # -- planning ----------------------------------------------------------
    def plan_create(self, name: str, ms: MappingState,
                    pools: list[str] | None = None,
                    mode: str | None = None) -> Plan:
        mode = mode or self.get_option("mode")
        if mode not in MODES:
            raise ValueError(f"unknown mode {mode!r}")
        plan = Plan(name, mode, ms, pools)
        self.plans[name] = plan
        return plan

    def optimize(self, plan: Plan) -> tuple[int, str]:
        """Dispatch by mode (reference module.py:930-962)."""
        _L.inc("plans_computed")
        with obs.span("mgr.optimize", mode=plan.mode), \
                _L.time("optimize_seconds"):
            if plan.mode == "upmap":
                return self.do_upmap(plan)
            if plan.mode == "crush-compat":
                return self.do_crush_compat(plan)
            if plan.mode == "none":
                return -errno.ENOEXEC, "balancer mode is 'none'"
            return -errno.EINVAL, f"unrecognized mode {plan.mode!r}"

    # -- upmap mode --------------------------------------------------------
    def do_upmap(self, plan: Plan) -> tuple[int, str]:
        """reference module.py:964-1029."""
        from ceph_tpu.balancer.upmap import calc_pg_upmaps

        max_optimizations = int(self.get_option("upmap_max_optimizations"))
        max_deviation = int(self.get_option("upmap_max_deviation"))
        m = plan.osdmap
        if plan.pools:
            pools = [p for p in plan.pools if p in m.pool_name.values()]
        else:
            pools = sorted(m.pool_name.values())
        if not pools:
            return -errno.ENOENT, "No pools available"
        # equal (in)attention across invocations (module.py:984)
        self.rng.shuffle(pools)
        by_name = {v: k for k, v in m.pool_name.items()}
        total_did = 0
        left = max_optimizations
        use_tpu = plan.initial.mapper == "jax"
        # a shared ClusterState on the MappingState serves membership
        # rows from its version-tagged cache; the per-pool provider
        # declines pools whose working-copy overlays already diverged
        state = getattr(plan.initial, "state", None)
        rows_source = (state.rows_source_for(m)
                       if state is not None else None)
        for pool in pools:
            pid = by_name[pool]
            with obs.span("mgr.do_upmap_pool", pool=pid, left=left):
                res = calc_pg_upmaps(
                    m, max_deviation=max_deviation, max_iter=left,
                    only_pools={pid}, use_tpu=use_tpu, rng=self.rng,
                    backend=self.get_option("upmap_state_backend"),
                    rows_source=rows_source,
                    candidate_batch=int(
                        self.get_option("upmap_candidate_batch")),
                )
            did = res.num_changed
            for pg, items in res.new_pg_upmap_items.items():
                plan.inc.new_pg_upmap_items[pg] = list(items)
                plan.inc.old_pg_upmap_items.discard(pg)
            for pg in res.old_pg_upmap_items:
                if pg in plan.inc.new_pg_upmap_items:
                    del plan.inc.new_pg_upmap_items[pg]
                plan.inc.old_pg_upmap_items.add(pg)
            total_did += did
            left -= did
            if left <= 0:
                break
        _L.inc("upmap_changes", total_did)
        _log(10, f"do_upmap: {total_did} changes over {len(pools)} pools")
        if total_did == 0:
            return -errno.EALREADY, (
                "Unable to find further optimization, or pools' "
                "pg_num is decreasing, or distribution is already perfect"
            )
        return 0, ""

    # -- crush-compat mode -------------------------------------------------
    def do_crush_compat(self, plan: Plan) -> tuple[int, str]:
        """reference module.py:1031-1190."""
        max_iterations = int(self.get_option("crush_compat_max_iterations"))
        if max_iterations < 1:
            return -errno.EINVAL, '"crush_compat_max_iterations" must be >= 1'
        step = float(self.get_option("crush_compat_step"))
        if step <= 0 or step >= 1.0:
            return -errno.EINVAL, (
                '"crush_compat_step" must be in (0, 1)'
            )
        max_misplaced = float(self.get_option("target_max_misplaced_ratio"))
        min_score = float(self.get_option("min_score"))

        ms = plan.initial
        m = plan.osdmap
        pe = self.eval(ms, plan.pools)
        if pe.score <= min_score:
            if pe.score == 0:
                return -errno.EALREADY, "Distribution is perfect"
            return -errno.EALREADY, (
                f"score {pe.score:.6f} <= min_score {min_score:.6f}, "
                "will not optimize"
            )

        orig_osd_weight = {
            osd: ms.osdmap.get_weightf(osd)
            for osd in range(ms.osdmap.max_osd)
        }
        orig_choose_args = m.crush.choose_args.get(-1)
        orig_ws = get_compat_weight_set_weights(m.crush)
        orig_ws = {a: b for a, b in orig_ws.items() if a >= 0}

        # roots must not share devices (module.py:1060-1075)
        visited: dict[int, str] = {}
        overlap: dict[int, list[str]] = {}
        for root, wm in pe.target_by_root.items():
            for osd in wm:
                if osd in visited:
                    overlap.setdefault(osd, [visited[osd]]).append(root)
                visited[osd] = root
        if overlap:
            return -errno.EOPNOTSUPP, (
                f"Some osds belong to multiple subtrees: {overlap}"
            )

        metrics = str(self.get_option("crush_compat_metrics")).split(",")
        key = metrics[0]  # balancing by the first metric (module.py:1082)
        if key not in ("pgs", "objects", "bytes"):
            return -errno.EINVAL, (
                f"unknown metric type {key!r}"
            )

        roots = sorted(pe.target_by_root)
        best_ws = dict(orig_ws)
        best_ow = dict(orig_osd_weight)
        best_pe = pe
        left = max_iterations
        bad_steps = 0
        next_ws = dict(best_ws)
        next_ow = dict(best_ow)
        while left > 0:
            _L.inc("compat_iterations")
            self.rng.shuffle(roots)
            for root in roots:
                target = best_pe.target_by_root[root]
                actual = best_pe.actual_by_root[root][key]
                queue = sorted(
                    actual.keys(),
                    key=lambda osd: (-abs(target[osd] - actual[osd]), osd),
                )
                for osd in queue:
                    if orig_osd_weight.get(osd, 0) == 0:
                        continue  # skip out osds (module.py:1106)
                    deviation = target[osd] - actual[osd]
                    if deviation == 0:
                        break
                    weight = best_ws[osd]
                    ow = orig_osd_weight[osd]
                    if actual[osd] > 0:
                        calc_weight = target[osd] / actual[osd] * weight * ow
                    else:
                        # newly created osds absorb `step` of their
                        # target on the next iteration (module.py:1118)
                        calc_weight = target[osd]
                    new_weight = weight * (1.0 - step) + calc_weight * step
                    next_ws[osd] = new_weight
                    if ow < 1.0:
                        next_ow[osd] = min(
                            1.0, max(step + (1.0 - step) * ow, ow + 0.005)
                        )
                # normalize weight-set sum back to the root's crush
                # weight (module.py:1135-1146)
                root_id = pe.root_ids[root]
                rb = m.crush.buckets.get(root_id)
                root_weight = (rb.weight / 0x10000) if rb else 0.0
                root_sum = sum(
                    b for a, b in next_ws.items() if a in target
                )
                if root_sum > 0 and root_weight > 0:
                    factor = root_sum / root_weight
                    for osd in actual:
                        next_ws[osd] = next_ws[osd] / factor

            # recalc with the candidate weight-set applied
            plan.compat_ws = dict(next_ws)
            plan.osd_weights = {
                osd: w for osd, w in next_ow.items()
                if w != orig_osd_weight.get(osd)
            }
            m.crush.choose_args[-1] = compat_ws_to_choose_args(
                m.crush, next_ws
            )
            for osd, w in next_ow.items():
                m.osd_weight[osd] = int(round(w * 0x10000))
            next_ms = plan.final_state()
            next_pe = self.eval(next_ms, plan.pools)
            next_misplaced = next_ms.misplaced_from(ms)
            _log(10, f"Step result score {best_pe.score:.6f} -> "
                     f"{next_pe.score:.6f}, misplacing {next_misplaced:.4f}")

            if next_misplaced > max_misplaced:
                if best_pe.score < pe.score:
                    break  # good enough; stop before misplacing more
                step /= 2.0
                next_ws = dict(best_ws)
                next_ow = dict(best_ow)
            elif next_pe.score > best_pe.score * 1.0001:
                # score got worse (module.py:1168-1178)
                _L.inc("compat_bad_steps")
                bad_steps += 1
                if bad_steps < 5 and int(self.rng.integers(0, 100)) < 70:
                    pass  # take another step anyway
                else:
                    step /= 2.0
                    next_ws = dict(best_ws)
                    next_ow = dict(best_ow)
                    bad_steps = 0
            else:
                bad_steps = 0
                best_pe = next_pe
                best_ws = dict(next_ws)
                best_ow = dict(next_ow)
                if best_pe.score == 0:
                    break
            left -= 1

        # a small regression is allowed while phasing out reweights
        # (module.py:1183-1186)
        fudge = 0.001 if best_ow != orig_osd_weight else 0.0

        if best_pe.score < pe.score + fudge:
            plan.compat_ws = best_ws
            plan.osd_weights = {
                osd: w for osd, w in best_ow.items()
                if w != orig_osd_weight.get(osd)
            }
            # leave the working map in the best state, not the last tried
            m.crush.choose_args[-1] = compat_ws_to_choose_args(
                m.crush, best_ws
            )
            for osd, w in best_ow.items():
                m.osd_weight[osd] = int(round(w * 0x10000))
            plan.final_eval = best_pe
            _log(10, f"do_crush_compat: score {pe.score:.6f} -> "
                     f"{best_pe.score:.6f}")
            return 0, ""
        # failure: the working map must match the (empty) plan, not the
        # last rejected candidate — restore the original weight-set and
        # reweights
        plan.compat_ws = {}
        plan.osd_weights = {}
        if orig_choose_args is None:
            m.crush.choose_args.pop(-1, None)
        else:
            m.crush.choose_args[-1] = orig_choose_args
        for osd, w in orig_osd_weight.items():
            m.osd_weight[osd] = int(round(w * 0x10000))
        return -errno.EDOM, (
            "Unable to find further optimization, change balancer "
            "mode and retry might help"
        )

    # -- execution ---------------------------------------------------------
    def execute(self, plan: Plan, m: OSDMap,
                state=None) -> tuple[int, str]:
        """Apply the plan to `m` through the epoch-delta machinery
        (reference module.py:1192-1230 issues mon commands; here the
        plan IS an Incremental and application is apply_incremental).
        With `state` (the ClusterState owning `m`) the delta ALSO lands
        on device in O(delta): upmap plans scatter into the overlay
        fixups, compat weight-sets upload their pos_weights planes —
        no re-key, no full rebuild."""
        inc = plan.finalize_inc()
        if inc.epoch != m.epoch + 1:
            return -errno.ESTALE, (
                f"plan epoch {inc.epoch} != map epoch {m.epoch}+1 "
                "(map changed since the plan was computed)"
            )
        with obs.span("mgr.execute", plan=plan.name, mode=plan.mode):
            if state is not None and state.m is m:
                state.apply(inc)
            else:
                apply_incremental(m, inc)
        self._diagnose_executed(plan, m)
        return 0, ""

    def _diagnose_executed(self, plan: Plan, m: OSDMap) -> None:
        """Post-execute decision accounting (CEPH_TPU_PLACEMENT_DIAG):
        run the instrumented pipeline over the plan's pools on the map
        the plan just produced, booking per-epoch bad-mapping /
        retry-exhaustion counts under source "mgr.<plan>" — the
        balancer-loop half of the placement flight recorder."""
        from ceph_tpu.utils import knobs

        if knobs.get("CEPH_TPU_PLACEMENT_DIAG", "0") != "1":
            return
        from ceph_tpu.obs import placement
        from ceph_tpu.osd.pipeline_jax import PoolMapper
        from ceph_tpu.runtime import DeviceLostError

        by_name = {v: k for k, v in m.pool_name.items()}
        pids = sorted(
            by_name[p] for p in (plan.pools or m.pool_name.values())
            if p in by_name
        )
        agg: dict = {"epoch": int(m.epoch), "mode": plan.mode}
        for pid in pids:
            # Diagnostics must never fail an execute whose incremental
            # already landed (same contract as ClusterSim._diagnose_epoch).
            try:
                placement.fold_summary(
                    agg, PoolMapper(m, pid).diagnose(record=False))
            except DeviceLostError as e:
                _log(1, f"device lost diagnosing pool {pid} ({e}); "
                        "skipping placement accounting")
                return
        placement.record(f"mgr.{plan.name}", agg)
