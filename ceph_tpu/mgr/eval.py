"""Distribution scoring — the balancer's Eval / calc_eval.

Semantics port of the reference mgr balancer's scoring pass
(reference pybind/mgr/balancer/module.py: `Eval` :60-130, `calc_stats`
:95-150, `calc_eval` :670-790): per pool and per CRUSH root, compare the
*actual* per-OSD distribution of PGs / objects / bytes against the
weight-proportional *target*, and reduce each (root, metric) pair to a
score in [0, 1) — 0 is a perfect distribution; the overall score is the
mean over roots and metrics.

The scoring formula is the reference's: for each overfull OSD the CDF of
the standard normal at the relative overfullness, weighted by the OSD's
target share (module.py:113-124 — erf-based so urgency saturates
steeply), plus the stddev of the weight-adjusted counts.

The expensive part — mapping every PG of every pool to build the actual
distributions — runs through the batched JAX pipeline (one XLA call per
pool, `osd.pipeline_jax.PoolMapper`); the reference iterates pg_dump.
Object/byte stats have no daemon to come from here, so `MappingState`
carries a per-PG stats table (synthesize one with `synthetic_pg_stats`);
stats belong to PGs, not mappings, so the same table must be shared by
the before/after states a plan is scored against.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ceph_tpu import obs
from ceph_tpu.balancer.crush_analysis import (
    find_takes_by_rule,
    get_rule_weight_osd_map,
)
from ceph_tpu.crush import mapper_ref
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgId

_L = obs.logger_for("mgr")
_L.add_u64("evals", "calc_eval passes")
_L.add_u64("eval_pgs_mapped", "PGs mapped while building eval distributions")
_L.add_time_avg("eval_seconds", "wall time per calc_eval pass")
_L.add_avg("score", "eval score after each calc_eval (0 = perfect)")

METRICS = ("pgs", "objects", "bytes")


def synthetic_pg_stats(
    m: OSDMap, objects_per_pg: int = 64, bytes_per_object: int = 4 << 20,
    seed: int = 0,
) -> dict[int, dict[str, np.ndarray]]:
    """Deterministic per-PG object/byte counts (the pg_dump stand-in).
    Mild spread (x0.5..x1.5 around the mean) so the objects/bytes scores
    are not degenerate copies of the pgs score."""
    out: dict[int, dict[str, np.ndarray]] = {}
    for pid, pool in sorted(m.pools.items()):
        rng = np.random.default_rng(seed * 1_000_003 + pid)
        objs = rng.integers(
            objects_per_pg // 2, objects_per_pg * 3 // 2 + 1,
            size=pool.pg_num, dtype=np.int64,
        )
        out[pid] = {"objects": objs, "bytes": objs * bytes_per_object}
    return out


class MappingState:
    """Snapshot the balancer scores: an OSDMap + per-PG stats + lazily
    computed per-pool `up` rows (reference module.py `MappingState`).

    mapper: "jax" maps each pool through the batched pipeline and keeps
    the rows DEVICE-RESIDENT — scoring and misplacement reduce on device
    (ceph_tpu.core.reduce) and only O(OSDs) vectors are fetched.  The
    pipeline runs overlay-free (so every balancer iteration shares the
    one compiled kernel regardless of accumulating pg_upmap entries); the
    few overlay-carrying PGs get exact host-computed rows scattered in,
    bit-identical to the overlay-gated kernel.  `PoolMapper` resolves
    `choose_args.get(pool_id, choose_args.get(-1))` exactly like the host
    oracle.  "host" walks `OSDMap.pg_to_up_acting_osds` (small maps,
    differential tests).
    """

    def __init__(self, osdmap: OSDMap, pg_stats=None, desc: str = "",
                 mapper: str = "jax", state=None, mesh=None):
        self.osdmap = osdmap
        self.desc = desc
        self.pg_stats = pg_stats or {}
        self.mapper = mapper
        # a shared `osd.state.ClusterState`: pools whose mapping inputs
        # match its version-tagged cache are served without any mapping
        # dispatch (the lifetime engine hands its own state in).  Rows
        # served from a meshed state arrive PG-sharded; the scoring
        # reductions below partition over them transparently.  `mesh`
        # shards the standalone mapping path the same way.
        self.state = state
        self.mesh = mesh if mesh is not None \
            else getattr(state, "mesh", None)
        self._up: dict[int, np.ndarray] = {}
        self._dev: dict[int, object] = {}

    def pool_up_device(self, pool_id: int):
        """[pg_num, W] i32 up rows as a DEVICE array (jax), overlay PGs
        fixed up from the host oracle."""
        rows = self._dev.get(pool_id)
        if rows is not None:
            return rows
        if self.state is not None:
            src = self.state.rows_source_for(self.osdmap)
            rows = src(pool_id) if src is not None else None
            if rows is not None:
                self._dev[pool_id] = rows
                return rows
        import jax.numpy as jnp

        from ceph_tpu.osd.pipeline_jax import PoolMapper, overlay_fixup_rows

        m = self.osdmap
        pool = m.pools[pool_id]
        n = pool.pg_num
        with obs.span("mgr.map_pool", pool=pool_id, pgs=n, mapper="jax"):
            pm = PoolMapper(m, pool_id, overlays=False, mesh=self.mesh)
            rows = pm.map_all_device()
            seeds, fix = overlay_fixup_rows(m, pool_id, int(rows.shape[1]))
            if len(seeds):
                rows = rows.at[jnp.asarray(seeds)].set(jnp.asarray(fix))
        _L.inc("eval_pgs_mapped", n)
        self._dev[pool_id] = rows
        return rows

    def pool_up(self, pool_id: int) -> np.ndarray:
        """[pg_num, W] i32 up rows, ITEM_NONE padded (host numpy)."""
        rows = self._up.get(pool_id)
        if rows is not None:
            return rows
        m = self.osdmap
        pool = m.pools[pool_id]
        if self.mapper == "jax":
            rows = np.asarray(self.pool_up_device(pool_id))
        else:
            with obs.span(
                "mgr.map_pool", pool=pool_id, pgs=pool.pg_num,
                mapper=self.mapper,
            ):
                rows = np.full((pool.pg_num, pool.size), ITEM_NONE, np.int32)
                for ps in range(pool.pg_num):
                    up, _, _, _ = m.pg_to_up_acting_osds(PgId(pool_id, ps))
                    rows[ps, : min(len(up), pool.size)] = up[: pool.size]
            _L.inc("eval_pgs_mapped", pool.pg_num)
        self._up[pool_id] = rows
        return rows

    def pool_counts(self, pool_id: int, o_pg: np.ndarray, b_pg: np.ndarray):
        """Per-OSD (pgs, objects, bytes) totals for one pool, reduced ON
        DEVICE from the device rows (mapper="jax"); only the O(OSDs)
        vectors cross to the host.  float64 scatter-adds of integer
        weights are exact below 2^53, so the result matches the host
        np.bincount path bit for bit."""
        import jax.numpy as jnp

        from ceph_tpu.core import reduce

        n_osd = max(int(self.osdmap.max_osd), 1)
        rows = self.pool_up_device(pool_id)
        with obs.span("mgr.pool_counts", pool=pool_id, osds=n_osd):
            c_pgs = reduce.osd_histogram(rows, n_osd, dtype=jnp.int64)
            c_obj = reduce.weighted_osd_histogram(rows, o_pg, n_osd)
            c_byt = reduce.weighted_osd_histogram(rows, b_pg, n_osd)
            return np.asarray(c_pgs), np.asarray(c_obj), np.asarray(c_byt)

    def misplaced_from(self, other: "MappingState") -> float:
        """Fraction of PG replica slots mapped differently than in
        `other` (the reference's calc_misplaced_from: misplaced objects /
        total; replica slots are the stand-in absent a pg_dump).
        Vectorized per-row membership (valid rows carry no duplicate
        OSDs, so elementwise not-a-member == set difference), chunked so
        the [chunk, W, W] comparison stays O(chunk) memory.  With both
        states on the jax mapper the comparison runs on device and only
        the scalar count is fetched."""
        moved = 0
        total = 0
        CH = 16384
        use_dev = self.mapper == "jax" and other.mapper == "jax"
        for pid, pool in sorted(self.osdmap.pools.items()):
            if pid not in other.osdmap.pools:
                continue
            n = pool.pg_num
            total += n * pool.size
            if use_dev:
                from ceph_tpu.core import reduce

                a = self.pool_up_device(pid)
                b = other.pool_up_device(pid)
                acc = 0
                for i in range(0, n, CH):
                    acc = acc + reduce.misplaced_lanes(
                        a[i:i + CH], b[i:i + CH]
                    )
                moved += int(acc)
                continue
            a = np.asarray(self.pool_up(pid))
            b = np.asarray(other.pool_up(pid))
            for i in range(0, n, CH):
                aa, bb = a[i:i + CH], b[i:i + CH]
                member = (bb[:, :, None] == aa[:, None, :]).any(axis=2)
                moved += int(
                    (~member & (bb != ITEM_NONE) & (bb >= 0)).sum()
                )
        return moved / total if total else 0.0


@dataclass
class Eval:
    """Scored distributions (reference module.py:60-130)."""

    ms: MappingState
    pool_name: dict[int, str] = field(default_factory=dict)
    pool_id: dict[str, int] = field(default_factory=dict)
    pool_roots: dict[str, list[str]] = field(default_factory=dict)
    root_pools: dict[str, list[str]] = field(default_factory=dict)
    root_ids: dict[str, int] = field(default_factory=dict)
    # target_by_root[root] = {osd: normalized weight fraction}
    target_by_root: dict[str, dict[int, float]] = field(default_factory=dict)
    count_by_pool: dict = field(default_factory=dict)
    count_by_root: dict = field(default_factory=dict)
    actual_by_pool: dict = field(default_factory=dict)
    actual_by_root: dict = field(default_factory=dict)
    total_by_pool: dict = field(default_factory=dict)
    total_by_root: dict = field(default_factory=dict)
    stats_by_pool: dict = field(default_factory=dict)
    stats_by_root: dict = field(default_factory=dict)
    score_by_pool: dict[str, float] = field(default_factory=dict)
    score_by_root: dict[str, dict[str, float]] = field(default_factory=dict)
    score: float = 0.0

    def calc_stats(self, count, target, total):
        """reference module.py:95-150.  `count[t][osd]`, `target[osd]`
        (fractions summing to 1 per root), `total[t]`."""
        num = max(len(target), 1)
        r = {}
        for t in METRICS:
            if total[t] == 0:
                r[t] = {
                    "avg": 0, "stddev": 0, "sum_weight": 0, "score": 0,
                }
                continue
            avg = float(total[t]) / float(num)
            dev = 0.0
            # score in [0, 1): erf of the relative overfullness of each
            # overweighted device, weighted by its target share
            # (module.py:113-124 — see the comment block there for why
            # erf over e.g. 1-e^-x: steeper saturation to 1)
            score = 0.0
            sum_weight = 0.0
            for k, v in count[t].items():
                if target.get(k):
                    adjusted = float(v) / target[k] / float(num)
                else:
                    adjusted = 0.0
                if adjusted > avg:
                    score += target[k] * math.erf(
                        ((adjusted - avg) / avg) / math.sqrt(2.0)
                    )
                    sum_weight += target[k]
                dev += (avg - adjusted) * (avg - adjusted)
            stddev = math.sqrt(dev / float(max(num - 1, 1)))
            score = score / max(sum_weight, 1)
            r[t] = {
                "avg": avg,
                "stddev": stddev,
                "sum_weight": sum_weight,
                "score": score,
            }
        return r

    def show(self, verbose: bool = False) -> str:
        ms = self.ms
        out = [f"[{ms.desc or 'current cluster'}] score {self.score:.6f}"]
        for root in sorted(self.score_by_root):
            s = self.score_by_root[root]
            out.append(
                f"  root {root!r:12} pools {self.root_pools.get(root)} "
                + " ".join(f"{t}={s[t]:.6f}" for t in METRICS)
            )
        if verbose:
            for pool in sorted(self.score_by_pool):
                out.append(
                    f"  pool {pool!r:12} score "
                    f"{self.score_by_pool[pool]:.6f}"
                )
            for root, tgt in sorted(self.target_by_root.items()):
                act = self.actual_by_root[root]["pgs"]
                for osd in sorted(tgt):
                    out.append(
                        f"    osd.{osd:<4} target {tgt[osd]:.4f} "
                        f"actual-pgs {act.get(osd, 0.0):.4f}"
                    )
        return "\n".join(out)


def calc_eval(ms: MappingState, pools: list[str] | None = None) -> Eval:
    """Build the scored distributions for `ms` (reference
    module.py:670-790 `calc_eval`).  `pools` restricts by pool name."""
    m = ms.osdmap
    pe = Eval(ms)
    _L.inc("evals")
    with obs.span("mgr.calc_eval"), _L.time("eval_seconds"):
        pool_rule: dict[str, int] = {}
        for pid, pool in sorted(m.pools.items()):
            name = m.pool_name.get(pid, f"pool{pid}")
            if pools and name not in pools:
                continue
            ruleno = mapper_ref.find_rule(
                m.crush, pool.crush_rule, int(pool.type), pool.size
            )
            if ruleno < 0:
                continue
            pe.pool_name[pid] = name
            pe.pool_id[name] = pid
            pool_rule[name] = ruleno
            pe.pool_roots[name] = []

        # roots + weight-proportional targets (adjusted = crush weight x
        # in/out reweight, the same weights calc_pg_upmaps balances to)
        for name, ruleno in pool_rule.items():
            for take in find_takes_by_rule(m.crush, ruleno):
                root = m.crush.item_names.get(take, str(take))
                pe.root_ids[root] = take
                if root not in pe.pool_roots[name]:
                    pe.pool_roots[name].append(root)
                pe.root_pools.setdefault(root, []).append(name)
                if root in pe.target_by_root:
                    continue
                wmap = get_rule_weight_osd_map(m.crush, ruleno)
                adj = {
                    osd: w * (m.get_weightf(osd) if osd < m.max_osd else 0.0)
                    for osd, w in wmap.items()
                }
                s = sum(adj.values())
                pe.target_by_root[root] = {
                    osd: (w / s if s > 0 else 0.0) for osd, w in adj.items()
                }

        # actual distributions: one batched mapping pass per pool
        for root in pe.target_by_root:
            pe.count_by_root[root] = {
                t: {osd: 0 for osd in pe.target_by_root[root]}
                for t in METRICS
            }
            pe.total_by_root[root] = {t: 0 for t in METRICS}
        for name, ruleno in pool_rule.items():
            pid = pe.pool_id[name]
            pool = m.pools[pid]
            n = pool.pg_num
            stats = ms.pg_stats.get(pid, {})
            objs = stats.get("objects")
            byts = stats.get("bytes")
            o_pg = (np.asarray(objs[:n], np.int64) if objs is not None
                    else np.ones(n, np.int64))
            b_pg = (np.asarray(byts[:n], np.int64) if byts is not None
                    else o_pg << 22)
            if ms.mapper == "jax":
                # device-resident reduction: the rows never cross to the
                # host, only the O(OSDs) count vectors do
                c_pgs, c_obj, c_byt = ms.pool_counts(pid, o_pg, b_pg)
            else:
                rows = np.asarray(ms.pool_up(pid))[:n]
                # vectorized per-OSD accumulation (the per-replica Python
                # loop dominated crush-compat wall time at scale); float64
                # bincount weights are exact below 2^53, far above any
                # per-OSD byte total these sims produce
                valid = (rows != ITEM_NONE) & (rows >= 0)
                row_idx = np.nonzero(valid)[0]
                osds = rows[valid].astype(np.int64)
                minlen = int(osds.max()) + 1 if osds.size else 1
                c_pgs = np.bincount(osds, minlength=minlen)
                c_obj = np.bincount(
                    osds, weights=o_pg[row_idx].astype(np.float64),
                    minlength=minlen,
                )
                c_byt = np.bincount(
                    osds, weights=b_pg[row_idx].astype(np.float64),
                    minlength=minlen,
                )
            present = np.nonzero(c_pgs)[0]
            cnt = {
                "pgs": {int(o): int(c_pgs[o]) for o in present},
                "objects": {int(o): int(round(c_obj[o]))
                            for o in present},
                "bytes": {int(o): int(round(c_byt[o])) for o in present},
            }
            tot = {t: sum(cnt[t].values()) for t in METRICS}
            pe.count_by_pool[name] = cnt
            pe.total_by_pool[name] = tot
            pe.actual_by_pool[name] = {
                t: {
                    osd: v / tot[t] if tot[t] else 0.0
                    for osd, v in cnt[t].items()
                }
                for t in METRICS
            }
            for root in pe.pool_roots[name]:
                rc = pe.count_by_root[root]
                rt = pe.total_by_root[root]
                for t in METRICS:
                    for osd, v in cnt[t].items():
                        if osd in rc[t]:
                            rc[t][osd] += v
                            rt[t] += v

        for root, rc in pe.count_by_root.items():
            rt = pe.total_by_root[root]
            pe.actual_by_root[root] = {
                t: {
                    osd: v / rt[t] if rt[t] else 0.0
                    for osd, v in rc[t].items()
                }
                for t in METRICS
            }
            pe.stats_by_root[root] = pe.calc_stats(
                rc, pe.target_by_root[root], rt
            )
            pe.score_by_root[root] = {
                t: pe.stats_by_root[root][t]["score"] for t in METRICS
            }

        for name in pool_rule:
            target = {}
            for root in pe.pool_roots[name]:
                target.update(pe.target_by_root[root])
            st = pe.calc_stats(
                pe.count_by_pool[name], target, pe.total_by_pool[name]
            )
            pe.stats_by_pool[name] = st
            pe.score_by_pool[name] = sum(
                st[t]["score"] for t in METRICS
            ) / 3.0

        # overall: mean over roots and metrics (module.py:786-790)
        pe.score = 0.0
        for root, vs in pe.score_by_root.items():
            pe.score += vs["pgs"] + vs["objects"] + vs["bytes"]
        if pe.score_by_root:
            pe.score /= 3 * len(pe.score_by_root)
        _L.observe("score", pe.score)
        obs.counter("mgr.score", pe.score)
    return pe
