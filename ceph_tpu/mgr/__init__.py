"""mgr — manager-module layer over the placement stack.

The reference runs balancing as a mgr module (`pybind/mgr/balancer/
module.py`): distribution scoring (`Eval`/`calc_eval`), `Plan` objects,
and two optimization modes — `upmap` (pg_upmap_items via the greedy
optimizer) and `crush-compat` (per-bucket choose_args weight-sets).
This package ports those brains over this framework's OSDMap/CRUSH
model, with the O(PGs) scoring work running through the batched JAX
pipeline.
"""

from ceph_tpu.mgr.eval import Eval, MappingState, calc_eval, synthetic_pg_stats
from ceph_tpu.mgr.module import Balancer, Plan, compat_ws_to_choose_args

__all__ = [
    "Balancer",
    "Eval",
    "MappingState",
    "Plan",
    "calc_eval",
    "compat_ws_to_choose_args",
    "synthetic_pg_stats",
]
