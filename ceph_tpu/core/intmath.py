"""Small integer helpers shared by the placement kernels.

- stable_mod: Ceph's power-of-two-friendly modulo used to fold the object
  hash onto pg_num (reference src/include/rados.h:96-102).  Chosen so that
  growing b from 2^(n-1) to 2^n moves each bucket's contents at most once.
- div_trunc_s64: C-style s64 division truncating toward zero (the semantics
  of div64_s64 used by straw2, reference src/crush/mapper.c:358).
"""

from __future__ import annotations

import numpy as np


def pg_mask_for(b: int) -> int:
    """bmask = next_pow2(b) - 1, e.g. b=12 -> 15 (pg_num_mask semantics,
    reference src/osd/osd_types.h calc_pg_masks)."""
    if b <= 0:
        return 0
    return (1 << (int(b) - 1).bit_length()) - 1


def stable_mod(x, b, bmask, xp=np):
    """ceph_stable_mod(x, b, bmask) (reference src/include/rados.h:96-102)."""
    x = xp.asarray(x).astype(xp.uint32)
    b = xp.asarray(b).astype(xp.uint32)
    bmask = xp.asarray(bmask).astype(xp.uint32)
    lo = x & bmask
    return xp.where(lo < b, lo, x & (bmask >> 1))


def div_trunc_int(a: int, w: int) -> int:
    """Scalar div64_s64: truncate toward zero, for Python ints (hot path of
    the host oracle; the array version below serves numpy/jax)."""
    q = abs(a) // abs(w)
    return -q if (a < 0) != (w < 0) else q


def div_trunc_s64(a, w, xp=np):
    """a // w truncating toward zero, on int64 (a may be negative, w > 0)."""
    if xp is np:
        a = np.asarray(a, dtype=np.int64)
        w = np.asarray(w, dtype=np.int64)
        q = np.abs(a) // np.abs(w)
        return np.where((a < 0) != (w < 0), -q, q).astype(np.int64)
    # jax: lax.div implements C truncating division for integers
    from jax import lax
    import jax.numpy as jnp

    return lax.div(jnp.asarray(a, jnp.int64), jnp.asarray(w, jnp.int64))
