"""Robert Jenkins 32-bit integer mix hash — CRUSH's only RNG.

Bit-exact with the C reference (reference src/crush/hash.c:12-89,
CRUSH_HASH_RJENKINS1).  Written once over a generic array namespace so the
same code runs under numpy (host oracle) and jax.numpy (vmapped TPU kernels):
every operation is a uint32 lattice op (wrapping sub, xor, shifts), which both
backends implement with identical wraparound semantics.

These are *vectorized*: all arguments broadcast, so hashing a [10M] batch of
PG seeds is one fused elementwise XLA kernel.
"""

from __future__ import annotations

import numpy as np

HASH_SEED = 1315423911  # 0x4E67C6A7, reference src/crush/hash.c:24


def _u32(xp, v):
    return xp.asarray(v).astype(xp.uint32)


def _wrapping(fn):
    """Silence numpy's scalar-overflow RuntimeWarnings: uint32 wraparound is
    the *point* of this hash.  No effect on the jax path."""

    def wrapper(*args, **kwargs):
        with np.errstate(over="ignore"):
            return fn(*args, **kwargs)

    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def _mix(a, b, c):
    """One round of Jenkins' 96-bit mix (reference src/crush/hash.c:12-22)."""
    a = (a - b) - c
    a = a ^ (c >> 13)
    b = (b - c) - a
    b = b ^ (a << 8)
    c = (c - a) - b
    c = c ^ (b >> 13)
    a = (a - b) - c
    a = a ^ (c >> 12)
    b = (b - c) - a
    b = b ^ (a << 16)
    c = (c - a) - b
    c = c ^ (b >> 5)
    a = (a - b) - c
    a = a ^ (c >> 3)
    b = (b - c) - a
    b = b ^ (a << 10)
    c = (c - a) - b
    c = c ^ (b >> 15)
    return a, b, c


_X = np.uint32(231232)
_Y = np.uint32(1232)


@_wrapping
def crush_hash32(a, xp=np):
    """hash of one u32 (reference src/crush/hash.c:26-35)."""
    a = _u32(xp, a)
    seed = xp.uint32(HASH_SEED)
    h = seed ^ a
    b = a
    x = _u32(xp, _X)
    y = _u32(xp, _Y)
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


@_wrapping
def crush_hash32_2(a, b, xp=np):
    """hash of two u32s (reference src/crush/hash.c:37-46)."""
    a = _u32(xp, a)
    b = _u32(xp, b)
    h = xp.uint32(HASH_SEED) ^ a ^ b
    x = _u32(xp, _X)
    y = _u32(xp, _Y)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


@_wrapping
def crush_hash32_3(a, b, c, xp=np):
    """hash of three u32s (reference src/crush/hash.c:48-59)."""
    a = _u32(xp, a)
    b = _u32(xp, b)
    c = _u32(xp, c)
    h = xp.uint32(HASH_SEED) ^ a ^ b ^ c
    x = _u32(xp, _X)
    y = _u32(xp, _Y)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


@_wrapping
def crush_hash32_4(a, b, c, d, xp=np):
    """hash of four u32s (reference src/crush/hash.c:61-73)."""
    a = _u32(xp, a)
    b = _u32(xp, b)
    c = _u32(xp, c)
    d = _u32(xp, d)
    h = xp.uint32(HASH_SEED) ^ a ^ b ^ c ^ d
    x = _u32(xp, _X)
    y = _u32(xp, _Y)
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


@_wrapping
def crush_hash32_5(a, b, c, d, e, xp=np):
    """hash of five u32s (reference src/crush/hash.c:75-90)."""
    a = _u32(xp, a)
    b = _u32(xp, b)
    c = _u32(xp, c)
    d = _u32(xp, d)
    e = _u32(xp, e)
    h = xp.uint32(HASH_SEED) ^ a ^ b ^ c ^ d ^ e
    x = _u32(xp, _X)
    y = _u32(xp, _Y)
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


def str_hash_rjenkins(data: bytes) -> int:
    """ceph_str_hash_rjenkins over a byte string (object-name hashing).

    Matches the reference's ceph_str_hash(CEPH_STR_HASH_RJENKINS, ...)
    (reference src/common/ceph_hash.cc) — Jenkins' lookup2-style hash over
    12-byte blocks with length folded into the tail mix.
    """
    a = np.uint32(0x9E3779B9)
    b = np.uint32(0x9E3779B9)
    c = np.uint32(0)  # previous hash / arbitrary value
    n = len(data)
    i = 0
    with np.errstate(over="ignore"):
        while n - i >= 12:
            a = a + np.uint32(int.from_bytes(data[i : i + 4], "little"))
            b = b + np.uint32(int.from_bytes(data[i + 4 : i + 8], "little"))
            c = c + np.uint32(int.from_bytes(data[i + 8 : i + 12], "little"))
            a, b, c = _mix(a, b, c)
            i += 12
        tail = data[i:]
        c = c + np.uint32(n)
        # tail bytes: a gets bytes 0-3, b gets 4-7, c gets 8-10 shifted <<8
        # (byte 11 of c is reserved for the length, as in lookup2)
        pad = tail + b"\x00" * (12 - len(tail))
        a = a + np.uint32(int.from_bytes(pad[0:4], "little"))
        b = b + np.uint32(int.from_bytes(pad[4:8], "little"))
        c = c + np.uint32(int.from_bytes(pad[8:11], "little") << 8)
        a, b, c = _mix(a, b, c)
    return int(c)
