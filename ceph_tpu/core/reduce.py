"""Shared device-resident reductions over mapping rows.

The reference tools all fold per-PG mappings into tiny per-OSD summaries
on the host as they loop (CrushTester's utilization histogram, reference
src/crush/CrushTester.cc:637-698; osdmaptool's pgs/primary counts,
src/tools/osdmaptool.cc:696-754; the balancer's deviation stddev,
src/osd/OSDMap.cc:4707-4732).  The batched pipeline produces the rows on
device, so fetching O(PGs) rows to re-reduce them on host wastes exactly
the transfer the batching saved.  These helpers do the same reductions ON
DEVICE — callers fetch only the O(OSDs) or O(1) results.  (Reductions
over data that already lives on host in O(OSDs) form — e.g. the
balancer's deviation bookkeeping over incrementally-maintained counts —
deliberately stay host-side; only row-shaped inputs belong here.)

All functions are plain traceable jax code (usable inside other jits);
none of them jit themselves.  `rows` is any integer array of OSD ids
where ITEM_NONE / negative values mark empty lanes.

Mesh contract: every reduction here is shape-polymorphic over a
PG-sharded input (rows committed to a `jax.sharding.Mesh` via
NamedSharding — see ceph_tpu.parallel.sharded): GSPMD partitions the
scatter-adds/compares per shard and all-reduces the tiny outputs, and
because the accumulations are exact (integer counts; float64 weighted
sums of integer values below 2^53) the partitioned result is
BIT-IDENTICAL to the single-device one — which is what lets the
sharded lifetime digest equal the unsharded digest.
"""

from __future__ import annotations

import jax.numpy as jnp

from ceph_tpu.crush.types import ITEM_NONE


def valid_lanes(rows):
    """Occupied lanes: not NONE, a real non-negative OSD id."""
    return (rows != ITEM_NONE) & (rows >= 0)


def osd_histogram(ids, n: int, extra_mask=None, dtype=jnp.int32):
    """Per-OSD counts via scatter-add; invalid lanes (ITEM_NONE pads, -1
    no-primary markers, out-of-range ids) fall off the end."""
    valid = valid_lanes(ids) & (ids < n)
    if extra_mask is not None:
        valid = valid & extra_mask
    idx = jnp.where(valid, jnp.clip(ids, 0, n - 1), n)
    return jnp.zeros(n + 1, dtype).at[idx.reshape(-1)].add(1)[:n]


def weighted_osd_histogram(rows, row_weight, n: int, extra_mask=None):
    """Per-OSD sums of a per-row weight: rows [N, W] of OSD ids,
    row_weight [N] broadcast across the W replica lanes.  float64
    accumulation — exact for integer-valued weights below 2^53 (objects /
    bytes totals), matching a host np.bincount bit for bit."""
    valid = valid_lanes(rows) & (rows < n)
    if extra_mask is not None:
        valid = valid & extra_mask
    idx = jnp.where(valid, jnp.clip(rows, 0, n - 1), n)
    w = jnp.broadcast_to(
        jnp.asarray(row_weight, jnp.float64)[:, None], rows.shape
    )
    w = jnp.where(valid, w, 0.0)
    return jnp.zeros(n + 1, jnp.float64).at[idx.reshape(-1)].add(
        w.reshape(-1)
    )[:n]


def result_sizes(rows, extra_mask=None):
    """Per-row count of occupied lanes (the tester's `result size`)."""
    valid = valid_lanes(rows)
    if extra_mask is not None:
        valid = valid & extra_mask
    return jnp.sum(valid.astype(jnp.int32), axis=-1)


def size_histogram(rows, max_size: int, extra_mask=None, dtype=jnp.int64):
    """Histogram of result_sizes over [0, max_size]."""
    sz = result_sizes(rows, extra_mask)
    return jnp.zeros(max_size + 1, dtype).at[
        jnp.clip(sz, 0, max_size)
    ].add(1)


def value_histogram(vals, max_value: int, extra_mask=None,
                    dtype=jnp.int64):
    """Histogram of small non-negative integer values over
    [0, max_value]; negative lanes (the diagnostics planes' -1 = no
    placement marker) fall off the end, and so do values above
    max_value — the reference histogram only increments when
    `ftotal <= len - 1` (mapper_ref.do_rule), so overflow is dropped,
    not clamped, to stay bit-identical with host collection."""
    valid = vals >= 0
    if extra_mask is not None:
        valid = valid & extra_mask
    valid = valid & (vals <= max_value)
    idx = jnp.where(valid, jnp.clip(vals, 0, max_value), max_value + 1)
    return jnp.zeros(max_value + 2, dtype).at[idx.reshape(-1)].add(
        1
    )[: max_value + 1]


def duplicate_rows(rows):
    """Per-row flag: the row carries the same OSD in two occupied lanes
    (the up/acting-set invariant a valid mapping can never violate —
    CRUSH rejects collisions, upmap refuses duplicate targets).  [N, W]
    -> bool [N]."""
    valid = valid_lanes(rows)
    eq = (rows[:, :, None] == rows[:, None, :]) \
        & valid[:, :, None] & valid[:, None, :]
    w = rows.shape[-1]
    upper = jnp.triu(jnp.ones((w, w), bool), k=1)
    return (eq & upper[None, :, :]).any(axis=(1, 2))


def moved_in_lanes(before, after):
    """Per-lane flag: occupied `after` lanes whose OSD is not a member
    of the same row in `before` (the elementwise form misplaced_lanes
    sums).  [N, W] x [N, W] -> bool [N, W]."""
    member = (after[:, :, None] == before[:, None, :]).any(axis=2)
    return ~member & valid_lanes(after)


def changed_rows(before, after):
    """Per-row flag: the row's occupied-OSD multiset changed between the
    two mappings (content-based — primary reordering alone does not
    count).  [N, W] x [N, W] -> bool [N]."""
    return moved_in_lanes(before, after).any(axis=-1) \
        | moved_in_lanes(after, before).any(axis=-1)


def misplaced_lanes(before, after, extra_mask=None):
    """Count of occupied `after` lanes whose OSD is not a member of the
    same row in `before` — the replica-slot form of the reference's
    calc_misplaced_from.  Valid rows carry no duplicate OSDs, so
    elementwise not-a-member == set difference.  [N, W] x [N, W] -> i64
    scalar (device); chunk the N axis host-side if W is wide enough for
    the [N, W, W] compare to matter."""
    moved = moved_in_lanes(before, after)
    if extra_mask is not None:
        moved = moved & extra_mask
    return jnp.sum(moved.astype(jnp.int64))
