"""Fixed-point log2 lookup tables + crush_ln — bit-exact with the reference.

crush_ln(x) computes 2^44 * log2(x+1) in pure integer arithmetic
(reference src/crush/mapper.c:247-290) using two lookup tables
(reference src/crush/crush_ln_table.h):

- RH_LH_TBL[2k]   = ceil(2^48 / (1 + k/128))       (reciprocal, "RH")
- RH_LH_TBL[2k+1] = floor(2^48 * log2(1 + k/128))  ("LH"; entry k=128 is
  capped at 0xffff00000000 rather than 2^48 in the reference — reproduced)
- LL_TBL[k]      ~= 2^48 * log2(1 + k/2^15)

The RH/LH halves are regenerated here from exact integer arithmetic (verified
element-wise against the reference table).  The LL table *cannot* be
regenerated from its nominal formula: the reference (which shares the table
with the Linux kernel) contains many entries that deviate from
round(2^48*log2(1+k/2^15)) — e.g. duplicated values like 0x...13ee805b — and
those deviations are load-bearing for bit-exact straw2 placement.  We
therefore ship the 256 s64 values as packed little-endian data.

Both a numpy path (host oracle) and a jax path (vmapped TPU kernel) are
provided; the jax path implements the count-leading-zeros normalization as a
5-step branchless binary search so the whole thing stays inside jit.
"""

from __future__ import annotations

import base64
import decimal
import math

import numpy as np


def _build_rh_lh() -> np.ndarray:
    tbl = np.zeros(258, dtype=np.int64)
    for k in range(129):
        num = (1 << 48) * 128
        den = 128 + k
        tbl[2 * k] = -((-num) // den)  # ceil division, exact
        if k == 0:
            lh = 0
        else:
            # floor(2^48*log2(1+k/128)); float64 is ~1 ulp short of exact at
            # this magnitude, so compute at 60 decimal digits.  The checksum
            # assert below catches any platform drift.
            with decimal.localcontext() as ctx:
                ctx.prec = 60
                v = (
                    decimal.Decimal(128 + k).ln() - decimal.Decimal(128).ln()
                ) / decimal.Decimal(2).ln() * (1 << 48)
                lh = int(v.to_integral_value(rounding=decimal.ROUND_FLOOR))
        tbl[2 * k + 1] = lh
    tbl[257] = 0x0000FFFF00000000  # reference quirk: capped, not 2^48
    return tbl


# reference src/crush/crush_ln_table.h:97-162, packed <q little-endian.
_LL_B64 = (
    "AAAAAAAAAAAACqbiAgAAAMVOtgwHAAAAZ85Q7wkAAAD9iOXRDAAAAJx+dLQPAAAAXq/9lhIAAABY"
    "G4F5FQAAAKHC/lsYAAAAUqV2PhsAAACAw+ggHgAAAEMdVQMhAAAAsrK75SMAAADkgxzIJgAAAPCQ"
    "d6opAAAA7dnMjCwAAADyXhxvLwAAABcgZlEyAAAAcR2qMzUAAAAaV+gVOAAAACbNIPg6AAAArn9T"
    "2j0AAADIboC8QAAAAIyap55DAAAAEAPJgEYAAABsqORiSQAAALaK+kRMAAAABqoKJ08AAAByBhUJ"
    "UgAAABOgGetUAAAA/XYYzVcAAABKixGvWgAAAA/dBJFdAAAAZGzycmAAAABgOdpUYwAAABpEvDZm"
    "AAAAqIyYGGkAAAAiE2/6awAAAJ/XP9xuAAAANdoKvnEAAAD9GtCfdAAAAAyaj4F3AAAAeldJY3oA"
    "AABeU/1EfQAAAM6NqyaAAAAA4wZUCIMAAACyvvbphQAAAFK1k8uIAAAA3OoqrYsAAABlX7yOjgAA"
    "AAUTSHCRAAAA0wXOUZQAAADlN04zlwAAAFOpyBSaAAAAM1o99pwAAACdSqzXnwAAAFg0f7CiAAAA"
    "aup4mqUAAAD7mdZ7qAAAAHCJLl2rAAAA47iAPq4AAABpKM0fsQAAABjYEwG0AAAACshU4rYAAABT"
    "+I/DuQAAAAxpxaS8AAAAShr1hb8AAAAmDB9nwgAAALY+Q0jFAAAAEbJhKcgAAABNZnoKywAAAIJb"
    "jevNAAAAyJGazNAAAAAzCaKt0wAAAN3Bo47WAAAA27ufb9kAAABE95VQ3AAAADB0hjHfAAAAtTJx"
    "EuIAAADqMlbz5AAAAOZ0NdTnAAAAwfgOteoAAACQvuKV7QAAAGzGsHbwAAAAahB5V/MAAACinDs4"
    "9gAAACpr+Bj5AAAAGnyv+fsAAACIz2Da/gAAAIxlDLsBAQAAPD6ymwQBAACvWVJ8BwEAAPy37FwK"
    "AQAAOlmBPQ0BAAB/PRAeEAEAAORkmf4SAQAAfs8c3xUBAABkfZq/GAEAAK1uEqAbAQAAcaOEgB4B"
    "AADGG/FgIQEAAMPXV0EkAQAAf9e4IScBAAAQGxQCKgEAAI6iaeIsAQAAD265wi8BAACqfQOjMgEA"
    "AHfRR4M1AQAAjGmGYzgBAAD/Rb9DOwEAAOlm8iM+AQAAXswfBEEBAAB4dkfkQwEAAEtlacRGAQAA"
    "8JiFpEkBAAB8EZyETAEAAAjPrGRPAQAAqdG3RFIBAAB2Gb0kVQEAAIemvARYAQAA8ni25FoBAADO"
    "kKrEXQEAADHumKRgAQAANJGBhGMBAADseWRkZgEAAHCoQURpAQAA1xwZJGwBAAC9Gcr2bQEAAKrX"
    "tuNxAQAARB59w3QBAAAcqz2jdwEAAEl++IJ6AQAA4petYn0BAAD+91xCgAEAAFg0f7CCAQAAGYyq"
    "AYYBAABGwEjhiAEAAFI74cCLAQAAUv1zoI4BAABdBgGAkQEAAItWiF+UAQAA8u0JP5cBAACqzIUe"
    "mgEAAMjy+/2cAQAAY2Bs3Z8BAACTFde8ogEAAG4SPJylAQAAC1ebe6gBAACA4/RaqwEAAOW3SDqu"
    "AQAAUNSWGbEBAADZON/4swEAAJXlIdi2AQAAm9pet7kBAAADGJaWvAEAAOOdx3W/AQAAUWzzVMIB"
    "AABlgxk0xQEAADbjORPIAQAA2YtU8soBAABnfWnRzQEAAPW3eLDQAQAAmjuCj9MBAABtCIZu1gEA"
    "AIYehE3ZAQAA+X18LNwBAADfJm8L3wEAAE4ZXOrhAQAAXVVDyeQBAAAj2ySo5wEAALWqAIfqAQAA"
    "K8TWZe0BAACdJ6dE8AEAAB/VcSPzAQAAysw2AvYBAACzDvbg+AEAAPOar7/7AQAAnnFjnv4BAADM"
    "khF9AQIAAJT+uVsEAgAADbVcOgcCAAASYm7ACQIAAGoCkfcMAgAAfJki1g8CAABYNH+wEgIAANio"
    "NJMVAgAAUCG1cRgCAAAX5S9QGwIAAI+nc2odAgAA7k4UDSECAAAs9X3rIwIAABPn4ckmAgAAuyRA"
    "qCkCAABOm2cjLAIAAKiD62QvAgAAG6U4QzICAACpEoAhNQIAAGnMwf83AgAApA47LDoCAABbgO4T"
    "PQIAAB8i6TVAAgAAJa+PeEMCAAA157RWRgIAAP5rZO1HAgAAmD3uEkwCAAAaXALxTgIAAJnHEM9R"
    "AgAAZU1kklQCAADuhRyLVwIAAPDYGWlaAgAAW4DuE10CAAAWZwMlYAIAAII4RZZiAgAAUyvW4GUC"
    "AADzAbe+aAIAAF4mkpxrAgAAqZj3Mm0CAADrWDdYcQIAADtnATZ0AgAAsMPFE3cCAABfboTxeQIA"
    "AGFnPc98AgAAy66AZX4CAACzRJ6KggIAADIpRmiFAgAAVVK/vYcCAABK3oQjiwIAAFuA7hONAgAA"
    "HyLpNZACAACCOEWWkgIAAGH7vZmWAgAAq3qjApkCAADJZLhUnAIAAIMQveqdAgAAtQucD6ICAABh"
    "XWDHpAIAAFVSv72nAgAA/NpWYKkCAADvFK89rAIAAMqeARuvAgAAgjhFlrICAAAP2CLQtQIAALMc"
    "R/q4AgAAE+cSkLoCAADMAUltvQIAAPZseUrAAgAApiikJ8MCAABMj14axgIAAPaR6OHIAgAAwj8C"
    "v8sCAABuPhaczgIAABOOJHnRAgAAxi4tVtQCAACdIDAz1wIAALBjLRDaAgAAFPgk7dwCAAA="
)

RH_LH_TBL = _build_rh_lh()
RH_LH_TBL.setflags(write=False)
# guard against platform/libm rounding drift in the floor-snap above: the
# reference table's exact content sum (verified against crush_ln_table.h)
assert int(RH_LH_TBL.sum()) & 0xFFFFFFFFFFFF == 0x4ED10B7A2217, hex(
    int(RH_LH_TBL.sum()) & 0xFFFFFFFFFFFF
)
LL_TBL = np.frombuffer(base64.b64decode(_LL_B64), dtype="<i8").astype(np.int64)
LL_TBL.setflags(write=False)
assert LL_TBL.shape == (256,) and int(LL_TBL.sum()) & 0xFFFFFFFF == 1238488602


def crush_ln_np(xin) -> np.ndarray:
    """Vectorized numpy crush_ln: 2^44*log2(xin+1), xin uint32 (<= 0xffff)."""
    x = np.asarray(xin, dtype=np.uint64) + 1
    # normalize: shift x left until bit 15/16 region is occupied
    masked = (x & np.uint64(0x1FFFF)).astype(np.uint32)
    need = (x & np.uint64(0x18000)) == 0
    # bits = clz32(masked) - 16  == 15 - floor(log2(masked)) for masked<0x8000
    fl = np.zeros(x.shape, dtype=np.uint64)
    m = masked.astype(np.uint64)
    for s in (16, 8, 4, 2, 1):
        g = m >= (np.uint64(1) << np.uint64(s))
        fl = np.where(g, fl + np.uint64(s), fl)
        m = np.where(g, m >> np.uint64(s), m)
    bits = np.where(need, np.uint64(15) - fl, np.uint64(0))
    x = np.where(need, x << bits, x)
    iexpon = np.uint64(15) - bits

    index1 = ((x >> np.uint64(8)) << np.uint64(1)).astype(np.int64)
    RH = RH_LH_TBL[index1 - 256].astype(np.uint64)
    LH = RH_LH_TBL[index1 + 1 - 256].astype(np.uint64)
    xl64 = (x * RH) >> np.uint64(48)
    index2 = (xl64 & np.uint64(0xFF)).astype(np.int64)
    LL = LL_TBL[index2].astype(np.uint64)
    result = iexpon << np.uint64(44)
    result = result + ((LH + LL) >> np.uint64(48 - 12 - 32))
    return result


_LN64K = None


def ln64k_table() -> np.ndarray:
    """Full 2^16-entry crush_ln table: LN64K[u] = crush_ln(u) for the only
    inputs the mapper ever feeds it (u = hash & 0xffff,
    reference src/crush/mapper.c:340).  One VMEM-resident gather replaces
    the normalize + two-table arithmetic per straw2 draw on device."""
    global _LN64K
    if _LN64K is None:
        t = crush_ln_np(np.arange(65536, dtype=np.uint32)).astype(np.int64)
        t.setflags(write=False)
        _LN64K = t
    return _LN64K


def crush_ln_jax(xin):
    """Same, for jax arrays inside jit/vmap (uint64 ops; requires x64)."""
    import jax.numpy as jnp

    rh_lh = jnp.asarray(RH_LH_TBL)
    ll = jnp.asarray(LL_TBL)
    x = xin.astype(jnp.uint64) + 1
    masked = x & 0x1FFFF
    need = (x & 0x18000) == 0
    fl = jnp.zeros(jnp.shape(x), dtype=jnp.uint64)
    m = masked
    for s in (16, 8, 4, 2, 1):
        g = m >= (1 << s)
        fl = jnp.where(g, fl + s, fl)
        m = jnp.where(g, m >> s, m)
    bits = jnp.where(need, 15 - fl, jnp.uint64(0))
    x = jnp.where(need, x << bits, x)
    iexpon = 15 - bits

    index1 = ((x >> 8) << 1).astype(jnp.int64)
    RH = rh_lh[index1 - 256].astype(jnp.uint64)
    LH = rh_lh[index1 + 1 - 256].astype(jnp.uint64)
    xl64 = (x * RH) >> 48
    index2 = (xl64 & 0xFF).astype(jnp.int64)
    LL = ll[index2].astype(jnp.uint64)
    return (iexpon << 44) + ((LH + LL) >> (48 - 12 - 32))


def crush_ln_scan_jax(xin):
    """crush_ln as a gather-free select-scan — the TPU hot-path form.

    XLA lowers data-dependent gathers on TPU to a serial scalar loop
    (~10 cycles/index; measured ~190ms for the 11.5M-lane ln64k gather one
    descent level needs), so the mapper replaces the table lookups with
    trace-time-unrolled select chains: 129 paired (RH,LH) selects + 256 LL
    selects of constant values, all VPU lane arithmetic that fuses into the
    surrounding straw2 kernel.  Bit-exact with crush_ln_np (tested over the
    full 2^16 input domain in tests/test_core_numerics.py).

    xin: int32/uint32 array of u = hash & 0xffff values (<= 0xffff).
    Returns int64 crush_ln values.
    """
    import jax.numpy as jnp

    x = jnp.asarray(xin).astype(jnp.int32) + 1  # in [1, 0x10000]
    # iexpon = min(floor(log2 x), 15); xn = x normalized into
    # [0x8000, 0x10000] (x = 0x10000 stays, hitting the capped k=128 row —
    # reference src/crush/mapper.c:261-271 + crush_ln_table.h quirk)
    iex = jnp.zeros_like(x)
    xs = x
    for s in (16, 8, 4, 2, 1):
        g = xs >= (1 << s)
        iex = iex + jnp.where(g, s, 0)
        xs = jnp.where(g, xs >> s, xs)
    iexpon = jnp.minimum(iex, 15)
    xn = x << jnp.clip(15 - iex, 0, 15)
    k = (xn >> 8) - 128  # RH/LH row, in [0, 128]

    # paired (RH, LH) select-scan over the 129 rows
    rh = jnp.full(k.shape, int(RH_LH_TBL[0]), jnp.int64)
    lh = jnp.full(k.shape, int(RH_LH_TBL[1]), jnp.int64)
    for i in range(1, 129):
        m = k == i
        rh = jnp.where(m, jnp.int64(int(RH_LH_TBL[2 * i])), rh)
        lh = jnp.where(m, jnp.int64(int(RH_LH_TBL[2 * i + 1])), lh)

    xl64 = (xn.astype(jnp.int64) * rh) >> 48
    j = (xl64 & 0xFF).astype(jnp.int32)
    ll = jnp.full(j.shape, int(LL_TBL[0]), jnp.int64)
    for i in range(1, 256):
        ll = jnp.where(j == i, jnp.int64(int(LL_TBL[i])), ll)

    return (iexpon.astype(jnp.int64) << 44) + ((lh + ll) >> 4)


_OH_TBL1 = None  # [129, 5] f32: rh limbs 24/24/1, lh limbs 24/24
_OH_TBL2 = None  # [256, 2] f32: ll limbs 24/24


def _onehot_tables():
    global _OH_TBL1, _OH_TBL2
    if _OH_TBL1 is None:
        rh = RH_LH_TBL[0::2][:129].astype(np.int64)
        lh = RH_LH_TBL[1::2][:129].astype(np.int64)
        _OH_TBL1 = np.stack(
            [
                (rh & 0xFFFFFF).astype(np.float32),
                ((rh >> 24) & 0xFFFFFF).astype(np.float32),
                (rh >> 48).astype(np.float32),
                (lh & 0xFFFFFF).astype(np.float32),
                ((lh >> 24) & 0xFFFFFF).astype(np.float32),
            ],
            axis=1,
        )
        _OH_TBL2 = np.stack(
            [
                (LL_TBL & 0xFFFFFF).astype(np.float32),
                ((LL_TBL >> 24) & 0xFFFFFF).astype(np.float32),
            ],
            axis=1,
        )
    return _OH_TBL1, _OH_TBL2


def crush_ln_onehot_jax(xin):
    """crush_ln as one-hot MXU matmuls — the large-batch TPU hot-path form.

    Same normalize arithmetic as crush_ln_scan_jax, but the RH/LH and LL
    table lookups contract a one-hot row vector against the tables split
    into 24-bit limb planes: f32 holds any 24-bit integer exactly and a
    one-hot contraction touches exactly one row, so reconstruction is
    bit-exact while the lookup cost rides the MXU instead of a serialized
    VPU select chain.  Bit-exact with crush_ln_np over the full 2^16 input
    domain (tests/test_core_numerics.py).
    """
    import jax.numpy as jnp

    t1, t2 = _onehot_tables()
    x = jnp.asarray(xin).astype(jnp.int32) + 1  # in [1, 0x10000]
    iex = jnp.zeros_like(x)
    xs = x
    for s in (16, 8, 4, 2, 1):
        g = xs >= (1 << s)
        iex = iex + jnp.where(g, s, 0)
        xs = jnp.where(g, xs >> s, xs)
    iexpon = jnp.minimum(iex, 15)
    xn = x << jnp.clip(15 - iex, 0, 15)
    k = (xn >> 8) - 128  # RH/LH row, in [0, 128]

    oh1 = (k[..., None] == jnp.arange(129, dtype=jnp.int32)).astype(
        jnp.float32
    )
    v1 = jnp.matmul(
        oh1, jnp.asarray(t1), precision="highest", preferred_element_type=jnp.float32
    )  # [..., 5]
    rh = (
        v1[..., 0].astype(jnp.int64)
        + (v1[..., 1].astype(jnp.int64) << 24)
        + (v1[..., 2].astype(jnp.int64) << 48)
    )
    lh = v1[..., 3].astype(jnp.int64) + (v1[..., 4].astype(jnp.int64) << 24)

    # bits 48..55 of xn*rh; two's-complement wrap preserves the low 64 bits
    # so s64 multiply is safe even when the product reaches 2^63
    j = ((xn.astype(jnp.int64) * rh) >> 48).astype(jnp.int32) & 0xFF
    oh2 = (j[..., None] == jnp.arange(256, dtype=jnp.int32)).astype(
        jnp.float32
    )
    v2 = jnp.matmul(
        oh2, jnp.asarray(t2), precision="highest", preferred_element_type=jnp.float32
    )  # [..., 2]
    ll = v2[..., 0].astype(jnp.int64) + (v2[..., 1].astype(jnp.int64) << 24)

    return (iexpon.astype(jnp.int64) << 44) + ((lh + ll) >> 4)


def crush_ln(xin, xp=np):
    if xp is np:
        return crush_ln_np(xin)
    return crush_ln_jax(xin)
