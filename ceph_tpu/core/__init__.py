from ceph_tpu.core.rjenkins import (
    crush_hash32,
    crush_hash32_2,
    crush_hash32_3,
    crush_hash32_4,
    crush_hash32_5,
    HASH_SEED,
)
from ceph_tpu.core.lntable import crush_ln, RH_LH_TBL, LL_TBL
from ceph_tpu.core.intmath import (
    stable_mod,
    div_trunc_s64,
    div_trunc_int,
    pg_mask_for,
)
