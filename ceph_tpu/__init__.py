"""ceph_tpu — a TPU-native framework for Ceph's compute-bound hot paths.

Re-implements, from scratch and TPU-first (JAX/XLA/Pallas), the two
embarrassingly-parallel kernels of the Ceph reference
(/root/reference, juztas/ceph):

1. CRUSH placement — the PG->OSD mapping pipeline
   (OSDMap::_pg_to_raw_osds -> crush_do_rule -> bucket_straw2_choose),
   batched over millions of PGs as one vmapped/pjit-sharded XLA call.
2. Erasure coding — Reed-Solomon / Clay encode+decode as batched GF(2^8)
   linear algebra on the MXU (bit-plane GF(2) matmuls / Pallas kernels).

All placement math is bit-exact with the C reference semantics
(src/crush/mapper.c, src/crush/hash.c, src/osd/OSDMap.cc), which is the
correctness oracle; architecture is idiomatic JAX, not a port.

The whole domain is integer math (uint32 hashes, s64 fixed-point logs), so
the package enables jax_enable_x64 at import.
"""

import os
import sys

if "jax" in sys.modules:
    # jax already loaded (e.g. the axon sitecustomize registered the TPU
    # backend at interpreter start) — flip the config flag directly.
    import jax

    jax.config.update("jax_enable_x64", True)
else:
    # Defer the ~4s jax import for jax-free entry points (CLI tools, the
    # codec/compiler layers are numpy-only); jax reads this env var when
    # it eventually loads.  x64 is load-bearing — an inherited
    # JAX_ENABLE_X64=0 would silently downcast the s64 straw2/hash math
    # to 32-bit — so we override, but warn when clobbering an explicit
    # conflicting setting; ensure_jax_backend() re-verifies the flag took.
    _prev = os.environ.get("JAX_ENABLE_X64")
    if _prev is not None and _prev.lower() not in (
        "true", "1", "y", "yes", "t", "on"
    ):
        import warnings

        warnings.warn(
            f"ceph_tpu requires 64-bit jax types; overriding "
            f"JAX_ENABLE_X64={_prev!r} with 'true' process-wide",
            stacklevel=2,
        )
    os.environ["JAX_ENABLE_X64"] = "true"

__version__ = "0.1.0"
