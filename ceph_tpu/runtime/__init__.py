"""Runtime robustness layer — how every entry point gets a backend.

The r01–r05 benchmark history is a catalogue of runs killed by the
runtime, not the math: TPU init hanging 90–240 s, stage-deadline
starvation leaving the north-star rebalance stage blank, a wedged chip
taking the whole process with it.  This package is the survivability
layer those runs lacked:

    from ceph_tpu import runtime

    info = runtime.acquire_backend()        # preflight + degradation
    info.provenance()                       # -> BENCH/MULTICHIP JSON

- `preflight` — watchdogged subprocess probe of `jax.devices()` (a hang
  costs the timeout, not the run), failure diagnosis (stale chip-holding
  process, libtpu lockfile, transport env), compile-cache pre-warm.
- `ladder` — the tpu → cpu → native degradation policy with bounded
  retries, exponential backoff + jitter, and full provenance (backend,
  fallback_reason, attempts, init_seconds) recorded in perf counters.
- `scheduler` — deadline-budgeted priority stage scheduler with atomic
  checkpoint/resume (the BENCH_partial.json shape) and per-stage
  watchdogs.
- `faults` — deterministic fault injection (CEPH_TPU_FAULTS) so every
  retry/backoff/degradation/resume path runs in fast CPU-only tests.

Importing this package is cheap: no jax import until a probe runs.
"""

from __future__ import annotations

from ceph_tpu.runtime import faults
from ceph_tpu.runtime.faults import DeviceLostError, FaultInjected
from ceph_tpu.runtime.ladder import (
    BackendInfo,
    RequiredBackendError,
    acquire_backend,
    default_ladder,
    last_provenance,
)
from ceph_tpu.runtime.preflight import (
    ProbeResult,
    diagnose_init_failure,
    prewarm_compile_cache,
    probe,
)
from ceph_tpu.runtime.scheduler import (
    Checkpoint,
    Stage,
    StageHandle,
    StageScheduler,
)

__all__ = [
    "BackendInfo",
    "Checkpoint",
    "DeviceLostError",
    "FaultInjected",
    "ProbeResult",
    "RequiredBackendError",
    "Stage",
    "StageHandle",
    "StageScheduler",
    "acquire_backend",
    "default_ladder",
    "diagnose_init_failure",
    "faults",
    "last_provenance",
    "prewarm_compile_cache",
    "probe",
]
