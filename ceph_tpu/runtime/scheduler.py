"""Deadline-budgeted stage scheduler with atomic checkpoint/resume.

The survivability pattern bench.py grew ad hoc (flush-per-stage partial
JSON, self-budgeting against a wall-clock deadline), promoted to a
reusable component and extended with the two pieces it lacked:

- **Priority scheduling against the deadline.**  Stages declare a
  priority and a cost estimate; the scheduler runs them highest priority
  first and *skips* (recording why) any stage whose minimum budget no
  longer fits in the remaining deadline.  The north-star rebalance stage
  outranks the slow headline stage, so a pathological headline run can
  no longer starve it — the starvation that kept BENCH r01–r05's
  rebalance numbers blank.

- **Checkpoint/resume.**  Every completed stage is written atomically
  (tmp + rename, the BENCH_partial.json shape, perf registry and trace
  embedded per flush).  Reopening the checkpoint with `resume=True`
  skips stages already done — `bench.py --resume` after a mid-run kill
  finishes the remainder instead of restarting from zero.

- **Watchdogged dispatch.**  A stage runs on a worker thread; if it
  exceeds its soft timeout the scheduler records the overrun, abandons
  the thread (daemonized — a wedged device call cannot be cancelled, but
  it no longer owns the run), and moves on.  Late results from an
  abandoned stage are discarded, never checkpointed.

Fault points (runtime.faults): `stage[.<name>]` fires on the stage
thread as it starts (arm `overrun:<s>` to trip the watchdog, `lost` for
mid-stage device loss); `stage_end[.<name>]` fires after the checkpoint
flush (arm `exit:<rc>` for kill/resume tests).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

from ceph_tpu.runtime import faults
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("runtime")


def _counters():
    from ceph_tpu import obs

    L = obs.logger_for("runtime")
    L.add_u64("stages_run", "stages executed to completion")
    L.add_u64("stages_failed", "stages that raised")
    L.add_u64("stages_skipped_budget", "stages skipped: deadline budget")
    L.add_u64("stages_skipped_resume", "stages skipped: already done")
    L.add_u64("stage_overruns", "stages abandoned by the watchdog")
    return L


class Checkpoint:
    """Atomic JSON stage store (the BENCH_partial.json shape).

    Every flush embeds the perf registry (latest snapshot top-level, a
    per-stage snapshot inside each stage record) and rewrites the
    CEPH_TPU_TRACE file, so a deadline-killed or hung run leaves a full
    diagnostic record.  `resume=True` loads an existing file so a re-run
    can skip completed stages."""

    def __init__(self, path: Path | str, resume: bool = False):
        self.path = Path(path)
        self.data: dict = {"stages_done": []}
        self._lock = threading.RLock()
        if resume:
            try:
                prev = json.loads(self.path.read_text())
            except (OSError, ValueError):
                prev = None
            if isinstance(prev, dict) and "stages_done" in prev:
                self.data = prev
                self.data["resumed"] = self.data.get("resumed", 0) + 1

    def done(self, name: str) -> bool:
        with self._lock:
            return name in self.data["stages_done"]

    def put(self, name: str, value) -> None:
        from ceph_tpu import obs

        with self._lock:
            if isinstance(value, dict):
                value = dict(value, perf=obs.perf_dump())
            self.data[name] = value
            if name not in self.data["stages_done"]:
                self.data["stages_done"].append(name)
            self.flush()
        _log(5, f"stage {name} checkpointed")

    def progress(self, name: str, value) -> None:
        """Mid-stage partial result: stored + flushed, NOT marked done
        (a killed worker keeps the partial; resume re-runs the stage)."""
        with self._lock:
            self.data[name] = value
            self.flush()

    def fail(self, name: str, err: BaseException | str) -> None:
        msg = (err if isinstance(err, str)
               else f"{type(err).__name__}: {err}"[:300])
        with self._lock:
            self.data.setdefault("errors", {})[name] = msg
            self.flush()
        _log(1, f"stage {name} FAILED: {msg[:200]}")

    def flush(self) -> None:
        from ceph_tpu import obs

        with self._lock:
            self.data["perf"] = obs.perf_dump()
            try:
                # SIGKILL survival: last flush before a kill wins
                tp = obs.flush()
                if tp:
                    self.data["trace"] = tp
            except OSError as e:
                # a bad CEPH_TPU_TRACE path must not kill the run (or
                # mask the stage error that routed through fail())
                self.data["trace_error"] = f"{type(e).__name__}: {e}"[:200]
            tmp = self.path.with_suffix(".tmp")
            tmp.write_text(json.dumps(self.data))
            tmp.replace(self.path)


@dataclass
class Stage:
    name: str
    fn: Callable[["StageHandle"], object]
    priority: int = 50  # higher runs earlier
    est_s: float = 30.0  # cost estimate (budgeting)
    min_budget_s: float | None = None  # default: est_s
    soft_timeout_s: float | None = None  # None = rest of the deadline
    reserve_s: float = 0.0  # deadline left for LATER stages: the
    # watchdog abandons this stage early enough that reserve_s of
    # wall-clock survives it (a cooperative in-stage budget check can't
    # help when a single step overruns, cf. BENCH r06: a 10M-PG round
    # ate the whole deadline before its first between-rounds check)
    order: int = 0  # declaration order (priority tiebreak)


class StageHandle:
    """What a running stage sees: progress flushing + remaining budget,
    both safe against the stage being abandoned by the watchdog."""

    def __init__(self, sched: "StageScheduler", stage: Stage):
        self._sched = sched
        self._stage = stage
        self.abandoned = threading.Event()

    @property
    def name(self) -> str:
        return self._stage.name

    def remaining(self) -> float:
        return self._sched.remaining()

    def progress(self, value) -> None:
        if not self.abandoned.is_set():
            self._sched.checkpoint.progress(self._stage.name, value)


class StageScheduler:
    """Run declared stages by priority under one wall-clock deadline."""

    def __init__(self, checkpoint: Checkpoint, deadline_s: float,
                 t0: float | None = None):
        self.checkpoint = checkpoint
        self.deadline_s = deadline_s
        self.t0 = time.time() if t0 is None else t0
        self.stages: list[Stage] = []

    def add(self, name: str, fn, *, priority: int = 50, est_s: float = 30.0,
            min_budget_s: float | None = None,
            soft_timeout_s: float | None = None,
            reserve_s: float = 0.0) -> None:
        self.stages.append(Stage(
            name, fn, priority=priority, est_s=est_s,
            min_budget_s=min_budget_s, soft_timeout_s=soft_timeout_s,
            reserve_s=reserve_s, order=len(self.stages),
        ))

    def remaining(self) -> float:
        return self.deadline_s - (time.time() - self.t0)

    def run(self) -> dict:
        from ceph_tpu import obs

        L = _counters()
        ck = self.checkpoint
        for st in sorted(self.stages, key=lambda s: (-s.priority, s.order)):
            if ck.done(st.name):
                L.inc("stages_skipped_resume")
                ck.data.setdefault("resumed_stages", [])
                if st.name not in ck.data["resumed_stages"]:
                    ck.data["resumed_stages"].append(st.name)
                _log(5, f"stage {st.name}: already checkpointed, skipping")
                continue
            rem = self.remaining()
            need = st.min_budget_s if st.min_budget_s is not None else st.est_s
            if rem < need:
                L.inc("stages_skipped_budget")
                ck.put(f"{st.name}_skipped", {
                    "remaining_s": round(rem, 1), "needed_s": need,
                })
                _log(1, f"stage {st.name}: skipped, {rem:.0f}s left < "
                        f"{need:.0f}s budget")
                continue
            self._run_one(st, rem, L)
        ck.flush()
        return ck.data

    def _run_one(self, st: Stage, rem: float, L) -> None:
        from ceph_tpu import obs

        handle = StageHandle(self, st)
        box: dict = {}

        def target():
            try:
                faults.check("stage", qual=st.name)
                box["result"] = st.fn(handle)
            except BaseException as e:  # checkpointed, not swallowed
                box["error"] = e

        timeout = min(st.soft_timeout_s or rem, rem - st.reserve_s, rem)
        if timeout <= 0:
            L.inc("stages_skipped_budget")
            self.checkpoint.put(f"{st.name}_skipped", {
                "remaining_s": round(rem, 1),
                "needed_s": st.reserve_s,
            })
            _log(1, f"stage {st.name}: skipped, {rem:.0f}s left <= "
                    f"{st.reserve_s:.0f}s reserved for later stages")
            return
        t = threading.Thread(
            target=target, name=f"stage-{st.name}", daemon=True
        )
        _log(5, f"stage {st.name}: start (budget {timeout:.0f}s)")
        with obs.span(f"stage.{st.name}", priority=st.priority):
            t.start()
            t.join(timeout)
        if t.is_alive():
            handle.abandoned.set()
            L.inc("stage_overruns")
            self.checkpoint.fail(
                st.name,
                f"overrun: still running after {timeout:.0f}s; abandoned",
            )
            obs.instant("stage.overrun", stage=st.name)
            return
        if "error" in box:
            L.inc("stages_failed")
            self.checkpoint.fail(st.name, box["error"])
        else:
            L.inc("stages_run")
            self.checkpoint.put(st.name, box["result"])
        # after the checkpoint flush: `stage_end.<name>=exit:<rc>` dies
        # here with the stage durably recorded — the resume test's hook
        faults.check("stage_end", qual=st.name)
