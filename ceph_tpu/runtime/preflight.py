"""Device preflight: probe an accelerator platform without betting the
process on it.

The failure mode this exists for (BENCH r01-r05): `jax.devices()` on the
TPU plugin blocks for 90-240 s — or forever — when the transport is down
or a stale process still holds the chip.  An in-process call cannot be
cancelled, so the probe runs `jax.devices()` in a *subprocess* under a
watchdog: a hang costs exactly the configured timeout, a crash costs an
exit code, and the parent process stays healthy either way.

    res = probe("auto", timeout_s=120)   # watchdogged subprocess probe
    res = probe("cpu", watchdog=False)   # in-process (library fast path)

`platform="auto"` probes whatever the session has configured (env pin /
sitecustomize) — the hang-prone path; any other name is forced via
`jax.config.update("jax_platforms", ...)`, the only override that works
once a sitecustomize has imported jax.

On failure, `diagnose_init_failure()` gathers best-effort evidence of
*why* — a stale chip-holding process (/dev/accel*, /dev/vfio held by
another pid), a leftover libtpu lockfile, the transport env — so the
provenance record says "chip held by pid 1234 (python3)" instead of
"timeout".

`prewarm_compile_cache()` is the persistent-compile-cache hook
(previously private to bench.py): enabling it right after acquisition
means every later jit in the process (bench stages, CLI batch calls)
hits the on-disk cache.

Fault points (runtime.faults): `init[.platform]` fires in the probe
child *before* the jax import — an injected hang is cheap to kill — and
in the in-process path right before `jax.devices()`.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path

from ceph_tpu.runtime import faults
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("runtime")

DEFAULT_TIMEOUT_S = float(os.environ.get("CEPH_TPU_INIT_TIMEOUT", 120))

# device nodes an accelerator process holds open; a stale holder is the
# classic "init hangs until the old run is killed" cause
_CHIP_DEVICE_PREFIXES = ("/dev/accel", "/dev/vfio", "/dev/apex")
_LIBTPU_LOCKFILE = "/tmp/libtpu_lockfile"


@dataclass
class ProbeResult:
    ok: bool
    platform: str  # requested rung ("auto", "cpu", "tpu", ...)
    backend: str = ""  # what jax actually reports on success
    device: str = ""
    n_devices: int = 0
    init_s: float = 0.0
    error: str = ""  # failure reason ("" on success)
    timed_out: bool = False
    diagnosis: list[str] = field(default_factory=list)


def _chip_holders() -> list[str]:
    """Best-effort scan for live processes holding an accelerator device
    node open (requires /proc; never raises)."""
    holders = []
    try:
        for pid_dir in Path("/proc").iterdir():
            if not pid_dir.name.isdigit() or int(pid_dir.name) == os.getpid():
                continue
            fd_dir = pid_dir / "fd"
            try:
                for fd in fd_dir.iterdir():
                    tgt = os.readlink(fd)
                    if tgt.startswith(_CHIP_DEVICE_PREFIXES):
                        comm = (pid_dir / "comm").read_text().strip()
                        holders.append(
                            f"chip device {tgt} held by pid "
                            f"{pid_dir.name} ({comm})"
                        )
                        break
            except OSError:
                continue  # permission / raced exit
    except OSError:
        pass
    return holders


def diagnose_init_failure(platform: str) -> list[str]:
    """Why might accelerator init have failed/hung?  Returns human-readable
    findings (possibly empty); pure observation, never raises."""
    finds = _chip_holders()
    try:
        if os.path.exists(_LIBTPU_LOCKFILE):
            finds.append(f"libtpu lockfile present: {_LIBTPU_LOCKFILE}")
    except OSError:
        pass
    for var in ("TPU_NAME", "TPU_WORKER_ID", "JAX_PLATFORMS"):
        val = os.environ.get(var)
        if val:
            finds.append(f"env {var}={val}")
    if not finds:
        finds.append(f"no local cause found for platform={platform!r} "
                     "(transport down?)")
    return finds


def prewarm_compile_cache(cache_dir: str | None = None) -> str | None:
    """Enable the JAX persistent compilation cache (idempotent); returns
    the cache dir, or None when jax refuses every knob."""
    import jax

    cache = Path(
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                          "/root/.cache/jax_bench_cache")
    )
    try:
        cache.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        _log(1, f"compile cache dir unavailable: {e}")
        return None
    took = False
    for opt, val in (
        ("jax_compilation_cache_dir", str(cache)),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
    ):
        try:
            jax.config.update(opt, val)
            took = True
        except Exception:
            pass  # older jax: knob absent; cache simply stays off
    return str(cache) if took else None


# ------------------------------------------------------------------ probes

def _probe_inprocess(platform: str) -> ProbeResult:
    t0 = time.perf_counter()
    try:
        faults.check("init", qual=platform)
        import jax

        if platform != "auto":
            jax.config.update("jax_platforms", platform)
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)
        devs = jax.devices()
        return ProbeResult(
            ok=True, platform=platform, backend=jax.default_backend(),
            device=str(devs[0]), n_devices=len(devs),
            init_s=time.perf_counter() - t0,
        )
    except Exception as e:  # RuntimeError from jax, FaultInjected, ...
        return ProbeResult(
            ok=False, platform=platform,
            error=f"{type(e).__name__}: {e}"[:250],
            init_s=time.perf_counter() - t0,
        )


# interpreter start + jax import in the probe child is real work, not a
# hang — it gets its own grace period so timeout_s can stay tight around
# the thing that actually wedges (device init)
IMPORT_GRACE_S = float(os.environ.get("CEPH_TPU_IMPORT_GRACE", 60))


def _kill_group(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except OSError:
        proc.kill()
    proc.wait()


def _probe_subprocess(platform: str, timeout_s: float) -> ProbeResult:
    """Watchdogged two-phase probe.  The child prints an "imported"
    marker once jax is loaded, then runs `jax.devices()` and prints the
    result; the parent allows IMPORT_GRACE_S to reach the marker and
    timeout_s from the marker to the result, killing the whole process
    group when either budget runs out.  So timeout_s bounds *device
    init* — the phase that actually hangs — not interpreter startup."""
    import select

    t0 = time.perf_counter()
    # the parent may import ceph_tpu off sys.path (repo checkout, not an
    # installed package) — the child must find it the same way
    pkg_root = str(Path(__file__).resolve().parents[2])
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH")) if p
    )
    # stderr goes to a spooled file, not a pipe: a chatty init (verbose
    # libtpu/absl logging) would fill a pipe buffer and block the child
    # mid-init — which this watchdog would then misreport as a hang
    import tempfile

    errf = tempfile.TemporaryFile()
    proc = subprocess.Popen(
        [sys.executable, "-m", "ceph_tpu.runtime.preflight", platform],
        stdout=subprocess.PIPE, stderr=errf, env=env,
        start_new_session=True,  # kill the group: libtpu forks helpers
    )
    info: dict = {}
    imported = False
    deadline = time.monotonic() + IMPORT_GRACE_S
    timed_out = False
    while True:
        wait = deadline - time.monotonic()
        if wait <= 0:
            timed_out = True
            _kill_group(proc)
            break
        r, _, _ = select.select([proc.stdout], [], [], min(wait, 0.25))
        if r:
            line = proc.stdout.readline()
            if not line:  # EOF: child finished (or died)
                proc.wait()
                break
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if msg.get("phase") == "imported":
                imported = True
                deadline = time.monotonic() + timeout_s
            else:
                info = msg
        elif proc.poll() is not None:
            break
    init_s = time.perf_counter() - t0
    if timed_out:
        res = ProbeResult(
            ok=False, platform=platform, timed_out=True,
            error=(f"device init hung > {timeout_s:g}s "
                   "(watchdog killed probe)" if imported else
                   f"probe never loaded jax within {IMPORT_GRACE_S:g}s"),
            init_s=init_s,
        )
    elif proc.returncode == 0 and info:
        res = ProbeResult(
            ok=True, platform=platform,
            backend=info.get("backend", ""),
            device=info.get("device", ""),
            n_devices=int(info.get("n_devices", 0)),
            init_s=init_s,
        )
    else:
        try:
            errf.seek(0)
            err = errf.read()
        except OSError:
            err = b""
        tail = err.decode(errors="replace").strip().splitlines()[-3:]
        res = ProbeResult(
            ok=False, platform=platform,
            error=(f"probe exited rc={proc.returncode}: "
                   + " | ".join(tail))[:300],
            init_s=init_s,
        )
    if proc.stdout:
        proc.stdout.close()
    errf.close()
    if not res.ok:
        res.diagnosis = diagnose_init_failure(platform)
    return res


def probe(platform: str, timeout_s: float = DEFAULT_TIMEOUT_S,
          watchdog: bool = True) -> ProbeResult:
    """Check that `platform` can initialize.  watchdog=True runs the
    check in a killable subprocess (entry points); watchdog=False runs it
    in-process (library fast path — cannot be cancelled, but also cannot
    desync this process's jax config from the verdict)."""
    from ceph_tpu import obs

    with obs.span("runtime.probe", platform=platform, watchdog=watchdog):
        if watchdog:
            return _probe_subprocess(platform, timeout_s)
        return _probe_inprocess(platform)


def _child_main(platform: str) -> int:
    """Probe-child entry (`python -m ceph_tpu.runtime.preflight <rung>`).

    Prints the "imported" marker once jax is loaded (arming the parent's
    tight device-init watchdog), then runs the `init` fault point and
    `jax.devices()` — so an injected hang sits exactly where the real
    one does and is killed in ~timeout_s."""
    t0 = time.perf_counter()
    import jax

    print(json.dumps({"phase": "imported"}), flush=True)
    if platform != "auto":
        jax.config.update("jax_platforms", platform)
    faults.check("init", qual=platform)
    devs = jax.devices()
    print(json.dumps({
        "backend": jax.default_backend(),
        "device": str(devs[0]),
        "n_devices": len(devs),
        "init_s": round(time.perf_counter() - t0, 2),
    }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(_child_main(sys.argv[1] if len(sys.argv) > 1 else "auto"))
