"""Degradation ladder: acquire the best usable backend, with provenance.

The policy object every entry point goes through (bench.py, the CLIs,
parallel.sharded) instead of ad-hoc `jax.devices()` + try/except:

    info = acquire_backend()             # tpu -> cpu -> native ladder
    info.backend                         # what we actually got
    info.provenance()                    # JSON-ready record

Each rung is probed (watchdogged subprocess for entry points, in-process
for library paths) with bounded retries and exponential backoff +
deterministic jitter; the first healthy rung is activated in-process and
the full history — attempts, per-attempt failures, init seconds, the
diagnosis of *why* earlier rungs failed — is recorded in the returned
`BackendInfo`, the `runtime` perf-counter group, and (via callers) every
BENCH/MULTICHIP JSON.

Rungs:

    "auto"    whatever the session configured (the hang-prone TPU path)
    "tpu"/"cpu"/...  an explicit jax platform, forced via jax.config
    "native"  no jax at all — callers select the C++/numpy host engines;
              terminal rung that always succeeds

`require=` is the hard gate (`BENCH_REQUIRE_TPU`): when the acquired
backend does not satisfy it, RequiredBackendError is raised instead of
degrading silently.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field

from ceph_tpu.runtime import preflight
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("runtime")

DEFAULT_ATTEMPTS = int(os.environ.get("CEPH_TPU_INIT_ATTEMPTS", 2))
BACKOFF_BASE_S = float(os.environ.get("CEPH_TPU_INIT_BACKOFF", 1.0))
BACKOFF_MAX_S = 8.0


class RequiredBackendError(RuntimeError):
    """The required backend could not be acquired (hard gate, no
    degradation)."""


@dataclass
class BackendInfo:
    """Provenance of one backend acquisition."""

    backend: str  # "tpu" | "cpu" | ... | "native"
    device: str = ""
    n_devices: int = 0
    attempts: int = 0  # total probe attempts across all rungs
    init_seconds: float = 0.0
    fallback_reason: str | None = None  # None = first rung succeeded
    rungs_tried: list[str] = field(default_factory=list)
    failures: list[str] = field(default_factory=list)
    diagnosis: list[str] = field(default_factory=list)
    compile_cache: str | None = None

    def provenance(self) -> dict:
        """The record embedded in BENCH/MULTICHIP JSON."""
        out = {
            "backend": self.backend,
            "device": self.device,
            "n_devices": self.n_devices,
            "attempts": self.attempts,
            "init_seconds": round(self.init_seconds, 2),
            "fallback_reason": self.fallback_reason,
        }
        if len(self.rungs_tried) > 1:
            out["rungs_tried"] = self.rungs_tried
        if self.failures:
            out["failures"] = self.failures
        if self.diagnosis:
            out["diagnosis"] = self.diagnosis
        return out


_last: BackendInfo | None = None


def last_provenance() -> dict | None:
    """Provenance of the most recent acquisition in this process (the
    `runtime` admin-socket command and MULTICHIP writers read this)."""
    return _last.provenance() if _last is not None else None


def default_ladder() -> list[str]:
    """From CEPH_TPU_LADDER if set; else probe the configured platform
    first, then degrade to cpu, then to the jax-free native engines."""
    env = os.environ.get("CEPH_TPU_LADDER")
    if env:
        return [r.strip() for r in env.split(",") if r.strip()]
    return ["auto", "cpu", "native"]


def _counters():
    from ceph_tpu import obs

    L = obs.logger_for("runtime")
    L.add_u64("init_attempts", "backend probe attempts")
    L.add_u64("init_failures", "backend probe failures")
    L.add_u64("fallbacks", "degradation ladder descents")
    L.add_time_avg("init_seconds", "backend acquisition wall time")
    return L


def _backoff_sleep(attempt: int, rung: str, sleep=time.sleep) -> float:
    """Exponential backoff with deterministic jitter (seeded per rung +
    attempt: reproducible runs, but concurrent workers probing the same
    chip do not stampede in lockstep)."""
    base = min(BACKOFF_BASE_S * (2 ** attempt), BACKOFF_MAX_S)
    jit = random.Random(f"{rung}:{attempt}:{os.getpid()}").uniform(0, base / 4)
    delay = base + jit
    sleep(delay)
    return delay


def _activate(rung: str, res: preflight.ProbeResult) -> None:
    """Point this process's jax at the verified rung."""
    if rung == "native":
        return
    import jax

    if rung != "auto":
        jax.config.update("jax_platforms", rung)
    if not jax.config.jax_enable_x64:
        # x64 is load-bearing (s64 straw2 draws, u64 ln math): a silent
        # 32-bit downcast would produce wrong placements
        jax.config.update("jax_enable_x64", True)
    jax.devices()  # probe-verified; completes the in-process init


def acquire_backend(
    ladder: list[str] | None = None,
    require: str | None = None,
    watchdog: bool = True,
    timeout_s: float | None = None,
    attempts: int = DEFAULT_ATTEMPTS,
    prewarm_cache: bool = False,
    sleep=time.sleep,
) -> BackendInfo:
    """Walk the degradation ladder; return provenance for the first rung
    that initializes.

    watchdog=True probes each rung in a killable subprocess (entry
    points: a TPU-init hang costs timeout_s, not the run); False probes
    in-process (library paths that must not fork).  `require` hard-gates
    the result: if the acquired backend does not match, raise instead of
    degrading (BENCH_REQUIRE_TPU semantics).
    """
    from ceph_tpu import obs

    global _last
    ladder = list(ladder or default_ladder())
    timeout_s = preflight.DEFAULT_TIMEOUT_S if timeout_s is None else timeout_s
    L = _counters()
    info = BackendInfo(backend="none")
    t_all = time.perf_counter()
    with obs.span("runtime.acquire_backend", ladder=",".join(ladder)):
        for rung_i, rung in enumerate(ladder):
            info.rungs_tried.append(rung)
            if rung == "native":
                info.backend = "native"
                info.device = "host (no jax)"
                break
            res = None
            for att in range(max(1, attempts)):
                info.attempts += 1
                L.inc("init_attempts")
                res = preflight.probe(rung, timeout_s, watchdog=watchdog)
                if res.ok:
                    break
                L.inc("init_failures")
                info.failures.append(f"{rung}[{att}]: {res.error}")
                info.diagnosis.extend(
                    d for d in res.diagnosis if d not in info.diagnosis
                )
                _log(1, f"probe {rung} attempt {att + 1} failed: "
                        f"{res.error}")
                if res.timed_out or att + 1 >= max(1, attempts):
                    # a watchdog-killed hang does not resolve by retrying
                    # immediately; move down the ladder instead
                    break
                _backoff_sleep(att, rung, sleep=sleep)
            if res is not None and res.ok:
                _activate(rung, res)
                info.backend = res.backend or rung
                info.device = res.device
                info.n_devices = res.n_devices
                break
            if rung_i + 1 < len(ladder):
                L.inc("fallbacks")
                if info.fallback_reason is None:
                    info.fallback_reason = (
                        f"{rung}: {res.error if res else 'not probed'}"
                    )
    info.init_seconds = time.perf_counter() - t_all
    L.observe("init_seconds", info.init_seconds)
    if info.backend == "none":
        raise RequiredBackendError(
            "no rung of the ladder "
            f"{ladder} initialized: {'; '.join(info.failures)}"
        )
    if require and info.backend != require:
        raise RequiredBackendError(
            f"required backend {require!r} unavailable, got "
            f"{info.backend!r} ({info.fallback_reason})"
        )
    if prewarm_cache and info.backend != "native":
        info.compile_cache = preflight.prewarm_compile_cache()
    if info.backend != "native":
        # prime the library-path guard: one acquisition per process.
        # Later ensure_jax_backend() calls short-circuit instead of
        # re-walking the ladder — which would re-probe a platform the
        # ladder already steered AWAY from (and, under injected init
        # faults, re-fire them in-process with no watchdog).
        from ceph_tpu.utils import platform as _platform_guard

        _platform_guard._checked = info.backend
    _last = info
    obs.instant("runtime.acquired", backend=info.backend,
                attempts=info.attempts)
    _log(5, f"acquired backend={info.backend} device={info.device!r} "
            f"attempts={info.attempts} "
            f"fallback={info.fallback_reason or 'none'}")
    return info
