"""Deterministic fault injection for the runtime robustness layer.

The reference exercises its failure paths with the qa thrasher
(reference qa/tasks/ceph_manager.py:185 OSDThrasher) — randomized kills
against a live cluster.  That style lives in `sim/failure.py`; this
module is the *deterministic* counterpart for the runtime layer itself:
named fault points compiled into the acquisition / dispatch / scheduler
paths, armed by env var or API, so every retry / backoff / degradation /
resume branch runs in fast CPU-only tests instead of waiting for a real
TPU to wedge.

Spec syntax (env `CEPH_TPU_FAULTS`, comma-separated):

    point[.qualifier]=action[:arg][@pP][xN]

    CEPH_TPU_FAULTS="init.tpu=hang:600"        # TPU init hangs 600s
    CEPH_TPU_FAULTS="init.tpu=fail:ENOLINK x2" # first 2 probes raise
    CEPH_TPU_FAULTS="stage.headline=stall:3"   # stage start stalls 3s
    CEPH_TPU_FAULTS="map_batch=lost x1"        # device loss, once
    CEPH_TPU_FAULTS="stage_end.ec_jax=exit:3"  # die after a checkpoint
    CEPH_TPU_FAULTS="stage.headline=overrun:9" # stage overruns 9s
    CEPH_TPU_FAULTS="epoch_apply=lost@p0.3x2"  # flaky: each hit fires
                                               # with prob 0.3, 2 firings

Actions:

    hang:<secs>   sleep that long (watchdogs are expected to fire first)
    stall:<secs>  sleep that long, then continue (compile-stall shape)
    fail[:why]    raise FaultInjected(why)
    lost[:why]    raise DeviceLostError(why) — the mid-stage device-loss
                  shape callers degrade from
    exit[:code]   os._exit(code) — a SIGKILL-grade death (no atexit, no
                  finally) for checkpoint/resume tests
    overrun:<s>   sleep — used at stage fault points to trip the stage
                  watchdog deterministically

`xN` arms the fault for the first N hits only (default: every hit).
Counts decrement in-process; a respawned worker re-arms from the env,
which is exactly what the retry-until-healthy tests want.

`@pP` arms the fault *probabilistically*: each hit fires with
probability P (a float in (0, 1]), drawn from a deterministic
`numpy.random.default_rng` seeded from the spec itself — the same armed
spec produces the same fire/skip sequence in every process, so chaos
schedules (sim/lifetime.py) can arm flaky faults and still replay
bit-identically.  A skipped (not-fired) hit consumes no `xN` budget.
When both a qualified and a bare fault are armed, the most specific
match decides alone — a probabilistic skip does not fall through to the
bare entry.

Fault points are cheap when disarmed: one dict lookup against a dict
that is empty in production.  Every firing is recorded in the `runtime`
perf-counter group and as an `obs` instant event, so an armed fault can
never silently shape a benchmark number.
"""

from __future__ import annotations

import os
import re
import threading
import time

from ceph_tpu.utils.dout import subsys_logger

ENV_VAR = "CEPH_TPU_FAULTS"

# The declared fault points: every compiled-in `check(point, ...)` site
# must use one of these bases, and every base must be exercised by at
# least one test (both checked statically by the graftlint `fault-point`
# pass — an unexercised fault point is a retry/degradation branch nobody
# runs until a real device wedges).  Tests may still arm ad-hoc points
# (e.g. qualifier-mismatch probes); only production call sites are held
# to the registry.
FAULT_POINTS: dict[str, str] = {
    "init": "backend preflight probe (qualifier: platform rung)",
    "map_batch": "mid-batch device dispatch in the mapping pipeline",
    "stage": "scheduler stage body start (qualifier: stage name)",
    "stage_end": "after a stage checkpoints (qualifier: stage name)",
    "epoch_apply": "lifetime-sim per-pool device accounting dispatch "
                   "(qualifier: epoch number)",
    "lifetime_step": "lifetime-sim step start, before the epoch's "
                     "Incremental is built (qualifier: epoch number)",
    "recovery_step": "lifetime-sim recovery-queue drain, before the "
                     "epoch's backlog is touched (qualifier: epoch "
                     "number; `lost` degrades the drain to the "
                     "bit-identical host mirror mid-run)",
    "hazard_decay": "lifetime-sim correlated-hazard decay step, before "
                    "the epoch's windows advance (qualifier: epoch "
                    "number; `fail`/`exit` here kills a run "
                    "mid-cascade — the hazard-state resume test)",
    "serve_dispatch": "placement-service micro-batch device dispatch "
                      "(qualifier: batch sequence number; `lost` "
                      "degrades the batch to the host mapper, `exit` "
                      "is the kill/restart test)",
    "epoch_swap": "placement-service epoch-swap staging, before the "
                  "new buffer is built (qualifier: target epoch; a "
                  "firing leaves the old epoch serving)",
}

_log = subsys_logger("runtime")
_lock = threading.Lock()


class FaultInjected(RuntimeError):
    """An armed `fail` fault point fired."""


class DeviceLostError(RuntimeError):
    """The device disappeared mid-operation (real transport loss raises
    jaxlib errors; the injected shape raises this so callers can degrade
    without pattern-matching vendor exception text)."""


# substrings of real jaxlib/XLA transport-loss messages; dispatch sites
# use looks_like_device_loss() to map them onto DeviceLostError so real
# losses take the same degradation path the injected ones test
_DEVICE_LOSS_MARKERS = (
    "device lost", "data loss", "unavailable", "transport",
    "socket closed", "connection reset", "device halted", "chip reboot",
)


def looks_like_device_loss(exc: BaseException) -> bool:
    """True when a raised exception is plausibly the device dying under
    us (vs. a bug in our code): a jaxlib/XLA runtime error whose message
    matches a known transport-loss shape."""
    if isinstance(exc, DeviceLostError):
        return True
    mod = type(exc).__module__ or ""
    if not (mod.startswith("jaxlib") or mod.startswith("jax")
            or type(exc).__name__ == "XlaRuntimeError"):
        return False
    msg = str(exc).lower()
    return any(m in msg for m in _DEVICE_LOSS_MARKERS)


class _Fault:
    __slots__ = ("action", "arg", "remaining", "p", "key", "_rng")

    def __init__(self, action: str, arg: str, remaining: int,
                 p: float = 1.0, key: str = ""):
        self.action = action
        self.arg = arg
        self.remaining = remaining  # <0 = unlimited
        self.p = p  # firing probability per hit (1.0 = always)
        self.key = key  # the armed point[.qual], part of the rng seed
        self._rng = None

    def draw(self) -> bool:
        """Deterministic per-hit firing decision for `@pP` faults: the
        rng seeds from the fault's own full spec item — point INCLUDED,
        so two points armed with the same action/arg/p still get
        independent fire/skip sequences — and every process arming the
        same spec sees the same sequence."""
        if self.p >= 1.0:
            return True
        if self._rng is None:
            import zlib

            import numpy as np

            seed = zlib.crc32(
                f"{self.key}={self.action}:{self.arg}@p{self.p}".encode()
            )
            self._rng = np.random.default_rng(seed)
        return float(self._rng.random()) < self.p


_armed: dict[str, _Fault] = {}

_P_RE = re.compile(r"@p([0-9.]+)$")


def _parse_one(item: str) -> tuple[str, _Fault]:
    point, _, act = item.partition("=")
    point, act = point.strip(), act.strip()
    if not point or not act:
        raise ValueError(f"bad fault spec item {item!r}")
    remaining = -1
    if "x" in act:
        head, _, cnt = act.rpartition("x")
        if cnt.strip().isdigit():
            act, remaining = head.strip(), int(cnt)
    p = 1.0
    m = _P_RE.search(act)
    if m is not None:
        try:
            p = float(m.group(1))
        except ValueError:
            raise ValueError(f"bad fault probability in {item!r}")
        if not 0.0 < p <= 1.0:
            raise ValueError(f"fault probability {p} not in (0, 1] "
                             f"in {item!r}")
        act = act[: m.start()].strip()
    action, _, arg = act.partition(":")
    action = action.strip()
    if action not in ("hang", "stall", "fail", "lost", "exit", "overrun"):
        raise ValueError(f"unknown fault action {action!r} in {item!r}")
    return point, _Fault(action, arg.strip(), remaining, p, key=point)


def configure(spec: str | None) -> None:
    """Replace the armed-fault table from a spec string ("" or None
    disarms everything)."""
    with _lock:
        _armed.clear()
        for item in (spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            point, f = _parse_one(item)
            _armed[point] = f


def arm(point: str, action: str, arg: str = "", count: int = -1,
        p: float = 1.0) -> None:
    """API-side arming (tests that do not want to mutate the env)."""
    with _lock:
        _armed[point] = _Fault(action, arg, count, p, key=point)


def disarm(point: str) -> None:
    """Remove ONE armed fault (the counterpart of `arm`).  Callers that
    arm a fault for their own scope must disarm exactly that key —
    `disarm_all` would also wipe env-armed faults aimed at later
    stages of the same process."""
    with _lock:
        _armed.pop(point, None)


def disarm_all() -> None:
    with _lock:
        _armed.clear()


def _take(point: str, qual: str | None) -> tuple[str, _Fault] | None:
    """Find the most specific armed fault for point[.qual] and consume
    one firing from its budget."""
    with _lock:
        for key in ((f"{point}.{qual}",) if qual else ()) + (point,):
            f = _armed.get(key)
            if f is None or f.remaining == 0:
                continue
            if not f.draw():
                # probabilistic skip: no budget consumed, and the most
                # specific match decides alone (no fall-through)
                return None
            if f.remaining > 0:
                f.remaining -= 1
            return key, f
    return None


def check(point: str, qual: str | None = None) -> None:
    """Execute the fault point.  No-op unless a matching fault is armed."""
    hit = _take(point, qual)
    if hit is None:
        return
    key, f = hit
    from ceph_tpu import obs

    _rt_counters().inc("faults_fired")
    obs.instant("fault.fired", point=key, action=f.action)
    _log(1, f"fault point {key} fired: {f.action}:{f.arg}")
    if f.action in ("hang", "stall", "overrun"):
        time.sleep(float(f.arg or 1.0))
    elif f.action == "fail":
        raise FaultInjected(f.arg or f"injected failure at {key}")
    elif f.action == "lost":
        raise DeviceLostError(f.arg or f"injected device loss at {key}")
    elif f.action == "exit":
        os._exit(int(f.arg or 1))


def active() -> dict[str, str]:
    """The armed table, for provenance records ({point: "action:arg"})."""
    with _lock:
        return {
            k: f"{f.action}:{f.arg}"
            + (f"@p{f.p:g}" if f.p < 1.0 else "")
            + (f" x{f.remaining}" if f.remaining >= 0 else "")
            for k, f in _armed.items()
        }


def _rt_counters():
    from ceph_tpu import obs

    L = obs.logger_for("runtime")
    L.add_u64("faults_fired", "armed fault points that fired")
    return L


# arm from the environment at import: worker subprocesses inherit the
# spec without any plumbing, which is how bench.py's supervisor/worker
# pair and the preflight probe child all see the same faults
configure(os.environ.get(ENV_VAR))
