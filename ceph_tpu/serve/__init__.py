"""Placement serving daemon — the heavy-traffic scenario.

Everything else in the repo is batch/CLI; this package is the
persistent service of ROADMAP item 3: answer `pg_to_up_acting_osds`
and object→PG→OSD queries at high QPS and stay correct and available
through epoch swaps, overload, device loss, and crash-restart.

    from ceph_tpu.serve import PlacementService, ServeConfig

    svc = PlacementService(osdmap)
    r = svc.lookup(pool_id, seed)          # r.acting, r.acting_primary
    svc.apply(incremental)                 # epoch swap, readers undisturbed
    svc.close()

Design (see `service.py` for the mechanics):

- **Micro-batched dispatch** — queries collect for ≤1 ms (or a fill
  threshold) and map as ONE fixed-shape device block through the
  trace-once `PoolMapper`/`_PIPE_CACHE` path, the batched-dispatch
  framing of "Rateless Codes for Near-Perfect Load Balancing in
  Distributed Matrix-Vector Multiplication" (PAPERS.md): the device
  stays saturated while individual requests carry deadlines.
- **Bulk protocol edge** — `query_block`/`submit_many` answer
  thousands of lookups per call on the caller's thread (pool-grouped,
  cycle-padded once, one dispatch per fixed-shape sub-block) with
  per-lane statuses; the serving buffer shards its PG axis over the
  `CEPH_TPU_MESH_DEVICES` mesh exactly like `ClusterState`
  (bit-identical answers on any device count — `meshcheck.py` is the
  witness).
- **Multi-replica front** — `front.ServeFront`: N replicas behind a
  rendezvous-hash router with staggered epoch fan-out (one replica
  staging at a time) and slowest-replica shedding, so one replica's
  swap or stall is absorbed instead of surfacing in client p99.
- **Double-buffered epoch swaps** — an `osd.incremental` apply stages a
  fresh buffer (map + compiled mappers + refreshed operands) off the
  reader path, then swaps atomically; readers drain on the old buffer.
  The reader-visible stall is measured (`swap_stall_seconds` quantile).
- **Admission control + deadlines** — a bounded queue sheds overload
  with an explicit EBUSY reply instead of queue collapse; expired
  requests get ETIMEDOUT.  Queries are answered, never dropped.
- **Degraded dispatch** — mid-traffic device loss answers the batch
  through the bit-exact host mapper (provenance recorded) and recovery
  re-walks back to the device.
- **Crash-restart** — `runtime.Checkpoint` persists epoch + map blob;
  a restarted daemon resumes serving the same epoch.

`chaos.py` drives the PR 10 lifetime engine's epoch churn against a
live service under seeded client load (`python -m ceph_tpu.cli.serve`).
"""

from __future__ import annotations

from ceph_tpu.serve.front import ServeFront
from ceph_tpu.serve.service import (
    REPLY_STATUSES,
    STATUS_CODES,
    BulkReply,
    PlacementService,
    Reply,
    ServeConfig,
    status_dump,
)

__all__ = [
    "BulkReply",
    "PlacementService",
    "Reply",
    "REPLY_STATUSES",
    "STATUS_CODES",
    "ServeConfig",
    "ServeFront",
    "status_dump",
]
