"""Chaos-client harness: lifetime-engine churn against a live service.

The PR 10 lifetime engine (`sim/lifetime.py`) is a ready-made hostile
control plane: every epoch it evolves one cluster through a seeded
failure/churn/growth event as a real `Incremental` chain link.  This
harness points that churn at a live `PlacementService` — after every
sim epoch the evolved map swaps into the service — while seeded client
threads keep a query load running.  When the scenario runs the client
workload generator (`workload=1`, the default here), those threads
shape their traffic with the same Zipf/diurnal formulas from
`sim/workload.py` the simulator scores — hot pools, power-law PG keys,
a diurnal batch curve — and the default scenario is correlated
(`correlated=1`): cascading domain outages and repeat-offender
flappers drive the churn the clients ride through.  Measured, from
the client side:

    p50/p99 request latency UNDER control-plane churn, QPS, shed and
    expired counts, and the never-dropped proof (every submitted
    request got exactly one reply).

This is the contention the online-EC SSD-array study (PAPERS.md) calls
out: the interesting behavior only appears when control-plane work and
client traffic compete for the same resources.  Value-only epochs swap
through the trace-once caches (0 compiles); structural epochs
(expansion, splits, new pools) pay their compiles in the staging phase,
off the reader path — the client tail is the witness.

Used by `python -m ceph_tpu.cli.serve chaos`, the `serve` bench stage,
and the sustained slow-tier test.
"""

from __future__ import annotations

import copy
import threading
import time

import numpy as np

from ceph_tpu import obs
from ceph_tpu.serve.service import PlacementService, ServeConfig
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("serve")

DEFAULT_CHAOS_SCENARIO = (
    "hosts=4,osds_per_host=3,racks=2,pgs=64,ec=,size=3,"
    "balance_every=8,balance_max=2,spotcheck_every=0,"
    "checkpoint_every=0,seed=23,p_split=0,p_pool_create=0,"
    "p_expand=0,p_remove=0,workload=1,wl_sample=64,"
    "correlated=1,flappers=2"
)


class _Client:
    """One seeded query-load thread through the full client path,
    latencies collected for the percentile summary.

    With a workload generator attached (scenario `workload=1`), the
    thread shapes its traffic with the SAME formulas the simulator
    scores (sim/workload.py): pools picked by the `(rank+1)^-hot_pool`
    Zipf rank weights, PG seeds by the `floor(n·u^zipf_a)` hot-key
    power law, a seeded read/write mix, and a per-iteration batch that
    rides the diurnal curve — so the degraded reads and SLO burn the
    service reports happen under the simulator's own correlated
    scenario, not a uniform stand-in.  Without one, the legacy uniform
    pool/seed draw is unchanged."""

    def __init__(self, svc: PlacementService, seed: int,
                 batch: int, stop: threading.Event, wl=None):
        self.svc = svc
        self.rng = np.random.default_rng([seed, 0x5e4e])
        self.batch = batch
        self.stop = stop
        self.wl = wl
        self.ticks = 0
        self.latencies: list[float] = []
        self.submitted = 0
        self.replied = 0
        self.reads = 0
        self.by_status: dict[str, int] = {}
        self.thread = threading.Thread(
            target=self._run, name=f"serve-client-{seed}", daemon=True)

    def _draw(self, pools: list[int], n_for) -> tuple[int, np.ndarray]:
        """One iteration's (pool, seeds) draw in the active traffic
        model; `n_for(pid)` defers the pg_num read until the pool is
        chosen (the active map can swap between iterations)."""
        wl = self.wl
        if wl is None:
            pid = int(pools[int(self.rng.integers(len(pools)))])
            seeds = self.rng.integers(
                0, n_for(pid), size=self.batch).astype(np.uint32)
            return pid, seeds
        from ceph_tpu.sim.workload import pool_rank_weights, zipf_pg_seeds

        cum = np.cumsum(pool_rank_weights(len(pools), wl.hot_pool))
        j = int(np.searchsorted(cum, self.rng.random() * cum[-1],
                                side="right"))
        pid = int(pools[min(j, len(pools) - 1)])
        # diurnal modulation: the tick index walks the same triangle
        # curve the simulator's QPS follows, scaled to the batch size
        eff = self.batch
        if wl.base_qps > 0:
            eff = max(1, int(self.batch * wl.qps(self.ticks)
                             / wl.base_qps))
        seeds = zipf_pg_seeds(
            self.rng.random(eff), n_for(pid), wl.zipf_a
        ).astype(np.uint32)
        self.reads += int(
            (self.rng.random(eff) < wl.read_fraction).sum())
        return pid, seeds

    def _run(self) -> None:
        svc = self.svc
        while not self.stop.is_set():
            pools = sorted(svc._active.m.pools)
            pid, seeds = self._draw(
                pools, lambda p: svc._active.m.pools[p].pg_num)
            self.ticks += 1
            t0 = time.perf_counter()
            self.submitted += len(seeds)
            r = svc.lookup_batch(pid, seeds)
            self.replied += len(seeds)
            self.by_status[r.status] = \
                self.by_status.get(r.status, 0) + len(seeds)
            if r.ok:
                self.latencies.append(time.perf_counter() - t0)


def _pct(vals: list[float], q: float) -> float | None:
    if not vals:
        return None
    return round(float(np.percentile(np.asarray(vals), q)), 6)


def run_chaos(scenario: str | None = None, epochs: int | None = None,
              config: ServeConfig | None = None,
              checkpoint: str | None = None, resume: bool = False,
              clients: int = 2, client_batch: int = 256,
              settle_s: float = 0.02,
              background_every: int = 0) -> dict:
    """Run lifetime churn against a live service under client load.

    With `resume=True` the service restores its checkpointed epoch
    FIRST and the summary records `resumed_epoch` + `sample_digest`
    before any new churn — the restart-answers-identically witness the
    kill test compares against the host oracle of the checkpoint.

    `background_every=N` runs one CONTINUOUS-BALANCING round
    (`PlacementService.background_balance`: a whole-plan device-loop
    upmap optimization, applied as a value-only overlay epoch) after
    every Nth churn epoch — between swaps, never on the query path —
    and records the rounds' wall-time distribution beside the client
    tail, the live proof that background balancing leaves p99
    bounded."""
    from ceph_tpu.sim.lifetime import LifetimeSim, Scenario

    sc = Scenario.parse(scenario if scenario is not None
                        else DEFAULT_CHAOS_SCENARIO)
    if epochs is not None:
        sc.epochs = epochs
    # workload-shaped clients (ROADMAP item 3): when the scenario runs
    # the client workload generator, the chaos threads draw from the
    # same Zipf/diurnal formulas — a parameter-only WorkloadGen (no
    # tallies booked) keeps one source of truth for the shape
    wl = None
    if sc.workload:
        from ceph_tpu.sim.workload import WorkloadGen

        wl = WorkloadGen(
            seed=sc.seed, base_qps=sc.base_qps,
            read_fraction=sc.read_fraction, zipf_a=sc.zipf_a,
            hot_pool=sc.hot_pool, diurnal_amp=sc.diurnal_amp,
            diurnal_period=sc.diurnal_period, obj_kb=sc.obj_kb,
            sample=sc.wl_sample, interval_s=sc.interval_s)
    # the serve perf group is process-global; snapshot it so THIS run's
    # shed/expired/degraded tallies are deltas, not whatever an earlier
    # service in the same process (e.g. bench phase A/B) accumulated
    base = dict(obs.perf_dump().get("serve") or {})
    out: dict = {"scenario": sc.spec()}
    sim = None
    if resume:
        # restart path: prove the resumed epoch answers before churning
        svc = PlacementService(config=config, checkpoint=checkpoint,
                               resume=True)
        out["resumed_epoch"] = svc.epoch
        out["sample_digest"] = svc.sample_digest()
    else:
        sim = LifetimeSim(sc, backend="jax")
        svc = PlacementService(copy.deepcopy(sim.m), config=config,
                               checkpoint=checkpoint)
    stop = threading.Event()
    pool_threads = [
        _Client(svc, i, client_batch, stop, wl=wl)
        for i in range(clients)
    ]
    t0 = time.perf_counter()
    swaps_ok = swaps_rejected = 0
    bg_rounds: list[dict] = []
    try:
        for c in pool_threads:
            c.thread.start()
        with obs.span("serve.chaos", epochs=sc.epochs):
            if sim is not None:
                for ep in range(sc.epochs):
                    step = sim.step()
                    r = svc.adopt_map(sim.m, reason=step["event"])
                    if r["ok"]:
                        swaps_ok += 1
                    else:
                        swaps_rejected += 1
                    # let at least one client batch land per epoch so
                    # every epoch's map actually served traffic
                    time.sleep(settle_s)
                    if background_every and \
                            (ep + 1) % background_every == 0:
                        # a live background balancing round between
                        # swaps, with the clients still querying
                        bg_rounds.append(svc.background_balance())
                # post-churn grace: the control plane goes quiet and
                # the clients get the final map to themselves, so the
                # summary always carries served-ok samples.  If churn
                # left the SLO story mid-episode (nothing scored yet, a
                # burn open, or breaches still in the fast window),
                # hold the quiet load — bounded — until the engine sees
                # a clean fast window: the raise->clear transition is
                # part of the recorded trajectory, not a truncated
                # cliffhanger
                def _episode_open() -> bool:
                    if not obs.health.enabled():
                        return False
                    st = svc.slo.status()
                    return (svc.slo.samples == 0 or st["burning"]
                            or st["fast_burn"] > 0)

                grace_end = time.perf_counter() + max(10 * settle_s, 0.3)
                slo_end = time.perf_counter() + 30.0
                while time.perf_counter() < grace_end or (
                        _episode_open()
                        and time.perf_counter() < slo_end):
                    time.sleep(settle_s)
            else:
                # resumed service: a short verification load, no churn
                time.sleep(max(10 * settle_s, 0.2))
    finally:
        stop.set()
        for c in pool_threads:
            c.thread.join(timeout=30)
    wall = time.perf_counter() - t0
    lat = [v for c in pool_threads for v in c.latencies]
    submitted = sum(c.submitted for c in pool_threads)
    replied = sum(c.replied for c in pool_threads)
    by_status: dict[str, int] = {}
    for c in pool_threads:
        for k, v in c.by_status.items():
            by_status[k] = by_status.get(k, 0) + v
    st = svc.status()

    def delta(key: str) -> int:
        v = st.get(key)
        prev = base.get(key, 0)
        return (v - prev) if isinstance(v, int) \
            and isinstance(prev, int) else v

    out.update({
        "epochs": 0 if sim is None else sim.steps,
        "final_epoch": svc.epoch,
        "wall_s": round(wall, 3),
        "traffic": "workload" if wl is not None else "uniform",
        "client_read_mix": round(
            sum(c.reads for c in pool_threads) / submitted, 3
        ) if wl is not None and submitted else None,
        "submitted": submitted,
        "replied": replied,
        "dropped": submitted - replied,  # must be 0: never-dropped proof
        "answered_ok": by_status.get("ok", 0),
        "by_status": by_status,
        "qps": round(by_status.get("ok", 0) / wall, 1) if wall else 0.0,
        "p50_s": _pct(lat, 50),
        "p99_s": _pct(lat, 99),
        "swaps_ok": swaps_ok,
        "swaps_rejected": swaps_rejected,
        # process-wide quantile (phase A's µs-scale flips share it); the
        # u64 tallies are this run's deltas
        "swap_stall_p99_s": st.get("swap_stall_p99_s"),
        "structural_swap_stalls": delta("structural_swap_stalls"),
        # micro-batch fill as a distribution, not just the lifetime
        # average: under-filled windows (the dispatcher outrunning the
        # producers — the bulk path's failure mode) show at p50/p99
        "batch_fill_p50": st.get("batch_fill_p50"),
        "batch_fill_p99": st.get("batch_fill_p99"),
        "degraded_answered": delta("degraded_answered"),
        "queries_shed": delta("queries_shed"),
        "queries_expired": delta("queries_expired"),
        "provenance": svc.provenance(),
        # the recorded-trajectory story: the burn engine's verdict plus
        # the serve-series extract the timeline kept through the churn
        "slo": svc.slo.status(),
        "health": obs.health.summary(),
        "timeline_samples": obs.timeline.next_index("serve"),
    })
    if bg_rounds:
        # the live background-balancing story: every round ran between
        # swaps with the clients querying; the client p50/p99 above IS
        # the bounded-tail witness (adopt_map resets the overlay each
        # churn epoch, so rounds keep finding work)
        out["background"] = {
            "rounds": len(bg_rounds),
            "applied": sum(1 for b in bg_rounds if b["ok"]),
            "changes": sum(b["num_changed"] for b in bg_rounds),
            "round_p50_ms": _pct(
                [b["round_s"] * 1e3 for b in bg_rounds], 50),
            "round_p99_ms": _pct(
                [b["round_s"] * 1e3 for b in bg_rounds], 99),
        }
    if sim is not None:
        out["sim_digest"] = sim.digest
        out["sim_violations"] = len(sim.violations)
        out["sample_digest"] = svc.sample_digest()
        if sim.workload is not None:
            # the simulator's client-visible story, surfaced beside the
            # service's own tallies (serve status carries the same
            # counters — one narrative, two reporters)
            wl = sim.workload.summary(sim.sim_seconds)
            out["degraded_reads_served"] = wl["degraded_reads"]
            out["at_risk_hits"] = wl["at_risk_hits"]
            out["backlog_hits"] = wl["backlog_hits"]
        if sim.recovery is not None:
            out["recovery_backlog_gb"] = \
                sim.recovery.summary()["backlog_gb"]
    svc.close()
    return out
