"""Serve SLO burn-rate engine: declared objectives over the timeline.

The rateless-codes load-balancing literature frames a QPS target as a
promise about the *tail history*, not the mean snapshot — so the serve
layer declares objectives (p99 latency, error ratio, shed ratio) and
this engine watches the per-dispatch-window samples the service records
on the "serve" timeline, multiwindow-burn-rate style (the SRE-workbook
fast/slow pattern):

- each sample either breaches an objective or not (windowed p99 from
  the request-latency histogram delta, error/shed ratios from counter
  deltas);
- a **fast** window (last `FAST` samples) catches an active burn, a
  **slow** window (last `SLOW`) keeps one blip from paging;
- the burn raises the `SLO_BURN` health check when both windows exceed
  their thresholds, and clears it only after a full fast window of
  clean samples — so a structural swap that blows p99 is a recorded
  raise->clear transition on the timeline, not a lost transient.

Objectives come from knobs (`CEPH_TPU_SLO_P99_MS`, `CEPH_TPU_SLO_ERROR_PCT`,
`CEPH_TPU_SLO_SHED_PCT`); everything here is host-side observation only.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ceph_tpu.obs import health
from ceph_tpu.utils import knobs
from ceph_tpu.utils.perf_counters import logger_for

_L = logger_for("slo")
_L.add_u64("slo_samples", "dispatch-window samples scored against the SLO")
_L.add_u64("slo_breaches", "samples that breached at least one objective")
_L.add_u64("burns_raised", "SLO_BURN raise transitions")
_L.add_u64("burns_cleared", "SLO_BURN clear transitions")


@dataclass(frozen=True)
class Objectives:
    """The declared serve SLO; ratios are fractions (0..1)."""

    p99_s: float
    error_ratio: float
    shed_ratio: float

    @classmethod
    def from_env(cls) -> "Objectives":
        return cls(
            p99_s=float(knobs.get("CEPH_TPU_SLO_P99_MS", "250")) / 1e3,
            error_ratio=float(
                knobs.get("CEPH_TPU_SLO_ERROR_PCT", "1")) / 100.0,
            shed_ratio=float(
                knobs.get("CEPH_TPU_SLO_SHED_PCT", "5")) / 100.0,
        )

    def as_dict(self) -> dict:
        return {"p99_ms": round(self.p99_s * 1e3, 3),
                "error_pct": round(self.error_ratio * 100, 3),
                "shed_pct": round(self.shed_ratio * 100, 3)}


class SloEngine:
    """Scores per-window samples and drives the SLO_BURN health check."""

    FAST = 8         # samples in the fast window
    SLOW = 48        # samples in the slow window (ring size)
    RAISE_FAST = 0.5   # breach fraction of the fast window to raise...
    RAISE_SLOW = 1.0 / 12.0  # ...with at least this much slow-window burn

    def __init__(self, objectives: Objectives | None = None):
        self.obj = objectives or Objectives.from_env()
        self._ring: list[bool] = []
        self.burning = False
        self.burns_raised = 0
        self.burns_cleared = 0
        self.burn_seconds = 0.0
        self._last_t: float | None = None
        self.samples = 0
        self.breaches = 0

    def observe(self, *, p99_s: float | None, queries: int, errors: int,
                shed: int, wall_t: float | None = None) -> dict:
        """Score one dispatch-window sample (all deltas/values host-side,
        already computed by the caller).  Returns the scored sample."""
        now = time.monotonic() if wall_t is None else wall_t
        total = max(1, queries)
        reasons = []
        if p99_s is not None and p99_s > self.obj.p99_s:
            reasons.append("p99")
        if errors / total > self.obj.error_ratio:
            reasons.append("errors")
        if shed / total > self.obj.shed_ratio:
            reasons.append("shed")
        breach = bool(reasons)
        self.samples += 1
        _L.inc("slo_samples")
        if breach:
            self.breaches += 1
            _L.inc("slo_breaches")
        self._ring.append(breach)
        del self._ring[:-self.SLOW]
        fast_burn = self._burn(self.FAST)
        slow_burn = self._burn(self.SLOW)
        if self.burning and self._last_t is not None:
            self.burn_seconds += max(0.0, now - self._last_t)
        self._last_t = now
        if (not self.burning and len(self._ring) >= 2
                and fast_burn >= self.RAISE_FAST
                and slow_burn >= self.RAISE_SLOW):
            self.burning = True
            self.burns_raised += 1
            _L.inc("burns_raised")
            health.raise_check(
                "SLO_BURN", health.WARN,
                f"serve SLO burning ({'+'.join(reasons)}): "
                f"fast={fast_burn:.2f} slow={slow_burn:.2f}",
                detail=(f"objectives={self.obj.as_dict()}",))
        elif self.burning and fast_burn == 0.0:
            self.burning = False
            self.burns_cleared += 1
            _L.inc("burns_cleared")
            health.clear("SLO_BURN")
        return {"breach": breach, "reasons": reasons, "burning": self.burning,
                "fast_burn": round(fast_burn, 4),
                "slow_burn": round(slow_burn, 4)}

    def _burn(self, window: int) -> float:
        w = self._ring[-window:]
        return (sum(w) / len(w)) if w else 0.0

    def status(self) -> dict:
        return {
            "objectives": self.obj.as_dict(),
            "burning": self.burning,
            "burns_raised": self.burns_raised,
            "burns_cleared": self.burns_cleared,
            "burn_minutes": round(self.burn_seconds / 60.0, 4),
            "fast_burn": round(self._burn(self.FAST), 4),
            "slow_burn": round(self._burn(self.SLOW), 4),
            "samples": self.samples,
            "breaches": self.breaches,
        }
