"""The placement service: micro-batcher, double-buffered epoch swaps,
admission control, degraded dispatch, crash-restart.

Threading model (all bounded, all join-able):

- client threads call `lookup`/`lookup_batch`/`lookup_object`: admission
  check under the queue lock (full queue -> immediate EBUSY reply), then
  block on the request's event with a watchdog timeout — the
  runtime/scheduler idiom: a reply that misses its deadline is abandoned
  by the waiter (late results are discarded, never delivered);
- the BULK protocol edge (`query_block`/`submit_many`) answers
  thousands of lookups per call ON the caller's thread: lanes are
  pool-grouped and cycle-padded once, then one `_PIPE_CACHE` dispatch
  per fixed-shape sub-block amortizes all dispatcher overhead across
  the block — no queue, no per-`_Request` Python objects, per-lane
  statuses instead (never-dropped: every lane gets exactly one).  The
  scalar `submit()` path stays a thin wrapper over the queued
  micro-batcher with unchanged EBUSY/ETIMEDOUT/ESHUTDOWN semantics;
- ONE dispatcher thread drains the queue: collects requests for at most
  `window_s` (or until `fill` queries are pending), groups them by pool,
  pads each pool's seeds to the fixed `block` shape (cycle-pad: one
  compiled executable per structure, exactly the repo-wide trace-once
  contract) and maps them as one device block;
- ONE warming thread runs structural stagings (`apply` structural
  epochs, `adopt_map`): any remaining compile happens against the next
  buffer's fork on that thread, never on a thread that answers
  queries, and the overlay-structure variants background balancing
  seeds (pair widths 1/2) are pre-traced at construction — so a
  structural epoch can never stall readers (`structural_swap_stalls`
  counts flips that broke the budget; it must stay 0);
- epoch swaps run on the caller's thread: stage a complete new buffer
  off the reader path, then flip the active reference.  VALUE-ONLY
  epochs (reweights, osd state, overlay values — `osd.state.
  classify_incremental`) stage by FORKING the active buffer's
  ClusterState: the O(delta) on-device apply — crush/pools host
  objects shared instead of deepcopied, vectors scatter-updated,
  compiled mappers re-bound, warm dispatches only for structures that
  actually changed (`swap_delta_applies`).  Structural epochs stage
  from scratch exactly as before (one deepcopy + fresh ClusterState +
  full warm, `swap_full_restages`).  The flip is the only
  reader-visible window and is timed into the `swap_stall_seconds`
  quantile; in-flight batches keep draining on the buffer they
  captured.

Degradation contract: a device loss inside the dispatch (real transport
loss, or the `serve_dispatch` fault point) answers that batch through
the bit-exact host mapper — same bytes, slower — records provenance,
and serves the next `degraded_batches` batches host-side before
re-walking back to the device (`device_recoveries` counts successful
returns).  Queries are answered, never dropped: every submitted request
ends in exactly one reply (ok / EBUSY / ETIMEDOUT / ESHUTDOWN / EFAULT).

Crash-restart: every accepted epoch flushes `{epoch, map blob}`
atomically through `runtime.Checkpoint`; constructing the service with
`resume=True` restores the map and serves the same epoch.
"""

from __future__ import annotations

import base64
import copy
import threading
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

from ceph_tpu import obs
from ceph_tpu.core.intmath import pg_mask_for, stable_mod
from ceph_tpu.crush.types import ITEM_NONE
from ceph_tpu.osd.incremental import Incremental, apply_incremental
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgId
from ceph_tpu.runtime import Checkpoint, faults
from ceph_tpu.serve.slo import SloEngine
from ceph_tpu.utils import knobs
from ceph_tpu.utils.dout import subsys_logger

_log = subsys_logger("serve")

_L = obs.logger_for("serve")
_L.add_u64("queries", "queries answered ok (device or degraded host path)")
_L.add_u64("queries_shed",
           "queries refused at admission with an EBUSY reply (bounded "
           "queue full — shed, not queued into collapse)")
_L.add_u64("queries_expired",
           "queries answered ETIMEDOUT (deadline budget spent before "
           "the reply; late results are discarded, never delivered)")
_L.add_u64("degraded_answered",
           "queries answered by the bit-exact host mapper after a "
           "device loss (same bytes, provenance recorded)")
_L.add_u64("batches", "micro-batches dispatched to the mapper")
_L.add_u64("epoch_swaps", "epoch swaps applied (staged + flipped)")
_L.add_u64("swap_rejected",
           "epoch swaps refused (fault/apply error) with the old epoch "
           "left serving")
_L.add_u64("device_recoveries",
           "dispatches that returned to the device after a degraded "
           "(host-mapper) spell")
_L.add_u64("swap_delta_applies",
           "value-only epoch swaps staged by ClusterState delta apply: "
           "no full-map copy, no table re-upload, vectors scatter on "
           "device in O(delta)")
_L.add_u64("swap_full_restages",
           "structural epoch swaps staged from scratch (deepcopy + "
           "fresh ClusterState + warm dispatches)")
_L.add_u64("serve_checkpoints", "epoch+map checkpoints flushed")
_L.add_avg("batch_fill", "queries per dispatched micro-batch")
_L.add_quantile("batch_fill_hist",
                "queries per dispatched micro-batch as a distribution "
                "(p50/p99 in the dump — under-filled windows, the bulk "
                "path's failure mode, are invisible in the lifetime "
                "average the plain batch_fill keeps)",
                bounds=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                        2048, 4096, 8192, 16384, 32768, 65536])
_L.add_u64("bulk_blocks",
           "bulk protocol blocks answered on the caller's thread "
           "(query_block/submit_many: one fixed-shape dispatch per "
           "sub-block, no per-request queue objects)")
_L.add_u64("bulk_lookups",
           "lookups submitted through the bulk protocol edge (every "
           "lane, whatever its per-lane status)")
_L.add_u64("structural_swap_stalls",
           "structural epoch flips whose reader-visible stall exceeded "
           "STRUCTURAL_STALL_BOUND_S — the stall-free swap gate (must "
           "stay 0: pre-traced variants + the warming thread keep "
           "compiles off the flip)")
_L.add_u64("prewarmed_structures",
           "overlay-structure variants pre-traced at service "
           "construction (the pair widths background balancing seeds) "
           "so a later structural/overlay-gate epoch stages against a "
           "warm _PIPE_CACHE")
_L.add_u64("warm_stages",
           "structural stagings executed on the warming thread (off "
           "every thread that answers queries)")
_L.add_quantile("request_seconds",
                "submit-to-reply latency per client request (p50/p99 "
                "in the dump — the serving tail the QPS target is "
                "written against)")
_L.add_quantile("swap_stall_seconds",
                "reader-visible stall of one epoch swap: the atomic "
                "buffer flip only — staging runs off the reader path "
                "(p99 proves the swap never blocks readers)")
_L.add_time_avg("swap_prepare_seconds",
                "off-path staging cost of one epoch swap (clone + "
                "apply + mapper construction + warm dispatch)")
# continuous background balancing: a whole-plan device-loop upmap
# optimization computed BETWEEN epoch swaps (never on the query path)
# and applied as a value-only overlay epoch
_L.add_u64("background_rounds",
           "background balancing rounds (one device-loop plan each, "
           "computed off the query path)")
_L.add_u64("background_changes",
           "upmap changes applied by background balancing rounds "
           "(value-only overlay epochs)")
_L.add_u64("background_stale_plans",
           "background plans discarded unapplied because another "
           "epoch flipped in while the plan was being computed")
_L.add_time_avg("background_round_seconds",
                "wall time of one background balancing round (plan + "
                "value-only apply)")
_L.add_quantile("background_round_hist",
                "background balancing round wall-time distribution "
                "(p50/p99 — the bound the serve bench gates while "
                "clients stay live)")


# reader-visible stall budget for a STRUCTURAL epoch flip: the flip is
# one reference assignment, so anything past this bound means staging
# leaked work (a compile, a warm dispatch) onto the flip window —
# counted by `structural_swap_stalls`, gated at 0 by bench and tests
STRUCTURAL_STALL_BOUND_S = 0.05


@dataclass
class ServeConfig:
    """Service tuning; `from_env` reads the CEPH_TPU_SERVE_* knobs."""

    window_s: float = 0.001   # micro-batch collection window (<=1ms)
    block: int = 1024         # fixed dispatch block width (pad-to-shape)
    fill: int = 4096          # stop collecting once this many queries wait
    max_queue: int = 256      # admission bound (pending requests)
    deadline_s: float = 0.25  # default per-request deadline (<=0 disables)
    degraded_batches: int = 16  # host batches before re-trying the device
    checkpoint_every: int = 1   # flush every Nth accepted epoch
    bulk_max: int = 8192      # bulk sub-block width (pad-to-shape; the
    #                           one extra dispatch shape warm() pays for)
    prewarm: bool = True      # pre-trace overlay-structure variants at
    #                           construction (stall-free first overlay)

    @classmethod
    def from_env(cls) -> "ServeConfig":
        return cls(
            window_s=float(knobs.get(
                "CEPH_TPU_SERVE_WINDOW_US", "1000")) / 1e6,
            block=int(knobs.get("CEPH_TPU_SERVE_BLOCK", "1024")),
            fill=int(knobs.get("CEPH_TPU_SERVE_FILL", "4096")),
            max_queue=int(knobs.get("CEPH_TPU_SERVE_QUEUE", "256")),
            deadline_s=float(knobs.get(
                "CEPH_TPU_SERVE_DEADLINE_MS", "250")) / 1e3,
            degraded_batches=int(knobs.get(
                "CEPH_TPU_SERVE_DEGRADED_BATCHES", "16")),
            bulk_max=int(knobs.get("CEPH_TPU_SERVE_BULK_MAX", "8192")),
            prewarm=knobs.get("CEPH_TPU_SERVE_PREWARM", "1") == "1",
        )


# reply-status registry — the single authoritative vocabulary of answer
# codes.  Every `Reply(...)` a dispatcher path constructs and every
# `STATUS_CODES[...]` lane code the bulk edge writes must name one of
# these; the graftlint `serve-reply` pass statically matches the call
# sites in ceph_tpu/serve/ against this dict (and requires each code to
# be pinned by at least one test literal), so an early-return path
# cannot invent an undocumented status or silently drop a reply.
REPLY_STATUSES: dict[str, str] = {
    "ok": "answered with placement rows (device or degraded host path)",
    "EBUSY": "shed at admission: queue (or bulk lane capacity) full",
    "ETIMEDOUT": "deadline budget spent before the reply; late results "
                 "are discarded, never delivered",
    "ESHUTDOWN": "service stopped before the reply",
    "EFAULT": "invalid request (unknown pool, empty batch) or a "
              "dispatcher error answered loudly",
}

# dense per-lane codes for the bulk path's status vector ("ok" == 0)
STATUS_NAMES: tuple[str, ...] = tuple(REPLY_STATUSES)
STATUS_CODES: dict[str, int] = {s: i for i, s in enumerate(STATUS_NAMES)}


@dataclass
class Reply:
    """One request's answer.  `status` is always set; rows are present
    only on "ok".  EBUSY/ETIMEDOUT/ESHUTDOWN/EFAULT are *answers* — the
    never-dropped contract is that every submit ends in exactly one."""

    status: str                      # ok|EBUSY|ETIMEDOUT|ESHUTDOWN|EFAULT
    epoch: int = 0
    source: str = ""                 # "device" | "host" (degraded)
    up: np.ndarray | None = None          # [n, W] i32, NONE-padded
    up_primary: np.ndarray | None = None  # [n] i32
    acting: np.ndarray | None = None      # [n, W] i32
    acting_primary: np.ndarray | None = None  # [n] i32
    error: str = ""

    @property
    def ok(self) -> bool:
        return self.status == "ok"


@dataclass
class BulkReply:
    """One bulk block's answer: per-lane status codes + full-width rows.

    `statuses[i]` indexes STATUS_NAMES ("ok" == 0); non-ok lanes carry
    NONE-padded rows.  The never-dropped contract holds per lane —
    every submitted lane ends with exactly one status."""

    statuses: np.ndarray                  # [n] uint8 -> STATUS_NAMES
    epoch: int = 0
    source: str = ""                 # "device" | "host" | "mixed"
    up: np.ndarray | None = None          # [n, W] i32, NONE-padded
    up_primary: np.ndarray | None = None  # [n] i32
    acting: np.ndarray | None = None      # [n, W] i32
    acting_primary: np.ndarray | None = None  # [n] i32
    error: str = ""

    @property
    def ok(self) -> bool:
        return bool((self.statuses == STATUS_CODES["ok"]).all())

    def counts(self) -> dict[str, int]:
        """Per-status lane tallies, zero entries elided."""
        out: dict[str, int] = {}
        for name, code in STATUS_CODES.items():
            c = int((self.statuses == code).sum())
            if c:
                out[name] = c
        return out


class _Request:
    """One queued lookup batch: (pool, seeds) + deadline + reply slot.
    Exactly ONE reply wins, under the request's own lock: the first
    `answer()` delivers (later ones — e.g. a batch-wide EFAULT after
    one pool already answered — are refused), and `abandon()` (the
    scheduler-watchdog idiom: the waiter gave up) refuses every later
    delivery, so a request can never be double-counted as both
    answered and expired."""

    __slots__ = ("pool", "seeds", "deadline", "t0", "event", "reply",
                 "abandoned", "_lock")

    def __init__(self, pool: int, seeds: np.ndarray,
                 deadline: float | None):
        self.pool = pool
        self.seeds = seeds
        self.deadline = deadline
        self.t0 = time.perf_counter()
        self.event = threading.Event()
        self.reply: Reply | None = None
        self.abandoned = False
        self._lock = threading.Lock()

    def answer(self, reply: Reply) -> bool:
        """Deliver; False (and no counter advance) when the waiter
        already abandoned the request or a reply was already won."""
        with self._lock:
            if self.abandoned or self.reply is not None:
                return False
            self.reply = reply
        self.event.set()
        return True

    def abandon(self) -> bool:
        """Waiter gives up; False when a reply won the race first (the
        waiter must deliver that reply instead of ETIMEDOUT)."""
        with self._lock:
            if self.reply is not None:
                return False
            self.abandoned = True
            return True


class _Buffer:
    """One immutable serving generation: map + compiled mappers.

    Mappers are constructed (and warmed) at staging time, off the
    reader path; after the flip, readers only dispatch already-compiled
    executables — a value-only epoch (weights/state/overlay values)
    books 0 compiles by the `_PIPE_CACHE` trace-once contract.

    `state` is the buffer's ClusterState: the mappers share its device
    arrays/tables/vectors, so a value-only swap forks it (O(delta)
    scatter, host crush/pools shared) instead of deepcopying the map
    and re-uploading every table."""

    def __init__(self, m: OSDMap, block: int, state=None,
                 bulk_block: int = 0, mesh=None):
        self.m = m
        self.epoch = m.epoch
        self.block = block
        self.bulk_block = bulk_block
        self.state = state
        # the serving buffer resolves CEPH_TPU_MESH_DEVICES exactly
        # like ClusterState: the state carries its mesh; the stateless
        # fallback still resolves the knob itself so PG-axis sharding
        # does not silently drop when ClusterState construction
        # degrades (provenance stays in last_mesh_provenance either way)
        if mesh is None:
            mesh = getattr(state, "mesh", None)
        if mesh is None and state is None:
            try:
                from ceph_tpu.parallel.sharded import default_mesh

                mesh = default_mesh()
            except Exception:
                mesh = None
        self.mesh = mesh
        self._mappers: dict[int, object] = {}

    def mapper(self, pool_id: int):
        from ceph_tpu.osd.pipeline_jax import PoolMapper

        pm = self._mappers.get(pool_id)
        if pm is None:
            pm = PoolMapper(self.m, pool_id, state=self.state,
                            mesh=self.mesh)
            self._mappers[pool_id] = pm
        return pm

    def warm_pool(self, pid: int) -> None:
        """One fixed-shape dispatch per served shape for one pool
        (fast + rescue kernels; the bulk sub-block shape too, so the
        first query_block never pays a compile)."""
        import jax.numpy as jnp

        from ceph_tpu.crush.mapper_jax import RESCUE_PADS

        pm = self.mapper(pid)
        seeds = (np.arange(self.block) % pm.spec.pg_num).astype(
            np.uint32)
        pm.map_batch(seeds)
        if self.bulk_block and self.bulk_block != self.block:
            pm.map_batch((np.arange(self.bulk_block)
                          % pm.spec.pg_num).astype(np.uint32))
        for p in RESCUE_PADS:
            pad = np.zeros(p, np.intp)
            pm.jitted_loop()(
                jnp.zeros(p, jnp.uint32), pm.dev,
                pm._ov_rows(pad),
            )

    def warm(self) -> None:
        """Warm every pool so the first post-flip batch never pays a
        compile the swap should have paid off-path."""
        for pid in sorted(self.m.pools):
            self.warm_pool(pid)

    def host_rows(self, pool_id: int, seeds: np.ndarray):
        """Bit-exact host replay of a seed batch (the degraded path).
        Rows use the SAME padded width as the device pipeline, so a
        degraded reply is byte-identical to the device one."""
        pm = self._mappers.get(pool_id)
        W = pm.spec.out_width if pm is not None \
            else max(self.m.pools[pool_id].size, 1)
        n = len(seeds)
        up = np.full((n, W), ITEM_NONE, np.int32)
        upp = np.full(n, -1, np.int32)
        act = np.full((n, W), ITEM_NONE, np.int32)
        actp = np.full(n, -1, np.int32)
        for i, s in enumerate(seeds):
            u, u_p, a, a_p = self.m.pg_to_up_acting_osds(
                PgId(pool_id, int(s)))
            up[i, : min(len(u), W)] = u[:W]
            act[i, : min(len(a), W)] = a[:W]
            upp[i], actp[i] = u_p, a_p
        return up, upp, act, actp


# live services of THIS process, for the admin-socket `serve status`
# surface (name -> service); a closed service removes itself
_SERVICES: dict[str, "PlacementService"] = {}
_services_lock = threading.Lock()


def status_dump() -> dict:
    """Every live service's status — the `serve status` admin payload."""
    with _services_lock:
        svcs = dict(_SERVICES)
    return {"services": {name: s.status() for name, s in svcs.items()}}


class PlacementService:
    """See the module docstring.  `m` may be None with `resume=True`
    and a checkpoint that holds a serialized epoch."""

    def __init__(self, m: OSDMap | None = None,
                 config: ServeConfig | None = None,
                 checkpoint: str | None = None, resume: bool = False,
                 name: str = "serve"):
        self.config = config or ServeConfig.from_env()
        self.name = name
        self.ck = Checkpoint(checkpoint, resume=resume) \
            if checkpoint else None
        self.resumed_from: int | None = None
        if resume and self.ck is not None:
            state = self.ck.data.get("serve")
            if state:
                from ceph_tpu.osd.codec import decode_osdmap

                m = decode_osdmap(base64.b64decode(state["map_b64"]))
                self.resumed_from = int(state["epoch"])
                if state.get("timeline"):
                    # resumed services continue the same sample indices
                    obs.timeline.restore("serve", state["timeline"])
                _log(1, f"serve resumed at epoch {self.resumed_from}")
        if m is None:
            raise ValueError(
                "PlacementService needs a map (or resume=True with a "
                "checkpoint that holds one)")
        self._q: deque[_Request] = deque()
        self._q_lock = threading.Lock()
        self._q_cv = threading.Condition(self._q_lock)
        self._apply_lock = threading.Lock()
        self._stop = False
        self._paused = False
        self._batch_seq = 0
        self._degraded_left = 0
        self._bulk_inflight = 0  # lanes inside query_block calls
        self.fallback_events: list[str] = []
        self._swaps_since_ck = 0
        self.slo = SloEngine()
        self._slo_prev: dict = {}  # counter snapshot at last window sample
        self._slo_t = 0.0
        # structural stagings run on this thread once the service is
        # live (constructed lazily by _stage_async); the initial stage
        # and the variant prewarm run here, before anything serves
        self._warm_cv = threading.Condition()
        self._warm_jobs: deque = deque()
        self._warmer: threading.Thread | None = None
        self._prewarmed: set[tuple] = set()
        self._active = self._stage(m)
        self._prewarm_structures(self._active)
        self._checkpoint()
        self._thread = threading.Thread(
            target=self._loop, name=f"ceph-tpu-{name}", daemon=True)
        self._thread.start()
        with _services_lock:
            _SERVICES[name] = self

    # -- client surface ----------------------------------------------------

    @property
    def epoch(self) -> int:
        return self._active.epoch

    def lookup_batch(self, pool: int, seeds, deadline_s: float | None
                     = None) -> Reply:
        """Answer a batch of placement seeds of one pool.  Blocks until
        the reply or the deadline; an expired wait abandons the request
        (ETIMEDOUT reply, late dispatcher results discarded)."""
        seeds = np.asarray(seeds, np.uint32)
        if not len(seeds):
            return Reply("EFAULT", epoch=self.epoch,
                         error="empty seed batch")
        deadline_s = self.config.deadline_s if deadline_s is None \
            else deadline_s
        # deadline_s <= 0 disables deadline bookkeeping entirely: no
        # per-request absolute deadline, no expiry triage in the
        # dispatcher, an unbounded reply wait (shutdown still answers)
        timed = deadline_s > 0
        req = _Request(pool, seeds,
                       time.perf_counter() + deadline_s if timed
                       else None)
        with self._q_cv:
            if self._stop:
                return Reply("ESHUTDOWN", epoch=self.epoch,
                             error="service stopped")
            if len(self._q) >= self.config.max_queue:
                # shed at admission: an explicit busy answer beats an
                # unbounded queue whose tail latency collapses for all
                _L.inc("queries_shed", len(seeds))
                return Reply("EBUSY", epoch=self.epoch,
                             error="admission queue full")
            self._q.append(req)
            self._q_cv.notify()
        # watchdogged wait (runtime/scheduler idiom): a margin past the
        # deadline covers the in-flight dispatch that may still answer
        if not req.event.wait(deadline_s + 0.25 if timed else None) \
                and req.abandon():
            _L.inc("queries_expired", len(seeds))
            return Reply("ETIMEDOUT", epoch=self.epoch,
                         error=f"no reply within {deadline_s:.3f}s")
        return req.reply

    def lookup(self, pool: int, seed: int,
               deadline_s: float | None = None) -> Reply:
        return self.lookup_batch(pool, [seed], deadline_s)

    def lookup_object(self, pool: int, key: str, ns: str = "",
                      deadline_s: float | None = None) -> Reply:
        """object name (+namespace) -> PG -> OSDs (the osdmaptool
        --test-map-object sequence: rjenkins str hash, stable_mod to a
        PG seed, then the normal placement path)."""
        p = self._active.m.pools.get(pool)
        if p is None:
            return Reply("EFAULT", epoch=self.epoch,
                         error=f"no pool {pool}")
        ps = p.hash_key(key, ns)
        seed = int(stable_mod(ps, p.pg_num, pg_mask_for(p.pg_num)))
        return self.lookup(pool, seed, deadline_s)

    def submit(self, pool: int, seed: int,
               deadline_s: float | None = None) -> Reply:
        """Scalar protocol edge: a thin wrapper over the queued
        micro-batcher, EBUSY/ETIMEDOUT/ESHUTDOWN semantics unchanged
        (the bulk edge below is where the throughput lives)."""
        return self.lookup_batch(pool, [seed], deadline_s)

    # -- the bulk protocol edge --------------------------------------------

    def _bulk_admit(self, n: int) -> int:
        """Grant up to `n` bulk lanes against the lane-capacity bound
        (`max_queue * block` lanes in flight across concurrent bulk
        calls — the same admission philosophy as the request queue,
        counted in lookups instead of requests).  Lanes beyond the
        grant shed EBUSY per-lane; the caller must release the grant."""
        cap = self.config.max_queue * self.config.block
        with self._q_lock:
            granted = max(0, min(n, cap - self._bulk_inflight))
            self._bulk_inflight += granted
        return granted

    def _bulk_release(self, granted: int) -> None:
        with self._q_lock:
            self._bulk_inflight -= granted

    def _bulk_rows(self, buf: _Buffer, pool: int, padded: np.ndarray,
                   n_real: int):
        """One bulk sub-block through ONE fixed-shape dispatch, with
        the same degraded-host ladder as the micro-batcher.  The fault
        qualifier is the SERVICE name, so a front can aim a stall at
        one replica (`serve_dispatch.<name>`) while the bare point
        still hits every dispatch."""
        if self._degraded_left > 0:
            self._degraded_left -= 1
            _L.inc("degraded_answered", n_real)
            return buf.host_rows(pool, padded[:n_real]), "host"
        try:
            faults.check("serve_dispatch", qual=self.name)
            pm = buf.mapper(pool)
            rows = pm.map_batch(padded)
            rows = tuple(o[:n_real] for o in rows)
            if self.fallback_events and not self._recovered_logged():
                _L.inc("device_recoveries")
                obs.instant("serve.recovered", pool=pool)
                self.fallback_events.append(
                    "recovered: device dispatch healthy again")
            return rows, "device"
        except Exception as e:
            if not faults.looks_like_device_loss(e):
                raise
            self._degraded_left = self.config.degraded_batches
            msg = (f"epoch {buf.epoch} pool {pool}: "
                   f"{type(e).__name__}: {e}"[:200] + " -> host mapper")
            self.fallback_events.append(msg)
            obs.instant("serve.degraded", pool=pool)
            _log(1, f"device lost mid-serve (bulk); {msg}")
            _L.inc("degraded_answered", n_real)
            return buf.host_rows(pool, padded[:n_real]), "host"

    def query_block(self, pool: int, seeds,
                    deadline_s: float | None = None) -> BulkReply:
        """Bulk protocol edge: answer thousands of lookups of ONE pool
        in one call.  Lanes are cycle-padded once to a fixed sub-block
        shape and each sub-block is ONE `_PIPE_CACHE` dispatch — the
        per-request Python cost of the queued path (request objects,
        events, the dispatcher handoff) is amortized across the whole
        block.  Runs on the CALLER's thread; the micro-batcher keeps
        serving scalar traffic beside it.  Per-lane statuses keep the
        never-dropped contract: over-capacity lanes shed EBUSY, lanes
        past the deadline answer ETIMEDOUT, every lane gets exactly
        one status."""
        seeds = np.ascontiguousarray(
            np.asarray(seeds, np.uint32).ravel())
        n = len(seeds)
        if n == 0:
            return BulkReply(np.zeros(0, np.uint8), epoch=self.epoch)
        t0 = time.perf_counter()
        if self._stop:
            return BulkReply(
                np.full(n, STATUS_CODES["ESHUTDOWN"], np.uint8),
                epoch=self.epoch, error="service stopped")
        buf = self._active  # captured once: swaps flip under us safely
        if pool not in buf.m.pools:
            return BulkReply(
                np.full(n, STATUS_CODES["EFAULT"], np.uint8),
                epoch=buf.epoch, error=f"no pool {pool}")
        deadline_s = self.config.deadline_s if deadline_s is None \
            else deadline_s
        deadline = t0 + deadline_s if deadline_s > 0 else None
        granted = self._bulk_admit(n)
        statuses = np.zeros(n, np.uint8)
        error = ""
        if granted < n:
            statuses[granted:] = STATUS_CODES["EBUSY"]
            _L.inc("queries_shed", n - granted)
            error = "bulk lane capacity full"
        pm = buf.mapper(pool)
        W = pm.spec.out_width
        up = np.full((n, W), ITEM_NONE, np.int32)
        upp = np.full(n, -1, np.int32)
        act = np.full((n, W), ITEM_NONE, np.int32)
        actp = np.full(n, -1, np.int32)
        cfg = self.config
        bmax = max(cfg.bulk_max, cfg.block)
        sources: set[str] = set()
        done = 0
        try:
            with obs.span("serve.bulk", lookups=n, pool=pool):
                while done < granted:
                    if deadline is not None and \
                            time.perf_counter() > deadline:
                        statuses[done:granted] = \
                            STATUS_CODES["ETIMEDOUT"]
                        _L.inc("queries_expired", granted - done)
                        error = error or \
                            f"deadline spent after {done} lanes"
                        break
                    take = min(bmax, granted - done)
                    # two warmed shapes only: the scalar block and the
                    # bulk sub-block (warm_pool paid both off-path)
                    shape = cfg.block if take <= cfg.block else bmax
                    blk = seeds[done:done + take]
                    rows, src = self._bulk_rows(
                        buf, pool, np.resize(blk, shape), take)
                    u, u_p, a, a_p = rows
                    up[done:done + take] = u
                    upp[done:done + take] = u_p
                    act[done:done + take] = a
                    actp[done:done + take] = a_p
                    sources.add(src)
                    done += take
        except Exception as e:
            # a dispatcher bug must not eat lanes: the remainder of the
            # grant answers EFAULT loudly, the shed/done lanes keep
            # their statuses
            statuses[done:granted] = STATUS_CODES["EFAULT"]
            error = f"{type(e).__name__}: {e}"[:200]
            _log(0, f"bulk dispatch error: {error}")
        finally:
            self._bulk_release(granted)
        if done:
            _L.inc("queries", done)
        _L.inc("bulk_blocks")
        _L.inc("bulk_lookups", n)
        _L.observe("request_seconds", time.perf_counter() - t0)
        source = sources.pop() if len(sources) == 1 else (
            "mixed" if sources else "")
        return BulkReply(statuses, epoch=buf.epoch, source=source,
                         up=up, up_primary=upp, acting=act,
                         acting_primary=actp, error=error)

    def submit_many(self, pools, seeds,
                    deadline_s: float | None = None) -> BulkReply:
        """Mixed-pool bulk submit: ONE stable argsort groups the lanes
        by pool, each group goes through `query_block`, and the replies
        scatter back to input order.  `pools` may be a scalar (pure
        single-pool fast path) or a per-lane array."""
        seeds = np.asarray(seeds, np.uint32).ravel()
        pools_a = np.asarray(pools, np.int64).ravel()
        if pools_a.size == 1:
            return self.query_block(int(pools_a[0]), seeds, deadline_s)
        if pools_a.shape != seeds.shape:
            return BulkReply(
                np.full(len(seeds), STATUS_CODES["EFAULT"], np.uint8),
                epoch=self.epoch, error="pools/seeds length mismatch")
        n = len(seeds)
        if n == 0:
            return BulkReply(np.zeros(0, np.uint8), epoch=self.epoch)
        deadline_s = self.config.deadline_s if deadline_s is None \
            else deadline_s
        t_end = time.perf_counter() + deadline_s if deadline_s > 0 \
            else None
        order = np.argsort(pools_a, kind="stable")
        sorted_pools = pools_a[order]
        cuts = np.flatnonzero(np.diff(sorted_pools)) + 1
        groups = np.split(order, cuts)
        replies: list[tuple[np.ndarray, BulkReply]] = []
        for idx in groups:
            left = (t_end - time.perf_counter()) if t_end is not None \
                else 0.0
            if t_end is not None and left <= 0:
                r = BulkReply(
                    np.full(len(idx), STATUS_CODES["ETIMEDOUT"],
                            np.uint8),
                    epoch=self.epoch, error="deadline spent")
            else:
                # the remaining absolute budget is shared across the
                # pool groups (0 = bookkeeping disabled end to end)
                r = self.query_block(int(pools_a[idx[0]]), seeds[idx],
                                     left)
            replies.append((idx, r))
        W = max((r.up.shape[1] for _, r in replies
                 if r.up is not None), default=0)
        statuses = np.zeros(n, np.uint8)
        up = np.full((n, W), ITEM_NONE, np.int32)
        upp = np.full(n, -1, np.int32)
        act = np.full((n, W), ITEM_NONE, np.int32)
        actp = np.full(n, -1, np.int32)
        sources: set[str] = set()
        errors: list[str] = []
        epoch = self.epoch
        for idx, r in replies:
            statuses[idx] = r.statuses
            if r.up is not None:
                w = r.up.shape[1]
                up[idx, :w] = r.up
                upp[idx] = r.up_primary
                act[idx, :w] = r.acting
                actp[idx] = r.acting_primary
            if r.source:
                sources.add(r.source)
            if r.error:
                errors.append(r.error)
            epoch = max(epoch, r.epoch)
        source = sources.pop() if len(sources) == 1 else (
            "mixed" if sources else "")
        return BulkReply(statuses, epoch=epoch, source=source,
                         up=up, up_primary=upp, acting=act,
                         acting_primary=actp,
                         error="; ".join(errors)[:200])

    # -- epoch swaps -------------------------------------------------------

    def apply(self, inc: Incremental) -> dict:
        """Apply one `osd.incremental` epoch: stage off the reader path,
        flip atomically.  A failure (including the `epoch_swap` fault
        point) leaves the old epoch serving and reports it.

        Value-only epochs (reweights, osd state, overlay values) stage
        by FORKING the active buffer's ClusterState: the O(delta)
        on-device apply — no full-map deepcopy, no table re-upload, no
        warm dispatches for structures that did not change.  Structural
        epochs stage from scratch exactly as before."""
        from ceph_tpu.osd.state import classify_incremental

        with self._apply_lock:
            old = self._active
            try:
                faults.check("epoch_swap", qual=str(inc.epoch))
                with obs.span("serve.swap", epoch=inc.epoch), \
                        _L.time("swap_prepare_seconds"):
                    classified = (classify_incremental(inc, old.m)
                                  if old.state is not None else
                                  ("rebuild", None))
                    structural = classified[0] != "delta"
                    if not structural:
                        buf = self._stage_value(old, inc, classified)
                        _L.inc("swap_delta_applies")
                    else:
                        # structural: any remaining compile runs on the
                        # warming thread against the next buffer's fork
                        # (never on a thread that answers queries)
                        m2 = copy.deepcopy(old.m)
                        m2 = apply_incremental(m2, inc)
                        buf = self._stage_async(
                            lambda: self._stage(m2))
                        _L.inc("swap_full_restages")
            except Exception as e:
                _L.inc("swap_rejected")
                _log(1, f"epoch swap to {inc.epoch} rejected "
                        f"({type(e).__name__}: {e}); epoch "
                        f"{old.epoch} keeps serving")
                return {"ok": False, "epoch": old.epoch,
                        "error": f"{type(e).__name__}: {e}"[:200]}
            return self._flip(buf, structural=structural)

    def adopt_map(self, m: OSDMap, reason: str = "") -> dict:
        """Swap to a complete map (the chaos harness hands the lifetime
        engine's evolved map over wholesale; same staging + flip path,
        same fault point).  ONE deepcopy — the caller keeps mutating
        its map — then a full stage: without the Incremental there is
        nothing to classify, so the delta path cannot apply here."""
        with self._apply_lock:
            old = self._active
            try:
                faults.check("epoch_swap", qual=str(m.epoch))
                with obs.span("serve.swap", epoch=m.epoch), \
                        _L.time("swap_prepare_seconds"):
                    m2 = copy.deepcopy(m)
                    buf = self._stage_async(lambda: self._stage(m2))
            except Exception as e:
                _L.inc("swap_rejected")
                _log(1, f"epoch swap to {m.epoch} rejected "
                        f"({type(e).__name__}: {e}); epoch "
                        f"{old.epoch} keeps serving ({reason})")
                return {"ok": False, "epoch": old.epoch,
                        "error": f"{type(e).__name__}: {e}"[:200]}
            return self._flip(buf, structural=True)

    def _stage(self, m: OSDMap) -> _Buffer:
        """Full staging: fresh ClusterState (device arrays/tables/
        vectors uploaded once) + every pool warmed.  The initial
        buffer, adopt_map, and structural epochs come through here."""
        state = None
        try:
            from ceph_tpu.osd.state import ClusterState

            state = ClusterState(m)
        except Exception as e:
            # state construction must never beat the old contract: a
            # backendless/degraded environment still stages the plain
            # per-mapper way
            _log(1, f"serve staging without ClusterState "
                    f"({type(e).__name__}: {e})")
        buf = _Buffer(m, self.config.block, state=state,
                      bulk_block=max(self.config.bulk_max,
                                     self.config.block))
        buf.warm()
        return buf

    def _stage_value(self, old: _Buffer, inc: Incremental,
                     classified: tuple) -> _Buffer:
        """Value-only staging: fork the active ClusterState (O(delta)
        on-device apply, crush/pools host objects shared) and warm ONLY
        pools whose compiled structure changed (an overlay gate
        flipping on) — a plain reweight epoch stages with zero mapping
        dispatches and zero full-table device_puts."""
        st2 = old.state.fork(inc, _classified=classified)
        buf = _Buffer(st2.m, self.config.block, state=st2,
                      bulk_block=old.bulk_block)
        for pid in sorted(st2.m.pools):
            pm_old = old._mappers.get(pid)
            if pm_old is None or \
                    buf.mapper(pid).cache_key != pm_old.cache_key:
                buf.warm_pool(pid)
        return buf

    def _warm_loop(self) -> None:
        while True:
            with self._warm_cv:
                while not self._warm_jobs and not self._stop:
                    self._warm_cv.wait(timeout=0.1)
                if self._stop and not self._warm_jobs:
                    return
                fn, done, slot = self._warm_jobs.popleft()
            try:
                slot["result"] = fn()
                _L.inc("warm_stages")
            except BaseException as e:  # staging errors travel back to
                slot["error"] = e       # the applier, never kill the loop
            done.set()

    def _stage_async(self, fn):
        """Run one staging job on the warming thread; the caller (the
        applier, under `_apply_lock`) blocks for the result.  Keeps
        structural compiles off every thread that answers queries —
        the GIL-visible stall of a trace never lands between a reader's
        dispatch and its reply.  Falls back inline when the warmer is
        unavailable (shutdown, or the warmer itself staging)."""
        if self._warmer is None or not self._warmer.is_alive():
            if self._stop:
                return fn()
            self._warmer = threading.Thread(
                target=self._warm_loop,
                name=f"ceph-tpu-{self.name}-warm", daemon=True)
            self._warmer.start()
        if threading.current_thread() is self._warmer:
            return fn()
        done = threading.Event()
        slot: dict = {}
        with self._warm_cv:
            self._warm_jobs.append((fn, done, slot))
            self._warm_cv.notify()
        done.wait()
        if "error" in slot:
            raise slot["error"]
        return slot["result"]

    def _prewarm_structures(self, buf: _Buffer) -> None:
        """Pre-trace the overlay-structure variants background
        balancing seeds: upmap pair widths 1 and 2 flip the pipeline's
        `n_upmap_pairs` structural gate, so the FIRST overlay epoch
        after construction would otherwise compile while the service
        is live.  A value-copied map with synthetic pairs on one PG
        mints the same `_PIPE_CACHE` entries here, at construction,
        off every measured window (the cache is process-global and
        keyed on structure, not map values)."""
        if not self.config.prewarm:
            return
        from ceph_tpu.osd.state import value_copy_map

        for pid in sorted(buf.m.pools):
            pm = buf.mapper(pid)
            have = pm.ov.n_pairs
            for k in (1, 2):
                key = (pid, pm.cache_key, k)
                if k == have or key in self._prewarmed:
                    continue
                self._prewarmed.add(key)
                try:
                    m2 = value_copy_map(buf.m)
                    m2.pg_upmap_items = dict(m2.pg_upmap_items)
                    m2.pg_upmap_items[PgId(pid, 0)] = [
                        (j, j) for j in range(k)]
                    vb = _Buffer(m2, self.config.block,
                                 bulk_block=buf.bulk_block,
                                 mesh=buf.mesh)
                    vb.warm_pool(pid)
                    _L.inc("prewarmed_structures")
                except Exception as e:
                    _log(1, f"structure prewarm pool {pid} pairs={k} "
                            f"failed ({type(e).__name__}: {e})")

    def _flip(self, buf: _Buffer, structural: bool = False) -> dict:
        # the only reader-visible window of a swap: one reference
        # assignment.  Readers that already captured the old buffer
        # drain on it; the quantile records the bound the bench gates.
        t0 = time.perf_counter()
        self._active = buf
        stall = time.perf_counter() - t0
        _L.observe("swap_stall_seconds", stall)
        if structural and stall > STRUCTURAL_STALL_BOUND_S:
            # the stall-free-structural-swap gate: staging (and any
            # compile) already happened off-path, so a flip that still
            # broke the budget is a contract violation worth counting
            _L.inc("structural_swap_stalls")
        _L.inc("epoch_swaps")
        obs.instant("serve.swap_applied", epoch=buf.epoch)
        self._swaps_since_ck += 1
        every = self.config.checkpoint_every
        if every and self._swaps_since_ck >= every:
            self._checkpoint()
        return {"ok": True, "epoch": buf.epoch,
                "swap_stall_s": round(stall, 6)}

    def background_balance(self, max_deviation: int = 1,
                           max_iter: int = 16,
                           candidate_batch: int = 16) -> dict:
        """One CONTINUOUS-BALANCING round: compute a whole-plan
        device-loop upmap optimization against the active epoch's map
        — off the query path, WITHOUT holding the apply lock, one XLA
        dispatch for the entire plan — and apply any changes as one
        value-only overlay epoch (O(delta) staging; readers only ever
        see the atomic flip).  A plan that raced a concurrent epoch
        swap is discarded, never applied stale."""
        from ceph_tpu.balancer.upmap import calc_pg_upmaps
        from ceph_tpu.osd.state import value_copy_map

        t0 = time.perf_counter()
        buf = self._active  # snapshot; planning never blocks appliers
        applied: dict = {"ok": True, "epoch": buf.epoch}
        with obs.span("serve.background_balance", epoch=buf.epoch), \
                _L.time("background_round_hist"):
            m2 = value_copy_map(buf.m)
            src = buf.state.rows_source_for(m2) \
                if buf.state is not None else None
            res = calc_pg_upmaps(
                m2, max_deviation=max_deviation, max_iter=max_iter,
                backend="device_loop", candidate_batch=candidate_batch,
                rows_source=src)
            if res.num_changed:
                if self._active is buf:
                    inc = Incremental(epoch=buf.epoch + 1)
                    inc.new_pg_upmap_items = {
                        pg: list(v)
                        for pg, v in res.new_pg_upmap_items.items()}
                    inc.old_pg_upmap_items = set(
                        res.old_pg_upmap_items)
                    applied = self.apply(inc)
                else:
                    _L.inc("background_stale_plans")
                    applied = {"ok": False,
                               "epoch": self._active.epoch,
                               "error": "stale plan (epoch moved "
                                        "during planning)"}
        _L.inc("background_rounds")
        if applied.get("ok"):
            _L.inc("background_changes", res.num_changed)
        dt = time.perf_counter() - t0
        _L.observe("background_round_seconds", dt)
        return {"ok": bool(applied.get("ok", False)),
                "epoch": int(applied.get("epoch", buf.epoch)),
                "num_changed": res.num_changed,
                "stddev": res.stddev,
                "max_deviation": res.max_deviation,
                "round_s": round(dt, 6)}

    def _checkpoint(self) -> None:
        if self.ck is None:
            return
        from ceph_tpu.osd.codec import encode_osdmap

        self.ck.progress("serve", {
            "epoch": self._active.epoch,
            "map_b64": base64.b64encode(
                encode_osdmap(self._active.m)).decode(),
            "timeline": obs.timeline.state("serve"),
        })
        self._swaps_since_ck = 0
        _L.inc("serve_checkpoints")

    # -- the dispatcher ----------------------------------------------------

    def pause(self) -> None:
        """Hold the dispatcher (deterministic overload tests: with the
        drain stopped, the max_queue+1'th request MUST shed)."""
        self._paused = True

    def unpause(self) -> None:
        with self._q_cv:
            self._paused = False
            self._q_cv.notify()

    def _collect(self) -> list[_Request]:
        """Block for work, then gather up to `window_s` / `fill`.

        The window clock is hoisted OUT of the per-request loop:
        already-queued requests drain with zero clock reads, and the
        clock is read once per wait cycle (only when the queue runs
        dry before `fill`) — at 1M lookups/s the per-request
        perf_counter() call was itself a measurable dispatcher tax."""
        cfg = self.config
        with self._q_cv:
            while not self._stop and (not self._q or self._paused):
                self._q_cv.wait(timeout=0.05)
            if self._stop:
                return []
            batch = [self._q.popleft()]
            n = len(batch[0].seeds)
            t_end = None  # window starts at the first dry wait
            while n < cfg.fill:
                if self._q:
                    req = self._q.popleft()
                    batch.append(req)
                    n += len(req.seeds)
                    continue
                now = time.perf_counter()
                if t_end is None:
                    t_end = now + cfg.window_s
                left = t_end - now
                if left <= 0:
                    break
                self._q_cv.wait(timeout=left)
                if not self._q:
                    break
        return batch

    def _loop(self) -> None:
        while not self._stop:
            batch = self._collect()
            if not batch:
                continue
            try:
                self._dispatch(batch)
            except Exception as e:  # a bug must not kill the drain:
                # answer loudly, keep serving
                _log(0, f"serve dispatch error: {type(e).__name__}: {e}")
                err = Reply("EFAULT", epoch=self.epoch,
                            error=f"{type(e).__name__}: {e}"[:200])
                for req in batch:
                    req.answer(err)
        # shutdown drain: pending requests still get an answer
        with self._q_cv:
            pending = list(self._q)
            self._q.clear()
        bye = Reply("ESHUTDOWN", epoch=self.epoch,
                    error="service stopped")
        for req in pending:
            req.answer(bye)

    def _map_rows(self, buf: _Buffer, pool: int, seeds: np.ndarray,
                  seq: int):
        """One pool's seed batch through fixed-shape device blocks, with
        the degraded-host ladder around the dispatch."""
        B = self.config.block
        if self._degraded_left > 0:
            # degraded spell: serve host-side, count down to recovery
            self._degraded_left -= 1
            _L.inc("degraded_answered", len(seeds))
            return buf.host_rows(pool, seeds), "host"
        try:
            # the dispatch boundary: real transport losses raise from
            # map_batch below; `serve_dispatch` injects the same shapes
            # (qualifier: batch sequence number, so `exit`/`lost` can be
            # aimed mid-serve deterministically)
            faults.check("serve_dispatch", qual=str(seq))
            pm = buf.mapper(pool)
            parts = []
            for i in range(0, len(seeds), B):
                blk = seeds[i:i + B]
                sub = pm.map_batch(np.resize(blk, B))
                parts.append(tuple(o[: len(blk)] for o in sub))
            rows = tuple(
                np.concatenate([p[j] for p in parts]) for j in range(4))
            if self.fallback_events and not self._recovered_logged():
                _L.inc("device_recoveries")
                obs.instant("serve.recovered", pool=pool)
                self.fallback_events.append("recovered: device dispatch "
                                            "healthy again")
            return rows, "device"
        except Exception as e:
            if not faults.looks_like_device_loss(e):
                raise
            # degrade, don't die: host mapper is bit-exact — answer the
            # in-flight queries, then serve host-side for a spell before
            # re-walking back to the device
            self._degraded_left = self.config.degraded_batches
            msg = (f"epoch {buf.epoch} pool {pool}: "
                   f"{type(e).__name__}: {e}"[:200] + " -> host mapper")
            self.fallback_events.append(msg)
            obs.instant("serve.degraded", pool=pool)
            _log(1, f"device lost mid-serve; {msg}")
            _L.inc("degraded_answered", len(seeds))
            return buf.host_rows(pool, seeds), "host"

    def _recovered_logged(self) -> bool:
        return bool(self.fallback_events) and \
            self.fallback_events[-1].startswith("recovered")

    def _dispatch(self, batch: list[_Request]) -> None:
        buf = self._active  # captured once: swaps flip under us safely
        self._batch_seq += 1
        now = time.perf_counter()
        live: dict[int, list[_Request]] = {}
        n_live = 0
        for req in batch:
            if req.abandoned:
                continue
            if req.deadline is not None and now > req.deadline:
                if req.answer(Reply(
                        "ETIMEDOUT", epoch=buf.epoch,
                        error="deadline budget spent in the queue")):
                    _L.inc("queries_expired", len(req.seeds))
                continue
            if req.pool not in buf.m.pools:
                req.answer(Reply("EFAULT", epoch=buf.epoch,
                                 error=f"no pool {req.pool}"))
                continue
            live.setdefault(req.pool, []).append(req)
            n_live += len(req.seeds)
        if not live:
            return
        _L.inc("batches")
        _L.observe("batch_fill", n_live)
        _L.observe("batch_fill_hist", n_live)
        with obs.span("serve.batch", queries=n_live, pools=len(live)):
            for pool, reqs in live.items():
                seeds = np.concatenate([r.seeds for r in reqs])
                rows, source = self._map_rows(
                    buf, pool, seeds, self._batch_seq)
                up, upp, act, actp = rows
                off = 0
                for r in reqs:
                    n = len(r.seeds)
                    delivered = r.answer(Reply(
                        "ok", epoch=buf.epoch, source=source,
                        up=up[off:off + n], up_primary=upp[off:off + n],
                        acting=act[off:off + n],
                        acting_primary=actp[off:off + n],
                    ))
                    if delivered:
                        _L.inc("queries", n)
                        _L.observe("request_seconds",
                                   time.perf_counter() - r.t0)
                    off += n
        self._observe_window()

    def _observe_window(self) -> None:
        """Pure-observer tail of a dispatch window: score an SLO sample
        and record a "serve" timeline point from counter deltas already
        on the host.  Windowed p99 comes from the delta of the
        cumulative request-latency histogram between samples (so it can
        recover after a spike, unlike the lifetime-cumulative p99).
        Throttled to one sample per 50 ms of dispatch activity."""
        if not (obs.health.enabled() or obs.timeline.enabled()):
            return
        now = time.perf_counter()
        if now - self._slo_t < 0.05:
            return
        self._slo_t = now
        d = _L.dump()
        prev = self._slo_prev

        def delta(k: str) -> int:
            return int(d.get(k, 0)) - int(prev.get(k, 0))

        req = d.get("request_seconds") or {}
        buckets = req.get("buckets")
        p99 = None
        if buckets:
            pb = prev.get("_req_buckets")
            window = ([a - b for a, b in zip(buckets, pb)]
                      if pb is not None and len(pb) == len(buckets)
                      else list(buckets))
            if sum(window) > 0:
                p99 = obs.quantiles.summarize(
                    req["bounds"], window)["p99"]
        ok = delta("queries")
        errors = delta("queries_expired")
        shed = delta("queries_shed")
        total = ok + errors + shed
        self._slo_prev = {
            k: d.get(k, 0)
            for k in ("queries", "queries_expired", "queries_shed",
                      "degraded_answered")
        }
        self._slo_prev["_req_buckets"] = list(buckets) if buckets else None
        if total <= 0:
            return  # nothing answered since the last sample
        sample = {"fast_burn": self.slo._burn(self.slo.FAST)}
        if obs.health.enabled():
            sample = self.slo.observe(
                p99_s=p99, queries=total, errors=errors, shed=shed)
        obs.timeline.sample("serve", {
            "epoch": self.epoch,
            "queries": total,
            "expired": errors,
            "shed": shed,
            "degraded": delta("degraded_answered"),
            "p99_ms": (p99 or 0.0) * 1e3,
            "burning": int(self.slo.burning),
            "fast_burn": sample["fast_burn"],
        })

    # -- introspection / lifecycle ----------------------------------------

    def sample_digest(self, per_pool: int = 64) -> str:
        """SHA-256 over the replies to a deterministic query sample of
        every pool — the restart-answers-identically witness: two
        services serving the same epoch produce the same digest."""
        import hashlib

        h = hashlib.sha256(str(self.epoch).encode())
        for pid in sorted(self._active.m.pools):
            n = self._active.m.pools[pid].pg_num
            rng = np.random.default_rng([pid, self.epoch])
            seeds = np.unique(rng.integers(0, n, size=per_pool))
            r = self.lookup_batch(pid, seeds, deadline_s=30.0)
            if not r.ok:
                h.update(f"{pid}:{r.status}".encode())
                continue
            h.update(np.ascontiguousarray(r.acting).tobytes())
            h.update(np.ascontiguousarray(r.acting_primary).tobytes())
        return h.hexdigest()

    def provenance(self) -> dict:
        return {
            "backend": "host-degraded" if self._degraded_left else
                       "device",
            "device_loss_fallbacks": sum(
                1 for e in self.fallback_events
                if not e.startswith("recovered")),
            "fallback_events": list(self.fallback_events)[-8:],
        }

    def status(self) -> dict:
        # counter fields are the process-global `serve` perf group (the
        # repo-wide registry idiom); epoch/queue/degraded state is this
        # service's own
        d = _L.dump()
        stall = d.get("swap_stall_seconds") or {}
        req = d.get("request_seconds") or {}
        fill = d.get("batch_fill_hist") or {}
        wl = obs.perf_dump().get("workload") or {}
        try:
            from ceph_tpu.parallel.sharded import last_mesh_provenance

            mesh_prov = last_mesh_provenance()
        except Exception:
            mesh_prov = {}
        mesh = self._active.mesh
        out = {
            "epoch": self.epoch,
            "pools": sorted(self._active.m.pools),
            "queue_depth": len(self._q),
            "paused": self._paused,
            "degraded_batches_left": self._degraded_left,
            "provenance": self.provenance(),
            "queries": d.get("queries", 0),
            "queries_shed": d.get("queries_shed", 0),
            "queries_expired": d.get("queries_expired", 0),
            "degraded_answered": d.get("degraded_answered", 0),
            "batches": d.get("batches", 0),
            "epoch_swaps": d.get("epoch_swaps", 0),
            "swap_rejected": d.get("swap_rejected", 0),
            "swap_delta_applies": d.get("swap_delta_applies", 0),
            "swap_full_restages": d.get("swap_full_restages", 0),
            "swap_stall_p99_s": stall.get("p99"),
            "structural_swap_stalls": d.get("structural_swap_stalls", 0),
            "prewarmed_structures": d.get("prewarmed_structures", 0),
            "bulk_blocks": d.get("bulk_blocks", 0),
            "bulk_lookups": d.get("bulk_lookups", 0),
            "request_p50_s": req.get("p50"),
            "request_p99_s": req.get("p99"),
            "batch_fill_p50": fill.get("p50"),
            "batch_fill_p99": fill.get("p99"),
            # mesh provenance: the serving buffer shards its PG axis
            # exactly like ClusterState (CEPH_TPU_MESH_DEVICES)
            "mesh": {
                "devices": int(mesh.devices.size) if mesh is not None
                else 1,
                "provenance": mesh_prov,
            },
            "health": obs.health.status(),
            "slo": self.slo.status(),
            # the client-visible story the lifetime workload model
            # tells (sim/workload.py, booked when a chaos harness runs
            # the simulator in this process): the daemon and the
            # simulator must agree on what clients experienced
            "workload": {
                "degraded_reads_served": wl.get("degraded_reads", 0),
                "at_risk_hits": wl.get("at_risk_hits", 0),
            },
            "config": {
                "window_s": self.config.window_s,
                "block": self.config.block,
                "fill": self.config.fill,
                "max_queue": self.config.max_queue,
                "deadline_s": self.config.deadline_s,
                "bulk_max": self.config.bulk_max,
            },
        }
        if self.resumed_from is not None:
            out["resumed_from"] = self.resumed_from
        return out

    def close(self) -> None:
        """Stop accepting, answer everything pending, final checkpoint."""
        with self._q_cv:
            self._stop = True
            self._q_cv.notify_all()
        with self._warm_cv:
            self._warm_cv.notify_all()
        self._thread.join(timeout=10)
        if self._warmer is not None:
            self._warmer.join(timeout=10)
        self._checkpoint()
        with _services_lock:
            if _SERVICES.get(self.name) is self:
                del _SERVICES[self.name]

    def __enter__(self) -> "PlacementService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
